"""Training harness tests: the TPU-native DP trainer and the MapReduce-
packaged digits example (the APRIL-ANN workload, SURVEY.md §3.5), plus the
grad-equivalence and checkpoint-resume guarantees."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.coord.persistent_table import PersistentTable
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.models.mlp import accuracy, init_mlp, nll_loss
from lua_mapreduce_tpu.parallel.mesh import host_mesh
from lua_mapreduce_tpu.store.memfs import MemStore
from lua_mapreduce_tpu.train import checkpoint as ckpt
from lua_mapreduce_tpu.train.data import make_digits
from lua_mapreduce_tpu.train.harness import DataParallelTrainer, TrainConfig


@pytest.fixture(scope="module")
def mesh():
    return host_mesh(8)


@pytest.fixture(scope="module")
def digits():
    return make_digits(seed=0)


def test_grad_accum_matches_big_batch(mesh, digits):
    """grad_accum=4 (microbatch scan, one optimizer update) must produce
    the same step as the whole batch at once — mean of equal-size
    microbatch grads ≡ grad of the mean loss."""
    x, y = digits[0][:128], digits[1][:128]
    params = init_mlp(jax.random.PRNGKey(7))

    losses, stepped = {}, {}
    for accum in (1, 4):
        tr = DataParallelTrainer(nll_loss, params, mesh,
                                 TrainConfig(grad_accum=accum))
        losses[accum] = tr.step(x, y)
        stepped[accum] = jax.tree.map(np.asarray, tr.params)
    assert abs(losses[1] - losses[4]) < 1e-6
    for k in stepped[1]:
        np.testing.assert_allclose(stepped[1][k], stepped[4][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)

    with pytest.raises(ValueError, match="grad_accum"):
        DataParallelTrainer(nll_loss, params, mesh,
                            TrainConfig(grad_accum=3)).step(x, y)


def test_dp_step_equals_single_device_step(mesh, digits):
    """pmean of per-shard grads == full-batch grad: one mesh step must
    match one plain optax step bit-for-bit (up to float assoc)."""
    x, y = digits[0][:128], digits[1][:128]
    params = init_mlp(jax.random.PRNGKey(42))

    tr = DataParallelTrainer(nll_loss, params, mesh, TrainConfig())
    tr.step(x, y)

    opt = optax.chain(optax.add_decayed_weights(TrainConfig.weight_decay),
                      optax.sgd(TrainConfig.learning_rate,
                                momentum=TrainConfig.momentum))
    state = opt.init(params)
    grads = jax.grad(nll_loss)(params, jnp.asarray(x), jnp.asarray(y))
    updates, _ = opt.update(grads, state, params)
    expected = optax.apply_updates(params, updates)

    for k in params:
        np.testing.assert_allclose(np.asarray(tr.params[k]),
                                   np.asarray(expected[k]),
                                   rtol=2e-5, atol=2e-6)


def test_fit_learns_and_checkpoints(mesh, digits):
    x_tr, y_tr, x_va, y_va = digits
    params = init_mlp(jax.random.PRNGKey(0))
    tr = DataParallelTrainer(nll_loss, params, mesh,
                             TrainConfig(max_epochs=6, patience=6))
    store = MemStore()
    conf = PersistentTable("conf", MemJobStore())
    out = tr.fit(x_tr, y_tr, x_va, y_va, checkpoint_store=store, conf=conf)
    assert out["best_val"] < 0.5
    assert float(accuracy(tr.params, x_va, y_va)) > 0.9
    assert store.exists("model.ckpt")
    assert conf["epoch"] >= 1 and conf["best_val"] == out["best_val"]

    # checkpoint round-trips exactly
    loaded = ckpt.load_pytree(store, "model.ckpt", params)
    best = out["best_epoch"]
    assert best >= 1
    for k in params:
        assert loaded[k].shape == np.asarray(params[k]).shape


def test_fit_resumes_from_conf(mesh, digits):
    """Restart parity (SURVEY.md §5 checkpoint/resume): a second fit() with
    the same conf+store continues from the recorded epoch."""
    x_tr, y_tr, x_va, y_va = digits
    store = MemStore()
    jobstore = MemJobStore()
    conf = PersistentTable("conf", jobstore)
    tr = DataParallelTrainer(nll_loss, init_mlp(jax.random.PRNGKey(0)), mesh,
                             TrainConfig(max_epochs=3, patience=10))
    tr.fit(x_tr, y_tr, x_va, y_va, checkpoint_store=store, conf=conf)
    assert conf["epoch"] == 3

    # the resume checkpoint must hold LAST-epoch params AND optimizer
    # state — resuming from the best-only file would rewind training and
    # zero the momentum buffers
    assert store.exists("model.ckpt.resume")
    saved_params, saved_opt = ckpt.load_pytree(
        store, "model.ckpt.resume", (tr.params, tr.opt_state))
    for k in tr.params:
        np.testing.assert_array_equal(np.asarray(saved_params[k]),
                                      np.asarray(tr.params[k]))
    momentum = [np.asarray(x) for x in jax.tree.leaves(saved_opt)]
    assert any(np.any(m != 0) for m in momentum)

    tr2 = DataParallelTrainer(nll_loss, init_mlp(jax.random.PRNGKey(9)), mesh,
                              TrainConfig(max_epochs=5, patience=10))
    conf2 = PersistentTable("conf", jobstore)
    out2 = tr2.fit(x_tr, y_tr, x_va, y_va, checkpoint_store=store,
                   conf=conf2)
    # resumed at epoch 4, ran 4 and 5 only
    assert [h["epoch"] for h in out2["history"]] == [4, 5]


SMALL = {"sizes": (64, 32, 10), "n_shards": 4, "bunch": 64,
         "max_steps": 30, "patience": 30}


@pytest.mark.heavy
def test_mapreduce_digits_example_learns():
    """The six-function DP-SGD loop (APRIL-ANN analog) on the host engine:
    loops until convergence/max and the validation loss drops."""
    import examples.digits.mr_train as mr
    model_store = "mem:digits-e2e"
    spec = TaskSpec(taskfn="examples.digits.mr_train",
                    mapfn="examples.digits.mr_train",
                    partitionfn="examples.digits.mr_train",
                    reducefn="examples.digits.mr_train",
                    finalfn="examples.digits.mr_train",
                    init_args={**SMALL, "model_store": model_store},
                    storage="mem:digits-e2e-shuffle")
    stats = LocalExecutor(spec, max_iterations=100).run()
    meta = mr.read_meta(model_store)
    assert meta["step"] == len(stats.iterations)
    assert meta["step"] >= 5
    # untrained small MLP starts near ln(10) ≈ 2.3 val NLL; must improve a lot
    assert meta["best_val"] < 1.0
    assert meta["finished"]


def test_mapreduce_step_matches_direct_math(tmp_path):
    """Exact parity: one MapReduce iteration == the same update computed
    directly (grad sum over shards, 1/sqrt(count) smoothing, momentum SGD)."""
    import examples.digits.mr_train as mr
    from lua_mapreduce_tpu.store.router import get_storage_from

    model_store = "mem:digits-parity"
    args = {"sizes": (32, 16, 10), "n_shards": 2, "bunch": 16,
            "max_steps": 1, "patience": 99, "model_store": model_store,
            "seed": 3}
    spec = TaskSpec(taskfn="examples.digits.mr_train",
                    mapfn="examples.digits.mr_train",
                    partitionfn="examples.digits.mr_train",
                    reducefn="examples.digits.mr_train",
                    finalfn="examples.digits.mr_train",
                    init_args=args, storage="mem:digits-parity-shuffle")
    # snapshot initial state before running
    store = get_storage_from(model_store)
    state0 = mr._load_state(store)
    data = make_digits(seed=3, dim=32)

    LocalExecutor(spec, max_iterations=2).run()
    state1 = mr._load_state(store)

    # recompute expected update
    x_tr, y_tr = data[0], data[1]
    grads_sum = {k: np.zeros_like(np.asarray(v))
                 for k, v in state0["params"].items()}
    for shard in range(2):
        rng = np.random.RandomState(1000 + 0 + shard)   # step=0
        idx = rng.randint(0, len(x_tr), 16)
        g = jax.grad(nll_loss)(state0["params"], jnp.asarray(x_tr[idx]),
                               jnp.asarray(y_tr[idx]))
        for k in grads_sum:
            grads_sum[k] += np.asarray(g[k])
    for k, p in state0["params"].items():
        smoothed = grads_sum[k] / np.sqrt(2) + 1e-5 * np.asarray(p)
        vel = -0.05 * smoothed                          # momentum starts at 0
        np.testing.assert_allclose(np.asarray(state1["params"][k]),
                                   np.asarray(p) + vel, rtol=1e-5, atol=1e-6)


def test_checkpoint_roundtrip_all_backends(tmp_path):
    from lua_mapreduce_tpu.store.objectfs import ObjectStore
    from lua_mapreduce_tpu.store.sharedfs import SharedStore

    tree = {"W": np.arange(12, dtype=np.float32).reshape(3, 4),
            "nested": {"b": np.array([1.5, -2.5], dtype=np.float64),
                       "i": np.array([1, 2, 3], dtype=np.int32)}}
    for store in (MemStore(), SharedStore(str(tmp_path / "s")),
                  ObjectStore(str(tmp_path / "o"))):
        ckpt.save_pytree(store, "t.ckpt", tree)
        out = ckpt.load_pytree(store, "t.ckpt", tree)
        for va, vb in zip(jax.tree.leaves(tree), jax.tree.leaves(out)):
            np.testing.assert_array_equal(va, vb)
            assert np.asarray(va).dtype == np.asarray(vb).dtype


def test_profile_dir_captures_trace(mesh, digits, tmp_path):
    """TrainConfig.profile_dir traces the second epoch (SURVEY §5
    tracing, hot-path half) — the trace directory must be non-empty."""
    import os

    from lua_mapreduce_tpu.models.mlp import init_mlp, nll_loss

    x_tr, y_tr, _, _ = digits
    pdir = str(tmp_path / "trace")
    tr = DataParallelTrainer(
        nll_loss, init_mlp(jax.random.PRNGKey(0)), mesh,
        TrainConfig(batch_size=64, profile_dir=pdir))
    rng = np.random.RandomState(0)
    tr.run_epoch(x_tr[:256], y_tr[:256], rng)
    assert not os.path.exists(pdir) or not os.listdir(pdir)
    tr.run_epoch(x_tr[:256], y_tr[:256], rng)
    found = [os.path.join(r, f) for r, _, fs in os.walk(pdir) for f in fs]
    assert found, "second epoch should have written a profiler trace"


@pytest.mark.heavy
def test_digits_sheet_accuracy_both_paths_agree():
    """The APRIL-ANN capability end to end WITH ACCURACY (VERDICT r3
    item 5): train on the checked-in full-size digits sheet (the
    reference's exact 16x16/800-200 contract) through both the
    TPU-native trainer and the six-function MapReduce loop; both must
    clear the validation-accuracy bar and agree. Smaller budgets than
    the committed artifact (benchmarks/results/digits_e2e.json) — same
    code path."""
    from benchmarks.digits_e2e import run

    out = run(native_steps=150, mr_steps=30, target=0.9)
    assert out["tpu_native_path"]["val_accuracy"] >= 0.9, out
    assert out["mapreduce_path"]["val_accuracy"] >= 0.9, out
    assert out["agree_within"] <= 0.05, out


class TestAsyncCheckpoint:
    def test_background_save_round_trips(self):
        from lua_mapreduce_tpu.store.memfs import MemStore

        store = MemStore()
        tree = {"w": jnp.arange(12.0).reshape(3, 4),
                "b": jnp.ones((4,), jnp.bfloat16)}
        ac = ckpt.AsyncCheckpoint()
        ac.submit(store, "a.ckpt", tree)
        ac.wait()
        got = ckpt.load_pytree(store, "a.ckpt", tree)
        np.testing.assert_array_equal(np.asarray(got["w"], np.float32),
                                      np.asarray(tree["w"], np.float32))
        assert got["b"].dtype == jnp.bfloat16

    def test_snapshot_is_taken_at_submit_time(self):
        """The write must capture the tree AS SUBMITTED even if the
        caller's arrays are replaced (donated/overwritten) before the
        background write finishes."""
        from lua_mapreduce_tpu.store.memfs import MemStore

        store = MemStore()
        ac = ckpt.AsyncCheckpoint()
        tree = {"x": jnp.zeros((256, 256))}
        ac.submit(store, "s.ckpt", tree)
        tree["x"] = jnp.ones((256, 256))       # caller moves on
        ac.wait()
        got = ckpt.load_pytree(store, "s.ckpt", tree)
        assert float(np.asarray(got["x"]).max()) == 0.0

    def test_wait_reraises_background_failure(self):
        class BrokenStore:
            def builder(self):
                raise IOError("disk gone")

        ac = ckpt.AsyncCheckpoint()
        ac.submit(BrokenStore(), "x.ckpt", {"a": jnp.zeros(3)})
        with pytest.raises(RuntimeError, match="async checkpoint"):
            ac.wait()
        ac.wait()          # error is consumed; idle wait is clean

    def test_serializes_overlapping_submits(self):
        from lua_mapreduce_tpu.store.memfs import MemStore

        store = MemStore()
        ac = ckpt.AsyncCheckpoint()
        for i in range(5):
            ac.submit(store, "r.ckpt", {"i": jnp.full((64,), float(i))})
        ac.wait()
        got = ckpt.load_pytree(store, "r.ckpt", {"i": jnp.zeros(64)})
        assert float(np.asarray(got["i"])[0]) == 4.0
