"""Golden-diff WordCount matrix — the end-to-end correctness harness.

Analog of reference test.sh:8-73: for each storage backend × engine config
(combiner + flagged reducer; no combiner + flagged reducer; general
unflagged reducer; single-module packaging), run WordCount over the
framework's own source files and diff the result against the naive
single-process golden count (misc/naive.lua analog).
"""

import glob
import os

import pytest

from examples.wordcount.naive import naive_wordcount
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = sorted(glob.glob(os.path.join(REPO, "lua_mapreduce_tpu", "**", "*.py"),
                          recursive=True))

CONFIGS = {
    "combiner": dict(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.mapfn",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        combinerfn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
    ),
    "no_combiner": dict(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.mapfn",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
    ),
    "general_reducer": dict(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.mapfn",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn2",
        finalfn="examples.wordcount.finalfn",
    ),
    "single_module": dict(
        taskfn="examples.wordcount.single",
        mapfn="examples.wordcount.single",
        partitionfn="examples.wordcount.single",
        reducefn="examples.wordcount.single",
        combinerfn="examples.wordcount.single",
        finalfn="examples.wordcount.single",
    ),
}


def _storages(tmp_path, tag):
    return [
        f"mem:{tag}",
        f"shared:{tmp_path}/shared",
        f"object:{tmp_path}/object",
    ]


def _counts_module(config):
    if config == "single_module":
        import examples.wordcount.single as m
    else:
        import examples.wordcount.finalfn as m
    return m


@pytest.mark.parametrize("config", list(CONFIGS))
@pytest.mark.parametrize("storage_idx", [0, 1, 2],
                         ids=["mem", "shared", "object"])
def test_wordcount_matches_naive(tmp_path, config, storage_idx,
                                 no_thread_leak):
    golden = naive_wordcount(CORPUS)
    storage = _storages(tmp_path, f"wc-{config}-{storage_idx}")[storage_idx]
    spec = TaskSpec(init_args={"files": CORPUS}, storage=storage,
                    **CONFIGS[config])
    ex = LocalExecutor(spec, map_parallelism=4)
    stats = ex.run()

    got = dict(_counts_module(config).counts)
    assert got == golden

    it = stats.iterations[-1]
    assert it.map.count == len(CORPUS)
    assert 0 < it.reduce.count <= 15   # ≤ NUM_REDUCERS; empty parts tolerated
    assert it.map.failed == 0 and it.reduce.failed == 0
    assert stats.wall_time > 0


def test_wordcount_autotune_on_and_off_match_naive(tmp_path,
                                                   no_thread_leak):
    """lmr-autotune (DESIGN §29) is semantics-neutral: the adaptive run
    golden-diffs exactly like the hand-set run, and a controller-off
    run stays on the legacy path (no controller is ever built)."""
    golden = naive_wordcount(CORPUS)
    for autotune in (False, True):
        spec = TaskSpec(init_args={"files": CORPUS},
                        storage=f"mem:wc-autotune-{int(autotune)}",
                        **CONFIGS["combiner"])
        ex = LocalExecutor(spec, map_parallelism=4, autotune=autotune)
        ex.run()
        assert ex.autotune is autotune
        if not autotune:
            assert ex._controller is None
        got = dict(_counts_module("combiner").counts)
        assert got == golden


def test_single_module_init_called_once(tmp_path):
    import examples.wordcount.single as single
    before = single._init_calls
    TaskSpec(init_args={"files": CORPUS[:2]}, storage=f"mem:initdedup",
             **CONFIGS["single_module"])
    assert single._init_calls == before + 1


def test_taskfn_duplicate_keys_rejected():
    def bad_taskfn(emit):
        emit(1, "a")
        emit(1, "b")

    spec = TaskSpec(taskfn=bad_taskfn,
                    mapfn="examples.wordcount.mapfn",
                    partitionfn="examples.wordcount.partitionfn",
                    reducefn="examples.wordcount.reducefn",
                    storage="mem:dupkeys")
    with pytest.raises(ValueError, match="duplicate"):
        LocalExecutor(spec).run()


def test_taskfn_value_size_cap():
    big = "x" * (17 * 1024)

    def bad_taskfn(emit):
        emit(1, big)

    spec = TaskSpec(taskfn=bad_taskfn,
                    mapfn="examples.wordcount.mapfn",
                    partitionfn="examples.wordcount.partitionfn",
                    reducefn="examples.wordcount.reducefn",
                    storage="mem:bigval")
    with pytest.raises(ValueError, match="bytes"):
        LocalExecutor(spec).run()


def test_delete_results_on_true(tmp_path):
    spec = TaskSpec(init_args={"files": CORPUS[:3], "delete_results": True},
                    storage="mem:delres", **CONFIGS["combiner"])
    ex = LocalExecutor(spec)
    ex.run()
    assert list(ex.results()) == []


def test_loop_shrinking_keyset_has_no_stale_results():
    """Partitions untouched in a later iteration must not leak the previous
    iteration's results (regression: results are dropped per iteration,
    reference server.lua:331-345)."""
    state = {"it": 0, "seen": []}

    def taskfn(emit):
        emit(1, ["alpha", "beta"] if state["it"] == 0 else ["alpha"])

    def mapfn(key, words, emit):
        for w in words:
            emit(w, 1)

    def partitionfn(key):
        return 0 if key == "alpha" else 1

    def reducefn(key, values):
        return sum(values)

    def finalfn(pairs):
        state["seen"] = sorted(k for k, _ in pairs)
        state["it"] += 1
        return "loop" if state["it"] < 2 else None

    spec = TaskSpec(taskfn=taskfn, mapfn=mapfn, partitionfn=partitionfn,
                    reducefn=reducefn, finalfn=finalfn, storage="mem:shrink")
    LocalExecutor(spec).run()
    assert state["seen"] == ["alpha"]  # no stale "beta" from iteration 1


def test_loop_protocol_counts_iterations():
    state = {"iters": 0}

    def taskfn(emit):
        emit(1, state["iters"])

    def mapfn(key, value, emit):
        emit("count", 1)

    def partitionfn(key):
        return 0

    def reducefn(key, values):
        return sum(values)

    def finalfn(pairs):
        state["iters"] += 1
        return "loop" if state["iters"] < 5 else None

    spec = TaskSpec(taskfn=taskfn, mapfn=mapfn, partitionfn=partitionfn,
                    reducefn=reducefn, finalfn=finalfn, storage="mem:loop")
    stats = LocalExecutor(spec).run()
    assert state["iters"] == 5
    assert len(stats.iterations) == 5


def test_wordcount_big_miniature(tmp_path):
    """The Europarl-scale module at miniature scale (3 splits) golden-
    diffs against a direct count of the generated corpus."""
    from collections import Counter

    from examples.wordcount_big import corpus
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.local import LocalExecutor

    cdir = str(tmp_path / "corpus")
    corpus.build(cdir, n_splits=3)
    golden = Counter()
    for i in range(3):
        with open(corpus.split_path(cdir, i)) as f:
            for line in f:
                golden.update(line.split())

    mod = "examples.wordcount_big.bigtask"
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    init_args={"corpus_dir": cdir, "n_splits": 3},
                    storage=f"shared:{tmp_path}/spill")
    ex = LocalExecutor(spec, map_parallelism=2)
    ex.run()
    got = {k: v[0] for k, v in ex.results()}
    assert got == dict(golden)
    assert sum(got.values()) == corpus.total_words(3)


def test_in_map_combiner_bounds_memory(monkeypatch):
    """The MAX_MAP_RESULT threshold must fire MID-map (reference
    job.lua:92-96): with a skewed key emitted far past the threshold,
    the in-memory bucket is folded in place and never grows unbounded,
    and the fold loses nothing."""
    from lua_mapreduce_tpu.engine import job as jobmod
    from lua_mapreduce_tpu.engine.job import make_map_emit

    monkeypatch.setattr(jobmod, "MAX_MAP_RESULT", 50)
    seen_bucket_sizes = []

    def combiner(key, values):
        seen_bucket_sizes.append(len(values))
        return sum(values)

    result = {}
    emit = make_map_emit(result, combiner)
    for _ in range(500):                    # one hot key, 10x threshold
        emit("hot", 1)
    emit("cold", 1)

    assert seen_bucket_sizes, "combiner never fired mid-map"
    assert max(seen_bucket_sizes) <= 51     # bucket stays bounded
    # nothing lost: a final fold over the remainder gives the true count
    assert combiner("hot", result["hot"]) == 500
    assert result["cold"] == [1]
