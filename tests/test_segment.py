"""Framed binary spill segments (JSEG0001, core/segment.py): format
round-trip, the store raw-bytes surface, v1 ↔ v2 interop (mixed runs,
mixed fleets), fuzz/property equivalence of the two data planes, and the
Python ↔ native merge golden diff over segments.

Run under BOTH merge engines (test.sh): once natively, once with
LMR_DISABLE_NATIVE=1 — the conformance matrix of DESIGN §17.
"""

import json
import random
import sys
import types
import zlib

import pytest

from lua_mapreduce_tpu.core import tuples
from lua_mapreduce_tpu.core.merge import merge_iterator
from lua_mapreduce_tpu.core.segment import (FRAME_BYTES, SegmentWriter,
                                            open_segment, record_stream,
                                            writer_for)
from lua_mapreduce_tpu.core.serialize import (dump_key, dump_record, key_lt,
                                              sorted_keys)
from lua_mapreduce_tpu.store.memfs import MemStore
from lua_mapreduce_tpu.store.objectfs import ObjectStore
from lua_mapreduce_tpu.store.sharedfs import SharedStore


def _backends(tmp_path):
    return {
        "mem": MemStore(),
        "shared": SharedStore(str(tmp_path / "shared")),
        "object": ObjectStore(str(tmp_path / "object")),
    }


# ---------------------------------------------------------------------------
# format round-trip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
@pytest.mark.parametrize("codec", ["raw", "zlib"])
def test_segment_roundtrip(tmp_path, backend, codec):
    store = _backends(tmp_path)[backend]
    recs = [(f"key{i:05d}", [i, f"v{i}", [i, i + 1]]) for i in range(2000)]
    w = SegmentWriter(store.builder(), codec=codec, frame_bytes=4096)
    for k, v in recs:
        w.add(k, v)
    w.build("runs.P0.M1")

    r = open_segment(store, "runs.P0.M1")
    assert r is not None
    assert r.records == len(recs)
    assert len(r.frames) > 1              # multi-frame at this frame size
    assert r.frames[0][3] == dump_key(recs[0][0])
    got = list(r.iter_records())
    assert got == recs
    # the format-agnostic stream serves the same records
    assert list(record_stream(store, "runs.P0.M1")) == recs


@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_raw_bytes_surface(tmp_path, backend):
    """write_bytes / read_range / size on every bundled backend."""
    store = _backends(tmp_path)[backend]
    b = store.builder()
    payload = bytes(range(256)) * 5
    b.write_bytes(payload)
    b.build("blob")
    assert store.size("blob") == len(payload)
    assert store.read_range("blob", 0, 8) == payload[:8]
    assert store.read_range("blob", 300, 10) == payload[300:310]
    # short read at EOF, POSIX-style
    assert store.read_range("blob", len(payload) - 4, 100) == payload[-4:]


def test_text_shim_default_surface():
    """A Store subclass with ONLY the text methods still serves segments
    through the base-class latin-1 shim (third-party backend path)."""
    from lua_mapreduce_tpu.store.base import FileBuilder, Store

    class _ShimStore(Store):
        def __init__(self):
            self.files = {}

        def builder(self):
            outer = self

            class _B(FileBuilder):
                def __init__(self):
                    self.parts = []

                def write(self, data):
                    self.parts.append(data)

                def build(self, name):
                    outer.files[name] = "".join(self.parts)
            return _B()

        def lines(self, name):
            return iter(self.files[name].splitlines(keepends=True))

        def list(self, pattern):
            return self._match(self.files, pattern)

        def exists(self, name):
            return name in self.files

        def remove(self, name):
            self.files.pop(name, None)

    store = _ShimStore()
    recs = [(f"k{i}", [i]) for i in range(50)]
    w = writer_for(store, "v2")           # rides the write_bytes shim
    for k, v in recs:
        w.add(k, v)
    w.build("seg")
    assert list(record_stream(store, "seg")) == recs
    # and v1 text through the same shim store still sniffs as text
    w = writer_for(store, "v1")
    w.add("a", [1])
    w.build("txt")
    assert open_segment(store, "txt") is None
    assert list(record_stream(store, "txt")) == [("a", [1])]


def test_corrupt_frame_detected(tmp_path):
    store = MemStore()
    w = writer_for(store, "v2")
    for i in range(100):
        w.add(f"k{i}", [i])
    w.build("seg")
    raw = store._files["seg"]
    flip = 8 + 13 + 7                     # a payload byte of frame 0
    store._files["seg"] = raw[:flip] + bytes([raw[flip] ^ 0xFF]) + raw[flip + 1:]
    with pytest.raises((ValueError, zlib.error)):
        list(open_segment(store, "seg").iter_records())


def test_truncated_segment_detected():
    store = MemStore()
    w = writer_for(store, "v2")
    for i in range(100):
        w.add(f"k{i}", [i])
    w.build("seg")
    store._files["trunc"] = store._files["seg"][:-9]   # clip the trailer
    with pytest.raises(ValueError):
        open_segment(store, "trunc")


def test_float_fast_path_byte_identity():
    """Satellite: the dump_record fast path now covers finite floats and
    must be byte-identical to the json.dumps slow path."""
    rng = random.Random(0)
    cases = [[0.0, -0.0, 1.5, 3.141592653589793, 1e-300, -2.5e17]]
    for _ in range(200):
        cases.append([rng.choice([
            rng.random(), rng.uniform(-1e9, 1e9), float(rng.randint(0, 99)),
            rng.randint(-100, 100), f"s{rng.randint(0, 9)}"])
            for _ in range(rng.randint(0, 5))])
    cases += [[float("inf")], [float("-inf")], [float("nan")], [True], [None]]
    for values in cases:
        fast = dump_record("k", values)
        slow = json.dumps(["k", values], separators=(",", ":"),
                          ensure_ascii=False)
        assert fast == slow, (values, fast, slow)


# ---------------------------------------------------------------------------
# fuzz/property: v1 text ↔ v2 frames equivalence
# ---------------------------------------------------------------------------

def _random_key(rng, depth=0):
    choices = ["int", "float", "str", "bool", "none"]
    if depth < 2:
        choices.append("tuple")
    kind = rng.choice(choices)
    if kind == "int":
        return rng.randint(-10**12, 10**12)
    if kind == "float":
        return rng.uniform(-1e6, 1e6)
    if kind == "str":
        return "".join(rng.choice('abc XYZ0"\\\n\té漢')
                       for _ in range(rng.randint(0, 8)))
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "none":
        return None
    return tuples.intern(tuple(_random_key(rng, depth + 1)
                               for _ in range(rng.randint(0, 3))))


def _random_value(rng, depth=0):
    kind = rng.choice(["int", "float", "str", "bool", "none"] +
                      (["list", "dict"] if depth < 2 else []))
    if kind == "int":
        return rng.randint(-10**9, 10**9)
    if kind == "float":
        return rng.uniform(-1e9, 1e9)
    if kind == "str":
        return "".join(rng.choice('ab"\\\n\t €deΩ')
                       for _ in range(rng.randint(0, 10)))
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "none":
        return None
    if kind == "list":
        return [_random_value(rng, depth + 1)
                for _ in range(rng.randint(0, 4))]
    return {f"f{i}": _random_value(rng, depth + 1)
            for i in range(rng.randint(0, 3))}


def _sorted_run(rng, n):
    keys = []
    seen = set()
    while len(keys) < n:
        k = _random_key(rng)
        marker = dump_key(k)
        if marker not in seen:            # run files hold unique keys
            seen.add(marker)
            keys.append(k)
    return [(k, [_random_value(rng) for _ in range(rng.randint(1, 4))])
            for k in sorted_keys(keys)]


@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_fuzz_v1_v2_identical_streams_and_merge(tmp_path, backend):
    """Satellite: random heterogeneous records written through BOTH data
    planes read back as identical (key, values) streams, and the k-way
    merge over {all-v1} / {all-v2} / {mixed} run sets yields identical
    groups on every backend."""
    store = _backends(tmp_path)[backend]
    rng = random.Random(hash(backend) & 0xFFFF)
    runs = [_sorted_run(rng, rng.randint(5, 60)) for _ in range(5)]

    for i, run in enumerate(runs):
        for fmt in ("v1", "v2"):
            w = SegmentWriter(store.builder(), frame_bytes=512) \
                if fmt == "v2" else writer_for(store, "v1")
            for k, v in run:
                w.add(k, v)
            w.build(f"{fmt}.run{i}")
        # per-run stream equivalence (tuple keys come back interned)
        a = list(record_stream(store, f"v1.run{i}"))
        b = list(record_stream(store, f"v2.run{i}"))
        assert a == b
        assert [type(k) for k, _ in a] == [type(k) for k, _ in b]

    names_v1 = [f"v1.run{i}" for i in range(len(runs))]
    names_v2 = [f"v2.run{i}" for i in range(len(runs))]
    mixed = [(names_v1[i] if i % 2 else names_v2[i])
             for i in range(len(runs))]
    m1 = list(merge_iterator(store, names_v1))
    m2 = list(merge_iterator(store, names_v2))
    mx = list(merge_iterator(store, mixed))
    assert m1 == m2 == mx
    # merged keys are strictly ascending in the canonical order
    for (ka, _), (kb, _) in zip(m1, m1[1:]):
        assert key_lt(ka, kb)


def test_native_merge_golden_diff_over_segments(tmp_path):
    """Python heap merge vs the C++ pass over v2 (zlib-framed) segments:
    identical groups. Skips where the toolchain is absent or disabled."""
    from lua_mapreduce_tpu.core import native_merge
    if not native_merge.native_available():
        pytest.skip("native merge unavailable (toolchain/LMR_DISABLE_NATIVE)")
    store = SharedStore(str(tmp_path / "nat"))
    rng = random.Random(42)
    runs = [_sorted_run(rng, 40) for _ in range(4)]
    names = []
    for i, run in enumerate(runs):
        w = SegmentWriter(store.builder(), frame_bytes=1024)
        for k, v in run:
            w.add(k, v)
        w.build(f"seg{i}")
        names.append(f"seg{i}")
    py = list(merge_iterator(store, names))
    nat = native_merge.native_merge_records(store, names)
    if nat is None:
        pytest.skip("native pass declined these records")
    assert list(nat) == py


# ---------------------------------------------------------------------------
# engine interop: mixed formats, mixed fleets
# ---------------------------------------------------------------------------

def _wc_module(name):
    mod = types.ModuleType(name)
    corpus = {f"d{i}": " ".join(
        random.Random(i).choice(["alpha", "beta", "gamma", "delta", "eps"])
        for _ in range(200)) for i in range(8)}
    mod.taskfn = lambda emit: [emit(k, v) for k, v in corpus.items()]

    def mapfn(key, value, emit):
        for w in value.split():
            emit(w, 1)
    mod.mapfn = mapfn
    mod.partitionfn = lambda key: sum(key.encode()) % 3
    mod.reducefn = lambda key, values: sum(values)
    mod.associative_reducer = True
    mod.commutative_reducer = True
    sys.modules[name] = mod
    return mod


@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
@pytest.mark.parametrize("pipeline", [False, True])
def test_local_executor_v1_v2_byte_identical(tmp_path, backend, pipeline):
    """Acceptance: final wordcount output byte-identical between the v1
    and v2 data planes, per backend, under both shuffle modes."""
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.local import LocalExecutor

    _wc_module("_seg_interop_wc")
    outs = {}
    for fmt in ("v1", "v2"):
        storage = {
            "mem": f"mem:_seg_ip_{pipeline}_{fmt}",
            "shared": f"shared:{tmp_path}/sh_{pipeline}_{fmt}",
            "object": f"object:{tmp_path}/ob_{pipeline}_{fmt}",
        }[backend]
        spec = TaskSpec(taskfn="_seg_interop_wc", mapfn="_seg_interop_wc",
                        partitionfn="_seg_interop_wc",
                        reducefn="_seg_interop_wc", storage=storage)
        ex = LocalExecutor(spec, map_parallelism=2, pipeline=pipeline,
                           premerge_min_runs=2, segment_format=fmt)
        ex.run()
        out = {}
        for name in ex.result_store.list(f"{spec.result_ns}.P*"):
            out[name] = "".join(ex.result_store.lines(name))
        outs[fmt] = out
    assert outs["v1"] == outs["v2"]
    assert outs["v1"], "no result partitions produced"


def test_reduce_over_mixed_format_runs(tmp_path):
    """v1 writer + v2 reader and vice versa at the job level: one
    partition whose runs were written by a v1 mapper AND a v2 mapper
    reduces to the same bytes as the all-v1 and all-v2 cases."""
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.job import run_map_job, run_reduce_job

    _wc_module("_seg_mixed_wc")
    spec = TaskSpec(taskfn="_seg_mixed_wc", mapfn="_seg_mixed_wc",
                    partitionfn="_seg_mixed_wc", reducefn="_seg_mixed_wc",
                    storage="mem:_seg_mixed")
    results = {}
    for combo in (("v1", "v1"), ("v2", "v2"), ("v1", "v2"), ("v2", "v1")):
        store = SharedStore(str(tmp_path / f"mix_{combo[0]}_{combo[1]}"))
        jobs = []
        sys.modules["_seg_mixed_wc"].taskfn(
            lambda k, v: jobs.append((k, v)))
        for i, (k, v) in enumerate(jobs):
            run_map_job(spec, store, str(i), k, v,
                        segment_format=combo[i % 2])
        out = {}
        for part in (0, 1, 2):
            files = store.list(f"result.P{part}.M*")
            if not files:
                continue
            run_reduce_job(spec, store, store, str(part), files,
                           f"result.P{part}")
            out[part] = "".join(store.lines(f"result.P{part}"))
        results[combo] = out
    assert len({json.dumps(v, sort_keys=True)
                for v in results.values()}) == 1
    assert results[("v1", "v1")], "no output produced"


def test_mixed_fleet_v1_and_v2_workers(tmp_path):
    """Acceptance: a v1-only worker and a v2 worker complete the same
    task against one store — the task doc negotiates v2, one worker pins
    v1, readers sniff per file."""
    import threading

    from lua_mapreduce_tpu.coord.jobstore import MemJobStore
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.server import Server
    from lua_mapreduce_tpu.engine.worker import Worker

    _wc_module("_seg_fleet_wc")
    spec = TaskSpec(taskfn="_seg_fleet_wc", mapfn="_seg_fleet_wc",
                    partitionfn="_seg_fleet_wc", reducefn="_seg_fleet_wc",
                    storage="mem:_seg_fleet")
    store = MemJobStore()
    server = Server(store, poll_interval=0.01, segment_format="v2",
                    pipeline=True, premerge_min_runs=2).configure(spec)
    w_old = Worker(store, name="v1-only").configure(
        max_iter=600, max_sleep=0.02, segment_format="v1")
    w_new = Worker(store, name="v2").configure(max_iter=600, max_sleep=0.02)
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in (w_old, w_new)]
    for t in threads:
        t.start()
    server.loop()
    for t in threads:
        t.join(timeout=30)

    from lua_mapreduce_tpu.engine.local import iter_results
    from lua_mapreduce_tpu.store.router import get_storage_from
    got = {k: v[0] for k, v in
           iter_results(get_storage_from("mem:_seg_fleet"), "result")}
    expect = {}
    jobs = []
    sys.modules["_seg_fleet_wc"].taskfn(lambda k, v: jobs.append((k, v)))
    for _, text in jobs:
        for w in text.split():
            expect[w] = expect.get(w, 0) + 1
    assert got == expect
    assert w_old.jobs_executed + w_new.jobs_executed > 0


def test_builder_close_releases_resources(tmp_path):
    """Satellite: _DirBuilder.close() (and the context-manager form)
    deterministically stops the async writer thread and removes the
    tempfile of an abandoned builder — no reliance on GC."""
    import os

    store = SharedStore(str(tmp_path / "cl"))
    b = store.builder()
    b.write("x" * (2 << 20))              # > FLUSH_BYTES: thread starts
    assert b._thread is not None and b._thread.is_alive()
    b.close()
    assert b._thread is None
    assert b._f.closed
    assert not any(f.startswith(".tmp.")
                   for f in os.listdir(store.path))
    b.close()                             # idempotent

    with store.builder() as b2:
        b2.write("abc\n")
        tmp2 = b2._tmp
        assert os.path.exists(tmp2)
    assert not os.path.exists(tmp2)       # CM exit released it

    # close() after build is a no-op and the file survives
    b3 = store.builder()
    b3.write("keep\n")
    b3.build("kept")
    b3.close()
    assert list(store.lines("kept")) == ["keep\n"]


def test_worker_rejects_bad_segment_format():
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore
    from lua_mapreduce_tpu.engine.server import Server

    with pytest.raises(ValueError):
        Server(MemJobStore(), segment_format="v3")
    from lua_mapreduce_tpu.engine.job import run_map_job
    with pytest.raises(ValueError):
        run_map_job(None, None, "0", "k", "v", segment_format="binary")
