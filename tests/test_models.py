"""Model-zoo tests: the conv models from the BASELINE.json configs
(LeNet-5/CIFAR-10, ResNet-18/ImageNet) built on the framework's own TPU
ops, trained data-parallel over the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.models import lenet
from lua_mapreduce_tpu.parallel.mesh import host_mesh
from lua_mapreduce_tpu.train.data import make_images
from lua_mapreduce_tpu.train.harness import DataParallelTrainer, TrainConfig


@pytest.fixture(scope="module")
def mesh():
    return host_mesh(8)


@pytest.fixture(scope="module")
def images():
    return make_images(seed=0, n_train=512, n_val=128)


class TestLeNet:
    def test_forward_shape_and_normalization(self, images):
        params = lenet.init_lenet(jax.random.PRNGKey(0))
        x = jnp.asarray(images[0][:8])
        logp = lenet_out = lenet.lenet_apply(params, x)
        assert lenet_out.shape == (8, 10)
        # log_softmax output: probabilities sum to 1
        np.testing.assert_allclose(
            np.exp(np.asarray(logp)).sum(axis=1), 1.0, atol=1e-5)

    @pytest.mark.heavy
    def test_gradients_flow_to_every_param(self, images):
        params = lenet.init_lenet(jax.random.PRNGKey(1))
        x = jnp.asarray(images[0][:16])
        y = jnp.asarray(images[1][:16])
        grads = jax.grad(lenet.nll_loss)(params, x, y)
        assert set(grads) == set(params)
        for name, g in grads.items():
            assert np.isfinite(np.asarray(g)).all(), name
            assert float(jnp.abs(g).max()) > 0.0, f"dead gradient: {name}"

    @pytest.mark.heavy
    def test_dp_training_learns(self, mesh, images):
        """A few DP epochs on the synthetic image classes must beat
        chance by a wide margin (the golden 'it trains' check)."""
        x_tr, y_tr, x_va, y_va = images
        params = lenet.init_lenet(jax.random.PRNGKey(2))
        tr = DataParallelTrainer(
            lenet.nll_loss, params, mesh,
            TrainConfig(batch_size=64, learning_rate=0.05, max_epochs=5,
                        patience=5))
        rng = np.random.RandomState(0)
        for _ in range(5):
            tr.run_epoch(x_tr, y_tr, rng)
        acc = float(lenet.accuracy(tr.params, jnp.asarray(x_va),
                                   jnp.asarray(y_va)))
        assert acc > 0.5, f"accuracy {acc} barely above chance"

    def test_flops_accounting_positive(self):
        assert lenet.flops_per_example() > 1e6


class TestResNet:
    @pytest.mark.heavy
    def test_forward_shape_imagenet_topology(self):
        """Full ResNet-18 wiring at reduced resolution: the imagenet stem
        (7x7/2 + maxpool) and all four stages must compose."""
        from lua_mapreduce_tpu.models import resnet
        cfg = resnet.ResNetConfig(input_shape=(64, 64, 3), n_classes=1000)
        params = resnet.init_resnet(jax.random.PRNGKey(0), cfg)
        x = jnp.asarray(np.random.RandomState(0).rand(2, 64, 64, 3),
                        jnp.float32)
        logp = resnet.resnet_apply(params, x, cfg=cfg)
        assert logp.shape == (2, 1000)
        np.testing.assert_allclose(
            np.exp(np.asarray(logp)).sum(axis=1), 1.0, atol=1e-4)

    @pytest.mark.heavy
    def test_gradients_flow_to_every_param(self):
        from lua_mapreduce_tpu.models import resnet
        cfg = resnet.ResNetConfig.tiny()
        params = resnet.init_resnet(jax.random.PRNGKey(1), cfg)
        x = jnp.asarray(np.random.RandomState(1).rand(4, 16, 16, 3),
                        jnp.float32)
        y = jnp.asarray(np.random.RandomState(2).randint(0, 10, 4))
        grads = jax.grad(resnet.make_loss(cfg))(params, x, y)
        assert set(grads) == set(params)
        for name, g in grads.items():
            assert np.isfinite(np.asarray(g)).all(), name
            assert float(jnp.abs(g).max()) > 0.0, f"dead gradient: {name}"

    @pytest.mark.heavy
    def test_dp_training_learns(self, mesh):
        from lua_mapreduce_tpu.models import resnet
        cfg = resnet.ResNetConfig(input_shape=(16, 16, 3), n_classes=10,
                                  widths=(16, 32), blocks_per_stage=(1, 1),
                                  imagenet_stem=False, norm_groups=8)
        x_tr, y_tr, x_va, y_va = make_images(
            seed=3, n_train=256, n_val=128, shape=(16, 16, 3))
        params = resnet.init_resnet(jax.random.PRNGKey(2), cfg)
        tr = DataParallelTrainer(
            resnet.make_loss(cfg), params, mesh,
            TrainConfig(batch_size=64, learning_rate=0.1, max_epochs=6,
                        patience=6))
        rng = np.random.RandomState(0)
        for _ in range(6):
            tr.run_epoch(x_tr, y_tr, rng)
        acc = float(resnet.accuracy(tr.params, jnp.asarray(x_va),
                                    jnp.asarray(y_va), cfg=cfg))
        assert acc > 0.5, f"accuracy {acc} barely above chance"

    def test_flops_accounting_imagenet_scale(self):
        from lua_mapreduce_tpu.models import resnet
        # ResNet-18 fwd ≈ 3.6 GFLOPs/img at 224²; fwd+bwd accounting = 3x
        f = resnet.flops_per_example(resnet.ResNetConfig.imagenet18())
        assert 8e9 < f < 13e9, f
