"""Model-zoo tests: the conv models from the BASELINE.json configs
(LeNet-5/CIFAR-10, ResNet-18/ImageNet) built on the framework's own TPU
ops, trained data-parallel over the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.models import lenet
from lua_mapreduce_tpu.parallel.mesh import host_mesh
from lua_mapreduce_tpu.train.data import make_images
from lua_mapreduce_tpu.train.harness import DataParallelTrainer, TrainConfig


@pytest.fixture(scope="module")
def mesh():
    return host_mesh(8)


@pytest.fixture(scope="module")
def images():
    return make_images(seed=0, n_train=512, n_val=128)


class TestLeNet:
    def test_forward_shape_and_normalization(self, images):
        params = lenet.init_lenet(jax.random.PRNGKey(0))
        x = jnp.asarray(images[0][:8])
        logp = lenet_out = lenet.lenet_apply(params, x)
        assert lenet_out.shape == (8, 10)
        # log_softmax output: probabilities sum to 1
        np.testing.assert_allclose(
            np.exp(np.asarray(logp)).sum(axis=1), 1.0, atol=1e-5)

    def test_gradients_flow_to_every_param(self, images):
        params = lenet.init_lenet(jax.random.PRNGKey(1))
        x = jnp.asarray(images[0][:16])
        y = jnp.asarray(images[1][:16])
        grads = jax.grad(lenet.nll_loss)(params, x, y)
        assert set(grads) == set(params)
        for name, g in grads.items():
            assert np.isfinite(np.asarray(g)).all(), name
            assert float(jnp.abs(g).max()) > 0.0, f"dead gradient: {name}"

    def test_dp_training_learns(self, mesh, images):
        """A few DP epochs on the synthetic image classes must beat
        chance by a wide margin (the golden 'it trains' check)."""
        x_tr, y_tr, x_va, y_va = images
        params = lenet.init_lenet(jax.random.PRNGKey(2))
        tr = DataParallelTrainer(
            lenet.nll_loss, params, mesh,
            TrainConfig(batch_size=64, learning_rate=0.05, max_epochs=5,
                        patience=5))
        rng = np.random.RandomState(0)
        for _ in range(5):
            tr.run_epoch(x_tr, y_tr, rng)
        acc = float(lenet.accuracy(tr.params, jnp.asarray(x_va),
                                   jnp.asarray(y_va)))
        assert acc > 0.5, f"accuracy {acc} barely above chance"

    def test_flops_accounting_positive(self):
        assert lenet.flops_per_example() > 1e6
