"""In-graph execution engine tests (engine/ingraph.py, DESIGN §26).

The compiled plane's whole contract, golden-diffed against the
interpreted store plane on both executors:

- byte-identical output for integer-keyed workloads (the wordcount
  sum-reducer shape and the extsort range-partition/identity-reduce
  singleton-fast-path shape),
- allclose output for float workloads (kmeans / ALS / digits SGD;
  atol 1e-4 — the two planes may reassociate float folds),
- the "loop" protocol compiling exactly ONCE per task (the no-retrace
  compile counter),
- oracle/runtime agreement: a task the static oracle verdicts in-graph
  but whose lowering raises at trace time degrades to the store plane
  under ``engine="auto"`` with ``ingraph_fallbacks`` bumped and
  byte-identical output — and RAISES under the ``engine="ingraph"``
  hard mode,
- the decision/fallback surfacing: ``lowering`` / ``ingraph.run`` /
  ``ingraph.fallback`` trace spans and the per-iteration engine map.
"""

import os
import sys
import threading

import numpy as np
import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.ingraph import (LoweringError, resolve_engine,
                                              select_engine)
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.engine.server import Server
from lua_mapreduce_tpu.engine.worker import Worker

# ---------------------------------------------------------------------------
# fixture task modules (materialized on sys.path so the STATIC oracle can
# resolve them — the in-graph selection path never imports, it parses)
# ---------------------------------------------------------------------------

# the wordcount sum-reducer shape with integer keys/values: mapfn buckets
# this shard's token ids, the REAL examples.wordcount.reducefn sums the
# counts — integer folds must be BYTE-identical across the planes
IG_SUM = """
import jax.numpy as jnp

def taskfn(emit):
    for j in range(6):
        emit(j, {"ids": [(j * 13 + i * 7) % 8 for i in range(32)]})

def mapfn(key, value, emit):
    ids = jnp.asarray(value["ids"], jnp.int32)
    for b in range(8):
        emit(b, jnp.sum(jnp.where(ids == b, 1, 0)))

def partitionfn(key):
    return int(key) % 3
"""

# the extsort shape: unique integer keys, range partitionfn monotone in
# the key, identity reducefn flagged ACI — every group is a singleton,
# exercising the merge fast path on both planes
IG_SORT = """
import jax.numpy as jnp

def taskfn(emit):
    for j in range(4):
        emit(j, {"vals": [(j * 16 + i) * 7 % 101 for i in range(16)]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["vals"], jnp.int32)
    for i in range(16):
        emit(int(key) * 16 + i, {"v": v[i] * 2})

def partitionfn(key):
    return (int(key) * 4) // 64

def reducefn(key, values):
    return values[0]

reducefn.associative_reducer = True
reducefn.commutative_reducer = True
reducefn.idempotent_reducer = True
"""

# oracle/runtime disagreement: every call is inside the oracle's
# whitelisted surface (verdict: in-graph), but the emitted KEY is a
# traced value — the lowering refuses data-dependent key spaces at
# trace time, so engine=auto must degrade to the store plane (where a
# concrete jax scalar key is fine) and engine=ingraph must raise
IG_TRACED_KEY = """
import jax.numpy as jnp

def taskfn(emit):
    for j in range(4):
        emit(j, {"v": [float(j + 1), 2.0]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["v"], jnp.float32)
    emit(jnp.sum(v), {"s": v[0]})

def partitionfn(key):
    return int(key) % 2

def reducefn(key, values):
    s = jnp.asarray(values[0]["s"])
    for i in range(1, len(values)):
        s = s + jnp.asarray(values[i]["s"])
    return {"s": s}
"""


@pytest.fixture(scope="module")
def igmod(tmp_path_factory):
    """Materialize fixture task sources as importable modules on
    sys.path (the oracle resolves module NAMES statically; tmp modules
    must be visible to both importlib and resolve_spec)."""
    root = tmp_path_factory.mktemp("igtasks")
    sys.path.insert(0, str(root))
    made = []

    def factory(name: str, src: str) -> str:
        path = root / f"{name}.py"
        path.write_text(src)
        made.append(name)
        return name

    yield factory
    sys.path.remove(str(root))
    for name in made:
        sys.modules.pop(name, None)


def _result_bytes(store, result_ns="result"):
    import re
    pat = re.compile(rf"^{re.escape(result_ns)}\.P(\d+)$")
    return {n: "".join(store.lines(n))
            for n in store.list(f"{result_ns}.P*") if pat.match(n)}


def _local(mod, engine, tag, *, reducefn=None, partitionfn=None,
           finalfn=None, init_args=None, **kw):
    spec = TaskSpec(taskfn=mod, mapfn=mod,
                    partitionfn=partitionfn or mod,
                    reducefn=reducefn or mod,
                    finalfn=finalfn, init_args=init_args,
                    storage=f"mem:ig-{tag}")
    ex = LocalExecutor(spec, engine=engine, **kw)
    ex.run()
    return ex


# ---------------------------------------------------------------------------
# golden diffs: integer byte-identity, LocalExecutor
# ---------------------------------------------------------------------------

def test_int_sum_reducer_byte_identical(igmod):
    mod = igmod("ig_sum_a", IG_SUM)
    ex_s = _local(mod, "store", "sum-s", reducefn="examples.wordcount.reducefn")
    ex_i = _local(mod, "ingraph", "sum-i", reducefn="examples.wordcount.reducefn")
    assert ex_i.engine_decision.verdict == "in-graph"
    assert ex_i.engine_decision.chosen == "ingraph"
    assert _result_bytes(ex_i.result_store) == _result_bytes(ex_s.result_store)
    assert ex_i._ingraph.engine.traces == 1
    assert ex_i.stats.iterations[-1].ingraph_iterations == 1
    assert ex_i.stats.iterations[-1].ingraph_fallbacks == 0
    # the store leg ran zero compiled iterations
    assert ex_s.stats.iterations[-1].ingraph_iterations == 0


def test_int_sum_auto_selects_ingraph(igmod):
    mod = igmod("ig_sum_b", IG_SUM)
    ex_a = _local(mod, "auto", "sum-auto",
                  reducefn="examples.wordcount.reducefn")
    assert ex_a.engine_decision.requested == "auto"
    assert ex_a.engine_decision.chosen == "ingraph"
    assert ex_a.stats.iterations[-1].ingraph_iterations == 1


def test_int_sort_singleton_fastpath_byte_identical(igmod):
    mod = igmod("ig_sort", IG_SORT)
    ex_s = _local(mod, "store", "sort-s")
    ex_i = _local(mod, "ingraph", "sort-i")
    out_s, out_i = (_result_bytes(ex_s.result_store),
                    _result_bytes(ex_i.result_store))
    assert out_i == out_s
    # range partition: 4 partitions, 16 unique singleton keys each
    assert len(out_i) == 4
    assert sum(o.count("\n") for o in out_i.values()) == 64


# ---------------------------------------------------------------------------
# golden diffs: float allclose (kmeans / ALS / digits), loop no-retrace
# ---------------------------------------------------------------------------

def _run_kmeans(engine, tag, **args):
    from examples.kmeans import mr_kmeans
    mod = "examples.kmeans.mr_kmeans"
    init_args = {"k": 8, "n": 512, "dim": 8, "n_shards": 4,
                 "max_iters": 4, "tol": 0.0, "seed": 11, "coord": "mem",
                 **args}
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    finalfn=mod, init_args=init_args,
                    storage=f"mem:igkm-{tag}")
    ex = LocalExecutor(spec, engine=engine, max_iterations=10)
    ex.run()
    return ex, mr_kmeans.read_state("mem")


def test_kmeans_allclose_and_compile_once():
    ex_s, st_s = _run_kmeans("store", "s")
    ex_i, st_i = _run_kmeans("ingraph", "i")
    assert st_i["iter"] == st_s["iter"] == 4
    np.testing.assert_allclose(st_i["centroids"], st_s["centroids"],
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(st_i["sse"], st_s["sse"], rtol=1e-4)
    # the "loop" protocol threads fresh centroid arrays through the SAME
    # compiled program: one trace across all 4 iterations
    assert ex_i._ingraph.engine.traces == 1
    assert sum(it.ingraph_iterations for it in ex_i.stats.iterations) == 4


def test_als_allclose():
    from examples.als import mr_als
    mod = "examples.als.mr_als"

    def run(engine, tag):
        args = {"n_users": 64, "n_items": 16, "rank": 4, "density": 0.4,
                "reg": 0.1, "n_shards": 4, "max_iters": 3, "seed": 9,
                "coord": "mem"}
        spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod,
                        reducefn=mod, finalfn=mod, init_args=args,
                        storage=f"mem:igals-{tag}")
        ex = LocalExecutor(spec, engine=engine, max_iterations=5)
        ex.run()
        return ex, mr_als.read_state("mem")

    ex_s, st_s = run("store", "s")
    ex_i, st_i = run("ingraph", "i")
    np.testing.assert_allclose(st_i["item_factors"], st_s["item_factors"],
                               rtol=1e-4, atol=1e-4)
    assert ex_i._ingraph.engine.traces == 1


def test_digits_sgd_allclose_collective_tier():
    from examples.digits import mr_sgd
    mod = "examples.digits.mr_sgd"

    def run(engine, tag):
        spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod,
                        reducefn=mod, finalfn=mod,
                        init_args={"max_steps": 5, "seed": 2},
                        storage=f"mem:igsgd-{tag}")
        ex = LocalExecutor(spec, engine=engine, max_iterations=10)
        ex.run()
        st = mr_sgd.read_state()
        return ex, ({k: v.copy() for k, v in st["params"].items()},
                    st["val_loss"])

    ex_s, (p_s, val_s) = run("store", "s")
    ex_i, (p_i, val_i) = run("ingraph", "i")
    # numeric keys + uniform per-job emission: the COLLECTIVE tier
    # (shard_map over the mesh's dp axis) must carry this workload
    assert ex_i._ingraph.engine.mode == "shard_map"
    assert ex_i._ingraph.engine.traces == 1
    for k in p_s:
        np.testing.assert_allclose(p_i[k], p_s[k], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(val_i, val_s, rtol=1e-4)


# ---------------------------------------------------------------------------
# both executors: the Server runs the compiled plane itself
# ---------------------------------------------------------------------------

def _server_store_pool(spec, n_workers=2):
    store = MemJobStore()
    server = Server(store, poll_interval=0.02, engine="store").configure(spec)
    workers = [Worker(store).configure(max_iter=400, max_sleep=0.05)
               for _ in range(n_workers)]
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    stats = server.loop()
    for t in threads:
        t.join(timeout=30)
    return server, stats


def test_server_int_sum_byte_identical(igmod):
    mod = igmod("ig_sum_srv", IG_SUM)

    def spec(tag):
        return TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod,
                        reducefn="examples.wordcount.reducefn",
                        storage=f"mem:igsrv-{tag}")

    # in-graph: the server computes the data plane itself — NO workers
    sp_i = spec("i")
    server = Server(MemJobStore(), poll_interval=0.02,
                    engine="ingraph").configure(sp_i)
    stats_i = server.loop()
    assert server._ingraph.engine.traces == 1
    assert stats_i.iterations[-1].ingraph_iterations == 1
    # the engine knob is task-doc deployed (sticky on resume)
    assert server.store.get_task()["engine"] == "ingraph"

    _, stats_s = _server_store_pool(spec("s"))
    from lua_mapreduce_tpu.store.router import get_storage_from
    assert _result_bytes(get_storage_from("mem:igsrv-i")) == \
        _result_bytes(get_storage_from("mem:igsrv-s"))
    assert stats_s.iterations[-1].ingraph_iterations == 0


def test_server_kmeans_loop_matches_local_store():
    """Server-compiled kmeans ≡ LocalExecutor-interpreted kmeans
    (allclose), with the multi-iteration loop compiling once."""
    from examples.kmeans import mr_kmeans
    mod = "examples.kmeans.mr_kmeans"
    args = {"k": 4, "n": 256, "dim": 4, "n_shards": 4, "max_iters": 3,
            "tol": 0.0, "seed": 13, "coord": "mem"}
    _, st_local = _run_kmeans("store", "twin", **args)

    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    finalfn=mod, init_args=args, storage="mem:igkmsrv")
    server = Server(MemJobStore(), poll_interval=0.02,
                    engine="auto").configure(spec)
    stats = server.loop()
    st_srv = mr_kmeans.read_state("mem")
    assert st_srv["iter"] == 3
    np.testing.assert_allclose(st_srv["centroids"], st_local["centroids"],
                               rtol=1e-4, atol=1e-4)
    assert server._ingraph.engine.traces == 1
    assert sum(it.ingraph_iterations for it in stats.iterations) == 3


# ---------------------------------------------------------------------------
# oracle/runtime agreement: trace-time failure degrades (auto) / raises
# (forced) — the DESIGN §26 never-crash ladder
# ---------------------------------------------------------------------------

def test_auto_fallback_on_trace_failure_byte_identical(igmod):
    mod = igmod("ig_traced_key", IG_TRACED_KEY)
    ex_s = _local(mod, "store", "fb-s")
    ex_a = _local(mod, "auto", "fb-a")
    # the static oracle accepted it...
    assert ex_a.engine_decision.verdict == "in-graph"
    assert ex_a.engine_decision.chosen == "ingraph"
    # ...the lowering refused it at trace time, and the iteration
    # re-ran on the store plane: counted, engine retired, bytes equal
    it = ex_a.stats.iterations[-1]
    assert it.ingraph_fallbacks == 1
    assert it.ingraph_iterations == 0
    assert ex_a._ingraph.engine is None
    assert _result_bytes(ex_a.result_store) == _result_bytes(ex_s.result_store)


def test_auto_fallback_server_degrades_to_store_plane(igmod):
    """The server-side degrade: workers carry the re-run store phases,
    the task doc records engine=store (sticky for any resume)."""
    mod = igmod("ig_traced_key_srv", IG_TRACED_KEY)
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    storage="mem:igfbsrv")
    store = MemJobStore()
    server = Server(store, poll_interval=0.02, engine="auto").configure(spec)
    workers = [Worker(store).configure(max_iter=400, max_sleep=0.05)
               for _ in range(2)]
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    stats = server.loop()
    for t in threads:
        t.join(timeout=30)
    it = stats.iterations[-1]
    assert it.ingraph_fallbacks == 1 and it.ingraph_iterations == 0
    assert store.get_task()["engine"] == "store"
    # the degraded run still produced the store plane's exact bytes
    ex_s = _local(mod, "store", "fbsrv-twin")
    from lua_mapreduce_tpu.store.router import get_storage_from
    assert _result_bytes(get_storage_from("mem:igfbsrv")) == \
        _result_bytes(ex_s.result_store)


def test_hard_mode_raises_instead_of_falling_back(igmod):
    mod = igmod("ig_traced_key_hard", IG_TRACED_KEY)
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    storage="mem:ig-hard")
    ex = LocalExecutor(spec, engine="ingraph")
    with pytest.raises(LoweringError):
        ex.run()


def test_hard_mode_forces_store_plane_task(igmod):
    """engine=ingraph on a host-bound task (oracle verdict store-plane)
    still tries — and raises at trace time instead of silently running
    the store plane: the CI mode must not mask a lost lowering."""
    src = IG_SUM.replace('emit(b, jnp.sum(jnp.where(ids == b, 1, 0)))',
                         'emit(b, sorted(value["ids"])[0])')
    mod = igmod("ig_hostbound", src)
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod,
                    reducefn="examples.wordcount.reducefn",
                    storage="mem:ig-hard2")
    dec = select_engine(spec, "ingraph")
    assert dec.requested == "ingraph" and dec.chosen == "ingraph"
    assert dec.verdict == "store-plane"
    ex = LocalExecutor(spec, engine="ingraph")
    with pytest.raises(LoweringError):
        ex.run()


def test_auto_store_plane_task_never_crashes(igmod):
    """engine=auto on a store-plane-verdicted task never compiles the
    whole plane: the offending function is named in the reason, zero
    whole-task compiled iterations, normal output. Since DESIGN §28 the
    ladder may still take the stage-granular hybrid rung for any
    per-function leg that qualifies (tests/test_hybrid.py owns that
    surface) — what it must NOT do is choose ingraph or crash."""
    src = IG_SUM.replace('emit(b, jnp.sum(jnp.where(ids == b, 1, 0)))',
                         'emit(str(b), 1)')
    mod = igmod("ig_storeplane", src)
    ex = _local(mod, "auto", "sp-auto",
                reducefn="examples.wordcount.reducefn")
    assert ex.engine_decision.chosen in ("store", "hybrid")
    assert ex.engine_decision.verdict == "store-plane"
    assert "mapfn" in ex.engine_decision.reason
    it = ex.stats.iterations[-1]
    assert it.ingraph_iterations == 0 and it.ingraph_fallbacks == 0
    assert len(_result_bytes(ex.result_store)) > 0


def test_auto_unresolvable_spec_degrades():
    """Dict/callable module specs can't be statically checked: auto
    degrades to the store plane with a reason, never a crash."""
    spec = TaskSpec(taskfn={"taskfn": lambda e: e(0, {"v": [1.0]})},
                    mapfn={"mapfn": lambda k, v, e: e(0, v["v"][0])},
                    partitionfn={"partitionfn": lambda k: 0},
                    reducefn={"reducefn": lambda k, vs: sum(vs)},
                    storage="mem:ig-dicts")
    ex = LocalExecutor(spec, engine="auto")
    ex.run()
    assert ex.engine_decision.chosen == "store"
    assert len(_result_bytes(ex.result_store)) == 1


# ---------------------------------------------------------------------------
# knob resolution + observability
# ---------------------------------------------------------------------------

def test_engine_env_resolution(monkeypatch):
    monkeypatch.setenv("LMR_ENGINE", "store")
    assert resolve_engine(None) == "store"
    monkeypatch.setenv("LMR_ENGINE", "ingraph")
    assert resolve_engine(None) == "ingraph"
    assert resolve_engine("store") == "store"   # explicit arg wins
    monkeypatch.delenv("LMR_ENGINE")
    assert resolve_engine(None) == "auto"
    with pytest.raises(ValueError):
        resolve_engine("tpu")


def test_cli_engine_flags():
    from lua_mapreduce_tpu.cli.execute_server import \
        build_parser as server_parser
    from lua_mapreduce_tpu.cli.execute_worker import \
        build_parser as worker_parser
    args = server_parser().parse_args(
        ["mem", "t", "m", "p", "r", "--engine", "ingraph"])
    assert args.engine == "ingraph"
    assert server_parser().parse_args(["mem", "t", "m", "p", "r"]).engine \
        is None                       # None → LMR_ENGINE env → "auto"
    assert worker_parser().parse_args(
        ["mem", "--engine", "store"]).engine == "store"
    with pytest.raises(SystemExit):
        server_parser().parse_args(
            ["mem", "t", "m", "p", "r", "--engine", "gpu"])


def test_lowering_spans_and_engine_report(igmod):
    from lua_mapreduce_tpu.store.router import get_storage_from
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    from lua_mapreduce_tpu.trace.span import Tracer, install_tracer

    mod = igmod("ig_sum_traced", IG_SUM)
    install_tracer(Tracer())
    try:
        ex = _local(mod, "auto", "span-i",
                    reducefn="examples.wordcount.reducefn")
    finally:
        install_tracer(None)
    col = TraceCollection.from_store(get_storage_from("mem:ig-span-i"))
    decs = col.lowering_decisions()
    assert decs and decs[0]["span"] == "lowering"
    assert decs[0]["engine"] == "ingraph"
    assert decs[0]["requested"] == "auto"
    assert decs[0]["verdict"] == "in-graph"
    assert "fn.mapfn" in decs[0]
    assert col.engines_by_iteration() == {1: "ingraph"}
    assert any(s["name"] == "ingraph.run" for s in col.spans)


def test_fallback_span_and_engine_report(igmod):
    from lua_mapreduce_tpu.store.router import get_storage_from
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    from lua_mapreduce_tpu.trace.span import Tracer, install_tracer

    mod = igmod("ig_traced_key_span", IG_TRACED_KEY)
    install_tracer(Tracer())
    try:
        _local(mod, "auto", "span-fb")
    finally:
        install_tracer(None)
    col = TraceCollection.from_store(get_storage_from("mem:ig-span-fb"))
    decs = col.lowering_decisions()
    spans = [d["span"] for d in decs]
    assert spans[0] == "lowering" and "ingraph.fallback" in spans
    fb = decs[spans.index("ingraph.fallback")]
    assert "traced" in fb.get("reason", "") or "key" in fb.get("reason", "")
    # the iteration's results came from the store plane — the engine
    # map must say so (the fallback is visible above, not silent)
    assert col.engines_by_iteration() == {1: "store"}


# the review-hardening regressions: combiner normalization, int32
# overflow refusal, and the collective tier's key-value-free signature

IG_COMBINER = """
import jax.numpy as jnp

def taskfn(emit):
    for j in range(4):
        emit(j, {"v": [float(j + 1), 2.0]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["v"], jnp.float32)
    emit(0, {"s": jnp.sum(v)})
    emit(0, {"s": v[0] * 2.0})

def partitionfn(key):
    return int(key) % 2

def reducefn(key, values):
    s = jnp.asarray(values[0]["s"])
    for i in range(1, len(values)):
        s = s + jnp.asarray(values[i]["s"])
    return {"s": s}

combinerfn = reducefn
reducefn.associative_reducer = True
reducefn.commutative_reducer = True
"""

IG_KEY_LOOP = """
import jax.numpy as jnp

STEP = [0]

def taskfn(emit):
    for i in range(8):
        emit(STEP[0] * 8 + i, {"v": [float(i + 1), 2.0]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["v"], jnp.float32)
    emit(0, {"s": jnp.sum(v) + 0.0 * key})

def partitionfn(key):
    return 0

def reducefn(key, values):
    s = jnp.asarray(values[0]["s"])
    for i in range(1, len(values)):
        s = s + jnp.asarray(values[i]["s"])
    return {"s": s}

reducefn.associative_reducer = True
reducefn.commutative_reducer = True

def finalfn(pairs):
    STEP[0] += 1
    return False if STEP[0] >= 3 else "loop"
"""


def test_array_combiner_normalized_on_store_plane(igmod):
    """An array-returning combinerfn must serialize on the store plane
    exactly like emitted values do (to_plain at the combine sites) —
    and agree with the compiled plane that traces the same combiner."""
    mod = igmod("ig_combiner", IG_COMBINER)

    def run(engine, tag):
        spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod,
                        reducefn=mod, combinerfn=mod,
                        storage=f"mem:ig-comb-{tag}")
        ex = LocalExecutor(spec, engine=engine)
        ex.run()
        return ex

    ex_s = run("store", "s")
    ex_i = run("ingraph", "i")
    out_s = _result_bytes(ex_s.result_store)
    assert out_s and out_s == _result_bytes(ex_i.result_store)


def test_int64_job_values_degrade_to_store(igmod):
    """Integers outside int32 range must NOT silently wrap on the
    compiled plane: auto degrades to the store plane (counted) and the
    exact values survive."""
    src = """
def taskfn(emit):
    for j in range(4):
        emit(j, {"ids": [3_000_000_000 + j]})

def mapfn(key, value, emit):
    emit(0, value["ids"][0])
    emit(1, value["ids"][0] % 97)

def partitionfn(key):
    return int(key) % 2
"""
    mod = igmod("ig_bigint", src)
    ex_s = _local(mod, "store", "big-s",
                  reducefn="examples.wordcount.reducefn")
    ex_a = _local(mod, "auto", "big-a",
                  reducefn="examples.wordcount.reducefn")
    assert ex_a.engine_decision.chosen == "ingraph"   # oracle accepted
    assert ex_a.stats.iterations[-1].ingraph_fallbacks == 1
    assert _result_bytes(ex_a.result_store) == _result_bytes(ex_s.result_store)


def test_collective_tier_no_retrace_on_key_values(igmod):
    """On the shard_map tier job keys ride as a traced argument: a loop
    emitting iteration-dependent NUMERIC keys must still compile once
    (the jit tier, which bakes keys, legitimately recompiles)."""
    mod = igmod("ig_key_loop", IG_KEY_LOOP)
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    finalfn=mod, storage="mem:ig-keyloop")
    ex = LocalExecutor(spec, engine="ingraph", max_iterations=5)
    ex.run()
    assert ex._ingraph.engine.mode == "shard_map"
    assert ex._ingraph.engine.traces == 1
    assert sum(it.ingraph_iterations for it in ex.stats.iterations) == 3


def test_counter_schema():
    from lua_mapreduce_tpu.utils.stats import COUNTER_FOLD, IterationStats
    assert "ingraph_iterations" in COUNTER_FOLD
    assert "ingraph_fallbacks" in COUNTER_FOLD
    d = IterationStats(iteration=1).as_dict()
    assert d["ingraph_iterations"] == 0 and d["ingraph_fallbacks"] == 0
