"""Weight-only int8 matmul (ops/q8.py): quantization error bounds,
kernel-vs-oracle parity, shape handling, and validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu import ops
from lua_mapreduce_tpu.ops.q8 import _dequant_matmul_xla


def _wx(seed, m, k, n):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(k, n).astype(np.float32))
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    return w, x


def test_quantize_roundtrip_error_bound():
    w, _ = _wx(0, 1, 128, 256)
    q, s = ops.quantize_q8(w)
    assert q.dtype == jnp.int8 and s.shape == (1, 256)
    # symmetric per-channel: error <= half a quantization step per entry
    step = np.asarray(s)[0]
    err = np.abs(np.asarray(q, np.float32) * np.asarray(s) -
                 np.asarray(w))
    assert (err <= step[None, :] * 0.5 + 1e-7).all()


def test_kernel_matches_oracle_bf16_matched():
    """Interpret kernel vs the SAME-precision oracle (bf16 x, f32
    accumulate, post-scale): agreement to accumulation noise."""
    w, x = _wx(1, 4, 300, 500)               # ragged: padding paths
    q, s = ops.quantize_q8(w)
    got = ops.q8_matmul(x, q, s.reshape(-1),
                        backend="pallas_interpret")
    want = _dequant_matmul_xla(x.astype(jnp.bfloat16), q,
                               s.reshape(1, -1))
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_quantized_matmul_close_to_full_precision():
    """End-to-end quantization error at the op level stays small
    relative to the output scale (the serving-accuracy argument)."""
    w, x = _wx(2, 8, 512, 256)
    q, s = ops.quantize_q8(w)
    got = ops.q8_matmul(x, q, s.reshape(-1), backend="xla")
    want = x @ w
    denom = float(jnp.std(want))
    rel = float(jnp.max(jnp.abs(got - want))) / denom
    assert rel < 0.05, rel


def test_single_row_matvec():
    w, x = _wx(3, 1, 256, 128)               # the decode matvec shape
    q, s = ops.quantize_q8(w)
    got = ops.q8_matmul(x, q, s.reshape(-1),
                        backend="pallas_interpret")
    want = _dequant_matmul_xla(x.astype(jnp.bfloat16), q,
                               s.reshape(1, -1))
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_validation():
    w, x = _wx(4, 2, 64, 32)
    q, s = ops.quantize_q8(w)
    with pytest.raises(ValueError, match="int8"):
        ops.q8_matmul(x, w, s.reshape(-1))
    with pytest.raises(ValueError, match="contraction"):
        ops.q8_matmul(x[:, :32], q, s.reshape(-1))
    with pytest.raises(ValueError, match="channels"):
        ops.q8_matmul(x, q, s.reshape(-1)[:16])


def test_module_utest():
    from lua_mapreduce_tpu.ops import q8

    q8.utest()
