"""Weight-only int8 matmul (ops/q8.py): quantization error bounds,
kernel-vs-oracle parity, shape handling, and validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu import ops
from lua_mapreduce_tpu.ops.q8 import _dequant_matmul_xla


def _wx(seed, m, k, n):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(k, n).astype(np.float32))
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    return w, x


def test_quantize_roundtrip_error_bound():
    w, _ = _wx(0, 1, 128, 256)
    q, s = ops.quantize_q8(w)
    assert q.dtype == jnp.int8 and s.shape == (1, 256)
    # symmetric per-channel: error <= half a quantization step per entry
    step = np.asarray(s)[0]
    err = np.abs(np.asarray(q, np.float32) * np.asarray(s) -
                 np.asarray(w))
    assert (err <= step[None, :] * 0.5 + 1e-7).all()


def test_kernel_matches_oracle_bf16_matched():
    """Interpret kernel vs the SAME-precision oracle (bf16 x, f32
    accumulate, post-scale): agreement to accumulation noise."""
    w, x = _wx(1, 4, 300, 500)               # ragged: padding paths
    q, s = ops.quantize_q8(w)
    got = ops.q8_matmul(x, q, s.reshape(-1),
                        backend="pallas_interpret")
    want = _dequant_matmul_xla(x.astype(jnp.bfloat16), q,
                               s.reshape(1, -1))
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_quantized_matmul_close_to_full_precision():
    """End-to-end quantization error at the op level stays small
    relative to the output scale (the serving-accuracy argument)."""
    w, x = _wx(2, 8, 512, 256)
    q, s = ops.quantize_q8(w)
    got = ops.q8_matmul(x, q, s.reshape(-1), backend="xla")
    want = x @ w
    denom = float(jnp.std(want))
    rel = float(jnp.max(jnp.abs(got - want))) / denom
    assert rel < 0.05, rel


def test_single_row_matvec():
    w, x = _wx(3, 1, 256, 128)               # the decode matvec shape
    q, s = ops.quantize_q8(w)
    got = ops.q8_matmul(x, q, s.reshape(-1),
                        backend="pallas_interpret")
    want = _dequant_matmul_xla(x.astype(jnp.bfloat16), q,
                               s.reshape(1, -1))
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_validation():
    w, x = _wx(4, 2, 64, 32)
    q, s = ops.quantize_q8(w)
    with pytest.raises(ValueError, match="int8"):
        ops.q8_matmul(x, w, s.reshape(-1))
    with pytest.raises(ValueError, match="contraction"):
        ops.q8_matmul(x[:, :32], q, s.reshape(-1))
    with pytest.raises(ValueError, match="channels"):
        ops.q8_matmul(x, q, s.reshape(-1)[:16])


def test_module_utest():
    from lua_mapreduce_tpu.ops import q8

    q8.utest()


class TestQuantizedLM:
    def _cfg(self):
        from lua_mapreduce_tpu.models import transformer as tfm

        return tfm.TransformerConfig(vocab=16, d_model=32, n_heads=2,
                                     n_layers=2, d_ff=64, max_seq=32)

    def test_quantize_lm_selects_projection_weights(self):
        from lua_mapreduce_tpu.models import transformer as tfm

        cfg = self._cfg()
        params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
        qp = tfm.quantize_lm(params)
        assert "L0_qkv_W::q8" in qp and "L0_qkv_W" not in qp
        assert qp["L0_qkv_W::q8"].dtype == jnp.int8
        assert "L0_ff1_W::q8" in qp and "L0_ff2_W::q8" in qp
        # embeddings / norms / biases untouched
        assert "tok_emb" in qp and "L0_ln1_g" in qp
        assert "L0_ff1_b" in qp
        # tied head: int8 COPY alongside the full-precision gather table
        assert qp["head::q8"].dtype == jnp.int8
        assert qp["head::q8"].shape == qp["tok_emb"].shape[::-1]

    def test_quantized_forward_logits_close(self):
        import numpy as np

        from lua_mapreduce_tpu.models import transformer as tfm

        cfg = self._cfg()
        params = tfm.init_transformer(jax.random.PRNGKey(1), cfg)
        qp = tfm.quantize_lm(params)
        toks = jnp.asarray(np.arange(16)[None, :] % 16, jnp.int32)
        full = tfm.transformer_apply(params, toks, cfg=cfg)
        quant = tfm.transformer_apply(qp, toks, cfg=cfg)
        rel = float(jnp.max(jnp.abs(full - quant))) / float(
            jnp.std(full))
        assert rel < 0.25, rel          # op-level 3-5% compounds per layer

    def test_quantize_lm_modern_recipe(self):
        """The llama-style config quantizes completely: SwiGLU's third
        FFN matrix (ff3) and GQA's narrower kv projection are dense 2-D
        projections too and must not slip through the name filter."""
        import numpy as np

        from lua_mapreduce_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig.llama_style(
            vocab=16, d_model=32, n_heads=4, n_kv_heads=2,
            n_layers=2, d_ff=64, max_seq=32)
        params = tfm.init_transformer(jax.random.PRNGKey(3), cfg)
        qp = tfm.quantize_lm(params)
        for name in ("L0_qkv_W", "L0_out_W", "L0_ff1_W", "L0_ff2_W",
                     "L0_ff3_W"):
            assert f"{name}::q8" in qp and name not in qp, name
            assert qp[f"{name}::q8"].dtype == jnp.int8
        # GQA: the quantized qkv projection keeps the narrow kv width
        assert (qp["L0_qkv_W::q8"].shape
                == params["L0_qkv_W"].shape)
        toks = jnp.asarray(np.arange(16)[None, :] % 16, jnp.int32)
        full = tfm.transformer_apply(params, toks, cfg=cfg)
        quant = tfm.transformer_apply(qp, toks, cfg=cfg)
        rel = float(jnp.max(jnp.abs(full - quant))) / float(
            jnp.std(full))
        assert rel < 0.25, rel
        # the KV-cached decode path serves the quantized modern dict
        out = tfm.greedy_decode(qp, toks[:, :8], 4, cfg=cfg)
        assert out.shape == (1, 12)      # prompt + 4 generated

    @pytest.mark.heavy
    def test_quantized_decode_matches_full_on_trained_model(self):
        """The serving claim end to end: train the stride task, then
        greedy-decode with full-precision AND int8-quantized weights —
        a trained model's logit margins dwarf quantization noise, so
        the TOKENS must match exactly (prefill path included)."""
        import numpy as np
        import optax

        from lua_mapreduce_tpu.models import transformer as tfm
        from lua_mapreduce_tpu.parallel.mesh import make_mesh

        cfg = tfm.TransformerConfig(vocab=16, d_model=32, n_heads=2,
                                    n_layers=2, d_ff=64, max_seq=32)
        mesh = make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                         axis_names=("dp", "sp"))
        params = tfm.init_transformer(jax.random.PRNGKey(2), cfg)
        opt = optax.adam(3e-3)
        step = tfm.make_train_step(cfg, mesh, opt, attn="ring")
        st = opt.init(params)
        rng = np.random.RandomState(0)
        for _ in range(80):
            start = rng.randint(0, 16, (8, 1))
            seq = (start + np.arange(17)) % 16
            toks = jnp.asarray(seq[:, :-1], jnp.int32)
            tgts = jnp.asarray(seq[:, 1:], jnp.int32)
            params, st, loss = step(params, st,
                                    *tfm.shard_batch(mesh, toks, tgts))
        jax.block_until_ready(params)
        assert float(loss) < 0.5, float(loss)

        prompt = jnp.asarray((np.arange(8) % 16)[None, :], jnp.int32)
        full = np.asarray(tfm.greedy_decode(params, prompt, 8, cfg=cfg))
        qp = tfm.quantize_lm(params)
        quant = np.asarray(tfm.greedy_decode(qp, prompt, 8, cfg=cfg))
        np.testing.assert_array_equal(full, quant)
        # prefill ingestion with quantized weights too
        quant_p = np.asarray(tfm.greedy_decode(qp, prompt, 8, cfg=cfg,
                                               use_prefill=True))
        np.testing.assert_array_equal(full, quant_p)
        # the FULL int8 serving config: int8 weights + int8 KV cache
        # (ops/decode.quantize_kv), scan and prefill ingestion — a
        # trained model's logit margins dwarf both noise sources
        both = np.asarray(tfm.greedy_decode(qp, prompt, 8, cfg=cfg,
                                            kv_q8=True))
        np.testing.assert_array_equal(full, both)
        both_p = np.asarray(tfm.greedy_decode(qp, prompt, 8, cfg=cfg,
                                              kv_q8=True,
                                              use_prefill=True))
        np.testing.assert_array_equal(full, both_p)
