"""Multi-host bootstrap helpers (parallel/multihost.py) on the virtual
8-device mesh: single-process degradation must be exact — same program
runs on one box and on a pod (the reference's any-box-joins-the-pool
property, SURVEY.md §2.6)."""

import numpy as np
import pytest

from lua_mapreduce_tpu.parallel import multihost


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert multihost.initialize_multihost() is False


def test_multihost_mesh_single_slice_shape():
    import jax

    mesh = multihost.make_multihost_mesh((4, 2), ("dp", "mp"))
    assert mesh.shape == {"dp": 4, "mp": 2}
    assert sorted(d.id for row in mesh.devices for d in row) == \
        sorted(d.id for d in jax.devices())


def test_multihost_mesh_rejects_wrong_device_count():
    with pytest.raises(ValueError, match="needs 16 devices"):
        multihost.make_multihost_mesh((8, 2), ("dp", "mp"))


def test_process_local_batch_single_process():
    # single process: every global batch is wholly local at offset 0
    # (the divisibility guard only bites with process_count > 1)
    per, off = multihost.process_local_batch(32)
    assert (per, off) == (32, 0)


def test_global_batch_array_roundtrip():
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = multihost.make_multihost_mesh((8,), ("dp",))
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    arr = multihost.global_batch_array(mesh, P("dp"), x)
    assert arr.shape == (8, 8)
    np.testing.assert_array_equal(np.asarray(arr), x)
    # really sharded over dp: each device holds one row
    assert len(arr.sharding.device_set) == 8

    # and it feeds a psum-style collective correctly
    @jax.jit
    def total(a):
        return a.sum()
    assert float(total(arr)) == float(x.sum())


def test_dp_training_step_over_multihost_mesh():
    """The DP trainer's mesh can come from the multihost builder — one
    step on the virtual mesh trains identically to make_mesh."""
    import jax
    import jax.numpy as jnp

    from lua_mapreduce_tpu.models.mlp import init_mlp, nll_loss
    from lua_mapreduce_tpu.train.harness import (DataParallelTrainer,
                                                 TrainConfig)

    mesh = multihost.make_multihost_mesh((8, 1), ("dp", "mp"))
    params = init_mlp(jax.random.PRNGKey(0), (16, 8, 4))
    tr = DataParallelTrainer(nll_loss, params, mesh,
                             TrainConfig(batch_size=16))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(16, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16))
    losses = np.asarray(tr.run_steps(x, y, 3))
    assert losses.shape[-1] == 3 or losses.size == 3
    assert np.all(np.isfinite(losses))
