"""Multi-host bootstrap helpers (parallel/multihost.py) on the virtual
8-device mesh: single-process degradation must be exact — same program
runs on one box and on a pod (the reference's any-box-joins-the-pool
property, SURVEY.md §2.6)."""

import numpy as np
import pytest

from lua_mapreduce_tpu.parallel import multihost


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert multihost.initialize_multihost() is False


def test_multihost_mesh_single_slice_shape():
    import jax

    mesh = multihost.make_multihost_mesh((4, 2), ("dp", "mp"))
    assert mesh.shape == {"dp": 4, "mp": 2}
    assert sorted(d.id for row in mesh.devices for d in row) == \
        sorted(d.id for d in jax.devices())


def test_multihost_mesh_rejects_wrong_device_count():
    with pytest.raises(ValueError, match="needs 16 devices"):
        multihost.make_multihost_mesh((8, 2), ("dp", "mp"))


def test_process_local_batch_single_process():
    # single process: every global batch is wholly local at offset 0
    # (the divisibility guard only bites with process_count > 1)
    per, off = multihost.process_local_batch(32)
    assert (per, off) == (32, 0)


def test_global_batch_array_roundtrip():
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = multihost.make_multihost_mesh((8,), ("dp",))
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    arr = multihost.global_batch_array(mesh, P("dp"), x)
    assert arr.shape == (8, 8)
    np.testing.assert_array_equal(np.asarray(arr), x)
    # really sharded over dp: each device holds one row
    assert len(arr.sharding.device_set) == 8

    # and it feeds a psum-style collective correctly
    @jax.jit
    def total(a):
        return a.sum()
    assert float(total(arr)) == float(x.sum())


_WORKER = r'''
import os, sys
from lua_mapreduce_tpu.utils.jax_compat import shard_map
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from lua_mapreduce_tpu.parallel import multihost
assert multihost.initialize_multihost(
    coordinator_address=f"localhost:{{port}}", num_processes=2,
    process_id=pid)
import numpy as np
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from lua_mapreduce_tpu.models.mlp import init_mlp, nll_loss

assert jax.process_count() == 2 and len(jax.devices()) == 4
mesh = multihost.make_multihost_mesh((4,), ("dp",))

params = jax.device_put(init_mlp(jax.random.PRNGKey(0), (8, 6, 3)),
                        NamedSharding(mesh, P()))
opt = optax.sgd(0.1)
opt_state = jax.device_put(opt.init(params), NamedSharding(mesh, P()))

# each process contributes ONLY its rows of the global batch — the
# gradient mean inside the jitted step crosses the process boundary
# (the DCN analog riding gloo on this one box)
per, off = multihost.process_local_batch(8)
rng = np.random.RandomState(7)
gx = rng.rand(8, 8).astype(np.float32)
gy = rng.randint(0, 3, 8)
x = multihost.global_batch_array(mesh, P("dp"), gx[off:off + per])
y = multihost.global_batch_array(mesh, P("dp"), gy[off:off + per])

@jax.jit
def step(params, opt_state, x, y):
    loss, grads = jax.value_and_grad(nll_loss)(params, x, y)
    updates, opt_state = opt.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss

# row-POSITION-sensitive probe: a mean-based loss alone cannot detect a
# wrong offset (any row permutation gives the same mean), so check the
# assembled global array really placed each process's rows at its offset
@jax.jit
def poswsum(a):
    return jnp.sum(a * jnp.arange(a.shape[0])[:, None])
want_pos = float(np.sum(gx * np.arange(8)[:, None]))
assert np.allclose(float(poswsum(x)), want_pos, rtol=1e-6)

# ring ppermute ACROSS the process boundary — the point-to-point
# collective ring attention rides; shard i's rows must land on shard
# i+1 (devices 1->2 and 3->0 cross processes here)
ring = jax.jit(shard_map(
    lambda a: jax.lax.ppermute(a, "dp", [(i, (i + 1) % 4)
                                         for i in range(4)]),
    mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))
rolled = ring(x)
want_roll = float(np.sum(np.roll(gx, 2, axis=0) *
                         np.arange(8)[:, None]))
assert np.allclose(float(poswsum(rolled)), want_roll, rtol=1e-6)

params, opt_state, loss = step(params, opt_state, x, y)
# single-process oracle on the full batch must match exactly
op = init_mlp(jax.random.PRNGKey(0), (8, 6, 3))
ol, og = jax.value_and_grad(nll_loss)(op, jnp.asarray(gx), jnp.asarray(gy))
ou, _ = opt.update(og, opt.init(op), op)
op = optax.apply_updates(op, ou)
assert np.allclose(float(loss), float(ol), rtol=1e-6), (loss, ol)
for k in op:
    np.testing.assert_allclose(
        np.asarray(jax.device_get(params[k])), np.asarray(op[k]),
        rtol=1e-5, atol=1e-6, err_msg=k)
print(f"P{{pid}}-OK loss={{float(loss):.6f}}", flush=True)
'''


@pytest.mark.heavy
def test_two_process_distributed_training_step(tmp_path):
    """REAL multi-controller e2e on one box: two OS processes join via
    jax.distributed (gloo CPU collectives — the DCN stand-in), each
    feeds only its local batch rows, and one jitted DP train step's
    cross-process gradient mean matches the single-process oracle
    exactly. The strongest multi-host proof available without pod
    hardware (the reference's one-box multi-node rig, SURVEY.md §4)."""
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = str(tmp_path / "mh_worker.py")
    with open(script, "w") as f:
        f.write(_WORKER.format(repo=repo))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_NUM_CPU_DEVICES")}

    # bind/close free-port discovery is a TOCTOU race under parallel CI;
    # retry the whole rendezvous on a fresh port if a worker fails fast
    for attempt in range(3):
        s = socket.socket()
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
        s.close()
        procs = [subprocess.Popen(
            [sys.executable, script, str(i), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out.decode())
        except subprocess.TimeoutExpired:
            # one worker died → its peer blocks in a collective. Kill,
            # REAP, and surface what the workers printed (the reason)
            for p in procs:
                p.kill()
            for p in procs:
                out, _ = p.communicate()
                outs.append(out.decode())
            raise AssertionError(
                "multihost worker timeout; outputs:\n"
                + "\n---\n".join(outs))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait()
        if (any(p.returncode != 0 for p in procs)
                and any("bind" in o.lower() or "address" in o.lower()
                        for o in outs) and attempt < 2):
            continue                     # port stolen: fresh rendezvous
        break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"P{i}-OK" in out, out
    # both controllers computed the SAME loss (replicated state in sync)
    l0 = outs[0].split("loss=")[1].split()[0]
    l1 = outs[1].split("loss=")[1].split()[0]
    assert l0 == l1


def test_dp_training_step_over_multihost_mesh():
    """The DP trainer's mesh can come from the multihost builder — one
    step on the virtual mesh trains identically to make_mesh."""
    import jax
    import jax.numpy as jnp

    from lua_mapreduce_tpu.models.mlp import init_mlp, nll_loss
    from lua_mapreduce_tpu.train.harness import (DataParallelTrainer,
                                                 TrainConfig)

    mesh = multihost.make_multihost_mesh((8, 1), ("dp", "mp"))
    params = init_mlp(jax.random.PRNGKey(0), (16, 8, 4))
    tr = DataParallelTrainer(nll_loss, params, mesh,
                             TrainConfig(batch_size=16))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(16, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16))
    losses = np.asarray(tr.run_steps(x, y, 3))
    assert losses.shape[-1] == 3 or losses.size == 3
    assert np.all(np.isfinite(losses))


_WORKER4 = r'''
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from lua_mapreduce_tpu.parallel import multihost
assert multihost.initialize_multihost(
    coordinator_address=f"localhost:{{port}}", num_processes=4,
    process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

assert jax.process_count() == 4 and len(jax.devices()) == 8

# hybrid mesh: dp factored over the 4 process granules (the DCN axis),
# mp inside each process (the ICI stand-in)
mesh = multihost.make_multihost_mesh((4, 2), ("dp", "mp"))
assert mesh.shape == {{"dp": 4, "mp": 2}}
dev = mesh.devices
for i in range(4):
    owners = {{d.process_index for d in dev[i]}}
    assert len(owners) == 1, f"dp row {{i}} spans processes {{owners}}"
row_owner = [dev[i][0].process_index for i in range(4)]
assert sorted(row_owner) == [0, 1, 2, 3], row_owner
assert row_owner != [0, 0, 1, 1], "mp must stay inside a process"

# global batch: each process feeds only its rows
per, off = multihost.process_local_batch(8)
assert per == 2 and off == 2 * jax.process_index()
rng = np.random.RandomState(3)
gx = rng.rand(8, 16).astype(np.float32)
x = multihost.global_batch_array(mesh, P("dp", "mp"), gx[off:off + per])

@jax.jit
def poswsum(a):
    return jnp.sum(a * jnp.arange(a.shape[0])[:, None])
want = float(np.sum(gx * np.arange(8)[:, None]))
assert np.allclose(float(poswsum(x)), want, rtol=1e-6), "row placement"

# dp ppermute ring: every hop crosses a process boundary (pure DCN)
ring = jax.jit(shard_map(
    lambda a: jax.lax.ppermute(a, "dp", [(i, (i + 1) % 4)
                                         for i in range(4)]),
    mesh=mesh, in_specs=P("dp", "mp"), out_specs=P("dp", "mp")))
rolled = ring(x)
want_roll = float(np.sum(np.roll(gx, 2, axis=0) *
                         np.arange(8)[:, None]))
assert np.allclose(float(poswsum(rolled)), want_roll, rtol=1e-6)

# cross-process gradient mean over dp + intra-process psum over mp:
# the hybrid collective pattern a real pod training step uses
w = np.linspace(-1, 1, 16).astype(np.float32)
wg = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P("mp")))

def loss_local(xs, ws):
    y = xs @ ws                        # (rows,) partial over mp cols
    y = jax.lax.psum(y, "mp")          # ICI-analog reduce
    l = jnp.sum(y * y) / 8.0
    return jax.lax.psum(l, "dp")       # DCN-analog reduce

lval = jax.jit(shard_map(
    lambda xs, ws: loss_local(xs, ws),
    mesh=mesh, in_specs=(P("dp", "mp"), P("mp")),
    out_specs=P()))(x, wg)
want_l = float(np.sum((gx @ w) ** 2) / 8.0)
assert np.allclose(float(lval), want_l, rtol=1e-5), (float(lval), want_l)
print(f"P{{pid}}-OK loss={{float(lval):.6f}}", flush=True)
'''


@pytest.mark.heavy
def test_four_process_hybrid_mesh_dcn_axis(tmp_path):
    """4-controller e2e (VERDICT r3 item 3b): four OS processes of two
    devices each form a (dp=4, mp=2) HYBRID mesh whose dp axis is
    factored over process granules (parallel/multihost.py's DCN policy).
    Verifies granule integrity (mp never crosses a process), row
    placement of process-local batches, a dp ppermute ring where every
    hop crosses a process boundary, and a two-level psum (mp inside the
    process, dp across) matching the numpy oracle."""
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = str(tmp_path / "mh4_worker.py")
    with open(script, "w") as f:
        f.write(_WORKER4.format(repo=repo))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_NUM_CPU_DEVICES")}

    for attempt in range(3):
        s = socket.socket()
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
        s.close()
        procs = [subprocess.Popen(
            [sys.executable, script, str(i), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for i in range(4)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out.decode())
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            for p in procs:
                out, _ = p.communicate()
                outs.append(out.decode())
            raise AssertionError("4-process hybrid-mesh timeout:\n"
                                 + "\n---\n".join(outs))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait()
        if (any(p.returncode != 0 for p in procs)
                and any("bind" in o.lower() or "address" in o.lower()
                        for o in outs) and attempt < 2):
            continue
        break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"P{i}-OK" in out, out
    losses = {o.split("loss=")[1].split()[0] for o in outs}
    assert len(losses) == 1, losses
