"""lmr-trace suite (DESIGN §22): span chains, histograms, export, and
the tracing-off/on invariants.

The acceptance legs:

1. **byte-identity** — tracing-on runs produce byte-identical result
   files to tracing-off twins (spans live under the ``_trace.`` prefix,
   outside every engine namespace);
2. **trace completeness under chaos** — with a seeded FaultPlan active,
   every committed job still shows an unbroken claim → body → commit
   span chain, retry attempts appear as error-tagged child spans, and
   the Chrome trace-event export of the chaos run validates against the
   schema oracle;
3. **speculation chains** — a slow-plan straggler run shows exactly one
   commit span per job (first-commit-wins), the clone's speculative
   claim, and the loser's chain;
4. **errors-stream linkage** — a chaos-injected fault's error entry
   carries the span id of the job body that was live when it fired, and
   that id resolves in the collected trace;
5. **fold drift** — Server and LocalExecutor surface the identical
   IterationStats counter key set through the one shared fold helper.

The ``smoke`` legs are the test.sh trace gate.
"""

import json
import os
import subprocess
import sys
import threading
from typing import Dict

import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.core.constants import Status
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor, iter_results
from lua_mapreduce_tpu.engine.server import Server
from lua_mapreduce_tpu.engine.worker import MAP_NS, PRE_NS, RED_NS, Worker
from lua_mapreduce_tpu.faults import FaultPlan, install_fault_plan
from lua_mapreduce_tpu.store.router import get_storage_from
from lua_mapreduce_tpu.trace import (TraceCollection, Tracer, install_tracer,
                                     validate_chrome)
from lua_mapreduce_tpu.utils.stats import COUNTER_FOLD, IterationStats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CORPUS = {
    f"doc{i}": " ".join(f"w{(i * 5 + j) % 17}" for j in range(30))
    for i in range(6)
}
GOLDEN: Dict[str, int] = {}
for _text in CORPUS.values():
    for _w in _text.split():
        GOLDEN[_w] = GOLDEN.get(_w, 0) + 1

_MOD = "tests._trace_wc"


def _install_module():
    import types

    mod = sys.modules.get(_MOD)
    if mod is None:
        mod = types.ModuleType(_MOD)

        def taskfn(emit):
            for k, v in sorted(CORPUS.items()):
                emit(k, v)

        def mapfn(key, value, emit):
            for w in value.split():
                emit(w, 1)

        mod.taskfn = taskfn
        mod.mapfn = mapfn
        mod.partitionfn = lambda key: sum(key.encode()) % 3
        mod.reducefn = lambda key, values: sum(values)
        sys.modules[_MOD] = mod
    return mod


def _storage(tmp_path, backend, tag):
    return {"mem": f"mem:{tag}",
            "shared": f"shared:{tmp_path}/shared-{tag}"}[backend]


def _result_bytes(storage_spec, ns="result"):
    """Final result files only — the byte-compare oracle (span files
    live under _trace. and must never leak into the result namespace)."""
    import re
    store = get_storage_from(storage_spec)
    keep = re.compile(rf"^{re.escape(ns)}\.P\d+$")
    return {name: "".join(store.lines(name))
            for name in store.list(f"{ns}.P*") if keep.match(name)}


def _run_local(tmp_path, backend, tag, traced, pipeline=True, plan=None):
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD, storage=_storage(tmp_path, backend, tag))
    install_fault_plan(plan)
    install_tracer(Tracer() if traced else None)
    try:
        ex = LocalExecutor(spec, map_parallelism=3, pipeline=pipeline,
                           premerge_min_runs=2)
        stats = ex.run()
    finally:
        install_tracer(None)
        install_fault_plan(None)
    assert {k: v[0] for k, v in ex.results()} == GOLDEN
    return spec, stats


def _run_distributed(tmp_path, backend, tag, traced, plan=None,
                     n_workers=2, speculation=0.0, straggler=False,
                     batch_k=2, pipeline=False):
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD, storage=_storage(tmp_path, backend, tag))
    store = MemJobStore()
    install_fault_plan(plan)
    install_tracer(Tracer() if traced else None)
    try:
        server = Server(store, poll_interval=0.01, pipeline=pipeline,
                        premerge_min_runs=2, batch_k=batch_k,
                        speculation=speculation).configure(spec)
        names = ([f"healthy-{i}" for i in range(n_workers - 1)]
                 + ["straggler-0"] if straggler else [None] * n_workers)
        workers = [Worker(store, name=names[i]).configure(max_iter=800,
                                                          max_sleep=0.02)
                   for i in range(n_workers)]
        threads = [threading.Thread(target=w.execute, daemon=True)
                   for w in workers]
        if straggler:
            final = {}
            st = threading.Thread(
                target=lambda: final.setdefault("stats", server.loop()),
                daemon=True)
            st.start()
            threads[-1].start()
            _wait_for_claim(store)
            for t in threads[:-1]:
                t.start()
            st.join(timeout=120)
            assert not st.is_alive(), "server wedged under the straggler"
            stats = final["stats"]
        else:
            for t in threads:
                t.start()
            stats = server.loop()
        for t in threads:
            t.join(timeout=30)
    finally:
        install_tracer(None)
        install_fault_plan(None)
    got = {k: v[0]
           for k, v in iter_results(get_storage_from(spec.storage),
                                    "result")}
    assert got == GOLDEN
    return spec, store, stats, server


def _wait_for_claim(store, timeout=30.0):
    import time as _t
    deadline = _t.time() + timeout
    while _t.time() < deadline:
        try:
            if store.counts(MAP_NS)[Status.RUNNING] > 0:
                return
        except Exception:
            pass
        _t.sleep(0.005)
    raise AssertionError("straggler never claimed a lease")


def _committed(store):
    return [(ns, d["_id"]) for ns in (MAP_NS, PRE_NS, RED_NS)
            for d in store.jobs(ns) if d["status"] == Status.WRITTEN]


# --- smoke legs: the test.sh trace gate --------------------------------------

def test_trace_smoke_local_artifacts(tmp_path):
    """One traced local pipelined run: body spans for every phase,
    per-op histograms, a waterfall, and a schema-valid Chrome export."""
    spec, _ = _run_local(tmp_path, "mem", "tr-smoke", traced=True)
    col = TraceCollection.from_store(get_storage_from(spec.storage))
    assert col.spans, "traced run flushed no spans"
    phases = {r["phase"] for r in col.phase_waterfall()}
    assert {"map", "reduce"} <= phases
    ops = col.op_stats()
    assert ops, "no op spans recorded"
    for name, st in ops.items():
        assert st["count"] > 0 and st["p50_ms"] <= st["p99_ms"] \
            <= st["max_ms"] + 1e-9, (name, st)
    assert any(n.startswith("store.") for n in ops)
    doc = col.to_chrome()
    assert validate_chrome(doc) == []
    assert any(e["ph"] == "X" and e["name"] == "map.body"
               for e in doc["traceEvents"])
    assert col.slowest_jobs(3)


def test_trace_smoke_off_on_byte_identical(tmp_path):
    """The golden invariant: tracing changes observability, never
    bytes. Off and on twins of the same task produce identical result
    files; the traced store additionally holds _trace.* files and the
    untraced one holds none."""
    for backend in ("mem", "shared"):
        _run_local(tmp_path, backend, f"tr-off-{backend}", traced=False)
        _run_local(tmp_path, backend, f"tr-on-{backend}", traced=True)
        off = _result_bytes(_storage(tmp_path, backend,
                                     f"tr-off-{backend}"))
        on = _result_bytes(_storage(tmp_path, backend,
                                    f"tr-on-{backend}"))
        assert off == on, f"{backend}: tracing changed result bytes"
        off_store = get_storage_from(_storage(tmp_path, backend,
                                              f"tr-off-{backend}"))
        on_store = get_storage_from(_storage(tmp_path, backend,
                                             f"tr-on-{backend}"))
        assert off_store.list("_trace.*") == []
        assert on_store.list("_trace.*") != []


def test_trace_off_wiring_is_absent():
    """With no tracer active the wrapper layer simply does not exist —
    the overhead story is structural, not measured."""
    from lua_mapreduce_tpu.faults.wrappers import (unwrap, wrap_jobstore,
                                                   wrap_store)
    from lua_mapreduce_tpu.store.memfs import MemStore
    from lua_mapreduce_tpu.trace.wrappers import (TracingJobStore,
                                                  TracingStore)
    raw = MemStore()
    layers = []
    obj = wrap_store(raw)
    while hasattr(obj, "_inner"):
        layers.append(type(obj).__name__)
        obj = obj._inner
    assert "TracingStore" not in layers
    js = MemJobStore()
    wrapped = wrap_jobstore(js)
    assert unwrap(wrapped) is js
    layers = []
    obj = wrapped
    while hasattr(obj, "_inner"):
        layers.append(type(obj).__name__)
        obj = obj._inner
    assert "TracingJobStore" not in layers
    # and with a tracer installed, both layers appear
    install_tracer(Tracer())
    try:
        obj = wrap_store(MemStore())
        names = []
        while hasattr(obj, "_inner"):
            names.append(type(obj).__name__)
            obj = obj._inner
        assert "TracingStore" in names
        obj = wrap_jobstore(MemJobStore())
        names = []
        while hasattr(obj, "_inner"):
            names.append(type(obj).__name__)
            obj = obj._inner
        assert "TracingJobStore" in names
        assert isinstance(wrap_jobstore(wrapped), type(wrapped))
    finally:
        install_tracer(None)


# --- chaos-matrix legs -------------------------------------------------------

def _chaos_plan(seed):
    """The chaos-suite mix (test_chaos._plan's shape): transient +
    error-after-write bursts, absorbable within the default retry
    budget, so completeness is asserted under real retries."""
    return FaultPlan(seed, transient=0.08, latency=0.05,
                     error_after_write=0.3, latency_ms=1.0, max_per_key=2)


@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
def test_trace_completeness_under_chaos(tmp_path, pipeline):
    """The acceptance gate: a traced chaos run keeps an unbroken
    claim → body → commit chain for EVERY committed job, injected-fault
    retry attempts appear as error-tagged child spans, and the Chrome
    export of the whole chaos run validates."""
    plan = _chaos_plan(29 + int(pipeline))
    spec, store, stats, _ = _run_distributed(
        tmp_path, "mem", f"tr-chaos-{int(pipeline)}", traced=True,
        plan=plan, pipeline=pipeline)
    assert plan.total_fired() > 0, "plan injected nothing"
    committed = _committed(store)
    assert committed
    col = TraceCollection.from_store(get_storage_from(spec.storage))
    problems = col.check_complete(committed)
    assert problems == [], f"broken chains: {problems}"
    # the injected faults are visible as error-tagged attempt spans,
    # and at least one hangs under a job body (the causal link — the
    # server's own housekeeping faults legitimately have no body parent)
    errored = [s for s in col.spans
               if s.get("attrs", {}).get("error", "").startswith("Injected")]
    assert errored, "no injected-fault attempt spans recorded"
    under_body = [s for s in errored
                  if col.by_sid.get(s.get("parent"), {}).get(
                      "name", "").endswith(".body")]
    assert under_body, "no attempt span parented to a job body"
    doc = col.to_chrome()
    assert validate_chrome(doc) == []
    # tracing-on chaos twin keeps golden bytes (checked in the runner)
    # and zero repetition charges — tracing must not perturb recovery
    for ns in (MAP_NS, PRE_NS, RED_NS):
        for d in store.jobs(ns):
            assert d["repetitions"] == 0


def test_trace_speculation_winner_and_loser_chains(tmp_path):
    """A slow-plan straggler with speculation on: the speculated job
    shows exactly one commit span (first-commit-wins), the clone's
    speculative claim span, and a loser chain — a second worker's body
    with no commit, or a cancelled clone."""
    plan = FaultPlan(91, slow_worker="straggler-*", slow_ms=120.0,
                     slow_s=3600.0)
    spec, store, stats, _ = _run_distributed(
        tmp_path, "mem", "tr-spec", traced=True, plan=plan, n_workers=3,
        speculation=3.0, straggler=True, batch_k=1)
    it = stats.iterations[-1]
    assert it.spec_launched >= 1 and it.spec_wins >= 1
    col = TraceCollection.from_store(get_storage_from(spec.storage))
    committed = _committed(store)
    assert col.check_complete(committed) == []
    outcomes = col.speculation_outcomes()
    assert outcomes, "no speculative claim spans recorded"
    assert all(o["commit_count"] == 1 for o in outcomes), \
        "a commit race produced more than one commit span"
    # at least one speculated job resolved with a visible loser:
    # a second executor's body span, or a cancelled shadow lease
    assert any(o["losers"] or o["cancelled"] for o in outcomes), outcomes
    assert validate_chrome(col.to_chrome()) == []


def test_error_entry_links_to_live_span(tmp_path):
    """Satellite: a chaos-injected fault that releases a job writes an
    errors-stream entry carrying the span id of the job body that was
    live when it fired — and the id resolves to a real span (name and
    job context match) in the collected trace."""
    # transient faults pinned to ONE partition-0 run file, outlasting
    # the retry budget (3): the reduce body exhausts, releases (zero
    # reps), and the re-execution's occurrence indices advance past the
    # faults. One file only — a per-file budget across the whole fan-in
    # would burn the per-worker release budget and march P0 to FAILED
    plan = FaultPlan(37, transient=1.0, pattern="result.P0.M00000000",
                     max_per_key=4)
    spec, store, stats, server = _run_distributed(
        tmp_path, "mem", "tr-errlink", traced=True, plan=plan)
    assert stats.iterations[-1].infra_releases >= 1
    linked = [e for e in server.errors if e.get("span_id")]
    assert linked, f"no error entry carries a span id: {server.errors}"
    col = TraceCollection.from_store(get_storage_from(spec.storage))
    for e in linked:
        sp = col.by_sid.get(e["span_id"])
        assert sp is not None, f"span {e['span_id']} not in the trace"
        assert sp["name"].endswith(".body")
        assert sp["ns"] == e["ns"] and sp["job"] == e["job_id"]
        assert sp["worker"] == e["span_worker"] == e["worker"]
        assert sp.get("attrs", {}).get("error")  # the failing body


# --- counter-fold drift (satellite) ------------------------------------------

def test_counter_fold_shared_and_key_sets_identical(tmp_path, monkeypatch):
    """Both executors must route their per-iteration counter folding
    through stats.IterationStats.fold_fault_counters and surface the
    identical counter key set — the drift that motivated the helper
    (LocalExecutor silently never folded infra_releases)."""
    calls = []
    orig = IterationStats.fold_fault_counters

    def spy(self, delta):
        calls.append(sorted(delta))
        return orig(self, delta)

    monkeypatch.setattr(IterationStats, "fold_fault_counters", spy)
    _, local_stats = _run_local(tmp_path, "mem", "fold-local",
                                traced=False, pipeline=False)
    assert calls, "LocalExecutor bypassed the shared fold helper"
    n_local = len(calls)
    _, _, dist_stats, _ = _run_distributed(tmp_path, "mem", "fold-dist",
                                           traced=False)
    assert len(calls) > n_local, "Server bypassed the shared fold helper"

    local_keys = set(local_stats.iterations[-1].as_dict())
    dist_keys = set(dist_stats.iterations[-1].as_dict())
    assert local_keys == dist_keys
    # every fold-managed field is a real dataclass field AND surfaced
    import dataclasses
    fields = {f.name for f in dataclasses.fields(IterationStats)}
    assert set(COUNTER_FOLD) <= fields
    assert set(COUNTER_FOLD) <= local_keys
    # the lmr-autotune counters ride the same fold (DESIGN §29): drift
    # between COUNTER_FOLD, the dataclass, and as_dict would silently
    # drop the controller's restraint/action evidence from the stats
    for key in ("autotune_decisions", "autotune_vetoes",
                "autotune_scale_events"):
        assert key in COUNTER_FOLD
        assert key in local_keys and key in dist_keys
    # the lmr-ha leader trio rides the same fold (DESIGN §31): a
    # LocalExecutor run has no coordinator plane, so the keys must
    # still surface — as zeros — or takeover evidence would vanish
    # from any stats consumer that intersects the two schemas
    for key in ("leader_takeovers", "fenced_writes", "standby_wakeups"):
        assert key in COUNTER_FOLD
        assert key in local_keys and key in dist_keys
        assert local_stats.iterations[-1].as_dict()[key] == 0


# --- CLI ---------------------------------------------------------------------

def test_trace_cli_report_and_export(tmp_path):
    """``python -m lua_mapreduce_tpu.trace`` over a traced shared-store
    run: the JSON report carries phases/ops, and --export writes
    schema-valid Chrome trace-event JSON."""
    _run_local(tmp_path, "shared", "tr-cli", traced=True)
    storage = _storage(tmp_path, "shared", "tr-cli")
    out = tmp_path / "chrome.json"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.trace", storage,
         "--export", str(out), "--format", "json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert rep["spans"] > 0 and rep["ops"]
    assert {row["phase"] for row in rep["phases"]} >= {"map", "reduce"}
    with open(out) as f:
        doc = json.load(f)
    assert validate_chrome(doc) == []
    # an untraced store reports cleanly (exit 1, no crash)
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.trace",
         f"shared:{tmp_path}/empty-ns"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert r.returncode == 1 and "no _trace" in r.stderr


def test_cli_parsers_accept_trace_and_profile():
    """Satellite: --trace / --profile exist on BOTH distributed CLIs
    (until now only train_lm had --profile)."""
    from lua_mapreduce_tpu.cli.execute_server import \
        build_parser as server_parser
    from lua_mapreduce_tpu.cli.execute_worker import \
        build_parser as worker_parser
    a = server_parser().parse_args(
        ["coord", "t", "m", "p", "r", "--trace", "--profile", "/tmp/prof"])
    assert a.trace and a.profile == "/tmp/prof"
    a = worker_parser().parse_args(["coord", "--trace", "--profile",
                                    "/tmp/prof"])
    assert a.trace and a.profile == "/tmp/prof"
