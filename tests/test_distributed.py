"""Distributed engine tests: server + elastic workers.

Analog of the reference's e2e harness (test.sh + .travis.yml simulated
multi-node, SURVEY.md §4): in-process thread pools over MemJobStore, true
multi-process pools over FileJobStore (the screen-d-m analog), injected
worker failures, and the server resume matrix.
"""

import glob
import os
import subprocess
import sys
import threading
import time

import pytest

from examples.wordcount.instrumented import read_count
from examples.wordcount.naive import naive_wordcount
from lua_mapreduce_tpu import (FileJobStore, MemJobStore, Server, TaskSpec,
                               Worker)
from lua_mapreduce_tpu.core.constants import Status, TaskStatus
from lua_mapreduce_tpu.engine.worker import MAP_NS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = sorted(glob.glob(os.path.join(REPO, "examples", "wordcount", "*.py")))


def _subprocess_env():
    """Env for worker subprocesses: REPO importable, ambient PYTHONPATH
    preserved (it registers the axon TPU plugin), and no trailing empty
    entry (an empty PYTHONPATH element means cwd and can shadow packages)."""
    ambient = os.environ.get("PYTHONPATH", "")
    path = REPO + os.pathsep + ambient if ambient else REPO
    return dict(os.environ, PYTHONPATH=path)


def _spec(storage, init_args=None):
    return TaskSpec(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.mapfn",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        combinerfn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
        init_args={"files": CORPUS, **(init_args or {})},
        storage=storage,
    )


def _run_pool(store, spec, n_workers=3, worker_kw=None):
    server = Server(store, poll_interval=0.02).configure(spec)
    workers = [Worker(store).configure(max_iter=400, max_sleep=0.05,
                                       **(worker_kw or {}))
               for _ in range(n_workers)]
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    stats = server.loop()
    for t in threads:
        t.join(timeout=30)
    return server, workers, stats


def test_inprocess_pool_matches_naive():
    import examples.wordcount.finalfn as finalfn
    golden = naive_wordcount(CORPUS)
    store = MemJobStore()
    server, workers, stats = _run_pool(store, _spec("mem:dist-basic"))
    assert dict(finalfn.counts) == golden
    it = stats.iterations[-1]
    assert it.map.count == len(CORPUS)
    assert it.map.failed == 0 and it.reduce.failed == 0
    # work was actually spread across the elastic pool
    assert sum(w.jobs_executed for w in workers) == it.map.count + it.reduce.count


def test_worker_failures_are_retried(tmp_path):
    """Injected mapfn failures mark jobs BROKEN; other (or the same) workers
    re-claim and finish; the run still produces the golden result."""
    import examples.wordcount.finalfn as finalfn
    golden = naive_wordcount(CORPUS)
    count_file = str(tmp_path / "mapcalls")
    spec = TaskSpec(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.instrumented",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
        init_args={"files": CORPUS, "count_file": count_file, "fail_times": 2},
        storage="mem:dist-flaky",
    )
    store = MemJobStore()
    server, workers, stats = _run_pool(store, spec)
    assert dict(finalfn.counts) == golden
    it = stats.iterations[-1]
    assert it.map.failed == 0
    # every map ran once, plus one retry per injected failure
    assert read_count(count_file) == len(CORPUS) + 2


def test_failed_jobs_surface_in_stats(tmp_path):
    """A job that fails MAX_JOB_RETRIES times goes FAILED and the phase
    completes anyway (server.lua:192-205 scavenger semantics)."""
    count_file = str(tmp_path / "mapcalls")
    spec = TaskSpec(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.instrumented",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        init_args={"files": CORPUS[:1], "count_file": count_file,
                   "fail_times": 10_000},
        storage="mem:dist-allfail",
    )
    store = MemJobStore()
    # workers die after MAX_WORKER_RETRIES consecutive errors — keep
    # replacing them, elastically, until the server finishes
    server = Server(store, poll_interval=0.02).configure(spec)
    stop = threading.Event()

    def pool():
        while not stop.is_set():
            w = Worker(store).configure(max_iter=50, max_sleep=0.05)
            try:
                w.execute()
            except RuntimeError:
                continue

    t = threading.Thread(target=pool, daemon=True)
    t.start()
    stats = server.loop()
    stop.set()
    it = stats.iterations[-1]
    assert it.map.failed == 1
    assert store.counts(MAP_NS)[Status.FAILED] == 1


def test_strict_mode_raises_instead_of_partial_final(tmp_path):
    """strict=True: an iterative (training-style) task whose map shard
    keeps failing must abort with PhaseFailed BEFORE finalfn consumes the
    partial result — a silent partial gradient sum is the hazard
    (VERDICT r1 item 8). Default mode (tested above) stays
    reference-compatible: warn and proceed."""
    from lua_mapreduce_tpu import PhaseFailed

    count_file = str(tmp_path / "mapcalls")
    import examples.wordcount.finalfn as finalfn
    spec = TaskSpec(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.instrumented",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
        init_args={"files": CORPUS, "count_file": count_file,
                   "fail_times": 10_000},
        storage="mem:dist-strict",
    )
    store = MemJobStore()
    server = Server(store, poll_interval=0.02, strict=True).configure(spec)
    finalfn.counts.clear()
    stop = threading.Event()

    def pool():
        while not stop.is_set():
            w = Worker(store).configure(max_iter=50, max_sleep=0.05)
            try:
                w.execute()
            except RuntimeError:
                continue

    t = threading.Thread(target=pool, daemon=True)
    t.start()
    with pytest.raises(PhaseFailed) as exc:
        server.loop()
    stop.set()
    assert exc.value.phase == "map"
    assert exc.value.failed >= 1
    assert exc.value.errors, "retained worker errors must ride the exception"
    # finalfn never stepped on the partial result
    assert dict(finalfn.counts) == {}


def test_batched_pool_amortizes_control_rounds():
    """An in-process pool sharing one MemJobStore with a server-deployed
    batch_k: the result matches the naive oracle, and the iteration's
    claim round-trip counter (the whole pool's — the store instance is
    shared) comes out well under one claim per job."""
    import examples.wordcount.finalfn as finalfn
    spec = _spec("mem:dist-batched")
    store = MemJobStore()
    server = Server(store, poll_interval=0.02, batch_k=8).configure(spec)
    finalfn.counts.clear()
    threads = [threading.Thread(
        target=Worker(store).configure(max_iter=400, max_sleep=0.05,
                                       batch_lease_s=60.0).execute,
        daemon=True) for _ in range(2)]
    for t in threads:
        t.start()
    stats = server.loop()
    assert dict(finalfn.counts) == naive_wordcount(CORPUS)
    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
    n_jobs = it.map.count + it.reduce.count
    assert it.claim_rounds > 0
    # workers follow the task doc's batch_k=8; after each worker's one
    # probe claim, leases amortize — strictly fewer claim rounds than
    # jobs proves batching engaged through the whole deployment path
    assert it.claim_rounds < n_jobs, (it.claim_rounds, n_jobs)
    assert it.commit_rounds < 2 * n_jobs


def test_loop_strict_kwarg_overrides_constructor():
    """loop(strict=True) is the per-run override form (VERDICT r1)."""
    spec = _spec("mem:dist-strict-kwarg")
    store = MemJobStore()
    server = Server(store, poll_interval=0.02).configure(spec)
    assert server.strict is False
    threads = [threading.Thread(
        target=Worker(store).configure(max_iter=400, max_sleep=0.05).execute,
        daemon=True) for _ in range(2)]
    for t in threads:
        t.start()
    server.loop(strict=True)     # healthy run: strict changes nothing
    assert server.strict is True


@pytest.mark.parametrize("engine", [
    pytest.param("python", marks=pytest.mark.heavy), "auto"])
def test_multiprocess_pool(tmp_path, engine):
    """True multi-process elastic pool over a FileJobStore + shared-dir
    storage — the .travis.yml single-box multi-node analog."""
    import examples.wordcount.finalfn as finalfn
    golden = naive_wordcount(CORPUS)
    root = str(tmp_path / "coord")
    spill = str(tmp_path / "spill")
    store = FileJobStore(root, engine=engine)

    worker_code = (
        "import sys\n"
        "from lua_mapreduce_tpu import FileJobStore, Worker\n"
        f"store = FileJobStore({root!r}, engine={engine!r})\n"
        "w = Worker(store).configure(max_iter=300, max_sleep=0.05)\n"
        "w.execute()\n"
    )
    env = _subprocess_env()
    procs = [subprocess.Popen([sys.executable, "-c", worker_code], env=env)
             for _ in range(2)]
    try:
        server = Server(store, poll_interval=0.05).configure(
            _spec(f"shared:{spill}"))
        stats = server.loop()
    finally:
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    assert dict(finalfn.counts) == golden
    it = stats.iterations[-1]
    assert it.map.count == len(CORPUS)
    assert it.map.failed == 0 and it.reduce.failed == 0
    # both subprocess workers really participated
    workers_seen = set()
    for doc in store.jobs(MAP_NS):
        workers_seen.add(doc["worker"])
    assert len(workers_seen) >= 1


@pytest.mark.heavy
def test_cross_host_pools_exchange_only_via_object_store(tmp_path):
    """Two disjoint worker pools — mappers and reducers with separate
    scratch dirs, phase-restricted so no process ever runs both sides —
    exchange intermediate data ONLY through the object store (the sshfs
    pull-across-hosts analog, fs.lua:143-160). Proves the spill really
    crosses a 'host' boundary: reduce workers never share a local dir
    with the map workers that produced their inputs (VERDICT r1 item 6).
    Also checks producer identities recorded in the reduce job docs
    (server.lua:286-289 analog)."""
    import examples.wordcount.finalfn as finalfn
    golden = naive_wordcount(CORPUS)
    root = str(tmp_path / "coord")
    bucket = str(tmp_path / "bucket")
    store = FileJobStore(root)
    finalfn.counts.clear()

    def pool_code(host: str, phases: str, scratch: str) -> str:
        return (
            "import os, sys, tempfile\n"
            f"os.makedirs({scratch!r}, exist_ok=True)\n"
            f"tempfile.tempdir = {scratch!r}\n"   # host-local scratch
            "from lua_mapreduce_tpu import FileJobStore, Worker\n"
            f"store = FileJobStore({root!r})\n"
            f"w = Worker(store, name={host!r}).configure(\n"
            f"    max_iter=300, max_sleep=0.05, phases=({phases!r},))\n"
            "w.execute()\n"
        )
    env = _subprocess_env()
    procs = [
        subprocess.Popen([sys.executable, "-c",
                          pool_code("mapper-a", "map",
                                    str(tmp_path / "hostA"))], env=env),
        subprocess.Popen([sys.executable, "-c",
                          pool_code("mapper-b", "map",
                                    str(tmp_path / "hostB"))], env=env),
        subprocess.Popen([sys.executable, "-c",
                          pool_code("reducer-c", "reduce",
                                    str(tmp_path / "hostC"))], env=env),
    ]
    try:
        server = Server(store, poll_interval=0.05).configure(
            _spec(f"object:{bucket}"))
        stats = server.loop()
    finally:
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    assert dict(finalfn.counts) == golden
    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
    map_workers = {d["worker"] for d in store.jobs(MAP_NS)}
    red_workers = {d["worker"] for d in store.jobs("red_jobs")}
    assert map_workers <= {"mapper-a", "mapper-b"}
    assert red_workers == {"reducer-c"}
    # reduce job docs name their producers (the reference's `mappers`)
    for doc in store.jobs("red_jobs"):
        assert set(doc["value"]["mappers"]) <= {"mapper-a", "mapper-b"}
        assert doc["value"]["mappers"], "producer list must not be empty"


def test_missing_run_file_fails_loudly_naming_producer():
    """A reduce whose run file vanished must raise naming the producer,
    not silently reduce fewer runs (pull-integrity, fs.lua:148-157)."""
    from lua_mapreduce_tpu.engine.worker import Worker as W

    store = MemJobStore()
    spec = _spec("mem:dist-missing-run")
    server = Server(store, poll_interval=0.02).configure(spec)

    # run the map phase with a normal pool, then sabotage one run file
    w = Worker(store).configure(max_iter=200, max_sleep=0.02,
                                phases=("map",))
    t = threading.Thread(target=server.loop, daemon=True)
    t.start()
    while store.get_task() is None or \
            store.get_task().get("status") != TaskStatus.REDUCE.value:
        w.poll_once()
        time.sleep(0.01)
        if not t.is_alive():
            break
    from lua_mapreduce_tpu.store.router import get_storage_from
    data = get_storage_from("mem:dist-missing-run")
    runs = data.list("result.P*.M*")
    assert runs
    data.remove(runs[0])

    victim = W(store, name="red-1")
    victim.configure(max_iter=50, max_sleep=0.02, phases=("reduce",))
    with pytest.raises(RuntimeError, match="not visible in storage"):
        while True:
            out = victim.poll_once()
            if out in ("finished",):
                raise AssertionError("reduce phase finished unexpectedly")
            time.sleep(0.005)

    # drain: retry the poisoned job to FAILED and finish healthy reduces
    # so the background server loop can complete (non-strict: proceeds)
    try:
        W(store, name="red-2").configure(
            max_iter=50, max_sleep=0.02, phases=("reduce",)).execute()
    except RuntimeError:
        pass
    W(store, name="red-3").configure(
        max_iter=50, max_sleep=0.02, phases=("reduce",)).execute()
    t.join(timeout=30)
    assert not t.is_alive(), "server loop did not complete after drain"


@pytest.mark.heavy
def test_server_resume_after_reduce_phase_restart(tmp_path):
    """Resume matrix (server.lua:470-492): a server restarted while the
    task doc says REDUCE must skip the map phase entirely."""
    import examples.wordcount.finalfn as finalfn
    golden = naive_wordcount(CORPUS)
    count_file = str(tmp_path / "mapcalls")
    spec = TaskSpec(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.instrumented",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
        init_args={"files": CORPUS, "count_file": count_file},
        storage="mem:dist-resume",
    )
    store = MemJobStore()
    server, workers, stats = _run_pool(store, spec)
    maps_after_first = read_count(count_file)
    assert maps_after_first == len(CORPUS)

    # simulate a crash after map finished: rewind task doc to REDUCE
    store.update_task({"status": TaskStatus.REDUCE.value})
    # reduce outputs were consumed; re-running reduce needs map outputs —
    # so re-create them by rewinding reduce job statuses is not enough; the
    # realistic crash point is before reduce consumed the runs. Rebuild:
    server2 = Server(store, poll_interval=0.02)
    w = Worker(store).configure(max_iter=400, max_sleep=0.05)
    t = threading.Thread(target=w.execute, daemon=True)
    t.start()
    # map runs were deleted by the first reduce; the resumed reduce phase
    # discovers no partitions and finishes with empty results
    stats2 = server2.loop()
    t.join(timeout=30)
    # the key assertion: no map job ever re-ran
    assert read_count(count_file) == maps_after_first


def test_server_resume_mid_map_keeps_written_jobs(tmp_path):
    """Resume matrix WAIT/MAP branch (server.lua:487-491): a server
    restarted mid-map keeps WRITTEN map jobs — only the unfinished ones
    run after the restart, and the result still golden-diffs."""
    import examples.wordcount.finalfn as finalfn
    golden = naive_wordcount(CORPUS)
    count_file = str(tmp_path / "mapcalls")
    finalfn.counts.clear()
    spec = TaskSpec(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.instrumented",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
        init_args={"files": CORPUS, "count_file": count_file},
        storage="mem:dist-resume-map",
    )
    store = MemJobStore()

    # phase 1: the server CRASHES mid-map — its barrier-poll progress
    # callback raises once half the maps are done, killing loop() for
    # real (no zombie second controller at reduce time)
    class _Crash(Exception):
        pass

    server1 = Server(store, poll_interval=0.02).configure(spec)

    def crash_at_half(phase, frac):
        if phase == "map" and frac >= 0.5:
            raise _Crash()

    crashed = threading.Event()

    def run1():
        try:
            server1.loop(progress=crash_at_half)
        except _Crash:
            crashed.set()

    t = threading.Thread(target=run1, daemon=True)
    t.start()
    w = Worker(store, name="early").configure(max_iter=200, max_sleep=0.02)
    while not crashed.is_set():
        w.poll_once()
        time.sleep(0.005)
        if not t.is_alive() and not crashed.is_set():
            raise AssertionError("server finished before the crash point")
    t.join(timeout=10)
    ran_before_restart = read_count(count_file)
    assert ran_before_restart >= len(CORPUS) // 2

    # phase 2: restarted server resumes in place (same store = the task
    # doc checkpoint); a fresh pool completes the remaining jobs
    _run_pool(store, spec, n_workers=2)

    assert dict(finalfn.counts) == golden
    # every map ran EXACTLY once across the crash boundary
    assert read_count(count_file) == len(CORPUS)


def test_long_job_heartbeat_prevents_wasteful_requeue(monkeypatch):
    """A job legitimately running 3× the server's stale timeout completes
    WITHOUT being requeued while its worker heartbeats; with heartbeats
    disabled the same job IS requeued (the control proving the test
    bites). VERDICT r3 item 8: staleness = silence, not elapsed time."""
    import examples.wordcount.finalfn as finalfn
    import examples.wordcount.mapfn as mapmod

    files = CORPUS[:2]
    golden = naive_wordcount(files)
    orig_mapfn = mapmod.mapfn

    def run(heartbeat_s):
        slow_used = []

        def slow(k, v, emit):
            if not slow_used:                 # exactly one long map job
                slow_used.append(1)
                time.sleep(1.5)               # 3× the 0.5 s stale timeout
            return orig_mapfn(k, v, emit)

        monkeypatch.setattr(mapmod, "mapfn", slow)
        store = MemJobStore()
        requeues = []
        orig_rq = store.requeue_stale

        def counting_rq(ns, older_than_s):
            n = orig_rq(ns, older_than_s)
            if n:
                requeues.append((ns, n))
            return n

        monkeypatch.setattr(store, "requeue_stale", counting_rq)
        server = Server(store, poll_interval=0.05,
                        stale_timeout_s=0.5).configure(
            _spec("mem:dist-hb", init_args={"files": files}))
        worker = Worker(store).configure(max_iter=400, max_sleep=0.05,
                                         heartbeat_s=heartbeat_s)
        t = threading.Thread(target=worker.execute, daemon=True)
        t.start()
        stats = server.loop()
        t.join(timeout=30)
        assert dict(finalfn.counts) == golden
        it = stats.iterations[-1]
        assert it.map.count == len(files) and it.map.failed == 0
        return sum(n for _, n in requeues)

    assert run(heartbeat_s=0.1) == 0      # beating: never requeued
    assert run(heartbeat_s=None) >= 1     # silent: stale-requeued (control)


def test_server_rejects_unreachable_storage(tmp_path):
    """Regression: bare 'mem' (private per process) and mem:tag over a
    multi-process FileJobStore would silently produce empty results."""
    with pytest.raises(ValueError, match="bare 'mem'"):
        Server(MemJobStore()).configure(_spec("mem"))
    with pytest.raises(ValueError, match="multi-process"):
        Server(FileJobStore(str(tmp_path / "c"))).configure(_spec("mem:tag"))


def test_worker_config_rejects_unknown_keys():
    w = Worker(MemJobStore())
    with pytest.raises(KeyError, match="unknown worker config"):
        w.configure(bogus=1)


def test_sigkilled_worker_job_is_requeued(tmp_path):
    """Chaos e2e: SIGKILL a worker process mid-map (no exception handler
    runs, its RUNNING job just goes silent) — the server's stale-requeue
    must hand the job to the surviving worker and the run must still
    golden-diff (SURVEY.md §5 elastic recovery, beyond the reference:
    its RUNNING jobs of dead workers stay stuck forever)."""
    golden = naive_wordcount(CORPUS)
    root = str(tmp_path / "coord")
    spill = str(tmp_path / "spill")
    store = FileJobStore(root)

    # victim worker: claims one map job, then hangs forever
    victim_code = (
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import examples.wordcount.mapfn as m\n"
        "orig = m.mapfn\n"
        "def stall(k, v, emit):\n"
        "    print('CLAIMED', flush=True)\n"
        "    time.sleep(3600)\n"
        "m.mapfn = stall\n"
        "from lua_mapreduce_tpu import FileJobStore, Worker\n"
        f"w = Worker(FileJobStore({root!r})).configure(\n"
        "    max_iter=400, max_sleep=0.05)\n"
        "w.execute()\n")
    victim = subprocess.Popen([sys.executable, "-c", victim_code],
                              env=_subprocess_env(),
                              stdout=subprocess.PIPE, text=True)

    server = Server(store, poll_interval=0.05,
                    stale_timeout_s=1.0).configure(_spec(f"shared:{spill}"))

    killed = {}
    # a healthy worker thread completes everything the victim abandons; it
    # must NOT start until the victim has claimed a job, or (on a 1-core
    # box) it drains every map while the victim is still booting Python.
    # Fast heartbeats: under machine load a job body can outlive the 1.0s
    # stale timeout, and a beat-less LIVE worker's lease would be requeued
    # with a repetition charge — three of those march a good job to FAILED
    # and flake the failed==0 assert. Beating pins repetition bumps to the
    # SIGKILLed victim, which is what the test is about.
    healthy = Worker(store).configure(max_iter=800, max_sleep=0.05,
                                      heartbeat_s=0.25)
    ht = threading.Thread(target=healthy.execute, daemon=True)
    once = threading.Lock()

    def start_healthy():
        if once.acquire(blocking=False):
            # the victim may still be wedged alive (watchdog path): kill it
            # so victim.wait() below returns and the CLAIMED assert reports
            victim.kill()
            ht.start()

    def chaos():
        line = victim.stdout.readline()     # wait until a job is claimed
        killed["claimed"] = line.strip()
        time.sleep(0.2)
        victim.kill()                        # SIGKILL: no cleanup runs
        # start the healthy worker even if the victim died claimless, so
        # the server loop still terminates and the assert reports it
        start_healthy()

    t = threading.Thread(target=chaos, daemon=True)
    t.start()
    # watchdog: if the victim wedges before printing CLAIMED, readline
    # blocks forever — start the healthy worker anyway so server.loop()
    # terminates and the CLAIMED assert reports the real problem
    watchdog = threading.Timer(30, start_healthy)
    watchdog.daemon = True
    watchdog.start()
    stats = server.loop()
    ht.join(timeout=30)
    victim.wait(timeout=10)
    t.join(timeout=10)

    assert killed.get("claimed") == "CLAIMED", "victim never claimed a job"
    import examples.wordcount.finalfn as finalfn
    assert dict(finalfn.counts) == golden
    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
