"""Fault subsystem tests (DESIGN §19): taxonomy, virtual-clock retry,
deterministic injection, build readback-verify, worker fault
discrimination (release-not-broken), heartbeat-thread resilience, the
errors-stream classification fields, and the ranged-read degradation."""

import random
import threading
import time

import pytest

from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job
from lua_mapreduce_tpu.core.constants import Status
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.worker import Worker
from lua_mapreduce_tpu.faults import (COUNTERS, FaultPlan, FaultyStore,
                                      PermanentStoreError, RetryingStore,
                                      RetryPolicy, TransientStoreError,
                                      install_fault_plan, unwrap)
from lua_mapreduce_tpu.store.memfs import MemStore
from lua_mapreduce_tpu.store.router import get_storage_from


def _policy(retries=3):
    return RetryPolicy(retries=retries, base_ms=1, sleep=lambda s: None,
                       rng=random.Random(0))


# --- retry schedule on a virtual clock --------------------------------------

def test_backoff_is_decorrelated_jitter_and_capped():
    sleeps = []
    p = RetryPolicy(retries=6, base_ms=20, cap_ms=100,
                    sleep=sleeps.append, rng=random.Random(3))
    with pytest.raises(TransientStoreError):
        p.call(lambda: (_ for _ in ()).throw(TimeoutError("x")),
               op="size", name="f")
    assert len(sleeps) == 6
    assert all(0.02 <= s <= 0.1 for s in sleeps)
    # decorrelated: the window widens with the previous draw
    assert sleeps != sorted(sleeps, reverse=True)


def test_retry_layer_never_retries_permanent_or_user_errors():
    calls = [0]

    def boom():
        calls[0] += 1
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        _policy().call(boom, op="lines", name="f")
    assert calls[0] == 1          # no second attempt, raw type preserved


# --- deterministic injection through a routed store -------------------------

def test_env_plan_activates_and_deactivates(monkeypatch):
    monkeypatch.setenv("LMR_FAULT_PLAN",
                       "seed=11;transient=0.4;max_per_key=2")
    s1 = get_storage_from("mem:_fault_env_t")
    assert isinstance(s1, RetryingStore)
    assert isinstance(s1._inner, FaultyStore)
    monkeypatch.delenv("LMR_FAULT_PLAN")
    s2 = get_storage_from("mem:_fault_env_t")
    assert isinstance(s2, RetryingStore)
    assert not isinstance(s2._inner, FaultyStore)
    assert unwrap(s1) is unwrap(s2)   # same underlying tagged store


def test_injected_bursts_are_absorbed_end_to_end():
    plan = FaultPlan(21, transient=0.3, latency=0.1, latency_ms=0.0,
                     max_per_key=2, sleep=lambda s: None)
    install_fault_plan(plan)
    try:
        store = get_storage_from("mem:_fault_burst_t")
        with store.builder() as b:
            b.write("x 1\n")
            b.build("runs.P0.M1")
        for _ in range(30):
            assert list(store.lines("runs.P0.M1")) == ["x 1\n"]
            assert store.exists("runs.P0.M1")
            assert store.list("runs.*") == ["runs.P0.M1"]
    finally:
        install_fault_plan(None)
    assert plan.total_fired() > 0     # the schedule really fired


# --- build ambiguity (readback-verify) --------------------------------------

def test_error_after_write_never_duplicates_published_segment():
    raw = MemStore()
    plan = FaultPlan(31, error_after_write=1.0, max_per_key=1,
                     sleep=lambda s: None)
    store = RetryingStore(FaultyStore(raw, plan), _policy())
    with store.builder() as b:
        b.write("v1 line\n")
        b.build("seg")
    # landed exactly once, whole, despite the post-publish error
    assert list(raw.lines("seg")) == ["v1 line\n"]
    assert plan.fired == {"error_after_write": 1}


def test_torn_write_detected_and_rebuilt_whole():
    plan = FaultPlan(32, torn=1.0, max_per_key=1, sleep=lambda s: None)
    raw = MemStore()
    store = RetryingStore(FaultyStore(raw, plan), _policy())
    with store.builder() as b:
        for i in range(50):
            b.write(f"record {i:04d}\n")
        b.build("spill")
    assert len(list(raw.lines("spill"))) == 50
    assert raw.size("spill") == 50 * len("record 0000\n")


# --- worker fault discrimination --------------------------------------------

def _spec(mapfn, tag):
    return TaskSpec(taskfn=lambda emit: emit("k", 1), mapfn=mapfn,
                    partitionfn=lambda key: 0,
                    reducefn=lambda key, values: sum(values),
                    storage=f"mem:{tag}")


def _one_claimed_job(store, worker):
    store.insert_jobs("map_jobs", [make_job("k", 1)])
    jobs = worker.store.claim_batch("map_jobs", worker.name, 1)
    assert len(jobs) == 1
    return jobs


@pytest.mark.parametrize("exc,status,reps,classification", [
    (TransientStoreError("503 burst"), Status.WAITING, 0,
     "infra-transient"),
    (PermanentStoreError("bucket gone"), Status.BROKEN, 1,
     "infra-permanent"),
    (ValueError("user bug"), Status.BROKEN, 1, "user-code"),
    # provenance matters: a RAW TimeoutError out of a job body is USER
    # code (an http call in a mapfn), not a releasable infra fault —
    # only StoreError subclasses provably crossed the store boundary
    (TimeoutError("user timeout"), Status.BROKEN, 1, "user-code"),
], ids=["transient-releases", "permanent-breaks", "user-code-breaks",
        "raw-builtin-is-user-code"])
def test_worker_discriminates_infra_from_user_faults(exc, status, reps,
                                                     classification):
    """The tentpole contract: transient infra faults release the job
    back to WAITING with NO repetition charge; deterministic faults
    (user code, permanent infra) mark BROKEN exactly as before."""
    store = MemJobStore()
    w = Worker(store, name="wdisc")
    w.heartbeat_s = 0          # keep the test single-threaded

    def mapfn(key, value, emit):
        raise exc

    jobs = _one_claimed_job(store, w)
    with pytest.raises(type(exc)):
        w._execute_batch(_spec(mapfn, f"wdisc-{classification}"),
                         "map_jobs", jobs)
    d = store.get_job("map_jobs", 0)
    assert d["status"] == status
    assert d["repetitions"] == reps
    (err,) = store.drain_errors()
    assert err["classification"] == classification
    assert err["exc_class"] == type(exc).__name__
    assert err["ns"] == "map_jobs" and err["job_id"] == 0
    assert err["msg"]            # abbreviated traceback present


def test_release_budget_bounds_pinned_transient_faults():
    """Liveness backstop: a job whose every execution raises a
    'transient' StoreError (a fault pinned to the job — corrupt object
    only its reads hit) is released at most MAX_JOB_RETRIES times per
    worker, then marches through BROKEN like a deterministic failure —
    no infinite release/re-claim livelock."""
    from lua_mapreduce_tpu.core.constants import MAX_JOB_RETRIES

    store = MemJobStore()
    store.insert_jobs("map_jobs", [make_job("k", 1)])
    w = Worker(store, name="wbudget")
    w.heartbeat_s = 0

    def mapfn(key, value, emit):
        raise TransientStoreError("pinned fault")

    spec = _spec(mapfn, "wbudget")
    for attempt in range(MAX_JOB_RETRIES + 1):
        jobs = w.store.claim_batch("map_jobs", "wbudget", 1)
        assert jobs, f"job not claimable on attempt {attempt}"
        with pytest.raises(TransientStoreError):
            w._execute_batch(spec, "map_jobs", jobs)
        d = store.get_job("map_jobs", 0)
        if attempt < MAX_JOB_RETRIES:
            assert d["status"] == Status.WAITING and d["repetitions"] == 0
        else:
            assert d["status"] == Status.BROKEN and d["repetitions"] == 1


def test_release_budget_resets_per_task_iteration():
    """The per-job release budget is scoped to ONE (task, iteration):
    namespaces are dropped and re-inserted per iteration, so job ids
    restart at 0 — a budget carried across iterations would wrongly
    charge iteration N+1's job 0 for iteration N's releases, and after
    a few iterations every transient infra fault on a recurring id
    would take the BROKEN path (the exact repetition charge the
    release mechanism exists to prevent)."""
    from lua_mapreduce_tpu.core.constants import TaskStatus

    store = MemJobStore()
    w = Worker(store, name="wgen")
    spec = TaskSpec(taskfn="examples.wordcount.taskfn",
                    mapfn="examples.wordcount.mapfn",
                    partitionfn="examples.wordcount.partitionfn",
                    reducefn="examples.wordcount.reducefn",
                    storage="mem:wgen")
    store.put_task({"_id": "unique", "status": TaskStatus.MAP.value,
                    "iteration": 1, "spec": spec.describe(),
                    "pipeline": False, "batch_k": 1,
                    "segment_format": "v1"})

    assert w.poll_once() == "idle"          # no claimable jobs
    w._infra_released[("map_jobs", 0)] = 3  # budget consumed this iter
    assert w.poll_once() == "idle"          # same iteration: retained
    assert w._infra_released == {("map_jobs", 0): 3}

    store.update_task({"iteration": 2})     # namespaces restart at id 0
    assert w.poll_once() == "idle"
    assert w._infra_released == {}

    w._infra_released[("map_jobs", 0)] = 3
    store.update_task({"status": TaskStatus.FINISHED.value})
    assert w.poll_once() == "finished"      # task over: budget dropped
    assert w._infra_released == {}


def test_worker_poll_loop_survives_coord_brownout(monkeypatch):
    """A transient coord-store burst on the UN-retried claim path must
    not kill the worker: classified infra faults back off and re-poll
    instead of burning the 3-strike user-code budget (a sub-second
    brownout would exhaust it in ~0.3s of fast polls and take down the
    whole fleet), while still giving up past MAX_INFRA_POLL_FAILURES."""
    store = MemJobStore()
    store.insert_jobs("map_jobs", [make_job("k", 1)])
    w = Worker(store, name="wpoll")
    w.heartbeat_s = 0
    monkeypatch.setattr(time, "sleep", lambda s: None)  # virtual clock

    outcomes = {"n": 0}
    real_poll = w.poll_once

    def flaky_poll():
        outcomes["n"] += 1
        if outcomes["n"] <= 5:          # > MAX_WORKER_RETRIES=3 bursts
            raise TransientStoreError("claim brownout")
        return real_poll()

    monkeypatch.setattr(w, "poll_once", flaky_poll)
    w.configure(max_iter=3, max_sleep=0.01)
    w.execute()                         # must NOT raise
    assert outcomes["n"] > 5            # polled through the brownout

    # liveness: a permanently failing coord store still kills the worker
    monkeypatch.setattr(
        w, "poll_once",
        lambda: (_ for _ in ()).throw(TransientStoreError("dead store")))
    with pytest.raises(TransientStoreError):
        w.execute()
    # and a user-code failure storm still dies at MAX_WORKER_RETRIES
    calls = {"n": 0}

    def user_fail():
        calls["n"] += 1
        raise ValueError("user bug")

    monkeypatch.setattr(w, "poll_once", user_fail)
    with pytest.raises(ValueError):
        w.execute()
    assert calls["n"] == 3


def test_no_replay_retention_on_atomic_publish_backends(tmp_path):
    """Atomic tempfile+rename backends (mem/shared/local-object) never
    pay the replay-chunk memory: a failed build there provably did not
    publish. Ambiguous backends (and FaultyStore, which tears builds on
    purpose) retain."""
    from lua_mapreduce_tpu.store.objectfs import ObjectStore
    from lua_mapreduce_tpu.store.sharedfs import SharedStore

    policy = _policy()
    for raw in (MemStore(), SharedStore(str(tmp_path / "s")),
                ObjectStore(str(tmp_path / "o"))):
        b = RetryingStore(raw, policy).builder()
        b.write("x\n")
        assert b._chunks is None, type(raw).__name__
        b.close()
    plan = FaultPlan(1, sleep=lambda s: None)
    b = RetryingStore(FaultyStore(MemStore(), plan), policy).builder()
    b.write("x\n")
    assert b._chunks == ["x\n"]
    b.close()


def test_release_preserves_batch_commit_prefix():
    """A transient fault on job i of a lease still commits the done
    prefix and releases the unstarted tail — the batch-lease failure
    discipline is unchanged by the discrimination."""
    store = MemJobStore()
    store.insert_jobs("map_jobs", [make_job(f"k{i}", i) for i in range(3)])
    w = Worker(store, name="wbatch")
    w.heartbeat_s = 0
    jobs = w.store.claim_batch("map_jobs", "wbatch", 3)
    calls = [0]

    def mapfn(key, value, emit):
        calls[0] += 1
        if calls[0] == 2:
            raise TransientStoreError("mid-lease blip")
        emit("n", value)

    with pytest.raises(TransientStoreError):
        w._execute_batch(_spec(mapfn, "wbatch"), "map_jobs", jobs)
    sts = [store.get_job("map_jobs", i)["status"] for i in range(3)]
    assert sts == [Status.WRITTEN, Status.WAITING, Status.WAITING]
    assert all(store.get_job("map_jobs", i)["repetitions"] == 0
               for i in range(3))


def test_duplicate_reduce_execution_short_circuits_on_published_result():
    """Degradation-ladder regression: a stale-requeued reduce job whose
    FIRST claimant already published the partition result (and deleted
    the consumed runs) must short-circuit as DONE on re-execution — the
    premerge spill-exists pattern. Failing instead livelocks the job:
    the runs are gone forever, every retry fails missing-runs, and a
    COMPLETED partition marches to FAILED (observed wedging the churn
    suite's batch-lease leg)."""
    from lua_mapreduce_tpu.coord.jobstore import make_job
    from lua_mapreduce_tpu.store.router import get_storage_from

    storage = "mem:_dup_reduce_t"
    store = get_storage_from(storage)
    with store.builder() as b:
        b.write('["n", [4]]\n')
        b.build("result.P0")            # the first claimant's publish
    # the consumed runs are already deleted; one stale leftover remains
    with store.builder() as b:
        b.write('["n", [1]]\n')
        b.build("result.P0.M00000001")

    js = MemJobStore()
    js.insert_jobs("red_jobs", [make_job(0, {
        "part": 0,
        "files": ["result.P0.M00000000", "result.P0.M00000001"],
        "result": "result.P0", "mappers": ["w-old"]})])
    w = Worker(js, name="wdup")
    w.heartbeat_s = 0
    jobs = w.store.claim_batch("red_jobs", "wdup", 1)
    spec = TaskSpec(taskfn=lambda emit: emit("k", 1),
                    mapfn=lambda key, value, emit: emit("n", value),
                    partitionfn=lambda key: 0,
                    reducefn=lambda key, values: sum(values),
                    storage=storage)
    w._execute_batch(spec, "red_jobs", jobs)        # must NOT raise
    d = js.get_job("red_jobs", 0)
    assert d["status"] == Status.WRITTEN and d["repetitions"] == 0
    assert list(store.lines("result.P0")) == ['["n", [4]]\n']  # untouched
    assert not store.exists("result.P0.M00000001")  # leftovers swept


# --- heartbeat thread resilience (satellite regression) ----------------------

class _FlakyHeartbeatStore:
    """JobStore facade whose heartbeat_batch raises (an UNCLASSIFIED
    error, so the retry layer passes it through) the first N calls."""

    def __init__(self, inner, fail_first):
        self._inner = inner
        self.fail_first = fail_first
        self.hb_calls = 0

    def heartbeat_batch(self, ns, jids, worker):
        self.hb_calls += 1
        if self.hb_calls <= self.fail_first:
            raise ValueError(f"flaky store (call {self.hb_calls})")
        return self._inner.heartbeat_batch(ns, jids, worker)

    def classify(self, exc):
        return self._inner.classify(exc)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def test_heartbeat_thread_survives_store_exceptions():
    """Regression (ISSUE 5 satellite): the beat thread used to be able
    to die with its exception unlogged, silently stopping liveness
    beats — the server then stale-requeues a LIVE worker's job. It must
    log, back off, and RESUME beating once the store recovers."""
    inner = MemJobStore()
    inner.insert_jobs("map_jobs", [make_job("k", 1)])
    flaky = _FlakyHeartbeatStore(inner, fail_first=3)
    w = Worker(flaky, name="whb")
    w.heartbeat_s = 0.01
    jobs = w.store.claim_batch("map_jobs", "whb", 1)
    assert jobs
    with w._beating("map_jobs", [0]):
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if flaky.hb_calls > 3 and \
                    inner.get_job("map_jobs", 0)["hb_time"] is not None:
                break
            time.sleep(0.01)
    assert flaky.hb_calls > 3, "beat thread died after the failures"
    assert inner.get_job("map_jobs", 0)["hb_time"] is not None, \
        "no beat landed after the store recovered"


# --- errors-stream structured fields over FileJobStore ----------------------

def test_filestore_errors_carry_classification_fields(tmp_path):
    fs = FileJobStore(str(tmp_path / "coord"))
    fs.insert_error("w1", "Traceback ...",
                    info={"exc_class": "TimeoutError",
                          "classification": "infra-transient",
                          "ns": "map_jobs", "job_id": 7})
    (err,) = fs.drain_errors()
    assert err["exc_class"] == "TimeoutError"
    assert err["classification"] == "infra-transient"
    assert err["job_id"] == 7 and err["worker"] == "w1"
    # info-less inserts (third-party callers) keep working
    fs.insert_error("w2", "plain")
    (err2,) = fs.drain_errors()
    assert err2["msg"] == "plain" and "exc_class" not in err2


# --- ranged-read degradation (segment reader) --------------------------------

class _RangedFlakyStore:
    """read_range fails with a transient fault for any offset > 0; the
    offset-0 whole-file read succeeds — the 'ranged GETs broken, plain
    GET fine' object-store failure shape."""

    def __init__(self, inner):
        self._inner = inner
        self.ranged_attempts = 0

    def read_range(self, name, offset, length):
        if offset > 0:
            self.ranged_attempts += 1
            raise TransientStoreError(f"ranged read {offset}+{length}")
        return self._inner.read_range(name, offset, length)

    def classify(self, exc):
        return self._inner.classify(exc)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def test_segment_reader_degrades_to_whole_file_read():
    from lua_mapreduce_tpu.core.segment import record_stream, writer_for

    raw = MemStore()
    recs = [(f"k{i:03d}", [i]) for i in range(200)]
    with writer_for(raw, "v2") as wtr:
        for k, v in recs:
            wtr.add(k, v)
        wtr.build("seg")

    before = COUNTERS.snapshot().get("degraded_reads", 0)
    flaky = _RangedFlakyStore(raw)
    assert list(record_stream(flaky, "seg")) == recs
    assert flaky.ranged_attempts == 1     # first ranged miss, then whole
    assert COUNTERS.snapshot().get("degraded_reads", 0) == before + 1


def test_stats_fold_fault_counters():
    """LocalExecutor folds the fault counters into IterationStats, so a
    chaos run's telemetry survives into the stats surface."""
    from lua_mapreduce_tpu.engine.local import LocalExecutor

    plan = FaultPlan(41, transient=0.25, max_per_key=1, sleep=lambda s: None)
    install_fault_plan(plan)
    try:
        corpus = {"d1": "a b a", "d2": "b"}

        def taskfn(emit):
            for k, v in corpus.items():
                emit(k, v)

        def mapfn(key, value, emit):
            for word in value.split():
                emit(word, 1)

        spec = TaskSpec(taskfn=taskfn, mapfn=mapfn,
                        partitionfn=lambda key: 0,
                        reducefn=lambda key, values: sum(values),
                        storage="mem:_fault_stats_t")
        ex = LocalExecutor(spec)
        stats = ex.run()
        assert {k: v[0] for k, v in ex.results()} == {"a": 2, "b": 2}
    finally:
        install_fault_plan(None)
    it = stats.iterations[-1]
    assert it.store_faults >= 1           # injections were counted
    d = it.as_dict()
    assert {"store_retries", "store_faults", "infra_releases",
            "degraded_reads"} <= set(d)


# --- RetryingStore.lines mid-stream contract (ISSUE 6 satellite) ------------

class _MidStreamFlakyStore:
    """lines() raises a transient fault BEFORE the first record on the
    first open, then — once reopened — dies again after yielding two
    records: the connection-drop-mid-scan shape. Tracks opens so the
    no-silent-reopen contract is assertable."""

    def __init__(self, inner, records):
        self._inner = inner
        self.records = records
        self.opens = 0

    def lines(self, name):
        self.opens += 1
        if self.opens == 1:
            raise TransientStoreError("dropped at open")
        for i, rec in enumerate(self.records):
            if self.opens == 2 and i == 2:
                raise TransientStoreError("dropped mid-stream")
            yield rec

    def classify(self, exc):
        return self._inner.classify(exc)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def test_retrying_lines_mid_stream_fault_propagates():
    """Pins the documented lines() retry scope: the OPEN + FIRST record
    ride the retry policy (a fault there is re-opened transparently),
    but a fault AFTER records were yielded downstream must propagate —
    a silent re-open would re-yield records the merge already consumed,
    duplicating data. The consumer-side recovery for the mid-stream
    shape is the job-level release (worker) + scavenger repair ladder,
    not a stream restart."""
    flaky = _MidStreamFlakyStore(MemStore(), [f"r{i}\n" for i in range(5)])
    store = RetryingStore(flaky, _policy())
    it = iter(store.lines("f"))
    assert next(it) == "r0\n"
    assert flaky.opens == 2               # open-fault was retried once
    assert next(it) == "r1\n"
    with pytest.raises(TransientStoreError, match="mid-stream"):
        next(it)
    assert flaky.opens == 2               # and NEVER silently reopened


def test_replicated_lines_mid_stream_fault_propagates():
    """The failover view keeps the same mid-stream contract: replica
    failover happens at open/first-record only — once records flowed, a
    fault propagates rather than restarting the stream on another copy
    (which would duplicate consumed records)."""
    from lua_mapreduce_tpu.faults.replicate import ReplicatedStore

    flaky = _MidStreamFlakyStore(MemStore(), [f"r{i}\n" for i in range(5)])
    store = ReplicatedStore(flaky, 2)
    it = iter(store.lines("f"))
    assert next(it) == "r0\n"             # first-record fault failed over
    with pytest.raises(TransientStoreError, match="mid-stream"):
        for _ in it:
            pass
    assert flaky.opens == 2               # no third-copy stream restart


# --- replica-aware shuffle (DESIGN §20) -------------------------------------

def test_worker_releases_reduce_on_total_replica_loss():
    """Every copy of a reduce input gone: the job is RELEASED (WAITING,
    zero repetition charge — the loss is not the job's fault) and the
    errors-stream entry names the lost files, the hook the server's
    scavenger repairs or requeues on."""
    from lua_mapreduce_tpu.faults.errors import LostShuffleDataError

    store = MemJobStore()
    w = Worker(store, name="wloss")
    w.heartbeat_s = 0
    w.configure(replication=2)
    spec = _spec(lambda key, value, emit: emit("k", 1), "wloss")
    files = ["result.P0.M00000000", "result.P0.M00000001"]
    store.insert_jobs("red_jobs", [make_job(0, {
        "part": 0, "files": files, "result": "result.P0", "mappers": []})])
    jobs = w.store.claim_batch("red_jobs", "wloss", 1)
    assert jobs
    with pytest.raises(LostShuffleDataError):
        w._execute_batch(spec, "red_jobs", jobs)
    d = store.get_job("red_jobs", 0)
    assert d["status"] == Status.WAITING and d["repetitions"] == 0
    (err,) = store.drain_errors()
    assert err["classification"] == "infra-transient"
    assert err["lost_files"] == files


def _recovery_server(tag, replication=2, n_maps=2):
    """A Server wired for scavenge-path unit tests: spec + data store
    bound (what loop() does), map jobs inserted and WRITTEN."""
    from lua_mapreduce_tpu.engine.server import Server

    store = MemJobStore()
    spec = _spec(lambda key, value, emit: emit("k", 1), tag)
    srv = Server(store, replication=replication)
    srv.spec = spec               # what configure()+loop() bind, without
    srv._data_store = None        # requiring module-path functions
    srv._data_store = get_storage_from(spec.storage)
    store.insert_jobs("map_jobs", [make_job(i, i) for i in range(n_maps)])
    for jid in range(n_maps):
        assert store.set_job_status("map_jobs", jid, Status.RUNNING)
        assert store.set_job_status("map_jobs", jid, Status.WRITTEN)
    return srv, store


def _publish(store, name, replication, payload="x\t[1]\n"):
    from lua_mapreduce_tpu.faults.replicate import spill_writer

    with spill_writer(store, "v1", replication) as wtr:
        wtr.add("x", [1])
        wtr.build(name)


def test_scavenger_repairs_under_replicated_file():
    """Rung 3 of the failover ladder: a lost copy with a survivor is
    REBUILT by the scavenger (counted replica_repairs) — no job state
    touched, no map re-run."""
    from lua_mapreduce_tpu.engine.placement import replica_name

    srv, store = _recovery_server("scav-repair")
    raw = srv._data_store
    name = "result.P0.M00000000"
    _publish(raw, name, 2)
    golden = raw.read_range(name, 0, raw.size(name))
    raw.remove(name)                      # primary lost, replica survives
    before = COUNTERS.snapshot().get("replica_repairs", 0)
    srv._recover_lost([name])
    assert raw.read_range(name, 0, 99) == golden[:99]   # primary rebuilt
    assert raw.exists(replica_name(name, 1))
    assert COUNTERS.snapshot()["replica_repairs"] == before + 1
    d = store.get_job("map_jobs", 0)
    assert d["status"] == Status.WRITTEN  # producer untouched


def test_scavenger_requeues_producer_on_total_loss():
    """Rung 4 (last resort): every copy gone — the producing map job is
    CAS-requeued WRITTEN→WAITING with no repetition charge, counted
    map_reruns, and the errors stream distinguishes the requeue as
    spill-lost-requeue (the ISSUE 6 diagnostics satellite)."""
    srv, store = _recovery_server("scav-requeue")
    name = "result.P0.M00000001"          # produced by map job 1
    before = COUNTERS.snapshot().get("map_reruns", 0)
    srv._recover_lost([name])             # no copy was ever published
    assert store.get_job("map_jobs", 1)["status"] == Status.WAITING
    assert store.get_job("map_jobs", 1)["repetitions"] == 0
    assert store.get_job("map_jobs", 0)["status"] == Status.WRITTEN
    assert COUNTERS.snapshot()["map_reruns"] == before + 1
    (err,) = store.drain_errors()
    assert err["classification"] == "spill-lost-requeue"
    assert err["job_id"] == 1


def test_scavenger_republishes_premerge_for_lost_spill():
    """A lost SPILL requeues every covering producer and, once they all
    re-land, republishes the pre-merge job so the retrying reduce finds
    its spill again — the pipelined half of the reconstruction path."""
    from lua_mapreduce_tpu.engine.premerge import spill_name

    srv, store = _recovery_server("scav-spill")
    raw = srv._data_store
    spill = spill_name("result", 0, 0, 1)     # covers map keys 0..1
    srv._recover_lost([spill])                # all copies gone
    for jid in range(2):
        assert store.get_job("map_jobs", jid)["status"] == Status.WAITING
    assert srv._spill_repairs == {spill: (0, 0, 1)}

    # producers re-ran: runs are back, statuses WRITTEN again
    for jid in range(2):
        _publish(raw, f"result.P0.M{jid:08d}", 2)
        assert store.set_job_status("map_jobs", jid, Status.RUNNING)
        assert store.set_job_status("map_jobs", jid, Status.WRITTEN)
    srv._settle_spill_repairs()
    assert srv._spill_repairs == {}
    (job,) = store.jobs("pre_jobs")
    assert job["value"]["spill"] == spill
    assert job["value"]["files"] == ["result.P0.M00000000",
                                     "result.P0.M00000001"]


def test_blackout_dark_tag_absorbed_by_replication():
    """The blackout kind × the placement function: every op on ONE
    placement tag fails transient for the window — with r=2 the copies
    live on two different tags, so the failover view serves every read
    from the lit tag and the blackout is invisible to consumers."""
    from lua_mapreduce_tpu.engine.placement import replica_name, tag_of
    from lua_mapreduce_tpu.faults.replicate import ReplicatedStore

    raw = MemStore()
    name = "result.P0.M00000007"
    _publish(raw, name, 2)
    vt = [0.0]
    plan = FaultPlan(9, blackout_tag=tag_of(name), blackout_s=60.0,
                     clock=lambda: vt[0], sleep=lambda s: None,
                     latency_ms=0)
    view = ReplicatedStore(FaultyStore(raw, plan), 2)
    rec = list(raw.lines(name))
    # dark window: primary's tag fails every op, replica serves
    assert list(view.lines(name)) == rec
    assert view.exists(name) and view.size(name) == raw.size(name)
    assert plan.fired.get("blackout", 0) > 0
    vt[0] = 61.0                          # window over: tag back, and
    fired = plan.fired["blackout"]        # the plan goes quiet
    assert list(view.lines(name)) == rec
    assert plan.fired["blackout"] == fired
    assert tag_of(replica_name(name, 1)) != tag_of(name)
