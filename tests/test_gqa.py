"""Grouped-query attention across the stack: kernels, sharded forms,
decode/prefill. The golden construction: a GQA model is EXACTLY an MHA
model whose kv projection columns are tiled per group — every test
pins the GQA path against that equivalence or against the oracle."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from lua_mapreduce_tpu.models import transformer as tfm
from lua_mapreduce_tpu.ops.attention import flash_attention
from lua_mapreduce_tpu.parallel.mesh import make_mesh

H, HKV, HD = 8, 2, 8
D = H * HD


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                     axis_names=("dp", "sp"))


@pytest.fixture(scope="module")
def gqa_cfg():
    return tfm.TransformerConfig(vocab=64, d_model=D, n_heads=H,
                                 n_layers=2, d_ff=96, max_seq=128,
                                 n_kv_heads=HKV)


def _tile_kv_to_mha(params, cfg):
    """GQA params → the equivalent MHA params (kv columns tiled per
    group). Exact: duplicated kv heads compute identical projections."""
    g = cfg.n_heads // tfm.kv_heads(cfg)
    h, hkv, hd = cfg.n_heads, tfm.kv_heads(cfg), cfg.d_model // cfg.n_heads
    d = cfg.d_model
    out = dict(params)
    for i in range(cfg.n_layers):
        w = params[f"L{i}_qkv_W"]
        q = w[:, :h * hd]
        k = w[:, h * hd:(h + hkv) * hd].reshape(d, hkv, hd)
        v = w[:, (h + hkv) * hd:].reshape(d, hkv, hd)
        out[f"L{i}_qkv_W"] = jnp.concatenate(
            [q, jnp.repeat(k, g, axis=1).reshape(d, h * hd),
             jnp.repeat(v, g, axis=1).reshape(d, h * hd)], axis=1)
    return out


def test_config_validation():
    with pytest.raises(ValueError, match="must divide"):
        tfm.init_transformer(jax.random.PRNGKey(0),
                             tfm.TransformerConfig(n_heads=4,
                                                   n_kv_heads=3))
    assert tfm.kv_heads(tfm.TransformerConfig(n_heads=4)) == 4
    assert tfm.kv_heads(tfm.TransformerConfig(n_heads=4,
                                              n_kv_heads=2)) == 2


def test_flash_kernel_gqa_matches_repeated_kv():
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 96, H, HD), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(2, 96, HKV, HD), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(2, 96, HKV, HD), jnp.float32) * 0.5
    g = H // HKV
    want = flash_attention(q, jnp.repeat(k, g, 2), jnp.repeat(v, g, 2),
                           causal=True, backend="xla")
    got = flash_attention(q, k, v, causal=True,
                          backend="pallas_interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_kernel_gqa_grads():
    """Fused backward under GQA: the dkv kernel's regrouped grid must
    sum every q-head-in-group's contribution into its kv head."""
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, 200, H, HD), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(2, 200, HKV, HD), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(2, 200, HKV, HD), jnp.float32) * 0.5

    def loss(backend):
        return lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, backend=backend) ** 2)

    gp = jax.grad(loss("pallas_interpret"), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gp, gx):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")
    assert gp[1].shape[2] == HKV    # kv grads live in kv-head space


def test_flash_gqa_shape_validation():
    q = jnp.zeros((1, 8, 6, 4))
    kv = jnp.zeros((1, 8, 4, 4))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, kv, kv)


def test_oracle_gqa_equals_tiled_mha(gqa_cfg):
    params = tfm.init_transformer(jax.random.PRNGKey(3), gqa_cfg)
    mha_cfg = dataclasses.replace(gqa_cfg, n_kv_heads=0)
    mha_params = _tile_kv_to_mha(params, gqa_cfg)
    toks = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 32)),
                       jnp.int32)
    a = tfm.transformer_apply(params, toks, cfg=gqa_cfg)
    b = tfm.transformer_apply(mha_params, toks, cfg=mha_cfg)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("attn", ["ring", "zigzag", "ulysses"])
@pytest.mark.heavy
def test_sharded_forward_gqa_matches_oracle(mesh, gqa_cfg, attn):
    params = tfm.init_transformer(jax.random.PRNGKey(4), gqa_cfg)
    toks = jnp.asarray(np.random.RandomState(5).randint(0, 64, (4, 64)),
                       jnp.int32)
    want = tfm.transformer_apply(params, toks, cfg=gqa_cfg)
    got = tfm.make_sharded_apply(gqa_cfg, mesh, attn=attn)(params, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_rejects_indivisible_kv_heads(mesh, gqa_cfg):
    # HKV=2 over sp=2 divides; force 1 kv head to trip the check
    cfg = dataclasses.replace(gqa_cfg, n_kv_heads=1)
    with pytest.raises(ValueError, match="n_kv_heads divisible"):
        tfm.make_sharded_apply(cfg, mesh, attn="ulysses")


@pytest.mark.heavy
def test_train_step_gqa_learns(mesh, gqa_cfg):
    """GQA training end to end (ring attention, flash backward under
    the hood): the copy task's loss must drop."""
    rng = np.random.RandomState(6)
    b, l = 8, 64
    start = rng.randint(0, 64, (b, 1))
    seq = (start + np.arange(l + 1)) % 64
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    targets = jnp.asarray(seq[:, 1:], jnp.int32)
    params = tfm.init_transformer(jax.random.PRNGKey(7), gqa_cfg)
    opt = optax.adam(3e-3)
    step = tfm.make_train_step(gqa_cfg, mesh, opt, attn="ring")
    st = opt.init(params)
    td = tfm.shard_batch(mesh, tokens, targets)
    first = None
    for _ in range(30):
        params, st, loss = step(params, st, *td)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.6 * first, (first, float(loss))


@pytest.mark.heavy
def test_decode_gqa_matches_full_forward(gqa_cfg):
    """KV-cached GQA decode (grouped einsum against the H_kv-head
    cache) vs re-running the full forward at every prefix."""
    params = tfm.init_transformer(jax.random.PRNGKey(8), gqa_cfg)
    prompt = jnp.asarray(np.random.RandomState(9).randint(0, 64, (3, 5)),
                         jnp.int32)
    n_new = 6
    got = tfm.greedy_decode(params, prompt, n_new, cfg=gqa_cfg)
    toks = prompt
    for _ in range(n_new):
        logits = tfm.transformer_apply(params, toks, cfg=gqa_cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    assert np.array_equal(np.asarray(got), np.asarray(toks))


@pytest.mark.heavy
def test_prefill_gqa_matches_scan_and_shrinks_cache(mesh, gqa_cfg):
    params = tfm.init_transformer(jax.random.PRNGKey(10), gqa_cfg)
    prompt = jnp.asarray(
        np.random.RandomState(11).randint(0, 64, (4, 16)), jnp.int32)
    caches, _ = tfm.prefill(params, prompt, cfg=gqa_cfg, total=24)
    # the cache carries H_kv heads — 4x smaller than MHA here
    assert caches["L0_k"].shape == (4, 24, HKV, HD)
    a = tfm.greedy_decode(params, prompt, 6, cfg=gqa_cfg)
    b = tfm.greedy_decode(params, prompt, 6, cfg=gqa_cfg,
                          use_prefill=True)
    c = tfm.greedy_decode(params, prompt, 6, cfg=gqa_cfg,
                          use_prefill=True, mesh=mesh, attn="ring")
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(np.asarray(a), np.asarray(c))


def test_3d_tp_rejects_gqa(gqa_cfg):
    devices = jax.devices("cpu")[:8]
    from jax.sharding import Mesh
    mesh3 = Mesh(np.array(devices).reshape(2, 2, 2), ("dp", "sp", "mp"))
    with pytest.raises(ValueError, match="MHA only"):
        tfm.make_train_step_3d(gqa_cfg, mesh3, optax.sgd(0.1))
    params = tfm.init_transformer(jax.random.PRNGKey(0), gqa_cfg)
    with pytest.raises(ValueError, match="MHA only"):
        tfm.shard_params_3d(params, mesh3, gqa_cfg)


def test_flops_per_token_gqa_accounting(gqa_cfg):
    """GQA shrinks only the kv projection term."""
    mha = dataclasses.replace(gqa_cfg, n_kv_heads=0)
    l = 32
    diff = tfm.flops_per_token(mha, l) - tfm.flops_per_token(gqa_cfg, l)
    # per layer: 2*d*(2H - 2Hkv)*hd fewer proj FLOPs, x3 for fwd+bwd
    want = 3.0 * gqa_cfg.n_layers * 2 * D * 2 * (H - HKV) * HD
    assert diff == want
