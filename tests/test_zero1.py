"""ZeRO-1 sharded optimizer state (parallel/zero1.py +
make_train_step(zero1=True)): numerically identical to the replicated
step, with Adam's moments actually living in 1/n_dp shards."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from lua_mapreduce_tpu.models import transformer as tfm
from lua_mapreduce_tpu.parallel import zero1 as z1
from lua_mapreduce_tpu.parallel.mesh import make_mesh
from lua_mapreduce_tpu.utils.jax_compat import shard_map

N_DP = 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=N_DP, mp=2, devices=jax.devices("cpu")[:8],
                     axis_names=("dp", "sp"))


@pytest.fixture(scope="module")
def cfg():
    return tfm.TransformerConfig.llama_style(
        vocab=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=48, max_seq=128)


def _batch(cfg, b=8, l=32, seed=0):
    rng = np.random.RandomState(seed)
    seq = rng.randint(0, cfg.vocab, (b, l + 1))
    return (jnp.asarray(seq[:, :-1], jnp.int32),
            jnp.asarray(seq[:, 1:], jnp.int32))


@pytest.mark.heavy
def test_zero1_matches_replicated_step(mesh, cfg):
    """5 Adam steps: the sharded-optimizer path lands on the SAME
    params and losses as the replicated path (reduce_scatter+update+
    all_gather ≡ all_reduce+update, up to float associativity)."""
    toks, tgts = _batch(cfg)
    td = tfm.shard_batch(mesh, toks, tgts)
    params = tfm.init_transformer(jax.random.PRNGKey(1), cfg)
    opt = optax.adam(3e-3)

    p_rep = jax.tree.map(jnp.copy, params)
    st_rep = opt.init(p_rep)
    step_rep = tfm.make_train_step(cfg, mesh, opt, attn="ring")
    p_z = jax.tree.map(jnp.copy, params)
    st_z = z1.init_state(opt, p_z, mesh, dp_axis="dp")
    step_z = tfm.make_train_step(cfg, mesh, opt, attn="ring",
                                 zero1=True)
    for i in range(5):
        p_rep, st_rep, l_rep = step_rep(p_rep, st_rep, *td)
        p_z, st_z, l_z = step_z(p_z, st_z, *td)
        assert abs(float(l_rep) - float(l_z)) < 1e-5, i
    for k in p_rep:
        np.testing.assert_allclose(np.asarray(p_z[k]),
                                   np.asarray(p_rep[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_state_is_actually_sharded(mesh, cfg):
    """Adam m/v leaves live in 1/n_dp shards on the dp axis; the step
    count replicates."""
    params = tfm.init_transformer(jax.random.PRNGKey(2), cfg)
    opt = optax.adam(1e-3)
    st = z1.init_state(opt, params, mesh, dp_axis="dp")
    leaves = jax.tree.leaves(st)
    arrays = [x for x in leaves if x.ndim >= 1]
    scalars = [x for x in leaves if x.ndim == 0]
    assert arrays and scalars
    total_param = sum(v.size for v in params.values())
    for a in arrays:
        assert a.sharding.spec == P("dp"), a.sharding
        # each leaf is ONE param's padded flat length
        shard_rows = a.addressable_shards[0].data.shape[0]
        assert shard_rows * N_DP == a.shape[0]
    # total sharded moment storage ≈ param count (padded), per moment:
    # structural proof of the ÷ n_dp memory claim
    m_total = sum(a.shape[0] for a in arrays) // 2   # mu and nu
    assert total_param <= m_total <= total_param + len(params) * N_DP


def test_padding_edge_leaf(mesh):
    """A leaf whose size doesn't divide n_dp pads without corrupting
    the update (biases of odd length are the common case)."""
    params = {"w": jnp.arange(10, dtype=jnp.float32)}   # 10 % 4 != 0
    opt = optax.sgd(0.5)
    st = z1.init_state(opt, params, mesh, dp_axis="dp")

    def body(p, s, g):
        gc = z1.scatter_mean_grads(g, "dp", N_DP)
        pc = jax.tree.map(lambda x: z1.chunk_of_rank(x, "dp", N_DP), p)
        up, s = opt.update(gc, s, pc)
        pc = optax.apply_updates(pc, up)
        return z1.gather_params(pc, p, "dp"), s

    st_specs = z1.state_specs(st, "dp")
    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), st_specs, P()),
        out_specs=(P(), st_specs), check_vma=False))
    g = {"w": jnp.ones(10, jnp.float32)}
    p2, _ = fn(params, st, g)
    np.testing.assert_allclose(np.asarray(p2["w"]),
                               np.arange(10) - 0.5, rtol=1e-6)


def test_zero1_rejections(mesh, cfg):
    import dataclasses
    moe = dataclasses.replace(cfg, ffn="gelu", moe_experts=4,
                              moe_capacity=64)
    with pytest.raises(ValueError, match="experts"):
        tfm.make_train_step(moe, mesh, optax.sgd(0.1), zero1=True)


@pytest.mark.heavy
def test_zero1_composes_with_grad_accum(mesh, cfg):
    """zero1 + grad_accum: identical numbers to zero1 alone (the
    microbatch fold feeds the same reduce-scatter)."""
    toks, tgts = _batch(cfg, b=8, l=32, seed=3)
    td = tfm.shard_batch(mesh, toks, tgts)
    params = tfm.init_transformer(jax.random.PRNGKey(4), cfg)
    opt = optax.adam(3e-3)

    outs = {}
    for accum in (1, 2):
        p = jax.tree.map(jnp.copy, params)
        st = z1.init_state(opt, p, mesh)
        step = tfm.make_train_step(cfg, mesh, opt, attn="ring",
                                   zero1=True, grad_accum=accum)
        for _ in range(3):
            p, st, loss = step(p, st, *td)
        outs[accum] = (float(loss), p)
    assert abs(outs[1][0] - outs[2][0]) < 2e-6
    for k in outs[1][1]:
        np.testing.assert_allclose(np.asarray(outs[1][1][k]),
                                   np.asarray(outs[2][1][k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


@pytest.mark.parametrize("accum", [1, 2], ids=["accum1", "accum2"])
def test_trainer_zero1_matches_replicated(mesh, accum):
    """DataParallelTrainer(zero1=True): identical params to the
    replicated trainer after several steps on the digits MLP — the
    flagship workload with sharded Adam; accum=2 exercises the
    microbatch fold inside the zero1 shard_map."""
    from lua_mapreduce_tpu.models.mlp import init_mlp, nll_loss
    from lua_mapreduce_tpu.train.harness import (DataParallelTrainer,
                                                 TrainConfig)

    rng = np.random.RandomState(5)
    x = rng.rand(64, 32).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int32)
    params = init_mlp(jax.random.PRNGKey(6), (32, 16, 10))
    opt = optax.adam(1e-2)

    trs = {}
    for z in (False, True):
        tr = DataParallelTrainer(nll_loss, params, mesh,
                                 TrainConfig(batch_size=64, zero1=z,
                                             grad_accum=accum),
                                 optimizer=opt)
        for _ in range(4):
            tr.step(x, y)
        trs[z] = tr
    for k in trs[False].params:
        np.testing.assert_allclose(np.asarray(trs[True].params[k]),
                                   np.asarray(trs[False].params[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    # the zero1 trainer's moments are genuinely dp-sharded
    mu = [l for l in jax.tree.leaves(trs[True].opt_state)
          if getattr(l, "ndim", 0) >= 1][0]
    assert mu.sharding.spec == P("dp")
