"""The auto-backend policy must follow the committed measurement.

DESIGN §7's doctrine: perf claims live in artifacts, and
``ops._TPU_AUTO_POLICY`` routes each op to whichever side the committed
kernel bench (benchmarks/results/kernels.json) measured faster — never
to a prediction. This test pins the two to each other: for every op
with a measured on-chip speedup entry, the policy must point at the
winner, with a dead band for near-parity (the ≥0.9× flip rule: between
0.9× and 1.0× either side is defensible — XLA keeps fusion-with-
neighbors advantages a standalone bench can't see, so the policy may
hold at "xla" there but must not claim "pallas").

If a re-measure flips a winner, this test fails until the policy (and
its rationale comment) is updated — policy drift against evidence
becomes a red suite, not a stale comment.
"""

import json
import os

import pytest

from lua_mapreduce_tpu import ops

ART = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks", "results", "kernels.json")

# op -> representative measured entries (large/primary shapes; the
# 1024-cube matmul is excluded: both operands fit VMEM and the policy
# rationale documents XLA's fully-resident schedule as structurally
# better there regardless of the big-shape verdict)
_ENTRIES = {
    "flash_attention": ["flash_s2048_h8_d128_causal",
                        "flash_s4096_h8_d128_causal",
                        "flash_grad_s2048_h8_d128_causal"],
    "matmul": ["matmul_4096_bf16", "matmul_8192_bf16"],
    "conv2d": ["conv_lenet_c1_b256", "conv_resnet_56_b64"],
    "softmax": ["log_softmax_8192x32768"],
    "maxpool2d": ["maxpool_b256_64x64x32"],
    "q8_matmul": ["q8_matvec_b8_4096x16384"],
}


def _artifact():
    with open(ART) as f:
        return json.load(f)


@pytest.mark.parametrize("op,entries", sorted(_ENTRIES.items()))
def test_policy_matches_measurement(op, entries):
    art = _artifact()
    if not art.get("on_tpu"):
        pytest.skip("kernels.json is not a TPU artifact")
    speedups = [art[e]["speedup_pallas_vs_xla"] for e in entries
                if e in art and "speedup_pallas_vs_xla" in art.get(e, {})]
    if not speedups:
        pytest.skip(f"no measured entries for {op}")
    policy = ops._TPU_AUTO_POLICY.get(op, "pallas")
    worst = min(speedups)
    best = max(speedups)
    if worst >= 1.0:
        assert policy == "pallas", (
            f"{op}: Pallas measured ≥1.0× on every entry ({speedups}) "
            f"but policy routes to {policy!r}")
    elif best < 0.9:
        assert policy == "xla", (
            f"{op}: Pallas measured <0.9× on every entry ({speedups}) "
            f"but policy routes to {policy!r}")
    # mixed or dead-band results: either side is defensible; the
    # rationale comment in ops/__init__.py carries the argument


def test_flash_block_defaults_match_tuner_artifact():
    """ADVICE r4 (medium): ops/attention.py's default (block_q, block_k)
    schedule is a perf claim, so it must equal the committed sweep's
    winner for every swept shape (benchmarks/results/flash_tune.json) —
    a re-sweep that crowns different blocks turns the suite red until
    the defaults (and their rationale comment) follow the artifact."""
    from lua_mapreduce_tpu.ops import attention

    path = os.path.join(os.path.dirname(ART), "flash_tune.json")
    with open(path) as f:
        tune = json.load(f)
    winners = {tag: tuple(v["best_blocks"]) for tag, v in tune.items()
               if isinstance(v, dict) and "best_blocks" in v}
    assert winners, "flash_tune.json carries no sweep winners"
    default = (attention._DEFAULT_BLOCK_Q, attention._DEFAULT_BLOCK_K)
    for tag, best in sorted(winners.items()):
        assert default == best, (
            f"flash default blocks {default} != flash_tune.json's "
            f"{tag} winner {best}; re-tune or update the defaults")


def test_artifact_is_tpu_measured():
    """The committed artifact must be real-chip evidence — a CPU
    fallback must never silently replace it (kernel_bench refuses at
    runtime; this guards the committed state)."""
    art = _artifact()
    assert art.get("on_tpu") is True
    assert "TPU" in art.get("device_kind", "")
