"""Flash-attention kernel vs the XLA oracle (interpreter mode on CPU —
the kernel-path test discipline of tests/test_ops.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.ops.attention import flash_attention


def _qkv(seed, b=2, l=96, h=3, d=32, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, l, h, d), dtype) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_kernel_matches_oracle(causal):
    q, k, v = _qkv(0)
    want = flash_attention(q, k, v, causal=causal, backend="xla")
    got = flash_attention(q, k, v, causal=causal,
                          backend="pallas_interpret",
                          block_q=32, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ragged_length_padding():
    """L not a multiple of any block size: padded tail must not leak."""
    q, k, v = _qkv(1, l=70)
    want = flash_attention(q, k, v, causal=True, backend="xla")
    got = flash_attention(q, k, v, causal=True,
                          backend="pallas_interpret",
                          block_q=16, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_mixed_dtypes_promoted():
    """bf16 q with f32 k/v must work on BOTH backends (the kernel dots
    run in operand dtype, so promotion happens at the public boundary)."""
    q, k, v = _qkv(5)
    want = flash_attention(q, k, v, causal=True, backend="xla")
    got_x = flash_attention(q.astype(jnp.bfloat16), k, v, causal=True,
                            backend="xla")
    got_p = flash_attention(q.astype(jnp.bfloat16), k, v, causal=True,
                            backend="pallas_interpret")
    for got in (got_x, got_p):
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=0.02, atol=0.02)


def test_bfloat16():
    q, k, v = _qkv(2, dtype=jnp.bfloat16)
    want = flash_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), causal=True,
                           backend="xla")
    got = flash_attention(q, k, v, causal=True,
                          backend="pallas_interpret")
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=0.1, atol=0.05)


def test_gradients_flow():
    q, k, v = _qkv(3, l=32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       backend="pallas_interpret",
                                       block_q=16, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       backend="xla") ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_shape_mismatch_rejected():
    q, k, v = _qkv(4)
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(q, k[:, :64], v)
