"""Flash-attention kernel vs the XLA oracle (interpreter mode on CPU —
the kernel-path test discipline of tests/test_ops.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.ops.attention import flash_attention


def _qkv(seed, b=2, l=96, h=3, d=32, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, l, h, d), dtype) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_kernel_matches_oracle(causal):
    q, k, v = _qkv(0)
    want = flash_attention(q, k, v, causal=causal, backend="xla")
    got = flash_attention(q, k, v, causal=causal,
                          backend="pallas_interpret",
                          block_q=32, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ragged_length_padding():
    """L not a multiple of any block size: padded tail must not leak."""
    q, k, v = _qkv(1, l=70)
    want = flash_attention(q, k, v, causal=True, backend="xla")
    got = flash_attention(q, k, v, causal=True,
                          backend="pallas_interpret",
                          block_q=16, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_mixed_dtypes_promoted():
    """bf16 q with f32 k/v must work on BOTH backends (the kernel dots
    run in operand dtype, so promotion happens at the public boundary)."""
    q, k, v = _qkv(5)
    want = flash_attention(q, k, v, causal=True, backend="xla")
    got_x = flash_attention(q.astype(jnp.bfloat16), k, v, causal=True,
                            backend="xla")
    got_p = flash_attention(q.astype(jnp.bfloat16), k, v, causal=True,
                            backend="pallas_interpret")
    for got in (got_x, got_p):
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=0.02, atol=0.02)


def test_bfloat16():
    q, k, v = _qkv(2, dtype=jnp.bfloat16)
    want = flash_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), causal=True,
                           backend="xla")
    got = flash_attention(q, k, v, causal=True,
                          backend="pallas_interpret")
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=0.1, atol=0.05)


def test_gradients_flow():
    q, k, v = _qkv(3, l=32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       backend="pallas_interpret",
                                       block_q=16, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       backend="xla") ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_shape_mismatch_rejected():
    q, k, v = _qkv(4)
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(q, k[:, :64], v)


class TestFusedBackward:
    """The Pallas backward (FlashAttention-2 shape): dq/dk/dv come from
    two fused kernels re-materializing p from the saved logsumexp —
    never from re-running the XLA composition. Parity with the XLA VJP
    across the geometries that exercise every masking/padding branch."""

    # l=96 runs the single-kv-block path; l=200 forces n_kv=2 (block_k
    # clamps to >=128), exercising the dq kernel's cross-kv-block
    # accumulation and the dkv kernel's per-kv-tile scratch re-init —
    # the geometry real training uses (code-review r3)
    @pytest.mark.parametrize("l", [96, 200], ids=["1kv", "2kv"])
    @pytest.mark.parametrize("causal", [False, True],
                             ids=["full", "causal"])
    def test_grads_match_xla_vjp(self, causal, l):
        q, k, v = _qkv(6, l=l)

        def loss(backend):
            def f(q, k, v):
                out = flash_attention(q, k, v, causal=causal,
                                      backend=backend,
                                      block_q=32, block_k=128)
                # non-uniform cotangent: catches dq/dk/dv mixups a
                # sum() cotangent of ones would let cancel out
                w = jnp.arange(out.size).reshape(out.shape) % 7
                return jnp.sum(out * w.astype(out.dtype))
            return f

        g = jax.grad(loss("pallas_interpret"), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{name}")

    def test_ragged_length_grads(self):
        """Padded tail rows/cols must contribute ZERO gradient."""
        q, k, v = _qkv(7, l=70)

        def loss(backend):
            return lambda q, k, v: jnp.sum(flash_attention(
                q, k, v, causal=True, backend=backend,
                block_q=16, block_k=128) ** 2)

        g = jax.grad(loss("pallas_interpret"), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.heavy
    def test_bf16_grads(self):
        """bf16 operands: backward dots run in bf16 (MXU-native) with
        f32 accumulation — grads close to the f32 XLA VJP."""
        q, k, v = _qkv(8, l=64, dtype=jnp.bfloat16)

        def loss_p(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True,
                backend="pallas_interpret").astype(jnp.float32) ** 2)

        def loss_x(q, k, v):
            return jnp.sum(flash_attention(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32), causal=True, backend="xla") ** 2)

        g = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_x, argnums=(0, 1, 2))(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32))
        for a, b in zip(g, g_ref):
            assert a.dtype == jnp.bfloat16
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b), rtol=0.1, atol=0.1)

    @pytest.mark.parametrize("causal", [False, True],
                             ids=["full", "causal"])
    def test_return_lse_parity_and_grads(self, causal):
        """return_lse=True: out AND lse agree between backends, and
        gradients flow correctly through BOTH outputs (the lse
        cotangent folds into the backward's delta term)."""
        q, k, v = _qkv(10, l=200)

        def loss(backend):
            def f(q, k, v):
                out, lse = flash_attention(q, k, v, causal=causal,
                                           backend=backend,
                                           return_lse=True)
                return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))
            return f

        op, lp = flash_attention(q, k, v, causal=causal,
                                 backend="pallas_interpret",
                                 return_lse=True)
        ox, lx = flash_attention(q, k, v, causal=causal, backend="xla",
                                 return_lse=True)
        assert lp.shape == (q.shape[0], q.shape[1], q.shape[2])
        np.testing.assert_allclose(np.asarray(op), np.asarray(ox),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(lx),
                                   rtol=1e-5, atol=1e-5)
        g = jax.grad(loss("pallas_interpret"), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{name}")

    def test_saved_lse_is_correct(self):
        """The forward's saved logsumexp equals the oracle's row-wise
        logsumexp of the masked scores (the quantity the backward
        trusts to re-materialize p)."""
        from lua_mapreduce_tpu.ops.attention import _flash_pallas
        b, l, h, d = 2, 64, 2, 32
        rng = np.random.RandomState(9)
        q, k, v = (jnp.asarray(rng.randn(b, l, h, d), jnp.float32) * 0.5
                   for _ in range(3))
        _, lse = _flash_pallas(q, k, v, causal=True, interpret=True,
                               with_lse=True)
        s = np.einsum("blhd,bmhd->bhlm", np.asarray(q), np.asarray(k),
                      dtype=np.float64) / np.sqrt(d)
        mask = np.tril(np.ones((l, l), bool))
        s = np.where(mask, s, -np.inf)
        want = np.log(np.sum(np.exp(s), axis=-1))      # (b, h, l)
        got = np.asarray(lse).reshape(b, h, l)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_dma_elision_clamps_are_exact_and_in_range():
    """The dead-tile DMA elision's two safety invariants, exhaustively
    over awkward geometries (incl. the banded-ring far hop where
    q_offset > window + block_k, which once drove _q_clamp's upper
    bound NEGATIVE): (a) a clamped index is always in range — an
    out-of-range block index becomes a wild DMA offset on hardware
    while interpret mode silently wraps; (b) on every LIVE tile the
    clamp is the identity — a clamped live step would silently compute
    on the wrong tile."""
    import numpy as np

    from lua_mapreduce_tpu.ops.attention import (_kv_clamp, _q_clamp,
                                                 _tile_live)

    geoms = [
        # (block_q, block_k, causal, window, q_offset, n_q, n_kv)
        (128, 128, True, 0, 0, 8, 8),
        (64, 128, True, 50, 128, 6, 3),
        (128, 128, True, 50, 512, 4, 4),     # far hop: hi < 0 regression
        (64, 128, True, 1, 0, 8, 4),         # window=1 off-by-one case
        (128, 256, True, 300, 1024, 8, 4),
        (8, 128, True, 17, 40, 5, 2),
    ]
    for bq, bk, causal, window, qo, n_q, n_kv in geoms:
        for qi in range(n_q):
            for ki in range(n_kv):
                kw = dict(block_q=bq, block_k=bk, causal=causal,
                          window=window, q_offset=qo)
                kc = int(_kv_clamp(qi, ki, n_kv=n_kv, **kw))
                qc = int(_q_clamp(qi, ki, n_q=n_q, **kw))
                assert 0 <= kc < n_kv, (kw, qi, ki, kc)
                assert 0 <= qc < n_q, (kw, qi, ki, qc)
                live = _tile_live(qi, ki, bq, bk, causal, window, qo)
                if live is not None and bool(np.asarray(live)):
                    assert kc == ki, ("live tile re-mapped", kw, qi, ki)
                    assert qc == qi, ("live tile re-mapped", kw, qi, ki)
