"""Soak: 100+ training iterations through the engine with worker churn
and a mid-reduce server restart (VERDICT r2 item 7).

The reference's elastic-pool + resume semantics under sustained
iteration (server.lua:470-492 resume matrix, worker.lua:97-103 elastic
join/leave): the digits DP-SGD example loops 100 optimizer steps while
short-lived workers continuously join and leave, the server process
"crashes" mid-reduce around the halfway point and a fresh server resumes
from the task-doc checkpoint. The run must produce the SAME loss
trajectory and final model as an unperturbed single-process run —
fault tolerance must be invisible in the numbers.
"""

import threading

import numpy as np
import pytest

import examples.digits.mr_train as mr
from lua_mapreduce_tpu import MemJobStore, Server, TaskSpec, Worker
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.store.router import get_storage_from
from lua_mapreduce_tpu.train import checkpoint as ckpt

N_ITER = 100
ARGS = {"sizes": (32, 16, 10), "n_shards": 2, "bunch": 16,
        "max_steps": N_ITER, "patience": 10_000, "seed": 0}


def _spec(model_tag, spill_tag):
    return TaskSpec(taskfn="examples.digits.mr_train",
                    mapfn="examples.digits.mr_train",
                    partitionfn="examples.digits.mr_train",
                    reducefn="examples.digits.mr_train",
                    finalfn="examples.digits.mr_train",
                    init_args={**ARGS, "model_store": f"mem:{model_tag}"},
                    storage=f"mem:{spill_tag}")


# the TRUE original, captured at import: _capture_trajectory is called
# twice per test and wrapping the previous wrapper would keep the first
# sink recording through the second run
_ORIG_FINALFN = mr.finalfn


def _capture_trajectory(monkeypatch, sink):
    """Wrap mr_train.finalfn to record (step, tr_loss, val_loss) per
    iteration — the meta file only keeps the last step."""

    def recording(pairs):
        verdict = _ORIG_FINALFN(pairs)
        meta = mr.read_meta(mr._cfg["model_store"])
        sink.append((meta["step"], meta["tr_loss"], meta["val_loss"]))
        return verdict

    monkeypatch.setattr(mr, "finalfn", recording)


def _final_params(model_tag):
    store = get_storage_from(f"mem:{model_tag}")
    return ckpt.load_pytree(store, mr.MODEL_FILE, mr._template())["params"]


@pytest.mark.heavy
def test_soak_100_iterations_churn_and_midreduce_restart(monkeypatch):
    # ---- golden: unperturbed single-process run --------------------------
    gold_traj = []
    _capture_trajectory(monkeypatch, gold_traj)
    LocalExecutor(_spec("soak-gold", "soak-gold-spill"),
                  max_iterations=N_ITER + 2).run()
    gold_params = _final_params("soak-gold")
    assert len(gold_traj) == N_ITER
    assert mr.read_meta("mem:soak-gold")["step"] == N_ITER

    # ---- perturbed: elastic churn + mid-reduce server restart ------------
    soak_traj = []
    _capture_trajectory(monkeypatch, soak_traj)
    store = MemJobStore()
    spec = _spec("soak-run", "soak-run-spill")

    # churn pool: every worker leaves after 25 executed jobs (~2
    # iterations' worth) and is immediately replaced, so membership
    # turns over continuously across the 100 iterations (the
    # reference's join-anytime pool, recycled k8s-pod style)
    stop = threading.Event()
    churned = {"count": 0}

    def pool():
        while not stop.is_set():
            w = Worker(store).configure(max_iter=60, max_sleep=0.02,
                                        max_jobs=25)
            try:
                w.execute()
            except RuntimeError:
                pass
            churned["count"] += 1

    pool_threads = [threading.Thread(target=pool, daemon=True)
                    for _ in range(3)]
    for t in pool_threads:
        t.start()

    # server 1 "crashes" (exception out of loop()) mid-reduce around
    # iteration 50 — the progress callback is the crash point, exactly
    # like the mid-map restart e2e
    class _Crash(Exception):
        pass

    seen_reduce = {"n": 0}

    def crash_mid_soak(phase, frac):
        if phase == "reduce" and frac >= 0.5:
            seen_reduce["n"] += 1
            if seen_reduce["n"] == 50:
                raise _Crash()

    server1 = Server(store, poll_interval=0.01).configure(spec)
    with pytest.raises(_Crash):
        server1.loop(progress=crash_mid_soak)
    crashed_at = len(soak_traj)
    assert crashed_at < N_ITER, "crash happened after the run finished"

    # server 2 resumes from the task-doc checkpoint (no configure():
    # the spec rides the task doc, server.lua:470-492) and finishes
    server2 = Server(store, poll_interval=0.01)
    server2.loop()
    stop.set()
    for t in pool_threads:
        t.join(timeout=30)

    # ---- the soak must be numerically invisible --------------------------
    meta = mr.read_meta("mem:soak-run")
    assert meta["step"] == N_ITER and meta["finished"]
    assert len(soak_traj) == N_ITER, (crashed_at, len(soak_traj))
    assert churned["count"] >= 10, "pool never actually churned"

    # loss trajectory identical to the unperturbed run, step by step
    for (gs, gt, gv), (ss, st, sv) in zip(gold_traj, soak_traj):
        assert gs == ss
        np.testing.assert_allclose(st, gt, rtol=1e-5, atol=1e-7,
                                   err_msg=f"tr_loss diverged at step {gs}")
        np.testing.assert_allclose(sv, gv, rtol=1e-5, atol=1e-7,
                                   err_msg=f"val_loss diverged at step {gs}")
    # and the losses really went somewhere (the soak trained a model)
    assert soak_traj[-1][2] < soak_traj[0][2]

    # final model bit-for-bit-close to the unperturbed run's
    soak_params = _final_params("soak-run")
    for name in gold_params:
        np.testing.assert_allclose(
            np.asarray(soak_params[name]), np.asarray(gold_params[name]),
            rtol=1e-5, atol=1e-7, err_msg=f"param {name} diverged")
