"""Modern-architecture knobs: RoPE + RMSNorm + SwiGLU (the llama_style
preset), composing with GQA and every execution form. Discipline as
everywhere: each sharded/incremental path golden-diffed against the
single-device oracle."""

import os
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from lua_mapreduce_tpu.models import transformer as tfm
from lua_mapreduce_tpu.models.transformer import _rope
from lua_mapreduce_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                     axis_names=("dp", "sp"))


@pytest.fixture(scope="module")
def cfg():
    return tfm.TransformerConfig.llama_style(
        vocab=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=48, max_seq=128)


class TestRopeUnit:
    def test_rotation_preserves_pair_norms(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 3, 16),
                        jnp.float32)
        pos = jnp.arange(8) * 7
        r = _rope(x, pos, 10000.0)
        h = 8
        n0 = np.asarray(x[..., :h] ** 2 + x[..., h:] ** 2)
        n1 = np.asarray(r[..., :h] ** 2 + r[..., h:] ** 2)
        np.testing.assert_allclose(n1, n0, rtol=1e-5, atol=1e-5)

    def test_position_zero_is_identity(self):
        x = jnp.asarray(np.random.RandomState(1).randn(1, 1, 2, 8),
                        jnp.float32)
        r = _rope(x, jnp.zeros((1,), jnp.int32), 10000.0)
        np.testing.assert_allclose(np.asarray(r), np.asarray(x),
                                   rtol=1e-6, atol=1e-6)

    def test_dot_products_depend_on_relative_position(self):
        """<rope(q,m), rope(k,n)> must equal <rope(q,m+s), rope(k,n+s)>
        — the property that makes rope a RELATIVE encoding."""
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 1, 1, 16), jnp.float32)
        k = jnp.asarray(rng.randn(1, 1, 1, 16), jnp.float32)

        def dot(m, n):
            qm = _rope(q, jnp.asarray([m]), 10000.0)
            kn = _rope(k, jnp.asarray([n]), 10000.0)
            return float(jnp.sum(qm * kn))

        assert abs(dot(9, 4) - dot(21, 16)) < 1e-4
        assert abs(dot(9, 4) - dot(9, 5)) > 1e-6  # and DOES move with gap

    def test_odd_head_dim_rejected(self):
        bad = tfm.TransformerConfig(d_model=12, n_heads=4, rope=True)
        with pytest.raises(ValueError, match="even head_dim"):
            tfm.init_transformer(jax.random.PRNGKey(0), bad)


def test_param_set_matches_arch(cfg):
    params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
    assert "pos_emb" not in params          # rope: no position table
    assert "L0_ff3_W" in params             # swiglu up-projection
    assert "L0_ff1_b" not in params         # no biases
    assert "L0_ln1_b" not in params         # rms: scale only
    assert "lnf_b" not in params
    with pytest.raises(ValueError, match="unknown norm"):
        tfm.init_transformer(jax.random.PRNGKey(0),
                             dataclasses.replace(cfg, norm="batch"))
    with pytest.raises(ValueError, match="unknown ffn"):
        tfm.init_transformer(jax.random.PRNGKey(0),
                             dataclasses.replace(cfg, ffn="relu"))


def test_swiglu_and_rms_formulas(cfg):
    """One block's FFN/norm against hand-written formulas."""
    params = tfm.init_transformer(jax.random.PRNGKey(3), cfg)
    x = jnp.asarray(np.random.RandomState(4).randn(2, 5, 32), jnp.float32)
    got = tfm._norm(params, "L0_ln1", x, cfg)
    want = x * (1.0 / np.sqrt(np.mean(np.asarray(x) ** 2, -1,
                                      keepdims=True) + 1e-5)) \
        * np.asarray(params["L0_ln1_g"])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-5)
    out, aux = tfm._ffn(params, "L0", x, cfg, None)
    w1, w3, w2 = (np.asarray(params[f"L0_ff{i}_W"]) for i in (1, 3, 2))
    xx = np.asarray(x)
    g = xx @ w1
    want = ((g / (1 + np.exp(-g))) * (xx @ w3)) @ w2
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                               atol=1e-4)
    assert aux == 0.0


@pytest.mark.parametrize("attn", ["ring", "zigzag", "ulysses"])
def test_sharded_forward_matches_oracle(mesh, cfg, attn):
    params = tfm.init_transformer(jax.random.PRNGKey(5), cfg)
    toks = jnp.asarray(np.random.RandomState(6).randint(0, 64, (4, 64)),
                       jnp.int32)
    want = tfm.transformer_apply(params, toks, cfg=cfg)
    got = tfm.make_sharded_apply(cfg, mesh, attn=attn)(params, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.heavy
def test_train_step_learns_and_remat_parity(mesh, cfg):
    """llama_style training on the mesh: learns the copy task, and
    remat=True gives identical numbers."""
    rng = np.random.RandomState(7)
    b, l = 8, 64
    start = rng.randint(0, 64, (b, 1))
    seq = (start + np.arange(l + 1)) % 64
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    targets = jnp.asarray(seq[:, 1:], jnp.int32)
    params = tfm.init_transformer(jax.random.PRNGKey(8), cfg)
    opt = optax.adam(3e-3)
    td = tfm.shard_batch(mesh, tokens, targets)

    losses = {}
    for remat in (False, True):
        c = dataclasses.replace(cfg, remat=remat)
        step = tfm.make_train_step(c, mesh, opt, attn="zigzag")
        p = jax.tree.map(jnp.copy, params)
        st = opt.init(p)
        first = last = None
        for _ in range(25):
            p, st, loss = step(p, st, *td)
            first = first if first is not None else float(loss)
            last = float(loss)
        losses[remat] = (first, last)
    assert losses[False][1] < 0.7 * losses[False][0], losses
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)


@pytest.mark.heavy
def test_3d_tp_modern_matches_oracle(cfg):
    """rope + rms + swiglu on the 3-D tp mesh (MHA heads — GQA stays
    rejected there): one step's loss equals the 2-D step's."""
    from jax.sharding import Mesh
    mha = dataclasses.replace(cfg, n_kv_heads=0)
    devices = jax.devices("cpu")[:8]
    mesh3 = Mesh(np.array(devices).reshape(2, 2, 2), ("dp", "sp", "mp"))
    mesh2 = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "sp"))
    rng = np.random.RandomState(9)
    seq = rng.randint(0, 64, (4, 33))
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    targets = jnp.asarray(seq[:, 1:], jnp.int32)
    params = tfm.init_transformer(jax.random.PRNGKey(10), mha)
    opt = optax.sgd(0.1)

    step2 = tfm.make_train_step(mha, mesh2, opt, attn="ring")
    p2 = jax.tree.map(jnp.copy, params)
    _, _, loss2 = step2(p2, opt.init(p2), *tfm.shard_batch(mesh2, tokens,
                                                           targets))

    step3 = tfm.make_train_step_3d(mha, mesh3, opt, attn="ring")
    p3 = tfm.shard_params_3d(params, mesh3, mha)
    _, _, loss3 = step3(p3, opt.init(p3), *tfm.shard_batch(mesh3, tokens,
                                                           targets))
    assert abs(float(loss2) - float(loss3)) < 2e-5


@pytest.mark.heavy
def test_pp_modern_runs(cfg):
    """Pipeline stacking handles the swiglu/rms key set (no fixed
    name list): one pp step on the llama-style MHA config."""
    from jax.sharding import Mesh
    mha = dataclasses.replace(cfg, n_kv_heads=0)
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("pp",))
    params = tfm.init_transformer(jax.random.PRNGKey(11), mha)
    stacked = tfm.shard_params_pp(params, mesh, mha)
    # round trip through stack/unstack preserves every key
    rt = tfm.unstack_params_pp(tfm.stack_params_pp(params, mha), mha)
    assert set(rt) == set(params)
    opt = optax.sgd(0.05)
    step = tfm.make_train_step_pp(mha, mesh, opt, n_micro=2)
    rng = np.random.RandomState(12)
    seq = rng.randint(0, 64, (4, 17))
    _, _, loss = step(stacked, opt.init(stacked),
                      jnp.asarray(seq[:, :-1], jnp.int32),
                      jnp.asarray(seq[:, 1:], jnp.int32))
    assert np.isfinite(float(loss))


@pytest.mark.heavy
def test_decode_and_prefill_match_full_forward(mesh, cfg):
    params = tfm.init_transformer(jax.random.PRNGKey(13), cfg)
    prompt = jnp.asarray(np.random.RandomState(14).randint(0, 64, (4, 8)),
                         jnp.int32)
    n_new = 6
    got = tfm.greedy_decode(params, prompt, n_new, cfg=cfg)
    toks = prompt
    for _ in range(n_new):
        logits = tfm.transformer_apply(params, toks, cfg=cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    assert np.array_equal(np.asarray(got), np.asarray(toks))
    pre = tfm.greedy_decode(params, prompt, n_new, cfg=cfg,
                            use_prefill=True)
    assert np.array_equal(np.asarray(pre), np.asarray(got))
    # sharded prefill too — rope positions ride _shard_pos
    shp = tfm.greedy_decode(params, prompt, n_new, cfg=cfg,
                            use_prefill=True, mesh=mesh, attn="ring")
    assert np.array_equal(np.asarray(shp), np.asarray(got))
    # a batch NOT divisible by dp replicates the batch axis instead of
    # failing (inference batches are often smaller than training dp)
    small = tfm.greedy_decode(params, prompt[:1], n_new, cfg=cfg,
                              use_prefill=True, mesh=mesh, attn="ring")
    ref = tfm.greedy_decode(params, prompt[:1], n_new, cfg=cfg)
    assert np.array_equal(np.asarray(small), np.asarray(ref))


def test_flops_accounting_swiglu(cfg):
    gelu = dataclasses.replace(cfg, ffn="gelu", norm="ln", rope=False)
    diff = tfm.flops_per_token(cfg, 16) - tfm.flops_per_token(gelu, 16)
    assert diff == 3.0 * cfg.n_layers * 2.0 * cfg.d_model * cfg.d_ff


@pytest.mark.heavy
def test_char_lm_converges_on_real_text():
    """Convergence, not finiteness (VERDICT r3 item 4): the full modern
    stack (llama-style + zero1 + bf16 f32-master, zigzag sp) trained
    char-level on the repo's own docs must beat a fixed loss target.
    Initial loss is ~ln(64)=4.16; the target proves real learning on
    real text through every lever at once. The committed artifact
    (benchmarks/results/lm_train.json) is the same run at a tighter
    target and bigger budget."""
    import argparse

    from examples.lm.train_lm import run

    args = argparse.Namespace(
        dp=4, sp=2, seq=128, batch=8, steps=100, grad_accum=2,
        attn="zigzag", kv_heads=0, modern=True, window=0, zero1=True,
        bf16=True, ckpt=None, ckpt_every=10, data="repo-docs",
        target_loss=3.0, out_json=None)
    summary = run(args)
    assert summary["reached_target"], summary["losses"]
    assert summary["losses"][0][1] > 3.4     # started near ln(64)


@pytest.mark.heavy
def test_lm_resume_is_exact(tmp_path):
    """Kill-and-resume equals never-stopped (the reference's resume
    matrix applied to the LM family): a 40-step run and a 20-step run
    resumed for the back 20 must produce IDENTICAL logged losses on the
    shared steps — per-step seeded batches + checkpointed
    (params, opt_state, step) leave no divergence anywhere."""
    import argparse

    from examples.lm.train_lm import run

    def mk(steps, resume):
        return argparse.Namespace(
            dp=4, sp=2, seq=64, batch=4, steps=steps, grad_accum=1,
            attn="zigzag", kv_heads=0, modern=False, window=0,
            zero1=False, bf16=False, ckpt=f"shared:{tmp_path}/ck",
            ckpt_every=10, data=None, target_loss=None, out_json=None,
            resume=resume)

    straight = run(mk(40, resume=False))

    import shutil
    shutil.rmtree(tmp_path / "ck")
    first = run(mk(20, resume=False))       # writes ckpt at step 20
    second = run(mk(40, resume=True))       # resumes at 20, runs 21-40

    assert second["resumed_at"] == 20, second
    tail = {s: l for s, l in straight["losses"] if s > 20}
    tail2 = {s: l for s, l in second["losses"] if s > 20}
    # shared cadence steps must agree exactly
    shared = set(tail) & set(tail2)
    assert shared, (straight["losses"], second["losses"])
    for s in sorted(shared):
        assert tail[s] == tail2[s], (s, tail[s], tail2[s])
    # and the front half really trained (sanity that first ran)
    assert first["steps"] == 20


@pytest.mark.heavy
def test_char_lm_validation_tracking():
    """Corpus-mode validation: a held-out tail is evaluated on a fixed
    window set every eval_every steps; best-so-far tracking feeds the
    reference-style early stopping (common.lua:144-202's discipline).
    Learning must show up on the HELD-OUT split, not just train."""
    import argparse

    from examples.lm.train_lm import run

    args = argparse.Namespace(
        dp=4, sp=2, seq=64, batch=8, steps=60, grad_accum=1,
        attn="zigzag", kv_heads=0, modern=True, window=0, zero1=False,
        bf16=False, ckpt=None, ckpt_every=10, data="repo-docs",
        target_loss=None, out_json=None, resume=False,
        val_frac=0.1, eval_every=15, patience=0)
    s = run(args)
    assert len(s["val_losses"]) == 4, s["val_losses"]
    first_val = s["val_losses"][0][1]
    assert s["best_val"] is not None and s["best_val"] < first_val
    assert s["best_step"] >= 15 and s["stopped_early"] is False


def test_device_trace_writes_profile(tmp_path):
    """utils/profiling.device_trace captures a jit region into a
    TensorBoard-readable trace directory."""
    import jax
    import jax.numpy as jnp

    from lua_mapreduce_tpu.utils.profiling import annotate, device_trace

    d = str(tmp_path / "trace")
    with device_trace(d):
        with annotate("tiny-matmul"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    files = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert files, "no trace output written"
    assert any("trace" in f or f.endswith(".pb") or ".xplane." in f
               for f in files), files


@pytest.mark.heavy
def test_lm_sigkill_mid_training_resumes(tmp_path):
    """Chaos e2e for the LM family: SIGKILL the training process after
    an observed checkpoint (no cleanup runs — the async writer dies
    with it), then --resume completes the budget from the atomic
    snapshot. The store's tmp+rename publish guarantees the reader
    never sees a torn checkpoint, whatever instant the KILL landed."""
    import signal
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_NUM_CPU_DEVICES")}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    prog = [sys.executable, "-m", "examples.lm.train_lm"]
    ck = ["--ckpt", f"shared:{tmp_path}/ck"]

    p = subprocess.Popen(prog + ["--steps", "500", "--ckpt-every", "5"]
                         + ck, cwd=repo, env=env,
                         stdout=subprocess.PIPE, text=True)
    try:
        # async semantics: 'checkpoint @' prints at SUBMIT; durability
        # of submit N is proven by submit N+1 (one write in flight at
        # most). Kill after the SECOND line → checkpoint #1 is on disk.
        # A watchdog kills a hung/drifted child so readline can't block
        # the suite forever (the wedged-tunnel hang test_cli documents).
        import threading
        watchdog = threading.Timer(240, p.kill)
        watchdog.daemon = True
        watchdog.start()
        seen = 0
        for line in p.stdout:
            if "checkpoint @" in line:
                seen += 1
                if seen == 2:
                    break
        assert seen == 2, "never observed two checkpoints (hung child?)"
        p.send_signal(signal.SIGKILL)
    finally:
        watchdog.cancel()
        if p.poll() is None:
            p.kill()
        p.wait(timeout=30)

    r = subprocess.run(prog + ["--steps", "30", "--ckpt-every", "10",
                               "--resume"] + ck,
                       cwd=repo, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout[-500:] + r.stderr[-500:]
    assert "resumed from checkpoint at step" in r.stdout, r.stdout[-400:]
    assert "done: final loss" in r.stdout, r.stdout[-400:]
