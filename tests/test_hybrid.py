"""Hybrid stage-granular lowering tests (engine/hybrid.py, DESIGN §28).

The third engine rung, golden-diffed against the pure store plane on
both executors:

- byte-identical output for integer workloads with BOTH legs compiled
  (forced ``engine="hybrid"``) and under the ``engine="auto"`` ladder
  (whole-task in-graph still wins; partially-numeric tasks take the
  hybrid rung; fully host-bound tasks stay store),
- the reduce fold's structural proof gating: a literal-seeded
  ``sum(values)`` reducer is NOT folded (python ``sum`` starts from the
  literal 0 — provably different jaxpr), the explicit accumulator loop
  IS, and an unproven fold changes speed, never bytes,
- the never-crash contract: forced hybrid with zero qualifying legs
  runs pure store-plane with counted/logged/traced evidence; a
  trace-time map failure retires the leg and replays interpreted,
- the Server/Worker plane: the per-stage split is negotiated on the
  task doc, sticky on resume (doc wins over a recompute), and the
  workers run exactly the negotiated legs,
- the decision chain: ``lowering`` + per-stage ``lowering.<stage>``
  spans, ``hybrid.run`` / ``hybrid.fallback``, and the per-iteration
  engine map reporting ``hybrid``.
"""

import threading

import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.hybrid import HybridReduceFold
from lua_mapreduce_tpu.engine.ingraph import select_engine
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.engine.server import Server
from lua_mapreduce_tpu.engine.worker import Worker
from lua_mapreduce_tpu.store.router import get_storage_from

from tests.test_ingraph import _result_bytes, igmod  # noqa: F401

# ---------------------------------------------------------------------------
# fixture task sources
# ---------------------------------------------------------------------------

# every data-plane function in-graph: integer values, uniform two-key
# emission (the shard_map tier shape), explicit-accumulator sum reducer
# (the provable fold shape) — forced hybrid must be byte-identical
HY_FULL = """
import jax.numpy as jnp

def taskfn(emit):
    for j in range(6):
        emit(j, {"v": [(j * 7 + i) % 23 for i in range(8)]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["v"], jnp.int32)
    emit(0, jnp.sum(v))
    emit(1, v[0] * 2)

def partitionfn(key):
    return int(key) % 2

def reducefn(key, values):
    acc = values[0]
    for i in range(1, len(values)):
        acc = acc + values[i]
    return acc

reducefn.associative_reducer = True
reducefn.commutative_reducer = True

combinerfn = reducefn
"""

# the hybrid-rung shape: numeric map+reduce, but partitionfn routes
# through hashlib (an "indirect call" — store-plane verdict), so the
# WHOLE task can never lower and engine=auto must take the stage rung
HY_PARTIAL = """
import hashlib
import jax.numpy as jnp

def taskfn(emit):
    for j in range(6):
        emit(j, {"v": [(j * 5 + i) % 17 for i in range(8)]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["v"], jnp.int32)
    emit(0, jnp.sum(v))
    emit(1, v[0] + 10)

def partitionfn(key):
    h = hashlib.blake2b(str(key).encode(), digest_size=2).hexdigest()
    return int(h, 16) % 2

def reducefn(key, values):
    acc = values[0]
    for i in range(1, len(values)):
        acc = acc + values[i]
    return acc

reducefn.associative_reducer = True
reducefn.commutative_reducer = True
"""

# fully host-bound: every stage store-plane — forced hybrid has ZERO
# qualifying legs and must still run (pure store) with evidence
HY_HOSTBOUND = """
def taskfn(emit):
    for j in range(4):
        emit(j, {"v": [j + i for i in range(4)]})

def mapfn(key, value, emit):
    emit(0, sorted(value["v"])[0])
    emit(1, sorted(value["v"])[-1])

def partitionfn(key):
    return int(key) % 2

def reducefn(key, values):
    return sorted(values)[0]
"""

# oracle-accepted map leg whose lowering fails at trace time: the
# emitted KEY is a traced value (the same refusal as the whole-task
# plane) — forced hybrid must degrade the leg, never crash
HY_TRACE_FAIL = """
import jax.numpy as jnp

def taskfn(emit):
    for j in range(4):
        emit(j, {"v": [float(j + 1), 2.0]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["v"], jnp.float32)
    emit(jnp.sum(v), v[0])

def partitionfn(key):
    return int(key) % 2

def reducefn(key, values):
    acc = values[0]
    for i in range(1, len(values)):
        acc = acc + values[i]
    return acc
"""


def _local(mod, engine, tag, **kw):
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    combinerfn=mod if kw.pop("combiner", False) else None,
                    storage=f"mem:hy-{tag}")
    ex = LocalExecutor(spec, engine=engine, **kw)
    ex.run()
    return ex


# ---------------------------------------------------------------------------
# golden diffs + ladder, LocalExecutor
# ---------------------------------------------------------------------------

def test_forced_hybrid_both_legs_byte_identical(igmod):  # noqa: F811
    mod = igmod("hy_full", HY_FULL)
    ex_s = _local(mod, "store", "full-s", combiner=True)
    ex_h = _local(mod, "hybrid", "full-h", combiner=True)
    assert ex_h.engine_decision.chosen == "hybrid"
    assert ex_h.engine_decision.stages == {"map": True, "reduce": True}
    out = _result_bytes(ex_h.result_store)
    assert out and out == _result_bytes(ex_s.result_store)
    it = ex_h.stats.iterations[-1]
    assert it.hybrid_map_legs == 1
    assert it.hybrid_reduce_legs >= 1
    assert it.hybrid_fallbacks == 0
    # uniform numeric-keyed jobs ride the batched shard_map tier, once
    assert ex_h._hybrid.map_engine.mode == "shard_map"
    assert ex_h._hybrid.map_engine.traces == 1
    assert ex_h._hybrid.fold.folded_groups >= 1
    # the store twin never touched the hybrid plane
    it_s = ex_s.stats.iterations[-1]
    assert it_s.hybrid_map_legs == 0 and it_s.hybrid_reduce_legs == 0


def test_auto_ladder_ingraph_still_wins(igmod):  # noqa: F811
    """A fully in-graph task under auto keeps the WHOLE-task plane —
    the hybrid rung only catches tasks the top rung rejects."""
    mod = igmod("hy_full_auto", HY_FULL)
    ex = _local(mod, "auto", "full-auto", combiner=True)
    assert ex.engine_decision.chosen == "ingraph"
    assert ex.stats.iterations[-1].hybrid_map_legs == 0


def test_auto_partial_task_takes_hybrid_rung(igmod):  # noqa: F811
    mod = igmod("hy_partial", HY_PARTIAL)
    ex_s = _local(mod, "store", "part-s")
    ex_h = _local(mod, "auto", "part-h")
    dec = ex_h.engine_decision
    assert dec.verdict == "store-plane"
    assert dec.chosen == "hybrid"
    assert dec.stages == {"map": True, "reduce": True}
    assert "partitionfn" in dec.reason
    assert _result_bytes(ex_h.result_store) == _result_bytes(ex_s.result_store)
    it = ex_h.stats.iterations[-1]
    assert it.hybrid_map_legs == 1 and it.hybrid_fallbacks == 0


def test_auto_hostbound_task_stays_store(igmod):  # noqa: F811
    mod = igmod("hy_hostbound_auto", HY_HOSTBOUND)
    ex = _local(mod, "auto", "host-auto")
    assert ex.engine_decision.chosen == "store"
    assert ex.engine_decision.stages is None
    it = ex.stats.iterations[-1]
    assert it.hybrid_map_legs == 0 and it.hybrid_fallbacks == 0
    assert len(_result_bytes(ex.result_store)) > 0


def test_forced_hybrid_zero_legs_never_crashes(igmod):  # noqa: F811
    """engine=hybrid on a fully host-bound task: pure store-plane run,
    normal output, and the once-per-task degrade evidence (counter)."""
    mod = igmod("hy_hostbound_forced", HY_HOSTBOUND)
    ex_s = _local(mod, "store", "host-s")
    ex_h = _local(mod, "hybrid", "host-h")
    assert ex_h.engine_decision.chosen == "hybrid"
    assert ex_h.engine_decision.stages == {"map": False, "reduce": False}
    assert _result_bytes(ex_h.result_store) == _result_bytes(ex_s.result_store)
    it = ex_h.stats.iterations[-1]
    assert it.hybrid_fallbacks == 1
    assert it.hybrid_map_legs == 0 and it.hybrid_reduce_legs == 0


def test_trace_failure_degrades_map_leg(igmod):  # noqa: F811
    mod = igmod("hy_trace_fail", HY_TRACE_FAIL)
    ex_s = _local(mod, "store", "tf-s")
    ex_h = _local(mod, "hybrid", "tf-h")
    assert ex_h.engine_decision.chosen == "hybrid"
    # the map leg died at trace time: retired, counted, replayed
    # interpreted — bytes still equal
    assert ex_h._hybrid.map_engine is None
    it = ex_h.stats.iterations[-1]
    assert it.hybrid_fallbacks >= 1 and it.hybrid_map_legs == 0
    assert _result_bytes(ex_h.result_store) == _result_bytes(ex_s.result_store)


# ---------------------------------------------------------------------------
# reduce fold proof gating (unit)
# ---------------------------------------------------------------------------

def _fold_spec(reducefn):
    # the fold's first witness is the declared algebra; these unit
    # tests exercise the second (the structural jaxpr proof)
    reducefn.associative_reducer = True
    reducefn.commutative_reducer = True
    return TaskSpec(taskfn={"taskfn": lambda e: e(0, 1)},
                    mapfn={"mapfn": lambda k, v, e: e(0, v)},
                    partitionfn={"partitionfn": lambda k: 0},
                    reducefn={"reducefn": reducefn},
                    storage="mem:hy-foldunit")


def test_fold_rejects_literal_seeded_sum():
    """python sum(values) folds from the LITERAL 0 — a jaxpr the sum
    proof must refuse (an add with a literal operand), so the group
    interprets; the fold stays live for provable groups."""
    fold = HybridReduceFold(_fold_spec(lambda k, vs: sum(vs)))
    assert fold(0, [1, 2, 3]) is None
    assert not fold.retired
    assert fold.folded_groups == 0 and not fold.take_used()


def test_fold_accepts_accumulator_loop_and_restores_bytes():
    def reducefn(k, vs):
        acc = vs[0]
        for i in range(1, len(vs)):
            acc = acc + vs[i]
        return acc

    fold = HybridReduceFold(_fold_spec(reducefn))
    assert fold(0, [3, 4, 5]) == 12
    assert fold.folded_groups == 1
    assert fold.take_used() and not fold.take_used()
    # singletons never fold (run_reduce_job's fast path owns them)
    assert fold(0, [7]) is None
    # non-numeric groups interpret without retiring the fold
    assert fold(1, ["a", "b"]) is None and not fold.retired


def test_fold_retires_on_proof_cache_blowup():
    def reducefn(k, vs):
        acc = vs[0]
        for i in range(1, len(vs)):
            acc = acc + vs[i]
        return acc

    fold = HybridReduceFold(_fold_spec(reducefn))
    fold.MAX_PROBES = 4
    for key in range(6):
        fold(key, [key, key + 1])
    assert fold.retired
    assert "signatures" in fold.retire_reason
    # retired: every later group interprets, silently and safely
    assert fold(99, [1, 2]) is None


# ---------------------------------------------------------------------------
# Server/Worker plane: doc negotiation, sticky resume, golden diff
# ---------------------------------------------------------------------------

def _fleet(spec, engine=None, n_workers=2, store=None):
    store = store or MemJobStore()
    server = Server(store, poll_interval=0.02,
                    engine=engine).configure(spec)
    workers = [Worker(store).configure(max_iter=400, max_sleep=0.05)
               for _ in range(n_workers)]
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    stats = server.loop()
    for t in threads:
        t.join(timeout=30)
    return server, stats, store


def _srv_spec(mod, tag):
    return TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    storage=f"mem:hysrv-{tag}")


def test_server_hybrid_negotiates_doc_and_matches_store(igmod):  # noqa: F811
    mod = igmod("hy_partial_srv", HY_PARTIAL)
    _, stats_h, store_h = _fleet(_srv_spec(mod, "h"), engine="auto")
    _, stats_s, _ = _fleet(_srv_spec(mod, "s"), engine="store")
    assert _result_bytes(get_storage_from("mem:hysrv-h")) == \
        _result_bytes(get_storage_from("mem:hysrv-s"))
    task = store_h.get_task()
    assert task["hybrid_stages"] == {"map": True, "reduce": True}
    it = stats_h.iterations[-1]
    assert it.hybrid_map_legs >= 1 and it.hybrid_reduce_legs >= 1
    assert it.hybrid_fallbacks == 0
    # the store fleet negotiated NO split
    assert stats_s.iterations[-1].hybrid_map_legs == 0


def test_server_resume_keeps_doc_stage_split(igmod):  # noqa: F811
    """Resume stickiness: a doc whose negotiated split disables the map
    leg wins over the oracle's fresh recompute — the fleet keeps
    running exactly the legs the crashed run's workers were running."""
    mod = igmod("hy_partial_resume", HY_PARTIAL)
    spec = _srv_spec(mod, "resume")
    # the oracle would say {"map": True, "reduce": True}...
    assert select_engine(spec, "auto").stages == \
        {"map": True, "reduce": True}
    store = MemJobStore()
    from lua_mapreduce_tpu.core.constants import TaskStatus
    store.put_task({"_id": "unique", "status": TaskStatus.WAIT.value,
                    "iteration": 1, "spec": spec.describe(),
                    "engine": "auto",
                    "hybrid_stages": {"map": False, "reduce": True}})
    _, stats, store = _fleet(spec, engine="auto", store=store)
    task = store.get_task()
    assert task["hybrid_stages"] == {"map": False, "reduce": True}
    it = stats.iterations[-1]
    # workers honored the doc: no compiled map, the reduce fold ran
    assert it.hybrid_map_legs == 0
    assert it.hybrid_reduce_legs >= 1
    ex_s = _local(mod, "store", "resume-twin")
    assert _result_bytes(get_storage_from("mem:hysrv-resume")) == \
        _result_bytes(ex_s.result_store)


# ---------------------------------------------------------------------------
# observability: decision chain, spans, counter schema, CLI knobs
# ---------------------------------------------------------------------------

def test_lowering_stage_spans_and_engine_report(igmod):  # noqa: F811
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    from lua_mapreduce_tpu.trace.span import Tracer, install_tracer

    mod = igmod("hy_partial_span", HY_PARTIAL)
    install_tracer(Tracer())
    try:
        _local(mod, "auto", "span-h")
    finally:
        install_tracer(None)
    col = TraceCollection.from_store(get_storage_from("mem:hy-span-h"))
    decs = col.lowering_decisions()
    chain = [d["span"] for d in decs]
    assert chain[:3] == ["lowering", "lowering.map", "lowering.reduce"]
    assert decs[0]["engine"] == "hybrid"
    assert decs[0]["verdict"] == "store-plane"
    stage_map = decs[chain.index("lowering.map")]
    assert stage_map["stage"] == "map"
    assert stage_map["engine"] == "hybrid"
    assert stage_map["compiled"] == "true"
    assert "fn.mapfn" in stage_map
    assert any(s["name"] == "hybrid.run" for s in col.spans)
    assert col.engines_by_iteration() == {1: "hybrid"}


def test_fallback_span_carries_stage(igmod):  # noqa: F811
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    from lua_mapreduce_tpu.trace.span import Tracer, install_tracer

    mod = igmod("hy_trace_fail_span", HY_TRACE_FAIL)
    install_tracer(Tracer())
    try:
        _local(mod, "hybrid", "span-fb")
    finally:
        install_tracer(None)
    col = TraceCollection.from_store(get_storage_from("mem:hy-span-fb"))
    decs = col.lowering_decisions()
    fbs = [d for d in decs if d["span"] == "hybrid.fallback"]
    assert fbs and fbs[0]["stage"] == "map"
    assert col.engines_by_iteration() == {1: "store"}


def test_counter_schema():
    from lua_mapreduce_tpu.utils.stats import COUNTER_FOLD, IterationStats
    for c in ("hybrid_map_legs", "hybrid_reduce_legs", "hybrid_fallbacks"):
        assert c in COUNTER_FOLD
    d = IterationStats(iteration=1).as_dict()
    assert d["hybrid_map_legs"] == 0
    assert d["hybrid_reduce_legs"] == 0
    assert d["hybrid_fallbacks"] == 0


def test_cli_hybrid_engine_choice():
    from lua_mapreduce_tpu.cli.execute_server import \
        build_parser as server_parser
    from lua_mapreduce_tpu.cli.execute_worker import \
        build_parser as worker_parser
    args = server_parser().parse_args(
        ["mem", "t", "m", "p", "r", "--engine", "hybrid"])
    assert args.engine == "hybrid"
    assert worker_parser().parse_args(
        ["mem", "--engine", "hybrid"]).engine == "hybrid"


def test_resolve_engine_accepts_hybrid(monkeypatch):
    from lua_mapreduce_tpu.engine.ingraph import resolve_engine
    assert resolve_engine("hybrid") == "hybrid"
    monkeypatch.setenv("LMR_ENGINE", "hybrid")
    assert resolve_engine(None) == "hybrid"
    with pytest.raises(ValueError):
        resolve_engine("stagewise")
