"""Chaos suite (DESIGN §19): the wordcount matrix under seeded
FaultPlans.

Each leg runs the same wordcount task twice — fault-free, then under a
deterministic FaultPlan injecting transient errors + latency +
error-after-write (and torn writes on the heavier legs) — across
{mem, shared, object} storage × {barrier, pipelined} shuffle × both
executors (LocalExecutor and the distributed Server + in-process
Worker pool), and asserts:

1. byte-identical outputs: the injected faults are invisible in the
   results;
2. ZERO repetition bumps attributable to injected transient faults
   (the distributed legs check every job's repetitions == 0 — the
   tentpole's release-not-broken contract);
3. the plan actually fired (a chaos run that injected nothing proves
   nothing).

The smoke legs (`-k smoke`) are the test.sh chaos gate: one seeded
plan per backend, fast. The full matrix is the tier-1 chaos suite.
"""

import threading
from typing import Dict

import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.core.constants import Status
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor, iter_results
from lua_mapreduce_tpu.engine.server import Server
from lua_mapreduce_tpu.engine.worker import MAP_NS, PRE_NS, RED_NS, Worker
from lua_mapreduce_tpu.faults import FaultPlan, install_fault_plan
from lua_mapreduce_tpu.store.router import get_storage_from

CORPUS = {
    f"doc{i}": " ".join(f"w{(i * 7 + j) % 23}" for j in range(40))
    for i in range(8)
}
GOLDEN: Dict[str, int] = {}
for _text in CORPUS.values():
    for _w in _text.split():
        GOLDEN[_w] = GOLDEN.get(_w, 0) + 1

_MOD = "tests._chaos_wc"


def _install_module():
    """The wordcount program as an importable module (the distributed
    engine round-trips specs through module paths)."""
    import sys
    import types

    mod = sys.modules.get(_MOD)
    if mod is None:
        mod = types.ModuleType(_MOD)

        def taskfn(emit):
            for k, v in sorted(CORPUS.items()):
                emit(k, v)

        def mapfn(key, value, emit):
            for w in value.split():
                emit(w, 1)

        mod.taskfn = taskfn
        mod.mapfn = mapfn
        mod.partitionfn = lambda key: sum(key.encode()) % 4
        mod.reducefn = lambda key, values: sum(values)
        sys.modules[_MOD] = mod
    return mod


def _storage(tmp_path, backend, tag):
    return {"mem": f"mem:{tag}",
            "shared": f"shared:{tmp_path}/shared-{tag}",
            "object": f"object:{tmp_path}/object-{tag}"}[backend]


def _result_bytes(storage_spec, ns="result", only_results=False):
    """The result namespace's exact bytes, partition by partition — the
    byte-compare oracle. ``only_results`` narrows to the final
    ``<ns>.P<d>`` files: replica-kill legs legitimately leave behind
    consumed runs whose copies sit on a destroyed target (their
    best-effort remove is swallowed, like any dead backend's)."""
    import re
    store = get_storage_from(storage_spec)
    keep = re.compile(rf"^{re.escape(ns)}\.P\d+$")
    out = {}
    for name in store.list(f"{ns}.P*"):
        if only_results and not keep.match(name):
            continue
        out[name] = "".join(store.lines(name))
    return out


def _plan(seed, heavy=False):
    """The acceptance-criteria mix: transient + latency +
    error-after-write (+ torn on heavy legs); latency_ms kept tiny so
    the suite stays fast. max_per_key=2 < the default retry budget of
    3, so every injected burst is absorbable — zero repetition bumps is
    therefore a hard assertion, not a hope."""
    return FaultPlan(seed, transient=0.08, latency=0.05,
                     error_after_write=0.3,
                     torn=0.2 if heavy else 0.0,
                     latency_ms=1.0, max_per_key=2)


def _run_local(tmp_path, backend, pipeline, tag, plan=None, replication=1,
               push=False, push_budget_mb=None, coding=None):
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, backend, tag))
    install_fault_plan(plan)
    try:
        ex = LocalExecutor(spec, map_parallelism=3, pipeline=pipeline,
                           premerge_min_runs=2,
                           segment_format="v2" if pipeline else "v1",
                           replication=replication, coding=coding,
                           push=push, push_budget_mb=push_budget_mb)
        stats = ex.run()
    finally:
        install_fault_plan(None)
    got = {k: v[0] for k, v in ex.results()}
    assert got == GOLDEN
    return _result_bytes(spec.storage,
                         only_results=replication > 1 or push
                         or coding is not None), stats


def _run_distributed(tmp_path, backend, pipeline, tag, plan=None,
                     n_workers=2, replication=1, speculation=0.0,
                     straggler=False, batch_k=2, push=False, coding=None):
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, backend, tag))
    store = MemJobStore()
    install_fault_plan(plan)
    try:
        server = Server(store, poll_interval=0.01, pipeline=pipeline,
                        premerge_min_runs=2, batch_k=batch_k,
                        segment_format="v2" if pipeline else "v1",
                        replication=replication, coding=coding,
                        speculation=speculation, push=push).configure(spec)
        # ``straggler`` names the LAST worker "straggler-0" (the slow
        # FaultPlan kind routes by worker name) and gives it a head
        # start so it deterministically holds at least one lease
        names = [f"healthy-{i}" for i in range(n_workers - 1)] \
            + ["straggler-0"] if straggler else [None] * n_workers
        workers = [Worker(store, name=names[i]).configure(max_iter=800,
                                                          max_sleep=0.02)
                   for i in range(n_workers)]
        threads = [threading.Thread(target=w.execute, daemon=True)
                   for w in workers]
        if straggler:
            # server in the background; the straggler alone gets first
            # claim so it deterministically holds a lease before any
            # healthy worker (or clone) exists
            final = {}
            st = threading.Thread(
                target=lambda: final.setdefault("stats", server.loop()),
                daemon=True)
            st.start()
            threads[-1].start()
            _wait_for_claim(store)
            for t in threads[:-1]:
                t.start()
            st.join(timeout=120)
            assert not st.is_alive(), "server wedged under the straggler"
            stats = final["stats"]
        else:
            for t in threads:
                t.start()
            stats = server.loop()
        for t in threads:
            t.join(timeout=30)
    finally:
        install_fault_plan(None)

    # the release-not-broken contract: NO repetition bump from any
    # injected transient fault, in any namespace
    for ns in (MAP_NS, PRE_NS, RED_NS):
        for d in store.jobs(ns):
            assert d["repetitions"] == 0, \
                (f"injected transient faults bumped repetitions: "
                 f"{ns} job {d['_id']} -> {d['repetitions']}")
        counts = store.counts(ns)
        assert counts[Status.FAILED] == 0
    got = {k: v[0]
           for k, v in iter_results(get_storage_from(spec.storage),
                                    "result")}
    assert got == GOLDEN
    # speculation legs narrow to final result files: a disowned
    # straggler finishing after the winner's reduce consumed the runs
    # legitimately leaves identical-bytes run files behind (its commit
    # lands nowhere), exactly like replica-kill legs leave dead copies
    return _result_bytes(spec.storage,
                         only_results=replication > 1 or speculation > 0
                         or push or coding is not None), stats


def _wait_for_claim(store, timeout=30.0):
    """Block until some worker holds a RUNNING lease (the straggler's
    head start)."""
    import time as _t
    deadline = _t.time() + timeout
    while _t.time() < deadline:
        try:
            if store.counts(MAP_NS)[Status.RUNNING] > 0:
                return
        except Exception:
            pass
        _t.sleep(0.005)
    raise AssertionError("straggler never claimed a lease")


# --- smoke legs: the test.sh chaos gate (one seeded plan per backend) -------

@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_chaos_smoke_backend(tmp_path, backend):
    clean, _ = _run_local(tmp_path, backend, False, f"smoke-{backend}-c")
    plan = _plan(seed=100 + len(backend))
    chaotic, stats = _run_local(tmp_path, backend, False,
                                f"smoke-{backend}-f", plan=plan)
    assert chaotic == clean, "fault leg output differs from fault-free"
    assert plan.total_fired() > 0, "plan injected nothing — seed too weak"
    assert stats.iterations[-1].store_faults > 0


# --- the full matrix ---------------------------------------------------------

@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_chaos_local_matrix(tmp_path, backend, pipeline):
    tag = f"loc-{backend}-{int(pipeline)}"
    clean, _ = _run_local(tmp_path, backend, pipeline, tag + "-c")
    plan = _plan(seed=7)
    chaotic, _ = _run_local(tmp_path, backend, pipeline, tag + "-f",
                            plan=plan)
    assert chaotic == clean
    assert plan.total_fired() > 0


@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_chaos_distributed_matrix(tmp_path, backend, pipeline):
    tag = f"dist-{backend}-{int(pipeline)}"
    clean, _ = _run_distributed(tmp_path, backend, pipeline, tag + "-c")
    plan = _plan(seed=13, heavy=True)
    chaotic, stats = _run_distributed(tmp_path, backend, pipeline,
                                      tag + "-f", plan=plan)
    assert chaotic == clean
    assert plan.total_fired() > 0
    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0


def test_chaos_rpc_faults_on_coord_plane(tmp_path):
    """Transient faults injected on the JOBSTORE RPCs (claims, commits,
    heartbeats) — the control-plane half of the tentpole — are absorbed
    with identical results and zero repetition bumps."""
    tag = "rpc-leg"
    clean, _ = _run_distributed(tmp_path, "mem", False, tag + "-c")
    plan = FaultPlan(17, rpc_transient=0.1, max_per_key=2)
    chaotic, _ = _run_distributed(tmp_path, "mem", False, tag + "-f",
                                  plan=plan)
    assert chaotic == clean
    assert plan.fired.get("rpc_transient", 0) > 0


# --- replica-aware shuffle legs (DESIGN §20) ---------------------------------
#
# The ISSUE 6 acceptance gate: a FaultPlan destroys r-1 replicas of
# every partition's shuffle data mid-run (permanent read faults on the
# PRIMARY copies — placement routes each file's r copies onto distinct
# targets, and the primary names are exactly what the [0-9] character
# classes match; replica copies are ~k.tag~-prefixed and stay lit).
# Output must be byte-identical to the fault-free twin with ZERO
# map-job repetition bumps and ZERO map re-runs: pure failover reads.

def _kill_primaries_plan(seed):
    """Every read of every primary run/spill copy fails permanently —
    'r-1 of r replicas destroyed' for r=2 (the char classes never match
    a ~-prefixed replica copy, nor a list() pattern argument)."""
    return FaultPlan(seed, permanent=1.0,
                     pattern="result.P[0-9]*.M*|result.P[0-9]*.SPILL-*",
                     max_per_key=100_000, latency_ms=0)


def test_replication_smoke_failover(tmp_path):
    """The test.sh replication chaos gate: one fast leg — primaries
    destroyed, replicas serve, zero re-runs, byte-identical output."""
    clean, _ = _run_local(tmp_path, "mem", False, "rep-smoke-c")
    plan = _kill_primaries_plan(61)
    chaotic, stats = _run_local(tmp_path, "mem", False, "rep-smoke-f",
                                plan=plan, replication=2)
    assert chaotic == clean
    assert plan.total_fired() > 0
    it = stats.iterations[-1]
    assert it.failover_reads > 0
    assert it.map_reruns_avoided > 0
    assert it.map_reruns == 0


@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_replication_chaos_distributed_matrix(tmp_path, backend, pipeline):
    """The full acceptance matrix, on the distributed engine: r-1
    replica kill across {mem,shared,object} × {barrier,pipelined} —
    byte-identical to the fault-free twin, zero repetition bumps
    (asserted per job inside _run_distributed), zero map re-runs."""
    tag = f"rep-{backend}-{int(pipeline)}"
    clean, _ = _run_distributed(tmp_path, backend, pipeline, tag + "-c")
    plan = _kill_primaries_plan(67)
    chaotic, stats = _run_distributed(tmp_path, backend, pipeline,
                                      tag + "-f", plan=plan, replication=2)
    assert chaotic == clean, "failover leg output differs from fault-free"
    assert plan.total_fired() > 0
    it = stats.iterations[-1]
    assert it.failover_reads > 0, "plan never forced a failover read"
    assert it.map_reruns_avoided > 0
    assert it.map_reruns == 0, "replication failed to absorb the kills"


def test_replication_chaos_blackout(tmp_path):
    """The blackout kind end-to-end: ONE placement tag dark for the
    whole run (every data-plane op on it fails transient, uncapped) —
    the whole-failure-domain shape. r=2 puts every file's second copy
    on a different tag, so the run completes with identical bytes and
    zero re-runs."""
    from lua_mapreduce_tpu.engine.placement import replica_pattern

    clean, _ = _run_local(tmp_path, "mem", True, "rep-bo-c")
    # scope the blackout to the shuffle plane — primaries AND the
    # replica copies routed onto the dark tag; result-file housekeeping
    # (which no replica protects) stays lit, as in a real deployment
    # where results land on a separate durable target
    shuffle = ["result.P[0-9]*.M*", "result.P[0-9]*.SPILL-*"]
    plan = FaultPlan(71, blackout_tag=3, blackout_s=3600.0,
                     pattern="|".join(shuffle
                                      + [replica_pattern(p)
                                         for p in shuffle]),
                     latency_ms=0)
    chaotic, stats = _run_local(tmp_path, "mem", True, "rep-bo-f",
                                plan=plan, replication=2)
    assert chaotic == clean
    assert plan.fired.get("blackout", 0) > 0, "the dark tag was never hit"
    it = stats.iterations[-1]
    assert it.map_reruns == 0


def test_replication_total_loss_falls_back_to_map_rerun(tmp_path):
    """The LAST rung of the ladder: every copy of one partition's runs
    destroyed (not just r-1) — the scavenger requeues the producing map
    jobs during the reduce phase, the pool regenerates the data, and
    the task still finishes byte-identical; map_reruns counts the
    last-resort re-runs and the errors stream tags them
    spill-lost-requeue."""
    import time

    from lua_mapreduce_tpu.engine.placement import replica_names

    clean, _ = _run_distributed(tmp_path, "mem", False, "rep-loss-c")

    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, "mem", "rep-loss-f"))
    store = MemJobStore()
    server = Server(store, poll_interval=0.01, premerge_min_runs=2,
                    batch_k=2, replication=2).configure(spec)
    # map-only worker first: the reduce phase is reached with NO reduce
    # consumer, so the destruction below races nothing
    mapper = Worker(store).configure(max_iter=4000, max_sleep=0.02,
                                     phases=("map",))
    final = {}
    st = threading.Thread(
        target=lambda: final.setdefault("stats", server.loop()),
        daemon=True)
    mt = threading.Thread(target=mapper.execute, daemon=True)
    st.start()
    mt.start()

    raw = get_storage_from(spec.storage)
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if store.counts(RED_NS)[Status.WAITING] > 0:
                break
        except Exception:
            pass
        time.sleep(0.01)
    else:
        raise AssertionError("never reached the reduce phase")

    victims = raw.list("result.P0.M*")
    assert victims, "partition 0 produced no runs"
    for name in victims:
        for copy in replica_names(name, 2):
            try:
                raw.remove(copy)
            except Exception:
                pass

    reducer = Worker(store).configure(max_iter=4000, max_sleep=0.05)
    rt = threading.Thread(target=reducer.execute, daemon=True)
    rt.start()
    st.join(timeout=60)
    assert not st.is_alive(), "server wedged after total replica loss"
    mt.join(timeout=10)
    rt.join(timeout=10)

    got = {k: v[0] for k, v in iter_results(raw, "result")}
    assert got == GOLDEN
    assert _result_bytes(spec.storage, only_results=True) == clean
    it = final["stats"].iterations[-1]
    assert it.map_reruns >= len(victims), \
        "total loss must requeue every destroyed producer"
    kinds = {e.get("classification") for e in server.errors}
    assert "spill-lost-requeue" in kinds


# --- speculative-execution legs (DESIGN §21) ---------------------------------
#
# The ISSUE 7 acceptance gate: one deterministically SLOW worker (the
# `slow` FaultPlan kind taxes every data-plane op of "straggler-0" with
# per-op latency) on every backend × both shuffle modes — with
# speculation on, output must be byte-identical to the fault-free twin,
# repetition counts all zero (asserted per job inside _run_distributed)
# and at least one clone must win its commit race (spec_wins ≥ 1).

def _slow_plan(seed, slow_ms=120.0):
    """Every data-plane op by the straggler pays ``slow_ms`` for the
    whole run — a ~20x op-latency multiplier against this suite's
    healthy ops, provoked deterministically."""
    return FaultPlan(seed, slow_worker="straggler-*", slow_ms=slow_ms,
                     slow_s=3600.0)


def test_speculation_smoke_straggler(tmp_path):
    """The test.sh speculation chaos gate: one fast leg — slow worker,
    clone wins, byte-identical output, zero repetition charges."""
    clean, _ = _run_distributed(tmp_path, "mem", False, "spec-smoke-c")
    plan = _slow_plan(81)
    chaotic, stats = _run_distributed(
        tmp_path, "mem", False, "spec-smoke-f", plan=plan, n_workers=3,
        speculation=3.0, straggler=True, batch_k=1)
    assert chaotic == clean, "speculation leg output differs"
    assert plan.fired.get("slow", 0) > 0, "the straggler was never slowed"
    it = stats.iterations[-1]
    assert it.spec_launched >= 1, "detector never opened a shadow lease"
    assert it.spec_wins >= 1, "no clone ever won the commit race"


@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_speculation_chaos_matrix(tmp_path, backend, pipeline):
    """The full acceptance matrix: a slow-plan straggler on every
    backend × both shuffle modes — speculation-on output byte-identical
    to the fault-free twin, zero repetition bumps, spec_wins ≥ 1."""
    tag = f"spec-{backend}-{int(pipeline)}"
    clean, _ = _run_distributed(tmp_path, backend, pipeline, tag + "-c")
    plan = _slow_plan(83)
    chaotic, stats = _run_distributed(
        tmp_path, backend, pipeline, tag + "-f", plan=plan, n_workers=3,
        speculation=3.0, straggler=True, batch_k=1)
    assert chaotic == clean, "speculation leg output differs"
    assert plan.fired.get("slow", 0) > 0
    it = stats.iterations[-1]
    assert it.spec_wins >= 1, "no clone ever won the commit race"
    assert it.map.failed == 0 and it.reduce.failed == 0


def test_speculation_off_same_bytes_under_straggler(tmp_path):
    """The tri-compare leg: the same slow-plan straggler run with
    speculation OFF still produces the identical bytes (slower — the
    straggler sets the wall clock) and the speculation-ON run matches
    both. Speculation changes WHO computes, never WHAT."""
    clean, _ = _run_distributed(tmp_path, "mem", False, "spec3-c")
    off, off_stats = _run_distributed(
        tmp_path, "mem", False, "spec3-off", plan=_slow_plan(89),
        n_workers=3, straggler=True, batch_k=1, speculation=0.0)
    on, on_stats = _run_distributed(
        tmp_path, "mem", False, "spec3-on", plan=_slow_plan(89),
        n_workers=3, straggler=True, batch_k=1, speculation=3.0)
    # the off leg leaves no orphans (nothing was ever disowned), so its
    # full listing equals the narrowed ones
    assert off == clean and on == clean
    assert off_stats.iterations[-1].spec_launched == 0
    assert on_stats.iterations[-1].spec_wins >= 1


def test_replication_total_loss_single_dual_phase_worker(tmp_path):
    """Regression: with ONE dual-phase worker, the reduce-phase claim
    must not shadow the requeued producer — the worker probes MAP_NS
    BEFORE reclaiming its own released lost-data reduce job, or the
    map re-run starves forever and the task fails. A map-only worker
    bounded to exactly the map job count exits at the barrier, so the
    destruction races nothing and the late dual-phase worker is the
    ONLY claimant for both the recovery map and the retrying reduce."""
    import time

    from lua_mapreduce_tpu.engine.placement import replica_names

    clean, _ = _run_distributed(tmp_path, "mem", False, "rep-1w-c")

    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, "mem", "rep-1w-f"))
    store = MemJobStore()
    server = Server(store, poll_interval=0.01, premerge_min_runs=2,
                    batch_k=2, replication=2).configure(spec)
    mapper = Worker(store).configure(max_iter=4000, max_sleep=0.02,
                                     phases=("map",),
                                     max_jobs=len(CORPUS))
    final = {}
    st = threading.Thread(
        target=lambda: final.setdefault("stats", server.loop()),
        daemon=True)
    mt = threading.Thread(target=mapper.execute, daemon=True)
    st.start()
    mt.start()
    mt.join(timeout=60)
    assert not mt.is_alive(), "bounded mapper never exited"

    raw = get_storage_from(spec.storage)
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if store.counts(RED_NS)[Status.WAITING] > 0:
                break
        except Exception:
            pass
        time.sleep(0.01)
    else:
        raise AssertionError("never reached the reduce phase")

    victims = raw.list("result.P0.M*")
    assert victims, "partition 0 produced no runs"
    for name in victims:
        for copy in replica_names(name, 2):
            try:
                raw.remove(copy)
            except Exception:
                pass

    solo = Worker(store).configure(max_iter=4000, max_sleep=0.05)
    wt = threading.Thread(target=solo.execute, daemon=True)
    wt.start()
    st.join(timeout=60)
    assert not st.is_alive(), \
        "server wedged: the solo worker starved its own producer re-run"
    wt.join(timeout=10)

    got = {k: v[0] for k, v in iter_results(raw, "result")}
    assert got == GOLDEN
    assert _result_bytes(spec.storage, only_results=True) == clean
    it = final["stats"].iterations[-1]
    assert it.map_reruns >= len(victims)


# --- push-shuffle legs (DESIGN §24) ------------------------------------------
#
# The ISSUE 12 chaos gate: the streaming shuffle under the same storms
# as the staged plane — seeded transient faults, a whole placement tag
# dark during the push, a SIGKILLed mapper mid-frame covered by a
# speculation clone, and the quarantine rule (a clone's inbox lineage
# must never become visible once the original's commit wins).

def test_push_chaos_smoke_faultplan(tmp_path):
    """Seeded transient/latency/error-after-write faults on a push run:
    invisible in the bytes (vs the fault-free STAGED twin — one oracle
    covers both mode equivalence and fault absorption)."""
    clean, _ = _run_local(tmp_path, "mem", False, "push-sm-c")
    plan = _plan(seed=211)
    chaotic, stats = _run_local(tmp_path, "mem", False, "push-sm-f",
                                plan=plan, push=True)
    assert chaotic == clean
    assert plan.total_fired() > 0
    assert stats.iterations[-1].push_frames > 0


def test_push_chaos_blackout_tag(tmp_path):
    """One placement tag dark for the whole run while frames are being
    pushed (fragments, tails, manifests AND their replica copies on the
    dark tag): r=2 failover serves every read — byte-identical output,
    zero map re-runs."""
    from lua_mapreduce_tpu.engine.placement import replica_pattern

    clean, _ = _run_local(tmp_path, "mem", True, "push-bo-c")
    shuffle = ["result.P[0-9]*.M*", "result.P[0-9]*.SPILL-*",
               "result.P[0-9]*.INBOX-*", "result.PUSH.M*"]
    plan = FaultPlan(223, blackout_tag=5, blackout_s=3600.0,
                     pattern="|".join(shuffle
                                      + [replica_pattern(p)
                                         for p in shuffle]),
                     latency_ms=0)
    chaotic, stats = _run_local(tmp_path, "mem", True, "push-bo-f",
                                plan=plan, push=True, replication=2)
    assert chaotic == clean
    assert plan.fired.get("blackout", 0) > 0, "the dark tag was never hit"
    it = stats.iterations[-1]
    assert it.push_frames > 0
    assert it.map_reruns == 0


def test_push_chaos_spec_straggler_quarantine(tmp_path):
    """Slow-plan straggler with speculation on a PUSH run: clones race
    the straggler's maps, first-commit-wins decides each visible inbox
    lineage, output stays byte-identical with zero repetition charges
    — and no quarantined (spec-tagged) fragment survives outside its
    winning lineage."""
    clean, _ = _run_distributed(tmp_path, "mem", True, "push-spec-c")
    plan = _slow_plan(227)
    chaotic, stats = _run_distributed(
        tmp_path, "mem", True, "push-spec-f", plan=plan, n_workers=3,
        speculation=3.0, straggler=True, batch_k=1, push=True)
    assert chaotic == clean, "push speculation leg output differs"
    assert plan.fired.get("slow", 0) > 0
    it = stats.iterations[-1]
    assert it.spec_wins >= 1, "no clone ever won the commit race"
    assert it.push_frames > 0
    # quarantine: every spec-tagged fragment left behind must belong to
    # a lineage that became canonical (a loser's inbox is swept or was
    # never referenced) — no reduce consumed a quarantined lineage, or
    # the byte-compare above would already have failed
    from lua_mapreduce_tpu.engine.push import (manifest_name,
                                               parse_inbox_name,
                                               read_manifest)
    store = get_storage_from(
        _storage(tmp_path, "mem", "push-spec-f"))
    for name in store.list("result.P*.INBOX-*"):
        parsed = parse_inbox_name("result", name)
        assert parsed is not None
        part, key, lineage, _seq, _tail = parsed
        if lineage is None:
            continue
        man = read_manifest(store, manifest_name("result", key))
        assert man is not None and man.get("lineage") == lineage, \
            f"quarantined fragment {name} visible outside its lineage"


def _sigkill_pusher_leg(tmp_path, modname, coding=None):
    """SIGKILL a pushing mapper mid-frame (a real subprocess worker,
    slowed by the plan so it is verifiably mid-push when killed) with
    speculation on and the stale-requeue DISABLED: only a clone's
    first-commit-wins coverage can finish the job, so completion with
    zero repetition charges is load-bearing, not luck. The victim's
    partial inbox (frames with no manifest) stays invisible and is
    swept; output is byte-identical to the fault-free staged twin.

    With ``coding`` set the same storm runs on the erasure-coded push
    plane (DESIGN §27): the kill lands mid-STRIPE, and the manifest
    gate — member manifests published strictly after every block — is
    what keeps the victim's partial stripe invisible."""
    import json as _json
    import os
    import signal
    import subprocess
    import sys
    import time

    from lua_mapreduce_tpu.coord.filestore import FileJobStore

    clean, _ = _run_local(tmp_path, "mem", False, f"kill-{modname}-c")

    _install_module()
    # the distributed fleet round-trips user modules by import path:
    # install the same wordcount as a real module file the subprocess
    # can import
    moddir = tmp_path / "mods"
    moddir.mkdir()
    (moddir / f"{modname}.py").write_text(
        "CORPUS = " + repr(CORPUS) + "\n"
        "def taskfn(emit):\n"
        "    for k, v in sorted(CORPUS.items()): emit(k, v)\n"
        "def mapfn(key, value, emit):\n"
        "    for w in value.split(): emit(w, 1)\n"
        "def partitionfn(key):\n"
        "    return sum(key.encode()) % 4\n"
        "def reducefn(key, values):\n"
        "    return sum(values)\n")
    coord = tmp_path / "kill-coord"
    spill = tmp_path / "kill-spill"
    import sys as _sys
    _sys.path.insert(0, str(moddir))
    try:
        spec = TaskSpec(taskfn=modname, mapfn=modname,
                        partitionfn=modname, reducefn=modname,
                        storage=f"shared:{spill}")
        plan = FaultPlan(229, slow_worker="victim-*", slow_ms=250.0,
                         slow_s=3600.0)
        env = dict(os.environ,
                   PYTHONPATH=f"{moddir}:{os.environ.get('PYTHONPATH', '')}",
                   LMR_FAULT_PLAN=plan.to_spec(),
                   JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def spawn(name):
            code = (
                "import sys\n"
                f"sys.path.insert(0, {repo!r})\n"
                f"sys.path.insert(0, {str(moddir)!r})\n"
                "from lua_mapreduce_tpu import FileJobStore, Worker\n"
                f"w = Worker(FileJobStore({str(coord)!r}), name={name!r})\n"
                "w.configure(max_iter=100000, max_sleep=0.05,\n"
                "            max_tasks=1, heartbeat_s=0.25)\n"
                "w.execute()\n")
            return subprocess.Popen([sys.executable, "-c", code], env=env)

        victim = spawn("victim-0")
        store = FileJobStore(str(coord))
        server = Server(store, poll_interval=0.05, push=True,
                        stale_timeout_s=None,   # ONLY speculation saves it
                        speculation=2.0, batch_k=1,
                        coding=coding).configure(spec)
        final = {}
        st = threading.Thread(
            target=lambda: final.setdefault("stats", server.loop()),
            daemon=True)
        st.start()
        # head start: the victim must HOLD a lease before the healthy
        # fleet exists, or the un-slowed workers drain the tiny job set
        # before the slowed victim ever claims
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if any(d["status"] == Status.RUNNING
                       and d.get("worker") == "victim-0"
                       for d in store.jobs(MAP_NS)):
                    break
            except Exception:
                pass
            time.sleep(0.05)
        else:
            raise AssertionError("victim never claimed a lease")
        # plain mode: the healthy fleet races the slowed victim from the
        # start. Coded mode: the victim's first physical artifact is a
        # stripe BLOCK near the end of its job body, so a pre-spawned
        # fleet's clone would commit the job before the mid-stripe
        # window ever opens — spawn the fleet AFTER the kill instead
        # (the contract under test is identical: only a clone's
        # zero-charge coverage may finish the dead victim's job)
        healthy = [] if coding is not None \
            else [spawn(f"healthy-{i}") for i in range(2)]

        # kill the victim the moment it is verifiably MID-PUSH: a
        # frame of one of its claimed jobs landed, more output pending
        from lua_mapreduce_tpu.engine.placement import parse_block
        deadline = time.time() + 90
        killed = False
        while time.time() < deadline and not killed:
            frags = []
            if spill.exists():
                frags = [f for f in os.listdir(spill)
                         if (parse_block(f) if coding is not None
                             else ".INBOX-" in f)]
            if frags:
                try:
                    # the victim must HOLD a live lease right now — the
                    # claim log alone also lists already-committed
                    # claims, and killing after its last commit would
                    # prove nothing
                    running = [d for d in store.jobs(MAP_NS)
                               if d["status"] == Status.RUNNING
                               and d.get("worker") == "victim-0"]
                except Exception:
                    running = []
                # ... and be verifiably MID-FRAME: a frame of one of
                # ITS running jobs already landed, its manifest/commit
                # have not (it is still RUNNING). Coded artifacts spell
                # the map key two ways: individually striped frames
                # embed the .INBOX- fragment name in each block, group
                # stripes embed the key in the .CODE. group base.
                from lua_mapreduce_tpu.engine.job import map_key_str
                keys = {map_key_str(d["_id"]) for d in running}
                mid_frame = any(f".INBOX-{k}-" in f or f".CODE.{k}" in f
                                for k in keys for f in frags)
                if mid_frame:
                    victim.send_signal(signal.SIGKILL)
                    killed = True
                    break
            time.sleep(0.05)
        assert killed, "victim never got mid-push before the deadline"
        if not healthy:
            healthy = [spawn(f"healthy-{i}") for i in range(2)]

        st.join(timeout=120)
        assert not st.is_alive(), \
            "server wedged after the pusher was SIGKILLed"
        for p in healthy:
            try:
                p.wait(timeout=30)
            except Exception:
                p.kill()
        victim.wait(timeout=10)
        stats = final["stats"]
    finally:
        _sys.path.remove(str(moddir))

    got = {}
    from lua_mapreduce_tpu.engine.local import iter_results as _ir
    for k, v in _ir(get_storage_from(spec.storage), "result"):
        got[k] = v[0]
    assert got == GOLDEN
    assert _result_bytes(spec.storage, only_results=True) == clean
    # zero repetition charges: with the stale requeue off, only the
    # clone's zero-charge coverage can have finished the victim's job
    for d in store.jobs(MAP_NS):
        assert d["repetitions"] == 0, \
            f"SIGKILL charged a repetition: map job {d['_id']}"
    # spec_wins is counted in the CLONE's process (a subprocess here);
    # the server-side proof is the detector having opened the shadow
    # lease — with the stale requeue off and zero repetitions, nothing
    # else can have finished the victim's job
    it = stats.iterations[-1]
    assert it.spec_launched >= 1, "detector never opened a shadow lease"

    if coding is not None:
        # the manifest gate, structurally: every stripe block left on
        # disk either belongs to a COMPLETE stripe (its logical name is
        # visible and fully readable through the coded view) or its
        # manifest never landed — in which case the logical name must
        # be invisible. A readable-but-partial stripe would be a torn
        # read waiting to happen; the gate makes that state
        # unrepresentable.
        from lua_mapreduce_tpu.engine.placement import base_name, parse_block
        from lua_mapreduce_tpu.faults.replicate import reading_view
        raw = get_storage_from(spec.storage)
        view = reading_view(raw, coding)
        blocks = [f for f in os.listdir(spill) if parse_block(f)]
        assert blocks, "coded sigkill leg never published a stripe block"
        for f in blocks:
            base = base_name(f)
            if view.exists(base):
                assert view.size(base) >= 0  # complete => readable
    return stats


def test_push_chaos_sigkill_pusher_midframe(tmp_path):
    _sigkill_pusher_leg(tmp_path, "pushkill_wc")


def test_coded_chaos_sigkill_pusher_midstripe(tmp_path):
    """The ISSUE 16 SIGKILL-mid-stripe chaos gate: the same storm on
    the coded push plane — a partial stripe (blocks with no member
    manifest) stays invisible, a clone covers the killed producer, and
    the output is byte-identical with zero repetition charges."""
    _sigkill_pusher_leg(tmp_path, "codedkill_wc", coding="4+1")


# --- erasure-coded shuffle legs (DESIGN §27) ---------------------------------
#
# The ISSUE 16 chaos gate: the replication bar carried over verbatim to
# the coded plane at ~1.3x write amplification instead of 2x. A
# FaultPlan destroys one block of EVERY stripe (the coded analog of
# 'every primary destroyed' — any <= m losses per stripe must decode
# inline), a whole placement tag goes dark during a coded push run, a
# producer is SIGKILLed mid-stripe (above), and a corrupted parity
# block must be caught by the block CRC and treated as one more lost
# block, not served.

def _kill_block0_plan(seed):
    """Every read of the FIRST data block of every stripe fails
    permanently — one destroyed block per stripe, the r-1-of-r kill
    translated to k+m (the pattern's ^0. prefix never matches a
    manifest copy, a plain tail, or a list() pattern argument)."""
    return FaultPlan(seed, permanent=1.0, pattern="^0.*^result.*",
                     max_per_key=100_000, latency_ms=0)


def test_coded_smoke_decode(tmp_path):
    """The test.sh coded chaos gate: one fast leg — a data block of
    every stripe destroyed, parity decodes inline, zero map re-runs,
    byte-identical output."""
    clean, _ = _run_local(tmp_path, "mem", False, "cod-smoke-c")
    plan = _kill_block0_plan(251)
    chaotic, stats = _run_local(tmp_path, "mem", False, "cod-smoke-f",
                                plan=plan, coding="4+1")
    assert chaotic == clean, "coded decode leg output differs"
    assert plan.total_fired() > 0
    it = stats.iterations[-1]
    assert it.decode_reads > 0, "plan never forced a decode"
    assert it.map_reruns_avoided > 0
    assert it.map_reruns == 0, "parity failed to absorb the block kills"


@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_coded_chaos_distributed_matrix(tmp_path, backend, pipeline):
    """The acceptance matrix on the distributed engine under coding
    4+1: one block of every stripe destroyed across
    {mem,shared,object} x {barrier,pipelined} — byte-identical to the
    fault-free twin, zero repetition bumps (asserted per job inside
    _run_distributed), zero map re-runs: pure decode reads."""
    tag = f"cod-{backend}-{int(pipeline)}"
    clean, _ = _run_distributed(tmp_path, backend, pipeline, tag + "-c")
    plan = _kill_block0_plan(257)
    chaotic, stats = _run_distributed(tmp_path, backend, pipeline,
                                      tag + "-f", plan=plan, coding="4+1")
    assert chaotic == clean, "coded decode leg output differs"
    assert plan.total_fired() > 0
    it = stats.iterations[-1]
    assert it.decode_reads > 0, "plan never forced a decode"
    assert it.map_reruns == 0, "parity failed to absorb the block kills"


def test_coded_chaos_blackout_push(tmp_path):
    """m placement tags dark (m=1 for 4+1) for the WHOLE of a coded
    PUSH run — every stripe block, group-stripe block, manifest copy
    and replicated eviction tail routed onto the dark tag is
    unreadable. Each stripe spans k+m distinct tags so it loses at
    most one block; each manifest and tail has m+1 copies on distinct
    tags: the run completes byte-identical with ZERO map re-runs."""
    from lua_mapreduce_tpu.engine.placement import replica_pattern
    from lua_mapreduce_tpu.faults.coded import stripe_patterns

    clean, _ = _run_local(tmp_path, "mem", True, "cod-bo-c")
    # scope the blackout to the whole shuffle plane, in every physical
    # spelling: plain names (staged runs, eviction tails), ~-replica
    # copies, ^-stripe blocks and manifest copies, and the shared
    # group-stripe blocks under the CODE tag
    shuffle = ["result.P[0-9]*.M*", "result.P[0-9]*.SPILL-*",
               "result.P[0-9]*.INBOX-*", "result.PUSH.M*", "result.CODE.*"]
    phys = []
    for p in shuffle:
        phys += [p, replica_pattern(p)]
        for sp in stripe_patterns(p):
            phys += [sp, replica_pattern(sp)]
    plan = FaultPlan(241, blackout_tag=2, blackout_s=3600.0,
                     pattern="|".join(phys), latency_ms=0)
    chaotic, stats = _run_local(tmp_path, "mem", True, "cod-bo-f",
                                plan=plan, push=True, coding="4+1")
    assert chaotic == clean, "coded blackout leg output differs"
    assert plan.fired.get("blackout", 0) > 0, "the dark tag was never hit"
    it = stats.iterations[-1]
    assert it.push_frames > 0
    assert it.decode_reads + it.failover_reads > 0, \
        "the blackout never forced a degraded read"
    assert it.map_reruns == 0


def test_coded_chaos_corrupt_parity_block(tmp_path):
    """A corrupted parity block is DETECTED by the per-block CRC and
    treated as one more lost block — never folded into a decode. With
    4+2, one data block destroyed AND one parity block corrupted on
    the same stripe still leaves k readable blocks: the reduce decodes
    inline, zero map re-runs, byte-identical output."""
    import time

    clean, _ = _run_distributed(tmp_path, "shared", False, "cod-crc-c")

    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, "shared", "cod-crc-f"))
    store = MemJobStore()
    server = Server(store, poll_interval=0.01, premerge_min_runs=2,
                    batch_k=2, coding="4+2").configure(spec)
    # map-only worker first: the reduce phase is reached with NO reduce
    # consumer, so the corruption below races nothing
    mapper = Worker(store).configure(max_iter=4000, max_sleep=0.02,
                                     phases=("map",))
    final = {}
    st = threading.Thread(
        target=lambda: final.setdefault("stats", server.loop()),
        daemon=True)
    mt = threading.Thread(target=mapper.execute, daemon=True)
    st.start()
    mt.start()

    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if store.counts(RED_NS)[Status.WAITING] > 0:
                break
        except Exception:
            pass
        time.sleep(0.01)
    else:
        raise AssertionError("never reached the reduce phase")

    # mutate the stripe on disk, under the engine: delete the block-0
    # file of one partition-0 run and flip one byte inside the SAME
    # stripe's first parity block (index k=4) — the decode that the
    # deletion forces must reject the corrupted parity on CRC and
    # reconstruct from the remaining k survivors
    import os

    from lua_mapreduce_tpu.engine.placement import base_name

    spill_dir = str(tmp_path / "shared-cod-crc-f")
    data0 = [f for f in os.listdir(spill_dir)
             if f.startswith("^0.") and "result.P0." in f]
    assert data0, "partition 0 produced no stripe blocks"
    victim_base = base_name(data0[0])
    stripe = [f for f in os.listdir(spill_dir)
              if f.endswith(victim_base) and "^" in f]
    parity = [f for f in stripe if f.startswith("^4.")]
    assert parity, f"stripe of {victim_base} has no parity block"
    ppath = os.path.join(spill_dir, parity[0])
    blob = open(ppath, "rb").read()
    pos = min(10, len(blob) - 1)
    with open(ppath, "wb") as fh:
        fh.write(blob[:pos] + bytes([blob[pos] ^ 0xFF]) + blob[pos + 1:])
    os.remove(os.path.join(spill_dir, data0[0]))

    reducer = Worker(store).configure(max_iter=4000, max_sleep=0.05)
    rt = threading.Thread(target=reducer.execute, daemon=True)
    rt.start()
    st.join(timeout=60)
    assert not st.is_alive(), "server wedged after the block mutation"
    mt.join(timeout=10)
    rt.join(timeout=10)

    raw = get_storage_from(spec.storage)
    got = {k: v[0] for k, v in iter_results(raw, "result")}
    assert got == GOLDEN
    assert _result_bytes(spec.storage, only_results=True) == clean
    it = final["stats"].iterations[-1]
    assert it.decode_reads > 0, "the mutation never forced a decode"
    assert it.map_reruns == 0, \
        "corrupt parity + one lost data block must decode, not re-run"


# ---------------------------------------------------------------------------
# ISSUE 17 hybrid chaos gate (DESIGN §28): an extsort-shaped task whose
# oracle split is map=compiled / partition=host — the fleet negotiates
# the hybrid stage split on the task doc, a subprocess worker is
# SIGKILLed MID-COMPILED-MAP-LEG (a spill of its running job has
# landed, its commit has not) under a seeded transient-fault storm,
# and only a speculation clone's zero-charge coverage may finish the
# job: byte-identical output, zero repetition bumps, compiled legs
# still counted on the surviving fleet.
# ---------------------------------------------------------------------------

_HYBRID_SORT_SRC = """
import hashlib
import jax.numpy as jnp

def taskfn(emit):
    for j in range(8):
        emit(j, {"vals": [(j * 16 + i) * 7 % 101 for i in range(16)]})

def mapfn(key, value, emit):
    v = jnp.asarray(value["vals"], jnp.int32)
    for i in range(16):
        # every key twice: multi-value groups are what the compiled
        # reduce fold folds (singleton groups take the merge fast path)
        emit(int(key) * 16 + i, v[i])
        emit(int(key) * 16 + i, v[i])

def partitionfn(key):
    h = hashlib.blake2b(str(int(key)).encode(),
                        digest_size=2).hexdigest()
    return int(h, 16) % 4

def reducefn(key, values):
    acc = values[0]
    for i in range(1, len(values)):
        acc = acc + values[i]
    return acc

reducefn.associative_reducer = True
reducefn.commutative_reducer = True
"""


def test_hybrid_chaos_sigkill_mid_compiled_leg(tmp_path):
    import json as _json
    import os
    import signal
    import subprocess
    import sys
    import time

    from lua_mapreduce_tpu.coord.filestore import FileJobStore
    from lua_mapreduce_tpu.engine.job import map_key_str

    modname = "hybridkill_sort"
    moddir = tmp_path / "mods"
    moddir.mkdir()
    (moddir / f"{modname}.py").write_text(_HYBRID_SORT_SRC)
    coord = tmp_path / "hyb-coord"
    spill = tmp_path / "hyb-spill"
    sys.path.insert(0, str(moddir))
    try:
        spec = TaskSpec(taskfn=modname, mapfn=modname,
                        partitionfn=modname, reducefn=modname,
                        storage=f"shared:{spill}")
        # the fault-free interpreted twin — the byte-compare golden
        twin = TaskSpec(taskfn=modname, mapfn=modname,
                        partitionfn=modname, reducefn=modname,
                        storage="mem:hybkill-twin")
        LocalExecutor(twin, engine="store").run()
        clean = _result_bytes("mem:hybkill-twin", only_results=True)

        # the acceptance storm (the smoke legs' absorbable mix) PLUS
        # the deterministic straggler tax on the victim so it is
        # verifiably mid-leg when killed — installed in the subprocess
        # (env) AND in this process (the healthy threads + server)
        plan = FaultPlan(311, transient=0.08, latency=0.05,
                         latency_ms=1.0, max_per_key=2,
                         slow_worker="victim-*", slow_ms=250.0,
                         slow_s=3600.0)
        install_fault_plan(plan)
        env = dict(os.environ,
                   PYTHONPATH=f"{moddir}:{os.environ.get('PYTHONPATH', '')}",
                   LMR_FAULT_PLAN=plan.to_spec(),
                   JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def spawn(name):
            code = (
                "import sys\n"
                f"sys.path.insert(0, {repo!r})\n"
                f"sys.path.insert(0, {str(moddir)!r})\n"
                "from lua_mapreduce_tpu import FileJobStore, Worker\n"
                f"w = Worker(FileJobStore({str(coord)!r}), name={name!r})\n"
                "w.configure(max_iter=100000, max_sleep=0.05,\n"
                "            max_tasks=1, heartbeat_s=0.25)\n"
                "w.execute()\n")
            return subprocess.Popen([sys.executable, "-c", code], env=env)

        victim = spawn("victim-0")
        store = FileJobStore(str(coord))
        server = Server(store, poll_interval=0.05, engine="auto",
                        stale_timeout_s=None,   # ONLY speculation saves it
                        speculation=2.0, batch_k=1).configure(spec)
        final = {}
        st = threading.Thread(
            target=lambda: final.setdefault("stats", server.loop()),
            daemon=True)
        st.start()
        # head start: the victim must hold a map lease before the
        # healthy fleet exists
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if any(d["status"] == Status.RUNNING
                       and d.get("worker") == "victim-0"
                       for d in store.jobs(MAP_NS)):
                    break
            except Exception:
                pass
            time.sleep(0.05)
        else:
            raise AssertionError("victim never claimed a lease")
        # the fleet negotiated the stage split on the doc before any
        # job was inserted — every worker (victim included) is running
        # the COMPILED map leg
        task = store.get_task()
        assert task["engine"] == "auto"
        assert task["hybrid_stages"] == {"map": True, "reduce": True}

        # kill the victim the moment it is verifiably MID-LEG: its
        # compiled batch ran and the publish tail has landed at least
        # one spill of a job it still holds (commit pending). The
        # healthy fleet spawns AFTER the kill — a racing clone would
        # cover the slowed victim's job before its mid-leg window
        # opens (the coded pusher leg's exact sequencing)
        deadline = time.time() + 90
        killed = False
        while time.time() < deadline and not killed:
            spills = os.listdir(spill) if spill.exists() else []
            try:
                running = [d for d in store.jobs(MAP_NS)
                           if d["status"] == Status.RUNNING
                           and d.get("worker") == "victim-0"]
            except Exception:
                running = []
            keys = {map_key_str(d["_id"]) for d in running}
            if any(f".M{k}" in f for k in keys for f in spills):
                victim.send_signal(signal.SIGKILL)
                killed = True
                break
            time.sleep(0.05)
        assert killed, "victim never got mid-compiled-leg before deadline"

        # the healthy fleet runs IN-PROCESS so its compiled-leg
        # counters fold into the server's IterationStats (the counter
        # fold is process-global — a subprocess's bumps stay its own,
        # like spec_wins in the pusher leg above)
        healthy = [Worker(store, name=f"healthy-{i}").configure(
            max_iter=100000, max_sleep=0.05, max_tasks=1,
            heartbeat_s=0.25) for i in range(2)]
        hthreads = [threading.Thread(target=w.execute, daemon=True)
                    for w in healthy]
        for t in hthreads:
            t.start()

        st.join(timeout=120)
        assert not st.is_alive(), \
            "server wedged after the compiled-leg worker was SIGKILLed"
        for t in hthreads:
            t.join(timeout=30)
        victim.wait(timeout=10)
        stats = final["stats"]
    finally:
        install_fault_plan(None)
        sys.path.remove(str(moddir))

    assert _result_bytes(spec.storage, only_results=True) == clean
    # zero repetition charges: with the stale requeue off, only the
    # clone's zero-charge coverage can have finished the victim's job
    for d in store.jobs(MAP_NS):
        assert d["repetitions"] == 0, \
            f"SIGKILL mid-leg charged a repetition: map job {d['_id']}"
    it = stats.iterations[-1]
    assert it.spec_launched >= 1, "detector never opened a shadow lease"
    # the surviving fleet kept running compiled legs, and the reduce
    # fold folded — the kill degraded ONE worker, not the hybrid plane
    assert it.hybrid_map_legs >= 1
    assert it.hybrid_reduce_legs >= 1
