"""Chaos suite (DESIGN §19): the wordcount matrix under seeded
FaultPlans.

Each leg runs the same wordcount task twice — fault-free, then under a
deterministic FaultPlan injecting transient errors + latency +
error-after-write (and torn writes on the heavier legs) — across
{mem, shared, object} storage × {barrier, pipelined} shuffle × both
executors (LocalExecutor and the distributed Server + in-process
Worker pool), and asserts:

1. byte-identical outputs: the injected faults are invisible in the
   results;
2. ZERO repetition bumps attributable to injected transient faults
   (the distributed legs check every job's repetitions == 0 — the
   tentpole's release-not-broken contract);
3. the plan actually fired (a chaos run that injected nothing proves
   nothing).

The smoke legs (`-k smoke`) are the test.sh chaos gate: one seeded
plan per backend, fast. The full matrix is the tier-1 chaos suite.
"""

import threading
from typing import Dict

import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.core.constants import Status
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor, iter_results
from lua_mapreduce_tpu.engine.server import Server
from lua_mapreduce_tpu.engine.worker import MAP_NS, PRE_NS, RED_NS, Worker
from lua_mapreduce_tpu.faults import FaultPlan, install_fault_plan
from lua_mapreduce_tpu.store.router import get_storage_from

CORPUS = {
    f"doc{i}": " ".join(f"w{(i * 7 + j) % 23}" for j in range(40))
    for i in range(8)
}
GOLDEN: Dict[str, int] = {}
for _text in CORPUS.values():
    for _w in _text.split():
        GOLDEN[_w] = GOLDEN.get(_w, 0) + 1

_MOD = "tests._chaos_wc"


def _install_module():
    """The wordcount program as an importable module (the distributed
    engine round-trips specs through module paths)."""
    import sys
    import types

    mod = sys.modules.get(_MOD)
    if mod is None:
        mod = types.ModuleType(_MOD)

        def taskfn(emit):
            for k, v in sorted(CORPUS.items()):
                emit(k, v)

        def mapfn(key, value, emit):
            for w in value.split():
                emit(w, 1)

        mod.taskfn = taskfn
        mod.mapfn = mapfn
        mod.partitionfn = lambda key: sum(key.encode()) % 4
        mod.reducefn = lambda key, values: sum(values)
        sys.modules[_MOD] = mod
    return mod


def _storage(tmp_path, backend, tag):
    return {"mem": f"mem:{tag}",
            "shared": f"shared:{tmp_path}/shared-{tag}",
            "object": f"object:{tmp_path}/object-{tag}"}[backend]


def _result_bytes(storage_spec, ns="result"):
    """The result namespace's exact bytes, partition by partition — the
    byte-compare oracle."""
    store = get_storage_from(storage_spec)
    out = {}
    for name in store.list(f"{ns}.P*"):
        out[name] = "".join(store.lines(name))
    return out


def _plan(seed, heavy=False):
    """The acceptance-criteria mix: transient + latency +
    error-after-write (+ torn on heavy legs); latency_ms kept tiny so
    the suite stays fast. max_per_key=2 < the default retry budget of
    3, so every injected burst is absorbable — zero repetition bumps is
    therefore a hard assertion, not a hope."""
    return FaultPlan(seed, transient=0.08, latency=0.05,
                     error_after_write=0.3,
                     torn=0.2 if heavy else 0.0,
                     latency_ms=1.0, max_per_key=2)


def _run_local(tmp_path, backend, pipeline, tag, plan=None):
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, backend, tag))
    install_fault_plan(plan)
    try:
        ex = LocalExecutor(spec, map_parallelism=3, pipeline=pipeline,
                           premerge_min_runs=2,
                           segment_format="v2" if pipeline else "v1")
        stats = ex.run()
    finally:
        install_fault_plan(None)
    got = {k: v[0] for k, v in ex.results()}
    assert got == GOLDEN
    return _result_bytes(spec.storage), stats


def _run_distributed(tmp_path, backend, pipeline, tag, plan=None,
                     n_workers=2):
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, backend, tag))
    store = MemJobStore()
    install_fault_plan(plan)
    try:
        server = Server(store, poll_interval=0.01, pipeline=pipeline,
                        premerge_min_runs=2, batch_k=2,
                        segment_format="v2" if pipeline else "v1",
                        ).configure(spec)
        workers = [Worker(store).configure(max_iter=800, max_sleep=0.02)
                   for _ in range(n_workers)]
        threads = [threading.Thread(target=w.execute, daemon=True)
                   for w in workers]
        for t in threads:
            t.start()
        stats = server.loop()
        for t in threads:
            t.join(timeout=30)
    finally:
        install_fault_plan(None)

    # the release-not-broken contract: NO repetition bump from any
    # injected transient fault, in any namespace
    for ns in (MAP_NS, PRE_NS, RED_NS):
        for d in store.jobs(ns):
            assert d["repetitions"] == 0, \
                (f"injected transient faults bumped repetitions: "
                 f"{ns} job {d['_id']} -> {d['repetitions']}")
        counts = store.counts(ns)
        assert counts[Status.FAILED] == 0
    got = {k: v[0]
           for k, v in iter_results(get_storage_from(spec.storage),
                                    "result")}
    assert got == GOLDEN
    return _result_bytes(spec.storage), stats


# --- smoke legs: the test.sh chaos gate (one seeded plan per backend) -------

@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_chaos_smoke_backend(tmp_path, backend):
    clean, _ = _run_local(tmp_path, backend, False, f"smoke-{backend}-c")
    plan = _plan(seed=100 + len(backend))
    chaotic, stats = _run_local(tmp_path, backend, False,
                                f"smoke-{backend}-f", plan=plan)
    assert chaotic == clean, "fault leg output differs from fault-free"
    assert plan.total_fired() > 0, "plan injected nothing — seed too weak"
    assert stats.iterations[-1].store_faults > 0


# --- the full matrix ---------------------------------------------------------

@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_chaos_local_matrix(tmp_path, backend, pipeline):
    tag = f"loc-{backend}-{int(pipeline)}"
    clean, _ = _run_local(tmp_path, backend, pipeline, tag + "-c")
    plan = _plan(seed=7)
    chaotic, _ = _run_local(tmp_path, backend, pipeline, tag + "-f",
                            plan=plan)
    assert chaotic == clean
    assert plan.total_fired() > 0


@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_chaos_distributed_matrix(tmp_path, backend, pipeline):
    tag = f"dist-{backend}-{int(pipeline)}"
    clean, _ = _run_distributed(tmp_path, backend, pipeline, tag + "-c")
    plan = _plan(seed=13, heavy=True)
    chaotic, stats = _run_distributed(tmp_path, backend, pipeline,
                                      tag + "-f", plan=plan)
    assert chaotic == clean
    assert plan.total_fired() > 0
    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0


def test_chaos_rpc_faults_on_coord_plane(tmp_path):
    """Transient faults injected on the JOBSTORE RPCs (claims, commits,
    heartbeats) — the control-plane half of the tentpole — are absorbed
    with identical results and zero repetition bumps."""
    tag = "rpc-leg"
    clean, _ = _run_distributed(tmp_path, "mem", False, tag + "-c")
    plan = FaultPlan(17, rpc_transient=0.1, max_per_key=2)
    chaotic, _ = _run_distributed(tmp_path, "mem", False, tag + "-f",
                                  plan=plan)
    assert chaotic == clean
    assert plan.fired.get("rpc_transient", 0) > 0
