"""Transformer LM family: the sharded (dp × sp) forms must golden-diff
against the single-device oracle, and the sequence-parallel train step
must actually learn."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from lua_mapreduce_tpu.models import transformer as tfm
from lua_mapreduce_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    # 2 dp × 4 sp over the 8 virtual CPU devices
    return make_mesh(dp=2, mp=4, devices=jax.devices("cpu")[:8],
                     axis_names=("dp", "sp"))


@pytest.fixture(scope="module")
def cfg():
    return tfm.TransformerConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return tfm.init_transformer(jax.random.PRNGKey(0), cfg)


def _tokens(cfg, b=4, l=64, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randint(0, cfg.vocab, (b, l)), jnp.int32)


@pytest.mark.parametrize("attn", ["ring", "zigzag", "ulysses"])
def test_sharded_forward_matches_oracle(mesh, cfg, params, attn):
    tokens = _tokens(cfg)
    want = tfm.transformer_apply(params, tokens, cfg=cfg)
    fwd = tfm.make_sharded_apply(cfg, mesh, attn=attn)
    got = fwd(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.heavy
def test_grad_accum_matches_whole_tile(mesh, cfg):
    """make_train_step(grad_accum=2): identical loss/params to the
    un-accumulated step (mean of equal microbatch grads ≡ grad of the
    mean loss), with remat on — the two memory levers must compose."""
    rng = np.random.RandomState(6)
    b, l = 8, 64
    seq = rng.randint(0, cfg.vocab, (b, l + 1))
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    targets = jnp.asarray(seq[:, 1:], jnp.int32)
    rcfg = tfm.TransformerConfig(**{**cfg.__dict__, "remat": True})
    params = tfm.init_transformer(jax.random.PRNGKey(8), rcfg)
    opt = optax.sgd(0.1)
    td = tfm.shard_batch(mesh, tokens, targets)

    outs = {}
    for accum in (1, 2):
        step = tfm.make_train_step(rcfg, mesh, opt, attn="ring",
                                   grad_accum=accum)
        p0 = jax.tree.map(jnp.copy, params)
        p, _, loss = step(p0, opt.init(p0), *td)
        outs[accum] = (float(loss), p)
    assert abs(outs[1][0] - outs[2][0]) < 2e-6
    for k in outs[1][1]:
        np.testing.assert_allclose(np.asarray(outs[1][1][k]),
                                   np.asarray(outs[2][1][k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


@pytest.mark.heavy
def test_zigzag_step_is_dropin_for_ring(mesh, cfg):
    """attn='zigzag' must be loss- and grad-equivalent to the contiguous
    ring (the permutation is internal; the loss is a token mean)."""
    rng = np.random.RandomState(5)
    b, l = 4, 64
    seq = rng.randint(0, cfg.vocab, (b, l + 1))
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    targets = jnp.asarray(seq[:, 1:], jnp.int32)
    params = tfm.init_transformer(jax.random.PRNGKey(3), cfg)
    opt = optax.sgd(0.1)
    tokens_d, targets_d = tfm.shard_batch(mesh, tokens, targets)

    outs = {}
    for attn in ("ring", "zigzag"):
        step = tfm.make_train_step(cfg, mesh, opt, attn=attn)
        # the step donates params/opt_state buffers — give each run its
        # own copies or the second run sees deleted arrays
        p0 = jax.tree.map(jnp.copy, params)
        p, _, loss = step(p0, opt.init(p0), tokens_d, targets_d)
        outs[attn] = (float(loss), p)
    assert abs(outs["ring"][0] - outs["zigzag"][0]) < 2e-5
    for k in outs["ring"][1]:
        np.testing.assert_allclose(np.asarray(outs["ring"][1][k]),
                                   np.asarray(outs["zigzag"][1][k]),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.heavy
def test_zigzag_pre_permuted_batch_matches_in_step_permutation(mesh, cfg):
    """zigzag_layout=True + shard_batch(schedule='zigzag'): identical
    loss/params to the default path that permutes inside the jitted
    step — the host-side pre-permutation is numerically invisible and
    removes the per-step cross-shard gather (VERDICT r2 item 8 /
    ADVICE r2)."""
    rng = np.random.RandomState(9)
    b, l = 4, 64
    seq = rng.randint(0, cfg.vocab, (b, l + 1))
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    targets = jnp.asarray(seq[:, 1:], jnp.int32)
    params = tfm.init_transformer(jax.random.PRNGKey(3), cfg)
    opt = optax.sgd(0.1)

    step_in = tfm.make_train_step(cfg, mesh, opt, attn="zigzag")
    p0 = jax.tree.map(jnp.copy, params)
    p_in, _, loss_in = step_in(p0, opt.init(p0),
                               *tfm.shard_batch(mesh, tokens, targets))

    step_pre = tfm.make_train_step(cfg, mesh, opt, attn="zigzag",
                                   zigzag_layout=True)
    p0 = jax.tree.map(jnp.copy, params)
    p_pre, _, loss_pre = step_pre(
        p0, opt.init(p0),
        *tfm.shard_batch(mesh, tokens, targets, schedule="zigzag"))

    assert abs(float(loss_in) - float(loss_pre)) < 1e-6
    for k in p_in:
        np.testing.assert_allclose(np.asarray(p_in[k]),
                                   np.asarray(p_pre[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)
    with pytest.raises(ValueError, match="requires attn"):
        tfm.make_train_step(cfg, mesh, opt, attn="ring",
                            zigzag_layout=True)


@pytest.mark.heavy
def test_train_step_learns_copy_task(mesh, cfg):
    """Sequence-parallel training on a deterministic pattern must reach
    low loss: sequences follow tok[t+1] = (tok[t] + 1) % vocab."""
    rng = np.random.RandomState(1)
    b, l = 8, 64
    start = rng.randint(0, cfg.vocab, (b, 1))
    seq = (start + np.arange(l + 1)) % cfg.vocab
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    targets = jnp.asarray(seq[:, 1:], jnp.int32)

    params = tfm.init_transformer(jax.random.PRNGKey(2), cfg)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    step = tfm.make_train_step(cfg, mesh, opt, attn="ring")
    tokens_d, targets_d = tfm.shard_batch(mesh, tokens, targets)

    losses = []
    for _ in range(60):
        params, opt_state, loss = step(params, opt_state, tokens_d,
                                       targets_d)
        losses.append(float(loss))
    assert losses[-1] < 0.5, losses[::10]
    assert losses[-1] < losses[0] / 4


def test_grads_cover_every_param(mesh, cfg):
    """The fused pmean backward must deliver a gradient for every
    parameter name (the grad-shuffle key-space invariant)."""
    tokens = _tokens(cfg, seed=3)
    targets = _tokens(cfg, seed=4)
    # the step donates its param buffers — snapshot to host first
    params = tfm.init_transformer(jax.random.PRNGKey(5), cfg)
    before = {k: np.asarray(v).copy() for k, v in params.items()}
    opt = optax.sgd(0.1)
    step = tfm.make_train_step(cfg, mesh, opt, attn="ulysses")
    new_params, _, loss = step(params, opt.init(params),
                               *tfm.shard_batch(mesh, tokens, targets))
    assert np.isfinite(float(loss))
    moved = [k for k in before
             if not np.allclose(before[k], np.asarray(new_params[k]))]
    assert set(moved) == set(before), set(before) - set(moved)


def test_seq_exceeding_max_seq_raises(mesh, cfg, params):
    long_tokens = jnp.zeros((2, cfg.max_seq + 4), jnp.int32)
    with pytest.raises(ValueError, match="max_seq"):
        tfm.transformer_apply(params, long_tokens, cfg=cfg)
    fwd = tfm.make_sharded_apply(cfg, mesh, attn="ring")
    with pytest.raises(ValueError, match="max_seq"):
        fwd(params, jnp.zeros((2, cfg.max_seq + 8), jnp.int32))


def test_unknown_attn_rejected_at_factory_time(mesh, cfg):
    with pytest.raises(ValueError, match="unknown attn"):
        tfm.make_train_step(cfg, mesh, optax.sgd(0.1), attn="rign")
    with pytest.raises(ValueError, match="unknown attn"):
        tfm.make_sharded_apply(cfg, mesh, attn="flash")


class Test3D:
    """dp x sp x mp (tensor-parallel) form vs the 2-D and oracle paths."""

    @pytest.fixture(scope="class")
    def mesh3(self):
        return jax.sharding.Mesh(
            np.array(jax.devices("cpu")[:8]).reshape(2, 2, 2),
            ("dp", "sp", "mp"))

    @pytest.mark.heavy
    def test_one_step_matches_2d_path(self, mesh3, cfg):
        """Same data, same init: one SGD step through the 3-D tp form
        must produce the same params as the 2-D (dp, sp) form."""
        rng = np.random.RandomState(0)
        b, l = 4, 32
        seq = rng.randint(0, cfg.vocab, (b, l + 1))
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)

        mesh2 = make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                          axis_names=("dp", "sp"))
        opt = optax.sgd(0.1)
        params0 = tfm.init_transformer(jax.random.PRNGKey(7), cfg)

        step2 = tfm.make_train_step(cfg, mesh2, opt, attn="ring")
        p2 = jax.tree.map(lambda x: jnp.array(x, copy=True), params0)
        p2, _, loss2 = step2(p2, opt.init(p2),
                             *tfm.shard_batch(mesh2, tokens, targets))

        step3 = tfm.make_train_step_3d(cfg, mesh3, opt, attn="ring")
        p3 = tfm.shard_params_3d(params0, mesh3, cfg)
        p3, _, loss3 = step3(p3, opt.init(p3),
                             *tfm.shard_batch(mesh3, tokens, targets))
        p3 = tfm.unshard_params_3d(p3, cfg)

        np.testing.assert_allclose(float(loss3), float(loss2), rtol=1e-5)
        for k in p2:
            np.testing.assert_allclose(
                np.asarray(p3[k]), np.asarray(p2[k]), rtol=2e-4,
                atol=2e-4, err_msg=k)

    @pytest.mark.heavy
    def test_3d_zigzag_matches_3d_ring(self, mesh3, cfg):
        """attn='zigzag' on the 3-D mesh: loss/params equivalent to the
        contiguous 3-D ring (internal permutation, token-mean loss)."""
        rng = np.random.RandomState(4)
        b, l = 4, 32
        seq = rng.randint(0, cfg.vocab, (b, l + 1))
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        opt = optax.sgd(0.1)
        params0 = tfm.init_transformer(jax.random.PRNGKey(9), cfg)

        outs = {}
        for attn in ("ring", "zigzag"):
            step = tfm.make_train_step_3d(cfg, mesh3, opt, attn=attn)
            p = tfm.shard_params_3d(
                jax.tree.map(jnp.copy, params0), mesh3, cfg)
            p, _, loss = step(p, opt.init(p),
                              *tfm.shard_batch(mesh3, tokens, targets))
            outs[attn] = (float(loss), tfm.unshard_params_3d(p, cfg))
        assert abs(outs["ring"][0] - outs["zigzag"][0]) < 2e-5
        for k in outs["ring"][1]:
            np.testing.assert_allclose(
                np.asarray(outs["ring"][1][k]),
                np.asarray(outs["zigzag"][1][k]),
                rtol=2e-4, atol=2e-4, err_msg=k)

    @pytest.mark.heavy
    def test_3d_grad_accum_matches_whole_tile(self, mesh3, cfg):
        rng = np.random.RandomState(11)
        b, l = 4, 32
        seq = rng.randint(0, cfg.vocab, (b, l + 1))
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        opt = optax.sgd(0.1)
        params0 = tfm.init_transformer(jax.random.PRNGKey(12), cfg)

        outs = {}
        for accum in (1, 2):
            step = tfm.make_train_step_3d(cfg, mesh3, opt, attn="ring",
                                          grad_accum=accum)
            p = tfm.shard_params_3d(
                jax.tree.map(jnp.copy, params0), mesh3, cfg)
            p, _, loss = step(p, opt.init(p),
                              *tfm.shard_batch(mesh3, tokens, targets))
            outs[accum] = (float(loss), tfm.unshard_params_3d(p, cfg))
        assert abs(outs[1][0] - outs[2][0]) < 2e-6
        for k in outs[1][1]:
            np.testing.assert_allclose(
                np.asarray(outs[1][1][k]), np.asarray(outs[2][1][k]),
                rtol=1e-5, atol=1e-6, err_msg=k)

    @pytest.mark.heavy
    def test_3d_training_learns(self, mesh3, cfg):
        rng = np.random.RandomState(1)
        b, l = 8, 32
        start = rng.randint(0, cfg.vocab, (b, 1))
        seq = (start + np.arange(l + 1)) % cfg.vocab
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        opt = optax.adam(3e-3)
        params = tfm.shard_params_3d(
            tfm.init_transformer(jax.random.PRNGKey(2), cfg), mesh3, cfg)
        step = tfm.make_train_step_3d(cfg, mesh3, opt, attn="ring")
        st = opt.init(params)
        td = tfm.shard_batch(mesh3, tokens, targets)
        first = None
        for _ in range(50):
            params, st, loss = step(params, st, *td)
            if first is None:
                first = float(loss)
        assert float(loss) < first / 3, (first, float(loss))

    def test_rejects_indivisible_heads(self, mesh3):
        bad = tfm.TransformerConfig(vocab=32, d_model=32, n_heads=3,
                                    n_layers=1, d_ff=32, max_seq=64)
        with pytest.raises(ValueError, match="not divisible"):
            tfm.make_train_step_3d(bad, mesh3, optax.sgd(0.1))


class TestMoE:
    """Expert-parallel transformer: switch-MoE FFN with experts over dp."""

    @pytest.fixture(scope="class")
    def moe_cfg(self):
        return tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=128, moe_experts=8, moe_capacity=256)

    def test_sharded_forward_matches_oracle(self):
        """Generous capacity (no drops) → routing is per-token, so the
        ep-sharded forward equals the single-device oracle exactly.
        Default-suite shape (ADVICE r5): shrunk from the class cfg so
        this end-to-end MoE golden diff runs on every `pytest tests/`,
        not only under --full."""
        cfg = tfm.TransformerConfig(
            vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq=64, moe_experts=4, moe_capacity=128)  # = b*l: no drops
        mesh2 = make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                          axis_names=("dp", "sp"))
        params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
        tokens = _tokens(cfg, b=4, l=32)    # b divisible by dp=4
        want = tfm.transformer_apply(params, tokens, cfg=cfg)
        fwd = tfm.make_sharded_apply(cfg, mesh2, attn="ring")
        got = fwd(tfm.shard_params_moe(params, mesh2), tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-4, atol=3e-4)

    @pytest.mark.heavy
    def test_moe_training_learns(self, moe_cfg):
        mesh2 = make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                          axis_names=("dp", "sp"))
        rng = np.random.RandomState(1)
        b, l = 8, 64
        start = rng.randint(0, moe_cfg.vocab, (b, 1))
        seq = (start + np.arange(l + 1)) % moe_cfg.vocab
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        opt = optax.adam(3e-3)
        params = tfm.shard_params_moe(
            tfm.init_transformer(jax.random.PRNGKey(2), moe_cfg), mesh2)
        step = tfm.make_train_step(moe_cfg, mesh2, opt, attn="ring")
        st = opt.init(params)
        td = tfm.shard_batch(mesh2, tokens, targets)
        first = None
        for _ in range(60):
            params, st, loss = step(params, st, *td)
            if first is None:
                first = float(loss)
        assert float(loss) < first / 3, (first, float(loss))

    def test_rejects_indivisible_experts(self, moe_cfg):
        mesh2 = make_mesh(dp=8, mp=1, devices=jax.devices("cpu")[:8],
                          axis_names=("dp", "sp"))
        bad = tfm.TransformerConfig(vocab=32, d_model=32, n_heads=4,
                                    n_layers=1, d_ff=32, max_seq=64,
                                    moe_experts=6, moe_capacity=16)
        with pytest.raises(ValueError, match="not divisible"):
            tfm.make_train_step(bad, mesh2, optax.sgd(0.1))

    def test_capacity_required_with_experts(self):
        nocap = tfm.TransformerConfig(vocab=32, d_model=32, n_heads=4,
                                      n_layers=1, d_ff=32, max_seq=64,
                                      moe_experts=4)
        with pytest.raises(ValueError, match="moe_capacity"):
            tfm.init_transformer(jax.random.PRNGKey(0), nocap)

    def test_moe_rejected_on_3d_path(self, moe_cfg):
        mesh3 = jax.sharding.Mesh(
            np.array(jax.devices("cpu")[:8]).reshape(2, 2, 2),
            ("dp", "sp", "mp"))
        with pytest.raises(ValueError, match="not supported"):
            tfm.make_train_step_3d(moe_cfg, mesh3, optax.sgd(0.1))


class TestPipeline:
    """Pipeline-parallel (GPipe) form: stages over pp, AD-transposed
    backward schedule."""

    @pytest.fixture(scope="class")
    def pp_cfg(self):
        return tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                     n_layers=4, d_ff=64, max_seq=128)

    @pytest.fixture(scope="class")
    def pp_mesh(self):
        return jax.sharding.Mesh(
            np.array(jax.devices("cpu")[:4]), ("pp",))

    @pytest.mark.heavy
    def test_one_step_matches_single_device(self, pp_cfg, pp_mesh):
        """One SGD step through the 4-stage pipeline == the same step on
        one device (same data, same init) — forward AND backward."""
        rng = np.random.RandomState(0)
        b, l = 8, 32
        seq = rng.randint(0, pp_cfg.vocab, (b, l + 1))
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        params0 = tfm.init_transformer(jax.random.PRNGKey(3), pp_cfg)
        opt = optax.sgd(0.1)

        # single-device oracle step
        def loss_fn(p):
            logp = jax.nn.log_softmax(
                tfm.transformer_apply(p, tokens, cfg=pp_cfg), axis=-1)
            return -jnp.mean(jnp.take_along_axis(
                logp, targets[..., None], axis=-1))

        l_ref, g_ref = jax.value_and_grad(loss_fn)(params0)
        up, _ = opt.update(g_ref, opt.init(params0))
        p_ref = optax.apply_updates(params0, up)

        step = tfm.make_train_step_pp(pp_cfg, pp_mesh, opt, n_micro=4)
        pp = tfm.shard_params_pp(params0, pp_mesh, pp_cfg)
        pp, _, l_pp = step(pp, opt.init(pp), tokens, targets)
        got = tfm.unstack_params_pp(pp, pp_cfg)

        np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
        for k in p_ref:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(p_ref[k]), rtol=2e-4,
                                       atol=2e-4, err_msg=k)

    @pytest.mark.heavy
    def test_pipeline_training_learns(self, pp_cfg, pp_mesh):
        rng = np.random.RandomState(1)
        b, l = 8, 32
        start = rng.randint(0, pp_cfg.vocab, (b, 1))
        seq = (start + np.arange(l + 1)) % pp_cfg.vocab
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        opt = optax.adam(3e-3)
        params = tfm.shard_params_pp(
            tfm.init_transformer(jax.random.PRNGKey(4), pp_cfg),
            pp_mesh, pp_cfg)
        step = tfm.make_train_step_pp(pp_cfg, pp_mesh, opt, n_micro=4)
        st = opt.init(params)
        first = None
        for _ in range(50):
            params, st, loss = step(params, st, tokens, targets)
            if first is None:
                first = float(loss)
        assert float(loss) < first / 3, (first, float(loss))

    def test_validations(self, pp_cfg, pp_mesh):
        bad = tfm.TransformerConfig(vocab=32, d_model=32, n_heads=4,
                                    n_layers=3, d_ff=32, max_seq=64)
        with pytest.raises(ValueError, match="not divisible"):
            tfm.make_train_step_pp(bad, pp_mesh, optax.sgd(0.1),
                                   n_micro=2)
        moe = tfm.TransformerConfig(vocab=32, d_model=32, n_heads=4,
                                    n_layers=4, d_ff=32, max_seq=64,
                                    moe_experts=4, moe_capacity=8)
        with pytest.raises(ValueError, match="dense blocks only"):
            tfm.make_train_step_pp(moe, pp_mesh, optax.sgd(0.1),
                                   n_micro=2)


def test_remat_matches_non_remat_grads():
    """cfg.remat recomputes blocks in backward — loss and grads must be
    IDENTICAL to the saved-activation path (same math, less memory).
    Default-suite shape (ADVICE r5): shortened sequence — the oracle
    property is shape-independent, so this golden diff stays in every
    `pytest tests/` run."""
    import dataclasses

    cfg = tfm.TransformerConfig.tiny()
    cfg_r = dataclasses.replace(cfg, remat=True)
    params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    seq = rng.randint(0, cfg.vocab, (2, 9))
    tok = jnp.asarray(seq[:, :-1], jnp.int32)
    tgt = jnp.asarray(seq[:, 1:], jnp.int32)

    import functools

    def loss(c):
        attn = functools.partial(tfm.attention_reference, causal=True)
        pos = jnp.arange(tok.shape[1])

        def f(p):
            return tfm.lm_loss_local(p, tok, tgt, c, attn, pos)
        return jax.value_and_grad(f)(params)

    l0, g0 = loss(cfg)
    l1, g1 = loss(cfg_r)
    assert np.allclose(float(l0), float(l1), rtol=1e-6)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]),
                                   rtol=2e-5, atol=1e-6, err_msg=k)


@pytest.mark.heavy
def test_remat_composes_with_sequence_parallel(mesh):
    """remat under the sharded sp form: one train step runs and matches
    the non-remat step's loss (collectives re-executed in backward)."""
    import dataclasses

    cfg = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                n_layers=2, d_ff=32, max_seq=64)
    rng = np.random.RandomState(1)
    seq = rng.randint(0, cfg.vocab, (4, 17))
    tok = jnp.asarray(seq[:, :-1], jnp.int32)
    tgt = jnp.asarray(seq[:, 1:], jnp.int32)
    opt = optax.sgd(0.05)

    losses = {}
    for name, c in (("plain", cfg),
                    ("remat", dataclasses.replace(cfg, remat=True))):
        # fresh params per variant: the step donates its param buffers
        params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
        step = tfm.make_train_step(c, mesh, opt, attn="ring")
        _, _, loss = step(params, opt.init(params),
                          *tfm.shard_batch(mesh, tok, tgt))
        losses[name] = float(loss)
    assert np.allclose(losses["plain"], losses["remat"], rtol=1e-6)


def test_flops_per_token_accounting():
    """MFU numerator sanity: hand-counted matmul FLOPs for a small cfg."""
    from lua_mapreduce_tpu.models.transformer import (TransformerConfig,
                                                      flops_per_token)
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=128, max_seq=64)
    d, dff, L = 32, 128, 16
    fwd = 2 * (8 * d * d + 4 * L * d * 0.5 + 4 * d * dff) + 2 * d * 64
    assert flops_per_token(cfg, L) == 3.0 * fwd
    # non-causal doubles only the attention term
    delta = flops_per_token(cfg, L, causal=False) - flops_per_token(cfg, L)
    assert delta == 3.0 * 2 * (2.0 * L * d)


class TestGreedyDecode:
    """KV-cached decode vs the no-cache oracle: identical tokens."""

    def test_matches_full_forward_rerun(self, cfg):
        # default-suite shape (ADVICE r5): fewer decode steps — each
        # naive-rerun prefix length is its own XLA compile, so the step
        # count, not the model, is the cost; the KV-cache-vs-oracle
        # golden diff itself is length-independent
        rng = np.random.RandomState(13)
        params = tfm.init_transformer(jax.random.PRNGKey(13), cfg)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (2, 5)), jnp.int32)
        n_new = 4
        got = tfm.greedy_decode(params, prompt, n_new, cfg=cfg)
        assert got.shape == (2, 9)
        assert np.array_equal(np.asarray(got[:, :5]), np.asarray(prompt))

        # naive loop: re-run the FULL forward at every prefix
        toks = prompt
        for _ in range(n_new):
            logits = tfm.transformer_apply(params, toks, cfg=cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        assert np.array_equal(np.asarray(got), np.asarray(toks))

    @pytest.mark.heavy
    def test_trained_model_continues_pattern(self, mesh, cfg):
        """Train on tok[t+1] = tok[t] + 1 (mod vocab), then decode: the
        continuation must follow the arithmetic pattern."""
        rng = np.random.RandomState(14)
        b, l = 8, 64
        start = rng.randint(0, cfg.vocab, (b, 1))
        seq = (start + np.arange(l + 1)) % cfg.vocab
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        params = tfm.init_transformer(jax.random.PRNGKey(2), cfg)
        opt = optax.adam(3e-3)
        opt_state = opt.init(params)
        step = tfm.make_train_step(cfg, mesh, opt, attn="ring")
        td = tfm.shard_batch(mesh, tokens, targets)
        for _ in range(60):
            params, opt_state, _ = step(params, opt_state, *td)

        prompt = jnp.asarray((np.arange(8) + 3) % cfg.vocab,
                             jnp.int32)[None, :]
        out = np.asarray(tfm.greedy_decode(params, prompt, 8, cfg=cfg))[0]
        want = (np.arange(16) + 3) % cfg.vocab
        # chance is 1/64 per token; ≥half right after 60 tiny-model
        # steps demonstrates the decode drives a LEARNED continuation
        acc = float(np.mean(out[8:] == want[8:]))
        assert acc >= 0.5, (out.tolist(), want.tolist())

    @pytest.mark.heavy
    def test_sampling(self, cfg):
        params = tfm.init_transformer(jax.random.PRNGKey(20), cfg)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        k = jax.random.PRNGKey(0)
        a = tfm.greedy_decode(params, prompt, 6, cfg=cfg,
                              temperature=1.0, key=k)
        b = tfm.greedy_decode(params, prompt, 6, cfg=cfg,
                              temperature=1.0, key=k)
        assert np.array_equal(np.asarray(a), np.asarray(b))  # per-key det.
        assert np.all(np.asarray(a) < cfg.vocab)
        c = tfm.greedy_decode(params, prompt, 6, cfg=cfg, temperature=1.0,
                              key=jax.random.PRNGKey(9), top_k=3)
        assert c.shape == (1, 10)
        # near-zero temperature concentrates on the argmax → greedy
        d = tfm.greedy_decode(params, prompt, 6, cfg=cfg,
                              temperature=1e-4, key=k)
        g = tfm.greedy_decode(params, prompt, 6, cfg=cfg)
        assert np.array_equal(np.asarray(d), np.asarray(g))
        with pytest.raises(ValueError, match="PRNG"):
            tfm.greedy_decode(params, prompt, 2, cfg=cfg, temperature=0.5)

    def test_prefill_matches_scan_decode(self, cfg):
        """use_prefill=True (batched prompt ingestion) produces the
        same tokens as the from-scratch position scan — greedy AND
        sampled (shared fold_in(key, t) stream)."""
        rng = np.random.RandomState(21)
        params = tfm.init_transformer(jax.random.PRNGKey(21), cfg)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (3, 7)), jnp.int32)
        a = tfm.greedy_decode(params, prompt, 6, cfg=cfg)
        b = tfm.greedy_decode(params, prompt, 6, cfg=cfg,
                              use_prefill=True)
        assert np.array_equal(np.asarray(a), np.asarray(b))
        k = jax.random.PRNGKey(3)
        c = tfm.greedy_decode(params, prompt, 6, cfg=cfg,
                              temperature=0.9, key=k)
        d = tfm.greedy_decode(params, prompt, 6, cfg=cfg,
                              temperature=0.9, key=k, use_prefill=True)
        assert np.array_equal(np.asarray(c), np.asarray(d))
        # n_new edge cases
        assert tfm.greedy_decode(params, prompt, 0, cfg=cfg,
                                 use_prefill=True).shape == (3, 7)
        e = tfm.greedy_decode(params, prompt, 1, cfg=cfg,
                              use_prefill=True)
        assert np.array_equal(np.asarray(e), np.asarray(
            tfm.greedy_decode(params, prompt, 1, cfg=cfg)))

    @pytest.mark.heavy
    def test_prefill_sharded_matches_single_device(self, mesh, cfg):
        """Sequence-parallel prefill (ring + zigzag over the mesh)
        yields the same caches/logits — and therefore tokens — as the
        single-device prefill."""
        rng = np.random.RandomState(22)
        params = tfm.init_transformer(jax.random.PRNGKey(22), cfg)
        # zigzag needs p_len % (2*sp) == 0
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (2, 16)),
                             jnp.int32)
        want_c, want_l = tfm.prefill(params, prompt, cfg=cfg, total=20)
        for attn in ("ring", "zigzag"):
            got_c, got_l = tfm.prefill(params, prompt, cfg=cfg,
                                       total=20, mesh=mesh, attn=attn)
            np.testing.assert_allclose(np.asarray(got_l),
                                       np.asarray(want_l),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=attn)
            for name in want_c:
                np.testing.assert_allclose(
                    np.asarray(got_c[name]), np.asarray(want_c[name]),
                    rtol=2e-4, atol=2e-4, err_msg=f"{attn}:{name}")
        out = tfm.greedy_decode(params, prompt, 4, cfg=cfg,
                                use_prefill=True, mesh=mesh, attn="ring")
        ref = tfm.greedy_decode(params, prompt, 4, cfg=cfg)
        assert np.array_equal(np.asarray(out), np.asarray(ref))

    @pytest.mark.heavy
    def test_prefill_moe_sharded_rejected(self, mesh):
        moe_cfg = tfm.TransformerConfig(vocab=16, d_model=16, n_heads=2,
                                        n_layers=1, d_ff=32, max_seq=32,
                                        moe_experts=2, moe_capacity=64)
        params = tfm.init_transformer(jax.random.PRNGKey(0), moe_cfg)
        prompt = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="dense"):
            tfm.prefill(params, prompt, cfg=moe_cfg, mesh=mesh)
        # single-device MoE prefill works (whole-prompt routing group)
        caches, logits = tfm.prefill(params, prompt, cfg=moe_cfg)
        assert logits.shape == (1, 16)
        assert caches["L0_k"].shape == (1, 8, 2, 8)
        # explicit total=0 must hit the guard, not silently mean p_len
        with pytest.raises(ValueError, match="shorter than the prompt"):
            tfm.prefill(params, prompt, cfg=moe_cfg, total=0)

    def test_moe_capacity_required(self):
        """A capacity-less MoE config must fail loudly at decode time
        just as it does at init/train time (the decode MoE path itself
        is golden-diffed in tests/test_moe.py)."""
        moe_cfg = tfm.TransformerConfig(vocab=16, d_model=16, n_heads=2,
                                        n_layers=1, d_ff=32, max_seq=32,
                                        moe_experts=2, moe_capacity=0)
        ok_cfg = dataclasses.replace(moe_cfg, moe_capacity=8)
        params = tfm.init_transformer(jax.random.PRNGKey(0), ok_cfg)
        with pytest.raises(ValueError, match="moe_capacity"):
            tfm.greedy_decode(params, jnp.zeros((1, 4), jnp.int32), 2,
                              cfg=moe_cfg)


def test_moe_with_grad_accum_rejected(mesh):
    moe_cfg = tfm.TransformerConfig(vocab=16, d_model=16, n_heads=4,
                                    n_layers=1, d_ff=32, max_seq=64,
                                    moe_experts=2, moe_capacity=8)
    with pytest.raises(ValueError, match="grad_accum"):
        tfm.make_train_step(moe_cfg, mesh, optax.sgd(0.1), grad_accum=2)


def test_empty_prompt_rejected(cfg):
    params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="at least one token"):
        tfm.greedy_decode(params, jnp.zeros((1, 0), jnp.int32), 4, cfg=cfg)
