"""Sliding-window attention (the mistral-style long-context lever):
flash kernel fwd/bwd + oracle + decode + prefill, windowed masks pinned
against a hand-written oracle; unsupported forms fail loudly."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.models import transformer as tfm
from lua_mapreduce_tpu.ops.attention import flash_attention

W = 37


def _manual(q, k, v, w):
    g = q.shape[2] // k.shape[2]
    kf, vf = jnp.repeat(k, g, 2), jnp.repeat(v, g, 2)
    l = q.shape[1]
    s = jnp.einsum("blhd,bmhd->bhlm", q, kf) / jnp.sqrt(q.shape[-1])
    rows, cols = jnp.arange(l)[:, None], jnp.arange(l)[None, :]
    s = jnp.where((rows >= cols) & (rows - cols < w), s, -1e30)
    return jnp.einsum("bhlm,bmhd->blhd", jax.nn.softmax(s, -1), vf)


class TestKernel:
    def test_fwd_matches_manual_oracle(self):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(2, 200, 4, 16), jnp.float32) * 0.5
        k = jnp.asarray(rng.randn(2, 200, 2, 16), jnp.float32) * 0.5
        v = jnp.asarray(rng.randn(2, 200, 2, 16), jnp.float32) * 0.5
        want = _manual(q, k, v, W)
        for be in ("xla", "pallas_interpret"):
            got = flash_attention(q, k, v, causal=True, window=W,
                                  backend=be, block_q=32, block_k=128)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5, err_msg=be)

    def test_grads_match_xla_vjp(self):
        """Windowed backward: the tile-skip predicate and in-tile mask
        must agree between fwd and bwd (a drift would show as grads of
        masked positions leaking)."""
        rng = np.random.RandomState(1)
        q, k, v = (jnp.asarray(rng.randn(2, 200, 4, 16),
                               jnp.float32) * 0.5 for _ in range(3))

        def loss(be):
            return lambda q, k, v: jnp.sum(flash_attention(
                q, k, v, causal=True, window=W, backend=be,
                block_q=32, block_k=128) ** 2)

        gp = jax.grad(loss("pallas_interpret"), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{name}")

    def test_window_requires_causal(self):
        q = jnp.zeros((1, 8, 2, 4))
        with pytest.raises(ValueError, match="implies"):
            flash_attention(q, q, q, window=4)

    def test_op_level_bad_args_rejected(self):
        """Negative window / q_offset and orphan q_offset fail at the
        OP boundary (the config path has its own check — code-review
        r3 caught the op-level guard dropped in a refactor)."""
        q = jnp.zeros((1, 8, 2, 4))
        with pytest.raises(ValueError, match="window must be"):
            flash_attention(q, q, q, causal=True, window=-1)
        with pytest.raises(ValueError, match="q_offset only"):
            flash_attention(q, q, q, causal=True, q_offset=4)
        with pytest.raises(ValueError, match="q_offset must be"):
            flash_attention(q, q, q, causal=True, window=4, q_offset=-2)
        from lua_mapreduce_tpu.parallel.ring_attention import \
            ring_attention
        from lua_mapreduce_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(dp=1, mp=2, devices=jax.devices("cpu")[:2],
                         axis_names=("dp", "sp"))
        q2 = jnp.zeros((1, 16, 2, 4))
        with pytest.raises(ValueError, match="window must be"):
            ring_attention(q2, q2, q2, mesh, axis="sp", causal=True,
                           window=-3)

    def test_window_one_sees_only_self(self):
        """window=1: every position attends only itself — output is
        exactly v (softmax over a single score)."""
        rng = np.random.RandomState(2)
        q, k, v = (jnp.asarray(rng.randn(1, 16, 2, 8),
                               jnp.float32) for _ in range(3))
        got = flash_attention(q, k, v, causal=True, window=1,
                              backend="pallas_interpret")
        np.testing.assert_allclose(np.asarray(got), np.asarray(v),
                                   rtol=1e-5, atol=1e-5)


@pytest.fixture()
def cfg():
    return tfm.TransformerConfig.llama_style(
        vocab=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=48, max_seq=128, window=8)


class TestModel:

    def test_oracle_windowed_differs_from_full(self, cfg):
        """The window genuinely changes the model (long-range context
        is cut off) while matching the full model inside the window."""
        full = dataclasses.replace(cfg, window=0)
        params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 64)),
                           jnp.int32)
        lw = tfm.transformer_apply(params, toks, cfg=cfg)
        lf = tfm.transformer_apply(params, toks, cfg=full)
        # first `window` positions see identical context
        np.testing.assert_allclose(np.asarray(lw[:, :8]),
                                   np.asarray(lf[:, :8]),
                                   rtol=1e-5, atol=1e-5)
        assert np.abs(np.asarray(lw[:, 20:]) -
                      np.asarray(lf[:, 20:])).max() > 1e-3

    @pytest.mark.heavy
    def test_decode_matches_full_forward(self, cfg):
        params = tfm.init_transformer(jax.random.PRNGKey(2), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, 64, (3, 12)), jnp.int32)
        n_new = 8
        got = tfm.greedy_decode(params, prompt, n_new, cfg=cfg)
        toks = prompt
        for _ in range(n_new):
            logits = tfm.transformer_apply(params, toks, cfg=cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        assert np.array_equal(np.asarray(got), np.asarray(toks))

    def test_prefill_decode_matches_scan(self, cfg):
        params = tfm.init_transformer(jax.random.PRNGKey(4), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(0, 64, (2, 16)), jnp.int32)
        a = tfm.greedy_decode(params, prompt, 6, cfg=cfg)
        b = tfm.greedy_decode(params, prompt, 6, cfg=cfg,
                              use_prefill=True)
        assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.heavy
    def test_rolling_cache_short_prompt(self, cfg):
        """Prompt SHORTER than the window: rolling slots beyond the
        prompt stay masked until filled; prefill and scan agree with
        the full-forward rerun (the window=8 cfg with p_len=5)."""
        params = tfm.init_transformer(jax.random.PRNGKey(6), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(7).randint(0, 64, (3, 5)), jnp.int32)
        n_new = 10               # generation crosses the w=8 boundary
        got = tfm.greedy_decode(params, prompt, n_new, cfg=cfg)
        pre = tfm.greedy_decode(params, prompt, n_new, cfg=cfg,
                                use_prefill=True)
        toks = prompt
        for _ in range(n_new):
            logits = tfm.transformer_apply(params, toks, cfg=cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        assert np.array_equal(np.asarray(got), np.asarray(toks))
        assert np.array_equal(np.asarray(pre), np.asarray(toks))

    def test_non_ring_parallel_forms_reject_window(self, cfg):
        """Windowed sequence-parallel runs ONLY as the banded ring;
        zigzag/ulysses reject (zigzag balances work a window already
        bounds; ulysses holds full-sequence heads)."""
        from lua_mapreduce_tpu.parallel.mesh import make_mesh
        import optax
        mesh = make_mesh(dp=2, mp=2, devices=jax.devices("cpu")[:4],
                         axis_names=("dp", "sp"))
        for attn in ("zigzag", "ulysses"):
            with pytest.raises(ValueError, match="(?i)banded"):
                tfm.make_train_step(cfg, mesh, optax.sgd(0.1), attn=attn)
            with pytest.raises(ValueError, match="(?i)banded"):
                tfm.make_sharded_apply(cfg, mesh, attn=attn)
        params = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((2, 16), jnp.int32)
        with pytest.raises(ValueError, match="(?i)banded"):
            tfm.prefill(params, prompt, cfg=cfg, mesh=mesh,
                        attn="zigzag")


class TestBandedRing:
    """Windowed SEQUENCE-PARALLEL attention: the banded ring unrolls
    its hops (static per-hop mask offsets for the kernel) and stops at
    ceil((w-1)/L_loc) hops — golden-diffed against the windowed
    oracle, gradients included."""

    @pytest.fixture(scope="class")
    def mesh(self):
        from lua_mapreduce_tpu.parallel.mesh import make_mesh
        return make_mesh(dp=1, mp=8, devices=jax.devices("cpu")[:8],
                         axis_names=("dp", "sp"))

    @pytest.mark.parametrize("w", [1, 5, 16, 40, 128],
                             ids=lambda w: f"w{w}")
    def test_standalone_matches_windowed_oracle(self, mesh, w):
        """Windows smaller than, equal to, and larger than L_loc=16 —
        0, 1, 3, and all hops of the 8-shard ring respectively."""
        from lua_mapreduce_tpu.parallel import ring_attention as ra
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(2, 128, 4, 16),
                               jnp.float32) * 0.5 for _ in range(3))
        want = ra.attention_reference(q, k, v, causal=True, window=w)
        got = ra.ring_attention(q, k, v, mesh, axis="sp", causal=True,
                                window=w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-5, atol=3e-5)

    @pytest.mark.heavy
    def test_gradients_match_windowed_oracle(self, mesh):
        from lua_mapreduce_tpu.parallel import ring_attention as ra
        rng = np.random.RandomState(1)
        q, k, v = (jnp.asarray(rng.randn(1, 64, 2, 8),
                               jnp.float32) * 0.5 for _ in range(3))

        def ring_loss(q):
            return jnp.sum(ra.ring_attention(
                q, k, v, mesh, axis="sp", causal=True, window=13) ** 2)

        def ref_loss(q):
            return jnp.sum(ra.attention_reference(
                q, k, v, causal=True, window=13) ** 2)

        np.testing.assert_allclose(
            np.asarray(jax.grad(ring_loss)(q)),
            np.asarray(jax.grad(ref_loss)(q)), rtol=1e-4, atol=1e-4)

    @pytest.mark.heavy
    def test_train_step_windowed_matches_oracle_loss(self, cfg):
        """make_train_step(attn='ring') with cfg.window: first-step
        loss equals the windowed oracle's mean NLL."""
        import optax
        from lua_mapreduce_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(dp=2, mp=2, devices=jax.devices("cpu")[:4],
                         axis_names=("dp", "sp"))
        rng = np.random.RandomState(2)
        seq = rng.randint(0, 64, (4, 33))
        toks = jnp.asarray(seq[:, :-1], jnp.int32)
        tgts = jnp.asarray(seq[:, 1:], jnp.int32)
        params = tfm.init_transformer(jax.random.PRNGKey(3), cfg)
        logits = tfm.transformer_apply(params, toks, cfg=cfg)
        logp = jax.nn.log_softmax(logits)
        want = -float(jnp.mean(
            jnp.take_along_axis(logp, tgts[..., None], -1)))
        opt = optax.sgd(0.1)
        step = tfm.make_train_step(cfg, mesh, opt, attn="ring")
        _, _, loss = step(params, opt.init(params),
                          *tfm.shard_batch(mesh, toks, tgts))
        assert abs(float(loss) - want) < 2e-5, (float(loss), want)

    @pytest.mark.heavy
    def test_sharded_windowed_prefill(self, cfg):
        from lua_mapreduce_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(dp=2, mp=2, devices=jax.devices("cpu")[:4],
                         axis_names=("dp", "sp"))
        params = tfm.init_transformer(jax.random.PRNGKey(4), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(0, 64, (2, 16)), jnp.int32)
        ref = tfm.greedy_decode(params, prompt, 5, cfg=cfg)
        got = tfm.greedy_decode(params, prompt, 5, cfg=cfg,
                                use_prefill=True, mesh=mesh, attn="ring")
        assert np.array_equal(np.asarray(got), np.asarray(ref))

    def test_negative_window_rejected(self, cfg):
        bad = dataclasses.replace(cfg, window=-1)
        with pytest.raises(ValueError, match="window"):
            tfm.init_transformer(jax.random.PRNGKey(0), bad)

    @pytest.mark.heavy
    def test_pipeline_supports_window(self, cfg):
        """pp doesn't shard the sequence, so windowed attention works
        there — and the pp loss must equal the oracle's (same mask)."""
        import optax
        from jax.sharding import Mesh
        mha = dataclasses.replace(cfg, n_kv_heads=0)
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("pp",))
        params = tfm.init_transformer(jax.random.PRNGKey(6), mha)
        opt = optax.sgd(0.05)
        step = tfm.make_train_step_pp(mha, mesh, opt, n_micro=2)
        rng = np.random.RandomState(7)
        seq = rng.randint(0, 64, (4, 33))
        toks = jnp.asarray(seq[:, :-1], jnp.int32)
        tgts = jnp.asarray(seq[:, 1:], jnp.int32)
        # oracle loss FIRST: the pp step donates its buffers, and the
        # stacked dict shares the embedding arrays with `params`
        logits = tfm.transformer_apply(params, toks, cfg=mha)
        logp = jax.nn.log_softmax(logits)
        want = -float(jnp.mean(
            jnp.take_along_axis(logp, tgts[..., None], -1)))
        stacked = tfm.shard_params_pp(params, mesh, mha)
        _, _, loss = step(stacked, opt.init(stacked), toks, tgts)
        assert abs(float(loss) - want) < 2e-5, (float(loss), want)

    def test_flops_accounting_windowed(self, cfg):
        """Windowed MFU numerator counts only visible keys (the kernel
        prunes the rest): mean visible = (Σ min(i, w)) / L."""
        full = dataclasses.replace(cfg, window=0)
        l, w, d = 64, 8, cfg.d_model
        diff = (tfm.flops_per_token(full, l) -
                tfm.flops_per_token(cfg, l))
        visible = (w * (w + 1) / 2 + (l - w) * w) / l
        want = 3.0 * cfg.n_layers * (4.0 * l * d * 0.5 -
                                     4.0 * d * visible)
        assert abs(diff - want) < 1e-6


class TestEmptyRows:
    @pytest.mark.heavy
    def test_rows_past_window_emit_zero_both_backends(self):
        """Banded-ring far-block geometry: q rows pushed more than
        `window` past every kv column have an EMPTY visible set. The
        kernel emits zeros (lse ~ -inf, so ring merges weight the
        partial out); the XLA oracle must match instead of returning
        softmax's meaningless uniform average over an all-masked row —
        the two paths' convention for empty rows is part of the
        contract now (round 4: found by driving block_q=64 with a
        misaligned offset; no prior test had an empty row)."""
        import jax
        import jax.numpy as jnp

        from lua_mapreduce_tpu.ops import flash_attention

        kq, kk, kv = jax.random.split(jax.random.PRNGKey(5), 3)
        q = jax.random.normal(kq, (1, 160, 2, 64))
        k = jax.random.normal(kk, (1, 160, 2, 64))
        v = jax.random.normal(kv, (1, 160, 2, 64))
        kw = dict(causal=True, window=50, q_offset=128)
        a = flash_attention(q, k, v, backend="pallas_interpret",
                            block_q=64, **kw)
        b = flash_attention(q, k, v, backend="xla", **kw)
        # rows 0..21 (global 128..149) still see keys; global rows from
        # 160+50-1... exactly: global row r sees cols (r-50, min(r, 159)];
        # empty once r - 50 >= 160 - 1 -> r >= 209 -> local row >= 81
        assert float(jnp.max(jnp.abs(a - b))) < 3e-5
        tail = jnp.abs(a[0, 90:])                 # deep in the empty zone
        assert float(tail.max()) == 0.0, "empty rows must emit zero"
        # gradients agree too (empty rows contribute nothing)
        ga = jax.grad(lambda *x: flash_attention(
            *x, backend="pallas_interpret", block_q=64, **kw).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(lambda *x: flash_attention(
            *x, backend="xla", **kw).sum(), argnums=(0, 1, 2))(q, k, v)
        for x, y in zip(ga, gb):
            assert float(jnp.max(jnp.abs(x - y))) < 1e-3
