"""Iterative-state workloads (BASELINE.json config 5): k-means and ALS.

Three angles per algorithm, mirroring the golden-diff discipline of the
reference's test.sh (SURVEY.md §4):
- the TPU-native jitted fit converges on synthetic data,
- the mesh-sharded run agrees with the single-device run,
- the six-function MapReduce packaging (persistent_table state) agrees
  with the TPU-native fit.
"""

import numpy as np
import pytest

from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.models import als, kmeans
from lua_mapreduce_tpu.parallel.mesh import host_mesh
from lua_mapreduce_tpu.train.data import make_blobs, make_ratings


@pytest.fixture(scope="module")
def mesh():
    return host_mesh(8)


@pytest.fixture(scope="module")
def blobs():
    return make_blobs(seed=3, n=2048, k=8, dim=16)


@pytest.fixture(scope="module")
def ratings():
    return make_ratings(seed=4, n_users=256, n_items=64, rank=4)


class TestKMeansNative:
    def test_recovers_centers_and_monotone_inertia(self, blobs):
        x, _, centers = blobs
        res = kmeans.kmeans_fit(x, kmeans.init_centroids(
            __import__("jax").random.PRNGKey(0), x, 8), n_iters=25)
        hist = np.asarray(res.history)
        assert (np.diff(hist) <= 1e-3).all(), "Lloyd inertia must not rise"
        # every true center has a fitted centroid nearby
        d = np.linalg.norm(np.asarray(res.centroids)[None, :, :]
                           - centers[:, None, :], axis=-1)
        assert d.min(axis=1).max() < 0.25, d.min(axis=1)

    def test_mesh_matches_single_device(self, blobs, mesh):
        x = blobs[0]
        c0 = x[:8]
        single = kmeans.kmeans_fit(x, c0, n_iters=10)
        sharded = kmeans.kmeans_fit(x, c0, n_iters=10, mesh=mesh)
        np.testing.assert_allclose(np.asarray(single.centroids),
                                   np.asarray(sharded.centroids),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float(single.inertia),
                                   float(sharded.inertia), rtol=1e-3)

    def test_empty_cluster_keeps_centroid(self):
        x = np.zeros((16, 2), np.float32)       # all points identical
        c0 = np.array([[0.0, 0.0], [9.0, 9.0]], np.float32)
        res = kmeans.kmeans_fit(x, c0, n_iters=3)
        np.testing.assert_allclose(np.asarray(res.centroids)[1],
                                   [9.0, 9.0])  # never assigned, unmoved


class TestALSNative:
    def test_converges_to_noise_floor(self, ratings):
        import jax
        r, w = ratings
        v0 = als.init_item_factors(jax.random.PRNGKey(0), 64, 4)
        res = als.als_fit(r, w, v0, n_iters=10, reg=0.01)
        hist = np.asarray(res.history)
        assert hist[-1] < 0.05, hist
        assert hist[-1] <= hist[0]
        # factors reconstruct observed entries
        recon = np.asarray(res.user_factors) @ np.asarray(res.item_factors).T
        err = (w * (recon - r))
        assert np.sqrt((err ** 2).sum() / w.sum()) < 0.05

    def test_mesh_matches_single_device(self, ratings, mesh):
        import jax
        r, w = ratings
        v0 = als.init_item_factors(jax.random.PRNGKey(1), 64, 4)
        single = als.als_fit(r, w, v0, n_iters=5)
        sharded = als.als_fit(r, w, v0, n_iters=5, mesh=mesh)
        np.testing.assert_allclose(np.asarray(single.item_factors),
                                   np.asarray(sharded.item_factors),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(single.user_factors),
                                   np.asarray(sharded.user_factors),
                                   rtol=1e-3, atol=1e-3)


def _run_example(module, args, iterations):
    spec = TaskSpec(taskfn=module, mapfn=module, partitionfn=module,
                    reducefn=module, finalfn=module,
                    init_args=args, storage="mem:kmals-test")
    ex = LocalExecutor(spec, map_parallelism=4,
                       max_iterations=iterations + 1)
    ex.run()
    return ex


class TestMapReducePackaging:
    def test_kmeans_example_matches_native(self):
        """Six-function k-means (persistent_table state) ≡ the jitted
        kmeans_fit from the same seed centroids."""
        from examples.kmeans import mr_kmeans
        args = {"k": 8, "n": 1024, "dim": 8, "n_shards": 4,
                "max_iters": 5, "tol": 0.0, "seed": 5, "coord": "mem"}
        _run_example("examples.kmeans.mr_kmeans", args, iterations=5)
        state = mr_kmeans.read_state("mem")
        assert state["iter"] == 5 and state["finished"]

        x, _, _ = make_blobs(seed=5, n=1024, k=8, dim=8)
        native = kmeans.kmeans_fit(x, x[:8], n_iters=5)
        np.testing.assert_allclose(
            np.asarray(state["centroids"]),
            np.asarray(native.centroids), rtol=1e-3, atol=1e-3)

    def test_kmeans_example_converges_by_tol(self):
        from examples.kmeans import mr_kmeans
        args = {"k": 4, "n": 512, "dim": 8, "n_shards": 4,
                "max_iters": 30, "tol": 1e-3, "seed": 6, "coord": "mem"}
        _run_example("examples.kmeans.mr_kmeans", args, iterations=30)
        state = mr_kmeans.read_state("mem")
        assert state["finished"] and state["iter"] < 30, state["iter"]
        assert state["shift"] < 1e-3

    def test_als_example_matches_native(self):
        from examples.als import mr_als
        args = {"n_users": 128, "n_items": 32, "rank": 4, "density": 0.4,
                "reg": 0.1, "n_shards": 4, "max_iters": 6, "seed": 7,
                "coord": "mem"}
        _run_example("examples.als.mr_als", args, iterations=6)
        state = mr_als.read_state("mem")
        assert state["iter"] == 6 and state["finished"]
        # mr rmse is the pre-update measurement (one round behind native)
        assert state["rmse"] < 0.5

        r, w = make_ratings(seed=7, n_users=128, n_items=32, rank=4,
                            density=0.4)
        v0 = 0.1 * np.random.RandomState(7).randn(32, 4)
        native = als.als_fit(r, w, v0, n_iters=6, reg=0.1)
        np.testing.assert_allclose(
            np.asarray(state["item_factors"]),
            np.asarray(native.item_factors), rtol=5e-3, atol=5e-3)
