"""Core data-model tests: tuples, heap, serialization, merge.

Analog of the reference's per-module utest() asserts (SURVEY.md §4):
tuple.lua:309-328, heap.lua:99-118, utils.lua:340-406.
"""

from lua_mapreduce_tpu.core import heap, merge, serialize, tuples


def test_tuples_utest():
    tuples.utest()


def test_heap_utest():
    heap.utest()


def test_serialize_utest():
    serialize.utest()


def test_merge_utest():
    merge.utest()


def test_package_utest_runs_all_modules():
    """mapreduce.utest parity (reference test.lua:30-39 / init.lua:36-38):
    the package-level runner drives EVERY module self-test, including the
    micro e2e in engine.server.utest."""
    import lua_mapreduce_tpu

    lua_mapreduce_tpu.utest()


def test_tuple_intern_table_is_bounded():
    t = tuples.intern(("bounded-key", 1))
    assert tuples.stats()["size"] <= tuples._MAX_ENTRIES
    # force overflow: table clears rather than growing without bound
    tuples._table.clear()
    for i in range(10):
        tuples.intern((i,))
    old_max, tuples._MAX_ENTRIES = tuples._MAX_ENTRIES, 10
    try:
        tuples.intern(("overflow",))
        assert tuples.stats()["size"] <= 10
    finally:
        tuples._MAX_ENTRIES = old_max
    assert tuples.intern(("bounded-key", 1)) == t


def test_record_roundtrip_unicode_and_nesting():
    rec = serialize.dump_record("wörd\t\"quoted\"", [1, [2, "x"], None, True])
    key, values = serialize.load_record(rec)
    assert key == "wörd\t\"quoted\""
    assert values == [1, [2, "x"], None, True]


def test_key_order_total_on_mixed_types():
    keys = ["z", 3, (1, 2), "a", 1, (1,), None, 2.5]
    s = serialize.sorted_keys(keys)
    # numbers < strings < tuples < None (stable total order)
    assert s == [1, 2.5, 3, "a", "z", (1,), (1, 2), None]


def test_merge_many_files_interleaved():
    from lua_mapreduce_tpu.store.memfs import MemStore
    store = MemStore()
    n_files, n_keys = 7, 50
    expected = {}
    for i in range(n_files):
        b = store.builder()
        for k in range(i % 3, n_keys, 2):  # overlapping, sorted, unique keys
            key = f"k{k:04d}"
            b.write(serialize.dump_record(key, [i]) + "\n")
            expected.setdefault(key, []).append(i)
        b.build(f"run.{i}")
    merged = dict(merge.merge_iterator(store, [f"run.{i}" for i in range(n_files)]))
    assert {k: sorted(v) for k, v in merged.items()} == \
           {k: sorted(v) for k, v in expected.items()}
    # keys come out in sorted order
    assert list(merged) == sorted(merged)


def test_sorted_keys_fast_path_matches_key_lt():
    """The canonical-form sort must equal an exact key_lt comparator
    sort for every key shape, including bool-vs-int inside tuples."""
    import functools
    from lua_mapreduce_tpu.core.serialize import key_lt, sorted_keys
    keys = [3, 1.5, "b", "a", True, False, None, (1, "a"), ("b",), (True, 2),
            (0, "x"), (2, 1), (1, "a", 0), 2, -7, "z", (False,), (),
            b"b", b"a"]      # rank-5 keys drive the exact-comparator fallback
    want = sorted(keys, key=functools.cmp_to_key(
        lambda a, b: -1 if key_lt(a, b) else (1 if key_lt(b, a) else 0)))
    assert sorted_keys(keys) == want
