"""Sequence-parallel attention: ring + Ulysses vs the single-device
oracle (golden-diff discipline, SURVEY.md §4) on the virtual 8-device
mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.parallel import ring_attention as ra
from lua_mapreduce_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=8, mp=1, devices=jax.devices("cpu")[:8],
                     axis_names=("sp", "mp"))


def _qkv(seed, b=2, l=64, h=8, d=16, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, l, h, d), dtype) * 0.5
    return mk(), mk(), mk()


class TestRing:
    @pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
    def test_matches_reference(self, mesh, causal):
        q, k, v = _qkv(0)
        want = ra.attention_reference(q, k, v, causal=causal)
        got = ra.ring_attention(q, k, v, mesh, axis="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_bfloat16_inputs(self, mesh):
        """bf16 in, f32 accumulate: still close to the f32 oracle."""
        q, k, v = _qkv(1, dtype=jnp.bfloat16)
        want = ra.attention_reference(q.astype(jnp.float32),
                                      k.astype(jnp.float32),
                                      v.astype(jnp.float32), causal=True)
        got = ra.ring_attention(q, k, v, mesh, axis="sp", causal=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=0.1, atol=0.05)

    @pytest.mark.heavy
    def test_gradients_match_reference(self, mesh):
        """d(sum(attn))/dq through the ring ≡ through the oracle — the
        ring must be trainable, not inference-only."""
        q, k, v = _qkv(2, l=32, h=4)

        def ref_loss(q):
            return jnp.sum(ra.attention_reference(q, k, v, causal=True))

        def ring_loss(q):
            return jnp.sum(ra.ring_attention(q, k, v, mesh, axis="sp",
                                             causal=True))

        g_ref = jax.grad(ref_loss)(q)
        g_ring = jax.grad(ring_loss)(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_rejects_indivisible_seq(self, mesh):
        q, k, v = _qkv(3, l=60)
        with pytest.raises(ValueError, match="not divisible"):
            ra.ring_attention(q, k, v, mesh)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
    def test_matches_reference(self, mesh, causal):
        q, k, v = _qkv(4)
        want = ra.attention_reference(q, k, v, causal=causal)
        got = ra.ulysses_attention(q, k, v, mesh, axis="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_ring_and_ulysses_agree(self, mesh):
        q, k, v = _qkv(5)
        a = ra.ring_attention(q, k, v, mesh, axis="sp", causal=True)
        b = ra.ulysses_attention(q, k, v, mesh, axis="sp", causal=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_heads(self, mesh):
        q, k, v = _qkv(6, h=6)
        with pytest.raises(ValueError, match="heads not divisible"):
            ra.ulysses_attention(q, k, v, mesh)


class TestZigzagRing:
    """schedule="zigzag": the causal load-balanced ring must be
    indistinguishable from the oracle — the permutation is internal."""

    @pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
    def test_matches_reference(self, mesh, causal):
        q, k, v = _qkv(7)
        want = ra.attention_reference(q, k, v, causal=causal)
        got = ra.ring_attention(q, k, v, mesh, axis="sp", causal=causal,
                                schedule="zigzag")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.heavy
    def test_gradients_match_reference(self, mesh):
        q, k, v = _qkv(8, l=32, h=4)

        def ref_loss(q):
            return jnp.sum(ra.attention_reference(q, k, v, causal=True))

        def zz_loss(q):
            return jnp.sum(ra.ring_attention(q, k, v, mesh, axis="sp",
                                             causal=True,
                                             schedule="zigzag"))

        np.testing.assert_allclose(np.asarray(jax.grad(zz_loss)(q)),
                                   np.asarray(jax.grad(ref_loss)(q)),
                                   rtol=1e-4, atol=1e-4)

    def test_bad_lengths_and_schedule_rejected(self, mesh):
        q, k, v = _qkv(9, l=24)      # 24 % (2*8) != 0
        with pytest.raises(ValueError, match="zigzag"):
            ra.ring_attention(q, k, v, mesh, axis="sp", causal=True,
                              schedule="zigzag")
        q, k, v = _qkv(9)
        with pytest.raises(ValueError, match="schedule"):
            ra.ring_attention(q, k, v, mesh, axis="sp",
                              schedule="stripy")

    def test_perm_is_a_permutation(self):
        perm = ra._zigzag_perm(32, 4)
        assert sorted(perm.tolist()) == list(range(32))
        # shard 0 holds the first and LAST stripes
        assert perm[:8].tolist() == [0, 1, 2, 3, 28, 29, 30, 31]


class TestZigzagPersistentLayout:
    """layout='zigzag' (VERDICT r2 item 8): callers keeping long-lived
    tensors in zigzag order skip the per-call permutation entirely."""

    def test_pre_permuted_matches_seq_layout(self, mesh):
        q, k, v = _qkv(10)
        want = ra.ring_attention(q, k, v, mesh, axis="sp", causal=True,
                                 schedule="zigzag")        # seq layout
        n = mesh.shape["sp"]
        qz, kz, vz = (ra.to_zigzag(x, n) for x in (q, k, v))
        got_z = ra.ring_attention(qz, kz, vz, mesh, axis="sp",
                                  causal=True, schedule="zigzag",
                                  layout="zigzag")
        # output comes back in zigzag order; un-permute once to compare
        np.testing.assert_allclose(
            np.asarray(ra.from_zigzag(got_z, n)), np.asarray(want),
            rtol=2e-5, atol=2e-5)

    def test_to_from_zigzag_roundtrip(self):
        x = np.arange(4 * 32 * 2).reshape(4, 32, 2)
        z = ra.to_zigzag(x, 4)
        assert not np.array_equal(z, x)
        assert np.array_equal(ra.from_zigzag(z, 4), x)

    def test_no_permutation_in_compiled_program(self, mesh):
        """The point of the flag: the zigzag-layout call's jitted HLO
        contains no gather/permutation of the inputs — only the shard
        body runs. Checked structurally: layout='zigzag' lowers the SAME
        cached compiled callable as the internal body (ring_attention
        adds the permutation OUTSIDE it), so its cost equals the body's.
        Here we assert the permutation ops are absent from the traced
        jaxpr of an end-to-end jit around the zigzag-layout call."""
        import jax

        n = mesh.shape["sp"]

        def f(q, k, v):
            return ra.ring_attention(q, k, v, mesh, axis="sp",
                                     causal=True, schedule="zigzag",
                                     layout="zigzag")
        q, k, v = _qkv(11)
        qz, kz, vz = (ra.to_zigzag(x, n) for x in (q, k, v))
        jaxpr = str(jax.make_jaxpr(f)(qz, kz, vz))
        assert "gather" not in jaxpr, "persistent layout still permutes"

    def test_layout_requires_zigzag_schedule(self, mesh):
        q, k, v = _qkv(12)
        with pytest.raises(ValueError, match="requires schedule"):
            ra.ring_attention(q, k, v, mesh, axis="sp", causal=True,
                              layout="zigzag")
        with pytest.raises(ValueError, match="unknown layout"):
            ra.ring_attention(q, k, v, mesh, axis="sp", causal=True,
                              schedule="zigzag", layout="weird")
