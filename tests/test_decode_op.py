"""ops/decode.py — fused decode attention.

The XLA path is the exact composition the decode scan ran in-line
before the op existed, so the long-standing token-exactness pins
(decode vs full-forward oracle, prefill vs scan) transitively cover
it; THIS file pins the Pallas kernel against that XLA path over the
(shape, position, roll) matrix in interpret mode, and the kernel's
Mosaic lowering lives in tests/test_tpu_lowering.py with the rest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.ops.decode import decode_attention


def _args(b, hkv, g, d, s_len, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, hkv, g, d), dtype)
    k = jnp.asarray(rng.randn(b, hkv, s_len, d), dtype)
    v = jnp.asarray(rng.randn(b, hkv, s_len, d), dtype)
    return q, k, v


class TestDecodeParity:
    @pytest.mark.parametrize("shape", [(2, 4, 1, 64, 256),   # MHA g=1
                                       (2, 2, 4, 64, 384),   # GQA
                                       (1, 1, 8, 128, 512),
                                       # ragged: s_len % block_s != 0
                                       # exercises the ceil-divided
                                       # grid's masked final block
                                       (2, 2, 2, 64, 300),
                                       (1, 2, 1, 64, 1000)])
    def test_kernel_matches_xla(self, shape):
        b, hkv, g, d, s_len = shape
        q, k, v = _args(*shape)
        for t in [0, 5, s_len // 2, s_len - 1]:
            for roll in (False, True):
                ref = decode_attention(q, k, v, jnp.int32(t), roll=roll,
                                       backend="xla")
                got = decode_attention(q, k, v, jnp.int32(t), roll=roll,
                                       backend="pallas_interpret")
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(ref), rtol=2e-5,
                    atol=2e-5, err_msg=f"t={t} roll={roll}")

    def test_kernel_matches_xla_bf16(self):
        """The real serving dtype: bf16 caches, ragged length."""
        q, k, v = _args(2, 2, 2, 64, 300, seed=9, dtype=jnp.bfloat16)
        for t in [0, 150, 299]:
            ref = decode_attention(q, k, v, jnp.int32(t), backend="xla")
            got = decode_attention(q, k, v, jnp.int32(t),
                                   backend="pallas_interpret")
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-2, atol=2e-2)

    def test_rolling_full_cache_all_slots_visible(self):
        """t ≥ S in rolling mode: every slot holds a live position —
        the containment-is-the-mask rule."""
        q, k, v = _args(1, 2, 2, 64, 128, seed=3)
        ref = decode_attention(q, k, v, jnp.int32(500), roll=True,
                               backend="xla")
        got = decode_attention(q, k, v, jnp.int32(500), roll=True,
                               backend="pallas_interpret")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_t_zero_attends_only_first_slot(self):
        """Degenerate start: exactly one visible slot; the online
        softmax must not divide by a zero denominator."""
        q, k, v = _args(1, 2, 1, 64, 256, seed=4)
        got = decode_attention(q, k, v, jnp.int32(0),
                               backend="pallas_interpret")
        # one visible slot → output IS that slot's v row
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(v[:, :, 0:1, :]),
                                   rtol=1e-5, atol=1e-5)

    def test_inside_scan_traced_t(self):
        """The real call shape: ``t`` is a traced scan counter, the
        caches ride the carry."""
        q, k, v = _args(1, 2, 1, 64, 128, seed=5)

        def body(c, t):
            return c, decode_attention(q, k, v, t,
                                       backend="pallas_interpret")

        _, outs = jax.lax.scan(body, 0, jnp.arange(4))
        for i in range(4):
            ref = decode_attention(q, k, v, jnp.int32(i), backend="xla")
            np.testing.assert_allclose(np.asarray(outs[i]),
                                       np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("shape", [(2, 4, 1, 64, 256),
                                       (2, 2, 2, 64, 300)])  # ragged
    def test_q8_kernel_matches_xla(self, shape):
        """int8-cache path: the kernel's factored-out scales must
        reproduce the XLA q8 composition (same rounding points), live
        range, roll, and ragged tail included."""
        from lua_mapreduce_tpu.ops.decode import quantize_kv

        b, hkv, g, d, s_len = shape
        q, k, v = _args(*shape, seed=7)
        q = q.astype(jnp.bfloat16)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        for t in [0, s_len // 2, s_len - 1]:
            for roll in (False, True):
                ref = decode_attention(q, kq, vq, jnp.int32(t),
                                       roll=roll, k_scale=ks,
                                       v_scale=vs, backend="xla")
                got = decode_attention(q, kq, vq, jnp.int32(t),
                                       roll=roll, k_scale=ks,
                                       v_scale=vs,
                                       backend="pallas_interpret")
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(ref), rtol=5e-3,
                    atol=5e-3, err_msg=f"t={t} roll={roll}")

    def test_q8_close_to_full_precision(self):
        """Quantization noise at d=64 stays under ~2% of the full-
        precision result — the accuracy budget kv_q8 serving spends."""
        from lua_mapreduce_tpu.ops.decode import quantize_kv

        q, k, v = _args(1, 2, 2, 64, 256, seed=8)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        full = decode_attention(q, k, v, jnp.int32(255), backend="xla")
        q8 = decode_attention(q, kq, vq, jnp.int32(255), k_scale=ks,
                              v_scale=vs, backend="xla")
        rel = float(jnp.abs(full - q8).max() / jnp.abs(full).max())
        assert rel < 0.02, rel

    def test_scales_must_come_together(self):
        from lua_mapreduce_tpu.ops.decode import quantize_kv

        q, k, v = _args(1, 1, 1, 64, 128)
        kq, ks = quantize_kv(k)
        with pytest.raises(ValueError, match="together"):
            decode_attention(q, kq, v, jnp.int32(0), k_scale=ks)

    def test_bad_backend_rejected(self):
        q, k, v = _args(1, 1, 1, 64, 128)
        with pytest.raises(ValueError, match="unknown backend"):
            decode_attention(q, k, v, jnp.int32(0), backend="cuda")
