"""TPU-engine tests on a virtual 8-device CPU mesh.

Covers the SPMD MapReduce executor (keyed psum shape, bucketed all_to_all
shuffle shape), the collectives wrappers, and the dual-path golden
equivalence demanded by SURVEY.md §7 ("the golden-diff harness must run
against both" the traceable and host engines).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.parallel import (ArrayTaskSpec, TpuExecutor, host_mesh)
from lua_mapreduce_tpu.parallel import collectives
from lua_mapreduce_tpu.utils.jax_compat import shard_map

VOCAB = 64
NUM_P = 16      # partitions; mesh dp=8 → 2 partitions per device


@pytest.fixture(scope="module")
def mesh():
    return host_mesh(8)


def test_keyed_sum_matches_global(mesh):
    x = np.arange(8 * 4 * 3, dtype=np.float32).reshape(8 * 4, 3)
    spec = ArrayTaskSpec(
        mapfn=lambda shard: {"s": jnp.sum(shard, axis=0),
                             "sq": jnp.sum(shard ** 2, axis=0)})
    ex = TpuExecutor(spec, mesh)
    out = ex.run_keyed(x)
    np.testing.assert_allclose(out["s"], x.sum(axis=0), rtol=1e-6)
    np.testing.assert_allclose(out["sq"], (x ** 2).sum(axis=0), rtol=1e-6)


def test_keyed_mean_and_max(mesh):
    x = np.random.RandomState(0).randn(16, 5).astype(np.float32)
    mean = TpuExecutor(ArrayTaskSpec(
        mapfn=lambda s: jnp.mean(s, axis=0), reduce_op="mean"), mesh)
    np.testing.assert_allclose(mean.run_keyed(x), x.mean(axis=0), rtol=1e-5)
    mx = TpuExecutor(ArrayTaskSpec(
        mapfn=lambda s: jnp.max(s, axis=0), reduce_op="max"), mesh)
    np.testing.assert_allclose(mx.run_keyed(x), x.max(axis=0))


def test_combiner_is_local_prereduction(mesh):
    """combinerfn runs per device before the collective — same contract as
    the map-side combiner (job.lua:92-96)."""
    x = np.ones((8, 4), dtype=np.float32)
    spec = ArrayTaskSpec(
        mapfn=lambda s: s,                       # [1, 4] per device shard
        combinerfn=lambda t: jnp.sum(t, axis=0)) # local fold → [4]
    out = TpuExecutor(spec, mesh).run_keyed(x)
    np.testing.assert_allclose(out, np.full(4, 8.0))


def _token_ids(texts):
    """Feature-hash words into VOCAB bins (static key space for the
    traceable path)."""
    ids = []
    for t in texts:
        for w in t.split():
            ids.append(hash_word(w))
    return np.array(ids, dtype=np.int32)


def hash_word(w: str) -> int:
    h = 2166136261
    for b in w.encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % VOCAB


def test_bucketed_shuffle_matches_host_engine(mesh):
    """The dual-path golden test: hash-bucketed wordcount through (a) the
    jitted all_to_all shuffle and (b) the host engine, byte-identical."""
    rng = np.random.RandomState(7)
    words = [f"w{i}" for i in range(200)]
    texts = [" ".join(rng.choice(words, size=50)) for _ in range(32)]

    ids = _token_ids(texts)
    pad = (-len(ids)) % 8
    ids = np.concatenate([ids, np.full(pad, -1, np.int32)])  # -1 = no token

    bins_per_p = VOCAB // NUM_P

    spec = ArrayTaskSpec(
        mapfn=lambda shard: jnp.zeros(VOCAB, jnp.int32).at[shard].add(
            jnp.where(shard >= 0, 1, 0)),
        partitionfn=lambda counts: counts.reshape(NUM_P, bins_per_p),
        num_partitions=NUM_P,
    )
    ex = TpuExecutor(spec, mesh)
    sharded = ex.run_bucketed(ids)               # [NUM_P, bins_per_p] sharded
    tpu_counts = np.asarray(sharded).reshape(-1)

    # host engine, same logical task: keys = bin index, values = 1
    import examples.wordcount  # noqa: F401  (package import side effects none)

    def taskfn(emit):
        for i, t in enumerate(texts):
            emit(i, t)

    def mapfn(key, text, emit):
        for w in text.split():
            emit(hash_word(w), 1)

    def partitionfn(key):
        return key // bins_per_p

    def reducefn(key, values):
        return sum(values)

    host = LocalExecutor(TaskSpec(taskfn=taskfn, mapfn=mapfn,
                                  partitionfn=partitionfn, reducefn=reducefn,
                                  storage="mem:tpu-golden"))
    host.run()
    host_counts = np.zeros(VOCAB, np.int64)
    for k, vs in host.results():
        host_counts[k] = vs[0]

    np.testing.assert_array_equal(tpu_counts, host_counts)
    # and both match straight-line numpy
    golden = np.bincount(_token_ids(texts), minlength=VOCAB)
    np.testing.assert_array_equal(tpu_counts, golden)


def test_bucketed_partition_divisibility_enforced(mesh):
    spec = ArrayTaskSpec(mapfn=lambda s: s,
                         partitionfn=lambda x: x.reshape(6, -1),
                         num_partitions=6)
    with pytest.raises(ValueError, match="multiple"):
        TpuExecutor(spec, mesh).run_bucketed(np.zeros((8, 6), np.float32))


def test_collectives_tree_ops(mesh):
    from jax.sharding import PartitionSpec as P

    x = np.arange(16, dtype=np.float32).reshape(8, 2)

    def body(t):
        return collectives.psum_tree({"a": t}, "dp")["a"]

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                              out_specs=P()))
    # each shard is [1, 2]; psum keeps the local shape → global [1, 2]
    np.testing.assert_allclose(f(x), x.sum(axis=0, keepdims=True))

    # reduce_scatter: each device keeps its slice of the cross-device sum
    x2 = np.arange(64, dtype=np.float32).reshape(8, 8)

    def body_rs(t):
        return collectives.reduce_scatter_tree(t.reshape(8), "dp")

    f2 = jax.jit(shard_map(body_rs, mesh=mesh, in_specs=(P("dp"),),
                               out_specs=P("dp")))
    np.testing.assert_allclose(np.asarray(f2(x2)).reshape(-1), x2.sum(axis=0))


def test_ppermute_ring_rotates(mesh):
    from jax.sharding import PartitionSpec as P

    x = np.arange(8, dtype=np.float32).reshape(8, 1)

    def body(t):
        return collectives.ppermute_ring(t, "dp", mesh_size=8, shift=1)

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                              out_specs=P("dp")))
    out = np.asarray(f(x)).reshape(-1)
    # device i's value moved to device i+1 → output is rolled by one
    np.testing.assert_array_equal(out, np.roll(np.arange(8), 1))


def test_run_loop_scan_harness(mesh):
    spec = ArrayTaskSpec(mapfn=lambda s: jnp.sum(s))
    ex = TpuExecutor(spec, mesh)

    def step(state):
        return state + 1.0, state

    final, trace = ex.run_loop(jnp.float32(0), step, n_steps=5)
    assert final == 5.0
    np.testing.assert_allclose(np.asarray(trace), np.arange(5.0))


def test_differentiable_keyed_grads_match_oracle(mesh):
    """Grads flow through the keyed MapReduce primitive — map AND
    cross-device reduction — and equal the single-device oracle."""
    from lua_mapreduce_tpu.parallel.tpu_engine import differentiable_keyed

    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.rand(4, 3), jnp.float32)
    x = jnp.asarray(rng.rand(16, 4), jnp.float32)
    y = jnp.asarray(rng.rand(16, 3), jnp.float32)

    def mapfn(params, shard):
        xs, ys = shard
        pred = xs @ params
        return {"sq": jnp.mean((pred - ys) ** 2)}

    f = differentiable_keyed(mapfn, mesh, axis="dp", reduce_op="mean")

    def loss(params):
        return f(params, (x, y))["sq"]

    def oracle(params):
        return jnp.mean((x @ params - y) ** 2)

    lv, g = jax.value_and_grad(loss)(w)
    ov, og = jax.value_and_grad(oracle)(w)
    np.testing.assert_allclose(float(lv), float(ov), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g), np.asarray(og), rtol=1e-5)

    # composes under jit too (traced once, no host round trips)
    jitted = jax.jit(jax.grad(loss))
    np.testing.assert_allclose(np.asarray(jitted(w)), np.asarray(og),
                               rtol=1e-5)
