"""lmr-sched test suite (DESIGN §23): watch/notify conformance across
backends, end-to-end wakeup dispatch, notify-off byte-equivalence,
multi-tenant fairness/starvation/admission, the protocol checker's
notify edges, the dispatch trace span, and a SIGKILL-churn leg with
notify on (heavy).
"""

import os
import signal
import subprocess
import sys
import threading
import time
from collections import Counter

import pytest

from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job
from lua_mapreduce_tpu.core.constants import Status, TaskStatus
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.worker import Worker, resolve_idle_poll_s
from lua_mapreduce_tpu.sched import (AdmissionError, FairScheduler,
                                     FairWorker, Tenant, TenantView,
                                     channel_for, dispatch_latencies,
                                     tenant_ns)
from lua_mapreduce_tpu.sched.waiter import (DirChannel, LocalChannel,
                                            NullChannel, StoreChannel)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHED_MOD = "benchmarks.sched_task"

BACKENDS = ("mem", "shared", "object", "fake-gcs")


def _make_channel(kind, tmp_path):
    """One wakeup channel per backend kind; returns (channel, cleanup)."""
    if kind == "mem":
        return channel_for(MemJobStore()), lambda: None
    if kind == "shared":
        return channel_for(FileJobStore(str(tmp_path / "coord"))), \
            lambda: None
    if kind == "object":
        from lua_mapreduce_tpu.store.objectfs import ObjectStore
        return channel_for(ObjectStore(str(tmp_path / "obj"))), \
            lambda: None
    from lua_mapreduce_tpu.store.fake_gcs import (install_fake_gcs,
                                                  uninstall_fake_gcs)
    from lua_mapreduce_tpu.store.objectfs import ObjectStore
    prev = install_fake_gcs()
    return channel_for(ObjectStore("gs://sched-test/pfx")), \
        lambda: uninstall_fake_gcs(prev)


# --------------------------------------------------------------------------
# notify conformance across backends
# --------------------------------------------------------------------------


@pytest.mark.parametrize("kind", BACKENDS)
def test_notify_conformance_wakeup_fires(kind, tmp_path):
    """A blocked waiter returns True promptly when the producer
    notifies — on every backend's channel implementation."""
    ch, cleanup = _make_channel(kind, tmp_path)
    try:
        w = ch.waiter()
        got = []
        t = threading.Thread(target=lambda: got.append(w.wait(10.0)))
        t.start()
        time.sleep(0.05)
        t0 = time.perf_counter()
        ch.notify()
        t.join(timeout=10.0)
        took = time.perf_counter() - t0
        assert got == [True]
        assert took < 2.0, f"{kind}: wakeup took {took:.3f}s"
    finally:
        cleanup()


@pytest.mark.parametrize("kind", BACKENDS)
def test_notify_conformance_lost_notification_times_out(kind, tmp_path):
    """No notification → the wait times out (returns False) after about
    the requested interval: the poll fallback, never a hang."""
    ch, cleanup = _make_channel(kind, tmp_path)
    try:
        w = ch.waiter()
        t0 = time.perf_counter()
        assert w.wait(0.15) is False
        assert time.perf_counter() - t0 >= 0.1
    finally:
        cleanup()


@pytest.mark.parametrize("kind", BACKENDS)
def test_notify_conformance_stale_wakeup_is_noop(kind, tmp_path):
    """A notification is consumed exactly once; pre-history absorbed at
    waiter creation never wakes; a raced notify (fired between waits)
    IS delivered by the next wait — the cursor contract."""
    ch, cleanup = _make_channel(kind, tmp_path)
    try:
        ch.notify()                      # pre-history
        w = ch.waiter()
        assert w.wait(0.05) is False     # absorbed as the baseline
        ch.notify()                      # raced between waits
        assert w.wait(2.0) is True       # delivered by the NEXT wait
        assert w.wait(0.05) is False     # consumed exactly once
    finally:
        cleanup()


def test_notify_off_switch_routes_null(monkeypatch):
    monkeypatch.setenv("LMR_SCHED_NOTIFY", "0")
    ch = channel_for(MemJobStore())
    assert isinstance(ch, NullChannel)
    t0 = time.perf_counter()
    assert ch.waiter().wait(0.05) is False
    assert time.perf_counter() - t0 >= 0.04


def test_channel_routing_by_backend(tmp_path):
    assert isinstance(channel_for(MemJobStore()), LocalChannel)
    assert isinstance(channel_for(FileJobStore(str(tmp_path / "c"))),
                      DirChannel)
    from lua_mapreduce_tpu.store.objectfs import ObjectStore
    assert isinstance(channel_for(ObjectStore(str(tmp_path / "o"))),
                      StoreChannel)
    # wrapper stacks unwrap to the shared concrete store: one bus
    from lua_mapreduce_tpu.faults.wrappers import wrap_jobstore
    js = MemJobStore()
    assert channel_for(wrap_jobstore(js)) is channel_for(js)
    assert channel_for(TenantView(js, Tenant("t"))) is channel_for(js)


# --------------------------------------------------------------------------
# end-to-end: inserts wake an idle worker in far less than the poll cap
# --------------------------------------------------------------------------


def _put_map_task(view_or_store):
    desc = TaskSpec(taskfn=SCHED_MOD, mapfn=SCHED_MOD,
                    partitionfn=SCHED_MOD, reducefn=SCHED_MOD,
                    storage="mem:sched_test").describe()
    view_or_store.put_task({"_id": "unique",
                            "status": TaskStatus.MAP.value,
                            "iteration": 1, "spec": desc, "batch_k": 1})


@pytest.mark.parametrize("coord", ("mem", "shared"))
def test_insert_wakes_idle_worker(coord, tmp_path):
    """With a 5s poll cap, dispatch must ride the wakeup channel: the
    claim lands within a small fraction of the cap."""
    store = MemJobStore() if coord == "mem" \
        else FileJobStore(str(tmp_path / "coord"))
    _put_map_task(store)
    w = Worker(store, name="wake-test").configure(
        max_iter=10 ** 6, max_sleep=5.0, heartbeat_s=None)
    t = threading.Thread(target=w.execute, daemon=True)
    t.start()
    time.sleep(0.4)                      # worker backs off into a wait
    from lua_mapreduce_tpu.sched.waiter import notify
    store.insert_jobs("map_jobs", [make_job("k", 0)])
    notify(store, "jobs")
    deadline = time.perf_counter() + 2.0
    while time.perf_counter() < deadline:
        if store.counts("map_jobs")[Status.WRITTEN]:
            break
        time.sleep(0.005)
    doc = store.get_job("map_jobs", 0)
    assert doc["status"] == Status.WRITTEN, \
        f"job not dispatched within 2s (cap was 5s): {doc['status']}"
    lat = doc["started_time"] - doc["creation_time"]
    assert lat < 1.5, f"dispatch latency {lat:.3f}s — wakeup did not fire"
    store.update_task({"status": TaskStatus.FINISHED.value})
    notify(store, "jobs")
    t.join(timeout=10.0)


def test_server_barrier_wakes_on_commit():
    """The server's "done" channel: one worker's commit wakes the
    barrier poll long before its interval elapses — the whole
    wordcount finishes in a fraction of the 2s poll interval."""
    import types

    from lua_mapreduce_tpu.engine.server import Server

    mod = types.ModuleType("_sched_barrier_mod")
    mod.taskfn = lambda emit: [emit(str(i), i) for i in range(3)]
    mod.mapfn = lambda key, value, emit: emit("n", value)
    mod.partitionfn = lambda key: 0
    mod.reducefn = lambda key, values: sum(values)
    mod.finalfn = lambda pairs: None
    sys.modules["_sched_barrier_mod"] = mod
    try:
        store = MemJobStore()
        spec = TaskSpec(taskfn="_sched_barrier_mod",
                        mapfn="_sched_barrier_mod",
                        partitionfn="_sched_barrier_mod",
                        reducefn="_sched_barrier_mod",
                        finalfn="_sched_barrier_mod",
                        storage="mem:_sched_barrier")
        server = Server(store, poll_interval=2.0).configure(spec)
        w = Worker(store).configure(max_iter=10 ** 6, max_sleep=2.0,
                                    heartbeat_s=None)
        t = threading.Thread(target=w.execute, daemon=True)
        t.start()
        t0 = time.perf_counter()
        server.loop()
        wall = time.perf_counter() - t0
        t.join(timeout=10.0)
        # two phases × 2s interval would cost ≥4s on pure polling
        assert wall < 3.0, f"barrier wall {wall:.2f}s — commit wakeups " \
                           "did not reach the server"
    finally:
        del sys.modules["_sched_barrier_mod"]


def test_notify_off_output_identical(monkeypatch):
    """The notify-off path must produce byte-identical results to the
    notify-on path (the degradation ladder's rung 3 — today's engine
    verbatim)."""
    import types

    from lua_mapreduce_tpu.engine.server import Server
    from lua_mapreduce_tpu.store.router import get_storage_from

    mod = types.ModuleType("_sched_equiv_mod")
    mod.taskfn = lambda emit: [emit(str(i), list(range(i + 1)))
                               for i in range(4)]

    def mapfn(key, values, emit):
        for v in values:
            emit(f"w{v % 3}", v)
    mod.mapfn = mapfn
    mod.partitionfn = lambda key: hash(key) % 2
    mod.reducefn = lambda key, values: sum(values)
    mod.finalfn = lambda pairs: None
    sys.modules["_sched_equiv_mod"] = mod

    def run(tag, notify_on):
        monkeypatch.setenv("LMR_SCHED_NOTIFY", "1" if notify_on else "0")
        store = MemJobStore()
        spec = TaskSpec(taskfn="_sched_equiv_mod",
                        mapfn="_sched_equiv_mod",
                        partitionfn="_sched_equiv_mod",
                        reducefn="_sched_equiv_mod",
                        finalfn="_sched_equiv_mod",
                        storage=f"mem:_sched_equiv_{tag}")
        server = Server(store, poll_interval=0.01).configure(spec)
        w = Worker(store).configure(max_iter=800, max_sleep=0.02)
        t = threading.Thread(target=w.execute, daemon=True)
        t.start()
        server.loop()
        t.join(timeout=10.0)
        st = get_storage_from(f"mem:_sched_equiv_{tag}")
        return {n: "".join(st.lines(n)) for n in st.list("result.P*")}

    try:
        on = run("on", True)
        off = run("off", False)
        assert on and {k.rsplit(".", 1)[-1]: v for k, v in on.items()} \
            == {k.rsplit(".", 1)[-1]: v for k, v in off.items()}
    finally:
        del sys.modules["_sched_equiv_mod"]


# --------------------------------------------------------------------------
# multi-tenancy: admission, weighted share, starvation regression
# --------------------------------------------------------------------------


def test_admission_quota_refuses_flood():
    store = MemJobStore()
    v = TenantView(store, Tenant("q", max_pending=5))
    v.insert_jobs("map_jobs", [make_job(f"k{i}", i) for i in range(5)])
    with pytest.raises(AdmissionError):
        v.insert_jobs("map_jobs", [make_job("k5", 5)])
    assert v.admission == {"admitted": 5, "rejected": 1}
    # AdmissionError is a PERMANENT store fault: the retry layer must
    # not burn backoff on a full queue
    from lua_mapreduce_tpu.faults.errors import classify_exception
    assert classify_exception(AdmissionError("full")) is False


def test_tenant_namespaces_and_task_docs_are_isolated():
    store = MemJobStore()
    a, b = TenantView(store, Tenant("a")), TenantView(store, Tenant("b"))
    _put_map_task(a)
    assert b.get_task() is None
    a.insert_jobs("map_jobs", [make_job("k", 1)])
    assert b.counts("map_jobs")[Status.WAITING] == 0
    assert store.counts(tenant_ns("a", "map_jobs"))[Status.WAITING] == 1
    # errors stream is shared but tenant-tagged
    a.insert_error("w", "boom", info={"ns": "map_jobs"})
    (err,) = store.drain_errors()
    assert err["tenant"] == "a"


def test_weighted_fair_share_converges():
    """Two backlogged tenants, one shared FairWorker: committed work
    converges to the 2:1 weight ratio (stride scheduling)."""
    store = MemJobStore()
    tenants = [Tenant("heavy", weight=2.0), Tenant("light", weight=1.0)]
    views = {t.name: TenantView(store, t) for t in tenants}
    for v in views.values():
        _put_map_task(v)
        v.insert_jobs("map_jobs",
                      [make_job(f"k{i}", i) for i in range(40)])
    fw = FairWorker(store, tenants, max_iter=5, heartbeat_s=None)
    for _ in range(36):
        assert fw.poll_once() == "executed"
    snap = fw.scheduler.snapshot()
    ratio = snap["heavy"]["charged"] / max(1, snap["light"]["charged"])
    assert 1.4 <= ratio <= 2.8, snap


def test_starvation_regression_flood_vs_barrier():
    """The acceptance leg: a flood tenant's tiny-job backlog cannot
    starve the barrier tenant. Fair two-tenant scheduling must beat
    the FIFO (no-tenancy) baseline on the barrier's dispatch p99 by a
    wide margin, and the barrier tenant must finish long before the
    flood drains."""
    from lua_mapreduce_tpu.trace.collect import percentile

    def leg(fair):
        store = MemJobStore()
        tenants = [Tenant("flood"), Tenant("barrier")] if fair \
            else [Tenant("flood")]
        views = {t.name: TenantView(store, t) for t in tenants}
        for v in views.values():
            _put_map_task(v)
        flood_jobs, barrier_jobs = 150, 8
        views["flood"].insert_jobs(
            "map_jobs", [make_job(f"f{i}", i) for i in range(flood_jobs)])
        bview = views["barrier"] if fair else views["flood"]
        bview.insert_jobs(
            "map_jobs", [make_job(f"b{i}", i) for i in range(barrier_jobs)])
        sched = FairScheduler(tenants)
        workers = [FairWorker(store, tenants, scheduler=sched,
                              max_iter=100000, max_sleep=0.05,
                              heartbeat_s=None) for _ in range(3)]
        threads = [threading.Thread(target=w.execute, daemon=True)
                   for w in workers]
        for t in threads:
            t.start()
        deadline = time.perf_counter() + 60.0
        total = flood_jobs + barrier_jobs
        while time.perf_counter() < deadline:
            done = sum(v.counts("map_jobs")[Status.WRITTEN]
                       for v in views.values())
            if done >= total:
                break
            time.sleep(0.005)
        for v in views.values():
            v.update_task({"status": TaskStatus.FINISHED.value})
        from lua_mapreduce_tpu.sched.waiter import notify
        notify(store, "jobs")
        for t in threads:
            t.join(timeout=20.0)
        if fair:
            barrier = dispatch_latencies(store, "barrier")
            flood = dispatch_latencies(store, "flood")
        else:
            every = dispatch_latencies(store, "flood")
            barrier, flood = every[flood_jobs:], every[:flood_jobs]
        assert len(barrier) == barrier_jobs
        return (percentile(barrier, 99), percentile(flood, 99))

    fair_p99, fair_flood_p99 = leg(fair=True)
    fifo_p99, _ = leg(fair=False)
    # fairness bound: the flooded barrier tenant's p99 stays well under
    # the FIFO baseline (where it rides behind the whole flood), and
    # under the flood tenant's own p99
    assert fair_p99 < 0.6 * fifo_p99, (fair_p99, fifo_p99)
    assert fair_p99 <= fair_flood_p99 * 1.5 + 0.005, \
        (fair_p99, fair_flood_p99)


# --------------------------------------------------------------------------
# protocol checker: notify edges
# --------------------------------------------------------------------------


def test_protocol_notify_edges_hold_invariants():
    from lua_mapreduce_tpu.analysis.protocol import (ModelConfig,
                                                     check_protocol)
    res = check_protocol(ModelConfig(n_workers=2, n_jobs=2,
                                     allow_notify=True))
    assert res.ok, res.violation and res.violation.message
    base = check_protocol(ModelConfig(n_workers=2, n_jobs=2))
    assert res.states > base.states      # the wakeup dimension is real


def test_protocol_lost_wakeup_race_refound_and_replayable(tmp_path):
    """The seeded lost-wakeup bug (no timeout fallback) must be
    re-found as a hang with a sleeping worker, and its trace must
    REPLAY against the real stores: the store ops reproduce and land
    every job exactly where the model stranded it."""
    from lua_mapreduce_tpu.analysis.protocol import (ModelConfig,
                                                     check_protocol,
                                                     replay_trace)
    bug = check_protocol(ModelConfig(n_workers=2, n_jobs=2,
                                     allow_notify=True,
                                     bug="lost_wakeup_no_fallback"))
    assert not bug.ok
    assert "asleep" in bug.violation.message
    for store in (MemJobStore(),
                  FileJobStore(str(tmp_path / "replay"))):
        rep = replay_trace(store, bug.violation.trace, bug.config,
                           final_state=bug.violation.state)
        assert rep["ok"], rep


def test_protocol_notify_bug_requires_notify_dimension():
    from lua_mapreduce_tpu.analysis.protocol import ModelConfig
    with pytest.raises(ValueError):
        ModelConfig(bug="lost_wakeup_no_fallback")   # allow_notify off


# --------------------------------------------------------------------------
# dispatch span (lmr-trace integration)
# --------------------------------------------------------------------------


def test_dispatch_span_reports_in_histograms():
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    from lua_mapreduce_tpu.trace.span import Tracer
    from lua_mapreduce_tpu.trace.wrappers import TracingJobStore

    tr = Tracer()
    tr.set_actor("w")
    store = TracingJobStore(MemJobStore(), tr)
    store.insert_jobs("map_jobs", [make_job("k", 1)])
    time.sleep(0.02)
    got = store.claim_batch("map_jobs", "w", 1)
    assert len(got) == 1
    col = TraceCollection(tr.drain())
    d = col.dispatch_stats()
    assert d is not None and d["count"] == 1
    assert d["p50_ms"] >= 15.0           # covers the insert→claim gap
    assert "dispatch" in col.op_stats()


# --------------------------------------------------------------------------
# idle-poll knob plumbing
# --------------------------------------------------------------------------


def test_idle_poll_resolution(monkeypatch):
    monkeypatch.delenv("LMR_IDLE_POLL_MS", raising=False)
    assert resolve_idle_poll_s(None, 20.0) == 20.0
    assert resolve_idle_poll_s(500, 20.0) == 0.5
    assert resolve_idle_poll_s(500, 0.2) == 0.2     # max_sleep still caps
    monkeypatch.setenv("LMR_IDLE_POLL_MS", "250")
    assert resolve_idle_poll_s(None, 20.0) == 0.25
    with pytest.raises(ValueError):
        resolve_idle_poll_s(-1, 20.0)
    with pytest.raises(ValueError):
        Worker(MemJobStore()).configure(idle_poll_ms=0)


def test_cli_expose_idle_poll_ms():
    from lua_mapreduce_tpu.cli.execute_server import \
        build_parser as server_parser
    from lua_mapreduce_tpu.cli.execute_worker import \
        build_parser as worker_parser
    wa = worker_parser().parse_args(["/tmp/x", "--idle-poll-ms", "250"])
    assert wa.idle_poll_ms == 250
    sa = server_parser().parse_args(
        ["/tmp/x", "a", "b", "c", "d", "--idle-poll-ms", "250"])
    assert sa.idle_poll_ms == 250


# --------------------------------------------------------------------------
# SIGKILL churn with notify on (heavy)
# --------------------------------------------------------------------------


def _env():
    ambient = os.environ.get("PYTHONPATH", "")
    path = REPO + os.pathsep + ambient if ambient else REPO
    return dict(os.environ, PYTHONPATH=path, LMR_SCHED_NOTIFY="1",
                LMR_IDLE_POLL_MS="200")


@pytest.mark.heavy
def test_sigkill_churn_with_notify_on(tmp_path):
    """The churn contract survives the event-driven plane: a worker is
    SIGKILLed mid-map with notify enabled; the stale requeue (whose
    notify wakes the healthy fleet) recovers its job, zero FAILED,
    golden-equal output."""
    from examples.wordcount_big import corpus
    from lua_mapreduce_tpu.engine.local import iter_results
    from lua_mapreduce_tpu.engine.server import Server
    from lua_mapreduce_tpu.store.router import get_storage_from

    coord = str(tmp_path / "coord")
    spill = str(tmp_path / "spill")
    corpus_dir = str(tmp_path / "corpus")
    corpus.build(corpus_dir, n_splits=4)
    golden = Counter()
    for i in range(4):
        with open(corpus.split_path(corpus_dir, i)) as f:
            golden.update(f.read().split())

    stall = (
        "import examples.wordcount_big.bigtask as bt\n"
        "import time\n"
        "def stall(k, v, emit):\n"
        "    print('CLAIMED', flush=True)\n"
        "    time.sleep(3600)\n"
        "bt.mapfn = stall\n"
        "import lua_mapreduce_tpu.core.native_wcmap as nw\n"
        "nw.native_available = lambda: False\n")
    code = (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "{extra}"
        "from lua_mapreduce_tpu import FileJobStore, Worker\n"
        f"w = Worker(FileJobStore({coord!r})).configure(\n"
        "    max_iter=2000, max_sleep=0.5)\n"
        "w.execute()\n")
    victim = subprocess.Popen(
        [sys.executable, "-c", code.format(extra=stall)], env=_env(),
        stdout=subprocess.PIPE, text=True)
    healthy = []
    try:
        spec = TaskSpec(taskfn="examples.wordcount_big.bigtask",
                        mapfn="examples.wordcount_big.bigtask",
                        partitionfn="examples.wordcount_big.bigtask",
                        reducefn="examples.wordcount_big.bigtask",
                        init_args={"corpus_dir": corpus_dir,
                                   "n_splits": 4},
                        storage=f"shared:{spill}")
        server = Server(FileJobStore(coord), poll_interval=0.05,
                        stale_timeout_s=2.0, strict=True).configure(spec)
        done = threading.Event()
        stats_box = {}

        def run_server():
            stats_box["stats"] = server.loop()
            done.set()

        st = threading.Thread(target=run_server, daemon=True)
        st.start()
        assert "CLAIMED" in victim.stdout.readline()
        victim.kill()
        victim.wait()
        healthy = [subprocess.Popen(
            [sys.executable, "-c", code.format(extra="")], env=_env())
            for _ in range(2)]
        assert done.wait(timeout=120.0), "task did not complete"
        it = stats_box["stats"].iterations[-1]
        assert it.map.failed == 0 and it.reduce.failed == 0
        store = get_storage_from(f"shared:{spill}")
        got = Counter({k: v[0] for k, v in iter_results(store, "result")})
        assert got == golden
    finally:
        victim.kill()
        for p in healthy:
            p.kill()
