"""lmr-autotune suite (DESIGN §29): the self-tuning feedback loop.

Covers the acceptance criteria end to end:

1. controller unit behavior — hysteresis bands, per-knob cooldowns,
   flip lockout, evidence emission — on a virtual clock;
2. chaos stability — under a seeded FaultPlan an adaptive distributed
   run produces byte-identical results to the controller-off fault-free
   twin, charges ZERO repetitions, never lets a knob reverse direction
   more than once, and leaves an ``autotune.<knob>`` evidence span for
   EVERY applied decision;
3. the elastic fleet — the controller grows a FleetSupervisor-backed
   thread pool under a backlog flood, retires it back to baseline when
   the queue drains, and no lease is lost across a retirement (the
   protocol checker enumerates the same edge exhaustively;
   analysis/protocol.py elastic=True);
4. the doc-seeded EWMA cold-start guard — a fresh worker's first
   (compile-inflated) observation folds at a quarter weight and is not
   echoed back into the fleet aggregate until the worker has two own
   observations.
"""

import threading
import time
import types
from typing import Dict

import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.core.constants import Status
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.engine.server import Server
from lua_mapreduce_tpu.engine.worker import MAP_NS, PRE_NS, RED_NS, Worker
from lua_mapreduce_tpu.faults import FaultPlan, install_fault_plan
from lua_mapreduce_tpu.faults.retry import (COUNTERS, configure_retry,
                                            retry_settings)
from lua_mapreduce_tpu.sched import controller as ctl
from lua_mapreduce_tpu.sched.controller import (AutotuneConfig,
                                                AutotuneController,
                                                FleetSupervisor,
                                                Observation,
                                                resolve_autotune)
from lua_mapreduce_tpu.store.router import get_storage_from
from lua_mapreduce_tpu.trace.span import Tracer, install_tracer

from tests.test_chaos import (CORPUS, GOLDEN, _install_module, _MOD,
                              _plan, _result_bytes, _wait_for_claim)


@pytest.fixture(autouse=True)
def _restore_globals():
    """Autotune legs move process-global state (the retry backoff base,
    the installed tracer); every test leaves both exactly as found."""
    before = retry_settings()
    try:
        yield
    finally:
        configure_retry(retries=int(before["retries"]),
                        base_ms=float(before["base_ms"]))
        install_tracer(None)
        install_fault_plan(None)


def _assert_no_oscillation(decisions):
    """The chaos-stability acceptance: no knob reverses direction more
    than once across the observed window."""
    seq: Dict[str, list] = {}
    for d in decisions:
        seq.setdefault(d.knob, []).append(d.direction)
    for knob, dirs in seq.items():
        flips = sum(1 for a, b in zip(dirs, dirs[1:]) if a != b)
        assert flips <= 1, f"knob {knob} oscillated: directions {dirs}"


# --- controller unit behavior ------------------------------------------------

def test_controller_utest():
    ctl.utest()


def test_resolve_autotune_resolution_order(monkeypatch):
    monkeypatch.delenv("LMR_AUTOTUNE", raising=False)
    assert resolve_autotune(None) is False
    monkeypatch.setenv("LMR_AUTOTUNE", "1")
    assert resolve_autotune(None) is True
    assert resolve_autotune(False) is False     # explicit arg wins
    monkeypatch.setenv("LMR_AUTOTUNE", "off")
    assert resolve_autotune(None) is False


def test_none_initialized_knobs_stay_disabled():
    """An owner with no push pool / no fleet hook never tunes those
    knobs, whatever the evidence says."""
    now = [0.0]
    c = AutotuneController(batch_k=2,
                           config=AutotuneConfig(cooldown_s=0.0),
                           clock=lambda: now[0])
    c.note_rpc(1.0)
    c.tick(Observation(t=0.0, body_ewma_s=0.01, rpc_p99_s=1.0,
                       push_evictions=100, push_frames=100,
                       store_retries=1000, waiting=500, fleet=1))
    assert {d.knob for d in c.decisions} == {"batch_k"}
    for knob in ("push_budget_mb", "speculation", "retry_base_ms",
                 "fleet"):
        assert c.value(knob) is None


def test_flip_lockout_is_structural_under_adversarial_signal():
    """Feed the controller a signal engineered to whipsaw batch_k every
    window; the flip lockout must bound the damage to ONE reversal no
    matter how long the storm lasts — the zero-oscillation acceptance
    as a structural property, not a tuning accident."""
    now = [0.0]
    c = AutotuneController(batch_k=4,
                           config=AutotuneConfig(cooldown_s=0.5,
                                                 flip_reset_s=1000.0),
                           clock=lambda: now[0])
    for i in range(40):
        now[0] += 1.0                  # always past the cooldown
        body = 0.001 if i % 2 == 0 else 100.0   # whipsaw ratio
        c.tick(Observation(t=now[0], body_ewma_s=body, rpc_p99_s=0.05))
    _assert_no_oscillation(c.decisions)
    assert len(c.decisions) >= 2       # it did act before locking out
    vetoed = COUNTERS.snapshot().get("autotune_vetoes", 0)
    assert vetoed > 0                  # and the storm WAS suppressed


def test_every_decision_emits_evidence_span():
    """The explainability contract: one ``autotune.<knob>`` span per
    applied decision, carrying metric / observed / threshold / old /
    new / direction — and the trace collector parses them back out."""
    tr = Tracer()
    install_tracer(tr)
    now = [0.0]
    c = AutotuneController(batch_k=1, retry_base_ms=25.0,
                           config=AutotuneConfig(cooldown_s=0.0),
                           clock=lambda: now[0])
    c.note_rpc(0.5)
    c.tick(Observation(t=0.0, body_ewma_s=0.01, rpc_p99_s=0.5,
                       store_retries=50))
    now[0] += 1.0
    c.tick(Observation(t=1.0, body_ewma_s=0.01, rpc_p99_s=0.5))
    assert len(c.decisions) >= 3
    store = get_storage_from("mem:autotune-evidence")
    tr.flush(store)
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    col = TraceCollection.from_store(store)
    entries = col.autotune_decisions()
    assert len(entries) == len(c.decisions)
    for entry, d in zip(entries, c.decisions):
        assert entry["span"] == f"autotune.{d.knob}"
        assert entry["knob"] == d.knob
        assert entry["metric"] == d.metric
        assert entry["old"] == d.old and entry["new"] == d.new
        assert entry["direction"] == d.direction
        assert entry["threshold"] == pytest.approx(d.threshold, rel=1e-4)
    # and the CLI report surfaces them (DESIGN §29's "explainable
    # after the fact" includes the human rendering)
    from lua_mapreduce_tpu.trace.__main__ import render_text
    text = render_text(col, top=3)
    assert "autotune: " in text and "batch_k" in text


# --- chaos stability (distributed) -------------------------------------------

def _run_wordcount(tmp_path, tag, *, autotune, plan=None, n_workers=2,
                   speculation=0.0, straggler=False, tracer=None):
    """One distributed wordcount leg, autotune on or off — the
    byte-compare twin harness (mirrors tests/test_chaos.py)."""
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD, storage=f"mem:{tag}")
    store = MemJobStore()
    if tracer is not None:
        install_tracer(tracer)
    install_fault_plan(plan)
    try:
        server = Server(store, poll_interval=0.01, batch_k=2,
                        speculation=speculation,
                        autotune=autotune).configure(spec)
        names = ([f"healthy-{i}" for i in range(n_workers - 1)]
                 + ["straggler-0"] if straggler
                 else [None] * n_workers)
        workers = [Worker(store, name=names[i]).configure(max_iter=800,
                                                          max_sleep=0.02)
                   for i in range(n_workers)]
        threads = [threading.Thread(target=w.execute, daemon=True)
                   for w in workers]
        if straggler:
            final = {}
            st = threading.Thread(
                target=lambda: final.setdefault("stats", server.loop()),
                daemon=True)
            st.start()
            threads[-1].start()
            _wait_for_claim(store)
            for t in threads[:-1]:
                t.start()
            st.join(timeout=120)
            assert not st.is_alive(), "server wedged under the straggler"
        else:
            for t in threads:
                t.start()
            server.loop()
        for t in threads:
            t.join(timeout=30)
    finally:
        install_fault_plan(None)
        if tracer is not None:
            install_tracer(None)
    for ns in (MAP_NS, PRE_NS, RED_NS):
        for d in store.jobs(ns):
            assert d["repetitions"] == 0, \
                (f"chaos charged a repetition under autotune={autotune}: "
                 f"{ns} job {d['_id']} -> {d['repetitions']}")
    narrowed = speculation > 0
    return (_result_bytes(spec.storage, only_results=narrowed),
            server, store)


def test_chaos_adaptive_run_byte_identical_to_controller_off(tmp_path):
    """The headline stability leg: controller-off fault-free vs
    controller-on under the seeded chaos mix — byte-identical results,
    zero repetition charges (asserted in the harness), zero knob
    oscillation, and every applied decision carries an evidence span."""
    clean, off_server, _ = _run_wordcount(tmp_path, "at-off",
                                          autotune=False)
    assert off_server._controller is None   # off never builds one
    plan = _plan(seed=29)
    tr = Tracer()
    chaotic, server, store = _run_wordcount(tmp_path, "at-on",
                                            autotune=True, plan=plan,
                                            tracer=tr)
    assert chaotic == clean, \
        "adaptive chaos leg output differs from controller-off clean leg"
    assert plan.total_fired() > 0
    c = server._controller
    assert c is not None                    # autotune=True did engage
    _assert_no_oscillation(c.decisions)
    # every decision explainable: spans live in the store (housekeeping
    # flush) or still buffered — count both
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    spans = list(TraceCollection.from_store(
        get_storage_from(f"mem:at-on")).spans) + tr.drain()
    evidence = [s for s in spans if s["name"].startswith("autotune.")]
    assert len(evidence) == len(c.decisions)
    for s in evidence:
        attrs = s.get("attrs") or {}
        for key in ("metric", "observed", "threshold", "old", "new"):
            assert key in attrs, f"evidence span missing {key}: {s}"


def test_chaos_adaptive_straggler_leg(tmp_path):
    """Chaos + speculation + a named slow worker, controller on: the
    straggler detector follows the doc-negotiated factor (the LMR018
    contract), results stay golden, no oscillation."""
    plan = FaultPlan(37, transient=0.05, latency=0.03, latency_ms=1.0,
                     slow_worker="straggler-*", slow_ms=250.0,
                     max_per_key=2)
    _, server, store = _run_wordcount(tmp_path, "at-strag",
                                      autotune=True, plan=plan,
                                      n_workers=3, speculation=3.0,
                                      straggler=True)
    from lua_mapreduce_tpu.engine.local import iter_results
    got = {k: v[0] for k, v in iter_results(
        get_storage_from(f"mem:at-strag"), "result")}
    assert got == GOLDEN
    _assert_no_oscillation(server._controller.decisions)


# --- the elastic fleet -------------------------------------------------------

_SLOW = "tests._autotune_slow_wc"


def _install_slow_module(map_sleep, reduce_sleep):
    """Wordcount with deliberate body weight — the backlog the elastic
    controller sees is real wall time, not scheduler noise."""
    import sys

    mod = types.ModuleType(_SLOW)

    def taskfn(emit):
        for k, v in sorted(CORPUS.items()):
            emit(k, v)

    def mapfn(key, value, emit):
        time.sleep(map_sleep)
        for w in value.split():
            emit(w, 1)

    def reducefn(key, values):
        time.sleep(reduce_sleep)
        return sum(values)

    mod.taskfn = taskfn
    mod.mapfn = mapfn
    mod.partitionfn = lambda key: sum(key.encode()) % 4
    mod.reducefn = reducefn
    sys.modules[_SLOW] = mod
    return mod


def test_elastic_fleet_grows_and_retires_without_losing_leases(tmp_path):
    """The full elastic loop against a REAL thread fleet: baseline of
    one worker, a flood of slow map jobs → the controller scales the
    FleetSupervisor up; the queue drains → it retires back to baseline;
    retired workers finish their in-flight lease first (max_jobs=0 is
    checked at the poll boundary), so zero repetitions are charged and
    the count golden-diffs — the runtime twin of the protocol model's
    join/retire edges."""
    _install_slow_module(map_sleep=0.08, reduce_sleep=0.005)
    # reducefn sleeps per KEY, and every partition holds many words —
    # the reduce phase leaves plenty of waiting==0 housekeeping windows
    # for the shrink decision to fire before the task completes
    spec = TaskSpec(taskfn=_SLOW, mapfn=_SLOW, partitionfn=_SLOW,
                    reducefn=_SLOW, storage=f"mem:at-elastic")
    store = MemJobStore()
    # compress the control clock to the test's scale: the default
    # config's 10s drain target would never trip on a sub-second queue
    server = Server(store, poll_interval=0.02, autotune=True,
                    autotune_config=AutotuneConfig(
                        cooldown_s=0.05, flip_reset_s=300.0,
                        shrink_after=2,
                        drain_target_s=0.2)).configure(spec)

    threads: Dict[object, threading.Thread] = {}

    def spawn(seq):
        w = Worker(store, name=f"elastic-{seq}").configure(max_iter=4000,
                                                           max_sleep=0.02)
        t = threading.Thread(target=w.execute, daemon=True)
        threads[w] = t
        t.start()
        return w

    sup = FleetSupervisor(spawn,
                          retire=lambda w: w.configure(max_jobs=0),
                          baseline=1, cap=4)
    sup.ensure_baseline()
    server.set_fleet(sup.resize, size=1, max_workers=4)
    before = COUNTERS.snapshot()
    server.loop()
    for t in threads.values():
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads.values()), \
        "a retired worker never exited"

    decisions = server._controller.decisions
    grew = [d for d in decisions if d.knob == "fleet" and d.direction > 0]
    shrank = [d for d in decisions
              if d.knob == "fleet" and d.direction < 0]
    assert grew, "the backlog flood never scaled the fleet up"
    assert shrank, "the drained queue never retired the surplus"
    assert sup.size == 1, "fleet did not settle back at baseline"
    assert int(shrank[-1].new) == 1
    _assert_no_oscillation(decisions)
    delta = COUNTERS.delta(before, COUNTERS.snapshot())
    assert delta.get("autotune_scale_events", 0) >= 2

    # no lease lost across the retirements: zero repetitions anywhere,
    # and the counts golden-diff
    for ns in (MAP_NS, PRE_NS, RED_NS):
        for d in store.jobs(ns):
            assert d["repetitions"] == 0, \
                f"retire abandoned a lease: {ns} job {d['_id']}"
    from lua_mapreduce_tpu.engine.local import iter_results
    got = {k: v[0] for k, v in iter_results(
        get_storage_from(spec.storage), "result")}
    assert got == GOLDEN
    # the deploy also landed on the doc for CLI subprocess autoscalers
    task = store.get_task() or {}
    assert task.get("autotune") is True
    assert int(task.get("fleet_target", -1)) == 1


def test_fleet_supervisor_retire_waits_for_inflight_lease():
    """The graceful-retire primitive in isolation: retiring a worker
    MID-LEASE must let the lease commit (no requeue, no repetition) —
    max_jobs=0 only fires at the next poll boundary."""
    _install_slow_module(map_sleep=0.15, reduce_sleep=0.0)
    spec = TaskSpec(taskfn=_SLOW, mapfn=_SLOW, partitionfn=_SLOW,
                    reducefn=_SLOW, storage=f"mem:at-retire")
    store = MemJobStore()
    server = Server(store, poll_interval=0.01).configure(spec)
    w = Worker(store, name="retiree-0").configure(max_iter=2000,
                                                  max_sleep=0.02)
    w2 = Worker(store, name="keeper-0").configure(max_iter=2000,
                                                  max_sleep=0.02)
    t1 = threading.Thread(target=w.execute, daemon=True)
    t2 = threading.Thread(target=w2.execute, daemon=True)
    st = threading.Thread(target=server.loop, daemon=True)
    st.start()
    t1.start()
    _wait_for_claim(store)          # the retiree holds a live lease NOW
    w.configure(max_jobs=0)          # retire it mid-lease
    t2.start()                       # the keeper finishes the task
    st.join(timeout=60)
    assert not st.is_alive()
    t1.join(timeout=10)
    assert not t1.is_alive(), "retired worker kept running"
    t2.join(timeout=10)
    for ns in (MAP_NS, PRE_NS, RED_NS):
        for d in store.jobs(ns):
            assert d["repetitions"] == 0
    from lua_mapreduce_tpu.engine.local import iter_results
    got = {k: v[0] for k, v in iter_results(
        get_storage_from(spec.storage), "result")}
    assert got == GOLDEN


# --- LocalExecutor mirror ----------------------------------------------------

def test_local_executor_autotune_matches_golden(tmp_path):
    """The LocalExecutor mirror of the loop: adaptive and controller-off
    runs both golden-diff (the controller is semantics-neutral)."""
    _install_module()
    for autotune in (False, True):
        spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                        reducefn=_MOD,
                        storage=f"mem:at-local-{int(autotune)}")
        ex = LocalExecutor(spec, map_parallelism=3, autotune=autotune)
        ex.run()
        got = {k: v[0] for k, v in ex.results()}
        assert got == GOLDEN


# --- the doc-seeded EWMA cold-start guard (satellite) ------------------------

def test_seeded_worker_first_overshoot_folds_at_quarter_weight():
    """A fresh (elastically spawned) worker seeded from the doc's fleet
    EWMA runs its first job with compile/warmup cost the steady state
    never pays. Folding that outlier at full alpha would inflate the
    very aggregate every OTHER fresh worker is seeded from."""
    from lua_mapreduce_tpu.engine.worker import _DUR_ALPHA
    w = Worker(MemJobStore(), name="cold-0")
    # the poll_once seeding path, minimally
    w._dur_ewma["m"] = 0.1
    w._ewma_seeded.add("m")
    w._note_duration("m", 1.0)          # 10x overshoot: compile cost
    quarter = _DUR_ALPHA / 4.0
    assert w._dur_ewma["m"] == pytest.approx(
        quarter * 1.0 + (1 - quarter) * 0.1)
    # an UNDERSHOOT folds at full weight — faster hardware should pull
    # the estimate down immediately
    w2 = Worker(MemJobStore(), name="cold-1")
    w2._dur_ewma["m"] = 0.5
    w2._ewma_seeded.add("m")
    w2._note_duration("m", 0.1)
    assert w2._dur_ewma["m"] == pytest.approx(
        _DUR_ALPHA * 0.1 + (1 - _DUR_ALPHA) * 0.5)
    # an UNSEEDED worker is untouched: first observation calibrates
    w3 = Worker(MemJobStore(), name="warm-0")
    w3._note_duration("m", 1.0)
    assert w3._dur_ewma["m"] == 1.0


def test_seeded_worker_holds_persist_until_two_own_observations():
    """The echo guard: a doc-seeded worker must not push its EWMA back
    into the fleet aggregate until it has folded two OWN observations —
    one sample over the doc's own value is an amplifier, not a signal."""
    store = MemJobStore()
    store.put_task({"taskfn": "x"})
    w = Worker(store, name="cold-2")
    w._dur_ewma["m"] = 0.1
    w._ewma_seeded.add("m")
    w._note_duration("m", 1.0)
    w._persist_ewma("m")                # held: only one own observation
    assert "dur_ewma:m" not in (store.get_task() or {})
    w._note_duration("m", 1.0)
    w._persist_ewma("m")                # two own observations: folds
    doc = store.get_task() or {}
    assert doc.get("dur_ewma:m") == pytest.approx(w._dur_ewma["m"])


# --- worker-side doc follow (controller-off inertness) -----------------------

def test_worker_follows_controller_knobs_only_under_marker():
    """Workers apply controller-owned process-state knobs (retry base,
    push budget) ONLY when the doc carries the autotune marker — an
    autotune-off fleet is bit-for-bit inert to stray doc keys."""
    base = float(retry_settings()["base_ms"])
    w = Worker(MemJobStore(), name="inert-0")
    # the poll path gates on the marker; the raw doc without it must
    # leave the process-global backoff untouched
    task = {"retry_base_ms": base * 7, "push_budget_mb": 3.0}
    if task.get("autotune"):
        w._follow_autotune(task)
    assert float(retry_settings()["base_ms"]) == base
    assert w._task_push_budget is None
    # under the marker both apply, and a live pool re-budgets in place
    w.push = True
    pool = w._push_pool()
    task["autotune"] = True
    w._follow_autotune(task)
    assert float(retry_settings()["base_ms"]) == base * 7
    assert w._task_push_budget == 3.0
    assert pool.budget == int(3.0 * 1024 * 1024)
    assert w._push_pool() is pool       # same pool, moved threshold
