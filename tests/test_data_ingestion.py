"""Real-data ingestion contract (VERDICT r2 item 6).

The reference's examples consume real inputs: APRIL-ANN slices
misc/digits.png into 16x16 patterns with an 800/200 split
(examples/APRIL-ANN/init.lua:80-123), and WordCountBig's taskfn lists
real Europarl split files from disk (WordCountBig/taskfn.lua:5-13).
These tests pin the build's equivalents — an image loader honoring the
exact slicing contract (checked-in fixture: tests/fixtures/
digits_tiny.png) and a file-driven corpus path — end to end through the
engine, with the synthetic generators remaining the fallback.
"""

import os
from collections import Counter

import numpy as np
import pytest

from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor
from lua_mapreduce_tpu.train.data import load_digits_image, write_digits_image

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "digits_tiny.png")


class TestDigitsImageLoader:
    def test_fixture_contract(self):
        """The checked-in sheet slices to the reference split shape:
        (R*10) 256-dim patterns, 4:1 train/val by tile-rows, labels
        cycling 0-9 column-fastest, values in [0,1]."""
        x_tr, y_tr, x_va, y_va = load_digits_image(FIXTURE)
        assert x_tr.shape == (80, 256) and x_va.shape == (20, 256)
        assert x_tr.dtype == np.float32 and y_tr.dtype == np.int32
        assert (np.arange(80) % 10 == y_tr).all()
        assert (np.arange(20) % 10 == y_va).all()
        assert x_tr.min() >= 0.0 and x_tr.max() <= 1.0

    def test_inversion_and_column_layout(self):
        """Ink pixels (dark on paper) come out HIGH, and each tile lands
        in the pattern matching its (row, column) grid slot: glyphs in
        column c carry label c."""
        x_tr, y_tr, _, _ = load_digits_image(FIXTURE)
        # the sheet is dark-ink-on-white-paper: after inversion the mean
        # activation of inked regions exceeds the paper background (~0)
        assert x_tr.mean() > 0.05
        # classes differ: per-class mean patterns are not all identical
        means = np.stack([x_tr[y_tr == c].mean(axis=0) for c in range(10)])
        assert np.std(means, axis=0).max() > 0.05

    def test_full_size_sheet_roundtrip(self, tmp_path):
        """A full 1600x160 sheet (the reference's misc/digits.png
        geometry) yields exactly the 800/200 split of init.lua:80-123."""
        p = str(tmp_path / "digits_full.png")
        write_digits_image(p, seed=3, tile_rows=100)
        x_tr, y_tr, x_va, y_va = load_digits_image(p)
        assert x_tr.shape == (800, 256) and x_va.shape == (200, 256)
        assert y_tr[:10].tolist() == list(range(10))

    def test_deterministic(self):
        a = load_digits_image(FIXTURE)
        b = load_digits_image(FIXTURE)
        for u, v in zip(a, b):
            assert np.array_equal(u, v)

    def test_bad_geometry_rejected(self, tmp_path):
        from PIL import Image
        bad = str(tmp_path / "bad.png")
        Image.fromarray(np.zeros((64, 64), np.uint8), "L").save(bad)
        with pytest.raises(ValueError, match="160px wide"):
            load_digits_image(bad)

    @pytest.mark.heavy
    def test_mr_train_consumes_image(self, tmp_path):
        """The digits MapReduce example trains on the REAL image when
        given one (image arg -> loader path), through the engine."""
        import examples.digits.mr_train as mr

        args = {"sizes": (256, 32, 10), "n_shards": 2, "bunch": 32,
                "max_steps": 2, "patience": 10, "seed": 0,
                "image": FIXTURE,
                "model_store": f"shared:{tmp_path}/model"}
        spec = TaskSpec(taskfn="examples.digits.mr_train",
                        mapfn="examples.digits.mr_train",
                        partitionfn="examples.digits.mr_train",
                        reducefn="examples.digits.mr_train",
                        finalfn="examples.digits.mr_train",
                        init_args=args,
                        storage=f"shared:{tmp_path}/spill")
        LocalExecutor(spec, max_iterations=4).run()
        meta = mr.read_meta(f"shared:{tmp_path}/model")
        assert meta["step"] == 2 and np.isfinite(meta["val_loss"])

    def test_mr_train_rejects_size_mismatch(self):
        import examples.digits.mr_train as mr
        with pytest.raises(ValueError, match="expects 128 inputs"):
            mr.init({"sizes": (128, 32, 10), "image": FIXTURE,
                     "model_store": "mem:ingest-mismatch"})


class TestEuroparlFilePath:
    def _write_corpus(self, tmp_path):
        """Europarl format: plain text, one sentence per line."""
        lines = {
            "ep-00.txt": ["resumption of the session",
                          "i declare resumed the session"],
            "ep-01.txt": ["please rise then for this minute s silence",
                          "the house rose and observed a minute s silence"],
            "ep-02.txt": ["madam president on a point of order"],
        }
        paths = []
        for name, ls in lines.items():
            p = tmp_path / name
            p.write_text("\n".join(ls) + "\n")
            paths.append(str(p))
        return paths

    def test_files_arg_counts_real_files(self, tmp_path):
        """bigtask consumes explicit real split files (no synthetic
        corpus build) and golden-diffs against a naive count."""
        paths = self._write_corpus(tmp_path)
        spec = TaskSpec(taskfn="examples.wordcount_big.bigtask",
                        mapfn="examples.wordcount_big.bigtask",
                        partitionfn="examples.wordcount_big.bigtask",
                        reducefn="examples.wordcount_big.bigtask",
                        init_args={"files": paths},
                        storage=f"shared:{tmp_path}/spill")
        ex = LocalExecutor(spec)
        ex.run()
        got = {k: v[0] for k, v in ex.results()}
        want = Counter()
        for p in paths:
            with open(p) as f:
                want.update(f.read().split())
        assert got == dict(want)
        # no synthetic corpus snuck onto disk
        assert not any(f.startswith("split") for f in os.listdir(tmp_path))

    def test_missing_file_fails_loudly(self, tmp_path):
        paths = self._write_corpus(tmp_path) + [str(tmp_path / "nope.txt")]
        with pytest.raises(FileNotFoundError, match="nope.txt"):
            import examples.wordcount_big.bigtask as bt
            bt.init({"files": paths})

    def test_duplicate_basenames_stay_distinct(self, tmp_path):
        """Two dirs shipping same-named splits must both be counted —
        the task key space disambiguates by index."""
        d1, d2 = tmp_path / "a", tmp_path / "b"
        d1.mkdir(); d2.mkdir()
        (d1 / "split.txt").write_text("alpha alpha\n")
        (d2 / "split.txt").write_text("beta\n")
        spec = TaskSpec(taskfn="examples.wordcount_big.bigtask",
                        mapfn="examples.wordcount_big.bigtask",
                        partitionfn="examples.wordcount_big.bigtask",
                        reducefn="examples.wordcount_big.bigtask",
                        init_args={"files": [str(d1 / "split.txt"),
                                             str(d2 / "split.txt")]},
                        storage=f"shared:{tmp_path}/spill")
        ex = LocalExecutor(spec)
        ex.run()
        got = {k: v[0] for k, v in ex.results()}
        assert got == {"alpha": 2, "beta": 1}
