"""Test harness configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), standing in for a TPU pod
slice — the analog of the reference's Travis single-box "multi-node"
simulation (.travis.yml:10-18, SURVEY.md §4). The axon TPU plugin registers
itself at interpreter start, so the platform is forced back to CPU via
jax.config (env vars alone are overridden by the plugin).
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_NUM_CPU_DEVICES", "8")

try:
    import jax  # noqa: E402
except ImportError:  # pure-host layers are testable without jax
    jax = None
else:
    jax.config.update("jax_platforms", "cpu")
