"""Test harness configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), standing in for a TPU pod
slice — the analog of the reference's Travis single-box "multi-node"
simulation (.travis.yml:10-18, SURVEY.md §4). The axon TPU plugin registers
itself at interpreter start, so the platform is forced back to CPU via
jax.config (env vars alone are overridden by the plugin).
"""

import os

import pytest

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_NUM_CPU_DEVICES", "8")

try:
    import jax  # noqa: E402
except ImportError:  # pure-host layers are testable without jax
    jax = None
else:
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    """LMR_LOCKCHECK=1: install the runtime lock-order sanitizer before
    test modules import the package, so module-level locks (tracer,
    native-build cache, ...) are created through the recording
    factories.  The session fails in pytest_sessionfinish if any
    observed acquisition order is absent from the static lock model."""
    if os.environ.get("LMR_LOCKCHECK") == "1":
        from lua_mapreduce_tpu.utils import lockcheck
        lockcheck.install()


def pytest_sessionfinish(session, exitstatus):
    if os.environ.get("LMR_LOCKCHECK") != "1":
        return
    from lua_mapreduce_tpu.utils import lockcheck
    from lua_mapreduce_tpu.analysis.lockset import static_lock_model
    lockcheck.uninstall()   # stop recording before the analyzer runs
    rep = lockcheck.report()
    violations = lockcheck.verify(static_lock_model())
    print(f"\n[lockcheck] {rep['acquisitions']} acquisitions across "
          f"{len(rep['sites'])} lock sites, "
          f"{len(rep['edges'])} distinct order edges")
    if violations:
        for v in violations:
            print(f"[lockcheck] VIOLATION: {v}")
        session.exitstatus = 1


@pytest.fixture
def no_thread_leak():
    """Asserts no non-daemon thread outlives the test body — the
    dynamic half of the thread-shutdown audit (the static half is
    analysis.threads.shutdown_report).  A short grace window lets
    executor/pool teardown stragglers finish their last poll."""
    import threading
    import time

    before = set(threading.enumerate())

    def leaked():
        return [t for t in threading.enumerate()
                if t not in before and t.is_alive() and not t.daemon]

    yield
    deadline = time.monotonic() + 5.0
    while leaked() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not leaked(), (
        f"non-daemon threads leaked past teardown: "
        f"{[t.name for t in leaked()]}")


def pytest_addoption(parser):
    parser.addoption(
        "--full", action="store_true", default=False,
        help="also run tests marked 'heavy' (soak, chaos, convergence, "
             "sharded-prefill e2e) — the full-coverage mode test.sh uses")


def pytest_collection_modifyitems(config, items):
    """Default runs skip the heavy tail so the suite stays fast enough
    to be run often (VERDICT r3 weak item 5: 23 min suites get run
    less); ``--full`` / LMR_FULL=1 restores every test."""
    full_env = os.environ.get("LMR_FULL", "")
    if config.getoption("--full") or full_env.lower() not in ("", "0",
                                                              "false"):
        return
    if "heavy" in (config.getoption("-m") or ""):
        return          # explicitly selecting heavy tests runs them
    skip = pytest.mark.skip(
        reason="heavy: run with --full or LMR_FULL=1")
    for item in items:
        if "heavy" in item.keywords:
            item.add_marker(skip)
