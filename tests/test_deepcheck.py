"""lmr-deepcheck tests (DESIGN §25): the whole-program call graph, the
interprocedural context-propagation rules (LMR013+) with the fixture
pairs the per-function lint provably misses, the stale-suppression
audit, SARIF export, the static task-contract checker, and the
pinned lowerability verdicts of every shipped task module."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from lua_mapreduce_tpu.analysis import callgraph as cg_mod
from lua_mapreduce_tpu.analysis import contracts
from lua_mapreduce_tpu.analysis import dataflow
from lua_mapreduce_tpu.analysis import lint as lint_mod
from lua_mapreduce_tpu.analysis import sarif
from lua_mapreduce_tpu.analysis.callgraph import CallGraph
from lua_mapreduce_tpu.analysis.lint import run_audit, run_lint

PKG = os.path.dirname(os.path.abspath(lint_mod.__file__))
REPO = os.path.dirname(os.path.dirname(PKG))


def _write_fixture(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return str(p)


def _deep(tmp_path, fixtures):
    for rel, src in fixtures.items():
        _write_fixture(tmp_path, rel, src)
    return dataflow.run_deep([str(tmp_path)], baseline="/nonexistent")


def _per_function(tmp_path):
    return run_lint([str(tmp_path)], baseline="/nonexistent")


# --- call graph -------------------------------------------------------------

def test_callgraph_resolves_every_edge_kind():
    g = CallGraph.from_sources([
        ("engine/a.py", textwrap.dedent("""\
            from engine.b import helper, Tool
            import engine.b

            class Runner:
                def top(self, cb):
                    self.low()
                    helper()
                    engine.b.other()
                    t = Tool()
                    cb(1)

                def low(self):
                    def inner():
                        return 1
                    return inner()
            """)),
        ("engine/b.py", textwrap.dedent("""\
            def helper():
                return other()

            def other():
                return 2

            class Tool:
                def __init__(self):
                    self.x = 1
            """)),
    ])
    kinds = {(e.caller.split("::")[1], e.callee, e.kind)
             for edges in g.edges_from.values() for e in edges}
    assert ("Runner.top", "engine/a.py::Runner.low", "method") in kinds
    assert ("Runner.top", "engine/b.py::helper", "direct") in kinds
    assert ("Runner.top", "engine/b.py::other", "direct") in kinds
    assert ("Runner.top", "engine/b.py::Tool.__init__", "ctor") in kinds
    assert ("Runner.top", "<param:cb>", "param") in kinds
    assert ("Runner.low", "engine/a.py::Runner.low.inner",
            "direct") in kinds


def test_callgraph_interface_surface_fans_out():
    g = CallGraph.from_sources([
        ("store/base.py", textwrap.dedent("""\
            class Store:
                def lines(self, name):
                    raise NotImplementedError
            """)),
        ("store/memfs.py", textwrap.dedent("""\
            class MemStore(Store):
                def lines(self, name):
                    return []
            """)),
        ("engine/job.py", textwrap.dedent("""\
            def read_all(store):
                return list(store.lines('x'))
            """)),
    ])
    edges = [e for e in g.callees("engine/job.py::read_all")
             if e.kind == "interface"]
    assert len(edges) == 1
    impls = set(g.iface_targets("lines"))
    assert impls == {"store/base.py::Store.lines",
                     "store/memfs.py::MemStore.lines"}


def test_callgraph_base_class_resolution_across_modules():
    g = CallGraph.from_sources([
        ("store/base.py", "class Base:\n"
                          "    def shared(self):\n"
                          "        return 1\n"),
        ("store/impl.py", "from store.base import Base\n"
                          "class Impl(Base):\n"
                          "    def use(self):\n"
                          "        return self.shared()\n"),
    ])
    edges = g.callees("store/impl.py::Impl.use")
    assert [(e.callee, e.kind) for e in edges] == \
        [("store/base.py::Base.shared", "method")]


def test_callgraph_indexes_defs_inside_except_handlers(tmp_path):
    """The import-fallback idiom (`except ImportError: def helper()`)
    nests the def two statement levels deep — it must still be a graph
    node, or the deep pass is blind through every fallback helper."""
    deep = _deep(tmp_path, {
        "coord/fb.py": """\
            import os
            try:
                from fast import helper
            except ImportError:
                def helper():
                    import json
                    return json.load(open('x'))

            class Idx:
                def claim(self):
                    fd = self._open_locked()
                    try:
                        return helper()
                    finally:
                        os.close(fd)
            """,
    })
    # json.load + open share line 7: same (path, line, rule) — the
    # shortest-chain dedup collapses them to ONE finding by design
    assert [(f.rule, f.line) for f in deep] == [("LMR013", 7)]


def test_real_package_graph_size_and_speed():
    import time
    t0 = time.perf_counter()
    g = cg_mod.build_callgraph()
    wall = time.perf_counter() - t0
    assert g.node_count() > 800 and g.edge_count() > 1500
    assert wall < 15.0, f"callgraph build took {wall:.1f}s"
    assert {"lines", "build", "claim_batch",
            "read_range"} <= g.interface_methods()


# --- LMR013: flock-reachable IO ---------------------------------------------

FLOCK_INDIRECT = {
    "coord/fx.py": """\
        import json, os, time

        class Idx:
            def claim(self):
                fd = self._open_locked()
                try:
                    return self._load_doc(fd)
                finally:
                    os.close(fd)

            def _load_doc(self, fd):
                doc = json.load(open('sidecar'))
                time.sleep(0.1)
                return doc
        """,
}


def test_lmr013_helper_io_under_flock_found_deep_missed_shallow(tmp_path):
    deep = _deep(tmp_path, FLOCK_INDIRECT)
    assert {f.rule for f in deep} == {"LMR013"}
    assert sorted({f.line for f in deep}) == [12, 13]
    assert any("json.load" in f.message or "open()" in f.message
               for f in deep)
    assert all("reached from" in f.message for f in deep)
    # the per-function pass provably misses the indirection
    per_fn = _per_function(tmp_path)
    assert [f for f in per_fn if f.rule == "LMR002"] == []


def test_lmr013_store_dataplane_call_in_region_and_clean_twin(tmp_path):
    deep = _deep(tmp_path, {
        "coord/direct.py": """\
            import os

            class Idx:
                def scan(self, store):
                    fd = self._open_locked()
                    try:
                        return store.lines('manifest')
                    finally:
                        os.close(fd)
            """,
        "coord/clean.py": """\
            import os

            class Idx:
                def good(self):
                    fd = self._open_locked()
                    try:
                        return self._read_rec(fd)
                    finally:
                        os.close(fd)

                def _read_rec(self, fd):
                    return os.read(fd, 88)
            """,
    })
    assert [f.rule for f in deep] == ["LMR013"]
    assert "store data-plane call" in deep[0].message
    assert deep[0].path == "coord/direct.py"


def test_lmr013_user_callback_one_frame_deep(tmp_path):
    deep = _deep(tmp_path, {
        "coord/cb.py": """\
            import os

            class Idx:
                def claim(self, notify):
                    fd = self._open_locked()
                    try:
                        self._fire(notify)
                    finally:
                        os.close(fd)

                def _fire(self, notify):
                    notify("claimed")
            """,
    })
    assert [f.rule for f in deep] == ["LMR013"]
    assert "user callback" in deep[0].message


# --- LMR014: unclassified raisables across the retry boundary ---------------

RETRY_INDIRECT = {
    "store/fx.py": """\
        class MyStore:
            def read_range(self, name, offset, length):
                return self._fetch(name)

            def _fetch(self, name):
                raise RuntimeError('backend hiccup')
        """,
}


def test_lmr014_helper_raise_found_deep_missed_shallow(tmp_path):
    deep = _deep(tmp_path, RETRY_INDIRECT)
    assert [f.rule for f in deep] == ["LMR014"]
    assert deep[0].line == 6 and "RuntimeError" in deep[0].message
    per_fn = _per_function(tmp_path)
    assert [f for f in per_fn if f.rule == "LMR008"] == []


def test_lmr014_classified_helper_raises_pass(tmp_path):
    deep = _deep(tmp_path, {
        "store/ok.py": """\
            class MyStore:
                def read_range(self, name, offset, length):
                    return self._fetch(name)

                def _fetch(self, name):
                    raise TransientStoreError('blip')

                def size(self, name):
                    return self._stat(name)

                def _stat(self, name):
                    raise FileNotFoundError(name)
            """,
    })
    assert deep == []


def test_lmr014_checks_the_directly_wrapped_policy_frame(tmp_path):
    """A function handed straight to RetryPolicy.call IS the retried
    frame, and it is not a boundary method LMR008 ever checks — its
    own depth-0 raise must still classify."""
    deep = _deep(tmp_path, {
        "faults/fx.py": """\
            def fetch_with_retry(policy):
                return policy.call(_do_fetch)

            def _do_fetch():
                raise RuntimeError('backend hiccup')
            """,
    })
    assert [(f.rule, f.line) for f in deep] == [("LMR014", 5)]


def test_lmr014_reaches_helpers_outside_store_paths(tmp_path):
    # the helper lives in core/ — outside LMR008's path scope entirely
    deep = _deep(tmp_path, {
        "core/codec.py": """\
            def encode_frame(payload):
                raise RuntimeError('bad frame')
            """,
        "store/user.py": """\
            from core.codec import encode_frame

            class S:
                def build(self, name):
                    return encode_frame(name)
            """,
    })
    assert [f.rule for f in deep] == ["LMR014"]
    assert deep[0].path == "core/codec.py"


# --- LMR015: clock/RNG in replay-deterministic regions ----------------------

REPLAY_INDIRECT = {
    "coord/fx.py": """\
        import time

        class S:
            def stamp(self):
                with self._lock:
                    self.t = self._now()

            def _now(self):
                return time.time()
        """,
}


def test_lmr015_hoistable_clock_found_deep_missed_shallow(tmp_path):
    deep = _deep(tmp_path, REPLAY_INDIRECT)
    assert [f.rule for f in deep] == ["LMR015"]
    assert deep[0].line == 9
    per_fn = _per_function(tmp_path)
    assert [f for f in per_fn if f.rule == "LMR004"] == []


def test_lmr015_trace_seeded_chain_and_hoisted_twin(tmp_path):
    deep = _deep(tmp_path, {
        "trace/fx.py": """\
            from core.util import jitter

            class Tracer:
                def add(self, name):
                    return jitter()
            """,
        "core/util.py": """\
            import random

            def jitter():
                return random.random()
            """,
        "coord/clean.py": """\
            import time

            class S:
                def stamp(self):
                    now = self._now()
                    with self._lock:
                        self.t = now

                def _now(self):
                    return time.time()
            """,
    })
    assert [f.rule for f in deep] == ["LMR015"]
    assert deep[0].path == "core/util.py"
    assert "random.random" in deep[0].message


# --- LMR016: non-replayable RPCs inside retried frames ----------------------

def test_lmr016_insert_jobs_reachable_from_retried_op(tmp_path):
    deep = _deep(tmp_path, {
        "store/fx.py": """\
            class S:
                def build(self, name):
                    self._publish(name)

                def _publish(self, name):
                    self.js.insert_jobs('ns', [])
            """,
    })
    assert [f.rule for f in deep] == ["LMR016"]
    assert "insert_jobs" in deep[0].message


def test_lmr016_policy_call_frame_and_unretried_claim_pass(tmp_path):
    deep = _deep(tmp_path, {
        "faults/fx.py": """\
            class Wrapper:
                def flush(self, name):
                    self._policy.call(lambda: self._inner.pt_cas(
                        name, None, {}), op='flush', name=name)
            """,
        "coord/ok.py": """\
            class JS:
                def claim(self, ns, worker):
                    # claim is NOT a retried frame: its claim_batch
                    # fallback is the documented default-1 path
                    return self.claim_batch(ns, worker, 1)

                def claim_batch(self, ns, worker, k):
                    return []
            """,
    })
    assert [f.rule for f in deep] == ["LMR016"]
    assert deep[0].path == "faults/fx.py"
    assert "pt_cas" in deep[0].message


# --- LMR017: jit-trace purity through helpers -------------------------------

JIT_INDIRECT = {
    "ops/fx.py": """\
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x + _noise(3)

        def _noise(n):
            return np.random.randn(n)
        """,
}


def test_lmr017_impure_helper_found_deep_missed_shallow(tmp_path):
    deep = _deep(tmp_path, JIT_INDIRECT)
    assert [f.rule for f in deep] == ["LMR017"]
    assert "np.random" in deep[0].message
    per_fn = _per_function(tmp_path)
    assert [f for f in per_fn if f.rule == "LMR007"] == []


def test_lmr017_pure_helper_and_untraced_users_pass(tmp_path):
    deep = _deep(tmp_path, {
        "ops/ok.py": """\
            import jax
            import jax.numpy as jnp

            @jax.jit
            def kernel(x):
                return _scale(x)

            def _scale(x):
                return x * jnp.float32(2.0)

            def host_bench():
                import numpy as np
                return _noise(np.random.default_rng(0))

            def _noise(rng):
                return rng.normal()
            """,
    })
    assert deep == []


# --- suppression + stale audit ----------------------------------------------

def test_deep_findings_respect_inline_and_baseline(tmp_path):
    fixtures = dict(REPLAY_INDIRECT)
    _write_fixture(tmp_path, "coord/fx.py", fixtures["coord/fx.py"])
    assert len(dataflow.run_deep([str(tmp_path)],
                                 baseline="/nonexistent")) == 1
    # inline pragma on the deep finding's line
    src = textwrap.dedent(fixtures["coord/fx.py"]).replace(
        "return time.time()",
        "return time.time()  # lmr: disable=LMR015")
    (tmp_path / "coord" / "fx.py").write_text(src)
    assert dataflow.run_deep([str(tmp_path)],
                             baseline="/nonexistent") == []
    # justified baseline entry
    (tmp_path / "coord" / "fx.py").write_text(
        textwrap.dedent(fixtures["coord/fx.py"]))
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{"rule": "LMR015", "path": "coord/fx.py",
                               "reason": "test"}]))
    assert dataflow.run_deep([str(tmp_path)], baseline=str(bl)) == []


def test_stale_pragma_and_baseline_detected(tmp_path):
    _write_fixture(tmp_path, "train/fx.py", """\
        def fine():
            return 1  # lmr: disable=LMR005
        """)
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{"rule": "LMR001", "path": "train/gone.py",
                               "reason": "file was deleted"}]))
    audit = run_audit([str(tmp_path)], baseline=str(bl))
    assert audit.findings == []
    assert audit.stale_pragmas == [{"path": "train/fx.py", "line": 2,
                                    "rule": "LMR005"}]
    assert audit.stale_baseline == [{"rule": "LMR001",
                                     "path": "train/gone.py",
                                     "reason": "file was deleted"}]
    assert audit.stale


def test_live_pragma_is_not_stale(tmp_path):
    _write_fixture(tmp_path, "train/fx.py", """\
        def swallow():
            try:
                work()
            except BaseException:  # lmr: disable=LMR005
                pass
        """)
    audit = run_audit([str(tmp_path)], baseline="/nonexistent")
    assert audit.findings == [] and not audit.stale


def test_docstring_mentions_are_not_pragmas(tmp_path):
    _write_fixture(tmp_path, "train/fx.py", '''\
        """Suppress with ``# lmr: disable=LMR005`` on the line."""
        SNIPPET = "x = 1  # lmr: disable=LMR001"
        ''')
    audit = run_audit([str(tmp_path)], baseline="/nonexistent")
    assert not audit.stale


def test_cli_fail_on_stale_and_json_payload(tmp_path):
    _write_fixture(tmp_path, "train/fx.py", """\
        def fine():
            return 1  # lmr: disable=LMR005
        """)
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "all",
         str(tmp_path), "--fail-on-stale", "--format", "json",
         "--baseline", "/nonexistent", "--workers", "1", "--jobs", "1",
         "--batch-k", "1", "--seed-bug", "commit_skips_owner_cas"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["stale_pragmas"][0]["rule"] == "LMR005"
    assert payload["count"] == 0


# --- SARIF ------------------------------------------------------------------

def test_sarif_export_schema_and_results(tmp_path):
    _write_fixture(tmp_path, "store/fx.py", RETRY_INDIRECT["store/fx.py"])
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "deep",
         str(tmp_path), "--format", "sarif",
         "--baseline", "/nonexistent"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    sarif.validate_sarif(doc)
    results = doc["runs"][0]["results"]
    assert len(results) == 1 and results[0]["ruleId"] == "LMR014"
    uri = results[0]["locations"][0]["physicalLocation"][
        "artifactLocation"]["uri"]
    assert uri == "store/fx.py"


def test_sarif_rejected_for_protocol():
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "protocol",
         "--format", "sarif"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 2
    assert "sarif" in r.stderr


# --- task-contract checker --------------------------------------------------

def test_contract_signature_and_emit_arity(tmp_path):
    p = _write_fixture(tmp_path, "task.py", """\
        def taskfn(emit, extra):
            emit(1)

        def mapfn(key, value, emit):
            emit(key, value, 1)

        def partitionfn(key):
            return 0

        def reducefn(key, values):
            return sum(values)
        """)
    rep = contracts.check_task(p)
    assert rep.verdict == contracts.VERDICT_INVALID
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["LMR021", "LMR022", "LMR022"]
    assert rep.functions["taskfn"].verdict == contracts.VERDICT_INVALID


def test_contract_missing_required_functions(tmp_path):
    p = _write_fixture(tmp_path, "half.py", """\
        def mapfn(key, value, emit):
            emit(key, value)
        """)
    rep = contracts.check_task(p)
    assert rep.verdict == contracts.VERDICT_INVALID
    missing = {f.message.split("'")[1] for f in rep.findings
               if f.rule == "LMR020"}
    assert missing == {"taskfn", "partitionfn", "reducefn"}


def test_contract_determinism_hazards(tmp_path):
    p = _write_fixture(tmp_path, "hazard.py", """\
        import time, random, os, glob

        def taskfn(emit):
            for path in glob.glob('*.txt'):
                emit(path, path)

        def mapfn(key, value, emit):
            emit(key, time.time())
            emit(key, random.random())

        def partitionfn(key):
            return hash(key) % 4

        def reducefn(key, values):
            total = 0
            for v in set(values):
                total += v
            return total
        """)
    rep = contracts.check_task(p)
    assert rep.verdict == contracts.VERDICT_STORE
    rules = {f.rule for f in rep.findings}
    assert {"LMR023", "LMR024", "LMR025"} <= rules
    # hazards make a function store-plane, never in-graph
    assert rep.functions["partitionfn"].verdict == contracts.VERDICT_STORE


def test_contract_hazards_seen_through_helpers(tmp_path):
    p = _write_fixture(tmp_path, "indirect.py", """\
        import time

        def _stamp():
            return time.time()

        def taskfn(emit):
            emit(0, 0)

        def mapfn(key, value, emit):
            emit(key, _stamp())

        def partitionfn(key):
            return 0

        def reducefn(key, values):
            return values[0]
        """)
    rep = contracts.check_task(p)
    hits = [f for f in rep.findings if f.rule == "LMR023"]
    assert len(hits) == 1 and "_stamp" in hits[0].message


def test_contract_sorted_listdir_passes(tmp_path):
    p = _write_fixture(tmp_path, "sortedio.py", """\
        import os

        def taskfn(emit):
            for i, p in enumerate(sorted(os.listdir('.'))):
                emit(i, p)

        def mapfn(key, value, emit):
            emit(key, value)

        def partitionfn(key):
            return 0

        def reducefn(key, values):
            return values[0]
        """)
    rep = contracts.check_task(p)
    assert not [f for f in rep.findings if f.rule == "LMR024"]


def test_contract_pure_numeric_task_is_ingraph(tmp_path):
    p = _write_fixture(tmp_path, "numeric.py", """\
        def taskfn(emit):
            for j in range(8):
                emit(j, j)

        def mapfn(key, value, emit):
            emit(key % 4, value * value + 1)

        def partitionfn(key):
            return key % 4

        def reducefn(key, values):
            return sum(values)
        """)
    rep = contracts.check_task(p)
    assert rep.verdict == contracts.VERDICT_INGRAPH
    assert all(fr.verdict == contracts.VERDICT_INGRAPH
               for fr in rep.functions.values())


def test_contract_unresolvable_module():
    rep = contracts.check_task("no.such.module.anywhere")
    assert rep.verdict == contracts.VERDICT_INVALID
    assert rep.findings[0].rule == "LMR020"


# --- shipped task modules: pinned verdicts (the e2e matrix) -----------------

def test_wordcount_package_is_store_plane_only():
    rep = contracts.check_task(os.path.join(REPO, "examples", "wordcount"))
    assert rep.verdict == contracts.VERDICT_STORE
    assert rep.findings == [], contracts.format_text(rep)
    # mapfn reads files — the whole task is store-plane; the pure sum
    # reducer alone is liftable
    assert rep.functions["mapfn"].verdict == contracts.VERDICT_STORE
    assert rep.functions["reducefn"].verdict == contracts.VERDICT_INGRAPH
    assert set(rep.functions) >= {"taskfn", "mapfn", "partitionfn",
                                  "reducefn", "finalfn"}


def test_extsort_has_ingraph_numeric_path():
    rep = contracts.check_task(
        os.path.join(REPO, "examples", "extsort", "sorttask.py"))
    assert rep.verdict == contracts.VERDICT_STORE
    assert rep.findings == [], contracts.format_text(rep)
    # the range-partition arithmetic and identity fold are the
    # in-graph-eligible numeric path (ROADMAP item 3's oracle)
    assert rep.functions["partitionfn"].verdict == contracts.VERDICT_INGRAPH
    assert rep.functions["reducefn"].verdict == contracts.VERDICT_INGRAPH
    assert rep.functions["mapfn"].verdict == contracts.VERDICT_STORE


def test_coord_task_is_store_plane_and_clean():
    rep = contracts.check_task(
        os.path.join(REPO, "benchmarks", "coord_task.py"))
    assert rep.verdict == contracts.VERDICT_STORE
    assert rep.findings == [], contracts.format_text(rep)


def test_sched_task_is_fully_ingraph():
    rep = contracts.check_task(
        os.path.join(REPO, "benchmarks", "sched_task.py"))
    assert rep.verdict == contracts.VERDICT_INGRAPH
    assert rep.findings == [], contracts.format_text(rep)


def test_task_cli_expect_verdicts():
    env = dict(os.environ, PYTHONPATH=REPO)
    ok = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "task",
         "examples.wordcount", "--expect", "store-plane"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "task",
         "examples.wordcount", "--expect", "in-graph"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert bad.returncode == 1
    fn = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "task",
         "examples.extsort.sorttask", "--expect", "store-plane",
         "--expect-ingraph-fn", "--format", "json"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert fn.returncode == 0, fn.stdout + fn.stderr
    payload = json.loads(fn.stdout)
    verdicts = {name: d["verdict"]
                for name, d in payload["tasks"][0]["functions"].items()}
    assert verdicts["reducefn"] == "in-graph"


# --- whole-repo gates -------------------------------------------------------

def test_repo_deep_pass_clean_and_fast():
    res = dataflow.analyze()
    assert res.findings == [], lint_mod.format_text(res.findings)
    assert res.wall_s < 30.0, f"deep pass took {res.wall_s:.1f}s"
    assert res.reached > 100          # contexts actually propagate


def test_repo_audit_has_no_stale_suppressions():
    audit = run_audit()
    assert audit.findings == [], lint_mod.format_text(audit.findings)
    assert not audit.stale, (audit.stale_pragmas, audit.stale_baseline)


def test_full_rule_catalog_spans_all_three_bands():
    ids = [r["id"] for r in lint_mod.rule_catalog()]
    assert ids == sorted(ids) and len(ids) == len(set(ids))
    for rid in ("LMR001", "LMR013", "LMR014", "LMR015", "LMR016",
                "LMR017", "LMR020", "LMR021", "LMR022", "LMR023",
                "LMR024", "LMR025"):
        assert rid in ids, rid


def test_native_engine_error_is_classified_permanent():
    """The at-head LMR014 fix: the native-engine refusals now raise a
    classified PERMANENT error (retrying cannot rebuild a .so) that
    stays RuntimeError-compatible for pre-taxonomy callers."""
    from lua_mapreduce_tpu.faults.errors import (NativeEngineError,
                                                 PermanentStoreError,
                                                 classify_exception)

    e = NativeEngineError("abi drift")
    assert isinstance(e, RuntimeError)
    assert isinstance(e, PermanentStoreError)
    assert classify_exception(e) is False
