"""Job store tests: claim CAS, status machine, scavenger, stale requeue,
native/Python index interop (analog of task.lua + cnn.lua utests)."""

import threading

import pytest

from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.idx import native_available, open_index
from lua_mapreduce_tpu.coord.idx_py import PyJobIndex
from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job
from lua_mapreduce_tpu.core.constants import Status


def _stores(tmp_path):
    return [MemJobStore(),
            FileJobStore(str(tmp_path / "fs-py"), engine="python"),
            FileJobStore(str(tmp_path / "fs-auto"))]


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_claim_and_status_machine(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    ids = store.insert_jobs("map_jobs", [make_job(i, f"v{i}") for i in range(3)])
    assert ids == [0, 1, 2]

    j = store.claim("map_jobs", "w1")
    assert j is not None and j["_id"] == 0 and j["key"] == 0
    assert j["value"] == "v0"
    assert store.get_job("map_jobs", 0)["status"] == Status.RUNNING

    # double-claim cannot hand out the same job
    j2 = store.claim("map_jobs", "w2")
    assert j2["_id"] == 1

    # CAS transitions honor expectations
    assert store.set_job_status("map_jobs", 0, Status.FINISHED,
                                expect=(Status.RUNNING,))
    assert not store.set_job_status("map_jobs", 0, Status.WRITTEN,
                                    expect=(Status.RUNNING,))
    assert store.set_job_status("map_jobs", 0, Status.WRITTEN,
                                expect=(Status.FINISHED,))

    counts = store.counts("map_jobs")
    assert counts[Status.WRITTEN] == 1
    assert counts[Status.RUNNING] == 1
    assert counts[Status.WAITING] == 1


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_broken_retry_and_scavenge(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    store.insert_jobs("map_jobs", [make_job(0, "x")])
    for expected_reps in (1, 2, 3):
        j = store.claim("map_jobs", "w")
        assert j is not None
        store.set_job_status("map_jobs", 0, Status.BROKEN)
        assert store.get_job("map_jobs", 0)["repetitions"] == expected_reps
    # BROKEN is re-claimable until the scavenger fails it (3 retries)
    assert store.scavenge("map_jobs", 3) == 1
    assert store.get_job("map_jobs", 0)["status"] == Status.FAILED
    assert store.claim("map_jobs", "w") is None


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_requeue_stale_running(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "x")])
    store.claim("ns", "dead-worker")
    assert store.requeue_stale("ns", older_than_s=3600) == 0  # too young
    assert store.requeue_stale("ns", older_than_s=0.0) == 1
    j = store.get_job("ns", 0)
    assert j["status"] == Status.BROKEN and j["repetitions"] == 1
    assert store.claim("ns", "live-worker")["_id"] == 0


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_requeue_stale_covers_finished(tmp_path, idx):
    """Regression: a worker killed between FINISHED and WRITTEN must not
    wedge the barrier — FINISHED is requeueable too."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "x")])
    store.claim("ns", "w")
    store.set_job_status("ns", 0, Status.FINISHED, expect=(Status.RUNNING,))
    assert store.requeue_stale("ns", older_than_s=0.0) == 1
    assert store.get_job("ns", 0)["status"] == Status.BROKEN


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_ownership_cas_blocks_stale_claimant(tmp_path, idx):
    """Regression: a worker whose claim was requeued and re-claimed by
    another worker must not be able to flip the job's status."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "x")])
    store.claim("ns", "worker-A")
    store.requeue_stale("ns", older_than_s=0.0)     # A judged dead
    store.claim("ns", "worker-B")                   # B re-claims

    # A's late transitions miss (both finish and mark-broken paths)
    assert not store.set_job_status("ns", 0, Status.FINISHED,
                                    expect=(Status.RUNNING,),
                                    expect_worker="worker-A")
    assert not store.set_job_status("ns", 0, Status.BROKEN,
                                    expect_worker="worker-A")
    reps_before = store.get_job("ns", 0)["repetitions"]

    # B's transitions land
    assert store.set_job_status("ns", 0, Status.FINISHED,
                                expect=(Status.RUNNING,),
                                expect_worker="worker-B")
    assert store.get_job("ns", 0)["repetitions"] == reps_before


def test_cas_on_dropped_namespace_is_false(tmp_path):
    """Regression: straggler CAS after drop_ns returns False (both store
    kinds), never raises."""
    for store in _stores(tmp_path)[:2]:
        store.insert_jobs("ns", [make_job(0, "x")])
        store.claim("ns", "w")
        store.drop_ns("ns")
        assert store.set_job_status("ns", 0, Status.FINISHED,
                                    expect=(Status.RUNNING,)) is False
        store.set_job_times("ns", 0, {"started": 0, "finished": 0,
                                      "written": 0, "cpu": 0, "real": 0})


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_preferred_and_steal(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(i, i) for i in range(4)])
    j = store.claim("ns", "w", preferred_ids=[2])
    assert j["_id"] == 2
    # preferred taken, no steal → nothing
    assert store.claim("ns", "w", preferred_ids=[2], steal=False) is None
    # steal allowed → first free
    assert store.claim("ns", "w", preferred_ids=[2], steal=True)["_id"] == 0


def test_errors_stream_and_task_doc(tmp_path):
    for store in _stores(tmp_path)[:2]:
        store.put_task({"_id": "unique", "status": "WAIT", "iteration": 1})
        store.update_task({"status": "MAP"})
        assert store.get_task()["status"] == "MAP"

        store.insert_error("w1", "boom")
        store.insert_error("w2", "bang")
        errs = store.drain_errors()
        assert [e["worker"] for e in errs] == ["w1", "w2"]
        assert store.drain_errors() == []

        store.delete_task()
        assert store.get_task() is None


def test_native_python_interop(tmp_path):
    if not native_available():
        pytest.skip("native index unavailable")
    path = str(tmp_path / "interop.idx")
    nat = open_index(path, "native")
    py = PyJobIndex(path)
    assert type(nat).__name__ == "NativeJobIndex"

    nat.insert(4)
    assert py.count() == 4
    assert py.claim(worker=7, now=1.0) == 0       # python claims
    assert nat.claim(worker=8, now=2.0) == 1      # native claims next
    s0 = py.get(0)
    assert s0[0] == Status.RUNNING and s0[2] == 7
    s1 = nat.get(1)
    assert s1[0] == Status.RUNNING and s1[2] == 8
    assert nat.cas_status(0, Status.BROKEN)
    assert py.get(0)[1] == 1                      # repetition visible to py
    c = nat.counts()
    assert c[Status.RUNNING] == 1 and c[Status.BROKEN] == 1
    assert c[Status.WAITING] == 2


def test_concurrent_claims_are_exclusive(tmp_path):
    """N threads hammering claim() must hand out each job exactly once."""
    store = FileJobStore(str(tmp_path / "conc"))
    n_jobs, n_workers = 40, 8
    store.insert_jobs("ns", [make_job(i, i) for i in range(n_jobs)])
    claimed = []
    lock = threading.Lock()

    def grab(wid):
        while True:
            j = store.claim("ns", f"w{wid}")
            if j is None:
                return
            with lock:
                claimed.append(j["_id"])

    threads = [threading.Thread(target=grab, args=(i,)) for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(claimed) == list(range(n_jobs))  # no dup, no loss
