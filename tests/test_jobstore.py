"""Job store tests: claim CAS, status machine, scavenger, stale requeue,
native/Python index interop (analog of task.lua + cnn.lua utests)."""

import threading
import time

import pytest

from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.idx import native_available, open_index
from lua_mapreduce_tpu.coord.idx_py import PyJobIndex
from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job
from lua_mapreduce_tpu.core.constants import Status


def _stores(tmp_path):
    return [MemJobStore(),
            FileJobStore(str(tmp_path / "fs-py"), engine="python"),
            FileJobStore(str(tmp_path / "fs-auto"))]


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_claim_and_status_machine(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    ids = store.insert_jobs("map_jobs", [make_job(i, f"v{i}") for i in range(3)])
    assert ids == [0, 1, 2]

    j = store.claim("map_jobs", "w1")
    assert j is not None and j["_id"] == 0 and j["key"] == 0
    assert j["value"] == "v0"
    assert store.get_job("map_jobs", 0)["status"] == Status.RUNNING

    # double-claim cannot hand out the same job
    j2 = store.claim("map_jobs", "w2")
    assert j2["_id"] == 1

    # CAS transitions honor expectations
    assert store.set_job_status("map_jobs", 0, Status.FINISHED,
                                expect=(Status.RUNNING,))
    assert not store.set_job_status("map_jobs", 0, Status.WRITTEN,
                                    expect=(Status.RUNNING,))
    assert store.set_job_status("map_jobs", 0, Status.WRITTEN,
                                expect=(Status.FINISHED,))

    counts = store.counts("map_jobs")
    assert counts[Status.WRITTEN] == 1
    assert counts[Status.RUNNING] == 1
    assert counts[Status.WAITING] == 1


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_broken_retry_and_scavenge(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    store.insert_jobs("map_jobs", [make_job(0, "x")])
    for expected_reps in (1, 2, 3):
        j = store.claim("map_jobs", "w")
        assert j is not None
        store.set_job_status("map_jobs", 0, Status.BROKEN)
        assert store.get_job("map_jobs", 0)["repetitions"] == expected_reps
    # BROKEN is re-claimable until the scavenger fails it (3 retries)
    assert store.scavenge("map_jobs", 3) == 1
    assert store.get_job("map_jobs", 0)["status"] == Status.FAILED
    assert store.claim("map_jobs", "w") is None


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_requeue_stale_running(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "x")])
    store.claim("ns", "dead-worker")
    assert store.requeue_stale("ns", older_than_s=3600) == 0  # too young
    assert store.requeue_stale("ns", older_than_s=0.0) == 1
    j = store.get_job("ns", 0)
    assert j["status"] == Status.BROKEN and j["repetitions"] == 1
    assert store.claim("ns", "live-worker")["_id"] == 0


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_requeue_stale_covers_finished(tmp_path, idx):
    """Regression: a worker killed between FINISHED and WRITTEN must not
    wedge the barrier — FINISHED is requeueable too."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "x")])
    store.claim("ns", "w")
    store.set_job_status("ns", 0, Status.FINISHED, expect=(Status.RUNNING,))
    assert store.requeue_stale("ns", older_than_s=0.0) == 1
    assert store.get_job("ns", 0)["status"] == Status.BROKEN


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_heartbeat_keeps_long_job_alive(tmp_path, idx):
    """Staleness measures SILENCE, not elapsed time: a RUNNING job whose
    worker heartbeats is spared by requeue_stale however old its claim
    is, while a silent sibling is requeued (VERDICT r3 item 8)."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "slow"), make_job(1, "dead")])
    store.claim("ns", "live-worker")        # job 0
    store.claim("ns", "dead-worker")        # job 1
    time.sleep(0.3)
    assert store.heartbeat("ns", 0, "live-worker")
    # cutoff 0.2s ago: both claims are 0.3s old, but job 0 beat just now
    assert store.requeue_stale("ns", older_than_s=0.2) == 1
    assert store.get_job("ns", 0)["status"] == Status.RUNNING
    assert store.get_job("ns", 1)["status"] == Status.BROKEN
    # once the beats stop, job 0 goes stale like anything else
    time.sleep(0.3)
    assert store.requeue_stale("ns", older_than_s=0.2) == 1
    assert store.get_job("ns", 0)["status"] == Status.BROKEN


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_heartbeat_ownership_and_state(tmp_path, idx):
    """Heartbeats are ownership-CASed like every other transition: a
    stale claimant cannot keep a re-claimed job alive, and only
    RUNNING|FINISHED jobs (the requeueable states) accept beats."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "x"), make_job(1, "y")])
    store.claim("ns", "w1")                       # job 0
    assert not store.heartbeat("ns", 0, "w2")     # non-owner misses
    assert not store.heartbeat("ns", 1, "w1")     # WAITING: no beat
    assert not store.heartbeat("ns", 99, "w1")    # out of bounds
    # FINISHED still beats (covers the FINISHED→WRITTEN kill gap)
    store.set_job_status("ns", 0, Status.FINISHED, expect=(Status.RUNNING,))
    assert store.heartbeat("ns", 0, "w1")
    store.set_job_status("ns", 0, Status.WRITTEN, expect=(Status.FINISHED,))
    assert not store.heartbeat("ns", 0, "w1")     # WRITTEN: done


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_ownership_cas_blocks_stale_claimant(tmp_path, idx):
    """Regression: a worker whose claim was requeued and re-claimed by
    another worker must not be able to flip the job's status."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(0, "x")])
    store.claim("ns", "worker-A")
    store.requeue_stale("ns", older_than_s=0.0)     # A judged dead
    store.claim("ns", "worker-B")                   # B re-claims

    # A's late transitions miss (both finish and mark-broken paths)
    assert not store.set_job_status("ns", 0, Status.FINISHED,
                                    expect=(Status.RUNNING,),
                                    expect_worker="worker-A")
    assert not store.set_job_status("ns", 0, Status.BROKEN,
                                    expect_worker="worker-A")
    reps_before = store.get_job("ns", 0)["repetitions"]

    # B's transitions land
    assert store.set_job_status("ns", 0, Status.FINISHED,
                                expect=(Status.RUNNING,),
                                expect_worker="worker-B")
    assert store.get_job("ns", 0)["repetitions"] == reps_before


TIMES = {"started": 1.0, "finished": 2.0, "written": 3.0, "cpu": 0.5,
         "real": 2.0}


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_claim_batch_conformance(tmp_path, idx):
    """Batch-lease claim semantics, identical across every store: up to
    k jobs in one pass, claim order, preferred-first, steal=False
    restriction, exactly-once handout, empty result when drained."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(i, {"v": i}) for i in range(6)])

    batch = store.claim_batch("ns", "w1", k=3)
    assert [d["_id"] for d in batch] == [0, 1, 2]
    assert all(d["status"] == Status.RUNNING and d["worker"] == "w1"
               and d["value"] == {"v": d["_id"]} for d in batch)

    # preferred ids come first; steal fills the remainder
    batch = store.claim_batch("ns", "w2", k=2, preferred_ids=[5])
    assert [d["_id"] for d in batch] == [5, 3]
    # steal=False restricts to preferred (all taken -> nothing)
    assert store.claim_batch("ns", "w2", k=2, preferred_ids=[5],
                             steal=False) == []
    # k larger than what's left: partial batch, then empty
    assert [d["_id"] for d in store.claim_batch("ns", "w3", k=10)] == [4]
    assert store.claim_batch("ns", "w3", k=10) == []


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_commit_batch_conformance(tmp_path, idx):
    """Batch commit: RUNNING→WRITTEN with times, CASed per entry on
    ownership — a claim lost mid-lease is skipped without disturbing the
    new claimant, and the rest of the batch lands."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(i, i) for i in range(4)])
    jids = [d["_id"] for d in store.claim_batch("ns", "w1", k=3)]
    assert jids == [0, 1, 2]

    # job 1's claim is stale-requeued and re-claimed by another worker
    store.set_job_status("ns", 1, Status.BROKEN)
    assert store.claim("ns", "thief")["_id"] == 1

    # job 2 is mid-flight in the v1 crash window (FINISHED, not yet
    # WRITTEN): commit_batch must retire RUNNING and FINISHED alike —
    # identical across every store — instead of leaving it for the
    # stale requeue to re-execute completed work
    assert store.set_job_status("ns", 2, Status.FINISHED,
                                expect=(Status.RUNNING,),
                                expect_worker="w1")
    done = store.commit_batch("ns", "w1", [(j, TIMES) for j in jids])
    assert done == [0, 2]
    for jid in (0, 2):
        doc = store.get_job("ns", jid)
        assert doc["status"] == Status.WRITTEN
        assert doc["times"] == TIMES
    assert store.get_job("ns", 1)["status"] == Status.RUNNING
    # the thief's own commit still lands
    assert store.commit_batch("ns", "thief", [(1, TIMES)]) == [1]
    counts = store.counts("ns")
    assert counts[Status.WRITTEN] == 3 and counts[Status.WAITING] == 1


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_release_batch_returns_unstarted_jobs(tmp_path, idx):
    """A batch aborted partway releases its unstarted tail: RUNNING →
    WAITING on ownership, repetitions untouched (the jobs never ran, so
    they must not creep toward the scavenger's FAILED threshold)."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(i, i) for i in range(3)])
    store.claim_batch("ns", "w1", k=3)
    assert store.release_batch("ns", "other", [1, 2]) == 0   # non-owner
    assert store.release_batch("ns", "w1", [1, 2]) == 2
    for jid in (1, 2):
        doc = store.get_job("ns", jid)
        assert doc["status"] == Status.WAITING
        assert doc["repetitions"] == 0
    # released jobs are immediately re-claimable
    assert store.claim("ns", "w2")["_id"] == 1


@pytest.mark.parametrize("idx", [0, 1, 2], ids=["mem", "file-py", "file-auto"])
def test_heartbeat_batch_beats_whole_lease(tmp_path, idx):
    """One beat refreshes every leased job this worker still owns; jobs
    already committed or re-claimed simply miss."""
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(i, i) for i in range(4)])
    jids = [d["_id"] for d in store.claim_batch("ns", "w1", k=3)]
    store.commit_batch("ns", "w1", [(0, TIMES)])      # retired early
    assert store.heartbeat_batch("ns", jids, "w1") == 2
    assert store.heartbeat_batch("ns", jids, "other") == 0
    time.sleep(0.25)
    assert store.heartbeat_batch("ns", [1], "w1") == 1
    # job 1 beat just now survives the requeue; job 2's last signal is
    # the claim itself — each lease member is judged INDEPENDENTLY
    assert store.requeue_stale("ns", older_than_s=0.2) == 1
    assert store.get_job("ns", 1)["status"] == Status.RUNNING
    assert store.get_job("ns", 2)["status"] == Status.BROKEN


def test_batch_interop_native_python(tmp_path):
    """Batch ops mix freely across engines on the same file: native
    claims a lease, python commits half of it, native sees the result."""
    if not native_available():
        pytest.skip("native index unavailable")
    path = str(tmp_path / "interop-b.idx")
    nat = open_index(path, "native")
    py = PyJobIndex(path)
    nat.insert(4)
    assert [j for j, _ in nat.claim_batch(7, 1.0, 3)] == [0, 1, 2]
    t5 = (1.0, 2.0, 3.0, 0.5, 2.0)
    assert py.commit_batch([(0, t5), (1, t5)], worker=7) == [True, True]
    got = nat.get(0)
    assert got[0] == Status.WRITTEN and got[4] == t5
    assert py.get(2)[0] == Status.RUNNING
    assert nat.heartbeat_batch([2], 7, 9.0) == 1
    assert py.cas_status_batch([2], Status.WAITING,
                               1 << Status.RUNNING, 7) == [True]


def test_cas_on_dropped_namespace_is_false(tmp_path):
    """Regression: straggler CAS after drop_ns returns False (both store
    kinds), never raises."""
    for store in _stores(tmp_path)[:2]:
        store.insert_jobs("ns", [make_job(0, "x")])
        store.claim("ns", "w")
        store.drop_ns("ns")
        assert store.set_job_status("ns", 0, Status.FINISHED,
                                    expect=(Status.RUNNING,)) is False
        store.set_job_times("ns", 0, {"started": 0, "finished": 0,
                                      "written": 0, "cpu": 0, "real": 0})


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file-py"])
def test_preferred_and_steal(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    store.insert_jobs("ns", [make_job(i, i) for i in range(4)])
    j = store.claim("ns", "w", preferred_ids=[2])
    assert j["_id"] == 2
    # preferred taken, no steal → nothing
    assert store.claim("ns", "w", preferred_ids=[2], steal=False) is None
    # steal allowed → first free
    assert store.claim("ns", "w", preferred_ids=[2], steal=True)["_id"] == 0


def test_errors_stream_and_task_doc(tmp_path):
    for store in _stores(tmp_path)[:2]:
        store.put_task({"_id": "unique", "status": "WAIT", "iteration": 1})
        store.update_task({"status": "MAP"})
        assert store.get_task()["status"] == "MAP"

        store.insert_error("w1", "boom")
        store.insert_error("w2", "bang")
        errs = store.drain_errors()
        assert [e["worker"] for e in errs] == ["w1", "w2"]
        assert store.drain_errors() == []

        store.delete_task()
        assert store.get_task() is None


def test_native_python_interop(tmp_path):
    if not native_available():
        pytest.skip("native index unavailable")
    path = str(tmp_path / "interop.idx")
    nat = open_index(path, "native")
    py = PyJobIndex(path)
    assert type(nat).__name__ == "NativeJobIndex"

    nat.insert(4)
    assert py.count() == 4
    assert py.claim(worker=7, now=1.0) == 0       # python claims
    assert nat.claim(worker=8, now=2.0) == 1      # native claims next
    s0 = py.get(0)
    assert s0[0] == Status.RUNNING and s0[2] == 7
    s1 = nat.get(1)
    assert s1[0] == Status.RUNNING and s1[2] == 8
    assert nat.cas_status(0, Status.BROKEN)
    assert py.get(0)[1] == 1                      # repetition visible to py
    c = nat.counts()
    assert c[Status.RUNNING] == 1 and c[Status.BROKEN] == 1
    assert c[Status.WAITING] == 2


def test_concurrent_claims_are_exclusive(tmp_path):
    """N threads hammering claim() must hand out each job exactly once."""
    store = FileJobStore(str(tmp_path / "conc"))
    n_jobs, n_workers = 40, 8
    store.insert_jobs("ns", [make_job(i, i) for i in range(n_jobs)])
    claimed = []
    lock = threading.Lock()

    def grab(wid):
        while True:
            j = store.claim("ns", f"w{wid}")
            if j is None:
                return
            with lock:
                claimed.append(j["_id"])

    threads = [threading.Thread(target=grab, args=(i,)) for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(claimed) == list(range(n_jobs))  # no dup, no loss


def test_batched_inserts_reference_fanin_scale(tmp_path):
    """Reference fan-in scale (README.md:59: ~2,000 map jobs / 1,970 run
    files -> 10 reduces): inserting 2,000 jobs must write O(batches)
    control-plane files, not one payload file per job (cnn.lua:80-111
    batched-insert analog), and claims must read the right payloads back
    across a fresh store instance (another process's view)."""
    import os
    import time

    store = FileJobStore(str(tmp_path))
    n = 2000
    t0 = time.perf_counter()
    ids = store.insert_jobs("map_jobs", [make_job(f"k{i}", {"split": i})
                                         for i in range(n)])
    insert_dt = time.perf_counter() - t0
    assert ids == list(range(n))

    ns_dir = os.path.join(str(tmp_path), "map_jobs.d")
    batch_files = [f for f in os.listdir(ns_dir) if f.startswith("b")]
    assert len(batch_files) == 1, batch_files  # 2,000 < MAX_PENDING_INSERTS
    assert insert_dt < 5.0, f"2,000-job insert took {insert_dt:.2f}s"

    # another process's store: payloads resolve through the manifest
    store2 = FileJobStore(str(tmp_path))
    t0 = time.perf_counter()
    seen = set()
    for _ in range(n):
        doc = store2.claim("map_jobs", "w1")
        assert doc is not None
        assert doc["value"] == {"split": doc["_id"]}
        seen.add(doc["_id"])
    claim_dt = time.perf_counter() - t0
    assert seen == set(range(n))
    assert store2.claim("map_jobs", "w1") is None
    # claims stay cheap: amortized well under a millisecond of payload
    # overhead each (the index CAS dominates)
    assert claim_dt < 30.0, f"2,000 claims took {claim_dt:.2f}s"


def test_batch_cache_not_stale_across_loop_reinsert(tmp_path):
    """The "loop" protocol drops and re-inserts a namespace each
    iteration; a long-lived worker-side store instance must see the NEW
    payloads, not its cached previous-iteration batch."""
    server_store = FileJobStore(str(tmp_path))
    worker_store = FileJobStore(str(tmp_path))

    server_store.insert_jobs("map_jobs", [make_job("a", {"it": 1})])
    doc = worker_store.claim("map_jobs", "w")
    assert doc["value"] == {"it": 1}

    server_store.drop_ns("map_jobs")
    server_store.insert_jobs("map_jobs", [make_job("a", {"it": 2})])
    doc = worker_store.claim("map_jobs", "w")
    assert doc["value"] == {"it": 2}, "stale payload from dropped iteration"


def test_multi_batch_chunking(tmp_path, monkeypatch):
    """Inserts above MAX_PENDING_INSERTS split into multiple manifests
    (flush threshold, cnn.lua:80-96)."""
    import os

    from lua_mapreduce_tpu.coord import filestore
    monkeypatch.setattr(filestore, "MAX_PENDING_INSERTS", 64)
    store = FileJobStore(str(tmp_path))
    store.insert_jobs("map_jobs", [make_job(i, i) for i in range(200)])
    ns_dir = os.path.join(str(tmp_path), "map_jobs.d")
    batches = sorted(f for f in os.listdir(ns_dir) if f.startswith("b"))
    assert len(batches) == 4       # 64+64+64+8
    fresh = FileJobStore(str(tmp_path))
    assert fresh.get_job("map_jobs", 170)["value"] == 170
    assert fresh.get_job("map_jobs", 0)["value"] == 0


def test_payload_cache_isolated_from_caller_mutation(tmp_path):
    """A claimant mutating job['value'] in place must not poison the
    process-wide payload cache — the retry path depends on re-reading the
    original payload (code-review r2 finding)."""
    store = FileJobStore(str(tmp_path))
    store.insert_jobs("map_jobs", [make_job("k", {"split": 7, "xs": [1]})])
    doc = store.claim("map_jobs", "w1")
    doc["value"].pop("split")
    doc["value"]["xs"].append(2)
    again = store.get_job("map_jobs", 0)
    assert again["value"] == {"split": 7, "xs": [1]}


def test_crash_orphaned_manifest_is_superseded(tmp_path):
    """A manifest written by a crashed insert (no idx.insert committed)
    must not shadow a later insert's payloads, and duplicate bases must
    not break payload resolution (code-review r2 finding)."""
    import json
    import os

    store = FileJobStore(str(tmp_path))
    ns_dir = os.path.join(str(tmp_path), "map_jobs.d")
    os.makedirs(ns_dir, exist_ok=True)
    # simulate: crash landed b0_3.json but never inserted index records
    with open(os.path.join(ns_dir, "b0_3.json"), "w") as f:
        json.dump([{"key": "stale", "value": i} for i in range(3)], f)

    store.insert_jobs("map_jobs", [make_job("fresh", {"n": i})
                                   for i in range(2)])
    fresh = FileJobStore(str(tmp_path))
    got = fresh.claim("map_jobs", "w")
    assert got["key"] == "fresh"
    assert got["value"] in ({"n": 0}, {"n": 1})
    names = sorted(f for f in os.listdir(ns_dir) if f.startswith("b"))
    assert names == ["b0_2.json"], names


def test_native_python_abi_drift_guard():
    """The v3 layout constants (JSIX0003, 16B header, 88B records) and
    the status enum must be asserted equal on both index engines: the
    Python side pins them at import, and the native build exports
    jsx_abi() which coord/idx.py verifies at load. Both engines write
    the same files — drift is corruption, and must fail loudly."""
    import ctypes

    from lua_mapreduce_tpu.coord import idx_py
    from lua_mapreduce_tpu.coord.idx import _load

    # python side: the import-time guard already ran; re-assert the
    # values it pinned
    assert idx_py.MAGIC == b"JSIX0003"
    assert idx_py.HEADER_SIZE == 16 and idx_py.RECORD_SIZE == 88
    assert [int(s) for s in Status] == [0, 1, 2, 3, 4, 5]

    if not native_available():
        pytest.skip("native engine unavailable in this environment")
    lib = _load()
    magic = ctypes.create_string_buffer(8)
    sizes = (ctypes.c_int64 * 2)()
    statuses = (ctypes.c_int32 * 6)()
    assert lib.jsx_abi(magic, sizes, statuses) == 1
    assert magic.raw == idx_py.MAGIC
    assert (sizes[0], sizes[1]) == (idx_py.HEADER_SIZE, idx_py.RECORD_SIZE)
    assert list(statuses) == [int(s) for s in Status]


def test_mem_store_claim_timestamps_decided_before_lock():
    """Lease stamps come from one clock read per batch (hoisted above
    the lock — lint rule LMR004): every job of one claim_batch carries
    the identical started_time."""
    store = MemJobStore()
    store.insert_jobs("map_jobs", [make_job(i, i) for i in range(4)])
    docs = store.claim_batch("map_jobs", "w1", k=4)
    assert len(docs) == 4
    assert len({d["started_time"] for d in docs}) == 1
