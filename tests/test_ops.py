"""Kernel correctness: Pallas (interpret mode on CPU) ≡ XLA reference.

The reference has no kernel tests of its own (kernels live in the
external APRIL-ANN toolkit, SURVEY.md §2.4); the framework's kernels get
the golden-diff treatment instead: every Pallas op must match its XLA
reference implementation bit-for-tolerance on the same inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lua_mapreduce_tpu import ops

RTOL = 1e-4   # K-blocked accumulation reorders float sums vs XLA
ATOL = 1e-4


def rand(*shape, seed=0, dtype=np.float32):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype(dtype))


# ---------------------------------------------------------------- matmul

@pytest.mark.parametrize("m,k,n", [
    (8, 128, 128),          # single tile
    (256, 256, 256),        # exact multi-tile
    (100, 70, 50),          # ragged → padding path
    (1, 256, 10),           # vector-ish
])
def test_matmul_matches_xla(m, k, n):
    a, b = rand(m, k, seed=1), rand(k, n, seed=2)
    want = ops.matmul(a, b, backend="xla")
    got = ops.matmul(a, b, backend="pallas_interpret", block_m=128,
                     block_n=128, block_k=128)
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_matmul_bf16_inputs_f32_accumulate():
    a = rand(64, 256, seed=3).astype(jnp.bfloat16)
    b = rand(256, 64, seed=4).astype(jnp.bfloat16)
    got = ops.matmul(a, b, backend="pallas_interpret", out_dtype=jnp.float32)
    want = jnp.dot(a, b, preferred_element_type=jnp.float32)
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)


def test_matmul_shape_mismatch():
    with pytest.raises(ValueError):
        ops.matmul(rand(4, 5), rand(6, 7), backend="pallas_interpret")


# --------------------------------------------------------------- softmax

@pytest.mark.parametrize("shape", [(4, 10), (33, 257), (2, 3, 100)])
def test_log_softmax_matches_xla(shape):
    x = rand(*shape, seed=5) * 10.0
    got = ops.log_softmax(x, backend="pallas_interpret")
    want = jax.nn.log_softmax(x, axis=-1)
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_softmax_rows_sum_to_one():
    x = rand(16, 40, seed=6) * 5.0
    got = ops.softmax(x, backend="pallas_interpret")
    want = jax.nn.softmax(x, axis=-1)
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(jnp.sum(got, axis=-1), 1.0, rtol=1e-5)


def test_log_softmax_extreme_values_stable():
    x = jnp.array([[1e4, -1e4, 0.0, 5.0]], jnp.float32)
    got = ops.log_softmax(x, backend="pallas_interpret")
    assert bool(jnp.all(jnp.isfinite(got)))


# ------------------------------------------------------------------ conv

@pytest.mark.parametrize("cfg", [
    dict(n=2, h=16, w=16, cin=3, cout=6, k=5, stride=1, padding="VALID"),
    dict(n=1, h=14, w=14, cin=6, cout=16, k=5, stride=1, padding="VALID"),
    dict(n=2, h=8, w=8, cin=4, cout=8, k=3, stride=2, padding="SAME"),
    dict(n=1, h=7, w=9, cin=2, cout=4, k=3, stride=1, padding=1),
    dict(n=1, h=8, w=8, cin=3, cout=4, k=2, stride=1, padding="SAME"),
    dict(n=1, h=9, w=9, cin=2, cout=4, k=4, stride=2, padding="SAME"),
])
def test_conv2d_matches_xla(cfg):
    x = rand(cfg["n"], cfg["h"], cfg["w"], cfg["cin"], seed=7)
    w = rand(cfg["k"], cfg["k"], cfg["cin"], cfg["cout"], seed=8) * 0.1
    b = rand(cfg["cout"], seed=9)
    want = ops.conv2d(x, w, b, stride=cfg["stride"],
                      padding=cfg["padding"], backend="xla")
    got = ops.conv2d(x, w, b, stride=cfg["stride"],
                     padding=cfg["padding"], backend="pallas_interpret")
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.heavy
def test_conv2d_same_preserves_shape_even_kernel():
    """TF-style SAME: output spatial dims == input dims at stride 1, even
    for even kernel sizes (needs asymmetric padding)."""
    x = rand(1, 8, 8, 3, seed=20)
    for k in (2, 3, 4, 5):
        w = rand(k, k, 3, 4, seed=21) * 0.1
        for backend in ("xla", "pallas_interpret"):
            out = ops.conv2d(x, w, padding="SAME", backend=backend)
            assert out.shape == (1, 8, 8, 4), (k, backend, out.shape)


def test_conv2d_grad_flows():
    """The im2col+matmul path must be differentiable (training uses it)."""
    x = rand(2, 8, 8, 3, seed=10)
    w = rand(3, 3, 3, 4, seed=11) * 0.1

    def loss(w):
        return jnp.sum(ops.conv2d(x, w, backend="pallas_interpret") ** 2)

    g = jax.grad(loss)(w)
    g_ref = jax.grad(
        lambda w: jnp.sum(ops.conv2d(x, w, backend="xla") ** 2))(w)
    np.testing.assert_allclose(g, g_ref, rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------------ pool

@pytest.mark.parametrize("window,stride", [(2, None), (2, 2), (3, 2)])
def test_maxpool_matches_xla(window, stride):
    x = rand(2, 12, 12, 5, seed=12)
    want = ops.maxpool2d(x, window, stride, backend="xla")
    got = ops.maxpool2d(x, window, stride, backend="pallas_interpret")
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_avgpool_matches_xla():
    x = rand(3, 8, 8, 4, seed=13)
    want = ops.avgpool2d(x, 2, backend="xla")
    got = ops.avgpool2d(x, 2, backend="pallas_interpret")
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_default_backend_mapping():
    on_tpu = jax.default_backend() == "tpu"
    # off-TPU everything is XLA (Pallas-TPU kernels don't lower); on TPU
    # "auto" resolves per op to the measured winner from
    # benchmarks/results/kernels.json (ops/__init__._TPU_AUTO_POLICY)
    assert ops.default_backend() == ("pallas" if on_tpu else "xla")
    for op, tpu_winner in ops._TPU_AUTO_POLICY.items():
        want = tpu_winner if on_tpu else "xla"
        assert ops.default_backend(op) == want
        assert ops.resolve_backend("auto", op) == want
    # explicit backends are never overridden by the policy
    assert ops.resolve_backend("pallas", "conv2d") == "pallas"
    with pytest.raises(ValueError):
        ops.resolve_backend("cuda")


# ------------------------------------------------- grads (training path)

def test_log_softmax_grad_matches_xla():
    x = rand(8, 33, seed=30) * 4.0

    def loss(x, backend):
        return jnp.sum(ops.log_softmax(x, backend=backend) ** 2)

    g = jax.grad(loss)(x, "pallas_interpret")
    g_ref = jax.grad(loss)(x, "xla")
    np.testing.assert_allclose(g, g_ref, rtol=1e-4, atol=1e-5)


def test_softmax_grad_matches_xla():
    x = rand(6, 20, seed=31) * 3.0

    def loss(x, backend):
        return jnp.sum(ops.softmax(x, backend=backend) ** 3)

    g = jax.grad(loss)(x, "pallas_interpret")
    g_ref = jax.grad(loss)(x, "xla")
    np.testing.assert_allclose(g, g_ref, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("pool,window,stride", [
    (ops.maxpool2d, 2, None), (ops.maxpool2d, 3, 2),
    (ops.avgpool2d, 2, None),
])
def test_pool_grad_matches_xla(pool, window, stride):
    x = rand(2, 8, 8, 4, seed=32)

    def loss(x, backend):
        return jnp.sum(pool(x, window, stride, backend=backend) ** 2)

    g = jax.grad(loss)(x, "pallas_interpret")
    g_ref = jax.grad(loss)(x, "xla")
    np.testing.assert_allclose(g, g_ref, rtol=1e-5, atol=1e-6)
