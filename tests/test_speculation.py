"""Speculative execution suite (DESIGN §21).

Three layers:

1. **Store conformance** — the duplicate-lease protocol
   (speculate / claim_spec / cancel_spec, first-commit-wins, shadow
   heartbeats, unlease dissolution) behaves identically on MemJobStore,
   FileJobStore(python) and FileJobStore(native) — the same
   three-stores × both-index-engines matrix as the batch-lease suite.

2. **Death regressions** — the clone dying mid-run leaves the original
   to commit with ZERO repetition bumps; the original dying leaves the
   clone's heartbeats protecting the job from the stale requeue until
   the clone commits, again zero bumps. (Thread workers can't take a
   real SIGKILL; "death" here is the protocol-visible shape — the
   holder simply never issues another op — which is exactly what the
   store sees after a kill. The multiprocess SIGKILL churn suite covers
   process death for the shared lease machinery.)

3. **Model-checker integration** — the both-commit race replayed
   against the real stores via ``replay_trace`` (both directions), and
   the seeded loser-commit-skips-winner-CAS race diverging at the real
   store's guarding CAS.

Engine-level behavior (detector, clone probe, revocation, EWMA
persistence) is covered here with in-process pools; the chaos
acceptance matrix lives in tests/test_chaos.py.
"""

import dataclasses
import threading
import time

import pytest

from lua_mapreduce_tpu.analysis import protocol as proto
from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job
from lua_mapreduce_tpu.core.constants import Status

NS = "map_jobs"
TIMES = {"started": 1.0, "finished": 2.0, "written": 3.0, "cpu": 0.1,
         "real": 2.0}


def _stores(tmp_path):
    return [MemJobStore(),
            FileJobStore(str(tmp_path / "fs-py"), engine="python"),
            FileJobStore(str(tmp_path / "fs-auto"))]


def _seed(store, n=3):
    return store.insert_jobs(NS, [make_job(f"k{i}", i) for i in range(n)])


# --- store conformance -------------------------------------------------------

def test_speculate_lifecycle_all_stores(tmp_path):
    """speculate CAS: only RUNNING, only once; claim_spec: never the
    job's own claimant, one shadow max; cancel_spec: holder-CASed."""
    for store in _stores(tmp_path):
        _seed(store)
        assert not store.speculate(NS, 0)          # WAITING: refused
        d = store.claim_batch(NS, "orig", 1)[0]
        jid = d["_id"]
        assert store.speculate(NS, jid)
        assert not store.speculate(NS, jid)        # one shadow at a time
        assert store.claim_spec(NS, "orig") is None  # never your own job
        clone = store.claim_spec(NS, "shadow")
        assert clone is not None and clone["_id"] == jid
        assert clone.get("speculative") is True
        assert clone["repetitions"] == 0
        assert store.claim_spec(NS, "third") is None  # lease is taken
        assert not store.cancel_spec(NS, jid, "third")  # holder CAS
        assert store.cancel_spec(NS, jid, "shadow")
        assert not store.cancel_spec(NS, jid, "shadow")  # idempotent


@pytest.mark.parametrize("winner", ["clone", "original"])
def test_first_commit_wins_both_directions(tmp_path, winner):
    """Whoever commits first retires the job; the loser's commit fails
    the status CAS and changes NOTHING — never a double commit, never a
    repetition bump against either worker."""
    for store in _stores(tmp_path):
        _seed(store)
        jid = store.claim_batch(NS, "orig", 1)[0]["_id"]
        store.speculate(NS, jid)
        assert store.claim_spec(NS, "shadow")["_id"] == jid
        first, second = (("shadow", "orig") if winner == "clone"
                         else ("orig", "shadow"))
        assert store.commit_batch(NS, first, [(jid, TIMES)]) == [jid]
        assert store.commit_batch(NS, second, [(jid, TIMES)]) == []
        doc = store.get_job(NS, jid)
        assert doc["status"] == Status.WRITTEN
        assert doc["repetitions"] == 0
        # and the loser's two-step path is equally refused
        assert not store.set_job_status(NS, jid, Status.FINISHED,
                                        expect=(Status.RUNNING,),
                                        expect_worker=second)


def test_shadow_heartbeat_ownership(tmp_path):
    """Both lease holders beat the shared record; anyone else misses —
    and the beat doubles as the revocation probe (False once the job
    left the leased states)."""
    for store in _stores(tmp_path):
        _seed(store)
        jid = store.claim_batch(NS, "orig", 1)[0]["_id"]
        store.speculate(NS, jid)
        store.claim_spec(NS, "shadow")
        assert store.heartbeat(NS, jid, "orig")
        assert store.heartbeat(NS, jid, "shadow")
        assert not store.heartbeat(NS, jid, "other")
        assert store.heartbeat_batch(NS, [jid], "shadow") == 1
        store.commit_batch(NS, "orig", [(jid, TIMES)])
        assert not store.heartbeat(NS, jid, "shadow")   # revoked


def test_unlease_dissolves_shadow(tmp_path):
    """Release and stale-requeue clear the shadow lease, and a stale
    clone can never commit the re-claimed job."""
    for store in _stores(tmp_path):
        _seed(store)
        # release path
        jid = store.claim_batch(NS, "orig", 1)[0]["_id"]
        store.speculate(NS, jid)
        store.claim_spec(NS, "shadow")
        assert store.release_batch(NS, "orig", [jid]) == 1
        doc = store.get_job(NS, jid)
        assert doc["status"] == Status.WAITING
        assert not doc.get("spec_state")
        # a new claimant owns it; the stale clone's commit must miss
        jid2 = store.claim_batch(NS, "third", 1)[0]["_id"]
        assert jid2 == jid
        assert store.commit_batch(NS, "shadow", [(jid, TIMES)]) == []
        assert store.get_job(NS, jid)["status"] == Status.RUNNING
        # requeue path
        store.speculate(NS, jid)
        store.claim_spec(NS, "shadow2")
        time.sleep(0.05)
        assert store.requeue_stale(NS, 0.01) >= 1
        doc = store.get_job(NS, jid)
        assert doc["status"] == Status.BROKEN
        assert not doc.get("spec_state")
        assert store.commit_batch(NS, "shadow2", [(jid, TIMES)]) == []


def test_claim_spec_prefers_other_placement_tag(tmp_path):
    """Among open shadow leases, claimants prefer stragglers on a
    DIFFERENT placement tag than their own; scan order inside each
    preference class is lowest id first (both engines agree)."""
    from lua_mapreduce_tpu.coord.filestore import worker_hash
    from lua_mapreduce_tpu.coord.idx_py import worker_tag

    # find worker names on two distinct tags, deterministically
    names = [f"w{i}" for i in range(64)]
    tag_of = {n: worker_tag(worker_hash(n)) for n in names}
    a = names[0]
    same = next(n for n in names[1:] if tag_of[n] == tag_of[a])
    other = next(n for n in names[1:] if tag_of[n] != tag_of[a])
    for store in _stores(tmp_path):
        _seed(store)
        # job 0 claimed by a same-tag worker, job 1 by a different-tag
        # worker (relative to claimant `a`); both speculation-open
        j0 = store.claim_batch(NS, same, 1)[0]["_id"]
        j1 = store.claim_batch(NS, other, 1)[0]["_id"]
        assert store.speculate(NS, j0) and store.speculate(NS, j1)
        got = store.claim_spec(NS, a)
        assert got["_id"] == j1, \
            "claimant must prefer the straggler on the OTHER tag"
        # the remaining (same-tag) one is the fallback
        assert store.claim_spec(NS, a)["_id"] == j0


# --- death regressions -------------------------------------------------------

def test_dead_clone_original_commits_zero_reps(tmp_path):
    """SIGKILL-the-clone shape: the shadow holder never issues another
    op. The original commits normally; repetitions stay zero; the
    stranded TAKEN marker on the terminal record is inert."""
    for store in _stores(tmp_path):
        _seed(store)
        jid = store.claim_batch(NS, "orig", 1)[0]["_id"]
        store.speculate(NS, jid)
        assert store.claim_spec(NS, "doomed-clone")["_id"] == jid
        # clone dies here — nothing more from it, ever
        assert store.commit_batch(NS, "orig", [(jid, TIMES)]) == [jid]
        doc = store.get_job(NS, jid)
        assert doc["status"] == Status.WRITTEN and doc["repetitions"] == 0


def test_dead_original_clone_protects_and_commits(tmp_path):
    """SIGKILL-the-original shape: the original goes silent after its
    claim; the clone's heartbeats keep the shared record live (no stale
    requeue, no repetition charge) until the clone commits. The
    negative control shows the same silence WITHOUT a beating clone IS
    requeued with a charge — the protection is real."""
    for store in _stores(tmp_path):
        _seed(store, n=2)
        jid = store.claim_batch(NS, "dead-orig", 1)[0]["_id"]
        store.speculate(NS, jid)
        store.claim_spec(NS, "live-clone")
        ctl = store.claim_batch(NS, "dead-too", 1)[0]["_id"]  # no clone
        time.sleep(0.08)
        assert store.heartbeat(NS, jid, "live-clone")   # clone beats
        assert store.requeue_stale(NS, 0.05) == 1       # only the control
        assert store.get_job(NS, ctl)["status"] == Status.BROKEN
        assert store.get_job(NS, ctl)["repetitions"] == 1
        doc = store.get_job(NS, jid)
        assert doc["status"] == Status.RUNNING and doc["repetitions"] == 0
        assert store.commit_batch(NS, "live-clone", [(jid, TIMES)]) == [jid]
        assert store.get_job(NS, jid)["repetitions"] == 0


# --- model checker ↔ real stores --------------------------------------------

_RACE_CFG = proto.ModelConfig(n_workers=2, n_jobs=1, batch_k=1,
                              allow_spec=True)

_D = proto._D_INTACT


def _race_trace(clone_first: bool):
    """The hand-written both-commit race: worker 0 claims, the detector
    opens speculation, worker 1 takes the shadow lease, both execute,
    both commit — in either order. The loser's commit must fail and its
    cancel dissolve the lease."""
    head = [("claim", 0, (0,)), ("speculate", 0), ("claim_spec", 1, 0),
            ("exec", 0, 0), ("spec_exec", 1, 0)]
    if clone_first:
        tail = [("commit_a", 1, 0, True), ("commit_b", 1, 0, True),
                ("commit_a", 0, 0, False)]
        final_spec = proto._SP_TAKEN0 + 1
    else:
        tail = [("commit_a", 0, 0, True), ("commit_b", 0, 0, True),
                ("commit_a", 1, 0, False), ("spec_cancel", 1, 0, True)]
        final_spec = proto._SP_NONE
    final = ((int(Status.WRITTEN), 0, 1, 0, _D, final_spec),)
    return head + tail, (final, None, None, None)


@pytest.mark.parametrize("clone_first", [True, False],
                         ids=["clone-wins", "original-wins"])
def test_both_commit_race_replays_on_real_stores(tmp_path, clone_first):
    trace, final = _race_trace(clone_first)
    for store in (MemJobStore(), FileJobStore(str(tmp_path / "fs"))):
        rep = proto.replay_trace(store, trace, _RACE_CFG,
                                 final_state=final,
                                 ns=f"race{int(clone_first)}")
        assert rep["ok"], rep


def test_seeded_spec_race_found_and_diverges(tmp_path):
    """The loser-commit-skips-winner-CAS race: the checker re-finds it
    exhaustively, and its trace DIVERGES on both real stores at the
    guarding CAS — the store is strictly stronger than the buggy
    model."""
    bug = proto.check_protocol(dataclasses.replace(
        _RACE_CFG, n_jobs=2, batch_k=2,
        bug="spec_commit_skips_winner_cas"))
    assert not bug.ok
    for store in (MemJobStore(), FileJobStore(str(tmp_path / "fsb"))):
        rep = proto.replay_trace(store, bug.violation.trace, bug.config,
                                 ns="seeded")
        assert not rep["ok"]
        assert rep["label"][0].startswith(("commit", "claim_spec",
                                           "spec_cancel"))


def test_spec_model_exhaustive_small_box():
    res = proto.check_protocol(proto.ModelConfig(
        n_workers=2, n_jobs=1, batch_k=1, allow_spec=True))
    assert res.ok and res.quiescent > 0


# --- engine level ------------------------------------------------------------

def _wc_module():
    import sys
    import types
    mod = sys.modules.get("tests._spec_wc")
    if mod is None:
        mod = types.ModuleType("tests._spec_wc")
        mod.taskfn = lambda emit: [emit(f"d{i}", f"w{i % 3} w{(i + 1) % 3}")
                                   for i in range(6)]

        def mapfn(key, value, emit):
            for w in value.split():
                emit(w, 1)
        mod.mapfn = mapfn
        mod.partitionfn = lambda key: sum(key.encode()) % 2
        mod.reducefn = lambda key, values: sum(values)
        sys.modules["tests._spec_wc"] = mod
    return mod


def _spec(tag):
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    _wc_module()
    return TaskSpec(taskfn="tests._spec_wc", mapfn="tests._spec_wc",
                    partitionfn="tests._spec_wc",
                    reducefn="tests._spec_wc", storage=f"mem:{tag}")


def test_detector_launches_and_respects_cap():
    """The server's housekeeping detector: RUNNING jobs older than
    factor × the task doc's fleet EWMA get a shadow lease, oldest
    first, at most speculation_cap live clones per namespace; repeated
    passes are idempotent; a cold fleet (no EWMA) speculates nothing."""
    from lua_mapreduce_tpu.engine.server import Server

    store = MemJobStore()
    server = Server(store, speculation=2.0, speculation_cap=2)
    store.put_task({"_id": "unique", "status": "MAP"})
    _seed(store, n=4)
    store.claim_batch(NS, "w1", 3)
    time.sleep(0.05)
    server._speculate_stragglers(NS)        # cold: no EWMA on the doc
    assert all(not d.get("spec_state") for d in store.jobs(NS))
    server._spec_scan_at.clear()            # the throttle is not under test
    store.update_task({f"dur_ewma:{NS}": 0.01})
    server._speculate_stragglers(NS)
    opened = [d for d in store.jobs(NS) if d.get("spec_state")]
    assert len(opened) == 2                 # capped below the 3 overdue
    server._spec_scan_at.clear()
    server._speculate_stragglers(NS)        # idempotent under the cap
    assert len([d for d in store.jobs(NS) if d.get("spec_state")]) == 2
    # a clone winning one frees cap budget for the third straggler
    victim = opened[0]["_id"]
    clone = store.claim_spec(NS, "shadow")
    assert clone["_id"] == victim or clone["_id"] == opened[1]["_id"]
    store.commit_batch(NS, "shadow", [(clone["_id"], TIMES)])
    server._spec_scan_at.clear()
    server._speculate_stragglers(NS)
    live_spec = [d for d in store.jobs(NS)
                 if d["status"] == Status.RUNNING and d.get("spec_state")]
    assert len(live_spec) == 2


def test_detector_retracts_abandoned_shadow_lease():
    """A clone that dies with a TAKEN shadow lease must not pin the
    speculation cap forever: once the lease has been TAKEN for longer
    than the detection threshold (a healthy clone finishes in ~one
    EWMA), the detector retracts it so the straggler can be re-cloned."""
    from lua_mapreduce_tpu.engine.server import Server

    store = MemJobStore()
    server = Server(store, speculation=2.0, speculation_cap=1)
    store.put_task({"_id": "unique", "status": "MAP",
                    f"dur_ewma:{NS}": 0.01})
    _seed(store, n=2)
    store.claim_batch(NS, "w1", 2)
    time.sleep(0.03)
    server._speculate_stragglers(NS)
    victim = next(d for d in store.jobs(NS) if d.get("spec_state"))
    clone = store.claim_spec(NS, "doomed-clone")
    assert clone["_id"] == victim["_id"]
    # the clone dies here; cap=1 is now fully pinned by a dead holder
    server._spec_scan_at.clear()
    server._speculate_stragglers(NS)        # first sighting of TAKEN
    time.sleep(0.03)                        # > threshold (2 x 0.01)
    server._spec_scan_at.clear()
    server._speculate_stragglers(NS)        # retraction pass
    doc = store.get_job(NS, victim["_id"])
    assert doc["status"] == Status.RUNNING and doc["repetitions"] == 0
    # the straggler is re-cloneable: either already re-OPENed by the
    # same pass's budget, or claimable after one more pass
    server._spec_scan_at.clear()
    server._speculate_stragglers(NS)
    assert any(d.get("spec_state") == 1 or
               (d.get("spec_state") == 2 and d.get("spec_worker") !=
                "doomed-clone")
               for d in store.jobs(NS)
               if d["_id"] == victim["_id"]) or \
        store.claim_spec(NS, "fresh-clone") is not None


def test_worker_ewma_persisted_and_seeded():
    """Satellite: the per-namespace duration EWMA is folded onto the
    task doc at lease end, and a FRESH worker seeds its own adaptive
    batch sizing from the doc instead of starting cold."""
    from lua_mapreduce_tpu.engine.server import Server
    from lua_mapreduce_tpu.engine.worker import Worker

    store = MemJobStore()
    server = Server(store, poll_interval=0.01).configure(_spec("ewma"))
    w = Worker(store, name="w-ewma").configure(max_iter=200,
                                               max_sleep=0.02)
    t = threading.Thread(target=w.execute, daemon=True)
    t.start()
    server.loop()
    t.join(timeout=30)
    # run to completion deletes the task doc only on verdict True; here
    # finalfn is absent so the doc survives with the folded aggregate
    task = store.get_task()
    assert task and task.get(f"dur_ewma:{NS}", 0) > 0
    # a fresh worker joining a LIVE task seeds its adaptive batch
    # sizing from the doc (seeding only happens on active tasks — a
    # FINISHED doc short-circuits the poll before config parsing)
    store.update_task({"status": "MAP"})
    fresh = Worker(store, name="w-fresh")
    assert fresh._dur_ewma == {}
    fresh.poll_once()
    assert fresh._dur_ewma.get(NS) == pytest.approx(
        task[f"dur_ewma:{NS}"])


def test_clone_loses_race_cancels_cleanly():
    """Worker.run_one on a clone whose original commits mid-body: the
    commit race is lost, the shadow lease dissolves, spec_cancelled and
    wasted seconds are counted, and the job is untouched."""
    from lua_mapreduce_tpu.engine.worker import Worker
    from lua_mapreduce_tpu.faults.retry import COUNTERS

    store = MemJobStore()
    spec = _spec("loser")
    from lua_mapreduce_tpu.engine.local import collect_task_jobs
    jobs = collect_task_jobs(spec)
    store.insert_jobs(NS, [make_job(k, v) for k, v in jobs])
    jid = store.claim_batch(NS, "orig", 1)[0]["_id"]
    store.speculate(NS, jid)
    w = Worker(store, name="clone-w")
    clone = store.claim_spec(NS, w.name)
    # the original wins while the clone is between claim and commit
    assert store.commit_batch(NS, "orig", [(jid, TIMES)]) == [jid]
    before = COUNTERS.snapshot()
    assert w.run_one(spec, NS, clone) is False
    delta = COUNTERS.delta(before, COUNTERS.snapshot())
    assert delta.get("spec_cancelled") == 1
    assert delta.get("spec_wins", 0) == 0
    doc = store.get_job(NS, jid)
    assert doc["status"] == Status.WRITTEN and doc["repetitions"] == 0
    assert not doc.get("spec_state")        # lease dissolved


def test_clone_body_failure_charges_nothing():
    """A clone whose body raises must not mark the job BROKEN or bump
    repetitions — the original still owns the lease (satellite: clone
    failure is never a job failure)."""
    from lua_mapreduce_tpu.engine.worker import Worker
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    import sys
    import types

    mod = types.ModuleType("tests._spec_boom")
    mod.taskfn = lambda emit: emit("k", "v")

    def mapfn(key, value, emit):
        raise RuntimeError("clone-side user explosion")
    mod.mapfn = mapfn
    mod.partitionfn = lambda key: 0
    mod.reducefn = lambda key, values: values
    sys.modules["tests._spec_boom"] = mod
    try:
        spec = TaskSpec(taskfn="tests._spec_boom", mapfn="tests._spec_boom",
                        partitionfn="tests._spec_boom",
                        reducefn="tests._spec_boom", storage="mem:boom")
        store = MemJobStore()
        store.insert_jobs(NS, [make_job("k", "v")])
        jid = store.claim_batch(NS, "orig", 1)[0]["_id"]
        store.speculate(NS, jid)
        w = Worker(store, name="boom-clone")
        clone = store.claim_spec(NS, w.name)
        assert w.run_one(spec, NS, clone) is False
        doc = store.get_job(NS, jid)
        assert doc["status"] == Status.RUNNING      # untouched
        assert doc["repetitions"] == 0
        assert not doc.get("spec_state")
    finally:
        del sys.modules["tests._spec_boom"]


def test_end_to_end_speculation_with_dead_original():
    """Engine-level original-death leg: a worker claims a job and dies
    (its thread simply stops polling with the lease held); with
    speculation on, a healthy worker clones the orphan and the task
    completes with ZERO repetition bumps — without waiting for the
    stale-requeue's BROKEN round-trip (which would charge one)."""
    from lua_mapreduce_tpu.engine.local import iter_results
    from lua_mapreduce_tpu.engine.server import Server
    from lua_mapreduce_tpu.engine.worker import Worker
    from lua_mapreduce_tpu.store.router import get_storage_from

    store = MemJobStore()
    spec = _spec("deadorig")
    server = Server(store, poll_interval=0.01, speculation=3.0,
                    stale_timeout_s=600.0).configure(spec)
    final = {}
    st = threading.Thread(
        target=lambda: final.setdefault("stats", server.loop()),
        daemon=True)
    st.start()
    # the doomed worker: executes exactly one poll (claiming one job,
    # executing it, then claiming another...) — emulate death-with-lease
    # by claiming directly and never acting again
    deadline = time.time() + 30
    while store.get_task() is None or \
            store.get_task().get("status") != "MAP":
        if time.time() > deadline:
            raise AssertionError("map phase never opened")
        time.sleep(0.005)
    while not store.claim_batch(NS, "doomed", 1):
        if time.time() > deadline:
            raise AssertionError("nothing claimable")
        time.sleep(0.005)
    # healthy pool: finishes the rest, folds EWMA, clones the orphan
    workers = [Worker(store, name=f"h{i}").configure(max_iter=800,
                                                     max_sleep=0.02)
               for i in range(2)]
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    st.join(timeout=60)
    assert not st.is_alive(), "server wedged on the dead original"
    for t in threads:
        t.join(timeout=10)
    got = dict(iter_results(get_storage_from(spec.storage), "result"))
    assert got                                   # task completed
    for d in store.jobs(NS):
        assert d["repetitions"] == 0, d
    it = final["stats"].iterations[-1]
    assert it.spec_wins >= 1
