"""Scaled elastic-pool chaos e2e (VERDICT r3 item 3a): a multi-process
worker pool over FileJobStore coordination + OBJECT storage runs the
wordcount_big task while workers are SIGKILLed mid-map AND mid-reduce.

The reference's scaled story is the 30-worker Europarl run
(README.md:77-79) on a pool where any box joins by pointing at the
shared Mongo; its RUNNING jobs of dead workers stay stuck forever
(task.lua FIXMEs). This e2e proves the re-design's stronger contract at
multi-process scale: ownership-CAS claims + stale-requeue recover BOTH
phases' abandoned jobs with zero failed jobs and a golden-equal result.

Choreography (deterministic, no sleeps-as-sync):
  1. map victim boots alone, claims a map job, stalls, prints CLAIMED
  2. SIGKILL it; start 3 map-only healthy processes + the reduce victim
     (reduce-restricted, so reduce jobs are exclusively its until wave B)
  3. reduce victim claims, stalls, prints RCLAIMED; SIGKILL it
  4. wave B: 4 full-phase healthy processes finish everything
Nine OS worker processes total; the server (this process) never stalls.
"""

import os
import subprocess
import sys
import threading
import time
from collections import Counter

import pytest

from lua_mapreduce_tpu import FileJobStore, Server, TaskSpec
from lua_mapreduce_tpu.engine.local import iter_results
from lua_mapreduce_tpu.store.router import get_storage_from

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_SPLITS = 6


def _env():
    ambient = os.environ.get("PYTHONPATH", "")
    path = REPO + os.pathsep + ambient if ambient else REPO
    return dict(os.environ, PYTHONPATH=path)


def _worker_code(coord, extra="", configure="max_iter=2000, max_sleep=0.05"):
    return (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        f"{extra}"
        "from lua_mapreduce_tpu import FileJobStore, Worker\n"
        f"w = Worker(FileJobStore({coord!r})).configure({configure})\n"
        "w.execute()\n")


_STALL_MAP = (
    "import examples.wordcount_big.bigtask as bt\n"
    "import time\n"
    "def stall(k, v, emit):\n"
    "    print('CLAIMED', flush=True)\n"
    "    time.sleep(3600)\n"
    "bt.mapfn = stall\n"
    # the native fast path would bypass the stalled python mapfn
    "import lua_mapreduce_tpu.core.native_wcmap as nw\n"
    "nw.native_available = lambda: False\n")

_STALL_REDUCE = (
    "import examples.wordcount_big.bigtask as bt\n"
    "import time\n"
    "def stall(k, values):\n"
    "    print('RCLAIMED', flush=True)\n"
    "    time.sleep(3600)\n"
    "bt.reducefn = stall\n"
    "import lua_mapreduce_tpu.core.native_merge as nm\n"
    "nm.native_available = lambda: False\n")

# batch-lease victim: the FIRST map job of the batch completes (its runs
# publish), the SECOND wedges — so the SIGKILL lands mid-lease with one
# executed-but-uncommitted job, one wedged job, and the rest of the
# lease claimed-but-unstarted. Every one of them must return to the pool
# independently via the stale requeue.
_STALL_MAP_MIDBATCH = (
    "import examples.wordcount_big.bigtask as bt\n"
    "import time\n"
    "_orig_mapfn = bt.mapfn\n"
    "_calls = [0]\n"
    "def stall(k, v, emit):\n"
    "    _calls[0] += 1\n"
    "    if _calls[0] >= 3:\n"
    "        print('CLAIMED', flush=True)\n"
    "        time.sleep(3600)\n"
    "    _orig_mapfn(k, v, emit)\n"
    "bt.mapfn = stall\n"
    # the native fast path would bypass the stalled python mapfn
    "import lua_mapreduce_tpu.core.native_wcmap as nw\n"
    "nw.native_available = lambda: False\n")


@pytest.mark.heavy
@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
def test_nine_process_pool_survives_map_and_reduce_sigkill(tmp_path,
                                                           pipeline):
    """The ``pipelined`` leg runs the same chaos with eager pre-merge
    jobs enabled: the map victim's SIGKILL lands while pre_merge jobs
    are live in the pool, their claims ride the same ownership CAS +
    stale-requeue recovery, and the golden result must still hold."""
    from examples.wordcount_big import corpus

    corpus_dir = str(tmp_path / "corpus")
    corpus.build(corpus_dir, n_splits=N_SPLITS)
    golden = Counter()
    for i in range(N_SPLITS):
        with open(corpus.split_path(corpus_dir, i)) as f:
            golden.update(f.read().split())

    coord = str(tmp_path / "coord")
    obj = str(tmp_path / "obj")
    storage = f"object:{obj}"
    store = FileJobStore(coord)
    mod = "examples.wordcount_big.bigtask"
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    init_args={"corpus_dir": corpus_dir,
                               "n_splits": N_SPLITS, "build": False},
                    storage=storage)

    env = _env()
    procs = []
    events = {}

    def spawn(code, capture=False):
        p = subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.PIPE if capture else subprocess.DEVNULL,
            text=capture)
        procs.append(p)
        return p

    map_victim = spawn(_worker_code(coord, extra=_STALL_MAP), capture=True)

    started = {"b": False}
    lock = threading.Lock()

    def wave_b():
        with lock:
            if started["b"]:
                return
            started["b"] = True
        for p in (map_victim, events.get("rv")):
            if p is not None and p.poll() is None:
                p.kill()
        for _ in range(4):
            spawn(_worker_code(coord))

    def chaos():
        events["map_claimed"] = map_victim.stdout.readline().strip()
        time.sleep(0.2)
        map_victim.kill()
        # wave A: map-only healthy pool + the reduce victim
        for _ in range(3):
            spawn(_worker_code(
                coord, configure="max_iter=2000, max_sleep=0.05, "
                                 "phases=('map',)"))
        rv = spawn(_worker_code(coord, extra=_STALL_REDUCE), capture=True)
        events["rv"] = rv
        events["reduce_claimed"] = rv.stdout.readline().strip()
        time.sleep(0.2)
        rv.kill()
        wave_b()

    t = threading.Thread(target=chaos, daemon=True)
    t.start()
    watchdog = threading.Timer(120, wave_b)   # victims wedged → still end
    watchdog.daemon = True
    watchdog.start()

    try:
        server = Server(store, poll_interval=0.05, stale_timeout_s=1.5,
                        pipeline=pipeline,
                        premerge_min_runs=2).configure(spec)
        stats = server.loop()
    finally:
        watchdog.cancel()
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass

    assert events.get("map_claimed") == "CLAIMED", \
        "map victim never claimed a job"
    assert events.get("reduce_claimed") == "RCLAIMED", \
        "reduce victim never claimed a job"
    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
    assert it.map.count == N_SPLITS

    result_store = get_storage_from(storage)
    got = {k: vs[0] for k, vs in iter_results(result_store, "result")}
    assert got == dict(golden)


@pytest.mark.heavy
def test_sigkill_churn_with_active_fault_plan_on_shared_store(tmp_path,
                                                              monkeypatch):
    """SIGKILL churn AND deterministic storage faults at once (ISSUE 5
    satellite): a seeded FaultPlan rides the SHARED store in every
    process (workers inherit it through LMR_FAULT_PLAN; the server's
    router reads the same env), injecting transient errors + latency +
    error-after-write while a stalled map victim is SIGKILLed. The
    stale requeue recovers the victim's lease, the retry layer absorbs
    the injected bursts, and the result must still equal the golden
    count with zero FAILED jobs — the two recovery mechanisms must not
    interfere."""
    from examples.wordcount_big import corpus

    corpus_dir = str(tmp_path / "corpus")
    corpus.build(corpus_dir, n_splits=N_SPLITS)
    golden = Counter()
    for i in range(N_SPLITS):
        with open(corpus.split_path(corpus_dir, i)) as f:
            golden.update(f.read().split())

    # max_per_key=2 < the default retry budget of 3: injected bursts
    # are always absorbable, so FAILED==0 is a hard assertion
    monkeypatch.setenv(
        "LMR_FAULT_PLAN",
        "seed=19;transient=0.04;latency=0.03;error_after_write=0.2;"
        "latency_ms=1;max_per_key=2")

    coord = str(tmp_path / "coord")
    storage = f"shared:{tmp_path}/spill"
    store = FileJobStore(coord)
    mod = "examples.wordcount_big.bigtask"
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    init_args={"corpus_dir": corpus_dir,
                               "n_splits": N_SPLITS, "build": False},
                    storage=storage)

    env = _env()
    env["LMR_FAULT_PLAN"] = os.environ["LMR_FAULT_PLAN"]
    procs = []

    def spawn(code, capture=False):
        p = subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.PIPE if capture else subprocess.DEVNULL,
            text=capture)
        procs.append(p)
        return p

    victim = spawn(_worker_code(coord, extra=_STALL_MAP), capture=True)

    started = {"b": False}
    lock = threading.Lock()

    def wave_b():
        with lock:
            if started["b"]:
                return
            started["b"] = True
        if victim.poll() is None:
            victim.kill()
        for _ in range(3):
            # fast heartbeats: injected latency + retry backoff stretch
            # job bodies, and under machine load a beat-less job can
            # outlive the stale timeout — the server would then requeue
            # a LIVE worker's lease and charge repetitions the test
            # attributes to the SIGKILL. Beating keeps healthy leases
            # fresh (the product mechanism for long jobs), so the dead
            # victim stays the only stale-requeue source.
            spawn(_worker_code(
                coord, configure="max_iter=2000, max_sleep=0.05, "
                                 "heartbeat_s=0.25"))

    def chaos():
        victim.stdout.readline()        # CLAIMED
        time.sleep(0.2)
        wave_b()

    t = threading.Thread(target=chaos, daemon=True)
    t.start()
    watchdog = threading.Timer(120, wave_b)
    watchdog.daemon = True
    watchdog.start()

    try:
        server = Server(store, poll_interval=0.05,
                        stale_timeout_s=2.5).configure(spec)
        stats = server.loop()
    finally:
        watchdog.cancel()
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass

    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
    assert it.map.count == N_SPLITS
    # the victim's SIGKILLed claim really was requeued (repetitions
    # come from the stale requeue, never from injected transients —
    # which the retry budget absorbs entirely)
    assert any(d["repetitions"] > 0 for d in store.jobs("map_jobs"))

    result_store = get_storage_from(storage)
    got = {k: vs[0] for k, vs in iter_results(result_store, "result")}
    assert got == dict(golden)


@pytest.mark.heavy
def test_sigkill_mid_batch_lease_requeues_whole_lease(tmp_path):
    """Batch leases under churn (ISSUE 2 satellite): a worker running
    with batch_k=8 claims a LEASE of map jobs, completes the lease's
    first job (runs published, commit still pending — batch commits
    retire at lease end), wedges on the second, and is SIGKILLed. The
    stale requeue must return every lease member to the pool
    INDEPENDENTLY — the committed probe job stays WRITTEN, the
    executed-but-uncommitted job, the wedged job, and the
    claimed-but-unstarted tail all go BROKEN and are re-executed by a
    healthy batched pool — and the result must equal the golden count
    byte-for-byte (re-runs republish the identical run files)."""
    from examples.wordcount_big import corpus

    corpus_dir = str(tmp_path / "corpus")
    corpus.build(corpus_dir, n_splits=N_SPLITS)
    golden = Counter()
    for i in range(N_SPLITS):
        with open(corpus.split_path(corpus_dir, i)) as f:
            golden.update(f.read().split())

    coord = str(tmp_path / "coord")
    obj = str(tmp_path / "obj")
    storage = f"object:{obj}"
    store = FileJobStore(coord)
    mod = "examples.wordcount_big.bigtask"
    spec = TaskSpec(taskfn=mod, mapfn=mod, partitionfn=mod, reducefn=mod,
                    init_args={"corpus_dir": corpus_dir,
                               "n_splits": N_SPLITS, "build": False},
                    storage=storage)

    env = _env()
    procs = []
    batch_cfg = ("max_iter=2000, max_sleep=0.05, batch_k=8, "
                 "batch_lease_s=3600.0")   # wide lease: 5 jobs, 1 claim

    def spawn(code, capture=False):
        p = subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.PIPE if capture else subprocess.DEVNULL,
            text=capture)
        procs.append(p)
        return p

    victim = spawn(_worker_code(coord, extra=_STALL_MAP_MIDBATCH,
                                configure=batch_cfg), capture=True)

    started = {"b": False}
    lock = threading.Lock()

    def wave_b():
        with lock:
            if started["b"]:
                return
            started["b"] = True
        if victim.poll() is None:
            victim.kill()
        for _ in range(3):
            spawn(_worker_code(coord, configure=batch_cfg))

    def chaos():
        # CLAIMED prints from the lease's second job: the first lease
        # job already executed (uncommitted), the tail is unstarted
        victim.stdout.readline()
        time.sleep(0.2)
        wave_b()

    t = threading.Thread(target=chaos, daemon=True)
    t.start()
    watchdog = threading.Timer(120, wave_b)
    watchdog.daemon = True
    watchdog.start()

    try:
        server = Server(store, poll_interval=0.05, stale_timeout_s=1.5,
                        batch_k=8).configure(spec)
        stats = server.loop()
    finally:
        watchdog.cancel()
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass

    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
    assert it.map.count == N_SPLITS
    # the victim's lease really was requeued: re-executed jobs carry
    # repetitions from the stale requeue
    assert any(d["repetitions"] > 0 for d in store.jobs("map_jobs"))

    result_store = get_storage_from(storage)
    got = {k: vs[0] for k, vs in iter_results(result_store, "result")}
    assert got == dict(golden)
