"""Analysis subsystem tests: one fixture per lint rule (positive AND
negative snippet), engine plumbing (suppression, baseline, CLI), the
repo-is-clean gate, and the protocol model checker (exhaustive pass,
seeded-race regressions, real-store trace replay)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from lua_mapreduce_tpu.analysis import lint as lint_mod
from lua_mapreduce_tpu.analysis import protocol as proto
from lua_mapreduce_tpu.analysis.lint import run_lint
from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.jobstore import MemJobStore

PKG = os.path.dirname(os.path.abspath(lint_mod.__file__))
REPO = os.path.dirname(os.path.dirname(PKG))


def _lint_snippet(tmp_path, rel, src):
    """Lint one fixture snippet as if it lived at package path ``rel``."""
    p = tmp_path / os.path.basename(rel)
    p.write_text(textwrap.dedent(src))
    ctx = lint_mod.FileContext(str(p), rel, p.read_text())
    out = []
    for rule in lint_mod.all_rules():
        if rule.applies(rel):
            out.extend(f for f in rule.check(ctx)
                       if f.rule not in ctx.line_disables(f.line))
    return out


# --- LMR001 builder lifecycle ----------------------------------------------

def test_lmr001_unclosed_builder_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        def leak(store):
            b = store.builder()
            b.write("x")
            b.build("f")
        """)
    assert [f.rule for f in got] == ["LMR001"] and got[0].line == 2


def test_lmr001_clean_patterns_pass(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        def with_block(store):
            with store.builder() as b:
                b.write("x")
                b.build("f")

        def try_finally(store):
            b = store.builder()
            try:
                b.write("x")
                b.build("f")
            finally:
                b.close()

        def container(store, parts):
            writers = {}
            try:
                for p in parts:
                    w = writers[p] = writer_for(store, "v2")
                    w.add(p, [1])
            finally:
                for w in writers.values():
                    w.close()

        def transfer(store):
            return store.builder()

        def wrapped(store):
            consume(SegmentWriter(store.builder()))
        """)
    # writer_for/SegmentWriter in engine/ now also trip LMR009 (the
    # replication-helper rule) — this fixture pins LMR001 only
    assert [f for f in got if f.rule == "LMR001"] == []


# --- LMR002 index-flock IO -------------------------------------------------

def test_lmr002_foreign_io_under_index_flock(tmp_path):
    got = _lint_snippet(tmp_path, "coord/fx.py", """\
        import json, os

        class Idx:
            def bad(self, cb):
                fd = self._open_locked()
                try:
                    doc = json.load(open(self.sidecar))
                    cb(doc)
                    os.replace("a", "b")
                    return os.read(fd, 8)
                finally:
                    os.close(fd)
        """)
    msgs = sorted((f.rule, f.line) for f in got)
    # json.load + open + the cb() callback + os.replace; os.read/os.close
    # are the allowed fd-local ops
    assert [r for r, _ in msgs] == ["LMR002"] * 4, got


def test_lmr002_fd_local_ops_pass(tmp_path):
    got = _lint_snippet(tmp_path, "coord/fx.py", """\
        import os

        class Idx:
            def good(self):
                fd = self._open_locked()
                try:
                    os.lseek(fd, 0, 0)
                    head = os.read(fd, 16)
                    os.write(fd, head)
                    return self._read_count(fd)
                finally:
                    os.close(fd)
        """)
    assert got == []


# --- LMR003 lock order -----------------------------------------------------

def test_lmr003_nested_locks_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "coord/fx.py", """\
        class S:
            def nested_with(self):
                with self._lock:
                    with self._rounds_lock:
                        pass

            def flock_under_memlock(self, path):
                with self._lock:
                    with _FLock(path):
                        pass

            def bump_under_lock(self):
                with self._lock:
                    self._bump("claim")
        """)
    assert [f.rule for f in got].count("LMR003") >= 3


def test_lmr003_sequential_locks_pass(tmp_path):
    got = _lint_snippet(tmp_path, "coord/fx.py", """\
        class S:
            def sequential(self):
                self._bump("claim")
                with self._lock:
                    return list(self._jobs)
        """)
    assert got == []


# --- LMR004 wall-clock under lock ------------------------------------------

def test_lmr004_clock_under_lock_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "coord/fx.py", """\
        import time

        class S:
            def bad(self):
                with self._lock:
                    self.t = time.time()

            def good(self):
                now = time.time()
                with self._lock:
                    self.t = now
        """)
    assert [(f.rule, f.line) for f in got] == [("LMR004", 6)]


def test_lmr004_scoped_to_coord(tmp_path):
    # the same pattern outside coord/ is not this rule's business
    got = _lint_snippet(tmp_path, "store/fx.py", """\
        import time

        class S:
            def elsewhere(self):
                with self._lock:
                    self.t = time.time()
        """)
    assert all(f.rule != "LMR004" for f in got)


# --- LMR005 swallow-except -------------------------------------------------

def test_lmr005_swallowers_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "train/fx.py", """\
        def bare():
            try:
                work()
            except:
                pass

        def base_exc(box):
            try:
                work()
            except BaseException as e:
                box.append(e)
        """)
    assert [f.rule for f in got] == ["LMR005", "LMR005"]


def test_lmr005_handled_and_narrow_pass(tmp_path):
    got = _lint_snippet(tmp_path, "train/fx.py", """\
        import logging
        _log = logging.getLogger(__name__)

        def reraises():
            try:
                work()
            except BaseException:
                cleanup()
                raise

        def logs(box):
            try:
                work()
            except BaseException as e:
                _log.warning("deferred: %r", e)
                box.append(e)

        def narrow():
            try:
                work()
            except Exception:
                pass
        """)
    assert got == []


# --- LMR006 raw-bytes contract ---------------------------------------------

def test_lmr006_half_pair_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "store/fx.py", """\
        class HalfStore(Store):
            def read_range(self, name, offset, length):
                return b""
        """)
    assert [f.rule for f in got] == ["LMR006"]
    assert "size" in got[0].message


def test_lmr006_utf8_shim_flagged_latin1_passes(tmp_path):
    got = _lint_snippet(tmp_path, "store/fx.py", """\
        class B1(FileBuilder):
            def write_bytes(self, data):
                self.write(data.decode("utf-8"))

        class B2(FileBuilder):
            def write_bytes(self, data):
                self.write(data.decode("latin-1"))

        class FullStore(Store):
            def read_range(self, name, offset, length):
                return b""

            def size(self, name):
                return 0
        """)
    assert [(f.rule, f.line) for f in got] == [("LMR006", 3)]


# --- LMR008 classified raisables across the retry boundary ------------------

def test_lmr008_generic_raise_in_store_op_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "store/fx.py", """\
        class MyStore:
            def read_range(self, name, offset, length):
                raise RuntimeError("backend hiccup")

            def build(self, name):
                raise OSError("publish failed")
        """)
    assert [f.rule for f in got] == ["LMR008", "LMR008"]
    assert got[0].line == 3 and got[1].line == 6


def test_lmr008_classified_and_out_of_scope_raises_pass(tmp_path):
    got = _lint_snippet(tmp_path, "coord/fx.py", """\
        class MyJobStore:
            def update_task(self, fields):
                raise NoTaskError("no task document")

            def commit_batch(self, entries, worker):
                raise NativeIndexError("jsx_commit_batch failed")

            def lines(self, name):
                raise FileNotFoundError(name)      # taxonomy maps it

            def helper_not_an_op(self):
                raise RuntimeError("not a retry-boundary method")

            def claim(self, worker):
                raise self._err_box[0]             # re-raise: unknowable
        """)
    assert got == []


def test_lmr008_scoped_to_store_and_coord(tmp_path):
    # the same generic raise in engine/ is out of the rule's paths
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        class Runner:
            def build(self, name):
                raise RuntimeError("engine-side, different contract")
        """)
    assert all(f.rule != "LMR008" for f in got)


# --- LMR009 replicated spill publishes --------------------------------------

def test_lmr009_raw_spill_writers_in_engine_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        from lua_mapreduce_tpu.core.segment import writer_for

        def run_map(store, fmt):
            w = writer_for(store, fmt)
            try:
                w.add("k", [1])
                w.build("ns.P0.M1")
            finally:
                w.close()

        def run_premerge(builder):
            w = SegmentWriter(builder, codec="zlib")
            try:
                w.build("ns.P0.SPILL-0-1")
            finally:
                w.close()
        """)
    assert [f.rule for f in got] == ["LMR009", "LMR009"]
    assert "spill_writer" in got[0].message


def test_lmr009_replication_helper_and_other_paths_pass(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        from lua_mapreduce_tpu.faults.replicate import spill_writer

        def run_map(store, fmt, r):
            w = spill_writer(store, fmt, r)
            try:
                w.add("k", [1])
                w.build("ns.P0.M1")
            finally:
                w.close()

        def publish_result(store, name):
            # results are deliberately unreplicated: plain builder is fine
            with store.builder() as b:
                b.write("x\\t[1]\\n")
                b.build(name)
        """)
    assert [f.rule for f in got] == []
    # the factory's own home (core/) and tests are out of scope
    got = _lint_snippet(tmp_path, "core/fx.py", """\
        def writer_for(store, fmt):
            return TextWriter(store.builder())
        """)
    assert all(f.rule != "LMR009" for f in got)


# --- LMR010 injectable clock in trace/ --------------------------------------

def test_lmr010_direct_clock_reads_in_trace_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "trace/fx.py", """\
        import time

        class Recorder:
            def op(self, name):
                t0 = time.time()
                self._spans.append((name, t0, time.perf_counter()))

        def stamp():
            return time.monotonic_ns()
        """)
    assert [f.rule for f in got] == ["LMR010"] * 3
    assert "injectable clock" in got[0].message


def test_lmr010_injectable_clock_patterns_pass(tmp_path):
    # the injection point itself (a default-arg REFERENCE to time.time)
    # and reads routed through the injected clock are the legal shapes
    got = _lint_snippet(tmp_path, "trace/fx.py", """\
        import time

        class Recorder:
            def __init__(self, clock=time.time):
                self._clock = clock

            def op(self, name):
                t0 = self._clock()
                self._spans.append((name, t0, self._clock()))

        def wait(tracer):
            time.sleep(0.1)        # sleeping is not a timestamp read
            return tracer.clock()
        """)
    assert got == []


def test_lmr010_scoped_to_trace(tmp_path):
    # engine job timing (JobTimes) predates the tracer and keeps its
    # own clock — the rule must not fire outside trace/
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        import time

        def run_job():
            return time.time()
        """)
    assert all(f.rule != "LMR010" for f in got)


# --- LMR011 waiter-routed waits in coord/engine -----------------------------

def test_lmr011_bare_sleep_in_engine_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        import time

        def idle_loop(self):
            while True:
                time.sleep(self.poll)
        """)
    assert [f.rule for f in got] == ["LMR011"]
    assert "Waiter" in got[0].message


def test_lmr011_bare_sleep_in_coord_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "coord/fx.py", """\
        import time

        def lock(self, poll):
            while not self.try_lock():
                time.sleep(poll)
        """)
    assert [f.rule for f in got] == ["LMR011"]


def test_lmr011_waiter_patterns_pass(tmp_path):
    # the legal shapes: waits routed through a Waiter, and time.sleep
    # bound as a DEFAULT (the injection point — a reference, not a call)
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        import time

        def idle_loop(self, waiter):
            while True:
                woken = waiter.wait(self.poll)

        def make_waiter(sleep=time.sleep):
            return sleep
        """)
    assert all(f.rule != "LMR011" for f in got)


def test_lmr011_scoped_to_coord_engine(tmp_path):
    # the sched Waiter itself (and stores, benches, tests) legitimately
    # sleeps — the rule scopes to the coord/engine wait paths
    got = _lint_snippet(tmp_path, "sched/fx.py", """\
        import time

        def wait(self, timeout):
            time.sleep(timeout)
        """)
    assert all(f.rule != "LMR011" for f in got)


# --- LMR012 inbox publishes through spill_writer -----------------------------

def test_lmr012_raw_builder_inbox_publish_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        def publish_frame(store, ns, key, seq):
            b = store.builder()
            try:
                b.write_bytes(b"JSEG0001")
                b.build(f"{ns}.P0.INBOX-{key}-{seq:05d}")
            finally:
                b.close()

        def publish_manifest(store, ns, key, payload):
            with store.builder() as b:
                b.write(payload)
                b.build(f"{ns}.PUSH.M{key}")
        """)
    assert [f.rule for f in got
            if f.rule == "LMR012"] == ["LMR012", "LMR012"]
    assert "spill_writer" in [f for f in got
                              if f.rule == "LMR012"][0].message


def test_lmr012_spill_writer_and_other_names_pass(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        from lua_mapreduce_tpu.faults.replicate import spill_writer

        def publish_frame(store, ns, key, seq, r):
            w = spill_writer(store, "v2", r)
            try:
                w.add_line("k", '["k",[1]]')
                w.build(f"{ns}.P0.INBOX-{key}-{seq:05d}")
            finally:
                w.close()

        def publish_result(store, name):
            # non-push names through a plain builder stay legal:
            # results are deliberately unreplicated
            with store.builder() as b:
                b.write("x")
                b.build(f"{name}.P0")
        """)
    assert all(f.rule != "LMR012" for f in got)
    # the rule scopes to engine/: a test harness building fixture
    # inbox files directly is out of scope
    got = _lint_snippet(tmp_path, "store/fx.py", """\
        def fixture(store):
            with store.builder() as b:
                b.build("r.P0.INBOX-1-00000")
        """)
    assert all(f.rule != "LMR012" for f in got)


# --- LMR009/LMR012 coded stripe-name hygiene (DESIGN §27) -------------------

def test_lmr009_stripe_block_literals_flagged(tmp_path):
    # "^i.t^" block names minted outside faults/coded.py bypass the
    # codec's manifest/CRC/placement contract — every literal spelling
    # (f-string with interpolated index/tag, fully literal, wildcard)
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        def read_block(store, base, i, t):
            return store.read_range(f"^{i}.{t}^{base}", 0, 8)

        def guess_block(store, base):
            return store.exists("^0.3^" + base)

        def scan_blocks(store, pat):
            return store.list(f"^*^{pat}")
        """)
    assert [f.rule for f in got] == ["LMR009"] * 3
    assert "faults.coded" in got[0].message


def test_lmr009_stripe_block_negatives_pass(tmp_path):
    # the codec's own home mints block names; helper calls and
    # docstrings documenting the shape stay legal everywhere
    got = _lint_snippet(tmp_path, "faults/coded.py", """\
        def block_names(name, i, t):
            return f"^{i}.{t}^{name}"
        """)
    assert all(f.rule != "LMR009" for f in got)
    got = _lint_snippet(tmp_path, "engine/fx.py", '''\
        from lua_mapreduce_tpu.faults.coded import stripe_patterns

        def scan(store, pat):
            """Lists physical stripe files (^0.3^x blocks etc.)."""
            out = []
            for sp in stripe_patterns(pat):
                out += store.list(sp)
            return out
        ''')
    assert all(f.rule != "LMR009" for f in got)


def test_lmr012_manifest_literal_flagged(tmp_path):
    # a hand-built "^M^" name forges the stripe visibility gate
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        def forge_gate(store, name):
            with store.builder() as b:
                b.write("{}")
                b.build(f"^M^{name}")
        """)
    assert [f.rule for f in got if f.rule == "LMR012"] == ["LMR012"]
    msg = [f for f in got if f.rule == "LMR012"][0].message
    assert "visibility gate" in msg
    # same marker in faults/ (the scavenger's neighborhood) trips too
    got = _lint_snippet(tmp_path, "faults/fx.py", """\
        def peek(store, name):
            return store.exists("^M^" + name)
        """)
    assert [f.rule for f in got if f.rule == "LMR012"] == ["LMR012"]


def test_lmr012_manifest_negatives_pass(tmp_path):
    # the coded module itself, the pattern helpers, and docstrings
    got = _lint_snippet(tmp_path, "faults/coded.py", """\
        def manifest_name(name):
            return f"^M^{name}"
        """)
    assert all(f.rule != "LMR012" for f in got)
    got = _lint_snippet(tmp_path, "engine/fx.py", '''\
        from lua_mapreduce_tpu.faults.coded import manifest_pattern

        def scan_manifests(store, pat):
            """Stripe manifests (^M^x) gate block visibility."""
            return store.list(manifest_pattern(pat))
        ''')
    assert all(f.rule != "LMR012" for f in got)


# --- LMR007 jax purity -----------------------------------------------------

def test_lmr007_impure_traced_functions_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "ops/fx.py", """\
        import functools
        import jax
        import numpy as np

        @functools.partial(jax.jit, static_argnames=("n",))
        def bad_rng(x, n):
            noise = np.random.randn(n)
            return x + noise

        def bad_print(x):
            print("tracing", x)
            return x * 2

        wrapped = jax.jit(bad_print)

        def sharded(x):
            import time
            return x * time.time()

        fn = shard_map(sharded, mesh=None, in_specs=(), out_specs=())
        """)
    assert sorted(f.rule for f in got) == ["LMR007"] * 3


def test_lmr007_pure_and_host_side_pass(tmp_path):
    got = _lint_snippet(tmp_path, "ops/fx.py", """\
        import jax
        import numpy as np

        @jax.jit
        def pure(x):
            jax.debug.print("ok {}", x)
            return x * 2

        def host_side_bench():
            rng = np.random.RandomState(0)
            return rng.randn(8)
        """)
    assert got == []


# --- LMR018 controller-owned knob bypass (DESIGN §29) -----------------------

def test_lmr018_direct_knob_read_flagged(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        def lease_width(self, store):
            task = store.get_task() or {}
            cap = self.batch_k
            return max(1, cap)

        def detector(self, task):
            return self.speculation * task.get("dur_ewma:map", 1.0)
        """)
    assert [f.rule for f in got] == ["LMR018", "LMR018"]
    assert [f.line for f in got] == [3, 7]
    assert "self.batch_k" in got[0].message


def test_lmr018_negotiated_deploy_and_unscoped_pass(tmp_path):
    got = _lint_snippet(tmp_path, "engine/fx.py", """\
        def negotiated(self, task):
            return float(task.get("speculation") or self.speculation)

        def deploy(self, store):
            task = store.get_task() or {}
            store.update_task({"batch_k": self.batch_k})
            return task

        def no_task_in_scope(self):
            return self.batch_k * 2

        def other_attr(self, task):
            return self.poll_interval
        """)
    assert got == []


def test_lmr018_scoped_to_engine(tmp_path):
    src = """\
        def lease_width(self, task):
            return self.batch_k
        """
    assert [f.rule for f in _lint_snippet(tmp_path, "engine/fx.py", src)] \
        == ["LMR018"]
    assert _lint_snippet(tmp_path, "benchmarks/fx.py", src) == []


# --- engine plumbing -------------------------------------------------------

def test_inline_suppression_and_baseline(tmp_path):
    src = ("try:\n    pass\nexcept BaseException:\n    pass\n")
    p = tmp_path / "fx.py"
    p.write_text(src)
    assert [f.rule for f in run_lint([str(p)], baseline="/nonexistent")] \
        == ["LMR005"]
    p.write_text(src.replace("except BaseException:",
                             "except BaseException:  # lmr: disable=LMR005"))
    assert run_lint([str(p)], baseline="/nonexistent") == []
    # baseline with a justified entry suppresses; line-pinned entries
    # only match their line
    p.write_text(src)
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(
        [{"rule": "LMR005", "path": "fx.py", "line": 3, "reason": "test"}]))
    assert run_lint([str(p)], baseline=str(bl)) == []
    bl.write_text(json.dumps(
        [{"rule": "LMR005", "path": "fx.py", "line": 99, "reason": "test"}]))
    assert len(run_lint([str(p)], baseline=str(bl))) == 1


def test_repo_package_is_lint_clean():
    findings = run_lint([os.path.join(REPO, "lua_mapreduce_tpu")])
    assert findings == [], lint_mod.format_text(findings)


def test_shipped_baseline_is_empty():
    # the acceptance bar: no suppressed debt hiding behind the gate
    assert lint_mod.load_baseline() == []


def test_rule_catalog_complete():
    rules = lint_mod.all_rules()
    assert [r.id for r in rules] == \
        [f"LMR00{i}" for i in range(1, 10)] + ["LMR010", "LMR011",
                                              "LMR012", "LMR018"]
    for r in rules:
        assert r.title and r.rationale and r.severity in ("error", "warning")


def test_cli_json_and_exit_codes(tmp_path):
    bad = tmp_path / "fx.py"
    bad.write_text("try:\n    pass\nexcept BaseException:\n    pass\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    # findings + --fail-on-findings → exit 1, json payload carries them
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "lint",
         str(bad), "--fail-on-findings", "--format", "json",
         "--baseline", "/nonexistent"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["count"] == 1
    assert payload["findings"][0]["rule"] == "LMR005"
    # without the flag the same findings report but do not gate
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "lint",
         str(bad), "--baseline", "/nonexistent"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 0
    # the rule catalog prints
    r = subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", "rules"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 0 and "LMR001" in r.stdout


# --- protocol model checker ------------------------------------------------

def test_protocol_exhaustive_small_configs_pass():
    for cfg in (proto.ModelConfig(n_workers=1, n_jobs=2, batch_k=2),
                proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1),
                proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=2,
                                  allow_fail=True, allow_death=False)):
        res = proto.check_protocol(cfg)
        assert res.ok, res.violation.message
        assert res.quiescent > 0 and res.states > 50


def test_protocol_finds_seeded_commit_requeue_race():
    # the regression the ISSUE names: a commit racing the scavenger's
    # requeue must be caught, in bounded steps, with a shortest trace
    cfg = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                            bug="commit_skips_owner_cas")
    res = proto.check_protocol(cfg, max_states=200_000)
    assert not res.ok
    assert "ownership" in res.violation.message
    ops = [t[0] for t in res.violation.trace]
    assert "requeue" in ops and "claim" in ops
    assert ops[-1].startswith("commit")
    assert len(res.violation.trace) <= 30     # bounded, shortest (BFS)


def test_protocol_finds_stuck_finished_gap():
    cfg = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                            bug="requeue_ignores_finished")
    res = proto.check_protocol(cfg, max_states=200_000)
    assert not res.ok
    assert "FINISHED" in res.violation.message
    assert any(t[0] == "die" for t in res.violation.trace)


@pytest.mark.parametrize("make_store", [
    lambda tmp: MemJobStore(),
    lambda tmp: FileJobStore(str(tmp / "js"), engine="python"),
], ids=["mem", "file-py"])
def test_replay_confirms_real_store_blocks_seeded_race(tmp_path,
                                                       make_store):
    cfg = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                            bug="commit_skips_owner_cas")
    res = proto.check_protocol(cfg)
    rep = proto.replay_trace(make_store(tmp_path), res.violation.trace,
                             cfg)
    # the REAL store's CAS refuses exactly the racy commit the buggy
    # model allowed — that divergence is the confirmation
    assert not rep["ok"]
    assert rep["label"][0].startswith("commit")
    assert "refuses" in rep["reason"]


@pytest.mark.parametrize("make_store", [
    lambda tmp: MemJobStore(),
    lambda tmp: FileJobStore(str(tmp / "js"), engine="python"),
], ids=["mem", "file-py"])
def test_replay_reproduces_correct_traces(tmp_path, make_store):
    """Every quiescent end-state of a small exhaustive run replays
    step-for-step on the real stores and lands in the same final
    per-job (status, reps)."""
    cfg = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=2)
    model = proto.LeaseModel(cfg)
    init = model.initial()
    # reconstruct a few full traces by walking BFS parents to quiescence
    visited = {init: []}
    frontier = [init]
    finals = []
    while frontier and len(finals) < 25:
        state = frontier.pop()
        trans = model.transitions(state)
        if all(label[0] == "die" for label, _ in trans):
            finals.append((visited[state], state))
            continue
        for label, new in trans:
            if new not in visited:
                visited[new] = visited[state] + [label]
                frontier.append(new)
    assert finals
    for i, (trace, final) in enumerate(finals):
        rep = proto.replay_trace(make_store(tmp_path), trace, cfg,
                                 final_state=final, ns=f"ns{i}")
        assert rep["ok"], rep


def test_protocol_replica_recovery_edge_exhaustive():
    """The reconstruct-vs-requeue scavenge edge (DESIGN §20): budgeted
    data-loss events, replica repair, and the lost-data WRITTEN→WAITING
    requeue keep the FULL invariant set — including the new
    zero-repetition-charge and no-stranded-data rules."""
    for cfg in (proto.ModelConfig(n_workers=1, n_jobs=2, batch_k=2,
                                  data_loss_budget=2),
                proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                                  data_loss_budget=1)):
        res = proto.check_protocol(cfg)
        assert res.ok, res.violation.message
        assert res.quiescent > 0


def test_protocol_finds_scavenger_that_never_requeues_lost_data():
    cfg = proto.ModelConfig(n_workers=1, n_jobs=2, batch_k=1,
                            data_loss_budget=1,
                            bug="scavenge_skips_lost_data")
    res = proto.check_protocol(cfg, max_states=200_000)
    assert not res.ok
    assert "stranded" in res.violation.message
    assert any(t[0] == "lose_all" for t in res.violation.trace)


def test_protocol_finds_lost_requeue_without_written_cas():
    """Dropping the expect=(WRITTEN,) CAS from the lost-data requeue
    lets the scavenger yank a job out of another worker's commit —
    caught as an illegal FINISHED→WAITING edge, and the real stores
    refuse the same step on replay (the CAS Server._requeue_maps
    carries)."""
    cfg = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                            data_loss_budget=2,
                            bug="lost_requeue_skips_written_cas")
    res = proto.check_protocol(cfg, max_states=400_000)
    assert not res.ok
    assert "illegal status edge" in res.violation.message
    assert res.violation.trace[-1][0] == "rerun_requeue"


@pytest.mark.parametrize("make_store", [
    lambda tmp: MemJobStore(),
    lambda tmp: FileJobStore(str(tmp / "js"), engine="python"),
], ids=["mem", "file-py"])
def test_replay_lost_data_requeue_on_real_stores(tmp_path, make_store):
    """A correct-model trace through loss → requeue → re-run replays
    step-for-step on the real stores: the WRITTEN→WAITING CAS lands,
    the re-claimed job commits again, and the final per-job state
    matches the model."""
    from lua_mapreduce_tpu.core.constants import Status

    cfg = proto.ModelConfig(n_workers=1, n_jobs=1, batch_k=1,
                            data_loss_budget=1, allow_death=False)
    model = proto.LeaseModel(cfg)
    init = model.initial()
    visited = {init: []}
    frontier = [init]
    picked = None
    while frontier:
        state = frontier.pop()
        trace = visited[state]
        ops = [t[0] for t in trace]
        if "rerun_requeue" in ops and "lose_all" in ops:
            jobs = state[0]
            if all(s == int(Status.WRITTEN) for s, *_ in jobs):
                picked = (trace, state)
                break
        for label, new in model.transitions(state):
            if new not in visited:
                visited[new] = trace + [label]
                frontier.append(new)
    assert picked, "no loss→requeue→recommit trace reachable"
    rep = proto.replay_trace(make_store(tmp_path), picked[0], cfg,
                             final_state=picked[1])
    assert rep["ok"], rep


def test_protocol_coded_recovery_edge_exhaustive():
    """The erasure-coded decode ladder (DESIGN §27): block-at-a-time
    lose_parity events, decode-repair, and the last-resort requeue keep
    the FULL invariant set — including decode-conservation (no repair
    of a below-k stripe)."""
    for cfg in (proto.ModelConfig(n_workers=1, n_jobs=2, batch_k=2,
                                  data_loss_budget=2, coded=True),
                proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                                  data_loss_budget=2, coded=True)):
        res = proto.check_protocol(cfg)
        assert res.ok, res.violation.message
        assert res.quiescent > 0


def test_protocol_finds_decode_of_lost_stripe():
    """A scavenger whose repair rung also 'heals' below-k stripes is
    fabricating data — caught by the decode-conservation invariant on
    the repair step itself."""
    cfg = proto.ModelConfig(n_workers=1, n_jobs=2, batch_k=1,
                            data_loss_budget=1, coded=True,
                            bug="coded_decode_lost_stripe")
    res = proto.check_protocol(cfg, max_states=200_000)
    assert not res.ok
    assert "below-k" in res.violation.message
    assert res.violation.trace[-1][0] == "repair"


def test_protocol_finds_decode_blind_requeue():
    """A scavenger that treats ANY block loss as total loss (never
    tries the decode rung) and skips the WRITTEN CAS yanks jobs out of
    a concurrent commit — the illegal FINISHED→WAITING edge."""
    cfg = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                            data_loss_budget=2, coded=True,
                            bug="coded_requeue_skips_decode")
    res = proto.check_protocol(cfg, max_states=400_000)
    assert not res.ok
    assert "illegal status edge" in res.violation.message
    assert res.violation.trace[-1][0] == "rerun_requeue"


@pytest.mark.parametrize("make_store", [
    lambda tmp: MemJobStore(),
    lambda tmp: FileJobStore(str(tmp / "js"), engine="python"),
], ids=["mem", "file-py"])
def test_replay_decode_blind_requeue_diverges_on_real_stores(
        tmp_path, make_store):
    """The decode-blind requeue bug's trace DIVERGES on both real
    stores: the expect=(WRITTEN,) CAS of the requeue refuses the step
    the buggy model allowed."""
    cfg = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=1,
                            data_loss_budget=2, coded=True,
                            bug="coded_requeue_skips_decode")
    res = proto.check_protocol(cfg, max_states=400_000)
    assert not res.ok
    rep = proto.replay_trace(make_store(tmp_path), res.violation.trace,
                             cfg)
    assert not rep["ok"], rep
    assert rep["label"][0] in ("rerun_requeue", "commit_a", "commit_b",
                               "claim")


def test_model_rejects_oversize_and_unknown_bug():
    with pytest.raises(ValueError):
        proto.ModelConfig(n_workers=9)
    with pytest.raises(ValueError):
        proto.ModelConfig(bug="nope")
    with pytest.raises(ValueError):
        # coded bugs are unreachable without the coded plane + budget
        proto.ModelConfig(bug="coded_requeue_skips_decode",
                          data_loss_budget=2)
    with pytest.raises(ValueError):
        # an inert coded plane (no budget → no lose_parity) is a
        # config error, not a vacuous pass
        proto.ModelConfig(coded=True)


def test_mark_broken_requires_running_status(tmp_path):
    """The protocol hole the checker found on its first run: a FAILED
    job must stay FAILED even if its last claimant reports its failure
    late — Worker._mark_broken now CASes on RUNNING."""
    from lua_mapreduce_tpu.core.constants import Status
    from lua_mapreduce_tpu.coord.jobstore import make_job
    from lua_mapreduce_tpu.engine.worker import Worker

    store = MemJobStore()
    store.insert_jobs("map_jobs", [make_job(0, "x")])
    store.claim("map_jobs", "w1")
    # scavenger path: requeued to BROKEN repeatedly, then FAILED
    for _ in range(3):
        store.set_job_status("map_jobs", 0, Status.BROKEN)
        if store.get_job("map_jobs", 0)["repetitions"] < 3:
            store.claim("map_jobs", "w1")
    assert store.scavenge("map_jobs") == 1
    assert store.get_job("map_jobs", 0)["status"] == Status.FAILED
    # the late failure report must NOT resurrect the job
    w = Worker(store, name="w1")
    try:
        raise RuntimeError("user code failed")
    except RuntimeError:
        w._mark_broken("map_jobs", 0)
    assert store.get_job("map_jobs", 0)["status"] == Status.FAILED


def test_unparseable_file_is_a_finding_not_a_crash(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def oops(:\n")
    got = run_lint([str(p)], baseline="/nonexistent")
    assert len(got) == 1 and got[0].rule == "LMR000"
    assert "parse" in got[0].message


def test_unreadable_and_nul_files_are_findings(tmp_path):
    nul = tmp_path / "nul.py"
    nul.write_bytes(b"x = 1\n\x00\n")
    lat = tmp_path / "lat.py"
    lat.write_bytes(b"caf\xe9 = 1\n")
    got = run_lint([str(nul), str(lat)], baseline="/nonexistent")
    assert sorted(f.rule for f in got) == ["LMR000", "LMR000"]
