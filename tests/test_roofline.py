"""Roofline/MFU accounting and the fixed-batch benchmark hot loop.

The reference publishes wall-clock tables only (README.md:43-113); the
build's north star is an MFU figure (BASELINE.md), so the accounting
itself needs tests: peak resolution order, the MFU formula, and that
``run_steps`` (the measured hot loop) computes the same training
trajectory as discrete ``step`` calls.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lua_mapreduce_tpu.models.mlp import init_mlp, nll_loss  # noqa: E402
from lua_mapreduce_tpu.parallel.mesh import make_mesh  # noqa: E402
from lua_mapreduce_tpu.train.harness import (  # noqa: E402
    DataParallelTrainer, TrainConfig)
from lua_mapreduce_tpu.utils import roofline  # noqa: E402


def test_peak_env_override(monkeypatch):
    monkeypatch.setenv("LMR_PEAK_FLOPS", "1e15")
    assert roofline.peak_flops_per_s() == 1e15


def test_peak_known_generation_table():
    # table entries are per-chip bf16 figures; spot-check the bench chip
    assert roofline.PEAK_BF16_FLOPS["TPU v5 lite"] == 197e12


def test_peak_unknown_kind_probes(monkeypatch):
    monkeypatch.delenv("LMR_PEAK_FLOPS", raising=False)
    # CPU device_kind is not in the table → measured-probe fallback
    peak = roofline.peak_flops_per_s(jax.devices()[0])
    assert peak > 0
    # cached: second call returns the identical object fast
    assert roofline.peak_flops_per_s(jax.devices()[0]) == peak


def test_mfu_formula(monkeypatch):
    monkeypatch.setenv("LMR_PEAK_FLOPS", "2e12")
    # 1e12 FLOPs in 1s on 1 chip of peak 2e12 → 50%
    assert roofline.mfu(1e12, 1.0, n_chips=1) == pytest.approx(0.5)
    assert roofline.mfu(1e12, 1.0, n_chips=2) == pytest.approx(0.25)


def test_run_steps_matches_discrete_steps():
    """run_steps(n) must be the same trajectory as n step() calls on the
    same fixed batch — the benchmark loop measures real training."""
    mesh = make_mesh(dp=8, mp=1)
    cfg = TrainConfig(batch_size=8, seed=0)
    rng = np.random.RandomState(0)
    x = rng.rand(8, 16).astype(np.float32)
    y = rng.randint(0, 4, 8)

    def make():
        return DataParallelTrainer(
            nll_loss, init_mlp(jax.random.PRNGKey(0), (16, 8, 4)),
            mesh, cfg)

    tr_a = make()
    losses = np.asarray(tr_a.run_steps(x, y, 3))
    tr_b = make()
    discrete = [tr_b.step(x, y) for _ in range(3)]

    assert losses.shape == (3,)
    np.testing.assert_allclose(losses, discrete, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(tr_a.params["W0"]), np.asarray(tr_b.params["W0"]),
        rtol=1e-5)
    # loss decreases on a fixed batch: it is really optimizing
    assert losses[-1] < losses[0]


def test_run_steps_caches_compiled_fn():
    mesh = make_mesh(dp=8, mp=1)
    tr = DataParallelTrainer(
        nll_loss, init_mlp(jax.random.PRNGKey(0), (16, 8, 4)),
        mesh, TrainConfig(batch_size=8))
    rng = np.random.RandomState(0)
    x = rng.rand(8, 16).astype(np.float32)
    y = rng.randint(0, 4, 8)
    tr.run_steps(x, y, 2)
    fn = tr._steps_cache[2]
    tr.run_steps(x, y, 2)
    assert tr._steps_cache[2] is fn and len(tr._steps_cache) == 1
