"""Every Pallas kernel must be callable from INSIDE ``jax.shard_map``.

Round 4's second hardware window exposed the gap: JAX 0.9 types values
inside shard_map with varying-mesh-axes (vma) sets and rejects any
``pallas_call`` whose out_shape is a plain ``ShapeDtypeStruct`` —
exactly how every sharded train step (the DP/TP/SP paths of
models/transformer.py and train/harness.py) invokes the kernels on TPU,
where the auto policy routes attention/pool/q8 to Pallas. The CPU suite
never saw it because off-TPU the policy resolves everything to "xla".
The fix is ``ops.out_struct`` propagating operand vma into the kernel's
output type.

Two kinds of regression here:

- **Lowering**: ``jax.export`` for the TPU platform over an
  ``AbstractMesh`` runs trace + Mosaic lowering of the kernel inside
  shard_map from a CPU-only host — the exact program shape that failed
  on the chip (vma check fires at trace time).
- **Numerics**: the flash kernels also EXECUTE inside a CPU-mesh
  shard_map in interpret mode, golden-diffed against the XLA oracle
  (SURVEY.md §4's golden-diff discipline at the kernel layer). The
  other kernels cannot: JAX 0.9's pallas HLO interpreter is itself not
  vma-aware when a kernel mixes varying operands with replicated or
  index values (its internal dynamic_slice trips the same check — an
  upstream limitation, not a kernel bug), so their in-shard_map
  coverage is lowering-only; interpret-mode parity OUTSIDE shard_map
  owns their numerics (tests/test_ops.py).
"""

import functools

import jax
import jax.export   # noqa: F401  (not an autoloaded submodule on older JAX)
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import AbstractMesh, Mesh, PartitionSpec as P

from lua_mapreduce_tpu import ops

# vma_shard_map: public-API shard_map with full vma checking where the
# checker understands pallas_call; on legacy experimental shard_map the
# rep check is disabled (no pallas_call rule there) instead of crashing
from lua_mapreduce_tpu.utils.jax_compat import vma_shard_map as shard_map


def _abstract_mesh():
    """AbstractMesh across the signature change: newer JAX takes
    (axis_sizes, axis_names); older JAX takes one shape_tuple of
    (name, size) pairs."""
    try:
        return AbstractMesh((4,), ("dp",))
    except TypeError:
        return AbstractMesh((("dp", 4),))


AMESH = _abstract_mesh()


def export_shardmap_tpu(f, in_specs, out_specs, *shapes):
    """Lower ``f`` inside shard_map for the TPU target from the CPU
    host; raises on any vma-typing or Mosaic legality violation."""
    g = shard_map(f, mesh=AMESH, in_specs=in_specs,
                  out_specs=out_specs)
    return jax.export.export(jax.jit(g), platforms=["tpu"])(*shapes)


def _close(a, b, tol=2e-2):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               rtol=tol, atol=tol)


class TestShardMapLowering:
    """Trace + Mosaic-lower each Pallas kernel inside shard_map."""

    def test_flash_attention_fwd(self):
        q = jax.ShapeDtypeStruct((8, 1024, 8, 128), jnp.bfloat16)
        export_shardmap_tpu(
            lambda q_, k_, v_: ops.flash_attention(
                q_, k_, v_, causal=True, backend="pallas"),
            (P("dp"), P("dp"), P("dp")), P("dp"), q, q, q)

    def test_flash_attention_grad(self):
        q = jax.ShapeDtypeStruct((8, 1024, 8, 128), jnp.bfloat16)

        def loss(q_, k_, v_):
            return ops.flash_attention(q_, k_, v_, causal=True,
                                       backend="pallas").sum()

        export_shardmap_tpu(
            jax.grad(loss, argnums=(0, 1, 2)),
            (P("dp"), P("dp"), P("dp")),
            (P("dp"), P("dp"), P("dp")), q, q, q)

    def test_matmul_replicated_rhs(self):
        """The DP-trainer shape: activations vary over dp, weights are
        replicated — pallas_call must accept mixed-vma operands."""
        a = jax.ShapeDtypeStruct((8, 256, 512), jnp.bfloat16)
        b = jax.ShapeDtypeStruct((512, 256), jnp.bfloat16)
        export_shardmap_tpu(
            lambda a_, b_: jax.vmap(lambda s: ops.matmul(
                s, b_, backend="pallas"))(a_),
            (P("dp"), P()), P("dp"), a, b)

    def test_log_softmax(self):
        x = jax.ShapeDtypeStruct((512, 1024), jnp.bfloat16)
        export_shardmap_tpu(
            lambda x_: ops.log_softmax(x_, backend="pallas"),
            (P("dp"),), P("dp"), x)

    @pytest.mark.parametrize("op", ["maxpool2d", "avgpool2d"])
    def test_pool(self, op):
        x = jax.ShapeDtypeStruct((8, 32, 32, 32), jnp.bfloat16)
        export_shardmap_tpu(
            lambda x_: getattr(ops, op)(x_, backend="pallas"),
            (P("dp"),), P("dp"), x)

    def test_q8_matmul_replicated_weights(self):
        """The quantized-decode shape: per-rank activations against
        replicated int8 weights + scales."""
        x = jax.ShapeDtypeStruct((8, 4096), jnp.bfloat16)
        q = jax.ShapeDtypeStruct((4096, 8192), jnp.int8)
        s = jax.ShapeDtypeStruct((8192,), jnp.float32)
        export_shardmap_tpu(
            lambda x_, q_, s_: ops.q8_matmul(x_, q_, s_,
                                             backend="pallas"),
            (P("dp"), P(), P()), P("dp"), x, q, s)

    def test_conv2d(self):
        x = jax.ShapeDtypeStruct((8, 32, 32, 16), jnp.bfloat16)
        w = jax.ShapeDtypeStruct((3, 3, 16, 32), jnp.bfloat16)
        export_shardmap_tpu(
            lambda x_, w_: ops.conv2d(x_, w_, backend="pallas"),
            (P("dp"), P()), P("dp"), x, w)


class TestShardMapNumerics:
    """Flash executes (interpret mode) inside a real CPU-device mesh."""

    def _mesh(self, n=4):
        return Mesh(np.array(jax.devices()[:n]), ("dp",))

    def test_flash_attention(self):
        mesh = self._mesh()
        k0 = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(kk, (4, 256, 2, 64), jnp.float32)
                   for kk in jax.random.split(k0, 3))
        fn = jax.jit(shard_map(
            lambda q_, k_, v_: ops.flash_attention(
                q_, k_, v_, causal=True, backend="pallas_interpret"),
            mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
            out_specs=P("dp")))
        ref = ops.flash_attention(q, k, v, causal=True, backend="xla")
        _close(fn(q, k, v), ref)

    def test_flash_attention_grad_with_lse(self):
        """The ring-attention training path: fused backward + lse
        cotangent, per shard; batch-sharded inputs under a sum loss
        make the concatenated shard grads equal the global grads."""
        mesh = self._mesh()
        k0 = jax.random.PRNGKey(1)
        q, k, v = (jax.random.normal(kk, (4, 256, 2, 64), jnp.float32)
                   for kk in jax.random.split(k0, 3))

        def loss(q_, k_, v_, backend):
            o, lse = ops.flash_attention(q_, k_, v_, causal=True,
                                         return_lse=True,
                                         backend=backend)
            return o.sum() + 0.1 * lse.sum()

        fn = jax.jit(shard_map(
            jax.grad(functools.partial(loss,
                                       backend="pallas_interpret"),
                     argnums=(0, 1, 2)),
            mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp"), P("dp"))))
        got = fn(q, k, v)
        want = jax.grad(functools.partial(loss, backend="xla"),
                        argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            _close(g, w)


def test_out_struct_plain_context():
    """Outside shard_map the helper degrades to an ordinary struct —
    vma is empty and plain-jit callers are unaffected."""
    s = ops.out_struct((4, 8), jnp.float32)
    assert s.shape == (4, 8) and s.dtype == jnp.float32
