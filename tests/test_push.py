"""Push-shuffle suite (DESIGN §24): knob resolution, the golden matrix
with push off AND on across {mem,shared,object} × {barrier,pipelined}
on both executors, the memory-budget eviction regression, manifest
gating (quarantine / promote / backstop), mixed push-on/off fleets, and
the SegmentReader parsed-footer cache regression."""

import re
import threading

import pytest

from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.engine import push as push_mod
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor, iter_results
from lua_mapreduce_tpu.engine.server import Server
from lua_mapreduce_tpu.engine.worker import MAP_NS, Worker
from lua_mapreduce_tpu.store.router import get_storage_from

CORPUS = {
    f"doc{i}": " ".join(f"w{(i * 11 + j) % 29}" for j in range(48))
    for i in range(8)
}
GOLDEN = {}
for _text in CORPUS.values():
    for _w in _text.split():
        GOLDEN[_w] = GOLDEN.get(_w, 0) + 1

_MOD = "tests._push_wc"


def _install_module():
    import sys
    import types

    mod = sys.modules.get(_MOD)
    if mod is None:
        mod = types.ModuleType(_MOD)

        def taskfn(emit):
            for k, v in sorted(CORPUS.items()):
                emit(k, v)

        def mapfn(key, value, emit):
            for w in value.split():
                emit(w, 1)

        mod.taskfn = taskfn
        mod.mapfn = mapfn
        mod.partitionfn = lambda key: sum(key.encode()) % 4
        mod.reducefn = lambda key, values: sum(values)
        sys.modules[_MOD] = mod
    return mod


def _storage(tmp_path, backend, tag):
    return {"mem": f"mem:{tag}",
            "shared": f"shared:{tmp_path}/shared-{tag}",
            "object": f"object:{tmp_path}/object-{tag}"}[backend]


def _result_bytes(storage_spec, ns="result"):
    store = get_storage_from(storage_spec)
    keep = re.compile(rf"^{re.escape(ns)}\.P\d+$")
    return {n: "".join(store.lines(n)) for n in store.list(f"{ns}.P*")
            if keep.match(n)}


def _run_local(tmp_path, backend, pipeline, tag, push=False,
               budget_mb=None, replication=1):
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, backend, tag))
    ex = LocalExecutor(spec, map_parallelism=3, pipeline=pipeline,
                       premerge_min_runs=2, push=push,
                       push_budget_mb=budget_mb, replication=replication)
    stats = ex.run()
    got = {k: v[0] for k, v in ex.results()}
    assert got == GOLDEN
    return _result_bytes(spec.storage), stats


# --- knob resolution ---------------------------------------------------------

def test_resolve_push_env_roundtrip(monkeypatch):
    assert push_mod.resolve_push(True) is True
    assert push_mod.resolve_push(None) is False
    monkeypatch.setenv("LMR_PUSH", "1")
    assert push_mod.resolve_push(None) is True
    monkeypatch.setenv("LMR_PUSH", "off")
    assert push_mod.resolve_push(None) is False
    monkeypatch.setenv("LMR_PUSH_BUDGET_MB", "2.5")
    assert push_mod.resolve_push_budget(None) == int(2.5 * 1024 * 1024)
    assert push_mod.resolve_push_budget(1) == 1024 * 1024
    monkeypatch.delenv("LMR_PUSH_BUDGET_MB")
    assert push_mod.resolve_push_budget(None) == \
        int(push_mod.DEFAULT_BUDGET_MB * 1024 * 1024)


def test_cli_parsers_accept_push_knobs():
    from lua_mapreduce_tpu.cli.execute_server import \
        build_parser as server_parser
    from lua_mapreduce_tpu.cli.execute_worker import \
        build_parser as worker_parser
    s = server_parser().parse_args(
        ["coord", "t", "m", "p", "r", "--push", "--push-budget-mb", "16"])
    assert s.push is True and s.push_budget_mb == 16.0
    s = server_parser().parse_args(["coord", "t", "m", "p", "r"])
    assert s.push is None            # None = LMR_PUSH env resolution
    w = worker_parser().parse_args(
        ["coord", "--push", "--push-budget-mb", "8"])
    assert w.push is True and w.push_budget_mb == 8.0


def test_worker_config_keys():
    w = Worker(MemJobStore(), name="push-cfg")
    w.configure(push=True, push_budget_mb=4.0)
    assert w._push_on() is True
    assert w._push_pool().budget == 4 * 1024 * 1024
    # unset worker follows the task document's fleet marker
    w2 = Worker(MemJobStore(), name="push-cfg2")
    assert w2._push_on() is False
    w2._task_push = True
    assert w2._push_on() is True


# --- the golden matrix: push off AND on, byte-identical ----------------------

@pytest.mark.parametrize("pipeline", [False, True],
                         ids=["barrier", "pipelined"])
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_push_golden_matrix_local(tmp_path, backend, pipeline):
    tag = f"pg-{backend}-{int(pipeline)}"
    off, _ = _run_local(tmp_path, backend, pipeline, tag + "-off")
    on, stats = _run_local(tmp_path, backend, pipeline, tag + "-on",
                           push=True)
    assert on == off, "push-on output differs from the staged path"
    assert stats.iterations[-1].push_frames > 0


def test_push_golden_replicated(tmp_path):
    # pushed frames ride the replication plane: r=2 stays byte-identical
    off, _ = _run_local(tmp_path, "mem", True, "pr-off")
    on, stats = _run_local(tmp_path, "mem", True, "pr-on", push=True,
                           replication=2)
    assert on == off
    assert stats.iterations[-1].push_frames > 0


def test_push_distributed_task_doc_deploy(tmp_path):
    """Server(push=True) deploys the marker through the task doc: stock
    workers follow it, output byte-identical to the staged twin, and
    the in-process pool's IterationStats carries the frame count."""
    _install_module()
    clean, _ = _run_local(tmp_path, "mem", False, "pd-off")
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD, storage=_storage(tmp_path, "mem", "pd-on"))
    store = MemJobStore()
    server = Server(store, poll_interval=0.01, pipeline=True,
                    premerge_min_runs=2, batch_k=2,
                    push=True).configure(spec)
    workers = [Worker(store).configure(max_iter=800, max_sleep=0.02)
               for _ in range(2)]
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    stats = server.loop()
    for t in threads:
        t.join(timeout=30)
    got = {k: v[0] for k, v in iter_results(
        get_storage_from(spec.storage), "result")}
    assert got == GOLDEN
    assert _result_bytes(spec.storage) == clean
    it = stats.iterations[-1]
    assert it.push_frames > 0
    assert it.map.failed == 0 and it.reduce.failed == 0


def test_push_mixed_fleet(tmp_path):
    """One worker pinned push=False (a push-off fleet member) while the
    fleet default is push: manifested maps and classic runs interleave
    in canonical order — output stays byte-identical."""
    _install_module()
    clean, _ = _run_local(tmp_path, "mem", False, "mix-off")
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, "mem", "mix-on"))
    store = MemJobStore()
    server = Server(store, poll_interval=0.01, push=True,
                    batch_k=2).configure(spec)
    pusher = Worker(store, name="pusher").configure(max_iter=800,
                                                    max_sleep=0.02)
    classic = Worker(store, name="classic").configure(
        max_iter=800, max_sleep=0.02, push=False)
    threads = [threading.Thread(target=w.execute, daemon=True)
               for w in (pusher, classic)]
    for t in threads:
        t.start()
    server.loop()
    for t in threads:
        t.join(timeout=30)
    got = {k: v[0] for k, v in iter_results(
        get_storage_from(spec.storage), "result")}
    assert got == GOLDEN
    assert _result_bytes(spec.storage) == clean


# --- satellite: memory-budget eviction regression ----------------------------

def test_push_budget_eviction_regression(tmp_path):
    """A push run with the budget far below the working set must
    complete via eviction-to-staged — ``push_evictions > 0`` in
    IterationStats — with byte-identical output (the degrade-to-staged
    rung, never an OOM or a failure)."""
    off, _ = _run_local(tmp_path, "mem", True, "bud-off")
    on, stats = _run_local(tmp_path, "mem", True, "bud-on", push=True,
                           budget_mb=0.0001)   # ~100 bytes: constant
    assert on == off
    it = stats.iterations[-1]
    assert it.push_evictions > 0, \
        "budget below working set must evict, not buffer"


def test_buffer_pool_accounting():
    pool = push_mod.BufferPool(1000)
    pool.charge(600)
    assert not pool.over()
    pool.charge(600)
    assert pool.over() and pool.held == 1200
    pool.uncharge(900)
    assert pool.held == 300 and not pool.over()
    pool.uncharge(10_000)
    assert pool.held == 0              # floor at zero, never negative


# --- manifest gate: quarantine / promote / backstop --------------------------

def test_spec_lineage_quarantined_until_promoted():
    """A clone's pushes stay invisible — spec-tagged fragments + a spec
    manifest — until promote(); promote is publish-if-absent, so a
    canonical lineage published by the original always wins."""
    from lua_mapreduce_tpu.store.memfs import MemStore
    store = MemStore()
    ns, key = "result", "00000003"
    # original execution: canonical lineage
    orig = push_mod.PushWriter(store, ns, key,
                               pool=push_mod.BufferPool(1 << 20))
    orig.add(0, "a", [1])
    orig.add(1, "b", [2])
    orig.finish()
    orig.close()
    # clone execution: different fragmentation, quarantined
    lin = push_mod.lineage_token("clone-worker")
    clone = push_mod.PushWriter(store, ns, key,
                                pool=push_mod.BufferPool(0),
                                lineage=lin)
    clone.add(0, "a", [1])
    clone.add(1, "b", [2])
    clone.finish()
    clone.close()
    man = push_mod.read_manifest(store, push_mod.manifest_name(ns, key))
    assert man["lineage"] == ""        # the original's lineage is visible
    # every visible file is canonical; the clone's files carry its tag
    visible = {f for files in
               push_mod.manifest_files_by_part(man).values()
               for f in files}
    assert all(f"-s{lin}" not in f for f in visible)
    # the original committed: promote must NOT flip the manifest
    assert push_mod.promote(store, ns, key, lin, 1) is False
    assert push_mod.read_manifest(
        store, push_mod.manifest_name(ns, key)) == man
    # discovery sweeps the losing clone's quarantined files
    parts = push_mod.discover_push(store, ns, [key])
    assert all(f"-s{lin}" not in f for files in parts.values()
               for f in files)
    leftover = [n for n in store.list(f"{ns}.P*.INBOX-*")
                if f"-s{lin}" in n]
    assert leftover == [], "losing clone's inbox must be swept"


def test_promote_gap_backstop():
    """The winning-clone-died-pre-promote gap: job committed, canonical
    manifest absent, spec manifest complete — ensure_canonical promotes
    it (deterministically) so the tracker/discovery never stall."""
    from lua_mapreduce_tpu.store.memfs import MemStore
    store = MemStore()
    ns, key = "result", "00000009"
    lin = push_mod.lineage_token("dead-winner")
    clone = push_mod.PushWriter(store, ns, key,
                                pool=push_mod.BufferPool(1 << 20),
                                lineage=lin)
    clone.add(0, "k", [1])
    clone.finish()
    clone.close()
    assert push_mod.read_manifest(
        store, push_mod.manifest_name(ns, key)) is None
    man = push_mod.ensure_canonical(store, ns, key, 1)
    assert man is not None and man["lineage"] == lin
    assert store.exists(push_mod.manifest_name(ns, key))
    # idempotent: a second resolution reads the promoted canonical
    assert push_mod.ensure_canonical(store, ns, key, 1) == man


def test_backstop_never_promotes_dangling_lineage():
    """A losing clone's stale ``.s`` manifest whose fragments were
    already swept must NOT be backstop-promoted after the scavenger
    invalidates the canonical manifest — promoting a dangling lineage
    would wedge recovery on files nobody can regenerate under those
    names."""
    from lua_mapreduce_tpu.store.memfs import MemStore
    store = MemStore()
    ns, key = "result", "00000011"
    lin = push_mod.lineage_token("losing-clone")
    clone = push_mod.PushWriter(store, ns, key,
                                pool=push_mod.BufferPool(1 << 20),
                                lineage=lin)
    clone.add(0, "k", [1])
    clone.finish()
    clone.close()
    # sweep the quarantined fragments (discovery's job), keep the stale
    # spec manifest, and leave no canonical (scavenger invalidated it)
    for n in store.list(f"{ns}.P*.INBOX-*"):
        store.remove(n)
    assert push_mod.ensure_canonical(store, ns, key, 1) is None
    assert not store.exists(push_mod.manifest_name(ns, key))
    # sweep_unreferenced drops a loser's .s manifest once a DIFFERENT
    # lineage is canonical (keeping the promote-gap case covered)
    orig = push_mod.PushWriter(store, ns, key,
                               pool=push_mod.BufferPool(1 << 20))
    orig.add(0, "k", [1])
    orig.finish()
    orig.close()
    _, referenced = push_mod.push_file_lists(store, ns, [key])
    push_mod.sweep_unreferenced(store, ns, referenced, [key])
    assert not store.exists(push_mod.manifest_name(ns, key, lin))


# --- satellite: SegmentReader parsed-footer cache ----------------------------

class _CountingStore:
    """Store wrapper counting read_range calls (duck-typed: only the
    surface SegmentReader touches)."""

    def __init__(self, inner):
        self._inner = inner
        self.reads = 0

    def read_range(self, name, off, length):
        self.reads += 1
        return self._inner.read_range(name, off, length)

    def size(self, name):
        return self._inner.size(name)


def test_footer_cache_saves_repeat_open_reads():
    """Re-opening a segment must hit the per-store parsed-footer cache:
    the trailer + footer ranged reads are paid once per (name, size),
    not once per SegmentReader — the incremental inbox merge's
    open-per-consumer pattern would otherwise pay O(openings) footer
    fetches. The saved reads are counted."""
    from lua_mapreduce_tpu.core import segment
    from lua_mapreduce_tpu.store.memfs import MemStore

    inner = MemStore()
    with segment.writer_for(inner, "v2") as w:
        for i in range(100):
            w.add(f"k{i:03d}", [i])
        w.build("seg.P0.INBOX-1-00000")

    counting = _CountingStore(inner)
    r1 = segment.SegmentReader(counting, "seg.P0.INBOX-1-00000")
    first_open = counting.reads
    assert first_open >= 3            # magic + trailer + footer
    saved0 = segment.FOOTER_READS_SAVED
    r2 = segment.SegmentReader(counting, "seg.P0.INBOX-1-00000")
    second_open = counting.reads - first_open
    assert second_open == first_open - 2, \
        "second open must skip exactly the trailer + footer reads"
    assert segment.FOOTER_READS_SAVED == saved0 + 2
    assert list(r2.iter_records()) == list(r1.iter_records())
    # the cache keys on size: a same-name file of a different size
    # (honest rewrite) re-reads its own footer
    with segment.writer_for(inner, "v2") as w:
        for i in range(7):
            w.add(f"z{i}", [i])
        w.build("seg.P0.INBOX-1-00000")
    r3 = segment.SegmentReader(counting, "seg.P0.INBOX-1-00000")
    assert [k for k, _ in r3.iter_records()] == [f"z{i}" for i in range(7)]


def test_footer_cache_purged_on_iteration_rollover():
    """Loop tasks reuse run/fragment names with NEW contents, and
    fixed-width records can reproduce the exact byte size — the
    engines' iteration-start cleanup purges the cache so a same-size
    rewrite can never serve a stale footer."""
    from lua_mapreduce_tpu.core import segment
    from lua_mapreduce_tpu.store.memfs import MemStore

    store = MemStore()

    def publish(keys):
        with segment.writer_for(store, "v2") as w:
            for k in keys:
                w.add(k, [0])
            w.build("r.P0.M00000001")

    publish([f"a{i:03d}" for i in range(50)])
    segment.SegmentReader(store, "r.P0.M00000001")      # cache fills
    publish([f"b{i:03d}" for i in range(50)])           # same byte size
    key = ("r.P0.M00000001", store.size("r.P0.M00000001"))
    assert key in store._jseg_footers                   # stale entry live
    segment.purge_footer_cache(store)                   # iteration hook
    assert key not in store._jseg_footers
    r = segment.SegmentReader(store, "r.P0.M00000001")
    assert [k for k, _ in r.iter_records()] == \
        [f"b{i:03d}" for i in range(50)]


# --- resume stickiness -------------------------------------------------------

def test_push_resume_sticky(tmp_path):
    """A resumed task keeps its push mode from the task doc (like the
    pipeline/replication rules): a crashed push run's data is visible
    only through manifests, which a push-off resume would never
    consult."""
    _install_module()
    spec = TaskSpec(taskfn=_MOD, mapfn=_MOD, partitionfn=_MOD,
                    reducefn=_MOD,
                    storage=_storage(tmp_path, "mem", "resume"))
    store = MemJobStore()
    from lua_mapreduce_tpu.core.constants import TaskStatus
    store.put_task({"_id": "unique", "status": TaskStatus.MAP.value,
                    "iteration": 1, "spec": spec.describe(),
                    "pipeline": False, "push": True, "batch_k": 1,
                    "segment_format": "v1", "replication": 1,
                    "speculation": 0.0})
    server = Server(store, poll_interval=0.01, push=False).configure(spec)
    w = Worker(store).configure(max_iter=800, max_sleep=0.02)
    t = threading.Thread(target=w.execute, daemon=True)
    t.start()
    server.loop()
    t.join(timeout=30)
    assert server.push is True, "resume must keep the task doc's push mode"
    got = {k: v[0] for k, v in iter_results(
        get_storage_from(spec.storage), "result")}
    assert got == GOLDEN
