"""CLI launchers (cli/execute_server, cli/execute_worker,
cli/remove_results): the reference's L7 layer (execute_server.lua,
execute_worker.lua, remove_results.sh — SURVEY.md §2.2) driven
end-to-end in-process."""

import glob
import os

import pytest

from examples.wordcount.naive import naive_wordcount
from lua_mapreduce_tpu.cli import (execute_server, execute_worker,
                                   remove_results)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = sorted(glob.glob(os.path.join(REPO, "examples", "wordcount",
                                       "[a-z]*.py")))


def test_execute_server_inline_workers(tmp_path, capsys):
    """Full wordcount through the server CLI with an in-process pool,
    slash-path module normalization included (execute_server.lua:37-39)."""
    import examples.wordcount.finalfn as finalfn
    finalfn.counts.clear()
    # taskfn reads files from init args
    rc = execute_server.main([
        "mem",
        "examples/wordcount/taskfn.py",
        "examples/wordcount/mapfn",
        "examples.wordcount.partitionfn",
        "examples.wordcount.reducefn",
        "--finalfn", "examples.wordcount.finalfn",
        "--inline-workers", "2",
        "--poll", "0.02",
        "--init-arg", f"files={os.pathsep.join(CORPUS)}",
        "--quiet",
    ])
    assert rc == 0
    golden = naive_wordcount(CORPUS)
    assert dict(finalfn.counts) == golden


def test_execute_worker_rejects_bad_phase():
    with pytest.raises(SystemExit):
        execute_worker.main(["/tmp/nowhere", "--phases", "bogus"])


def test_execute_server_strict_flag_parses():
    args = execute_server.build_parser().parse_args(
        ["mem", "a", "b", "c", "d", "--strict"])
    assert args.strict is True


def test_batch_k_flags_parse():
    """The batch-lease knob on both launchers: the server flag is the
    fleet default (task doc), the worker flag an explicit override
    (None = follow the doc)."""
    args = execute_server.build_parser().parse_args(
        ["mem", "a", "b", "c", "d", "--batch-k", "16"])
    assert args.batch_k == 16
    args = execute_worker.build_parser().parse_args(["/tmp/x"])
    assert args.batch_k is None and args.max_jobs is None
    args = execute_worker.build_parser().parse_args(
        ["/tmp/x", "--batch-k", "8", "--max-jobs", "40"])
    assert args.batch_k == 8 and args.max_jobs == 40


def test_segment_format_flags_parse():
    """The spill-encoding knob on both launchers: server flag = fleet
    default (task doc), worker flag = explicit per-host pin (None =
    follow the doc); bogus values are rejected at parse time."""
    import pytest

    args = execute_server.build_parser().parse_args(
        ["mem", "a", "b", "c", "d", "--segment-format", "v2"])
    assert args.segment_format == "v2"
    args = execute_server.build_parser().parse_args(
        ["mem", "a", "b", "c", "d"])
    assert args.segment_format == "v1"
    args = execute_worker.build_parser().parse_args(["/tmp/x"])
    assert args.segment_format is None
    args = execute_worker.build_parser().parse_args(
        ["/tmp/x", "--segment-format", "v1"])
    assert args.segment_format == "v1"
    with pytest.raises(SystemExit):
        execute_server.build_parser().parse_args(
            ["mem", "a", "b", "c", "d", "--segment-format", "v3"])


def test_execute_server_segment_v2_end_to_end(capsys):
    """End-to-end through the server CLI with --segment-format v2:
    inline workers pick the format up from the task document and the
    result matches the naive oracle (results themselves stay v1)."""
    import examples.wordcount.finalfn as finalfn
    finalfn.counts.clear()
    rc = execute_server.main([
        "mem",
        "examples.wordcount.taskfn",
        "examples.wordcount.mapfn",
        "examples.wordcount.partitionfn",
        "examples.wordcount.reducefn",
        "--finalfn", "examples.wordcount.finalfn",
        "--inline-workers", "2",
        "--poll", "0.02",
        "--segment-format", "v2",
        "--init-arg", f"files={os.pathsep.join(CORPUS)}",
        "--quiet",
    ])
    assert rc == 0
    assert dict(finalfn.counts) == naive_wordcount(CORPUS)


def test_execute_server_batched_inline_workers(tmp_path, capsys):
    """End-to-end through the server CLI with --batch-k: inline workers
    inherit the lease size from the task document and the result still
    matches the naive oracle."""
    import examples.wordcount.finalfn as finalfn
    finalfn.counts.clear()
    rc = execute_server.main([
        "mem",
        "examples.wordcount.taskfn",
        "examples.wordcount.mapfn",
        "examples.wordcount.partitionfn",
        "examples.wordcount.reducefn",
        "--finalfn", "examples.wordcount.finalfn",
        "--inline-workers", "2",
        "--poll", "0.02",
        "--batch-k", "4",
        "--init-arg", f"files={os.pathsep.join(CORPUS)}",
        "--quiet",
    ])
    assert rc == 0
    assert dict(finalfn.counts) == naive_wordcount(CORPUS)


def test_remove_results_drops_store_and_files(tmp_path):
    from lua_mapreduce_tpu.coord.filestore import FileJobStore
    from lua_mapreduce_tpu.coord.jobstore import make_job
    from lua_mapreduce_tpu.store.router import get_storage_from

    coord = str(tmp_path / "coord")
    spill = str(tmp_path / "spill")
    store = FileJobStore(coord)
    store.insert_jobs("map_jobs", [make_job("k", 1)])
    store.put_task({"_id": "unique", "status": "MAP", "spec": {}})
    data = get_storage_from(f"shared:{spill}")
    b = data.builder()
    b.write("x\n")
    b.build("result.P0")

    rc = remove_results.main([coord, "--storage", f"shared:{spill}",
                              "--yes"])
    assert rc == 0
    assert store.get_task() is None
    assert sum(store.counts("map_jobs").values()) == 0
    assert data.list("result.P*") == []


def test_remove_results_aborts_without_confirmation(tmp_path, monkeypatch):
    monkeypatch.setattr("builtins.input", lambda *_: "n")
    coord = str(tmp_path / "coord")
    from lua_mapreduce_tpu.coord.filestore import FileJobStore
    FileJobStore(coord).put_task({"_id": "unique", "status": "MAP",
                                  "spec": {}})
    rc = remove_results.main([coord])
    assert rc == 1
    assert FileJobStore(coord).get_task() is not None


@pytest.mark.heavy
def test_lm_example_smoke():
    """The long-context LM demo must run end to end on a virtual mesh
    (and regression-guards the jax_env fix: with JAX_PLATFORMS=cpu in
    the env, the process must PIN jax.config too — the axon plugin's
    sitecustomize overrides the env var alone, which once left this
    demo hanging on a wedged tunnel backend)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "examples.lm.train_lm", "--steps", "2",
         "--seq", "32", "--dp", "2", "--sp", "2", "--grad-accum", "1",
         "--batch", "4"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done: final loss" in out.stdout
