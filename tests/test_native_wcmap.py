"""Native C++ wordcount map (core/native_wcmap.py): must produce
byte-identical run files to the Python mapfn+partitionfn path it
replaces, and slot into the engine transparently."""

import os

import pytest

from lua_mapreduce_tpu.core import native_wcmap
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.job import run_map_job
from lua_mapreduce_tpu.store.sharedfs import SharedStore

pytestmark = pytest.mark.skipif(
    not native_wcmap.native_available(),
    reason="native wcmap did not build (no g++?)")

TEXT = ('the quick "brown" fox\tjumps\n over the lazy dog\n'
        'the fox\x1cagain\nback\\slash and tab\there\n' + "zz " * 2500)


def _run_both(tmp_path, text):
    """Run the same map job natively and in Python; return both dirs."""
    inp = tmp_path / "split0.txt"
    inp.write_text(text)

    import sys
    import types

    from collections import Counter
    mod = types.ModuleType("wcmap_mod")

    def mapfn(key, value, emit):
        with open(value) as f:
            counts = Counter(f.read().split())
        for w, n in counts.items():
            emit(w, n)
    mod.mapfn = mapfn
    mod.taskfn = lambda emit: emit("s", str(inp))
    mod.partitionfn = lambda key: sum(key[:4].encode()) % 5
    mod.reducefn = lambda key, values: sum(values)
    sys.modules["wcmap_mod"] = mod

    outs = {}
    for variant, tagged in (("native", True), ("python", False)):
        if tagged:
            mapfn.native_map = {"kind": "wordcount_file",
                                "num_reducers": 5, "hash_prefix": 4}
        else:
            mapfn.__dict__.pop("native_map", None)
        spill = str(tmp_path / f"spill_{variant}")
        spec = TaskSpec(taskfn="wcmap_mod", mapfn="wcmap_mod",
                        partitionfn="wcmap_mod", reducefn="wcmap_mod",
                        storage=f"shared:{spill}")
        store = SharedStore(spill)
        run_map_job(spec, store, "0", "s", str(inp))
        outs[variant] = {
            name: "".join(store.lines(name))
            for name in store.list("result.P*.M*")
        }
    return outs


def test_native_run_files_byte_identical(tmp_path):
    outs = _run_both(tmp_path, TEXT)
    assert outs["native"], "native path produced no run files"
    assert outs["native"] == outs["python"]


def test_non_ascii_falls_back_to_python(tmp_path):
    """Unicode input (NBSP is Python whitespace) must take the Python
    path — results still correct, via fallback."""
    outs = _run_both(tmp_path, "café nb sp café\n")
    assert outs["native"] == outs["python"]
    joined = "".join(outs["native"].values())
    assert '["café",[2]]' in joined
    # NBSP really split the words (Python semantics preserved)
    assert '["nb",[1]]' in joined and '["sp",[1]]' in joined


def test_bigtask_tag_runs_native_end_to_end(tmp_path, monkeypatch):
    """The Europarl-scale task module's declared tag routes through the
    native kernel inside a full engine run and still golden-diffs. The
    native path must ACTUALLY run (a silent gate regression falling back
    to Python would keep results green while the benchmark's headline
    claim quietly reverts — code-review r2)."""
    from examples.wordcount_big import corpus
    from lua_mapreduce_tpu.core import native_wcmap as nw
    from lua_mapreduce_tpu.engine.local import LocalExecutor

    native_hits = []
    real = nw.run_native_map

    def counting(*a, **k):
        ok = real(*a, **k)
        if ok:
            native_hits.append(1)
        return ok
    monkeypatch.setattr(nw, "run_native_map", counting)

    cdir = str(tmp_path / "corpus")
    spec = TaskSpec(taskfn="examples.wordcount_big.bigtask",
                    mapfn="examples.wordcount_big.bigtask",
                    partitionfn="examples.wordcount_big.bigtask",
                    reducefn="examples.wordcount_big.bigtask",
                    init_args={"corpus_dir": cdir, "n_splits": 3},
                    storage=f"shared:{tmp_path}/spill")
    ex = LocalExecutor(spec)
    ex.run()
    got = {k: v[0] for k, v in ex.results()}
    assert len(native_hits) == 3, "native kernel did not serve all maps"

    # golden: count the same splits naively
    from collections import Counter
    want = Counter()
    for i in range(3):
        with open(corpus.split_path(cdir, i)) as f:
            want.update(f.read().split())
    assert got == dict(want)
