"""Native C++ shuffle merge: must produce EXACTLY the Python heap merge's
groups (the golden-diff contract of core/native_merge.py) across key
types, and slot transparently into the reduce path."""

import numpy as np
import pytest

from lua_mapreduce_tpu.core import native_merge
from lua_mapreduce_tpu.core.merge import merge_iterator
from lua_mapreduce_tpu.core.serialize import dump_record, sorted_keys
from lua_mapreduce_tpu.store.sharedfs import SharedStore

pytestmark = pytest.mark.skipif(
    not native_merge.native_available(),
    reason="native merge did not build (no g++?)")


def _write_run(store, name, records):
    b = store.builder()
    for k, vs in records:
        b.write(dump_record(k, vs) + "\n")
    b.build(name)


def _sorted_run(pairs):
    keys = sorted_keys([k for k, _ in pairs])
    d = dict(pairs)
    return [(k, d[k]) for k in keys]


def test_matches_python_merge_mixed_types(tmp_path):
    store = SharedStore(str(tmp_path))
    runs = {
        "r.0": _sorted_run([(False, [1]), (3, [10]), ("apple", [1, 2]),
                            ((1, "a"), [5]), (None, ["z"])]),
        "r.1": _sorted_run([(True, [2]), (3, [20]), (3.5, [9]),
                            ("apple", [3]), ("käse", [7]),
                            ((1, "a"), [6]), ((1, "a", 0), [8])]),
        "r.2": _sorted_run([(-2, [0]), ("Zebra", [4]),
                            ("line\nbreak\t\"q\"", [11])]),
    }
    for name, recs in runs.items():
        _write_run(store, name, recs)
    names = sorted(runs)
    want = list(merge_iterator(store, names))
    got = list(native_merge.native_merge_records(store, names))
    assert got == want


def test_large_fanin_wordcount_shape(tmp_path):
    """Many runs, overlapping string keys, concatenated value lists."""
    store = SharedStore(str(tmp_path))
    rng = np.random.RandomState(0)
    vocab = [f"w{i:03d}" for i in range(200)]
    names = []
    for r in range(16):
        words = sorted(rng.choice(vocab, size=80, replace=False))
        _write_run(store, f"run.{r}", [(w, [1] * rng.randint(1, 4))
                                       for w in words])
        names.append(f"run.{r}")
    want = list(merge_iterator(store, names))
    got = list(native_merge.native_merge_records(store, names))
    assert got == want
    assert sum(len(v) for _, v in got) == sum(len(v) for _, v in want)


def test_empty_and_blank_runs(tmp_path):
    store = SharedStore(str(tmp_path))
    _write_run(store, "a", [("k", [1])])
    b = store.builder()
    b.write("\n\n")
    b.build("blank")
    b2 = store.builder()
    b2.build("empty")
    got = list(native_merge.native_merge_records(
        store, ["a", "blank", "empty"]))
    assert got == [("k", [1])]


def test_non_local_store_falls_back(tmp_path):
    from lua_mapreduce_tpu.store.memfs import MemStore
    assert native_merge.native_merge_records(MemStore(), ["x"]) is None


def test_reduce_path_uses_it_end_to_end(tmp_path):
    """Whole engine run on the shared backend still golden-diffs (the
    reduce path now routes through the native merge)."""
    import types, sys
    mod = types.ModuleType("nm_wc")
    corpus = {"d1": "a b a c", "d2": "b a"}
    mod.taskfn = lambda emit: [emit(k, v) for k, v in corpus.items()]
    def mapfn(key, value, emit):
        for w in value.split():
            emit(w, 1)
    mod.mapfn = mapfn
    mod.partitionfn = lambda key: sum(key.encode()) % 3
    def reducefn(key, values):
        return sum(values)
    reducefn.associative_reducer = True
    reducefn.commutative_reducer = True
    mod.reducefn = reducefn
    sys.modules["nm_wc"] = mod

    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.local import LocalExecutor
    spec = TaskSpec(taskfn="nm_wc", mapfn="nm_wc", partitionfn="nm_wc",
                    reducefn="nm_wc", storage=f"shared:{tmp_path}/spill")
    ex = LocalExecutor(spec)
    ex.run()
    out = {k: v[0] for k, v in ex.results()}
    assert out == {"a": 3, "b": 2, "c": 1}


def test_bigint_keys_stay_distinct(tmp_path):
    """Keys beyond double precision must not merge (exact digit-string
    comparison, matching Python's arbitrary-precision ints)."""
    store = SharedStore(str(tmp_path))
    big = 2 ** 64
    _write_run(store, "a", [(big, [1])])
    _write_run(store, "b", [(big + 1, [2]), (-big - 1, [3])])
    names = ["a", "b"]
    want = list(merge_iterator(store, names))
    got = list(native_merge.native_merge_records(store, names))
    assert got == want
    assert len(got) == 3


def test_mixed_int_float_keys_beyond_2p53(tmp_path):
    """Int 2**53+1 vs float 9007199254740992.0 round to the same double;
    Python compares exactly and keeps them distinct — the native compare
    must too (ADVICE r1: silent native/Python divergence)."""
    store = SharedStore(str(tmp_path))
    runs = {
        "a": _sorted_run([(2 ** 53 + 1, [1]), (2 ** 53, [7])]),
        "b": _sorted_run([(float(2 ** 53 + 2), [3]), (-(2 ** 53) - 1, [4]),
                          (float(2 ** 53), [2]), (0.5, [6])]),
        "c": _sorted_run([(10 ** 40, [8]), (1e40, [9]),
                          (-float(2 ** 53), [5])]),
    }
    for name, recs in runs.items():
        _write_run(store, name, recs)
    names = sorted(runs)
    want = list(merge_iterator(store, names))
    got = list(native_merge.native_merge_records(store, names))
    assert got == want


def _write_runs(store, runs):
    for name, recs in runs.items():
        _write_run(store, name, recs)
    return sorted(runs)


def test_fold_sum_matches_python_reduce(tmp_path):
    """Fused merge+sum must publish a result file byte-identical to the
    Python merge + sum fold + dump_record path."""
    store = SharedStore(str(tmp_path / "runs"))
    out_n = SharedStore(str(tmp_path / "out_native"))
    out_p = SharedStore(str(tmp_path / "out_python"))
    runs = {
        "r.0": _sorted_run([("a", [1, 2]), ("b", [3]), ("z", [0]),
                            (7, [10]), ((1, "k"), [4])]),
        "r.1": _sorted_run([("a", [5]), ("c", [-2, 2]), (7, [1]),
                            ((1, "k"), [6])]),
    }
    names = _write_runs(store, runs)

    ok = native_merge.native_merge_reduce_sum(store, names, out_n, "res.P0")
    assert ok

    b = out_p.builder()
    for k, vs in merge_iterator(store, names):
        b.write(dump_record(k, [sum(vs)]) + "\n")
    b.build("res.P0")
    assert "".join(out_n.lines("res.P0")) == "".join(out_p.lines("res.P0"))


@pytest.mark.parametrize("poison", [
    [("a", [1.5])],                    # float value
    [("a", ["x"])],                    # string value
    [("a", [[1, 2]])],                 # nested value
    [("a", [2 ** 64])],                # > int64
    [("a", [2 ** 62]), ("a", [2 ** 62, 2 ** 62])],   # overflow on fold
])
def test_fold_sum_falls_back_on_non_int64(tmp_path, poison):
    store = SharedStore(str(tmp_path / "runs"))
    out = SharedStore(str(tmp_path / "out"))
    runs = {"r.0": _sorted_run([("a", [1])]), "r.1": poison}
    names = _write_runs(store, runs)
    assert native_merge.native_merge_reduce_sum(
        store, names, out, "res.P0") is False
    assert out.list("*") == []         # no partial result published


def test_fold_sum_reduce_job_end_to_end(tmp_path, monkeypatch):
    """run_reduce_job routes a native_reduce='sum' + ACI reducer through
    the fused pass — asserted with a spy, not assumed (a silent gate
    regression must fail here, not pass vacuously via the Python
    fallback) — and the result equals the Python engine's."""
    import sys
    import types

    from lua_mapreduce_tpu.engine import job as job_mod
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.local import LocalExecutor

    fused_hits = []
    real = job_mod.native_merge_reduce_sum

    def counting(*a, **k):
        ok = real(*a, **k)
        if ok:
            fused_hits.append(1)
        return ok
    monkeypatch.setattr(job_mod, "native_merge_reduce_sum", counting)

    corpus = {"d1": "a b a c a", "d2": "b a d"}
    results = {}
    for variant, tag in (("native", "sum"), ("python", None)):
        mod = types.ModuleType(f"fold_{variant}")
        mod.taskfn = lambda emit: [emit(k, v) for k, v in corpus.items()]
        def mapfn(key, value, emit):
            for w in value.split():
                emit(w, 1)
        mod.mapfn = mapfn
        mod.partitionfn = lambda key: sum(key.encode()) % 3
        def reducefn(key, values):
            return sum(values)
        reducefn.associative_reducer = True
        reducefn.commutative_reducer = True
        if tag:
            reducefn.native_reduce = tag
        mod.reducefn = reducefn
        sys.modules[f"fold_{variant}"] = mod
        spec = TaskSpec(taskfn=f"fold_{variant}", mapfn=f"fold_{variant}",
                        partitionfn=f"fold_{variant}",
                        reducefn=f"fold_{variant}",
                        storage=f"shared:{tmp_path}/sp_{variant}")
        ex = LocalExecutor(spec)
        ex.run()
        results[variant] = {k: v[0] for k, v in ex.results()}
    assert results["native"] == results["python"] == \
        {"a": 4, "b": 2, "c": 1, "d": 1}
    assert fused_hits, "fused native reduce never fired for the tagged task"


def test_unparseable_records_fall_back(tmp_path):
    """NaN keys parse on the Python path but not in C++ — the native
    wrapper must return None (fallback), not raise mid-reduce."""
    store = SharedStore(str(tmp_path))
    b = store.builder()
    b.write('[NaN,[1]]\n')
    b.build("nan_run")
    assert native_merge.native_merge_records(store, ["nan_run"]) is None


def test_global_native_kill_switch(tmp_path, monkeypatch):
    """LMR_DISABLE_NATIVE=1 must force the pure-Python path everywhere
    (single choke point: native_build.load_native) while results stay
    identical — the production divergence-debugging switch."""
    store = SharedStore(str(tmp_path))
    _write_run(store, "a", [("k", [1, 2])])
    monkeypatch.setenv("LMR_DISABLE_NATIVE", "1")
    assert native_merge.native_available() is False
    assert native_merge.native_merge_records(store, ["a"]) is None
    assert native_merge.native_merge_reduce_sum(
        store, ["a"], store, "res") is False
    from lua_mapreduce_tpu.core import native_wcmap
    assert native_wcmap.native_available() is False
    monkeypatch.delenv("LMR_DISABLE_NATIVE")
    assert native_merge.native_available() is True
