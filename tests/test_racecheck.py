"""lmr-racecheck tests (DESIGN §30): the thread-spawn graph, the
interprocedural lockset/lock-order pass (LMR026-030) with fixture
pairs, the seeded-race pins, the runtime lock-order sanitizer, the
thread-shutdown audit, the conc CLI/SARIF surface, the whole-repo
cleanliness + wall-budget gates, and regressions for the three at-head
races this band found and fixed (BufferPool.budget, FleetSupervisor.
resize, the pipelined premerge exists-under-lock)."""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from lua_mapreduce_tpu.analysis import lockset, sarif
from lua_mapreduce_tpu.analysis import threads as threads_mod
from lua_mapreduce_tpu.analysis.callgraph import CallGraph, build_callgraph
from lua_mapreduce_tpu.utils import lockcheck

PKG = os.path.dirname(os.path.abspath(lockset.__file__))
REPO = os.path.dirname(os.path.dirname(PKG))


def _conc(*files):
    g = CallGraph.from_sources(
        [(rel, textwrap.dedent(src)) for rel, src in files])
    return lockset.analyze_conc(graph=g, baseline="/nonexistent")


def _rules(res):
    return [f.rule for f in res.findings]


# --- thread-spawn graph -----------------------------------------------------

SPAWNY = ("engine/fx.py", """\
    import threading

    class Worker:
        def configure(self):
            return self

        def execute(self):
            self.state = 1

    def mint():
        w = Worker()
        return w

    def spawn_fluent():
        w = Worker().configure()
        threading.Thread(target=w.execute, daemon=True).start()

    def spawn_factory():
        w = mint()
        threading.Thread(target=w.execute, daemon=True).start()
    """)


def test_thread_graph_resolves_fluent_builder_and_factory_targets():
    """The two spawn shapes the real CLIs use: a fluent-builder chain
    (``Worker(store).configure(...)``) and a local mint() factory.
    Losing either makes Worker.execute look main-thread-only and
    silences every contested-ness-gated rule downstream."""
    g = CallGraph.from_sources([(SPAWNY[0], textwrap.dedent(SPAWNY[1]))])
    tg = threads_mod.build_thread_graph(g)
    entries = {s.entry for s in tg.spawns}
    assert entries == {"engine/fx.py::Worker.execute"}
    # two distinct spawn sites -> the entry races itself
    assert "engine/fx.py::Worker.execute" in tg.multi_entries
    assert tg.contested(["engine/fx.py::Worker.execute"])


def test_thread_graph_roots_separate_thread_code_from_main():
    g = CallGraph.from_sources([("engine/fx.py", textwrap.dedent("""\
        import threading

        class W:
            def go(self):
                threading.Thread(target=self.loop, daemon=True).start()
                self.prep()

            def loop(self):
                self.tick()

            def tick(self):
                pass

            def prep(self):
                pass
        """))])
    tg = threads_mod.build_thread_graph(g)
    assert tg.roots_of("engine/fx.py::W.tick") == {"engine/fx.py::W.loop"}
    assert "main" in tg.roots_of("engine/fx.py::W.prep")


# --- LMR026: dropped-lock write ---------------------------------------------

def test_lmr026_unguarded_write_to_guarded_field_fires():
    res = _conc(("engine/fx.py", """\
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def start(self):
                threading.Thread(target=self.add, daemon=True).start()

            def add(self):
                with self._lock:
                    self.total += 1

            def drain(self):
                out = self.total
                self.total = 0
                return out
        """))
    assert "LMR026" in _rules(res), res.findings
    assert any(f.line == 17 for f in res.findings)   # the naked write


def test_lmr026_quiet_when_every_access_is_guarded():
    res = _conc(("engine/fx.py", """\
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def start(self):
                threading.Thread(target=self.add, daemon=True).start()

            def add(self):
                with self._lock:
                    self.total += 1

            def drain(self):
                with self._lock:
                    out = self.total
                    self.total = 0
                return out
        """))
    assert _rules(res) == [], res.findings


def test_lmr026_quiet_without_thread_contestation():
    """Same dropped guard, no second thread root: single-threaded code
    gets to be sloppy — the band only polices actually-shared state."""
    res = _conc(("engine/fx.py", """\
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def add(self):
                with self._lock:
                    self.total += 1

            def drain(self):
                self.total = 0
        """))
    assert _rules(res) == [], res.findings


# --- LMR027: inconsistent locksets ------------------------------------------

SPLIT_GUARD = """\
    import threading

    class Split:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()
            self.v = 0

        def start(self):
            threading.Thread(target=self.inc, daemon=True).start()

        def inc(self):
            with self._a_lock:
                self.v += 1

        def dec(self):
            with self._b_lock:
                self.v -= 1
    """


def test_lmr027_disjoint_guards_exclude_nothing():
    res = _conc(("engine/fx.py", SPLIT_GUARD))
    assert "LMR027" in _rules(res), res.findings


def test_lmr027_quiet_with_one_consistent_guard():
    res = _conc(("engine/fx.py", SPLIT_GUARD.replace("self._b_lock:",
                                                     "self._a_lock:")))
    assert "LMR027" not in _rules(res), res.findings


# --- LMR028: lock-order cycles + re-acquisition -----------------------------

ABBA = """\
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def start(self):
            threading.Thread(target=self.ab, daemon=True).start()

        def ab(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def ba(self):
            with self._b_lock:
                with self._a_lock:
                    pass
    """


def test_lmr028_abba_cycle_fires_and_consistent_order_is_quiet():
    res = _conc(("engine/fx.py", ABBA))
    assert "LMR028" in _rules(res), res.findings
    assert res.cycles, "the SCC must be reported, not just the finding"
    fixed = ABBA.replace(
        "with self._b_lock:\n                with self._a_lock:",
        "with self._a_lock:\n                with self._b_lock:")
    res = _conc(("engine/fx.py", fixed))
    assert _rules(res) == [] and not res.cycles, res.findings


def test_lmr028_interprocedural_reacquire_of_module_lock():
    """outer() holds the module Lock and calls inner() which takes it
    again — self-deadlock on a non-reentrant lock that no single
    function shows. An RLock makes the same shape legal."""
    src = """\
        import threading
        _lock = threading.Lock()

        def outer():
            with _lock:
                inner()

        def inner():
            with _lock:
                pass
        """
    res = _conc(("engine/fx.py", src))
    assert _rules(res) == ["LMR028"], res.findings
    res = _conc(("engine/fx.py",
                 src.replace("threading.Lock()", "threading.RLock()")))
    assert _rules(res) == [], res.findings


# --- LMR029: blocking while holding a lock ----------------------------------

def test_lmr029_sleep_under_lock_fires_and_outside_is_quiet():
    src = """\
        import threading
        import time

        class Slow:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    time.sleep(1)
        """
    res = _conc(("engine/fx.py", src))
    assert _rules(res) == ["LMR029"], res.findings
    res = _conc(("engine/fx.py", src.replace(
        "with self._lock:\n                    time.sleep(1)",
        "with self._lock:\n                    pass\n"
        "                time.sleep(1)")))
    assert _rules(res) == [], res.findings


def test_lmr029_blocking_call_three_frames_below_the_lock():
    """The reason this band is interprocedural: the lock and the sleep
    are in different functions, so the per-function pass is blind —
    only may-held propagation connects them."""
    res = _conc(("engine/fx.py", """\
        import threading
        import time

        class Deep:
            def __init__(self):
                self._lock = threading.Lock()

            def top(self):
                with self._lock:
                    self.mid()

            def mid(self):
                self.low()

            def low(self):
                time.sleep(1)
        """))
    assert [(f.rule, f.line) for f in res.findings] == [("LMR029", 16)], \
        res.findings
    assert "via engine/fx.py::Deep.mid" in res.findings[0].message, \
        res.findings[0].message      # the held-by-caller witness chain


# --- LMR030: cross-thread publish without hand-off --------------------------

PUBLISH = """\
    import threading

    def collect():
        out = []

        def work():
            out.append(1)

        t = threading.Thread(target=work)
        t.start()
        return len(out)
    """


def test_lmr030_read_after_spawn_without_join_fires():
    res = _conc(("engine/fx.py", PUBLISH))
    assert "LMR030" in _rules(res), res.findings


def test_lmr030_join_before_read_is_a_proper_handoff():
    res = _conc(("engine/fx.py", PUBLISH.replace(
        "t.start()", "t.start()\n    t.join()")))
    assert _rules(res) == [], res.findings


# --- suppression + catalog + seeded pins ------------------------------------

def test_conc_findings_honor_inline_pragmas():
    rel, rule, src = lockset.KNOWN_RACES["dropped-lock-write"]
    lines = src.splitlines()
    # the seeded fixture's naked write gets an explicit excuse
    lines[12] += "  # lmr: disable=LMR026"
    g = CallGraph.from_sources([(rel, "\n".join(lines) + "\n")])
    res = lockset.analyze_conc(graph=g, baseline="/nonexistent")
    assert "LMR026" not in _rules(res), res.findings
    assert any(f.rule == "LMR026" for f in res.raw)   # raw keeps it


def test_rule_catalog_includes_the_conc_band():
    from lua_mapreduce_tpu.analysis.lint import rule_catalog
    ids = {r["id"] for r in rule_catalog()}
    assert {"LMR026", "LMR027", "LMR028", "LMR029", "LMR030"} <= ids


@pytest.mark.parametrize("name", sorted(lockset.KNOWN_RACES))
def test_seeded_race_is_refound(name):
    """The protocol checker's discipline on the lock plane: every race
    seeded into KNOWN_RACES must keep being found, forever — a pass
    that stops seeing a planted race has quietly lost its teeth."""
    hits = lockset.find_seeded(name)
    expected = lockset.KNOWN_RACES[name][1]
    assert hits and all(f.rule == expected for f in hits), (name, hits)


# --- whole-repo gates -------------------------------------------------------

def test_repo_is_conc_clean_within_the_wall_budget():
    res = lockset.analyze_conc()
    assert res.findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in res.findings)
    assert not res.cycles
    assert res.wall_s < 30.0, res.wall_s


def test_repo_thread_shutdown_audit():
    """Every Thread the package ever spawns is either daemon (dies with
    the process) or joined by its owning module — no thread can outlive
    its executor un-stopped. The dynamic twin is the no_thread_leak
    fixture on the golden matrix."""
    tg = threads_mod.build_thread_graph(build_callgraph(None))
    report = threads_mod.shutdown_report(tg)
    assert report, "the package does spawn threads; an empty report " \
                   "means the spawn scan broke"
    bad = [e for e in report if not (e["daemon"] or e["module_joins"])]
    assert bad == [], bad


def test_static_lock_model_matches_source_sites():
    """Every modeled creation site must point at an actual
    threading.Lock()/RLock() call in the file it names — the runtime
    sanitizer keys on exactly these (rel, line) pairs, so a drifted
    line number would fail the LMR_LOCKCHECK gate spuriously."""
    model = lockset.static_lock_model()
    assert model["locks"] and not model["cyclic"]
    for site in model["locks"]:
        rel, _, line = site.rpartition(":")
        src_line = open(os.path.join(PKG, "..", rel)).read() \
            .splitlines()[int(line) - 1]
        assert "Lock(" in src_line, (site, src_line)


# --- runtime lock-order sanitizer -------------------------------------------

def test_lockcheck_utest():
    lockcheck.utest()


def test_lockcheck_records_and_verifies_nested_order():
    now = [0.0]
    lockcheck.install(clock=lambda: now[0])
    try:
        lockcheck.reset()
        # created from test code (outside the package): raw, invisible
        raw = threading.Lock()
        assert type(raw) is type(threading.RLock()) or \
            not isinstance(raw, lockcheck._LockProxy)
        assert lockcheck.report()["sites"] == []
    finally:
        lockcheck.uninstall()
        lockcheck.reset()


def test_lockcheck_overhead_is_negligible_when_uninstalled():
    """LMR_LOCKCHECK unset = the factories are the raw builtins; the
    watchdog must cost exactly nothing when off."""
    assert threading.Lock is lockcheck._real_lock
    assert threading.RLock is lockcheck._real_rlock


# --- conc CLI surface -------------------------------------------------------

def _cli(*argv):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "lua_mapreduce_tpu.analysis", *argv],
        capture_output=True, text=True, env=env, cwd=REPO)


def test_cli_conc_gate_is_green_and_pins_the_seeded_races():
    r = _cli("conc", "--fail-on-findings", "--format", "json")
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["count"] == 0
    conc = payload["conc"]
    assert conc["locks"] >= 20 and conc["spawn_sites"] >= 8
    assert conc["cycles"] == []
    assert conc["wall_s"] < 30.0
    seeded = {e["run"]: e["found"] for e in conc["seeded"]}
    assert seeded == {"seeded:dropped-lock-write": True,
                      "seeded:abba-deadlock": True}


def test_cli_conc_fails_on_a_raced_fixture_and_exports_sarif(tmp_path):
    p = tmp_path / "engine" / "fx.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent(ABBA))
    r = _cli("conc", str(tmp_path), "--fail-on-findings",
             "--baseline", "/nonexistent")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "LMR028" in r.stdout
    r = _cli("conc", str(tmp_path), "--format", "sarif",
             "--baseline", "/nonexistent")
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    sarif.validate_sarif(doc)
    assert any(res["ruleId"] == "LMR028"
               for res in doc["runs"][0]["results"])


# --- regressions for the three at-head fixes --------------------------------

def test_bufferpool_budget_is_a_locked_property():
    """At-head LMR026: worker.py's autotune apply and local.py's spill
    sizing both assign ``pool.budget`` from other threads while
    charge() reads it under the pool lock. The fix routes the public
    attribute through a locked property; hammer it to prove the
    property holds under contention."""
    from lua_mapreduce_tpu.engine.push import BufferPool
    assert isinstance(BufferPool.budget, property)
    pool = BufferPool(1 << 20)
    stop = threading.Event()

    def churn():
        while not stop.is_set():
            pool.budget = pool.budget + 1

    threads = [threading.Thread(target=churn) for _ in range(3)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 0.5
    while time.monotonic() < deadline:
        assert pool.budget >= (1 << 20)
        pool.charge(64)
        pool.uncharge(64)
    stop.set()
    for t in threads:
        t.join(5.0)
    assert not any(t.is_alive() for t in threads)
    assert pool.held == 0


def test_fleet_resize_runs_spawn_and_retire_outside_the_lock():
    """At-head LMR029: resize used to call the injected spawn/retire
    callbacks while holding the supervisor lock — a callback touching
    the supervisor (here: reading .size, as a real minting hook
    logging fleet state would) deadlocked. Now it must complete."""
    from lua_mapreduce_tpu.sched.controller import FleetSupervisor
    sizes = []
    sup = FleetSupervisor(
        spawn=lambda seq: sizes.append(sup.size) or f"w{seq}",
        retire=lambda m: sizes.append(sup.size),
        baseline=1, cap=8)
    done = []

    def run():
        sup.resize(5)
        sup.resize(2)
        done.append(True)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(10.0)
    assert done, "resize deadlocked on a re-entrant spawn/retire hook"
    assert sup.size == 2
    assert len(sizes) == 5 + 3   # 5 spawns up, 3 retires down


def test_fleet_concurrent_resize_converges():
    from lua_mapreduce_tpu.sched.controller import FleetSupervisor
    sup = FleetSupervisor(spawn=lambda seq: f"w{seq}",
                          retire=lambda m: None, baseline=1, cap=16)
    ts = [threading.Thread(target=sup.resize, args=(n,))
          for n in (4, 9, 16, 2, 7)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10.0)
    assert 1 <= sup.size <= 16
    assert len(sup.members) == len(set(sup.members))   # no double-adds


def test_premerge_failure_probes_store_outside_the_tracker_lock():
    """At-head LMR029: the pipelined premerge failure path used to call
    ``self._view.exists()`` (store IO) while holding the spill-tracker
    lock, convoying every map worker behind one slow store probe. Pin
    the fixed shape statically: the fixture twin of the OLD shape still
    fires, and the real engine/local.py is clean (covered by the
    whole-repo gate above)."""
    res = _conc(("engine/fx.py", """\
        import threading

        class View:
            def exists(self, name):
                return True

        class Pipeline:
            def __init__(self):
                self._view = View()
                self._lock = threading.Lock()
                self.failed = 0

            def start(self):
                threading.Thread(target=self.premerge,
                                 daemon=True).start()

            def premerge(self):
                with self._lock:
                    self.failed += 1
                    self._view.exists("sp")
        """))
    assert "LMR029" in _rules(res), res.findings
