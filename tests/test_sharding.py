"""Sharded input pipeline (misc/make_sharded.lua analog): shard layout,
manifest contract, map-split view, host-sliced batch streams."""

import numpy as np
import pytest

from lua_mapreduce_tpu.store.memfs import MemStore
from lua_mapreduce_tpu.train.sharding import ShardedDataset, make_sharded


@pytest.fixture
def data():
    rng = np.random.RandomState(0)
    x = rng.rand(197, 8).astype(np.float32)     # 197-split contract size
    y = rng.randint(0, 10, 197).astype(np.int32)
    return x, y


def test_roundtrip_covers_every_example(data):
    x, y = data
    store = MemStore()
    names = make_sharded(store, "euro", x, y, n_shards=13)
    assert len(names) == 13
    ds = ShardedDataset(store, "euro")
    assert ds.n_shards == 13 and ds.n_examples == 197
    xs, ys = zip(*(ds.load_shard(i) for i in range(13)))
    np.testing.assert_array_equal(np.concatenate(xs), x)
    np.testing.assert_array_equal(np.concatenate(ys), y)


def test_shard_names_are_the_map_splits(data):
    store = MemStore()
    make_sharded(store, "euro", *data, n_shards=5)
    ds = ShardedDataset(store, "euro")
    for name in ds.shard_names():
        assert store.exists(name)


def test_host_partition_disjoint_and_complete(data):
    """Across hosts, every example is seen exactly once per epoch
    (shard i → host i % n_hosts; labels used as example identity)."""
    x, y = data
    y = np.arange(197, dtype=np.int64)          # unique ids
    store = MemStore()
    make_sharded(store, "euro", x, y, n_shards=8)
    ds = ShardedDataset(store, "euro")
    seen = []
    for host in range(3):
        rng = np.random.RandomState(host)
        for _, yb in ds.batches(7, rng=rng, host_id=host, n_hosts=3,
                                drop_remainder=False):
            seen.extend(yb.tolist())
    assert sorted(seen) == list(range(197))


def test_batches_cross_shard_boundaries(data):
    """Batch size larger than a shard: leftovers must carry across
    shards instead of yielding short batches."""
    x, y = data
    store = MemStore()
    make_sharded(store, "euro", x, y, n_shards=10)   # ~20/shard
    ds = ShardedDataset(store, "euro")
    batches = list(ds.batches(32, rng=np.random.RandomState(1)))
    assert all(len(xb) == 32 for xb, _ in batches)
    assert len(batches) == 197 // 32


def test_manifest_required_and_remove_idempotent(data):
    store = MemStore()
    with pytest.raises(FileNotFoundError):
        ShardedDataset(store, "nope")
    make_sharded(store, "euro", *data, n_shards=4)
    ds = ShardedDataset(store, "euro")
    ds.remove()
    ds.remove()
    assert store.list("euro*") == []


def test_rejects_bad_shard_count(data):
    x, y = data
    store = MemStore()
    with pytest.raises(ValueError):
        make_sharded(store, "e", x, y, n_shards=0)
    with pytest.raises(ValueError):
        make_sharded(store, "e", x, y, n_shards=198)


def test_equal_step_counts_across_hosts(data):
    """SPMD contract: with drop_remainder every host yields EXACTLY
    steps_per_epoch batches, however unevenly shards divide (unequal
    counts would deadlock the collective steps)."""
    x, y = data
    store = MemStore()
    make_sharded(store, "euro", x, y, n_shards=8)    # 3/3/2 shards → 3 hosts
    ds = ShardedDataset(store, "euro")
    expect = ds.steps_per_epoch(7, n_hosts=3)
    assert expect >= 1
    counts = [sum(1 for _ in ds.batches(7, rng=np.random.RandomState(h),
                                        host_id=h, n_hosts=3))
              for h in range(3)]
    assert counts == [expect] * 3, counts


def test_zero_step_hosts_raise(data):
    """steps_per_epoch must fail loudly, never return a silent 0."""
    x, y = data
    store = MemStore()
    make_sharded(store, "euro", x, y, n_shards=4)
    ds = ShardedDataset(store, "euro")
    with pytest.raises(ValueError, match="cannot feed"):
        ds.steps_per_epoch(7, n_hosts=8)        # hosts without shards
    with pytest.raises(ValueError, match="zero steps"):
        ds.steps_per_epoch(120, n_hosts=2)      # batch > host share


def test_reshard_replaces_layout_without_orphans(data):
    x, y = data
    store = MemStore()
    make_sharded(store, "euro", x, y, n_shards=13)
    make_sharded(store, "euro", x, y, n_shards=5)
    shards = [n for n in store.list("euro.S*")]
    assert len(shards) == 5, shards              # no 13-shard orphans
    ds = ShardedDataset(store, "euro")
    xs, _ = zip(*(ds.load_shard(i) for i in range(5)))
    np.testing.assert_array_equal(np.concatenate(xs), x)
