"""Mixture-of-experts layer: expert-parallel all_to_all routing vs the
single-device oracle, capacity semantics, and trainability."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lua_mapreduce_tpu.parallel import moe
from lua_mapreduce_tpu.parallel.mesh import make_mesh

D, FF, E, CAP = 16, 32, 8, 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=8, mp=1, devices=jax.devices("cpu")[:8],
                     axis_names=("ep", "unused"))


@pytest.fixture(scope="module")
def params():
    return moe.init_moe(jax.random.PRNGKey(0), D, FF, E)


def _tokens(seed, t=32):
    return jnp.asarray(np.random.RandomState(seed).randn(t, D),
                       jnp.float32)


def test_reference_routes_and_combines(params):
    x = _tokens(0)
    out, aux = moe.moe_ffn_reference(params, x, capacity=CAP)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    assert 0.5 < float(aux) < float(E)      # balanced-ish random router


def test_capacity_drops_overflow_tokens(params):
    """Force every token to one expert: only the first CAP tokens get
    output; the rest are dropped (zero contribution)."""
    p = dict(params)
    bias = jnp.zeros((D, E)).at[:, 3].set(100.0)
    p["moe_router_W"] = bias
    # positive tokens → positive feature sum → every token scores
    # expert 3 highest (a linear router has no bias term)
    x = jnp.abs(_tokens(1, t=16))
    out, _ = moe.moe_ffn_reference(p, x, capacity=CAP)
    norms = np.linalg.norm(np.asarray(out), axis=-1)
    assert (norms[:CAP] > 1e-6).all()
    np.testing.assert_allclose(norms[CAP:], 0.0, atol=1e-6)


def test_shard_matches_per_tile_reference(mesh, params):
    """ep-sharded MoE ≡ the oracle applied per device tile (same
    per-tile capacity semantics)."""
    n_ep = 8
    t_local = 16
    x = _tokens(2, t=n_ep * t_local)            # (128, D), tile = 16

    want = jnp.concatenate([
        moe.moe_ffn_reference(params, x[i * t_local:(i + 1) * t_local],
                              capacity=CAP)[0]
        for i in range(n_ep)])

    def body(params, x):
        out, aux = moe.moe_ffn_shard(params, x, capacity=CAP,
                                     ep_axis="ep")
        return out, aux

    specs = {k: (P("ep") if k.startswith("moe_w") or
                 k.startswith("moe_b") else P())
             for k in params}
    sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
               for k, v in params.items()}
    fn = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(specs, P("ep")),
        out_specs=(P("ep"), P())))
    got, aux = fn(sharded, jax.device_put(
        x, NamedSharding(mesh, P("ep"))))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert np.isfinite(float(aux))


def test_moe_trains_and_uses_multiple_experts(mesh):
    """A small ep-sharded regression task must reduce loss AND keep the
    router spread across experts (aux loss regularizer working)."""
    n_ep = 8
    params = moe.init_moe(jax.random.PRNGKey(1), D, FF, E)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(128, D), jnp.float32)
    y = jnp.asarray(np.sin(2 * np.asarray(x)), jnp.float32)

    specs = {k: (P("ep") if k.startswith("moe_w") or
                 k.startswith("moe_b") else P())
             for k in params}

    def body(params, x, y):
        out, aux = moe.moe_ffn_shard(params, x, capacity=32,
                                     ep_axis="ep")
        mse = jnp.mean((out - y) ** 2)
        return jax.lax.pmean(mse, "ep") + 0.01 * aux

    grad_fn = jax.jit(jax.shard_map(
        lambda p, x, y: jax.value_and_grad(
            lambda p: body(p, x, y))(p),
        mesh=mesh, in_specs=(specs, P("ep"), P("ep")),
        out_specs=(P(), specs)))

    opt = optax.adam(1e-2)
    sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
               for k, v in params.items()}
    st = opt.init(sharded)
    xd = jax.device_put(x, NamedSharding(mesh, P("ep")))
    yd = jax.device_put(y, NamedSharding(mesh, P("ep")))
    first = None
    for _ in range(60):
        loss, g = grad_fn(sharded, xd, yd)
        up, st = opt.update(g, st)
        sharded = optax.apply_updates(sharded, up)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.6, (first, float(loss))
    # router still uses several experts after training
    gates = np.asarray(jax.nn.softmax(
        x @ np.asarray(sharded["moe_router_W"]), axis=-1))
    used = (np.bincount(gates.argmax(-1), minlength=E) > 0).sum()
    assert used >= 3, f"router collapsed to {used} experts"
