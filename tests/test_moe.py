"""Mixture-of-experts layer: expert-parallel all_to_all routing vs the
single-device oracle, capacity semantics, and trainability."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lua_mapreduce_tpu.parallel import moe
from lua_mapreduce_tpu.parallel.mesh import make_mesh
from lua_mapreduce_tpu.utils.jax_compat import (shard_map, spec_axes,
                                                stamp_replicated)

D, FF, E, CAP = 16, 32, 8, 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=8, mp=1, devices=jax.devices("cpu")[:8],
                     axis_names=("ep", "unused"))


@pytest.fixture(scope="module")
def params():
    return moe.init_moe(jax.random.PRNGKey(0), D, FF, E)


def _tokens(seed, t=32):
    return jnp.asarray(np.random.RandomState(seed).randn(t, D),
                       jnp.float32)


def test_reference_routes_and_combines(params):
    x = _tokens(0)
    out, aux = moe.moe_ffn_reference(params, x, capacity=CAP)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    assert 0.5 < float(aux) < float(E)      # balanced-ish random router


def test_capacity_drops_overflow_tokens(params):
    """Force every token to one expert: only the first CAP tokens get
    output; the rest are dropped (zero contribution)."""
    p = dict(params)
    bias = jnp.zeros((D, E)).at[:, 3].set(100.0)
    p["moe_router_W"] = bias
    # positive tokens → positive feature sum → every token scores
    # expert 3 highest (a linear router has no bias term)
    x = jnp.abs(_tokens(1, t=16))
    out, _ = moe.moe_ffn_reference(p, x, capacity=CAP)
    norms = np.linalg.norm(np.asarray(out), axis=-1)
    assert (norms[:CAP] > 1e-6).all()
    np.testing.assert_allclose(norms[CAP:], 0.0, atol=1e-6)


def test_shard_matches_per_tile_reference(mesh, params):
    """ep-sharded MoE ≡ the oracle applied per device tile (same
    per-tile capacity semantics)."""
    n_ep = 8
    t_local = 16
    x = _tokens(2, t=n_ep * t_local)            # (128, D), tile = 16

    want = jnp.concatenate([
        moe.moe_ffn_reference(params, x[i * t_local:(i + 1) * t_local],
                              capacity=CAP)[0]
        for i in range(n_ep)])

    def body(params, x):
        out, aux = moe.moe_ffn_shard(params, x, capacity=CAP,
                                     ep_axis="ep")
        return out, aux

    specs = {k: (P("ep") if k.startswith("moe_w") or
                 k.startswith("moe_b") else P())
             for k in params}
    sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
               for k, v in params.items()}
    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P("ep")),
        out_specs=(P("ep"), P())))
    got, aux = fn(sharded, jax.device_put(
        x, NamedSharding(mesh, P("ep"))))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert np.isfinite(float(aux))


def test_moe_trains_and_uses_multiple_experts(mesh):
    """A small ep-sharded regression task must reduce loss AND keep the
    router spread across experts (aux loss regularizer working)."""
    n_ep = 8
    params = moe.init_moe(jax.random.PRNGKey(1), D, FF, E)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(128, D), jnp.float32)
    y = jnp.asarray(np.sin(2 * np.asarray(x)), jnp.float32)

    specs = {k: (P("ep") if k.startswith("moe_w") or
                 k.startswith("moe_b") else P())
             for k in params}

    def body(params, x, y):
        out, aux = moe.moe_ffn_shard(params, x, capacity=32,
                                     ep_axis="ep")
        mse = jnp.mean((out - y) ** 2)
        return jax.lax.pmean(mse, "ep") + 0.01 * aux

    def vag(p, x, y):
        l, g = jax.value_and_grad(lambda p: body(p, x, y))(p)
        # replicated-leaf grads (router etc.) ARE psum'd across ep by
        # the transpose machinery; the pmean stamp makes that
        # statically checkable (utils/jax_compat.py)
        return l, {k: stamp_replicated(
            v, tuple(a for a in ("ep",) if a not in spec_axes(specs[k])))
            for k, v in g.items()}

    grad_fn = jax.jit(shard_map(
        vag, mesh=mesh, in_specs=(specs, P("ep"), P("ep")),
        out_specs=(P(), specs)))

    opt = optax.adam(1e-2)
    sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
               for k, v in params.items()}
    st = opt.init(sharded)
    xd = jax.device_put(x, NamedSharding(mesh, P("ep")))
    yd = jax.device_put(y, NamedSharding(mesh, P("ep")))
    first = None
    for _ in range(60):
        loss, g = grad_fn(sharded, xd, yd)
        up, st = opt.update(g, st)
        sharded = optax.apply_updates(sharded, up)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.6, (first, float(loss))
    # router still uses several experts after training
    gates = np.asarray(jax.nn.softmax(
        x @ np.asarray(sharded["moe_router_W"]), axis=-1))
    used = (np.bincount(gates.argmax(-1), minlength=E) > 0).sum()
    assert used >= 3, f"router collapsed to {used} experts"


class TestMoEDecode:
    """KV-cached decode on switch-MoE configs (VERDICT r2 item 5):
    capacity-bounded routing at one position per step."""

    def _cfgs(self, n_experts=4, capacity=64):
        from lua_mapreduce_tpu.models.transformer import TransformerConfig
        moe = TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                n_layers=2, d_ff=24, max_seq=32,
                                moe_experts=n_experts,
                                moe_capacity=capacity)
        dense = TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                  n_layers=2, d_ff=24, max_seq=32)
        return moe, dense

    def test_decode_golden_vs_dense_on_identical_experts(self):
        """MoE decode ≡ dense decode when every expert IS the dense FFN.

        Construction: zero router → uniform gates (1/E each, argmax
        breaks the tie to expert 0); every expert's first layer equals
        the dense ff1 and its second layer is the dense ff2 scaled by E,
        so combine-weight 1/E times the expert output reproduces the
        dense FFN exactly (E a power of two → the scaling is exact in
        f32). Token-exact golden diff between the two decode paths."""
        from lua_mapreduce_tpu.models import transformer as tfm
        moe_cfg, dense_cfg = self._cfgs()
        e = moe_cfg.moe_experts
        dense_params = tfm.init_transformer(jax.random.PRNGKey(7),
                                            dense_cfg)
        # non-FFN params copied VERBATIM (same-seed init would not do:
        # the two configs consume different numbers of PRNG splits, so
        # their attention weights diverge); FFN params constructed
        moe_params = {k: v for k, v in dense_params.items()
                      if "_ff" not in k}
        for i in range(moe_cfg.n_layers):
            p = f"L{i}"
            moe_params[f"{p}_moe_router_W"] = jnp.zeros(
                (moe_cfg.d_model, e))
            moe_params[f"{p}_moe_w1"] = jnp.tile(
                dense_params[f"{p}_ff1_W"][None], (e, 1, 1))
            moe_params[f"{p}_moe_b1"] = jnp.tile(
                dense_params[f"{p}_ff1_b"][None], (e, 1))
            moe_params[f"{p}_moe_w2"] = jnp.tile(
                e * dense_params[f"{p}_ff2_W"][None], (e, 1, 1))
            moe_params[f"{p}_moe_b2"] = jnp.tile(
                e * dense_params[f"{p}_ff2_b"][None], (e, 1))

        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, 32, (3, 5)), jnp.int32)
        got = tfm.greedy_decode(moe_params, prompt, 6, cfg=moe_cfg)
        want = tfm.greedy_decode(dense_params, prompt, 6, cfg=dense_cfg)
        assert np.array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.heavy
    def test_decode_matches_full_forward_rerun(self):
        """Random-router MoE decode vs re-running the FULL MoE forward
        at every prefix: token-exact when no bucket overflows (capacity
        ≥ every per-group worst case, so drop decisions are empty in
        both the per-step and the whole-tile routing groups)."""
        from lua_mapreduce_tpu.models import transformer as tfm
        moe_cfg, _ = self._cfgs(capacity=3 * 32)   # ≥ B*L: no drops
        params = tfm.init_transformer(jax.random.PRNGKey(11), moe_cfg)
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(0, 32, (3, 4)), jnp.int32)
        n_new = 6
        got = tfm.greedy_decode(params, prompt, n_new, cfg=moe_cfg)
        toks = prompt
        for _ in range(n_new):
            logits = tfm.transformer_apply(params, toks, cfg=moe_cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        assert np.array_equal(np.asarray(got), np.asarray(toks))

    @pytest.mark.heavy
    def test_prefill_decode_matches_scan_when_no_overflow(self):
        """MoE + use_prefill: token-exact vs the scan decode when no
        routing bucket overflows (capacity >= every group's worst
        case); under overflow the two grouping schemes drop different
        tokens by design (documented caveat)."""
        from lua_mapreduce_tpu.models import transformer as tfm
        moe_cfg, _ = self._cfgs(capacity=3 * 32)       # >= B*P: no drops
        params = tfm.init_transformer(jax.random.PRNGKey(2), moe_cfg)
        prompt = jnp.asarray(
            np.random.RandomState(6).randint(0, 32, (3, 6)), jnp.int32)
        a = tfm.greedy_decode(params, prompt, 5, cfg=moe_cfg)
        b = tfm.greedy_decode(params, prompt, 5, cfg=moe_cfg,
                              use_prefill=True)
        assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_decode_sampling_moe(self):
        """Temperature sampling works on the MoE path and is
        deterministic per key."""
        from lua_mapreduce_tpu.models import transformer as tfm
        moe_cfg, _ = self._cfgs()
        params = tfm.init_transformer(jax.random.PRNGKey(1), moe_cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        k = jax.random.PRNGKey(4)
        a = tfm.greedy_decode(params, prompt, 5, cfg=moe_cfg,
                              temperature=0.8, key=k)
        b = tfm.greedy_decode(params, prompt, 5, cfg=moe_cfg,
                              temperature=0.8, key=k)
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.all(np.asarray(a) < moe_cfg.vocab)


class TestTopK:
    @pytest.mark.heavy
    def test_top2_matches_dense_composition_with_big_capacity(self,
                                                              params):
        """With capacity >= T nothing drops, so top-2 routing must equal
        the dense oracle: run every expert on every token, take each
        token's two highest-gated experts, renormalize their gates, and
        mix."""
        x = _tokens(3)
        t = x.shape[0]
        out, _ = moe.moe_ffn_reference(params, x, capacity=t, top_k=2)

        w = {k[len("moe_"):]: v for k, v in params.items()}
        gates = jax.nn.softmax(x @ w["router_W"], axis=-1)      # (T, E)
        h = jax.nn.gelu(jnp.einsum("td,edf->tef", x, w["w1"])
                        + w["b1"][None])
        ye = jnp.einsum("tef,efd->ted", h, w["w2"]) + w["b2"][None]
        top2 = jnp.argsort(gates, axis=-1)[:, -2:]              # (T, 2)
        g2 = jnp.take_along_axis(gates, top2, axis=-1)
        g2 = g2 / g2.sum(axis=-1, keepdims=True)
        want = jnp.einsum(
            "tk,tkd->td", g2,
            jnp.take_along_axis(ye, top2[:, :, None], axis=1))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_top2_capacity_accounts_across_both_choices(self, params):
        """Slots are shared between first and second choices: forcing
        every token's top-2 onto the same two experts fills each bucket
        once, not twice."""
        p = dict(params)
        bias = jnp.zeros((D, E)).at[:, 2].set(100.0).at[:, 5].set(99.0)
        p["moe_router_W"] = bias
        x = jnp.abs(_tokens(4, t=16))
        out, _ = moe.moe_ffn_reference(p, x, capacity=CAP, top_k=2)
        norms = np.linalg.norm(np.asarray(out), axis=-1)
        # experts 2 and 5 each keep their first CAP tokens (the same
        # first CAP tokens — routing is token-ordered), rest dropped
        assert (norms[:CAP] > 1e-6).all()
        np.testing.assert_allclose(norms[CAP:], 0.0, atol=1e-6)

    def test_top2_shard_matches_reference(self, mesh, params):
        """The golden-diff extends to top-k: per-tile reference routing
        equals the ep-sharded form."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = mesh.shape["ep"]
        x = _tokens(5, t=8 * n)
        tiles = x.reshape(n, -1, D)
        want = jnp.concatenate([
            moe.moe_ffn_reference(params, tiles[i], capacity=CAP,
                                  top_k=2)[0] for i in range(n)])

        def body(xt, pr):
            out, aux = moe.moe_ffn_shard(pr, xt, capacity=CAP,
                                         ep_axis="ep", top_k=2)
            return out

        shard_p = {k: (NamedSharding(mesh, P("ep"))
                       if k != "moe_router_W"
                       else NamedSharding(mesh, P()))
                   for k in params}
        pr = {k: jax.device_put(v, shard_p[k])
              for k, v in params.items()}
        got = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P("ep"), {k: (P("ep") if k != "moe_router_W"
                                    else P()) for k in params}),
            out_specs=P("ep")))(x, pr)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_top_k_validation(self):
        from lua_mapreduce_tpu.models.transformer import (
            TransformerConfig, init_transformer)

        cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                n_layers=1, d_ff=32, max_seq=16,
                                moe_experts=4, moe_capacity=8,
                                moe_top_k=5)
        with pytest.raises(ValueError, match="moe_top_k"):
            init_transformer(jax.random.PRNGKey(0), cfg)

    @pytest.mark.heavy
    def test_top2_transformer_trains(self):
        """A top-2 MoE transformer learns the stride task through the
        full sharded train step — moe_top_k threads end to end."""
        from lua_mapreduce_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(vocab=16, d_model=32, n_heads=2,
                                    n_layers=2, d_ff=64, max_seq=64,
                                    moe_experts=4, moe_capacity=128,
                                    moe_top_k=2)
        mesh2 = make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                          axis_names=("dp", "sp"))
        rng = np.random.RandomState(1)
        b, l = 8, 64
        start = rng.randint(0, cfg.vocab, (b, 1))
        seq = (start + np.arange(l + 1)) % cfg.vocab
        tokens = jnp.asarray(seq[:, :-1], jnp.int32)
        targets = jnp.asarray(seq[:, 1:], jnp.int32)
        opt = optax.adam(3e-3)
        from lua_mapreduce_tpu.models.transformer import shard_params_moe
        params = shard_params_moe(
            tfm.init_transformer(jax.random.PRNGKey(2), cfg), mesh2)
        step = tfm.make_train_step(cfg, mesh2, opt, attn="ring")
        st = opt.init(params)
        td = tfm.shard_batch(mesh2, tokens, targets)
        first = None
        for _ in range(60):
            params, st, loss = step(params, st, *td)
            if first is None:
                first = float(loss)
        assert float(loss) < first / 3, (first, float(loss))


class TestSortedRouting:
    """The sort+gather routing (the default impl) must be EXACTLY the
    one-hot einsum oracle's semantics — same top-k choices, same
    first-C-in-token-order capacity fill (round-major for k>1), same
    pre-drop renormalization, same aux — on outputs AND gradients
    (DESIGN §14: the einsum form's dispatch/combine contractions are
    8× the expert FFN's FLOPs; the sorted form removes them, so it
    must be a pure reformulation, not an approximation)."""

    @pytest.mark.parametrize("top_k", [1, 2])
    @pytest.mark.parametrize("cap", [CAP, 64])
    def test_outputs_match_einsum_oracle(self, params, top_k, cap):
        x = _tokens(7, t=48)
        want, aux_w = moe.moe_ffn_reference(params, x, capacity=cap,
                                            top_k=top_k, impl="einsum")
        got, aux_g = moe.moe_ffn_reference(params, x, capacity=cap,
                                           top_k=top_k, impl="sorted")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_g), float(aux_w), rtol=1e-5)

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_outputs_match_under_heavy_overflow(self, params, top_k):
        """Collapse the router onto one expert so most tokens drop —
        the fill order (round-major, then token order) must agree."""
        p = dict(params)
        p["moe_router_W"] = jnp.zeros((D, E)).at[:, 3].set(100.0)
        x = jnp.abs(_tokens(8, t=24))
        want, _ = moe.moe_ffn_reference(p, x, capacity=CAP,
                                        top_k=top_k, impl="einsum")
        got, _ = moe.moe_ffn_reference(p, x, capacity=CAP,
                                       top_k=top_k, impl="sorted")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_grads_match_einsum_oracle(self, params, top_k):
        x = _tokens(9, t=32)

        def loss(params, x, impl):
            out, aux = moe.moe_ffn_reference(params, x, capacity=CAP,
                                             top_k=top_k, impl=impl)
            return jnp.sum(out ** 2) + 0.01 * aux

        gw_p, gw_x = jax.grad(loss, argnums=(0, 1))(params, x, "einsum")
        gs_p, gs_x = jax.grad(loss, argnums=(0, 1))(params, x, "sorted")
        np.testing.assert_allclose(np.asarray(gs_x), np.asarray(gw_x),
                                   rtol=2e-4, atol=1e-5)
        for k in gw_p:
            np.testing.assert_allclose(
                np.asarray(gs_p[k]), np.asarray(gw_p[k]),
                rtol=2e-4, atol=1e-5, err_msg=k)

    def test_shard_sorted_matches_einsum_shard(self, mesh, params):
        """Both impls inside shard_map over the ep axis: identical
        outputs — the all_to_all operates on identical (E, C, d)
        buckets regardless of how they were built."""
        n_ep, t_local = 8, 16
        x = _tokens(10, t=n_ep * t_local)
        specs = {k: (P("ep") if k.startswith("moe_w") or
                     k.startswith("moe_b") else P())
                 for k in params}
        sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                   for k, v in params.items()}
        xs = jax.device_put(x, NamedSharding(mesh, P("ep")))

        def run(impl):
            def body(params, x):
                return moe.moe_ffn_shard(params, x, capacity=CAP,
                                         ep_axis="ep", impl=impl)
            fn = jax.jit(shard_map(
                body, mesh=mesh, in_specs=(specs, P("ep")),
                out_specs=(P("ep"), P())), static_argnums=())
            return fn(sharded, xs)

        want, _ = run("einsum")
        got, _ = run("sorted")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
