"""Persistent table tests (analog persistent_table.lua:256-264 utest:
two clients round-tripping through one document)."""

import threading

import pytest

from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.jobstore import MemJobStore
from lua_mapreduce_tpu.coord.persistent_table import (ConflictError,
                                                      PersistentTable)


def _stores(tmp_path):
    return [MemJobStore(), FileJobStore(str(tmp_path / "pt"))]


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file"])
def test_two_clients_roundtrip(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    a = PersistentTable("conf", store)
    a["model"] = "m.ckpt"
    a["epoch"] = 3
    a.update()

    b = PersistentTable("conf", store)
    assert b["model"] == "m.ckpt" and b["epoch"] == 3

    b["epoch"] = 4
    b.update()
    a.update()          # clean → refresh pulls b's commit
    assert a["epoch"] == 4


@pytest.mark.parametrize("idx", [0, 1], ids=["mem", "file"])
def test_optimistic_conflict_detected(tmp_path, idx):
    store = _stores(tmp_path)[idx]
    a = PersistentTable("c", store)
    b = PersistentTable("c", store)
    a["x"] = 1
    a.update()
    b["x"] = 2          # b still holds the pre-commit timestamp
    with pytest.raises(ConflictError):
        b.update()
    b.refresh()
    b.update()          # after refresh the commit goes through
    assert PersistentTable("c", store)["x"] == 2


def test_lock_mutual_exclusion(tmp_path):
    store = FileJobStore(str(tmp_path / "lk"))
    t1 = PersistentTable("locked", store)
    order = []

    def contender():
        t2 = PersistentTable("locked", store)
        t2.lock(poll=0.01)
        order.append("t2")
        t2.unlock()

    t1.lock()
    order.append("t1")
    th = threading.Thread(target=contender)
    th.start()
    th.join(timeout=0.2)
    assert th.is_alive()        # blocked on t1's lock
    t1.unlock()
    th.join(timeout=5)
    assert order == ["t1", "t2"]


def test_reserved_keys_and_read_only(tmp_path):
    store = MemJobStore()
    t = PersistentTable("r", store)
    with pytest.raises(KeyError):
        t["timestamp"] = 1
    with pytest.raises(KeyError):
        t["_hidden"] = 1
    t["ok"] = 1
    t.update()

    ro = PersistentTable("r", store, read_only=True)
    assert ro["ok"] == 1
    with pytest.raises(PermissionError):
        ro["ok"] = 2
    with pytest.raises(PermissionError):
        ro.drop()


def test_conflict_retry_preserves_other_writers_keys(tmp_path):
    """Regression: after ConflictError → refresh() → update(), keys this
    table never touched must keep the OTHER writer's committed values."""
    store = MemJobStore()
    t1 = PersistentTable("m", store)
    t1.set({"a": 1, "b": 1})
    t1.update()

    t2 = PersistentTable("m", store)
    t1["a"] = 5                 # t1 dirty on 'a' only
    t2["b"] = 2
    t2.update()                 # t2 commits b=2 first
    with pytest.raises(ConflictError):
        t1.update()
    t1.refresh()
    t1.update()
    final = PersistentTable("m", store)
    assert final["a"] == 5 and final["b"] == 2   # b=2 not reverted


def test_commit_under_lock_keeps_lock(tmp_path):
    """Regression: update() inside a lock() section must not release the
    advisory lock."""
    store = MemJobStore()
    a = PersistentTable("held", store)
    a.lock()
    a["x"] = 1
    a.update()          # must preserve the locked flag
    b = PersistentTable("held", store)
    with pytest.raises(TimeoutError):
        b.lock(poll=0.01, timeout=0.1)
    a.unlock()
    b.lock(poll=0.01, timeout=1.0)
    b.unlock()


def test_drop(tmp_path):
    store = MemJobStore()
    t = PersistentTable("d", store)
    t["k"] = "v"
    t.update()
    t.drop()
    fresh = PersistentTable("d", store)
    assert "k" not in fresh
