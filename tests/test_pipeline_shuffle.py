"""Pipelined-shuffle golden-diff harness.

The tentpole's non-negotiable contract: with ``pipeline=True`` the
engine overlaps eager pre-merge with the map phase, and the task output
must be BYTE-identical to the barrier executor on every storage backend
— same partitions, same files, same bytes — including the ``"loop"``
iteration protocol. The matrix mirrors test_wordcount_golden's configs
(combiner / no-combiner / general reducer) over all three backends, on a
corpus small enough to run often but wide enough (many mappers, low
``premerge_min_runs``) that pre-merge genuinely fires.
"""

import glob
import os
import re

import pytest

from examples.wordcount.naive import naive_wordcount
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# ~25 mapper-sized corpus: engine + store + coord sources
CORPUS = sorted(
    glob.glob(os.path.join(REPO, "lua_mapreduce_tpu", "engine", "*.py"))
    + glob.glob(os.path.join(REPO, "lua_mapreduce_tpu", "store", "*.py"))
    + glob.glob(os.path.join(REPO, "lua_mapreduce_tpu", "coord", "*.py")))

CONFIGS = {
    "combiner": dict(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.mapfn",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        combinerfn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
    ),
    "no_combiner": dict(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.mapfn",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn",
        finalfn="examples.wordcount.finalfn",
    ),
    "general_reducer": dict(
        taskfn="examples.wordcount.taskfn",
        mapfn="examples.wordcount.mapfn",
        partitionfn="examples.wordcount.partitionfn",
        reducefn="examples.wordcount.reducefn2",
        finalfn="examples.wordcount.finalfn",
    ),
}

_RESULT_RE = re.compile(r"^result\.P(\d+)$")


def _result_bytes(ex):
    """partition → full result-file content, read through the backend."""
    out = {}
    for name in ex.result_store.list("result.P*"):
        m = _RESULT_RE.match(name)
        if m:
            out[int(m.group(1))] = "".join(ex.result_store.lines(name))
    return out


def _run(config, storage, pipeline):
    spec = TaskSpec(init_args={"files": CORPUS}, storage=storage,
                    **CONFIGS[config])
    ex = LocalExecutor(spec, map_parallelism=4, pipeline=pipeline,
                       premerge_min_runs=2)
    stats = ex.run()
    import examples.wordcount.finalfn as fmod
    return dict(fmod.counts), _result_bytes(ex), stats


@pytest.mark.parametrize("config", list(CONFIGS))
@pytest.mark.parametrize("backend", ["mem", "shared", "object"])
def test_pipelined_byte_identical_to_barrier(tmp_path, config, backend):
    storages = {
        "mem": (f"mem:pipe-{config}-b", f"mem:pipe-{config}-p"),
        "shared": (f"shared:{tmp_path}/b", f"shared:{tmp_path}/p"),
        "object": (f"object:{tmp_path}/b", f"object:{tmp_path}/p"),
    }[backend]
    golden = naive_wordcount(CORPUS)

    got_b, bytes_b, _ = _run(config, storages[0], pipeline=False)
    got_p, bytes_p, stats_p = _run(config, storages[1], pipeline=True)

    assert got_b == golden
    assert got_p == golden
    assert set(bytes_b) == set(bytes_p)
    for part in bytes_b:
        assert bytes_b[part] == bytes_p[part], \
            f"partition {part} result differs between barrier and pipelined"

    it = stats_p.iterations[-1]
    assert it.premerge.count > 0, "pre-merge never fired"
    assert it.premerge.failed == 0
    assert 0.0 <= it.overlap_fraction <= 1.0
    # spills and consumed runs must not leak past the reduce
    leftovers = [n for n in ex_list(storages[1])
                 if ".SPILL-" in n or ".M" in n]
    assert leftovers == [], leftovers


def ex_list(storage):
    from lua_mapreduce_tpu.store.router import get_storage_from
    return get_storage_from(storage).list("result.P*")


def test_pipelined_loop_protocol():
    """The iterative protocol under pipelining: per-iteration results are
    correct, stale partitions don't leak across iterations, and the
    premerge namespace resets every loop."""
    state = {"it": 0, "seen": []}

    def taskfn(emit):
        words = (["alpha", "beta"] * 8) if state["it"] == 0 else ["alpha"] * 8
        for i, w in enumerate(words):
            emit(i, [w])

    def mapfn(key, words, emit):
        for w in words:
            emit(w, 1)

    def partitionfn(key):
        return 0 if key == "alpha" else 1

    def reducefn(key, values):
        return sum(values)

    def finalfn(pairs):
        state["seen"] = sorted((k, v[0]) for k, v in pairs)
        state["it"] += 1
        return "loop" if state["it"] < 3 else None

    spec = TaskSpec(taskfn=taskfn, mapfn=mapfn, partitionfn=partitionfn,
                    reducefn=reducefn, finalfn=finalfn,
                    storage="mem:pipe-loop")
    stats = LocalExecutor(spec, map_parallelism=4, pipeline=True,
                          premerge_min_runs=2).run()
    assert state["it"] == 3
    assert len(stats.iterations) == 3
    # iteration 1 had both keys; later iterations must not leak "beta"
    assert state["seen"] == [("alpha", 8)]
    assert sum(it.premerge.count for it in stats.iterations) > 0


def test_pipelined_server_inprocess():
    """Server + elastic worker threads with pipeline=True over the
    in-memory job store: pre_merge jobs are claimed under the worker's
    CAS protocol and the result equals the barrier server's, byte for
    byte."""
    import sys
    import threading
    import types

    from lua_mapreduce_tpu.coord.jobstore import MemJobStore
    from lua_mapreduce_tpu.engine.server import Server
    from lua_mapreduce_tpu.engine.worker import Worker
    from lua_mapreduce_tpu.store.router import get_storage_from

    mod = types.ModuleType("_pipe_srv_mod")

    def taskfn(emit):
        for i in range(12):
            emit(i, i)

    def mapfn(key, value, emit):
        for j in range(30):
            emit(f"k{(value * 31 + j) % 17:02d}", 1)

    def reducefn(key, values):
        return sum(values)

    mod.taskfn, mod.mapfn, mod.reducefn = taskfn, mapfn, reducefn
    mod.partitionfn = lambda key: int(key[1:]) % 3
    sys.modules["_pipe_srv_mod"] = mod
    try:
        def leg(pipeline, tag):
            store = MemJobStore()
            spec = TaskSpec(taskfn="_pipe_srv_mod", mapfn="_pipe_srv_mod",
                            partitionfn="_pipe_srv_mod",
                            reducefn="_pipe_srv_mod",
                            storage=f"mem:{tag}")
            server = Server(store, poll_interval=0.01, pipeline=pipeline,
                            premerge_min_runs=2).configure(spec)
            workers = [Worker(store).configure(max_iter=600, max_sleep=0.02)
                       for _ in range(3)]
            threads = [threading.Thread(target=w.execute, daemon=True)
                       for w in workers]
            for t in threads:
                t.start()
            stats = server.loop()
            for t in threads:
                t.join(timeout=30)
            st = get_storage_from(f"mem:{tag}")
            return {n: "".join(st.lines(n))
                    for n in st.list("result.P*")
                    if _RESULT_RE.match(n)}, stats

        bytes_b, _ = leg(False, "pipe-srv-b")
        bytes_p, stats_p = leg(True, "pipe-srv-p")
        assert bytes_b and bytes_b == bytes_p
        it = stats_p.iterations[-1]
        assert it.map.failed == 0 and it.reduce.failed == 0
        assert it.premerge.failed == 0
    finally:
        del sys.modules["_pipe_srv_mod"]


def test_discover_pipelined_overlapping_spills():
    """Zombie double-publish recovery (code-review r6): a NESTED
    overlapping spill pair resolves to the widest (same runs' data, a
    superset) with the narrower swept; a STAGGERED overlap — where each
    spill uniquely holds some positions and duplicates others — fails
    loudly instead of silently double-counting."""
    from lua_mapreduce_tpu.engine.job import map_key_str
    from lua_mapreduce_tpu.engine.premerge import (discover_pipelined,
                                                   spill_name)
    from lua_mapreduce_tpu.store.router import get_storage_from

    ns = "result"
    keys = [map_key_str(i) for i in range(10)]

    def put(store, name):
        b = store.builder()
        b.write("x 1\n")
        b.build(name)

    st = get_storage_from("mem:overlap-nested")
    put(st, spill_name(ns, 0, 0, 7))          # restarted server's spill
    put(st, spill_name(ns, 0, 0, 5))          # zombie's narrower spill
    put(st, f"{ns}.P0.M{keys[8]}")            # tail raw run
    parts = discover_pipelined(st, ns, keys)
    assert parts[0] == [spill_name(ns, 0, 0, 7), f"{ns}.P0.M{keys[8]}"]
    assert not st.exists(spill_name(ns, 0, 0, 5))   # swept

    st2 = get_storage_from("mem:overlap-staggered")
    put(st2, spill_name(ns, 0, 0, 3))
    put(st2, spill_name(ns, 0, 2, 5))
    with pytest.raises(RuntimeError, match="staggered"):
        discover_pipelined(st2, ns, keys)
