"""TPU-target lowering regression for every Pallas kernel.

Round 4's hardware window exposed that the flash kernels had NEVER
lowered on TPU: Mosaic rejects (1, block_q) row-state blocks whenever
B·H > 1, and CPU interpret mode — all the suite ran between hardware
windows — never enforces block legality. The fix is ops/attention.py's
lane-replicated row state; THIS file is the structural fix for the test
gap: ``jax.export`` runs the full TPU lowering pipeline (including
Mosaic's legality checks, verified to reproduce the exact round-3
failure) on a CPU-only host, so a kernel that cannot lower on the chip
now fails the suite on every box, between windows included.

Export stops at lowering — nothing executes, so these are fast and
numerics-free; interpret-mode parity tests elsewhere own correctness.
"""

import jax
import jax.numpy as jnp
import pytest

from lua_mapreduce_tpu import ops
from lua_mapreduce_tpu.ops.attention import _flash_pallas


def export_tpu(f, *shapes):
    """Lower ``f`` for the TPU target from the CPU host; raises on any
    Mosaic legality violation."""
    return jax.export.export(jax.jit(f), platforms=["tpu"])(*shapes)


def _q(b, l, h, d, dtype=jnp.bfloat16):
    return jax.ShapeDtypeStruct((b, l, h, d), dtype)


class TestFlashLowering:
    def test_forward_causal(self):
        q = _q(2, 1024, 8, 128)
        export_tpu(lambda q, k, v: _flash_pallas(q, k, v, True), q, q, q)

    def test_forward_full(self):
        q = _q(2, 512, 4, 128)
        export_tpu(lambda q, k, v: _flash_pallas(q, k, v, False), q, q, q)

    def test_forward_gqa(self):
        q = _q(2, 512, 8, 128)
        kv = _q(2, 512, 2, 128)
        export_tpu(lambda q, k, v: _flash_pallas(q, k, v, True),
                   q, kv, kv)

    def test_forward_head_dim_64(self):
        q = _q(2, 512, 4, 64)
        export_tpu(lambda q, k, v: _flash_pallas(q, k, v, True), q, q, q)

    def test_forward_ragged_seq_padding(self):
        # odd L exercises _pad_seq + _clamp_blocks geometry on-chip
        q = _q(1, 300, 2, 128)
        export_tpu(lambda q, k, v: _flash_pallas(q, k, v, True), q, q, q)

    def test_forward_windowed_offset(self):
        q = _q(2, 512, 4, 128)
        export_tpu(lambda q, k, v: _flash_pallas(
            q, k, v, True, window=128, q_offset=64), q, q, q)

    def test_forward_with_lse(self):
        q = _q(2, 512, 4, 128)
        export_tpu(lambda q, k, v: _flash_pallas(q, k, v, True,
                                                 with_lse=True), q, q, q)

    def test_grad_both_outputs(self):
        """The training path: fused backward kernels (dq and dkv),
        lse-cotangent fold included — the exact program ring training
        runs per shard."""
        q = _q(2, 512, 8, 128)
        kv = _q(2, 512, 2, 128)

        def loss(q_, k_, v_):
            o, lse = ops.flash_attention(q_, k_, v_, causal=True,
                                         return_lse=True,
                                         backend="pallas")
            return o.sum() + lse.sum()

        export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, kv, kv)

    def test_grad_windowed(self):
        q = _q(1, 512, 4, 128)

        def loss(q_, k_, v_):
            return ops.flash_attention(q_, k_, v_, causal=True,
                                       window=128,
                                       backend="pallas").sum()

        export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)


class TestOtherKernelsLowering:
    def test_matmul_default_blocks(self):
        a = jax.ShapeDtypeStruct((1024, 1024), jnp.bfloat16)
        export_tpu(lambda a, b: ops.matmul(a, b, backend="pallas"), a, a)

    def test_matmul_wide_blocks(self):
        # the 512²-tile auto schedule (DESIGN §8) must stay legal
        a = jax.ShapeDtypeStruct((2048, 2048), jnp.bfloat16)
        export_tpu(lambda a, b: ops.matmul(a, b, backend="pallas"), a, a)

    def test_conv2d(self):
        x = jax.ShapeDtypeStruct((8, 32, 32, 16), jnp.bfloat16)
        w = jax.ShapeDtypeStruct((3, 3, 16, 32), jnp.bfloat16)
        export_tpu(lambda x, w: ops.conv2d(x, w, backend="pallas"), x, w)

    def test_maxpool(self):
        x = jax.ShapeDtypeStruct((8, 32, 32, 32), jnp.bfloat16)
        export_tpu(lambda x: ops.maxpool2d(x, backend="pallas"), x)

    def test_avgpool(self):
        x = jax.ShapeDtypeStruct((8, 32, 32, 32), jnp.bfloat16)
        export_tpu(lambda x: ops.avgpool2d(x, backend="pallas"), x)

    def test_log_softmax(self):
        x = jax.ShapeDtypeStruct((256, 1024), jnp.bfloat16)
        export_tpu(lambda x: ops.log_softmax(x, backend="pallas"), x)


def test_export_actually_enforces_block_legality():
    """Guard the guard: a deliberately illegal (1, block) row-state
    block spec must be REJECTED by the export path — if a jax upgrade
    ever stops running Mosaic legality checks under export, this test
    fails and the whole file stops meaning anything."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(x_ref, r_ref):
        r_ref[...] = x_ref[0].sum(axis=-1).reshape(1, 128)

    def f(x):
        return pl.pallas_call(
            kern,
            grid=(4, 2),
            in_specs=[pl.BlockSpec((1, 128, 128), lambda b, i: (b, i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((1, 128), lambda b, i: (b, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((4, 256), jnp.float32),
        )(x)

    x = jax.ShapeDtypeStruct((4, 256, 128), jnp.float32)
    with pytest.raises(ValueError, match="divisible by 8 and 128"):
        export_tpu(f, x)


@pytest.mark.heavy
def test_resnet18_imagenet_grad_lowers_with_tpu_policy():
    """The whole ImageNet ResNet-18 training program — Pallas maxpool
    stem included, exactly what the TPU auto policy routes — lowers
    for the TPU target from this host. Pinned because the round-4
    hardware windows could never compile it THROUGH THE TUNNEL (the
    axon remote-compile helper subprocess crashes with HTTP 500 at any
    batch size, kernels.json note): this export is the evidence the
    failure is the tunnel environment's, not the framework's."""
    import jax.numpy as jnp

    import lua_mapreduce_tpu.ops as ops_pkg
    from lua_mapreduce_tpu.models import resnet

    orig = ops_pkg.default_backend
    ops_pkg.default_backend = (
        lambda op=None: ops_pkg._TPU_AUTO_POLICY.get(op, "pallas"))
    try:
        cfg = resnet.ResNetConfig.imagenet18()
        params = resnet.init_resnet(jax.random.PRNGKey(0), cfg,
                                    dtype=jnp.bfloat16)
        loss_fn = resnet.make_loss(cfg)

        def step(params, x, y):
            return jax.grad(lambda p: loss_fn(p, x, y))(params)

        x = jax.ShapeDtypeStruct((8, *cfg.input_shape), jnp.bfloat16)
        y = jax.ShapeDtypeStruct((8,), jnp.int32)
        p_abs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
        export_tpu(step, p_abs, x, y)
    finally:
        ops_pkg.default_backend = orig


class TestDecodeLowering:
    """ops/decode.py's flash-decode kernel — the scalar-prefetch grid
    (dynamic dead-chunk elision) must stay Mosaic-legal at the decode
    bench shapes, MHA (g=1 q rows) and GQA alike."""

    @pytest.mark.parametrize("shape", [(4, 16, 1, 64, 4096),
                                       (4, 4, 4, 128, 4096),
                                       (2, 2, 8, 64, 300)])
    def test_decode_kernel(self, shape):
        from lua_mapreduce_tpu.ops.decode import _decode_pallas

        b, hkv, g, d, s_len = shape
        q = jax.ShapeDtypeStruct((b, hkv, g, d), jnp.bfloat16)
        kv = jax.ShapeDtypeStruct((b, hkv, s_len, d), jnp.bfloat16)
        t = jax.ShapeDtypeStruct((), jnp.int32)
        export_tpu(lambda q_, k_, v_, t_: _decode_pallas(q_, k_, v_, t_),
                   q, kv, kv, t)

    def test_decode_kernel_q8(self):
        from lua_mapreduce_tpu.ops.decode import _decode_pallas

        q = jax.ShapeDtypeStruct((4, 16, 1, 64), jnp.bfloat16)
        kv = jax.ShapeDtypeStruct((4, 16, 4096, 64), jnp.int8)
        sc = jax.ShapeDtypeStruct((4, 16, 4096), jnp.float32)
        t = jax.ShapeDtypeStruct((), jnp.int32)
        export_tpu(lambda q_, k_, v_, ks_, vs_, t_: _decode_pallas(
            q_, k_, v_, t_, k_scale=ks_, v_scale=vs_),
            q, kv, kv, sc, sc, t)

    def test_decode_kernel_rolling(self):
        from lua_mapreduce_tpu.ops.decode import _decode_pallas

        q = jax.ShapeDtypeStruct((2, 4, 1, 64), jnp.bfloat16)
        kv = jax.ShapeDtypeStruct((2, 4, 512, 64), jnp.bfloat16)
        t = jax.ShapeDtypeStruct((), jnp.int32)
        export_tpu(lambda q_, k_, v_, t_: _decode_pallas(
            q_, k_, v_, t_, roll=True), q, kv, kv, t)


class TestQ8Lowering:
    def test_q8_matmul_decode_shapes(self):
        x = jax.ShapeDtypeStruct((8, 4096), jnp.bfloat16)
        q = jax.ShapeDtypeStruct((4096, 16384), jnp.int8)
        s = jax.ShapeDtypeStruct((16384,), jnp.float32)
        export_tpu(lambda x, q, s: ops.q8_matmul(x, q, s,
                                                 backend="pallas"),
                   x, q, s)

    def test_q8_matmul_ragged(self):
        x = jax.ShapeDtypeStruct((1, 300), jnp.float32)
        q = jax.ShapeDtypeStruct((300, 500), jnp.int8)
        s = jax.ShapeDtypeStruct((500,), jnp.float32)
        export_tpu(lambda x, q, s: ops.q8_matmul(x, q, s,
                                                 backend="pallas"),
                   x, q, s)


def test_every_tuner_candidate_lowers():
    """The block-size sweeps (benchmarks/flash_tune.py, matmul_tune.py)
    run on rare, short hardware windows — a Mosaic-illegal candidate
    would burn the window on compile errors. Export every candidate
    the tuners enumerate (shared module-level definitions, so the
    tuners and this guard cannot drift), the flash ones through the
    BACKWARD kernels too (the sweep times fwd+bwd)."""
    from benchmarks.flash_tune import CANDIDATES as FLASH_CANDS
    from benchmarks.matmul_tune import candidates as matmul_cands
    from lua_mapreduce_tpu.ops.attention import _flash_pallas
    from lua_mapreduce_tpu.ops.matmul import _matmul_pallas

    q = jax.ShapeDtypeStruct((4, 2048, 8, 128), jnp.bfloat16)
    for bq, bk in FLASH_CANDS:
        export_tpu(lambda q_, k_, v_, bq=bq, bk=bk: _flash_pallas(
            q_, k_, v_, True, block_q=bq, block_k=bk), q, q, q)

        def loss(q_, k_, v_, bq=bq, bk=bk):
            return ops.flash_attention(q_, k_, v_, causal=True,
                                       backend="pallas", block_q=bq,
                                       block_k=bk).sum()

        export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)

    a = jax.ShapeDtypeStruct((4096, 4096), jnp.bfloat16)
    for bm, bn, bkk in matmul_cands():
        export_tpu(lambda x, y, bm=bm, bn=bn, bkk=bkk: _matmul_pallas(
            x, y, block_m=bm, block_n=bn, block_k=bkk), a, a)


class TestSortedMoeLowering:
    """The sorted MoE routing (argsort + bincount + row gathers, the
    default impl since DESIGN §14) is pure XLA, but sort/scatter
    lowering on TPU is exactly the kind of thing a green CPU suite
    can't attest — export the fwd AND grad paths for the TPU pipeline
    the same way the Pallas kernels are."""

    def test_moe_sorted_fwd_and_grad(self):
        from lua_mapreduce_tpu.parallel import moe

        d, ff, e, cap, t = 64, 128, 8, 32, 128
        params = moe.init_moe(jax.random.PRNGKey(0), d, ff, e,
                              jnp.bfloat16)
        x = jax.ShapeDtypeStruct((t, d), jnp.bfloat16)

        def loss(params, x):
            out, aux = moe.moe_ffn_reference(params, x, capacity=cap,
                                             top_k=2, impl="sorted")
            return jnp.sum(out.astype(jnp.float32) ** 2) + aux

        export_tpu(loss, params, x)
        export_tpu(jax.grad(loss), params, x)
