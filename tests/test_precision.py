"""f32 master weights (train/precision.py): the crisp failure mode it
fixes — bf16 params freezing when updates round below their ulp — and
its composition with ZeRO-1 sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from lua_mapreduce_tpu.parallel import zero1 as z1
from lua_mapreduce_tpu.parallel.mesh import make_mesh
from lua_mapreduce_tpu.train.precision import with_f32_master


def test_small_updates_accumulate_instead_of_vanishing():
    """A constant update far below bf16's ulp at |p|=1 (~0.0078):
    naive bf16 SGD leaves the param FROZEN (p + u rounds back to p);
    the master version accumulates in f32 and the working copy steps
    once the accumulated change crosses the ulp."""
    p0 = jnp.ones((4,), jnp.bfloat16)
    u = 1e-4                      # << bf16 ulp at 1.0

    naive = optax.sgd(1.0)
    st = naive.init({"w": p0})
    p = {"w": p0}
    for _ in range(100):
        upd, st = naive.update({"w": jnp.full((4,), u, jnp.bfloat16)},
                               st, p)
        p = optax.apply_updates(p, upd)
    assert np.all(np.asarray(p["w"], np.float32) == 1.0), "expected frozen"

    master = with_f32_master(optax.sgd(1.0))
    st = master.init({"w": p0})
    p = {"w": p0}
    for _ in range(100):
        upd, st = master.update({"w": jnp.full((4,), u, jnp.float32)},
                                st, p)
        p = optax.apply_updates(p, upd)
    moved = np.asarray(p["w"], np.float32)
    assert np.all(moved < 1.0), moved        # 100 * 1e-4 = 0.01 > ulp
    # and the MASTER tracked the sum exactly in f32
    m = np.asarray(st[0]["w"])
    np.testing.assert_allclose(m, 1.0 - 0.01, rtol=1e-5)


def test_f32_params_pass_through_losslessly():
    """With f32 params the wrapper must match the bare optimizer."""
    rng = np.random.RandomState(0)
    p = {"w": jnp.asarray(rng.randn(8), jnp.float32)}
    g = {"w": jnp.asarray(rng.randn(8), jnp.float32)}
    bare = optax.adam(1e-2)
    wrapped = with_f32_master(optax.adam(1e-2))
    pb, sb = dict(p), bare.init(p)
    pw, sw = dict(p), wrapped.init(p)
    for _ in range(5):
        ub, sb = bare.update(g, sb, pb)
        pb = optax.apply_updates(pb, ub)
        uw, sw = wrapped.update(g, sw, pw)
        pw = optax.apply_updates(pw, uw)
    np.testing.assert_allclose(np.asarray(pw["w"]), np.asarray(pb["w"]),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.heavy
def test_composes_with_zero1_sharded_masters():
    """Under ZeRO-1 the f32 masters live in the per-rank chunks: the
    sharded-master training matches a replicated-master run, and the
    master leaves are genuinely dp-sharded (f32 master cost 4/n_dp
    bytes per param)."""
    from lua_mapreduce_tpu.models import transformer as tfm

    mesh = make_mesh(dp=4, mp=2, devices=jax.devices("cpu")[:8],
                     axis_names=("dp", "sp"))
    cfg = tfm.TransformerConfig.llama_style(
        vocab=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=1,
        d_ff=48, max_seq=64)
    params32 = tfm.init_transformer(jax.random.PRNGKey(0), cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params32)
    opt = with_f32_master(optax.adam(3e-3))
    rng = np.random.RandomState(1)
    seq = rng.randint(0, 64, (8, 17))
    td = tfm.shard_batch(mesh, jnp.asarray(seq[:, :-1], jnp.int32),
                         jnp.asarray(seq[:, 1:], jnp.int32))

    outs = {}
    for z in (False, True):
        p = jax.tree.map(jnp.copy, params)
        st = (z1.init_state(opt, p, mesh) if z else opt.init(p))
        step = tfm.make_train_step(cfg, mesh, opt, attn="ring", zero1=z)
        for _ in range(4):
            p, st, loss = step(p, st, *td)
        outs[z] = (p, st, float(loss))
    assert abs(outs[True][2] - outs[False][2]) < 1e-3
    for k in outs[False][0]:
        np.testing.assert_allclose(
            np.asarray(outs[True][0][k], np.float32),
            np.asarray(outs[False][0][k], np.float32),
            rtol=2e-2, atol=2e-2, err_msg=k)
    # master leaves in the zero1 state are f32, chunked, dp-sharded
    masters = jax.tree.leaves(outs[True][1][0])
    assert all(m.dtype == jnp.float32 for m in masters)
    assert all(m.sharding.spec == P("dp") for m in masters)


def test_update_requires_params():
    opt = with_f32_master(optax.sgd(0.1))
    st = opt.init({"w": jnp.zeros(2)})
    with pytest.raises(ValueError, match="requires params"):
        opt.update({"w": jnp.ones(2)}, st)


def test_bf16_checkpoint_roundtrip(tmp_path):
    """bf16 leaves survive save_pytree/load_pytree: numpy round-trips
    ml_dtypes as raw void arrays, and load re-views them through the
    template's dtype (code-review r3 — the bf16 training path's
    checkpoints were unreadable before)."""
    from lua_mapreduce_tpu.store.router import get_storage_from
    from lua_mapreduce_tpu.train import checkpoint as ckpt

    store = get_storage_from(f"shared:{tmp_path}")
    tree = {"w": jnp.asarray(np.random.RandomState(0).randn(4, 3),
                             jnp.bfloat16),
            "b": jnp.arange(5, dtype=jnp.float32)}
    ckpt.save_pytree(store, "mp.ckpt", tree)
    back = ckpt.load_pytree(store, "mp.ckpt", tree)
    assert np.dtype(back["w"].dtype) == np.dtype(jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(back["w"], np.float32), np.asarray(tree["w"],
                                                      np.float32))
    # shape mismatches fail loudly naming the leaf
    bad_like = {"w": tree["w"][:2], "b": tree["b"]}
    with pytest.raises(ValueError, match="leaf 1"):
        ckpt.load_pytree(store, "mp.ckpt", bad_like, check_shapes=True)
    # default (sharded dataset loaders need variable-shape templates):
    # shapes unchecked, dtype restoration still applies
    loose = ckpt.load_pytree(store, "mp.ckpt", bad_like)
    assert loose["w"].shape == (4, 3)


def test_checkpoint_dtype_manifest_guards_reinterpret(tmp_path):
    """v2 manifests record leaf dtype names, so void (ml_dtypes) leaves
    restore FAITHFULLY to their written dtype — a bfloat16 checkpoint
    loaded through a float16 template comes back as correct bfloat16
    values, never bit-reinterpreted garbage (advisor r3). Resume paths
    pin dtypes with check_dtypes=True and get a loud error instead."""
    import json as _json

    from lua_mapreduce_tpu.store.router import get_storage_from
    from lua_mapreduce_tpu.train import checkpoint as ckpt

    store = get_storage_from(f"shared:{tmp_path}")
    vals = np.linspace(-2.0, 2.0, 12).reshape(4, 3)
    tree = {"w": jnp.asarray(vals, jnp.bfloat16)}
    ckpt.save_pytree(store, "d.ckpt", tree)
    # faithful restore regardless of the template's (wrong) dtype
    back = ckpt.load_pytree(store, "d.ckpt",
                            {"w": jnp.ones((4, 3), jnp.float16)})
    assert np.dtype(back["w"].dtype) == np.dtype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(back["w"], np.float32),
                                  np.asarray(tree["w"], np.float32))
    # resume-style loads pin dtypes loudly, in BOTH directions
    with pytest.raises(ValueError, match="written as bfloat16"):
        ckpt.load_pytree(store, "d.ckpt",
                         {"w": jnp.ones((4, 3), jnp.float16)},
                         check_dtypes=True)
    ckpt.save_pytree(store, "f16.ckpt", {"w": jnp.ones((4, 3),
                                                       jnp.float16)})
    with pytest.raises(ValueError, match="written as float16"):
        ckpt.load_pytree(store, "f16.ckpt",
                         {"w": jnp.ones((4, 3), jnp.bfloat16)},
                         check_dtypes=True)
    # legacy v1 files (no dtype record) keep the itemsize-view
    # fallback: strip "dtypes" from the manifest and reload
    lines = list(store.lines("d.ckpt"))
    hdr = _json.loads(lines[0])
    del hdr["dtypes"]
    hdr["v"] = 1
    b = store.builder()
    b.write(_json.dumps(hdr) + "\n")
    for ln in lines[1:]:
        b.write(ln if ln.endswith("\n") else ln + "\n")
    b.build("legacy.ckpt")
    back = ckpt.load_pytree(store, "legacy.ckpt", tree)
    assert np.dtype(back["w"].dtype) == np.dtype(jnp.bfloat16)
    # structured dtypes are ALSO kind 'V' but round-trip through np.load
    # exactly — the faithful-restore view must not touch them
    rec = np.zeros(3, dtype=[("a", "<i4"), ("b", "<f8")])
    rec["a"] = [1, 2, 3]
    ckpt.save_pytree(store, "s.ckpt", {"x": rec})
    sback = ckpt.load_pytree(store, "s.ckpt", {"x": rec}, check_dtypes=True)
    assert sback["x"].dtype == rec.dtype
    np.testing.assert_array_equal(sback["x"]["a"], rec["a"])
