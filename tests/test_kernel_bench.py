"""Smoke tests for the benchmark harness functions that are cheap on
CPU: the bench code itself must stay runnable between hardware windows
(the kernels.json drift of round 2 came from the script only ever being
exercised on the wedge-prone chip)."""

import numpy as np
import pytest


def test_bench_conv_train_lenet_smoke():
    """bench_conv_train produces finite, sane numbers on CPU at toy
    scale (same code path the TPU run takes)."""
    from benchmarks.kernel_bench import bench_conv_train

    out = bench_conv_train("lenet5_cifar", batch=4, steps=1)
    assert out["ms_per_step"] > 0
    assert out["images_per_sec"] > 0
    assert np.isfinite(out["mfu"]) and out["mfu"] >= 0
    assert "lenet5_cifar" in out["config"]


@pytest.mark.heavy
def test_bench_decode_smoke():
    """bench_decode at toy scale on CPU: sane numbers, prefill path
    actually faster-or-equal is NOT asserted (CPU timings are noise) —
    only that both paths run and the dict is well-formed."""
    from benchmarks.kernel_bench import bench_decode

    out = bench_decode(d_model=32, n_heads=4, n_layers=1, d_ff=64,
                       vocab=64, max_seq=64, prompt_len=48, n_new=8,
                       batch=2)
    assert out["prefill_total_s"] > 0 and out["scan_total_s"] > 0
    assert out["decode_tokens_per_sec"] > 0
    assert out["end_to_end_tokens_per_sec"] > 0


def test_bench_transformer_step_moe_smoke():
    """The MoE train-step bench entry at toy scale: router/capacity
    machinery + shard_params_moe must survive the exact call the TPU
    window makes (a new case must never burn a window on a crash)."""
    from benchmarks.kernel_bench import bench_transformer_step

    out = bench_transformer_step(d_model=32, n_heads=4, n_layers=1,
                                 d_ff=64, vocab=64, seq=64, batch=4,
                                 steps=2, moe_experts=2)
    assert out["tokens_per_sec"] > 0
    assert "switch-moe2x" in out["config"]


@pytest.mark.heavy
def test_bench_transformer_step_long_seq_smoke():
    """The seq-doubling entry's path (modern recipe at seq > d_ff)."""
    from benchmarks.kernel_bench import bench_transformer_step

    out = bench_transformer_step(d_model=32, n_heads=4, n_layers=1,
                                 d_ff=64, vocab=64, seq=128, batch=2,
                                 steps=2, modern=True)
    assert out["tokens_per_sec"] > 0
    assert "seq128" in out["config"]


@pytest.mark.heavy
def test_bench_decode_quantized_smoke():
    """The int8 serving copy drives the same bench (q8 path resolves
    to the XLA dequant composition off-TPU)."""
    from benchmarks.kernel_bench import bench_decode

    out = bench_decode(d_model=32, n_heads=4, n_layers=1, d_ff=64,
                       vocab=64, max_seq=64, prompt_len=48, n_new=8,
                       batch=2, quantized=True)
    assert out["decode_tokens_per_sec"] > 0


def test_bench_conv_train_unknown_model_rejected():
    from benchmarks.kernel_bench import bench_conv_train

    with pytest.raises(ValueError, match="unknown conv bench model"):
        bench_conv_train("alexnet", batch=8)


def test_bench_pair_speedup_from_unrounded_seconds(monkeypatch):
    """ADVICE r2: an op faster than the ms-rounding granularity must
    still emit speedup_pallas_vs_xla (computed from unrounded seconds),
    and FLOP-less ops get the HBM-roofline suspect_elided check."""
    import benchmarks.kernel_bench as kb

    # fake measurement: both ops "run" in 20 ns — rounds to 0.0 ms at
    # 4 decimals, which used to drop the speedup key silently
    monkeypatch.setattr(kb, "_call_overhead", lambda: 0.001)
    monkeypatch.setattr(kb, "_measure_op",
                        lambda *a, **k: (2e-8, 8))

    import jax.numpy as jnp
    x = jnp.zeros((4, 4), jnp.float32)

    def make():
        return (lambda x: x, lambda x: x, (x,), None)

    # force a known HBM bandwidth so the roofline check is exercised
    monkeypatch.setenv("LMR_PEAK_HBM_BYTES", "1e9")
    out = kb._bench_pair(make)
    assert out["speedup_pallas_vs_xla"] == 1.0
    # 64 bytes in 20 ns = 3.2 GB/s > 1.1 * 1 GB/s → flagged on both
    assert out["pallas_suspect_elided"] and out["xla_suspect_elided"]


def test_attn_memory_measures_the_l2_term():
    """The compiler-reported temp bytes for the XLA attention grad must
    contain the analytic O(L²) score term — the measured basis of the
    flash auto-policy (ops/__init__.py, DESIGN.md §9). Small shape so
    the compile stays cheap on CPU."""
    from benchmarks.attn_memory import flash_analytic, xla_measured

    b, h, l, d = 1, 2, 512, 64
    meas = xla_measured(b, h, l, d)
    ana = flash_analytic(b, h, l, d)
    # fwd and grad both materialize at least one (L, L) f32 buffer
    assert meas["fwd"]["temp_bytes"] >= ana["xla_score_term_bytes"]
    assert meas["grad"]["temp_bytes"] >= 2 * ana["xla_score_term_bytes"]
    # flash residents are O(L): far below the score term at this shape
    assert ana["hbm_grad_bytes"] < ana["xla_score_term_bytes"]


def test_attn_memory_utest():
    import benchmarks.attn_memory as am

    am.utest()


@pytest.mark.heavy
def test_moe_profile_smoke():
    """benchmarks/moe_profile.py's component breakdown at toy scale on
    CPU: every timed component and both cost analyses must produce a
    number, not an error row (a crash here would burn sprint phase B's
    slice of a hardware window)."""
    from benchmarks.moe_profile import profile

    res = profile(T=64, E=4, D=16, FF=32, cap=32, target_s=0.03)
    for name in ("dense_ffn_fwd", "dense_ffn_fwdbwd", "moe_einsum_fwd",
                 "moe_einsum_fwdbwd", "moe_sorted_fwd",
                 "moe_sorted_fwdbwd", "sorted_route_and_gather_fwd",
                 "expert_ffn_only_fwd"):
        assert "ms" in res[name], (name, res[name])
        assert res[name]["ms"] >= 0
    for impl in ("einsum", "sorted"):
        assert "flops" in res[f"cost_analysis_{impl}_fwdbwd"], (
            res[f"cost_analysis_{impl}_fwdbwd"])


@pytest.mark.heavy
def test_lenet_roofline_smoke():
    """benchmarks/lenet_roofline.py at toy batch on CPU: every stage
    row must carry a time, not an error (sprint phase G)."""
    from benchmarks.lenet_roofline import profile

    res = profile(batch=8, target_s=0.03)
    for name in ("fwd_loss", "fwdbwd", "conv1_5x5_3to6", "tanh_28x28x6",
                 "pool1_pallas", "pool1_xla", "conv2_5x5_6to16",
                 "pool2_pallas", "fc_stack_400_120_84_10",
                 "control_conv_5x5_128to128_b128"):
        assert "ms" in res[name], (name, res[name])


@pytest.mark.heavy
def test_lm_convergence_quick_smoke():
    """benchmarks/lm_convergence.py --quick end to end on CPU (sprint
    phase H, the longest phase): corpus build, the word tokenizer, the
    train_lm flags, and the artifact assembly must all survive — a
    crash here would burn the biggest slice of a hardware window."""
    import json
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "benchmarks/lm_convergence.py", "--quick"],
        capture_output=True, text=True, timeout=540,
        cwd=__file__.rsplit("/tests/", 1)[0])
    assert r.returncode == 0, r.stderr[-800:]
    out = json.loads(r.stdout.strip().rsplit("\n", 1)[-1])
    assert out["losses"], out
    assert out["sample"] is not None
    assert out["config"]["tok"] == "word:8192"
