"""Storage backend tests (analog fs.lua:213-251 utest: round-trip
build/list/read/remove through every backend)."""

import pytest

from lua_mapreduce_tpu.store.memfs import MemStore
from lua_mapreduce_tpu.store.objectfs import ObjectStore
from lua_mapreduce_tpu.store.router import get_storage_from, parse_storage
from lua_mapreduce_tpu.store.sharedfs import SharedStore


def _backends(tmp_path):
    return [
        MemStore(),
        SharedStore(str(tmp_path / "shared")),
        ObjectStore(str(tmp_path / "object")),
    ]


@pytest.mark.parametrize("idx", [0, 1, 2])
def test_roundtrip_build_list_read_remove(tmp_path, idx):
    store = _backends(tmp_path)[idx]
    b = store.builder()
    b.write("line one\n")
    b.write("line two\n")
    b.build("ns.P3.M7")

    b2 = store.builder()
    b2.write("other\n")
    b2.build("ns.P4.M7")

    assert store.exists("ns.P3.M7")
    assert store.list("ns.P*.M*") == ["ns.P3.M7", "ns.P4.M7"]
    assert store.list("ns.P3.*") == ["ns.P3.M7"]
    assert list(store.lines("ns.P3.M7")) == ["line one\n", "line two\n"]

    store.remove("ns.P3.M7")
    assert not store.exists("ns.P3.M7")
    store.remove("ns.P3.M7")  # idempotent
    assert store.list("ns.P*.M*") == ["ns.P4.M7"]


@pytest.mark.parametrize("idx", [0, 1, 2])
def test_build_overwrites_atomically(tmp_path, idx):
    store = _backends(tmp_path)[idx]
    for content in ("v1\n", "v2\n"):
        b = store.builder()
        b.write(content)
        b.build("f")
    assert list(store.lines("f")) == ["v2\n"]


def test_names_with_slashes(tmp_path):
    for store in _backends(tmp_path):
        b = store.builder()
        b.write("x\n")
        b.build("dir/sub.P0.M1")
        assert store.list("dir/sub.P*.M*") == ["dir/sub.P0.M1"]
        assert list(store.lines("dir/sub.P0.M1")) == ["x\n"]


def test_router_spec_parsing(tmp_path):
    assert parse_storage("mem") == ("mem", None)
    assert parse_storage("gridfs") == ("mem", None)
    assert parse_storage(f"shared:{tmp_path}") == ("shared", str(tmp_path))
    assert parse_storage(f"sshfs:{tmp_path}") == ("object", str(tmp_path))
    with pytest.raises(ValueError):
        parse_storage("bogus:x")
    with pytest.raises(ValueError):
        parse_storage("shared")  # needs a path

    s1 = get_storage_from("mem:tagA")
    s2 = get_storage_from("mem:tagA")
    assert s1 is s2  # process-wide shared instance per tag


@pytest.fixture
def fake_gcs(monkeypatch):
    """Inject the packaged google.cloud.storage lookalike
    (lua_mapreduce_tpu.store.fake_gcs — public for user tests, with
    configurable injected 503/timeout schedules) so ObjectStore's gs://
    branch runs without network (VERDICT r1 item 6: the real-GCS path
    had zero tests)."""
    import sys

    from lua_mapreduce_tpu.store.fake_gcs import (FakeGcsClient,
                                                  fake_module_tree)

    FakeGcsClient.reset()
    for name, mod in fake_module_tree():
        monkeypatch.setitem(sys.modules, name, mod)
    return FakeGcsClient


def test_gcs_branch_roundtrip(fake_gcs):
    from lua_mapreduce_tpu.store.objectfs import ObjectStore

    store = ObjectStore("gs://testbkt/spill")
    b = store.builder()
    b.write("line1\n")
    b.write("line2\n")
    b.build("runs.P0.M1")
    assert store.exists("runs.P0.M1")
    assert list(store.lines("runs.P0.M1")) == ["line1\n", "line2\n"]
    # objects live under the prefix in the (fake) bucket
    bucket = fake_gcs._buckets["testbkt"]
    assert "spill/runs.P0.M1" in bucket._objects
    assert store.list("runs.P0.*") == ["runs.P0.M1"]
    store.remove("runs.P0.M1")
    assert not store.exists("runs.P0.M1")
    assert store.list("*") == []


def test_gcs_sibling_prefixes_do_not_leak(fake_gcs):
    """list() under prefix "inter" must not surface blobs of sibling
    prefix "inter2" with mangled names (code-review r2 finding: the raw
    string prefix matched both)."""
    from lua_mapreduce_tpu.store.objectfs import ObjectStore

    s1 = ObjectStore("gs://bkt/inter")
    s2 = ObjectStore("gs://bkt/inter2")
    b = s1.builder(); b.write("one\n"); b.build("a.P0.M0")
    b = s2.builder(); b.write("two\n"); b.build("a.P0.M1")
    assert s1.list("*") == ["a.P0.M0"]
    assert s2.list("*") == ["a.P0.M1"]
    assert list(s1.lines("a.P0.M0")) == ["one\n"]


def test_gcs_branch_end_to_end_wordcount(fake_gcs):
    """Whole engine run with intermediate spill through the mocked
    gs:// bucket — fails if the object path silently degrades to local
    filesystem assumptions (rename, append, local_path)."""
    import sys
    import types

    mod = types.ModuleType("gcs_wc")
    corpus = {"d1": "a b a c", "d2": "b a"}
    mod.taskfn = lambda emit: [emit(k, v) for k, v in corpus.items()]
    def mapfn(key, value, emit):
        for w in value.split():
            emit(w, 1)
    mod.mapfn = mapfn
    mod.partitionfn = lambda key: sum(key.encode()) % 3
    mod.reducefn = lambda key, values: sum(values)
    sys.modules["gcs_wc"] = mod

    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.local import LocalExecutor
    spec = TaskSpec(taskfn="gcs_wc", mapfn="gcs_wc", partitionfn="gcs_wc",
                    reducefn="gcs_wc", storage="object:gs://wcbkt/inter")
    ex = LocalExecutor(spec)
    ex.run()
    out = {k: v[0] for k, v in ex.results()}
    assert out == {"a": 3, "b": 2, "c": 1}
    # the shuffle really flowed through the bucket
    assert "wcbkt" in fake_gcs._buckets


def test_gcs_ranged_reads_and_segments(fake_gcs):
    """The raw-bytes surface over the gs:// branch: read_range is a
    ranged GET, size comes from blob metadata, and a v2 framed segment
    round-trips through the bucket (DESIGN §17)."""
    from lua_mapreduce_tpu.core.segment import open_segment, record_stream
    from lua_mapreduce_tpu.core.segment import writer_for
    from lua_mapreduce_tpu.store.objectfs import ObjectStore

    store = ObjectStore("gs://segbkt/spill")
    b = store.builder()
    payload = bytes(range(256))
    b.write_bytes(payload)
    b.build("blob")
    assert store.size("blob") == 256
    assert store.read_range("blob", 10, 5) == payload[10:15]
    assert store.read_range("blob", 250, 100) == payload[250:]
    assert store.read_range("blob", 300, 10) == b""   # past EOF: short read

    recs = [(f"k{i:03d}", [i, "x" * (i % 7)]) for i in range(300)]
    w = writer_for(store, "v2")
    for k, v in recs:
        w.add(k, v)
    w.build("runs.P0.M1")
    assert open_segment(store, "runs.P0.M1") is not None
    assert list(record_stream(store, "runs.P0.M1")) == recs


def test_gcs_injected_503_classified_and_retried(fake_gcs):
    """The harness's configurable 503 schedule (DESIGN §19): ObjectStore
    classifies the injected ServiceUnavailable transient, and the retry
    layer absorbs a bounded burst — the read succeeds with no caller-
    visible failure."""
    import random

    from lua_mapreduce_tpu.faults import RetryingStore, RetryPolicy
    from lua_mapreduce_tpu.store.fake_gcs import (FakeGcsClient,
                                                  ServiceUnavailable)
    from lua_mapreduce_tpu.store.objectfs import ObjectStore

    FakeGcsClient.reset(faults={"download": [2, 3]})
    raw = ObjectStore("gs://fltbkt/x")
    with raw.builder() as b:      # upload (download calls 0 so far)
        b.write("payload\n")
        b.build("obj")
    assert raw.classify(ServiceUnavailable("x")) is True

    store = RetryingStore(raw, RetryPolicy(retries=3, base_ms=1,
                                           sleep=lambda s: None,
                                           rng=random.Random(0)))
    assert raw._get("obj") == b"payload\n"           # download #1 clean
    assert store.read_range("obj", 0, 7) == b"payload"   # #2,#3 injected
    assert FakeGcsClient.faults.fired == {"download": 2}


def test_gcs_injected_timeout_exhausts_to_transient_error(fake_gcs):
    """A burst longer than the retry budget surfaces as a classified
    TransientStoreError chaining the timeout — the worker's release-
    not-broken discrimination keys off exactly this."""
    import random

    from lua_mapreduce_tpu.faults import (RetryingStore, RetryPolicy,
                                          TransientStoreError)
    from lua_mapreduce_tpu.store.fake_gcs import FakeGcsClient, FakeGcsTimeout
    from lua_mapreduce_tpu.store.objectfs import ObjectStore

    FakeGcsClient.reset(faults={"download": 10}, fault_kind="timeout")
    raw = ObjectStore("gs://tobkt/x")
    with raw.builder() as b:
        b.write("v\n")
        b.build("obj")
    store = RetryingStore(raw, RetryPolicy(retries=2, base_ms=1,
                                           sleep=lambda s: None,
                                           rng=random.Random(0)))
    with pytest.raises(TransientStoreError) as ei:
        store.read_range("obj", 0, 2)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, FakeGcsTimeout)


def test_gcs_missing_dependency_error_message(monkeypatch):
    """Without google-cloud-storage importable, gs:// must fail with the
    actionable message, not an AttributeError later."""
    import builtins
    import sys

    for m in ("google", "google.cloud", "google.cloud.storage"):
        monkeypatch.delitem(sys.modules, m, raising=False)
    real_import = builtins.__import__

    def no_gcs(name, *a, **k):
        if name.startswith("google"):
            raise ImportError(name)
        return real_import(name, *a, **k)
    monkeypatch.setattr(builtins, "__import__", no_gcs)
    from lua_mapreduce_tpu.store.objectfs import ObjectStore
    with pytest.raises(RuntimeError, match="google-cloud-storage"):
        ObjectStore("gs://nope/x")
