"""Storage backend tests (analog fs.lua:213-251 utest: round-trip
build/list/read/remove through every backend)."""

import pytest

from lua_mapreduce_tpu.store.memfs import MemStore
from lua_mapreduce_tpu.store.objectfs import ObjectStore
from lua_mapreduce_tpu.store.router import get_storage_from, parse_storage
from lua_mapreduce_tpu.store.sharedfs import SharedStore


def _backends(tmp_path):
    return [
        MemStore(),
        SharedStore(str(tmp_path / "shared")),
        ObjectStore(str(tmp_path / "object")),
    ]


@pytest.mark.parametrize("idx", [0, 1, 2])
def test_roundtrip_build_list_read_remove(tmp_path, idx):
    store = _backends(tmp_path)[idx]
    b = store.builder()
    b.write("line one\n")
    b.write("line two\n")
    b.build("ns.P3.M7")

    b2 = store.builder()
    b2.write("other\n")
    b2.build("ns.P4.M7")

    assert store.exists("ns.P3.M7")
    assert store.list("ns.P*.M*") == ["ns.P3.M7", "ns.P4.M7"]
    assert store.list("ns.P3.*") == ["ns.P3.M7"]
    assert list(store.lines("ns.P3.M7")) == ["line one\n", "line two\n"]

    store.remove("ns.P3.M7")
    assert not store.exists("ns.P3.M7")
    store.remove("ns.P3.M7")  # idempotent
    assert store.list("ns.P*.M*") == ["ns.P4.M7"]


@pytest.mark.parametrize("idx", [0, 1, 2])
def test_build_overwrites_atomically(tmp_path, idx):
    store = _backends(tmp_path)[idx]
    for content in ("v1\n", "v2\n"):
        b = store.builder()
        b.write(content)
        b.build("f")
    assert list(store.lines("f")) == ["v2\n"]


def test_names_with_slashes(tmp_path):
    for store in _backends(tmp_path):
        b = store.builder()
        b.write("x\n")
        b.build("dir/sub.P0.M1")
        assert store.list("dir/sub.P*.M*") == ["dir/sub.P0.M1"]
        assert list(store.lines("dir/sub.P0.M1")) == ["x\n"]


def test_router_spec_parsing(tmp_path):
    assert parse_storage("mem") == ("mem", None)
    assert parse_storage("gridfs") == ("mem", None)
    assert parse_storage(f"shared:{tmp_path}") == ("shared", str(tmp_path))
    assert parse_storage(f"sshfs:{tmp_path}") == ("object", str(tmp_path))
    with pytest.raises(ValueError):
        parse_storage("bogus:x")
    with pytest.raises(ValueError):
        parse_storage("shared")  # needs a path

    s1 = get_storage_from("mem:tagA")
    s2 = get_storage_from("mem:tagA")
    assert s1 is s2  # process-wide shared instance per tag
