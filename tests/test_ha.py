"""HA coordinator suite (docs/DESIGN.md §31): epoch-fenced leader
lease, hot-standby takeover, zombie fencing, and the loop-state
checkpoint that closes the last resume hole.

Tiers:
  * ``smoke``-named tests are the test.sh gate (`-k smoke`): lease
    election/fencing semantics on a virtual clock, the FencedJobStore
    rejection contract + errors-stream evidence, one clean HA server
    lifecycle, standby observation, and one in-process takeover.
  * plain tests cover the trace-survival regression (a takeover is a
    RESUME — the dead leader's ``_trace.*`` half of the timeline must
    survive) and the fake-GCS loop-checkpoint takeover.
  * ``@heavy`` tests are the chaos tier (``--full``/LMR_FULL):
    SIGKILLed single servers passively resumed at four phases,
    SIGKILLed leaders hot-taken-over at four phases, a SIGSTOPped
    zombie fenced on revival, and a SIGKILL landed exactly inside the
    checkpoint-save→doc-flip window on FileJobStore.

Every chaos leg compares against a fault-free golden (the corpus
Counter for wordcount_big, :func:`examples.loopsum.expected` for the
order-sensitive threaded-state loop) and asserts ZERO repetition
charges — workers are leader-agnostic, so a coordinator death must
never cost a job re-execution.
"""

import os
import signal
import subprocess
import sys
import threading
import time
from collections import Counter

import pytest

from lua_mapreduce_tpu import (FileJobStore, MemJobStore, Server, TaskSpec,
                               Worker)
from lua_mapreduce_tpu.core.constants import TaskStatus
from lua_mapreduce_tpu.engine.local import iter_results
from lua_mapreduce_tpu.faults.errors import StaleLeaderError
from lua_mapreduce_tpu.faults.retry import COUNTERS
from lua_mapreduce_tpu.faults.wrappers import unwrap
from lua_mapreduce_tpu.sched.lease import (STATE_NS, FencedJobStore,
                                           LeaderLease)
from lua_mapreduce_tpu.store.router import get_storage_from

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WC = "examples.wordcount_big.bigtask"
LS = "examples.loopsum"
N_SPLITS = 6


# -- process / spec helpers (the churn-suite choreography idiom) ------------

def _env():
    ambient = os.environ.get("PYTHONPATH", "")
    path = REPO + os.pathsep + ambient if ambient else REPO
    return dict(os.environ, PYTHONPATH=path)


def _worker_code(coord, configure="max_iter=2000, max_sleep=0.05"):
    return (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from lua_mapreduce_tpu import FileJobStore, Worker\n"
        f"w = Worker(FileJobStore({coord!r})).configure({configure})\n"
        "w.execute()\n")


def _wc_spec_line(corpus_dir, storage):
    return (f"spec = TaskSpec(taskfn={WC!r}, mapfn={WC!r}, "
            f"partitionfn={WC!r}, reducefn={WC!r}, "
            f"init_args={{'corpus_dir': {corpus_dir!r}, "
            f"'n_splits': {N_SPLITS}, 'build': False}}, "
            f"storage={storage!r})\n")


def _ls_spec_line(n_iters, storage):
    return (f"spec = TaskSpec(taskfn={LS!r}, mapfn={LS!r}, "
            f"partitionfn={LS!r}, reducefn={LS!r}, combinerfn={LS!r}, "
            f"finalfn={LS!r}, init_args={{'n_iters': {n_iters}}}, "
            f"storage={storage!r})\n")


def _server_code(coord, spec_line, patch="", server_args=""):
    """A ``python -c`` coordinator: optional Server method patches
    (stall markers for deterministic kill windows) + configure + loop."""
    return (
        "import sys, os, signal, time, threading\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from lua_mapreduce_tpu import FileJobStore, Server, TaskSpec, "
        "Worker\n"
        "from lua_mapreduce_tpu.engine.server import Server as _S\n"
        + patch + spec_line +
        f"store = FileJobStore({coord!r})\n"
        f"server = Server(store, poll_interval=0.05{server_args})"
        ".configure(spec)\n"
        "server.loop()\n"
        "from lua_mapreduce_tpu.faults.retry import COUNTERS\n"
        "print('FENCED', COUNTERS.snapshot().get('fenced_writes', 0), "
        "flush=True)\n")


def _stall_wait_patch(phase):
    """Stall (forever) on entering the named barrier phase, once. The
    renewal daemon keeps the lease alive through the stall, so the hot
    standby stays standing by until the SIGKILL actually lands."""
    return (
        "_orig_wait = _S._wait_phase\n"
        "def _stall(self, ns, total, phase, progress):\n"
        f"    if phase == {phase!r} and not getattr(self, '_st', False):\n"
        "        self._st = True\n"
        "        print('STALLED', flush=True)\n"
        "        time.sleep(3600)\n"
        "    return _orig_wait(self, ns, total, phase, progress)\n"
        "_S._wait_phase = _stall\n")


_STALL_PREMERGE_PATCH = (
    "def _stall(self, store, n_map, progress):\n"
    "    print('STALLED', flush=True)\n"
    "    time.sleep(3600)\n"
    "_S._pipelined_map_phase = _stall\n")

_STALL_SAVE_PATCH = (
    "_orig_save = _S._save_loop_state\n"
    "def _stall(self, iteration):\n"
    "    if iteration == 3:\n"
    "        print('STALLED', flush=True)\n"
    "        time.sleep(3600)\n"
    "    return _orig_save(self, iteration)\n"
    "_S._save_loop_state = _stall\n")

# the flip-window kill: checkpoint WRITTEN, doc flip NOT — the exact
# crash the keep-{N-1,N} checkpoint sweep exists for
_KILL_IN_FLIP_WINDOW_PATCH = (
    "_orig_save = _S._save_loop_state\n"
    "def _boom(self, iteration):\n"
    "    _orig_save(self, iteration)\n"
    "    if iteration == 6:\n"
    "        print('SAVED6', flush=True)\n"
    "        os.kill(os.getpid(), signal.SIGKILL)\n"
    "_S._save_loop_state = _boom\n")

# mark the zombie window, then keep polling: the SIGSTOP lands inside
# the sleep, the post-SIGCONT continuation walks straight into the
# fenced housekeeping ops
_ZOMBIE_WINDOW_PATCH = (
    "_orig_wait = _S._wait_phase\n"
    "def _zwait(self, ns, total, phase, progress):\n"
    "    if phase == 'map' and not getattr(self, '_zm', False):\n"
    "        self._zm = True\n"
    "        print('ZWINDOW', flush=True)\n"
    "        time.sleep(3.0)\n"
    "    return _orig_wait(self, ns, total, phase, progress)\n"
    "_S._wait_phase = _zwait\n")


def _build_corpus(tmp_path):
    from examples.wordcount_big import corpus
    corpus_dir = str(tmp_path / "corpus")
    corpus.build(corpus_dir, n_splits=N_SPLITS)
    golden = Counter()
    for i in range(N_SPLITS):
        with open(corpus.split_path(corpus_dir, i)) as f:
            golden.update(f.read().split())
    return corpus_dir, dict(golden)


def _wc_spec(corpus_dir, storage):
    return TaskSpec(taskfn=WC, mapfn=WC, partitionfn=WC, reducefn=WC,
                    init_args={"corpus_dir": corpus_dir,
                               "n_splits": N_SPLITS, "build": False},
                    storage=storage)


def _ls_spec(n_iters, storage):
    return TaskSpec(taskfn=LS, mapfn=LS, partitionfn=LS, reducefn=LS,
                    combinerfn=LS, finalfn=LS,
                    init_args={"n_iters": n_iters}, storage=storage)


def _results(storage):
    return {k: vs[0]
            for k, vs in iter_results(get_storage_from(storage), "result")}


def _worker_thread(store, **cfg):
    cfg.setdefault("max_iter", 5000)
    cfg.setdefault("max_sleep", 0.05)
    w = Worker(store).configure(**cfg)
    t = threading.Thread(target=w.execute, daemon=True)
    t.start()
    return t


def _server_thread(store, result, key="stats", spec=None, **kw):
    kw.setdefault("poll_interval", 0.05)

    def run():
        server = Server(store, **kw)
        if spec is not None:
            server.configure(spec)
        result[key + "_server"] = server
        try:
            result[key] = server.loop()
        except BaseException as exc:
            result[key + "_error"] = exc
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _assert_no_repetitions(store):
    for ns in ("map_jobs", "red_jobs"):
        reps = [d["repetitions"] for d in store.jobs(ns)]
        assert all(r == 0 for r in reps), (ns, reps)


def _kill_all(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:
            pass


# -- smoke tier (the test.sh `-k smoke` gate) -------------------------------

def test_smoke_lease_election_epoch_fencing_virtual_clock():
    """The lease ladder on an injectable clock: acquire → refuse live →
    renew → expiry takeover (epoch bump, took_over) → the fenced loser
    can neither renew nor validate → clean release hands over WITHOUT
    a takeover verdict, still bumping the epoch."""
    store = MemJobStore()
    now = [100.0]
    a = LeaderLease(store, holder="A", ttl_s=10.0, clock=lambda: now[0])
    b = LeaderLease(store, holder="B", ttl_s=10.0, clock=lambda: now[0])

    assert a.try_acquire() and a.epoch == 1 and not a.took_over
    assert not b.try_acquire(), "live lease must refuse a second leader"
    now[0] += 5.0
    assert a.renew() and a.validate()

    now[0] += 10.1                      # strictly past A's deadline
    assert b.try_acquire() and b.epoch == 2
    assert b.took_over, "expiry acquire must carry the takeover verdict"
    assert not a.renew(), "the ousted leader's renew must CAS-fail"
    assert not a.validate(), "a fenced lease must never validate"

    c = LeaderLease(store, holder="C", ttl_s=10.0, clock=lambda: now[0])
    b.release()
    doc = store.pt_get("leader")
    assert doc["holder"] == "" and b.epoch == 0
    assert c.try_acquire() and c.epoch == 3
    assert not c.took_over, "a released lease is a handover, not a takeover"


def test_smoke_fenced_store_rejects_and_lands_on_errors_stream():
    """Satellite: a FencedJobStore mutation under a stale epoch raises
    the PERMANENT StaleLeaderError carrying the fencing evidence, bumps
    fenced_writes, and lands the rejection on the job store's errors
    stream with top-level epoch/holder diagnosis keys."""
    store = MemJobStore()
    now = [0.0]
    a = LeaderLease(store, holder="A", ttl_s=5.0, clock=lambda: now[0])
    assert a.try_acquire()
    fenced = FencedJobStore(store, a)
    fenced.put_task({"_id": "unique", "status": "WAIT"})   # live: passes
    assert store.get_task() is not None

    now[0] += 6.0
    b = LeaderLease(store, holder="B", ttl_s=5.0, clock=lambda: now[0])
    assert b.try_acquire() and b.epoch == 2

    before = COUNTERS.snapshot()
    with pytest.raises(StaleLeaderError) as ei:
        fenced.update_task({"poison": True})
    err = ei.value
    assert err.transient is False, "fenced writes must never be retried"
    assert err.op == "update_task"
    assert err.epoch == 1 and err.current_epoch == 2 and err.holder == "B"
    delta = COUNTERS.delta(before, COUNTERS.snapshot())
    assert delta.get("fenced_writes", 0) >= 1
    assert store.get_task().get("poison") is None, \
        "the rejected mutation must not have landed"

    errs = store.drain_errors()
    assert any(e.get("classification") == "fenced-write"
               and e.get("op") == "update_task"
               and e.get("epoch") == 1 and e.get("current_epoch") == 2
               and e.get("current_holder") == "B" for e in errs), errs

    # reads stay unfenced: a zombie may diagnose, never mutate
    assert fenced.get_task() is not None


def test_smoke_ha_server_clean_lifecycle(tmp_path):
    """Server(ha=True) with no contention: elect at epoch 1, run the
    loop task fenced end-to-end, release on completion (holder cleared,
    epoch retained in the doc for the next election's bump)."""
    import examples.loopsum as loopsum
    store = MemJobStore()
    storage = f"shared:{tmp_path}/spill"
    spec = _ls_spec(3, storage)
    server = Server(store, poll_interval=0.01, ha=True,
                    lease_ttl_s=5.0).configure(spec)
    wt = _worker_thread(store, max_sleep=0.01)
    stats = server.loop()
    wt.join(timeout=30)
    assert not wt.is_alive()

    assert [it.iteration for it in stats.iterations] == [1, 2, 3]
    acc, result = loopsum.expected(3)
    assert loopsum.ACC == acc
    assert _results(storage) == result
    doc = store.pt_get("leader")
    assert doc["holder"] == "" and doc["epoch"] == 1


def test_smoke_hot_standby_returns_after_leader_finishes(tmp_path):
    """A standby that never gets to lead: it wakes on the leader topic,
    watches the task go active then FINISHED under the leader, and
    returns its own empty stats — results live in result storage."""
    import examples.loopsum as loopsum
    store = MemJobStore()
    storage = f"shared:{tmp_path}/spill"
    spec = _ls_spec(2, storage)
    res = {}
    lead = _server_thread(store, res, key="lead", spec=spec,
                          poll_interval=0.01, ha=True, lease_ttl_s=5.0)
    # no workers yet: the map barrier holds the task ACTIVE while the
    # standby proves it is hot (standby_wakeups observed)
    deadline = time.time() + 10
    while time.time() < deadline:
        task = store.get_task()
        if task is not None and task.get("status") != "FINISHED":
            break
        time.sleep(0.005)
    else:
        pytest.fail("leader never opened the task")

    before = COUNTERS.snapshot()
    standby = _server_thread(store, res, key="sb", poll_interval=0.01,
                             ha=True, lease_ttl_s=5.0)
    deadline = time.time() + 10
    while time.time() < deadline:
        if COUNTERS.delta(before, COUNTERS.snapshot()).get(
                "standby_wakeups", 0) >= 1:
            break
        time.sleep(0.005)
    else:
        pytest.fail("standby never woke on the leader topic")

    wt = _worker_thread(store, max_sleep=0.01)
    lead.join(timeout=60)
    standby.join(timeout=60)
    wt.join(timeout=30)
    assert not lead.is_alive() and not standby.is_alive()
    assert "sb_error" not in res, res.get("sb_error")
    assert res["sb"].iterations == [], "a pure standby led no iterations"
    assert res["sb_server"].finished_value is None
    _, result = loopsum.expected(2)
    assert _results(storage) == result


def test_smoke_takeover_mid_loop_restores_threaded_state(tmp_path,
                                                         monkeypatch):
    """In-process takeover: the leader crashes in finalfn mid-loop
    (lease left to EXPIRE — the SIGKILL-equivalent path), module state
    is reset to init values (simulating the standby being a different
    process), and the takeover must restore the checkpointed threaded
    state — the order-sensitive fold only matches expected() if
    restore_state really fed iteration N exactly what N-1 produced."""
    import examples.loopsum as loopsum
    store = MemJobStore()
    storage = f"shared:{tmp_path}/spill"
    spec = _ls_spec(4, storage)
    monkeypatch.setattr(loopsum, "CRASH_AT", 2)

    res = {}
    wt = _worker_thread(store, max_sleep=0.01)
    lead = _server_thread(store, res, key="lead", spec=spec,
                          poll_interval=0.01, ha=True, lease_ttl_s=0.5)
    lead.join(timeout=30)
    assert not lead.is_alive(), "leader should have crashed at CRASH_AT"
    assert isinstance(res.get("lead_error"), RuntimeError)

    # the standby is "another process": it starts from init-time state
    loopsum.ACC = 0
    loopsum.ITER = 0
    before = COUNTERS.snapshot()
    standby = Server(store, poll_interval=0.01, ha=True, lease_ttl_s=0.5)
    stats = standby.loop()
    wt.join(timeout=30)

    assert COUNTERS.delta(before, COUNTERS.snapshot()).get(
        "leader_takeovers", 0) >= 1
    assert stats.iterations[0].iteration == 3, \
        "takeover must resume at the doc's iteration, not restart"
    acc, result = loopsum.expected(4)
    assert loopsum.ACC == acc, "threaded state diverged across takeover"
    assert _results(storage) == result
    _assert_no_repetitions(store)
    doc = store.pt_get("leader")
    assert doc["epoch"] == 2 and doc["holder"] == ""


# -- satellite: a takeover is a resume — the trace timeline survives --------

def test_takeover_preserves_both_tenures_trace_spans(tmp_path, monkeypatch):
    """Both leaders' spans land in ONE collection: the epoch-1
    leader.acquire, the epoch-2 leader.takeover, and phase spans from
    iterations on both sides of the crash."""
    import examples.loopsum as loopsum
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    from lua_mapreduce_tpu.trace.span import Tracer, install_tracer

    store = MemJobStore()
    storage = f"shared:{tmp_path}/spill"
    spec = _ls_spec(4, storage)
    monkeypatch.setattr(loopsum, "CRASH_AT", 2)
    install_tracer(Tracer())
    try:
        res = {}
        wt = _worker_thread(store, max_sleep=0.01)
        lead = _server_thread(store, res, key="lead", spec=spec,
                              poll_interval=0.01, ha=True, lease_ttl_s=0.5)
        lead.join(timeout=30)
        assert not lead.is_alive() and "lead_error" in res
        standby = Server(store, poll_interval=0.01, ha=True,
                         lease_ttl_s=0.5)
        standby.loop()
        wt.join(timeout=30)
    finally:
        install_tracer(None)

    col = TraceCollection.from_store(unwrap(get_storage_from(storage)))
    by_name = {}
    for s in col.spans:
        by_name.setdefault(s["name"], []).append(s)
    assert "leader.acquire" in by_name, sorted(by_name)
    assert "leader.takeover" in by_name, \
        "the first tenure's spans were purged by the takeover"
    assert any(s.get("attrs", {}).get("epoch") == 1
               for s in by_name["leader.acquire"])
    assert any(s.get("attrs", {}).get("epoch") == 2
               for s in by_name["leader.takeover"])
    its = {s.get("it") for s in col.spans}
    assert 1 in its and 4 in its, \
        f"one continuous timeline must span both tenures, got {sorted(its)}"


def test_fresh_start_after_takeover_keeps_trace_purges_state(tmp_path):
    """The purge gating edge: a takeover landing where the doc is
    already FINISHED drops state and starts the task FRESH — but it is
    still a takeover, so `_trace.*` survives while the stale
    `_state.*` checkpoints (a CORRECTNESS purge) do not. A plain
    non-takeover fresh start purges both."""
    storage = f"shared:{tmp_path}/spill"
    spec = _ls_spec(1, storage)
    raw = unwrap(get_storage_from(storage))

    def seed():
        with raw.builder() as b:
            b.write_bytes(b"previous tenure's timeline")
            b.build("_trace.zombie.0")
        with raw.builder() as b:
            b.write_bytes(b"stale checkpoint")
            b.build(f"{STATE_NS}.3")

    # takeover leg: dead leader's expired lease + FINISHED doc
    store = MemJobStore()
    seed()
    store.put_task({"_id": "unique", "status": TaskStatus.FINISHED.value,
                    "iteration": 1, "spec": spec.describe()})
    dead = LeaderLease(store, holder="dead", ttl_s=0.2)
    assert dead.try_acquire()
    time.sleep(0.45)                       # let the lease expire
    server = Server(store, poll_interval=0.01, ha=True,
                    lease_ttl_s=5.0).configure(spec)
    wt = _worker_thread(store, max_sleep=0.01)
    server.loop()
    wt.join(timeout=30)
    assert server._took_over is False      # reset after the clean return
    assert raw.exists("_trace.zombie.0"), \
        "takeover fresh-start must NOT purge the dead leader's spans"
    assert not raw.exists(f"{STATE_NS}.3"), \
        "stale loop-state must be purged even on the takeover edge"

    # control: an ordinary fresh start purges the foreign timeline
    store2 = MemJobStore()
    seed()
    server2 = Server(store2, poll_interval=0.01).configure(
        _ls_spec(1, storage))
    wt2 = _worker_thread(store2, max_sleep=0.01)
    server2.loop()
    wt2.join(timeout=30)
    assert not raw.exists("_trace.zombie.0")
    assert not raw.exists(f"{STATE_NS}.3")


# -- fake-GCS loop-checkpoint takeover (in-process, two backends) -----------

def test_loop_checkpoint_takeover_on_fake_gcs(tmp_path, monkeypatch):
    """The mid-loop takeover with the checkpoint riding OBJECT storage
    (fake google.cloud.storage): the CRC frame round-trips through the
    blob API and the takeover resumes the threaded fold exactly."""
    import examples.loopsum as loopsum
    from lua_mapreduce_tpu.store.fake_gcs import (install_fake_gcs,
                                                  uninstall_fake_gcs)
    prev = install_fake_gcs()
    try:
        store = MemJobStore()
        storage = "object:gs://ha-bkt/spill"
        spec = _ls_spec(8, storage)
        monkeypatch.setattr(loopsum, "CRASH_AT", 4)

        res = {}
        wt = _worker_thread(store, max_sleep=0.01)
        lead = _server_thread(store, res, key="lead", spec=spec,
                              poll_interval=0.01, ha=True, lease_ttl_s=0.5)
        lead.join(timeout=60)
        assert not lead.is_alive() and "lead_error" in res

        loopsum.ACC = 0                   # "fresh process" standby
        loopsum.ITER = 0
        before = COUNTERS.snapshot()
        standby = Server(store, poll_interval=0.01, ha=True,
                         lease_ttl_s=0.5)
        stats = standby.loop()
        wt.join(timeout=30)

        assert COUNTERS.delta(before, COUNTERS.snapshot()).get(
            "leader_takeovers", 0) >= 1
        assert stats.iterations[0].iteration == 5
        acc, result = loopsum.expected(8)
        assert loopsum.ACC == acc
        assert _results(storage) == result
        _assert_no_repetitions(store)
    finally:
        uninstall_fake_gcs(prev)


# -- heavy tier: OS-level chaos ---------------------------------------------

_PASSIVE_LEGS = {
    "mid-map": ("wc", _stall_wait_patch("map"), ""),
    "mid-premerge": ("wc", _STALL_PREMERGE_PATCH,
                     ", pipeline=True, premerge_min_runs=2"),
    "reduce-barrier": ("wc", _stall_wait_patch("reduce"), ""),
    "between-iterations": ("ls", _STALL_SAVE_PATCH, ""),
}


@pytest.mark.heavy
@pytest.mark.parametrize("leg", sorted(_PASSIVE_LEGS), ids=sorted(_PASSIVE_LEGS))
def test_sigkill_server_passive_restart_resumes(tmp_path, leg):
    """Satellite: the single-server restart matrix. A (non-HA) server
    is SIGKILLed at a deterministic phase marker; a NEW server pointed
    at the same job store resumes from the task doc — no spec
    reconfiguration, workers never restarted — and the result equals
    the fault-free golden with zero repetition charges."""
    kind, patch, server_args = _PASSIVE_LEGS[leg]
    import examples.loopsum as loopsum
    coord = str(tmp_path / "coord")
    storage = f"object:{tmp_path}/obj"
    store = FileJobStore(coord)
    if kind == "wc":
        corpus_dir, golden = _build_corpus(tmp_path)
        spec_line = _wc_spec_line(corpus_dir, storage)
    else:
        spec_line = _ls_spec_line(6, storage)
        golden = None

    env = _env()
    procs = []
    try:
        for _ in range(3):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _worker_code(coord)], env=env,
                stdout=subprocess.DEVNULL))
        victim = subprocess.Popen(
            [sys.executable, "-c",
             _server_code(coord, spec_line, patch, server_args)],
            env=env, stdout=subprocess.PIPE, text=True)
        procs.append(victim)
        assert victim.stdout.readline().strip() == "STALLED", \
            "server never reached the stall marker"
        victim.kill()
        victim.wait(timeout=10)

        kw = {"poll_interval": 0.05}
        if "pipeline" in server_args:
            kw.update(pipeline=True, premerge_min_runs=2)
        resumed = Server(store, **kw)       # spec comes from the task doc
        stats = resumed.loop()
    finally:
        _kill_all(procs)

    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
    _assert_no_repetitions(store)
    if kind == "wc":
        assert stats.iterations[0].iteration == 1
        assert _results(storage) == golden
    else:
        # stall sat before _save_loop_state(3): the doc still reads
        # iteration 2, and _state.2 (published at the previous flip)
        # feeds the re-run of finalfn over iteration 2's stored results
        assert stats.iterations[0].iteration == 2
        acc, result = loopsum.expected(6)
        assert loopsum.ACC == acc
        assert _results(storage) == result


_HA_LEGS = {
    "mid-map": ("wc", _stall_wait_patch("map"), ""),
    "mid-premerge": ("wc", _STALL_PREMERGE_PATCH,
                     ", pipeline=True, premerge_min_runs=2"),
    "reduce-barrier": ("wc", _stall_wait_patch("reduce"), ""),
    "between-iterations": ("ls", _STALL_SAVE_PATCH, ""),
}


@pytest.mark.heavy
@pytest.mark.parametrize("leg", sorted(_HA_LEGS), ids=sorted(_HA_LEGS))
def test_sigkill_leader_hot_standby_takes_over(tmp_path, leg):
    """The tentpole acceptance: SIGKILL the LEADER at a phase marker
    while a hot standby stands by in this process. The standby must
    take over mid-phase via the resume matrix and finish to the
    fault-free golden with ZERO repetition charges — workers are
    leader-agnostic and their in-flight claims survive."""
    kind, patch, server_args = _HA_LEGS[leg]
    import examples.loopsum as loopsum
    coord = str(tmp_path / "coord")
    storage = f"object:{tmp_path}/obj"
    store = FileJobStore(coord)
    if kind == "wc":
        corpus_dir, golden = _build_corpus(tmp_path)
        spec_line = _wc_spec_line(corpus_dir, storage)
    else:
        spec_line = _ls_spec_line(8, storage)
        golden = None

    env = _env()
    procs = []
    res = {}
    try:
        for _ in range(3):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _worker_code(coord)], env=env,
                stdout=subprocess.DEVNULL))
        leader = subprocess.Popen(
            [sys.executable, "-c",
             _server_code(coord, spec_line, patch,
                          ", ha=True, lease_ttl_s=1.5" + server_args)],
            env=env, stdout=subprocess.PIPE, text=True)
        procs.append(leader)
        assert leader.stdout.readline().strip() == "STALLED"

        before = COUNTERS.snapshot()
        kw = {"ha": True, "lease_ttl_s": 1.5}
        if "pipeline" in server_args:
            kw.update(pipeline=True, premerge_min_runs=2)
        standby = _server_thread(store, res, key="sb", **kw)
        # prove hotness: the standby is probing before the leader dies
        deadline = time.time() + 10
        while time.time() < deadline:
            if COUNTERS.delta(before, COUNTERS.snapshot()).get(
                    "standby_wakeups", 0) >= 1:
                break
            time.sleep(0.01)
        else:
            pytest.fail("standby never entered the standby loop")

        leader.kill()
        leader.wait(timeout=10)
        standby.join(timeout=120)
        assert not standby.is_alive(), "standby never finished the task"
        assert "sb_error" not in res, res.get("sb_error")
    finally:
        _kill_all(procs)

    stats = res["sb"]
    assert COUNTERS.delta(before, COUNTERS.snapshot()).get(
        "leader_takeovers", 0) >= 1
    it = stats.iterations[-1]
    assert it.map.failed == 0 and it.reduce.failed == 0
    _assert_no_repetitions(store)
    doc = store.pt_get("leader")
    assert doc["epoch"] == 2 and doc["holder"] == ""
    if kind == "wc":
        assert _results(storage) == golden
    else:
        acc, result = loopsum.expected(8)
        assert loopsum.ACC == acc, \
            "threaded state diverged across the takeover"
        assert _results(storage) == result


@pytest.mark.heavy
def test_sigstop_zombie_leader_is_fenced_on_revival(tmp_path):
    """The zombie leg: SIGSTOP the leader past its TTL (GC-pause /
    partition stand-in), let the hot standby take over and finish,
    then SIGCONT. The revived zombie's next server-side mutation must
    be fenced (fenced_writes > 0, exit through the abdication path
    with code 0), the rejection must land on the errors stream with
    the epoch evidence, and the output must equal the golden."""
    coord = str(tmp_path / "coord")
    storage = f"object:{tmp_path}/obj"
    store = FileJobStore(coord)
    corpus_dir, golden = _build_corpus(tmp_path)
    spec_line = _wc_spec_line(corpus_dir, storage)

    env = _env()
    procs = []
    res = {}
    try:
        for _ in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _worker_code(coord)], env=env,
                stdout=subprocess.DEVNULL))
        zombie = subprocess.Popen(
            [sys.executable, "-c",
             _server_code(coord, spec_line, _ZOMBIE_WINDOW_PATCH,
                          ", ha=True, lease_ttl_s=1.0")],
            env=env, stdout=subprocess.PIPE, text=True)
        procs.append(zombie)

        assert zombie.stdout.readline().strip() == "ZWINDOW"
        # the zombie is inside its marker window with the renewal
        # daemon still beating: start the standby now (it can only
        # stand by — the lease is live) and prove it is hot before
        # freezing the leader
        before = COUNTERS.snapshot()
        standby = _server_thread(store, res, key="sb", ha=True,
                                 lease_ttl_s=1.0)
        deadline = time.time() + 10
        while time.time() < deadline:
            if COUNTERS.delta(before, COUNTERS.snapshot()).get(
                    "standby_wakeups", 0) >= 1:
                break
            time.sleep(0.01)
        else:
            pytest.fail("standby never entered the standby loop")
        os.kill(zombie.pid, signal.SIGSTOP)    # freeze renewals too

        standby.join(timeout=120)
        assert not standby.is_alive() and "sb_error" not in res, \
            res.get("sb_error")
        assert COUNTERS.delta(before, COUNTERS.snapshot()).get(
            "leader_takeovers", 0) >= 1

        os.kill(zombie.pid, signal.SIGCONT)
        out, _ = zombie.communicate(timeout=60)
        assert zombie.returncode == 0, \
            "the fenced zombie must abdicate cleanly, not crash"
        fenced_line = [ln for ln in out.splitlines()
                       if ln.startswith("FENCED")]
        assert fenced_line, out
        assert int(fenced_line[0].split()[1]) > 0, \
            "the zombie's guarded writes were not fenced"
    finally:
        _kill_all(procs)

    # the rejection's post-mortem evidence on the errors stream
    errs = list(res["sb_server"].errors) + list(store.drain_errors())
    fenced_errs = [e for e in errs
                   if e.get("classification") == "fenced-write"]
    assert fenced_errs, errs
    assert any(e.get("epoch") == 1 and e.get("current_epoch") == 2
               for e in fenced_errs), fenced_errs

    assert _results(storage) == golden
    _assert_no_repetitions(store)


@pytest.mark.heavy
def test_sigkill_inside_checkpoint_flip_window_filestore(tmp_path):
    """The exact window the keep-{N-1,N} checkpoint sweep exists for:
    the leader SIGKILLs itself right after publishing _state.6 but
    BEFORE the doc flips to iteration 6. The takeover resumes at the
    doc's iteration 5, must find _state.5 still present (the sweep may
    not have collected it), and the threaded fold converges to the
    10-iteration golden."""
    import examples.loopsum as loopsum
    coord = str(tmp_path / "coord")
    storage = f"shared:{tmp_path}/spill"
    store = FileJobStore(coord)
    spec_line = _ls_spec_line(10, storage)

    # the victim runs its own worker threads: SIGKILL lands between
    # phases (inside _save_loop_state), so no claim is in flight and
    # the takeover's zero-repetitions assertion is exact
    code = (
        "import sys, os, signal, threading, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from lua_mapreduce_tpu import FileJobStore, Server, TaskSpec, "
        "Worker\n"
        "from lua_mapreduce_tpu.engine.server import Server as _S\n"
        + _KILL_IN_FLIP_WINDOW_PATCH + spec_line +
        f"store = FileJobStore({coord!r})\n"
        "for i in range(2):\n"
        "    w = Worker(store).configure(max_iter=5000, max_sleep=0.05)\n"
        "    threading.Thread(target=w.execute, daemon=True).start()\n"
        "server = Server(store, poll_interval=0.05, ha=True, "
        "lease_ttl_s=1.0).configure(spec)\n"
        "server.loop()\n")
    env = _env()
    victim = subprocess.Popen([sys.executable, "-c", code], env=env,
                              stdout=subprocess.PIPE, text=True)
    try:
        assert victim.stdout.readline().strip() == "SAVED6"
        victim.wait(timeout=10)             # SIGKILLed itself

        raw = unwrap(get_storage_from(storage))
        assert raw.exists(f"{STATE_NS}.6")
        assert raw.exists(f"{STATE_NS}.5"), \
            "the sweep collected the checkpoint the flip-window resume needs"

        before = COUNTERS.snapshot()
        takeover = Server(store, poll_interval=0.05, ha=True,
                          lease_ttl_s=1.0)
        wts = [_worker_thread(store) for _ in range(2)]
        stats = takeover.loop()
        for t in wts:
            t.join(timeout=30)
    finally:
        if victim.poll() is None:
            victim.kill()
        victim.wait(timeout=10)

    assert COUNTERS.delta(before, COUNTERS.snapshot()).get(
        "leader_takeovers", 0) >= 1
    assert stats.iterations[0].iteration == 5, \
        "the takeover must resume at the doc's (pre-flip) iteration"
    acc, result = loopsum.expected(10)
    assert loopsum.ACC == acc
    assert _results(storage) == result
    _assert_no_repetitions(store)
    raw = unwrap(get_storage_from(storage))
    assert len(raw.list(f"{STATE_NS}.*")) <= 2, \
        "the checkpoint sweep stopped collecting"
    doc = store.pt_get("leader")
    assert doc["epoch"] == 2 and doc["holder"] == ""
