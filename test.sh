#!/bin/bash
# Self-test entry point (reference test.sh analog): per-module utest()
# sweep, then the full golden-diff + unit suite on the virtual 8-device
# CPU mesh. The reference's screen-backed multi-storage e2e matrix
# (test.sh:8-73) lives in tests/ as pytest suites (test_wordcount_golden
# covers every storage x combiner/reducer-property config; see
# SURVEY.md §4).
set -e
cd "$(dirname "$0")"
python -c "import lua_mapreduce_tpu; lua_mapreduce_tpu.utest(); print('utest: all module self-tests passed')"
# collection gate: API-drift import/collection errors (e.g. a changed JAX
# signature at module scope) must fail loudly here, not hide behind a
# --continue-on-collection-errors run that still reports green dots
python -m pytest tests/ --collect-only -q > /dev/null
echo "collect gate: tests/ collects cleanly"
# segment conformance under BOTH merge engines: the v1/v2 interop +
# fuzz suite runs once with the native C++ pass (built on demand) and
# once forced onto the pure-Python data plane — mixed-format runs,
# mixed fleets, and frame decode must agree byte-for-byte in both
python -m pytest tests/test_segment.py -q
LMR_DISABLE_NATIVE=1 python -m pytest tests/test_segment.py -q
echo "segment conformance: python + native merge engines agree"
# chaos-smoke gate (DESIGN §19): one seeded FaultPlan wordcount leg per
# storage backend, byte-compared against its fault-free twin — the
# retry/degradation layer must make injected transient faults invisible
python -m pytest tests/test_chaos.py -q -k "smoke"
echo "chaos smoke: injected faults invisible on all three backends"
# replication chaos-smoke gate (DESIGN §20): every primary replica
# destroyed mid-run — the failover reads + scavenger reconstruction
# must deliver byte-identical output with ZERO map re-runs
python -m pytest tests/test_chaos.py -q -k "replication" \
    --deselect tests/test_chaos.py::test_replication_chaos_distributed_matrix
echo "replication smoke: r-1 replica kills absorbed with zero map re-runs"
# speculation chaos-smoke gate (DESIGN §21): one deterministically slow
# worker (the `slow` FaultPlan kind) with speculation on — a clone must
# win the first-commit-wins race, output byte-identical to the
# fault-free twin, zero repetition charges; plus the store-level
# duplicate-lease conformance suite across all three job stores
python -m pytest tests/test_chaos.py::test_speculation_smoke_straggler \
    tests/test_speculation.py -q
echo "speculation smoke: straggler covered by a clone, zero rep bumps"
# trace smoke gate (DESIGN §22): one traced run must yield body spans,
# per-op histograms, and a schema-valid Chrome export — and a traced
# twin must stay byte-identical to the tracing-off run (spans live
# under the _trace. prefix, outside every engine namespace)
python -m pytest tests/test_trace.py -q -k "smoke"
echo "trace smoke: spans collected, exports valid, bytes unchanged"
# sched smoke gate (DESIGN §23): notify conformance across all three
# store backends (wakeup fires, lost notification falls back to the
# poll, stale wakeup is a no-op), the flood-vs-barrier fairness
# regression, and the notify-off byte-equivalence control; the LMR011
# (Waiter-routed waits) + notify-edge protocol gates ride the
# lmr-analyze line below
python -m pytest tests/test_sched.py -q \
    -k "conformance or starvation or notify_off or wakes"
echo "sched smoke: wakeups fire, lost notifies degrade, fairness holds"
# push smoke gate (DESIGN §24): the streaming-shuffle golden matrix
# (push off AND on, byte-identical), the memory-budget eviction
# regression, the quarantine/promote manifest gate, and the parsed-
# footer cache regression; plus the push chaos legs (seeded faults,
# one placement tag dark during the push, SIGKILL a pushing mapper
# mid-frame covered by a zero-charge speculation clone)
python -m pytest tests/test_push.py -q
python -m pytest tests/test_chaos.py -q -k "push"
echo "push smoke: golden matrix identical, eviction degrades, chaos held"
# external-sort smoke leg: a tiny CloudSort-shaped end-to-end sort —
# push vs staged byte-identical, globally sorted, frames actually
# pushed (the full GB-scale artifact is benchmarks/results/sort.json)
python benchmarks/sort_bench.py --smoke
# coded-shuffle chaos smoke gate (DESIGN §27): a data block of every
# 4+1 stripe destroyed — decode-from-survivors must deliver
# byte-identical output with zero map re-runs; then the acceptance
# leg: the extsort sort under coding with every stripe degraded at
# the reduce barrier, byte-identical + globally sorted + zero
# repetition charges (write amplification 1.3x where r=2 pays 2.0x —
# benchmarks/results/replication.json coded_overhead carries the
# measured numbers)
python -m pytest tests/test_chaos.py -q -k "coded and smoke"
python benchmarks/sort_bench.py --smoke-coded
echo "coded smoke: degraded stripes decode inline, zero re-runs"
# lmr-analyze gate: the framework-aware lint pass AND the
# interprocedural deep pass (DESIGN §25: whole-program call graph +
# context propagation — LMR013 flock-reachable IO, LMR014 unclassified
# raisables across the retry boundary, LMR015 clock/RNG in
# replay-deterministic regions, LMR016 non-replayable RPCs in retried
# frames, LMR017 trace-impure helpers) must be clean against the
# checked-in suppression baseline (analysis/baseline.json — shipped
# EMPTY), with NO stale suppressions (--fail-on-stale: a pragma or
# baseline entry that no longer fires has outlived the code it
# excused), and the lease-protocol model checker must exhaustively
# pass the 2-worker lifecycle (worker death included), the
# replica-recovery (reconstruct-vs-requeue) edge, the speculation
# (duplicate-lease / first-commit-wins / revoke) edge, AND the
# watch/notify (sleep / wake / lost-notification) edge while
# re-finding all six seeded races. Machine output: --format json
# (or --format sarif on lint/deep/task for CI annotation).
python -m lua_mapreduce_tpu.analysis --fail-on-findings --fail-on-stale
echo "lmr-analyze: lint+deep clean, no stale suppressions, protocol model-checked"
# lmr-racecheck gate (DESIGN §30): the concurrency band — thread-spawn
# graph + interprocedural locksets + the lock-order cycle scan
# (LMR026-030) — must be clean over the full repo inside its 30 s wall
# budget with both seeded races (dropped-lock write, ABBA deadlock)
# re-found; then the runtime cross-validation leg: the chaos smoke
# re-runs under LMR_LOCKCHECK=1 with every package Lock/RLock wrapped
# in the site-keyed order recorder — an acquisition order the static
# model lacks fails the session, and the chaos suite's own golden
# diffs prove the instrumented run stays byte-identical
python -m lua_mapreduce_tpu.analysis conc --fail-on-findings
LMR_LOCKCHECK=1 python -m pytest tests/test_chaos.py -q -k "smoke"
echo "lmr-racecheck: conc band clean, seeded races re-found, runtime lock orders all modeled"
# task-contract gate (DESIGN §25): every shipped task module must
# statically validate — plugin signatures, emit arity, determinism
# hazards — and classify to its pinned lowerability verdict: the
# wordcount matrix is store-plane (mapfn reads files), extsort is
# store-plane with in-graph-eligible partition/reduce (lifted by
# engine/ingraph.py's jit tier when forced), the sched bench task is
# fully in-graph eligible, and the converted iterative examples
# (kmeans / ALS / digits SGD — state threaded through job values,
# DESIGN §26) pin in-graph so engine=auto keeps compiling them
python -m lua_mapreduce_tpu.analysis task examples.wordcount --expect store-plane
# extsort also pins the HYBRID stage split (DESIGN §28): the map leg
# stays interpreted (mapfn's hashlib helper), the reduce leg compiles —
# the exact split engine=auto hands the stage-granular plane
python -m lua_mapreduce_tpu.analysis task examples.extsort.sorttask --expect store-plane --expect-ingraph-fn \
    --expect-stage map=interpreted --expect-stage reduce=compiled \
    --expect-stage mapfn=store-plane --expect-stage partitionfn=in-graph \
    --expect-stage reducefn=in-graph
python -m lua_mapreduce_tpu.analysis task benchmarks/coord_task.py --expect store-plane
python -m lua_mapreduce_tpu.analysis task benchmarks/sched_task.py --expect in-graph
# the hybrid bench task is the inverse extsort pin: compiled map+combine,
# host partition — the split the hybrid_sort bench leg measures
python -m lua_mapreduce_tpu.analysis task benchmarks/hybrid_task.py --expect store-plane \
    --expect-stage map=compiled --expect-stage reduce=compiled \
    --expect-stage mapfn=in-graph --expect-stage partitionfn=store-plane
python -m lua_mapreduce_tpu.analysis task examples.kmeans.mr_kmeans --expect in-graph
python -m lua_mapreduce_tpu.analysis task examples.als.mr_als --expect in-graph
python -m lua_mapreduce_tpu.analysis task examples.digits.mr_sgd --expect in-graph
echo "task contracts: all shipped task modules classify to their pinned verdicts"
# in-graph engine smoke gate (DESIGN §26): the golden-diff suite —
# integer workloads byte-identical compiled-vs-interpreted, float
# workloads allclose, one compile per loop task, the
# oracle-accepts/lowering-raises fallback degrading (never crashing)
# with the counter bumped — plus a tiny paired bench round proving
# plane selection + state agreement end-to-end on the CPU mesh
JAX_PLATFORMS=cpu python -m pytest tests/test_ingraph.py -q
JAX_PLATFORMS=cpu python benchmarks/ingraph_bench.py --smoke
echo "ingraph smoke: compiled plane byte/allclose-identical, fallback degrades"
# hybrid smoke gate (DESIGN §28): the stage-granular suite — forced and
# auto-negotiated splits byte-identical on both executors, doc
# negotiation sticky on resume, per-stage spans, fold proof gating,
# zero-leg evidence — plus the SIGKILL-mid-compiled-map-leg chaos leg
# and one tiny paired bench round per hybrid split (compiled legs run,
# fallback-free, byte/allclose vs the interpreted twin)
JAX_PLATFORMS=cpu python -m pytest tests/test_hybrid.py -q
JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py::test_hybrid_chaos_sigkill_mid_compiled_leg -q
JAX_PLATFORMS=cpu python benchmarks/ingraph_bench.py --smoke-hybrid
echo "hybrid smoke: stage legs compiled, split negotiated, chaos held"
# autotune smoke gate (DESIGN §29): the feedback-controller suite —
# hysteresis/cooldown/flip-lockout stability under adversarial signal,
# every decision carrying its autotune.* evidence span, chaos legs
# byte-identical with the controller on vs off, and the elastic
# FleetSupervisor growing under flood then retiring to baseline
# without losing a lease
JAX_PLATFORMS=cpu python -m pytest tests/test_autotune.py -q
echo "autotune smoke: knobs stable, decisions evidenced, fleet elastic"
# lmr-ha smoke gate (DESIGN §31): lease election + epoch fencing on a
# virtual clock, the fenced mutation surface landing its evidence on
# the errors stream, a clean --ha lifecycle releasing the lease, a hot
# standby retiring when the leader finishes, and a mid-loop takeover
# restoring save_state/restore_state threaded state; then the
# leader-lease protocol gate re-pinned standalone — the exhaustive
# 2-coordinator election/renewal/expiry/zombie sweep must pass and
# BOTH seeded HA races (double_leader, zombie_leader_write) must be
# re-found (also rides the full lmr-analyze sweep above; pinned here
# so an HA regression fails under its own banner). The heavy tier
# (--full below) SIGKILLs the leader at four phases with a hot
# standby, fences a SIGSTOP zombie, and lands a SIGKILL inside the
# checkpoint-save→doc-flip window.
python -m pytest tests/test_ha.py -q -k "smoke"
python - << 'PYEOF'
import dataclasses
from lua_mapreduce_tpu.analysis import protocol as proto
base = proto.ModelConfig(n_workers=2, n_jobs=2, batch_k=2, ha=True)
res = proto.check_protocol(base)
assert res.ok, f"leader-lease exhaustive sweep FAILED: {res.violation.message}"
print(f"leader-lease sweep: {res.states} states, "
      f"{res.transitions} transitions, ok")
for bug in proto.HA_BUGS:
    res = proto.check_protocol(dataclasses.replace(base, bug=bug))
    assert not res.ok, f"seeded HA bug {bug} NOT re-found"
    print(f"seeded {bug}: re-found ({res.states} states)")
PYEOF
echo "ha smoke: election fenced, takeover restores state, seeded races re-found"
python -m pytest tests/ -q --full
