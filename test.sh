#!/bin/bash
# Self-test entry point (reference test.sh analog): per-module utest()
# sweep, then the full golden-diff + unit suite on the virtual 8-device
# CPU mesh. The reference's screen-backed multi-storage e2e matrix
# (test.sh:8-73) lives in tests/ as pytest suites (test_wordcount_golden
# covers every storage x combiner/reducer-property config; see
# SURVEY.md §4).
set -e
cd "$(dirname "$0")"
python -c "import lua_mapreduce_tpu; lua_mapreduce_tpu.utest(); print('utest: all module self-tests passed')"
python -m pytest tests/ -q --full
