"""Server launcher.

Analog of reference execute_server.lua:1-62 with the same positional
contract: coordination spec, then the user-function module names, then
storage. ``/``-paths are normalized to dotted module names
(execute_server.lua:37-39).

    python -m lua_mapreduce_tpu.cli.execute_server \\
        COORD_DIR TASKFN MAPFN PARTITIONFN REDUCEFN \\
        [--combinerfn M] [--finalfn M] [--storage SPEC] \\
        [--result-ns NS] [--init-arg K=V ...]

COORD_DIR is the shared job-store directory (the connection-string analog);
"mem" runs an in-process pool with --inline-workers N.
"""

from __future__ import annotations

import argparse
import sys
import threading


def normalize_module(name: str) -> str:
    """a/b/c.py or a/b/c → a.b.c (execute_server.lua:37-39)."""
    if name.endswith(".py"):
        name = name[:-3]
    return name.strip("/").replace("/", ".")


def parse_init_args(pairs) -> dict:
    out = {}
    for pair in pairs or ():
        k, sep, v = pair.partition("=")
        if not sep:
            raise SystemExit(f"--init-arg needs K=V, got {pair!r}")
        out[k] = v
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="execute_server",
        description="Run the MapReduce server (orchestrator).")
    p.add_argument("coord", help="shared job-store directory, or 'mem'")
    p.add_argument("taskfn")
    p.add_argument("mapfn")
    p.add_argument("partitionfn")
    p.add_argument("reducefn")
    p.add_argument("--combinerfn")
    p.add_argument("--finalfn")
    p.add_argument("--storage", default=None,
                   help="backend[:path] — mem:TAG | shared:DIR | object:DIR "
                        "(default: mem:cli for an in-process pool, "
                        "shared:<COORD>/spill for a shared-dir pool)")
    p.add_argument("--result-ns", default="result")
    p.add_argument("--init-arg", action="append", metavar="K=V")
    p.add_argument("--inline-workers", type=int, default=0,
                   help="run N worker threads in this process")
    p.add_argument("--idle-poll-ms", type=float, default=None,
                   help="idle-poll CAP in ms for the inline workers "
                        "(lmr-sched, DESIGN §23): bounds the "
                        "lost-notification fallback latency; wakeup "
                        "channels interrupt waits long before it "
                        "(default: LMR_IDLE_POLL_MS, else the worker "
                        "max_sleep; LMR_SCHED_NOTIFY=0 disables "
                        "wakeups fleet-wide)")
    p.add_argument("--poll", type=float, default=0.1)
    p.add_argument("--stale-timeout", type=float, default=600.0,
                   help="requeue RUNNING jobs of silently-dead workers "
                        "after this many seconds (0 disables)")
    p.add_argument("--strict", action="store_true",
                   help="abort with PhaseFailed when any job goes FAILED "
                        "instead of running finalfn on partial results")
    p.add_argument("--pipeline", action="store_true",
                   help="pipelined shuffle: publish eager pre_merge jobs "
                        "while mappers run (byte-identical results, less "
                        "reduce fan-in; see docs/DESIGN.md §15)")
    p.add_argument("--premerge-min-runs", type=int, default=4,
                   help="min committed runs one pre_merge consolidates")
    p.add_argument("--premerge-max-runs", type=int, default=8,
                   help="max runs per pre_merge job")
    p.add_argument("--batch-k", type=int, default=1,
                   help="fleet default claim-lease size, written to the "
                        "task doc: workers claim up to K jobs per "
                        "control-plane round trip and commit them in one "
                        "batch (many-small-jobs amortization; workers "
                        "still shrink long-job leases to 1 adaptively)")
    p.add_argument("--segment-format", choices=("v1", "v2"), default="v1",
                   help="intermediate spill encoding, written to the task "
                        "doc as the fleet default: v1 = JSON text lines, "
                        "v2 = framed binary segments (block-compressed, "
                        "CRC-guarded, ranged reads; docs/DESIGN.md §17). "
                        "Readers sniff per file, final results stay v1")
    p.add_argument("--store-retries", type=int, default=None,
                   help="transient store/coord fault retry budget per op "
                        "(default 3, or LMR_STORE_RETRIES; 0 disables "
                        "the retry layer — DESIGN §19)")
    p.add_argument("--retry-base-ms", type=float, default=None,
                   help="decorrelated-jitter backoff base in ms "
                        "(default 25, or LMR_RETRY_BASE_MS)")
    p.add_argument("--replication", type=int, default=None,
                   help="shuffle replication factor r, written to the "
                        "task doc as the fleet default (default 1, or "
                        "LMR_REPLICATION): each spill publishes r copies "
                        "on distinct placement targets, readers fail over "
                        "to any survivor, and the scavenger reconstructs "
                        "lost copies instead of re-running map jobs — "
                        "docs/DESIGN.md §20. r=1 is byte-identical to "
                        "the unreplicated path")
    p.add_argument("--coding", type=str, default=None, metavar="K+M",
                   help="erasure-coded shuffle spec 'k+m' (e.g. 4+1), "
                        "written to the task doc as the fleet default "
                        "(or LMR_CODING): each spill stripes into k data "
                        "+ m Reed-Solomon parity blocks on distinct "
                        "placement targets, any m losses decode inline, "
                        "at (k+m)/k write amplification instead of "
                        "replication's r — docs/DESIGN.md §27. Mutually "
                        "exclusive with --replication")
    p.add_argument("--speculation-factor", type=float, default=None,
                   help="straggler factor (default 0 = off, or "
                        "LMR_SPECULATION): a RUNNING job older than "
                        "FACTOR x the fleet per-namespace duration EWMA "
                        "gets a speculative duplicate lease; idle "
                        "workers race it and the first commit wins — "
                        "the loser degrades to a zero-repetition no-op "
                        "(docs/DESIGN.md §21)")
    p.add_argument("--speculation-cap", type=int, default=2,
                   help="max live speculative clones per namespace "
                        "(bounds wasted duplicate work)")
    p.add_argument("--push", action="store_true", default=None,
                   help="push-based streaming shuffle (docs/DESIGN.md "
                        "§24), written to the task doc as the fleet "
                        "default: maps push JSEG frames into "
                        "per-partition reducer inboxes as they fill, "
                        "gated by per-map manifests; the reduce side "
                        "merges them incrementally behind the map "
                        "phase. Default off, or LMR_PUSH=1 (the "
                        "subprocess-fleet round-trip); byte-identical "
                        "output either way")
    p.add_argument("--push-budget-mb", type=float, default=None,
                   help="push buffer-pool memory budget in MB for the "
                        "inline workers (default 64, or "
                        "LMR_PUSH_BUDGET_MB): over-budget partitions "
                        "evict to the staged spill path — graceful "
                        "degradation instead of OOM (counted "
                        "push_evictions)")
    p.add_argument("--engine",
                   choices=("auto", "ingraph", "hybrid", "store"),
                   default=None,
                   help="execution engine (docs/DESIGN.md §26/§28; "
                        "default auto, or LMR_ENGINE): 'auto' consults "
                        "the static lowerability oracle at task load "
                        "and compiles in-graph-verdicted tasks to ONE "
                        "jitted shard_map program running on this "
                        "server (no jobs dispatched); tasks with only "
                        "SOME in-graph stages take the hybrid rung — "
                        "qualifying map/reduce legs compile on the "
                        "workers, the rest stays interpreted; pure "
                        "store-plane tasks fall back entirely. Every "
                        "decision is logged and traced ('lowering' + "
                        "per-stage 'lowering.<stage>' spans). 'ingraph' "
                        "forces the whole-task plane and RAISES on any "
                        "lowering failure (the CI hard mode); 'hybrid' "
                        "forces stage-granular lowering and NEVER "
                        "raises (unqualified legs degrade with counted "
                        "evidence); 'store' opts out. Written to the "
                        "task doc (with the per-stage split) and "
                        "sticky on resume")
    p.add_argument("--autotune", action="store_true", default=None,
                   help="self-tuning controller (docs/DESIGN.md §29; "
                        "default off, or LMR_AUTOTUNE=1): the server's "
                        "housekeeping tick reads the live stats/trace "
                        "stream and adapts batch_k, the push buffer "
                        "budget, the speculation factor, the retry "
                        "backoff base, and (with --inline-workers) the "
                        "worker-pool size — every change deployed "
                        "through the task doc with an autotune.<knob> "
                        "evidence span, hysteresis-banded and "
                        "cooldown/flip-lockout gated so knobs never "
                        "oscillate")
    p.add_argument("--autotune-max-workers", type=int, default=None,
                   help="elastic ceiling for the --inline-workers pool "
                        "under --autotune (default: the controller's "
                        "fleet cap, clamped by tenant admission quotas "
                        "when a fair-scheduling config is active)")
    p.add_argument("--ha", action="store_true", default=None,
                   help="highly-available coordination (docs/DESIGN.md "
                        "§31; default off, or LMR_HA=1): contend for "
                        "the epoch-fenced leader lease on the job "
                        "store's persistent table before orchestrating. "
                        "Losers hot-standby on the 'leader' wakeup "
                        "topic and take over MID-PHASE through the "
                        "resume matrix when the lease expires; every "
                        "server-side mutation is epoch-fenced, so a "
                        "paused-and-resumed zombie leader gets "
                        "StaleLeaderError instead of corrupting state. "
                        "Workers need no flag — they are "
                        "leader-agnostic")
    p.add_argument("--lease-ttl-s", type=float, default=None,
                   help="leader lease TTL in seconds (default 10, or "
                        "LMR_LEASE_TTL_S): renewed every TTL/3; a "
                        "standby takes over after the last renewal "
                        "ages past TTL. Lower = faster failover, more "
                        "control-plane CAS traffic")
    p.add_argument("--trace", action="store_true",
                   help="lmr-trace (docs/DESIGN.md §22): record "
                        "claim/body/publish/commit spans and per-op "
                        "latencies, flushed into the task storage as "
                        "_trace.* files; inspect with 'python -m "
                        "lua_mapreduce_tpu.trace STORAGE'. Subprocess "
                        "workers enable theirs via LMR_TRACE=1")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="wrap the run in utils/profiling.device_trace "
                        "(JAX/XLA profile into DIR, TensorBoard-"
                        "loadable). With --trace, span names are "
                        "bridged into the device profile so host and "
                        "TPU timelines correlate")
    p.add_argument("--quiet", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # probe the accelerator from a killable subprocess BEFORE this process
    # touches jax — a wedged single-tenant tunnel hangs in-process init
    from lua_mapreduce_tpu.utils.jax_env import force_cpu_if_unavailable
    force_cpu_if_unavailable()

    from lua_mapreduce_tpu.coord.filestore import FileJobStore
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore
    from lua_mapreduce_tpu.engine.contract import TaskSpec
    from lua_mapreduce_tpu.engine.server import Server
    from lua_mapreduce_tpu.engine.worker import Worker
    from lua_mapreduce_tpu.faults.retry import configure_retry

    if args.store_retries is not None or args.retry_base_ms is not None:
        configure_retry(args.store_retries, args.retry_base_ms)
    if args.trace:
        from lua_mapreduce_tpu.trace.span import Tracer, install_tracer
        install_tracer(Tracer(annotate=bool(args.profile)))

    import os as _os
    storage = args.storage or (
        "mem:cli" if args.coord == "mem"
        else f"shared:{_os.path.join(args.coord, 'spill')}")

    spec = TaskSpec(
        taskfn=normalize_module(args.taskfn),
        mapfn=normalize_module(args.mapfn),
        partitionfn=normalize_module(args.partitionfn),
        reducefn=normalize_module(args.reducefn),
        combinerfn=normalize_module(args.combinerfn) if args.combinerfn else None,
        finalfn=normalize_module(args.finalfn) if args.finalfn else None,
        init_args=parse_init_args(args.init_arg),
        storage=storage,
        result_ns=args.result_ns,
    )

    store = MemJobStore() if args.coord == "mem" else FileJobStore(args.coord)
    server = Server(store, poll_interval=args.poll,
                    stale_timeout_s=args.stale_timeout or None,
                    verbose=not args.quiet,
                    strict=args.strict,
                    pipeline=args.pipeline,
                    premerge_min_runs=args.premerge_min_runs,
                    premerge_max_runs=args.premerge_max_runs,
                    batch_k=args.batch_k,
                    segment_format=args.segment_format,
                    replication=args.replication,
                    coding=args.coding,
                    speculation=args.speculation_factor,
                    speculation_cap=args.speculation_cap,
                    push=args.push,
                    engine=args.engine,
                    autotune=args.autotune,
                    ha=args.ha,
                    lease_ttl_s=args.lease_ttl_s).configure(spec)

    def spawn_worker(_seq: int):
        w = Worker(store).configure(max_iter=10_000)
        if args.idle_poll_ms is not None:
            w.configure(idle_poll_ms=args.idle_poll_ms)
        if args.push_budget_mb is not None:
            w.configure(push_budget_mb=args.push_budget_mb)
        threading.Thread(target=w.execute, daemon=True).start()
        return w

    if args.inline_workers:
        if server.autotune:
            # elastic inline pool (DESIGN §29): the controller's fleet
            # knob resizes through a FleetSupervisor — retire clamps
            # max_jobs to 0, so the member leaves AFTER its current
            # poll settles (no lease is ever abandoned)
            from lua_mapreduce_tpu.sched.controller import FleetSupervisor
            cap = args.autotune_max_workers or max(args.inline_workers, 8)
            sup = FleetSupervisor(
                spawn_worker, retire=lambda w: w.configure(max_jobs=0),
                baseline=args.inline_workers, cap=cap)
            sup.ensure_baseline()
            server.set_fleet(sup.resize, size=args.inline_workers,
                             max_workers=cap)
        else:
            for i in range(args.inline_workers):
                spawn_worker(i)

    def report(phase: str, frac: float) -> None:
        if not args.quiet:
            print(f"\r[{phase}] {100 * frac:5.1f}%", end="", file=sys.stderr)
            if frac >= 1:
                print(file=sys.stderr)

    import contextlib
    profile_ctx = contextlib.nullcontext()
    if args.profile:
        # backend-bootstrap-before-trace ordering: entering device_trace
        # initializes the JAX backend, so it must come AFTER the
        # force_cpu_if_unavailable probe at the top of main() — the
        # documented train_lm discipline (utils/profiling.py)
        from lua_mapreduce_tpu.utils.profiling import device_trace
        profile_ctx = device_trace(args.profile)
    with profile_ctx:
        stats = server.loop(progress=report)
    last = stats.last
    if not args.quiet and last is not None:
        print(f"cluster_time={last.cluster_time:.2f}s "
              f"wall={stats.wall_time:.2f}s "
              f"map(sum cpu/real)={last.map.sum_cpu_time:.2f}/"
              f"{last.map.sum_real_time:.2f}s "
              f"reduce(sum cpu/real)={last.reduce.sum_cpu_time:.2f}/"
              f"{last.reduce.sum_real_time:.2f}s "
              f"failed={last.map.failed}/{last.reduce.failed}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
