"""CLI launchers (reference L7: execute_server.lua / execute_worker.lua)."""
