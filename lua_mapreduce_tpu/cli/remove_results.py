"""Result/coordination cleanup launcher.

Analog of reference remove_results.sh:1-9 (drops the whole task
database via the mongo shell). Here the same reset is: drop the job
store's task state (map/reduce namespaces, task doc, errors) and delete
the task's files from the intermediate/result storage.

    python -m lua_mapreduce_tpu.cli.remove_results COORD_DIR \\
        [--storage SPEC] [--result-ns NS] [--yes]

COORD_DIR may be a FileJobStore directory or "mem" (no-op for the
store half — in-process stores die with their process).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="remove_results",
        description="Drop a task's coordination state and results "
                    "(remove_results.sh analog).")
    p.add_argument("coord", help="job-store directory, or 'mem'")
    p.add_argument("--storage", default=None,
                   help="also delete this storage spec's task files "
                        "(backend[:path])")
    p.add_argument("--result-ns", default="result")
    p.add_argument("--yes", action="store_true",
                   help="skip the confirmation prompt")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.yes:
        try:
            reply = input(f"drop task state in {args.coord!r}"
                          + (f" and files under {args.storage!r}"
                             if args.storage else "")
                          + "? [y/N] ")
        except (EOFError, KeyboardInterrupt):
            reply = ""          # no TTY (cron/CI without --yes) = no
        if reply.strip().lower() not in ("y", "yes"):
            print("aborted", file=sys.stderr)
            return 1

    removed = 0
    if args.coord != "mem":
        from lua_mapreduce_tpu.coord.filestore import FileJobStore
        from lua_mapreduce_tpu.engine.worker import MAP_NS, RED_NS
        store = FileJobStore(args.coord)
        store.drop_ns(MAP_NS)
        store.drop_ns(RED_NS)
        store.delete_task()
        store.drain_errors()
        print(f"dropped {MAP_NS}/{RED_NS}/task/errors in {args.coord}")

    if args.storage:
        from lua_mapreduce_tpu.store.router import get_storage_from
        data = get_storage_from(args.storage)
        for name in data.list(f"{args.result_ns}.P*"):
            data.remove(name)
            removed += 1
        print(f"removed {removed} file(s) under {args.result_ns}.P* "
              f"in {args.storage}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
