"""Worker launcher.

Analog of reference execute_worker.lua:1-11:

    python -m lua_mapreduce_tpu.cli.execute_worker COORD_DIR \\
        [--max-iter N] [--max-sleep S] [--max-tasks N] [--verbose]

Workers are leader-agnostic: they talk to the job store, never to a
coordinator process, so an HA leader takeover (execute_server --ha,
docs/DESIGN.md §31) is invisible here — no flag, no reconnect, no
restart. In-flight claims survive the takeover and commit normally.
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="execute_worker",
        description="Run one elastic MapReduce worker.")
    p.add_argument("coord", help="shared job-store directory")
    p.add_argument("--max-iter", type=int, default=20)
    p.add_argument("--max-sleep", type=float, default=20.0)
    p.add_argument("--max-tasks", type=int, default=1)
    p.add_argument("--name")
    p.add_argument("--max-jobs", type=int, default=None,
                   help="leave after executing N jobs (bounded lifetime "
                        "for churned elastic pools)")
    p.add_argument("--batch-k", type=int, default=None,
                   help="claim up to K jobs per control-plane round trip "
                        "(batch lease); default follows the task "
                        "document's server-deployed batch_k")
    p.add_argument("--segment-format", choices=("v1", "v2"), default=None,
                   help="spill encoding THIS worker writes (default: "
                        "follow the task document's fleet default); pin "
                        "v1 on hosts that must stay text-only during a "
                        "rollout — readers sniff per file either way")
    p.add_argument("--replication", type=int, default=None,
                   help="shuffle replication factor THIS worker publishes "
                        "and reads with (default: follow the task "
                        "document's fleet default — DESIGN §20)")
    p.add_argument("--coding", type=str, default=None, metavar="K+M",
                   help="erasure-coding spec 'k+m' THIS worker publishes "
                        "and reads with (default: follow the task "
                        "document's deployed value — DESIGN §27)")
    p.add_argument("--idle-poll-ms", type=float, default=None,
                   help="idle-poll CAP in ms (lmr-sched, DESIGN §23): "
                        "the longest an idle worker waits between "
                        "claim-surface scans. Waits are capped jittered "
                        "backoff that the store's wakeup channel "
                        "interrupts, so this bounds only the "
                        "lost-notification fallback latency (default: "
                        "LMR_IDLE_POLL_MS, else --max-sleep; "
                        "LMR_SCHED_NOTIFY=0 disables wakeups entirely)")
    p.add_argument("--push", action="store_true", default=None,
                   help="push-based streaming shuffle for THIS worker "
                        "(docs/DESIGN.md §24; default: follow the task "
                        "document's fleet default — which LMR_PUSH=1 "
                        "round-trips to subprocess fleets): map output "
                        "lands as manifest-gated JSEG inbox frames "
                        "instead of staged run files")
    p.add_argument("--push-budget-mb", type=float, default=None,
                   help="push buffer-pool memory budget in MB (default "
                        "64, or LMR_PUSH_BUDGET_MB): over-budget "
                        "partitions evict to the staged spill path "
                        "instead of OOMing (counted push_evictions)")
    p.add_argument("--engine",
                   choices=("auto", "ingraph", "hybrid", "store"),
                   default=None,
                   help="execution engine (docs/DESIGN.md §26/§28) — "
                        "fleet-launcher parity: in-graph iterations run "
                        "ON THE SERVER (this worker simply sees no jobs "
                        "for them), and the hybrid plane's compiled "
                        "map/reduce legs follow the task document's "
                        "server-negotiated per-stage split regardless "
                        "of this flag; it validates and exports "
                        "LMR_ENGINE (the standalone-worker fallback "
                        "when a doc predates the negotiation, and the "
                        "knob for any LocalExecutor the user task "
                        "spawns in-process), so a launcher can pass "
                        "one uniform --engine to every process")
    p.add_argument("--phases", default="map,reduce",
                   help="comma list of phases this worker claims "
                        "(heterogeneous pools: dedicated mapper hosts "
                        "pass 'map', reducer hosts 'reduce')")
    p.add_argument("--store-retries", type=int, default=None,
                   help="transient store/coord fault retry budget per op "
                        "(default 3, or LMR_STORE_RETRIES; 0 disables "
                        "the retry layer — DESIGN §19)")
    p.add_argument("--retry-base-ms", type=float, default=None,
                   help="decorrelated-jitter backoff base in ms "
                        "(default 25, or LMR_RETRY_BASE_MS)")
    p.add_argument("--autotune-fleet", type=int, default=None, metavar="N",
                   help="elastic pool mode (docs/DESIGN.md §29): run N "
                        "baseline worker threads in this process and "
                        "follow the task document's controller-written "
                        "fleet_target — the pool grows toward the target "
                        "and retires surplus members gracefully (a "
                        "retiring member stops claiming and exits after "
                        "its current lease commits, so no lease is ever "
                        "lost to a scale-down)")
    p.add_argument("--autotune-max-workers", type=int, default=8,
                   help="elastic ceiling for --autotune-fleet (raised to "
                        "the baseline if smaller)")
    p.add_argument("--trace", action="store_true",
                   help="lmr-trace (docs/DESIGN.md §22): record this "
                        "worker's claim/body/publish/commit spans, "
                        "flushed into the task storage as _trace.* "
                        "files (also enabled fleet-wide via LMR_TRACE=1)")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="wrap execute() in utils/profiling.device_trace "
                        "(JAX/XLA profile into DIR — today only "
                        "train_lm had this). With --trace, span names "
                        "are bridged into the device profile")
    p.add_argument("--verbose", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # probe the accelerator from a killable subprocess BEFORE this process
    # touches jax — a wedged single-tenant tunnel hangs in-process init
    from lua_mapreduce_tpu.utils.jax_env import force_cpu_if_unavailable
    force_cpu_if_unavailable()

    from lua_mapreduce_tpu.coord.filestore import FileJobStore
    from lua_mapreduce_tpu.engine.worker import Worker
    from lua_mapreduce_tpu.faults.retry import configure_retry

    if args.store_retries is not None or args.retry_base_ms is not None:
        configure_retry(args.store_retries, args.retry_base_ms)
    if args.trace:
        from lua_mapreduce_tpu.trace.span import Tracer, install_tracer
        install_tracer(Tracer(annotate=bool(args.profile)))
    if args.engine is not None:
        import os
        from lua_mapreduce_tpu.engine.ingraph import resolve_engine
        os.environ["LMR_ENGINE"] = resolve_engine(args.engine)
    phases = tuple(s.strip() for s in args.phases.split(",") if s.strip())
    for ph in phases:
        if ph not in ("map", "reduce"):
            raise SystemExit(f"--phases: unknown phase {ph!r}")
    store = FileJobStore(args.coord)

    def mint(name):
        w = Worker(store, name=name, verbose=args.verbose).configure(
            max_iter=args.max_iter, max_sleep=args.max_sleep,
            max_tasks=args.max_tasks, phases=phases, max_jobs=args.max_jobs)
        if args.batch_k is not None:
            w.configure(batch_k=args.batch_k)
        if args.idle_poll_ms is not None:
            w.configure(idle_poll_ms=args.idle_poll_ms)
        if args.segment_format is not None:
            w.configure(segment_format=args.segment_format)
        if args.replication is not None:
            w.configure(replication=args.replication)
        if args.coding is not None:
            w.configure(coding=args.coding)
        if args.push is not None:
            w.configure(push=args.push)
        if args.push_budget_mb is not None:
            w.configure(push_budget_mb=args.push_budget_mb)
        return w

    import contextlib
    profile_ctx = contextlib.nullcontext()
    if args.profile:
        # backend-bootstrap-before-trace ordering: device_trace
        # initializes the JAX backend, so it must come AFTER the
        # force_cpu_if_unavailable probe above (utils/profiling.py)
        from lua_mapreduce_tpu.utils.profiling import device_trace
        profile_ctx = device_trace(args.profile)
    if args.autotune_fleet:
        # elastic pool mode (DESIGN §29): thread members share this
        # process's store handle; the supervisor loop follows the task
        # doc's fleet_target (written by the server's controller) and
        # runs until every member's own lifetime bounds retire it
        import threading
        import time
        from lua_mapreduce_tpu.sched.controller import FleetSupervisor

        threads = {}

        def spawn(seq):
            w = mint(f"{args.name or 'elastic'}-{seq}")
            t = threading.Thread(target=w.execute, daemon=True)
            threads[id(w)] = t
            t.start()
            return w

        cap = max(args.autotune_fleet, args.autotune_max_workers)
        sup = FleetSupervisor(
            spawn, retire=lambda w: w.configure(max_jobs=0),
            baseline=args.autotune_fleet, cap=cap)
        with profile_ctx:
            sup.ensure_baseline()
            while any(t.is_alive() for t in threads.values()):
                task = store.get_task() or {}
                if task.get("autotune") and task.get("fleet_target"):
                    sup.resize(int(task["fleet_target"]))
                time.sleep(0.2)
    else:
        worker = mint(args.name)
        with profile_ctx:
            worker.execute()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
