"""Deterministic fault injection + transient-fault-aware retry/degradation.

The robustness subsystem (DESIGN §19). Four pieces:

- ``errors``   — the :class:`StoreError` taxonomy and the central
  transient/permanent classification table;
- ``retry``    — :class:`RetryPolicy` (capped decorrelated-jitter
  backoff, injectable clock/sleep) and the process-global
  :class:`FaultCounters`;
- ``plan``     — :class:`FaultPlan`, a seeded deterministic fault
  schedule (transient / permanent / latency / torn write /
  error-after-write / RPC faults);
- ``wrappers`` — :class:`FaultyStore` / :class:`FaultyJobStore`
  (injection) and :class:`RetryingStore` / :class:`RetryingJobStore`
  (transparent retry with build readback-verify), plus the router and
  engine wiring points;
- ``replicate`` — the replica-aware shuffle data plane (DESIGN §20):
  r-way spill publish fanout (:func:`spill_writer`), failover reads
  (:class:`ReplicatedStore`), and scavenger reconstruction
  (:func:`repair`), addressed by the deterministic placement function
  in engine/placement.py;
- ``coded`` — the erasure-coded data plane (DESIGN §27): GF(256)
  Reed–Solomon k+m striping behind the same three choke points
  (``spill_writer``/``reading_view``/``repair`` dispatch on the
  unified redundancy knob), replication-grade durability at
  (k+m)/k write amplification.
"""

from lua_mapreduce_tpu.faults.errors import (ConcurrentInsertError,
                                             InjectedFault,
                                             InjectedPermanentFault,
                                             NoTaskError,
                                             PermanentStoreError,
                                             StaleLeaderError, StoreError,
                                             TransientStoreError,
                                             classify_exception,
                                             describe_classification,
                                             is_transient_fault)
from lua_mapreduce_tpu.faults.coded import (CodedStore, Coding,
                                            check_redundancy, parse_coding,
                                            redundancy_on, repair_stripe,
                                            resolve_redundancy)
from lua_mapreduce_tpu.faults.errors import LostShuffleDataError
from lua_mapreduce_tpu.faults.plan import FaultPlan
from lua_mapreduce_tpu.faults.replicate import (ReplicatedStore,
                                                reading_view, repair,
                                                spill_writer)
from lua_mapreduce_tpu.faults.retry import (COUNTERS, FaultCounters,
                                            RetryPolicy, configure_retry,
                                            default_policy, retry_settings)
from lua_mapreduce_tpu.faults.wrappers import (FaultyJobStore, FaultyStore,
                                               RetryingJobStore,
                                               RetryingStore, active_plan,
                                               install_fault_plan, unwrap,
                                               wiring_token, wrap_jobstore,
                                               wrap_store)

__all__ = [
    "StoreError", "TransientStoreError", "PermanentStoreError",
    "InjectedFault", "InjectedPermanentFault", "NoTaskError",
    "ConcurrentInsertError", "LostShuffleDataError", "StaleLeaderError",
    "classify_exception",
    "is_transient_fault", "describe_classification",
    "ReplicatedStore", "reading_view", "repair", "spill_writer",
    "Coding", "CodedStore", "parse_coding", "check_redundancy",
    "redundancy_on", "resolve_redundancy", "repair_stripe",
    "RetryPolicy", "FaultCounters", "COUNTERS", "configure_retry",
    "retry_settings", "default_policy",
    "FaultPlan",
    "FaultyStore", "FaultyJobStore", "RetryingStore", "RetryingJobStore",
    "install_fault_plan", "active_plan", "wrap_store", "wrap_jobstore",
    "unwrap", "wiring_token",
]


def utest() -> None:
    """Run the subsystem's module self-tests."""
    from lua_mapreduce_tpu.faults import (coded, errors, plan, replicate,
                                          retry, wrappers)
    for mod in (errors, retry, plan, wrappers, replicate, coded):
        mod.utest()
