"""Store/JobStore wrappers: deterministic injection + transparent retry.

Layering (router/engine wiring in store/router.py, engine/worker.py,
engine/server.py)::

    RetryingStore( TracingStore( FaultyStore( real Store ) ) )
    RetryingJobStore( TracingJobStore( FaultyJobStore( real JobStore ) ) )

The Faulty* layer exists only when a :class:`FaultPlan` is installed
(chaos suites, ``LMR_FAULT_PLAN`` env); the Tracing* layer (DESIGN §22,
lua_mapreduce_tpu/trace/) only when a tracer is active (``--trace`` /
``LMR_TRACE``) — placed INSIDE the retry layer so every retry attempt
records its own span, and OVER the injection layer so injected faults
are visible as error-tagged attempt spans; the Retrying* layer exists
whenever the retry budget is > 0 (the production default). Fault-free,
trace-free overhead is one bound-method delegation per op — the ≤2%
bench budget.

Build/commit ambiguity: a transient error out of ``build`` may mean the
publish DID land (error-after-write) or landed torn. The retrying
builder resolves it by READBACK-VERIFY — ``exists`` + ``size`` against
the byte count it streamed — before retrying, so a retry never
publishes a duplicate spill segment and a torn publish is always
rebuilt. Replay needs the data: chunks are retained up to
``REPLAY_CAP_BYTES``; past the cap a transient build failure surfaces
as a classified TransientStoreError — the worker releases the job (no
repetition charge) and the re-execution republishes idempotently
(DESIGN §19).
"""

from __future__ import annotations

import logging
import threading
from typing import Iterator, List, Optional, Union

from lua_mapreduce_tpu.faults.errors import (InjectedFault,
                                             InjectedPermanentFault,
                                             TransientStoreError)
from lua_mapreduce_tpu.faults.plan import RPC_OPS, FaultPlan
from lua_mapreduce_tpu.faults.retry import COUNTERS, RetryPolicy
from lua_mapreduce_tpu.store.base import FileBuilder, Store

_log = logging.getLogger(__name__)

REPLAY_CAP_BYTES = 64 << 20     # retain chunks for build replay up to 64MB


def unwrap(obj):
    """The innermost real store/jobstore under any wrapper stack."""
    while hasattr(obj, "_inner"):
        obj = obj._inner
    return obj


# --------------------------------------------------------------------------
# deterministic injection
# --------------------------------------------------------------------------


class _FaultyBuilder(FileBuilder):
    """Builder that can tear or ghost-fail its publish per the plan."""

    def __init__(self, store: "FaultyStore"):
        self._store = store
        self._inner = store._inner.builder()
        self._chunks: List[Union[str, bytes]] = []

    def write(self, data: str) -> None:
        self._chunks.append(data)

    def write_bytes(self, data: bytes) -> None:
        self._chunks.append(data)

    def _feed(self, builder, chunks) -> None:
        for c in chunks:
            if isinstance(c, bytes):
                builder.write_bytes(c)
            else:
                builder.write(c)

    def build(self, name: str) -> None:
        kind = self._store._plan.decide("build", name)
        if kind is not None:
            COUNTERS.bump("faults_injected")
        if kind == "latency":
            self._store._plan.apply_latency()
            kind = None
        elif kind == "slow":
            self._store._plan.apply_slow()
            kind = None
        if kind == "torn":
            # publish a PREFIX (the crash-mid-upload shape an object
            # store can surface), then report failure: readback-verify
            # must see the short object and rebuild
            torn = self._torn_prefix()
            self._inner.close()
            with self._store._inner.builder() as tb:
                self._feed(tb, torn)
                tb.build(name)
            raise InjectedFault(f"injected torn write on build({name!r})",
                                op="build", name=name)
        self._feed(self._inner, self._chunks)
        self._inner.build(name)
        if kind == "error_after_write":
            raise InjectedFault(
                f"injected error-after-write on build({name!r}) — the "
                "publish LANDED", op="build", name=name)
        if kind in ("transient", "permanent"):    # pragma: no cover
            raise InjectedFault(f"injected {kind} on build({name!r})",
                                op="build", name=name)

    def _torn_prefix(self) -> List[Union[str, bytes]]:
        out: List[Union[str, bytes]] = []
        budget = max(1, sum(len(c) for c in self._chunks) // 2)
        for c in self._chunks:
            if budget <= 0:
                break
            out.append(c[:budget] if len(c) > budget else c)
            budget -= len(out[-1])
        return out

    def close(self) -> None:
        self._inner.close()


class FaultyStore(Store):
    """Store wrapper injecting the plan's faults ahead of each op.

    Deliberately exposes ONLY the portable Store surface — native
    shortcuts like ``local_path`` are hidden so injected faults cannot
    be bypassed by the C++ fast paths during chaos runs. Publishes are
    ambiguous by construction (the plan can tear them or ghost-fail
    them on ANY backend), so the retry layer always retains replay
    chunks under injection.
    """

    publish_ambiguous = True

    def __init__(self, inner: Store, plan: FaultPlan):
        self._inner = inner
        self._plan = plan

    def _gate(self, op: str, name: str) -> None:
        kind = self._plan.decide(op, name)
        if kind is None:
            return
        COUNTERS.bump("faults_injected")
        if kind == "latency":
            self._plan.apply_latency()
        elif kind == "slow":
            self._plan.apply_slow()
        elif kind == "permanent":
            raise InjectedPermanentFault(
                f"injected permanent fault on {op}({name!r})",
                op=op, name=name)
        else:
            raise InjectedFault(f"injected transient fault on "
                                f"{op}({name!r})", op=op, name=name)

    def builder(self) -> FileBuilder:
        return _FaultyBuilder(self)

    def lines(self, name: str) -> Iterator[str]:
        self._gate("lines", name)
        return self._inner.lines(name)

    def read_range(self, name: str, offset: int, length: int) -> bytes:
        self._gate("read_range", name)
        return self._inner.read_range(name, offset, length)

    def size(self, name: str) -> int:
        self._gate("size", name)
        return self._inner.size(name)

    def list(self, pattern: str) -> List[str]:
        self._gate("list", pattern)
        return self._inner.list(pattern)

    def exists(self, name: str) -> bool:
        self._gate("exists", name)
        return self._inner.exists(name)

    def remove(self, name: str) -> None:
        self._gate("remove", name)
        return self._inner.remove(name)

    def classify(self, exc: BaseException):
        return self._inner.classify(exc)


# --------------------------------------------------------------------------
# transparent retry
# --------------------------------------------------------------------------


class _RetryingBuilder(FileBuilder):
    """Streams through to the real builder; on backends whose publish
    can fail ambiguously (``Store.publish_ambiguous``) it also retains
    chunk refs for replay and resolves build failures by
    readback-verify. Atomic-publish backends skip retention entirely —
    a failed build there provably published nothing, so there is
    nothing to verify and replaying would only duplicate spill memory."""

    def __init__(self, store: "RetryingStore"):
        self._store = store
        self._inner = store._inner.builder()
        self._ambiguous = getattr(store._inner, "publish_ambiguous", True)
        self._chunks: Optional[List[Union[str, bytes]]] = \
            [] if self._ambiguous else None
        self._approx = 0

    def _retain(self, data) -> None:
        if self._chunks is not None:
            self._approx += len(data)
            if self._approx > REPLAY_CAP_BYTES:
                self._chunks = None     # too big to replay: verify-only
            else:
                self._chunks.append(data)

    def write(self, data: str) -> None:
        self._retain(data)
        self._inner.write(data)

    def write_bytes(self, data: bytes) -> None:
        self._retain(data)
        self._inner.write_bytes(data)

    def _expected_size(self) -> int:
        from lua_mapreduce_tpu.store.base import encode_chunks
        return len(encode_chunks(self._chunks or []))

    def _landed(self, name: str, expected: int) -> bool:
        """Readback-verify: did an ambiguous publish actually land,
        whole? exists + size — both through the retrying store, so the
        verification itself survives transient blips."""
        try:
            if not self._store.exists(name):
                return False
            return self._store.size(name) == expected
        except Exception as exc:
            if self._store.classify(exc) is not None:
                return False            # can't verify → assume not landed
            raise

    def build(self, name: str) -> None:
        policy = self._store._policy
        classify = self._store._inner.classify
        try:
            self._inner.build(name)
            return
        except Exception as exc:
            if classify(exc) is not True:
                raise
            first = exc
        # ambiguous: the publish may have landed (whole or torn)
        expected = self._expected_size() if self._chunks is not None else -1
        if self._chunks is not None and self._landed(name, expected):
            COUNTERS.bump("build_verified")
            _log.warning("build(%r): transient error AFTER the publish "
                         "landed (%s) — verified by readback, not "
                         "retried", name, type(first).__name__)
            return
        if self._chunks is None:
            # no retained bytes to rebuild from — either an atomic-
            # publish backend (retention skipped by design: the failed
            # publish provably landed nothing) or a stream past the
            # replay cap (cannot readback-verify: exact byte count
            # unknown). Either way the fault is still a TRANSIENT piece
            # of infrastructure weather, so surface it CLASSIFIED: the
            # worker then releases the job (no repetition charge) and
            # the re-execution republishes idempotently. Raising
            # `first` raw would launder an infra fault into user code
            # and burn a repetition.
            why = (f"stream past the replay cap "
                   f"({REPLAY_CAP_BYTES >> 20}MB) — cannot verify or "
                   f"rebuild in place" if self._ambiguous else
                   "atomic-publish backend retains no replay bytes "
                   "(the failed publish landed nothing)")
            raise TransientStoreError(
                f"build({name!r}): transient failure; {why}; "
                f"releasing to job-level retry",
                op="build", name=name) from first

        def rebuild():
            self._inner.close()
            self._inner = self._store._inner.builder()
            for c in self._chunks:
                if isinstance(c, bytes):
                    self._inner.write_bytes(c)
                else:
                    self._inner.write(c)
            self._inner.build(name)

        policy.call(rebuild, op="build", name=name, classify=classify,
                    before_retry=lambda e: self._landed(name, expected))

    def close(self) -> None:
        self._inner.close()


class RetryingStore(Store):
    """Every portable store op behind the retry policy.

    ``lines`` retries the OPEN + FIRST record only: once a record has
    been yielded downstream, a silent restart would duplicate data, so
    mid-stream faults propagate (the merge layer's whole-file
    degradation in core/segment.py covers ranged readers).

    Unknown attributes (``local_path``, memfs test hooks) forward to the
    wrapped store so native fast paths keep working.
    """

    def __init__(self, inner: Store, policy: RetryPolicy):
        self._inner = inner
        self._policy = policy

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def _call(self, op, name, fn):
        return self._policy.call(fn, op=op, name=name,
                                 classify=self._inner.classify)

    def builder(self) -> FileBuilder:
        return _RetryingBuilder(self)

    def lines(self, name: str) -> Iterator[str]:
        def open_primed():
            it = iter(self._inner.lines(name))
            try:
                return next(it), it
            except StopIteration:
                return None, None

        first, it = self._call("lines", name, open_primed)
        if it is None:
            return
        yield first
        yield from it

    def read_range(self, name: str, offset: int, length: int) -> bytes:
        return self._call("read_range", name,
                          lambda: self._inner.read_range(name, offset,
                                                         length))

    def size(self, name: str) -> int:
        return self._call("size", name, lambda: self._inner.size(name))

    def list(self, pattern: str) -> List[str]:
        return self._call("list", pattern, lambda: self._inner.list(pattern))

    def exists(self, name: str) -> bool:
        return self._call("exists", name, lambda: self._inner.exists(name))

    def remove(self, name: str) -> None:
        return self._call("remove", name, lambda: self._inner.remove(name))

    def classify(self, exc: BaseException):
        return self._inner.classify(exc)


# --------------------------------------------------------------------------
# coord plane
# --------------------------------------------------------------------------

# JobStore methods wrapped by injection (Faulty*) and by retry
# (Retrying*). The retried set EXCLUDES the non-idempotent-on-replay
# ops: insert_jobs (a retried insert whose first attempt landed would
# double-insert; server-only, once per phase), pt_cas (same), and
# claim_batch — its index mutation lands under the flock BEFORE the
# claim-log append and payload resolution, so a transient error in
# those later steps retried as a fresh claim would lease ADDITIONAL
# jobs while the first lease sits orphaned (never heartbeaten, stale-
# requeued with a repetition charge — the exact bump this subsystem
# exists to prevent). An unretried claim failure simply surfaces to the
# worker's poll loop, which sleeps and re-polls; by then the stale
# requeue recovers any orphan WITHOUT this worker re-claiming blind.
# claim_spec shares the exclusion for the same shape (a landed first
# attempt would strand a TAKEN shadow lease nobody executes; the
# stranded lease is harmless — the original still commits — but it
# blocks the speculation cap until then, so don't retry blind).
_WRAPPED_RPCS = tuple(sorted(RPC_OPS))
_RETRIED_RPCS = tuple(sorted(RPC_OPS - {"claim_batch", "claim_spec"}))


class _JobStoreProxy:
    """Shared delegation base: anything not explicitly wrapped forwards
    to the inner store (put_task, insert_jobs, jobs, pt_*, rounds...)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


class FaultyJobStore(_JobStoreProxy):
    """Injects the plan's ``rpc_transient`` faults ahead of coord RPCs."""

    def __init__(self, inner, plan: FaultPlan):
        super().__init__(inner)
        self._plan = plan


class RetryingJobStore(_JobStoreProxy):
    """Coord RPCs behind the retry policy — the ``_RETRIED_RPCS`` set
    only, each of which is idempotent-on-retry under the CAS protocol:
    a commit/status CAS whose first attempt landed simply reports False
    on the replay (the expected state already moved on), never a double
    transition. Non-replayable ops (claim_batch, insert_jobs, pt_cas)
    pass through unretried — see the set's comment. Exception to the
    idempotence rule: the errors-stream ops (``insert_error`` append,
    ``drain_errors`` destructive read) are AT-LEAST-ONCE telemetry — a
    fault landing between the append/remove and the return can replay
    into a duplicate post-mortem entry, which is acceptable; losing the
    entry (or aborting a worker failure handler) is not."""

    def __init__(self, inner, policy: RetryPolicy):
        super().__init__(inner)
        self._policy = policy


def _make_rpc_wrappers():
    """Generate the per-op wrapped methods once, at import time — a
    hand-written 14-method wall of identical delegation would drift."""
    def faulty(op):
        def call(self, *args, **kw):
            # only a namespace-shaped first arg names the op stream:
            # update_task's fields dict would otherwise mint a fresh
            # occurrence key per call and defeat max_per_key
            ns = args[0] if args and isinstance(args[0], str) else op
            kind = self._plan.decide(op, ns)
            if kind is not None:
                COUNTERS.bump("faults_injected")
                if kind == "latency":       # pragma: no cover - rpc lat
                    self._plan.apply_latency()
                else:
                    raise InjectedFault(
                        f"injected transient fault on {op}({ns!r})",
                        op=op, name=ns)
            return getattr(self._inner, op)(*args, **kw)
        call.__name__ = op
        return call

    def retrying(op):
        def call(self, *args, **kw):
            ns = args[0] if args and isinstance(args[0], str) else op
            return self._policy.call(
                lambda: getattr(self._inner, op)(*args, **kw),
                op=op, name=ns, classify=self._inner.classify)
        call.__name__ = op
        return call

    for op in _WRAPPED_RPCS:
        setattr(FaultyJobStore, op, faulty(op))
    for op in _RETRIED_RPCS:
        setattr(RetryingJobStore, op, retrying(op))


_make_rpc_wrappers()


# --------------------------------------------------------------------------
# process-global plan install + wiring helpers
# --------------------------------------------------------------------------

_plan_lock = threading.Lock()
_installed_plan: Optional[FaultPlan] = None
_plan_generation = 0
_env_plans: dict = {}      # spec string -> parsed FaultPlan (one schedule
                           # per process per spec; NOT promoted to the
                           # installed slot, so un-setting the env var
                           # deactivates it)


def install_fault_plan(plan: Optional[FaultPlan]) -> None:
    """Install (or clear, with None) the process-wide fault plan. New
    stores built by the router and new engine wrappers pick it up; the
    chaos suite installs per-test and clears in a finally."""
    global _installed_plan, _plan_generation
    with _plan_lock:
        _installed_plan = plan
        _plan_generation += 1


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one parsed from ``LMR_FAULT_PLAN`` (the
    subprocess-fleet channel), else None. Env plans are memoized per
    spec string — one process, one schedule per spec — and deactivate
    when the variable is unset."""
    with _plan_lock:
        if _installed_plan is not None:
            return _installed_plan
    import os
    spec = os.environ.get("LMR_FAULT_PLAN")
    if not spec:
        return None
    with _plan_lock:
        plan = _env_plans.get(spec)
        if plan is None:
            plan = _env_plans[spec] = FaultPlan.from_spec(spec)
        return plan


def wiring_token() -> tuple:
    """Changes whenever the wrapper configuration would change — cache
    key for memoized wrapped stores (router's mem:tag instances)."""
    import os

    from lua_mapreduce_tpu.faults.retry import config_generation
    from lua_mapreduce_tpu.trace.span import trace_generation
    with _plan_lock:
        gen = _plan_generation
    return (gen, config_generation(), trace_generation(),
            os.environ.get("LMR_FAULT_PLAN") or "")


def wrap_store(store: Store) -> Store:
    """The router's one wiring point: injection (if a plan is active),
    tracing (if a tracer is active — DESIGN §22), then retry (if the
    budget is > 0), innermost to outermost."""
    from lua_mapreduce_tpu.faults.retry import default_policy
    from lua_mapreduce_tpu.trace.span import active_tracer
    plan = active_plan()
    if plan is not None:
        store = FaultyStore(store, plan)
    tracer = active_tracer()
    if tracer is not None:
        from lua_mapreduce_tpu.trace.wrappers import TracingStore
        store = TracingStore(store, tracer)
    policy = default_policy()
    if policy.retries > 0:
        store = RetryingStore(store, policy)
    return store


def wrap_jobstore(store):
    """Worker/Server wiring point for the coord plane. Idempotent — an
    already-wrapped store passes through."""
    from lua_mapreduce_tpu.trace.wrappers import TracingJobStore
    if isinstance(store, (RetryingJobStore, FaultyJobStore,
                          TracingJobStore)):
        return store
    from lua_mapreduce_tpu.faults.retry import default_policy
    from lua_mapreduce_tpu.trace.span import active_tracer
    plan = active_plan()
    if plan is not None:
        store = FaultyJobStore(store, plan)
    tracer = active_tracer()
    if tracer is not None:
        store = TracingJobStore(store, tracer)
    policy = default_policy()
    if policy.retries > 0:
        store = RetryingJobStore(store, policy)
    return store


def utest() -> None:
    """Self-test: injection determinism through the store surface,
    retry absorption, build readback-verify, torn-write rebuild."""
    import random

    from lua_mapreduce_tpu.store.memfs import MemStore

    # error-after-write: publish lands once, ambiguity verified away
    plan = FaultPlan(3, error_after_write=1.0, max_per_key=1,
                     sleep=lambda s: None)
    policy = RetryPolicy(retries=3, base_ms=1, sleep=lambda s: None,
                         rng=random.Random(0))
    raw = MemStore()
    store = RetryingStore(FaultyStore(raw, plan), policy)
    with store.builder() as b:
        b.write("k 1\n")
        b.write_bytes(b"\x00\x01")
        b.build("amb")
    assert raw.size("amb") == 6
    assert plan.fired.get("error_after_write") == 1

    # torn write: the truncated publish is detected and rebuilt whole
    plan2 = FaultPlan(4, torn=1.0, max_per_key=1, sleep=lambda s: None)
    store2 = RetryingStore(FaultyStore(MemStore(), plan2), policy)
    with store2.builder() as b:
        for i in range(20):
            b.write(f"line {i:03d}\n")
        b.build("torn")
    assert len(list(store2.lines("torn"))) == 20
    assert plan2.fired.get("torn") == 1

    # read-side transient bursts absorbed; lines restarts pre-yield only
    plan3 = FaultPlan(5, transient=0.6, max_per_key=2, sleep=lambda s: None)
    raw3 = MemStore()
    with raw3.builder() as b:
        b.write("a 1\nb 2\n")
        b.build("r")
    store3 = RetryingStore(FaultyStore(raw3, plan3), policy)
    for _ in range(12):
        assert store3.read_range("r", 0, 3) == b"a 1"
        assert list(store3.lines("r")) == ["a 1\n", "b 2\n"]
        assert store3.exists("r") and store3.size("r") == 8
    assert unwrap(store3) is raw3

    # jobstore RPC injection + retry: commits survive an injected burst;
    # claim_batch is deliberately NOT retried (non-replayable — a landed
    # first attempt would orphan its lease), so its injected faults
    # surface to the caller (the worker's poll loop re-polls)
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job
    js = MemJobStore()
    js.insert_jobs("map_jobs", [make_job("k", 1)])
    plan4 = FaultPlan(6, rpc_transient=0.7, max_per_key=4,
                      sleep=lambda s: None)
    wrapped = RetryingJobStore(FaultyJobStore(js, plan4), policy)
    assert "claim_batch" not in RetryingJobStore.__dict__
    got = []
    for _ in range(8):          # the poll loop's re-poll, in miniature
        try:
            got = wrapped.claim_batch("map_jobs", "w1", 1)
            break
        except InjectedFault:
            continue
    assert len(got) == 1
    assert wrapped.commit_batch("map_jobs", "w1",
                                [(got[0]["_id"], None)]) == [got[0]["_id"]]
    assert unwrap(wrapped) is js

    # install/active/env plumbing
    install_fault_plan(plan4)
    try:
        assert active_plan() is plan4
        t0 = wiring_token()
    finally:
        install_fault_plan(None)
    assert active_plan() is None and wiring_token() != t0
    assert isinstance(wrap_store(MemStore()), RetryingStore)
    assert wrap_jobstore(wrapped) is wrapped
