"""FaultPlan — deterministic, seeded fault schedules for chaos testing.

None of the robustness machinery is testable against luck: the chaos
suite needs to PROVOKE a 503 on exactly the third ranged read of one
spill file, on every run, on every machine. A :class:`FaultPlan` is that
schedule: the decision for an operation depends only on
``(seed, op, name, occurrence_index)`` — hashed, never drawn from a
shared RNG stream — so concurrent workers interleaving their ops cannot
perturb each other's schedules, and a re-executed job (whose occurrence
indices advance past the faulted ones) makes progress instead of
re-faulting forever.

Fault kinds (the failure modes the store/coord planes must survive):

- ``transient``          — raise :class:`InjectedFault` (retryable)
- ``permanent``          — raise :class:`InjectedPermanentFault`
- ``latency``            — sleep ``latency_ms`` before the op
- ``torn``               — build publishes a truncated file, then raises
                           transient (readback-verify must detect the
                           short object and rebuild)
- ``error_after_write``  — build lands COMPLETELY, then raises transient
                           (readback-verify must accept it and never
                           publish a duplicate)
- ``rpc_transient``      — transient faults on jobstore RPCs (claim /
                           commit / heartbeat / counts ...)
- ``blackout``           — every data-plane op on ONE placement tag
                           (engine/placement.py) fails transient for a
                           clock window: the whole-failure-domain shape
                           ("all replicas on one backend died") the
                           replicated shuffle must absorb (DESIGN §20).
                           Coded-stripe blocks and manifest copies
                           (faults/coded.py, DESIGN §27) route by the
                           tag embedded in their physical names — a
                           dark domain costs each stripe at most the
                           one block it placed there, exactly the shape
                           inline decode-from-survivors absorbs
- ``slow``               — every data-plane op by workers matching
                           ``slow_worker`` sleeps ``slow_ms`` for a
                           clock window: the DEGRADED-MACHINE shape
                           (thermal throttle, sick disk, noisy
                           neighbor) the speculative-execution layer
                           must absorb (DESIGN §21). A latency
                           multiplier in effect: an op that cost ε now
                           costs ε + slow_ms, every time, only for the
                           named worker — deterministic stragglers on
                           demand

``max_per_key`` bounds the faults charged to one ``(op, name)`` stream,
guaranteeing liveness under any retry budget (the blackout and slow
kinds are bounded by their WINDOW instead — a dark failure domain fails
every op and a sick machine slows every op, not a budgeted few). Plans
serialize to a compact ``k=v;k=v`` spec so subprocess fleets inherit
one through the ``LMR_FAULT_PLAN`` environment variable (parsed by the
router at store-wrap time). The ``slow`` kind needs to know WHICH
worker is executing: the worker runtime declares itself via
:func:`set_current_worker` (a thread-local — worker threads in one
process, one worker per process in subprocess fleets, both just work).
"""

from __future__ import annotations

import fnmatch
import hashlib
import threading
import time
from typing import Dict, Optional

_KINDS = ("transient", "permanent", "latency", "torn", "error_after_write",
          "rpc_transient")

# jobstore RPC op names (rate 'rpc_transient' applies; 'pattern' does not).
# put_task/delete_task/drop_ns are idempotent on replay (overwrite /
# tolerate-missing) — the server's inter-phase housekeeping must not
# abort a whole task over one store blip any more than scavenge may.
# speculate/cancel_spec are CASed idempotent (a replayed attempt reports
# False); claim_spec shares claim_batch's non-replayable exclusion below.
RPC_OPS = frozenset({
    "get_task", "put_task", "update_task", "delete_task", "drop_ns",
    "claim_batch", "commit_batch", "release_batch", "heartbeat",
    "heartbeat_batch", "set_job_status", "set_job_times", "counts",
    "scavenge", "requeue_stale", "insert_error", "drain_errors",
    "speculate", "claim_spec", "cancel_spec",
})

# build-only kinds never apply to read ops and vice versa
_BUILD_KINDS = ("torn", "error_after_write")

# ops a BLACKOUT darkens: the per-file data plane. ``build`` is excluded
# (the injected-build shapes torn/error_after_write model publish
# failure precisely; a pre-op transient on build is indistinguishable
# from error_after_write=never — the kind-orthogonality rule below),
# and ``list`` addresses a pattern, not a file on a tag.
_BLACKOUT_OPS = frozenset({"lines", "read_range", "size", "exists",
                           "remove"})

# ops a SLOW worker pays its latency tax on: the whole data plane a job
# body touches — reads AND publishes AND listings (a sick machine is
# slow at everything; unlike blackout, no tag routing is involved, so
# list's pattern argument is as taxable as any name)
_SLOW_OPS = frozenset({"lines", "read_range", "size", "exists", "remove",
                       "build", "list"})

# which worker is executing on THIS thread — the slow kind's routing
# input. Worker.execute declares its name here (thread-local: in-process
# pools run one worker per thread; subprocess fleets one per process);
# server/executor threads never declare and are never slowed.
_current_worker = threading.local()


def set_current_worker(name: Optional[str]) -> None:
    """Declare (or with None, clear) the worker identity executing on
    this thread — consumed by the ``slow`` fault kind's per-worker
    schedule."""
    _current_worker.name = name


def current_worker() -> Optional[str]:
    return getattr(_current_worker, "name", None)


class FaultPlan:
    """Seeded deterministic fault schedule over store/coord operations."""

    def __init__(self, seed: int = 0, *,
                 transient: float = 0.0, permanent: float = 0.0,
                 latency: float = 0.0, torn: float = 0.0,
                 error_after_write: float = 0.0, rpc_transient: float = 0.0,
                 latency_ms: float = 2.0, pattern: str = "*",
                 max_per_key: int = 2,
                 blackout_tag: Optional[int] = None,
                 blackout_s: float = 0.0, blackout_from_s: float = 0.0,
                 slow_worker: Optional[str] = None, slow_ms: float = 0.0,
                 slow_s: float = 0.0, slow_from_s: float = 0.0,
                 sleep=time.sleep, clock=time.monotonic):
        self.seed = int(seed)
        self.rates: Dict[str, float] = {
            "transient": transient, "permanent": permanent,
            "latency": latency, "torn": torn,
            "error_after_write": error_after_write,
            "rpc_transient": rpc_transient,
        }
        self.latency_ms = float(latency_ms)
        self.pattern = pattern
        self.max_per_key = int(max_per_key)
        # blackout: placement tag ``blackout_tag`` is dark for the
        # window [blackout_from_s, blackout_from_s + blackout_s) on the
        # plan's clock, zeroed at the FIRST decide() call — injectable
        # clock keeps chaos suites deterministic and virtual-time fast
        self.blackout_tag = (None if blackout_tag is None
                             else int(blackout_tag))
        self.blackout_s = float(blackout_s)
        self.blackout_from_s = float(blackout_from_s)
        # slow: workers matching the ``slow_worker`` glob pay slow_ms of
        # latency on every data-plane op inside the window
        # [slow_from_s, slow_from_s + slow_s) — the deterministic
        # straggler (DESIGN §21). Shares the blackout clock zero.
        self.slow_worker = slow_worker or None
        self.slow_ms = float(slow_ms)
        self.slow_s = float(slow_s)
        self.slow_from_s = float(slow_from_s)
        self._clock = clock
        self._t0: Optional[float] = None
        self._sleep = sleep
        self._lock = threading.Lock()
        self._occ: Dict[tuple, int] = {}      # (op, name) -> occurrences
        self._charged: Dict[tuple, int] = {}  # (op, name) -> faults fired
        self.fired: Dict[str, int] = {}       # kind -> count (telemetry)

    # -- decision ----------------------------------------------------------

    def _uniform(self, op: str, name: str, k: int) -> float:
        h = hashlib.blake2b(f"{self.seed}:{op}:{name}:{k}".encode(),
                            digest_size=8).digest()
        return int.from_bytes(h, "little") / 2.0 ** 64

    def decide(self, op: str, name: str) -> Optional[str]:
        """The fault kind for THIS occurrence of ``(op, name)``, or None.

        Deterministic in (seed, op, name, occurrence index); the index
        advances per call under a lock, so each logical op stream sees
        its own reproducible schedule regardless of thread interleaving
        across different keys.
        """
        key = (op, name)
        is_rpc = op in RPC_OPS
        # one lock hold for check + decide + charge: a split
        # check-then-act would let two threads racing the same stream
        # both pass the cap check and overshoot max_per_key — the
        # liveness guarantee the chaos suites' zero-repetition
        # assertions lean on (cap < retry budget must stay true)
        with self._lock:
            k = self._occ[key] = self._occ.get(key, 0) + 1
            if not is_rpc and not self._matches(name):
                return None
            # blackout before the per-key cap: a dark failure domain
            # fails EVERY matched op on its tag for the window — never
            # rate-drawn, never charged to the cap (the window is the
            # liveness bound). It shares the pattern gate with every
            # other kind: the name family being darkened is the plan
            # author's scope knob (chaos legs blacking out the shuffle
            # plane must not also take down result-file housekeeping,
            # which no replica can absorb).
            if self.blackout_tag is not None and op in _BLACKOUT_OPS:
                if self._t0 is None:
                    self._t0 = self._clock()
                t = self._clock() - self._t0
                if (self.blackout_from_s <= t
                        < self.blackout_from_s + self.blackout_s):
                    from lua_mapreduce_tpu.engine.placement import tag_of
                    if tag_of(name) == self.blackout_tag:
                        self.fired["blackout"] = \
                            self.fired.get("blackout", 0) + 1
                        return "transient"
            # slow, like blackout, before the per-key cap: a sick
            # machine is slow at EVERY op for its window, never a
            # budgeted few — and never charged to the cap (latency is
            # not a fault the retry layer absorbs; liveness is the
            # window). Routed by the executing WORKER, not the name:
            # the thread-local identity the worker runtime declares.
            if self.slow_worker is not None and op in _SLOW_OPS:
                me = current_worker()
                if me is not None and fnmatch.fnmatchcase(
                        me, self.slow_worker):
                    if self._t0 is None:
                        self._t0 = self._clock()
                    t = self._clock() - self._t0
                    if (self.slow_from_s <= t
                            < self.slow_from_s + self.slow_s):
                        self.fired["slow"] = self.fired.get("slow", 0) + 1
                        return "slow"
            if self._charged.get(key, 0) >= self.max_per_key:
                return None
            u = self._uniform(op, name, k)
            acc = 0.0
            for kind in _KINDS:
                if is_rpc != (kind == "rpc_transient"):
                    continue
                if kind in _BUILD_KINDS and op != "build":
                    continue
                if not is_rpc and kind not in _BUILD_KINDS and op == "build":
                    # builds only tear / error-after-write / lag — a
                    # plain pre-op transient on build is
                    # indistinguishable from error_after_write=never,
                    # so keep the kinds orthogonal
                    if kind != "latency":
                        continue
                acc += self.rates[kind]
                if u < acc:
                    self._charged[key] = self._charged.get(key, 0) + 1
                    self.fired[kind] = self.fired.get(kind, 0) + 1
                    return kind
        return None

    def _matches(self, name: str) -> bool:
        """``pattern`` is ``|``-alternated globs — chaos schedules
        addressing several name families (raw runs AND spills, say)
        need one plan, not one per family."""
        return any(fnmatch.fnmatchcase(name, p)
                   for p in self.pattern.split("|"))

    def apply_latency(self) -> None:
        if self.latency_ms > 0:
            self._sleep(self.latency_ms / 1000.0)

    def apply_slow(self) -> None:
        """The slow kind's per-op latency tax (separate knob from
        latency_ms — a plan can mix background jitter with one
        deterministic straggler)."""
        if self.slow_ms > 0:
            self._sleep(self.slow_ms / 1000.0)

    def total_fired(self) -> int:
        with self._lock:
            return sum(self.fired.values())

    # -- spec round-trip (subprocess inheritance) --------------------------

    def to_spec(self) -> str:
        parts = [f"seed={self.seed}"]
        parts += [f"{k}={v:g}" for k, v in self.rates.items() if v > 0]
        if self.latency_ms != 2.0:
            parts.append(f"latency_ms={self.latency_ms:g}")
        if self.pattern != "*":
            parts.append(f"pattern={self.pattern}")
        if self.max_per_key != 2:
            parts.append(f"max_per_key={self.max_per_key}")
        if self.blackout_tag is not None:
            parts.append(f"blackout_tag={self.blackout_tag}")
            parts.append(f"blackout_s={self.blackout_s:g}")
            if self.blackout_from_s:
                parts.append(f"blackout_from_s={self.blackout_from_s:g}")
        if self.slow_worker is not None:
            parts.append(f"slow_worker={self.slow_worker}")
            parts.append(f"slow_ms={self.slow_ms:g}")
            parts.append(f"slow_s={self.slow_s:g}")
            if self.slow_from_s:
                parts.append(f"slow_from_s={self.slow_from_s:g}")
        return ";".join(parts)

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse ``seed=7;transient=0.05;latency=0.02;pattern=*.SPILL-*``.
        Unknown keys are rejected loudly — a typo in a chaos-test spec
        must not silently run fault-free."""
        kw: Dict[str, object] = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            k, sep, v = part.partition("=")
            if not sep:
                raise ValueError(f"bad fault-plan entry {part!r}")
            k = k.strip()
            if k in ("pattern", "slow_worker"):
                kw[k] = v.strip()
            elif k in ("seed", "max_per_key", "blackout_tag"):
                kw[k] = int(v)
            elif k in _KINDS or k in ("latency_ms", "blackout_s",
                                      "blackout_from_s", "slow_ms",
                                      "slow_s", "slow_from_s"):
                kw[k] = float(v)
            else:
                raise ValueError(f"unknown fault-plan key {k!r}")
        seed = int(kw.pop("seed", 0))
        return cls(seed, **kw)  # type: ignore[arg-type]


def utest() -> None:
    """Self-test: determinism, occurrence advance, caps, spec round-trip."""
    mk = lambda: FaultPlan(7, transient=0.5, latency=0.2, max_per_key=3,
                           sleep=lambda s: None)
    a, b = mk(), mk()
    seq_a = [a.decide("read_range", "f.P0.M1") for _ in range(40)]
    seq_b = [b.decide("read_range", "f.P0.M1") for _ in range(40)]
    assert seq_a == seq_b                      # identical schedules
    assert any(k == "transient" for k in seq_a)
    assert sum(k is not None for k in seq_a) <= 3   # max_per_key cap

    # independent (op, name) streams don't perturb each other
    c = mk()
    for _ in range(5):
        c.decide("size", "other")
    assert [c.decide("read_range", "f.P0.M1") for _ in range(40)] == seq_a

    # build-only kinds fire only on build; rpc rate only on RPC ops
    p = FaultPlan(1, torn=1.0, max_per_key=100)
    assert all(p.decide("read_range", "x") is None for _ in range(10))
    assert p.decide("build", "x") == "torn"
    r = FaultPlan(2, rpc_transient=1.0, max_per_key=100)
    assert r.decide("claim_batch", "map_jobs") == "rpc_transient"
    assert r.decide("read_range", "map_jobs") is None

    spec = FaultPlan(9, transient=0.25, error_after_write=0.5,
                     pattern="*.SPILL-*", max_per_key=1).to_spec()
    q = FaultPlan.from_spec(spec)
    assert (q.seed, q.pattern, q.max_per_key) == (9, "*.SPILL-*", 1)
    assert q.rates["error_after_write"] == 0.5
    try:
        FaultPlan.from_spec("seed=1;bogus=2")
    except ValueError:
        pass
    else:
        raise AssertionError("unknown plan key must be rejected")

    # pattern alternation: one plan addresses several name families
    alt = FaultPlan(3, permanent=1.0, pattern="ns.P*.M*|ns.P*.SPILL-*",
                    max_per_key=100)
    assert alt.decide("lines", "ns.P0.M00000001") == "permanent"
    assert alt.decide("lines", "ns.P0.SPILL-00000-00003") == "permanent"
    assert alt.decide("lines", "ns.P0") is None

    # blackout: one placement tag dark for a virtual-clock window —
    # every data-plane op on that tag fails transient (no per-key cap);
    # other tags and post-window ops are untouched
    from lua_mapreduce_tpu.engine.placement import replica_name, tag_of
    vt = [0.0]
    bo = FaultPlan(4, blackout_tag=tag_of("ns.P0.M1"), blackout_s=5.0,
                   clock=lambda: vt[0], sleep=lambda s: None)
    dark = replica_name("other.P1.M9", 1)        # route a replica onto
    while tag_of(dark) != bo.blackout_tag:       # the dark tag
        dark = replica_name(dark[-1] + dark, 1)
    for _ in range(6):                           # window, uncapped
        assert bo.decide("read_range", "ns.P0.M1") == "transient"
    assert bo.decide("size", dark) == "transient"
    lit = "ns.P0.M2"
    if tag_of(lit) == bo.blackout_tag:           # find a lit name
        lit = next(f"ns.P0.M{i}" for i in range(3, 99)
                   if tag_of(f"ns.P0.M{i}") != bo.blackout_tag)
    assert bo.decide("read_range", lit) is None  # other tags lit
    vt[0] = 5.0                                  # window over
    assert bo.decide("read_range", "ns.P0.M1") is None
    assert bo.fired["blackout"] == 7
    # coded-stripe blocks route by their EMBEDDED tag (placement
    # parse_block): a dark tag darkens exactly the one block each
    # stripe placed there — the ≤m-loss shape decode absorbs (§27)
    from lua_mapreduce_tpu.faults.coded import Coding, block_names
    blocks = block_names("cns.P0.M1", Coding(4, 1))
    vt3 = [0.0]
    bo2 = FaultPlan(11, blackout_tag=tag_of(blocks[2]), blackout_s=5.0,
                    clock=lambda: vt3[0], sleep=lambda s: None)
    assert sum(tag_of(b) == bo2.blackout_tag for b in blocks) == 1
    for b2 in blocks:
        want = "transient" if tag_of(b2) == bo2.blackout_tag else None
        assert bo2.decide("read_range", b2) == want

    spec2 = FaultPlan(5, blackout_tag=3, blackout_s=0.25,
                      blackout_from_s=0.1).to_spec()
    q2 = FaultPlan.from_spec(spec2)
    assert (q2.blackout_tag, q2.blackout_s, q2.blackout_from_s) == \
        (3, 0.25, 0.1)

    # slow: only the matching worker pays the tax, only in the window,
    # only on data-plane ops; deterministic and uncapped; spec round-trip
    slept = []
    vt2 = [0.0]
    sl = FaultPlan(6, slow_worker="straggler-*", slow_ms=100.0, slow_s=4.0,
                   clock=lambda: vt2[0], sleep=slept.append)
    assert sl.decide("read_range", "f") is None       # no worker declared
    set_current_worker("straggler-7")
    try:
        assert sl.decide("read_range", "f") == "slow"
        assert sl.decide("build", "g") == "slow"      # publishes slowed too
        assert sl.decide("claim_batch", "map_jobs") is None   # RPCs exempt
        sl.apply_slow()
        assert slept == [0.1]
        set_current_worker("healthy-1")
        assert sl.decide("read_range", "f") is None   # other workers lit
        set_current_worker("straggler-7")
        vt2[0] = 4.0                                  # window over
        assert sl.decide("read_range", "f") is None
        assert sl.fired["slow"] == 2
    finally:
        set_current_worker(None)
    q3 = FaultPlan.from_spec(
        FaultPlan(8, slow_worker="w-[0-9]", slow_ms=50, slow_s=2.5,
                  slow_from_s=0.5).to_spec())
    assert (q3.slow_worker, q3.slow_ms, q3.slow_s, q3.slow_from_s) == \
        ("w-[0-9]", 50.0, 2.5, 0.5)
