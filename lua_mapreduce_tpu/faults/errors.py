"""Store/coord error taxonomy — transient vs permanent classification.

The reference treats every failure inside a job as a user-code failure:
worker.lua's xpcall marks the job BROKEN, repetitions climb, and three
storage hiccups push a perfectly good job to permanent FAILED
(server.lua:192-205). TensorFlow (arXiv:1605.08695 §4.2) and
Exoshuffle-CloudSort (arXiv:2301.03734) both separate *infrastructure*
faults — the 503 from an object store, the EIO from a flaky NFS mount,
a connection reset — from *deterministic* faults in user code, because
the right response differs: transient infra faults are retried (op
level) or released (job level, no repetition charge); deterministic
faults must burn a repetition so the scavenger can eventually give up.

This module is the shared vocabulary for that distinction:

- :class:`StoreError` — base of all *classified* storage/coordination
  faults, carrying ``transient`` (True = retry may help).
- :class:`TransientStoreError` / :class:`PermanentStoreError` — the two
  leaves everything raisable maps onto.
- :func:`classify_exception` — the central table mapping RAW exceptions
  (OSError errnos, timeouts, connection resets, GCS-shaped API errors)
  onto the taxonomy: True (transient), False (permanent), or None (not
  a storage fault at all — user code, logic errors).

Backends refine the table via ``Store.classify`` / ``JobStore.classify``
hooks (objectfs adds GCS error shapes); the retry layer
(faults/retry.py) consults the hook, and the worker's fault
discrimination (engine/worker.py) consults :func:`is_transient_fault`
on whatever finally propagates.
"""

from __future__ import annotations

import errno
from typing import Optional

# errnos a POSIX/NFS/FUSE mount produces under transient pressure: retry
# is the documented remedy for every one of these
_TRANSIENT_ERRNOS = frozenset(e for e in (
    errno.EIO, errno.EAGAIN, errno.EINTR, errno.EBUSY, errno.ETIMEDOUT,
    errno.ESTALE, errno.ENETDOWN, errno.ENETUNREACH, errno.ECONNRESET,
    errno.ECONNABORTED, errno.ECONNREFUSED, errno.EHOSTDOWN,
    errno.EHOSTUNREACH, errno.ENOBUFS, errno.ENOMEM, errno.EMFILE,
    errno.ENFILE, errno.EDEADLK,
) if e is not None)

# errnos that will not change on retry (caller bug or real absence)
_PERMANENT_ERRNOS = frozenset(e for e in (
    errno.ENOENT, errno.EACCES, errno.EPERM, errno.EISDIR, errno.ENOTDIR,
    errno.ENAMETOOLONG, errno.EROFS, errno.ENOSPC, errno.EDQUOT,
    errno.EBADF, errno.EINVAL,
) if e is not None)

# HTTP statuses a cloud object store returns for retryable conditions
# (GCS/S3 retry guidance: 408 request timeout, 429 rate limit, 5xx)
_TRANSIENT_HTTP = frozenset({408, 429, 500, 502, 503, 504})

# exception CLASS NAMES of third-party SDKs (google-cloud-storage,
# requests, urllib3) that mean "try again" — matched by name so the
# taxonomy never imports optional dependencies
_TRANSIENT_CLASS_NAMES = frozenset({
    "ServiceUnavailable", "TooManyRequests", "InternalServerError",
    "BadGateway", "GatewayTimeout", "DeadlineExceeded", "RetryError",
    "TransportError", "ChunkedEncodingError", "ReadTimeout",
    "ConnectTimeout", "ReadTimeoutError", "ProtocolError",
})


class StoreError(Exception):
    """A classified storage/coordination-plane fault.

    ``transient`` is the class-level verdict: True means a retry (same
    op, brief backoff) may succeed; False means it deterministically
    will not. Instances raised by the retry layer chain the original
    exception (``raise ... from exc``) and carry ``op``/``name`` —
    which store operation on which file/namespace — plus ``attempts``.
    """

    transient: bool = False

    def __init__(self, msg: str, *, op: Optional[str] = None,
                 name: Optional[str] = None, attempts: int = 1):
        super().__init__(msg)
        self.op = op
        self.name = name
        self.attempts = attempts


class TransientStoreError(StoreError):
    """Retry may help: 503s, timeouts, EIO, connection resets, flock
    contention. The retry layer absorbs bounded bursts of these; when a
    burst outlives the budget, the WORKER releases the job back to
    WAITING with no repetition charge (engine/worker.py)."""

    transient = True


class PermanentStoreError(StoreError):
    """Retry cannot help: the object is gone, the path is wrong, the
    credential is denied. Treated like any deterministic failure — the
    job goes BROKEN, repetitions climb, the scavenger can give up and
    the degradation ladders (premerge poison, strict-mode abort) fire."""

    transient = False


class InjectedFault(TransientStoreError):
    """A fault raised by FaultPlan injection (faults/plan.py) — its own
    type so test assertions can tell injected faults from real ones."""


class InjectedPermanentFault(PermanentStoreError):
    """Deterministic-injection flavor of a permanent fault."""


class NativeIndexError(TransientStoreError, OSError):
    """A job-index engine op reported failure without an errno (the
    native jsx_* calls return -1 on any IO/lock trouble). Classified
    transient — flock contention and IO pressure are the realistic
    causes, and the retry budget bounds the cost of being wrong.
    Subclasses OSError so pre-taxonomy callers keep catching it."""


class NoTaskError(PermanentStoreError, RuntimeError):
    """update_task on a store with no task document — a protocol misuse,
    never retryable. Subclasses RuntimeError so pre-taxonomy callers
    (``except RuntimeError``) keep working."""


class ConcurrentInsertError(PermanentStoreError, RuntimeError):
    """Two inserters raced a namespace (a namespace has exactly ONE
    inserter — the server). Deterministic protocol violation."""


class NativeEngineError(PermanentStoreError, RuntimeError):
    """The native index engine is unusable by construction — ABI drift
    from idx_py, a pre-guard cached .so, or an explicitly requested
    native build that is unavailable. Deterministic: retrying cannot
    rebuild a .so, so the retry layer must fail fast, not back off.
    Subclasses RuntimeError so pre-taxonomy callers keep working.
    (Distinct from :class:`NativeIndexError`, the TRANSIENT per-op
    failure of a healthy engine.)"""


class StaleLeaderError(PermanentStoreError, RuntimeError):
    """A server-side mutation carried a fencing epoch older than the
    current leader lease (DESIGN §31): the writer is a ZOMBIE — a
    coordinator that lost its lease to a takeover (GC pause, partition,
    SIGSTOP) and came back believing it still leads. Permanent by
    classification: retrying the same write with the same stale epoch
    deterministically fails again, so the retry layer must fail fast
    and the holder must abdicate (re-enter standby), never back off
    and corrupt state later. Subclasses RuntimeError so pre-taxonomy
    callers keep catching it. ``epoch``/``current_epoch``/``holder``
    carry the fencing evidence for the errors stream."""

    def __init__(self, msg: str, *, epoch: Optional[int] = None,
                 current_epoch: Optional[int] = None,
                 holder: Optional[str] = None, **kw):
        super().__init__(msg, **kw)
        self.epoch = epoch
        self.current_epoch = current_epoch
        self.holder = holder


class LostShuffleDataError(TransientStoreError):
    """Every replica of a shuffle file is unreadable (DESIGN §20).

    Raised by the replicated read view (faults/replicate.py) when the
    failover ladder runs out of copies. Transient by classification —
    the worker RELEASES the consuming job (no repetition charge) while
    the server's scavenger repairs the file from a survivor or, with
    all ``r`` copies gone, requeues the producing map job (the
    last-resort re-run). ``lost_files`` names the logical files so the
    scavenger acts on structure, not on traceback parsing."""

    def __init__(self, msg: str, *, files=(), **kw):
        super().__init__(msg, **kw)
        self.lost_files = list(files)


def classify_exception(exc: BaseException) -> Optional[bool]:
    """The central classification table.

    Returns True (transient — retry may help), False (permanent — it
    will not), or None (not a storage fault: user code, data errors,
    logic bugs — the retry layer must propagate these untouched).
    """
    if isinstance(exc, StoreError):
        return exc.transient
    # stdlib networking/timeout shapes are transient by construction
    if isinstance(exc, (TimeoutError, ConnectionError, InterruptedError)):
        return True
    if isinstance(exc, (FileNotFoundError, PermissionError, IsADirectoryError,
                        NotADirectoryError)):
        return False
    if isinstance(exc, OSError):
        if exc.errno in _TRANSIENT_ERRNOS:
            return True
        if exc.errno in _PERMANENT_ERRNOS:
            return False
        # an OSError with no recognizable errno (the native index engine
        # raises bare OSError on a failed jsx op; fcntl can surface
        # unmapped codes): IO-shaped, cause unknown — retry is cheap and
        # the budget is bounded, so err toward transient
        return True
    # KeyError from memfs lines()/read_range() on a missing name — the
    # in-memory analog of FileNotFoundError
    if isinstance(exc, KeyError):
        return False
    # cloud-SDK shapes, matched without importing the SDKs: a numeric
    # ``code`` (google-api-core) or ``status_code`` (requests) in the
    # retryable set, or a well-known transient class name
    code = getattr(exc, "code", None)
    if not isinstance(code, int):
        code = getattr(exc, "status_code", None)
    if isinstance(code, int) and code in _TRANSIENT_HTTP:
        return True
    if type(exc).__name__ in _TRANSIENT_CLASS_NAMES:
        return True
    return None


def is_transient_fault(exc: BaseException) -> bool:
    """True when ``exc`` is a *transient infrastructure* fault, judged
    by the type table — for call sites where the exception is KNOWN to
    come from a store op (the segment reader's ranged reads). Permanent
    and unclassified exceptions both answer False."""
    return classify_exception(exc) is True


def is_transient_job_fault(exc: BaseException) -> bool:
    """The worker's release-not-broken predicate for whole JOB BODIES.

    Provenance matters here: a job body runs user code too, and a user
    mapfn raising TimeoutError must not be laundered into an
    infrastructure fault (it would be released and re-executed forever).
    Only :class:`StoreError` subclasses provably crossed the store/coord
    boundary — the retry layer wraps every exhausted transient burst in
    one — so only they qualify. Raw builtins escaping a job body are
    treated as user code (exactly the pre-taxonomy behavior; with the
    retry layer stripped via retries=0, discrimination degrades to that
    old behavior rather than misfiring)."""
    return isinstance(exc, StoreError) and exc.transient


def classify_job_fault(exc: BaseException) -> str:
    """Errors-stream label for a failed JOB: 'infra-transient' /
    'infra-permanent' for classified StoreErrors (provenance known),
    'user-code' for everything else — see
    :func:`is_transient_job_fault` for why raw builtins land in
    user-code."""
    if isinstance(exc, StoreError):
        return "infra-transient" if exc.transient else "infra-permanent"
    return "user-code"


def describe_classification(exc: BaseException) -> str:
    """Human label by the TYPE TABLE alone: 'infra-transient',
    'infra-permanent', or 'user-code' (unclassified). For store-op
    contexts; job-level call sites use :func:`classify_job_fault`."""
    verdict = classify_exception(exc)
    if verdict is True:
        return "infra-transient"
    if verdict is False:
        return "infra-permanent"
    return "user-code"


def utest() -> None:
    """Self-test: the classification table's contract."""
    assert classify_exception(TimeoutError()) is True
    assert classify_exception(ConnectionResetError()) is True
    assert classify_exception(OSError(errno.EIO, "eio")) is True
    assert classify_exception(OSError("weird no-errno failure")) is True
    assert classify_exception(FileNotFoundError("x")) is False
    assert classify_exception(PermissionError("x")) is False
    assert classify_exception(KeyError("missing")) is False
    assert classify_exception(ValueError("user data")) is None
    assert classify_exception(RuntimeError("user logic")) is None

    class _Gcs503(Exception):
        code = 503

    class ServiceUnavailable(Exception):
        pass

    assert classify_exception(_Gcs503()) is True
    assert classify_exception(ServiceUnavailable()) is True

    assert TransientStoreError("t").transient is True
    assert PermanentStoreError("p").transient is False
    assert classify_exception(InjectedFault("i")) is True
    assert is_transient_fault(TransientStoreError("t"))
    assert not is_transient_fault(PermanentStoreError("p"))
    assert not is_transient_fault(ValueError("v"))
    assert describe_classification(TimeoutError()) == "infra-transient"
    assert describe_classification(KeyError("k")) == "infra-permanent"
    assert describe_classification(ValueError("v")) == "user-code"
    # job-level discrimination requires StoreError PROVENANCE: a user
    # mapfn's raw TimeoutError is user code, not a releasable infra fault
    assert is_transient_job_fault(TransientStoreError("t"))
    assert not is_transient_job_fault(TimeoutError("user timeout"))
    assert not is_transient_job_fault(PermanentStoreError("p"))
    assert classify_job_fault(TransientStoreError("t")) == "infra-transient"
    assert classify_job_fault(PermanentStoreError("p")) == "infra-permanent"
    assert classify_job_fault(TimeoutError("user")) == "user-code"
    assert classify_job_fault(KeyError("user")) == "user-code"
    # pre-taxonomy except-clauses keep catching the coord protocol errors
    assert issubclass(NoTaskError, RuntimeError)
    assert issubclass(ConcurrentInsertError, RuntimeError)
    # fencing rejections are permanent (fail fast, never back off) and
    # carry the epoch evidence the errors stream records (DESIGN §31)
    assert issubclass(StaleLeaderError, RuntimeError)
    sl = StaleLeaderError("fenced", epoch=2, current_epoch=3, holder="s1")
    assert sl.transient is False and classify_exception(sl) is False
    assert (sl.epoch, sl.current_epoch, sl.holder) == (2, 3, "s1")
    e = TransientStoreError("m", op="read_range", name="f", attempts=4)
    assert (e.op, e.name, e.attempts) == ("read_range", "f", 4)
