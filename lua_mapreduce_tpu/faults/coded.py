"""Erasure-coded shuffle redundancy: k+m striping, decode-from-survivors.

DESIGN §27. The replicated data plane (faults/replicate.py, DESIGN §20)
buys millisecond failover at ``r``·1.0x write amplification — full
copies on distinct placement tags. Coded MapReduce's core result
(PAPERS.md) is that the same durability is cheaper than copies: split a
payload into ``k`` data blocks, derive ``m`` Reed–Solomon parity blocks
over GF(256), and place each of the ``k+m`` blocks on a DISTINCT
placement tag (engine/placement.py). Any ``m`` lost tags still leave
``k`` evaluations of the degree-<k polynomial — enough to reconstruct
everything — at ``(k+m)/k`` write amplification: 4+1 ≈ 1.27x tolerates
any single-domain loss that r=2 pays 2.0x for.

Stripe layout (one logical file)::

    ^0.<t0>^<name>  ...  ^<k-1>.<tk-1>^<name>     k data blocks
    ^<k>.<tk>^<name> ... ^<k+m-1>.<..>^<name>     m parity blocks
    ^M^<name>  (+ m replica copies ~j.<t>~^M^<name>)   the manifest

Block ``i`` lives on tag ``(primary_tag(name)+i) % NUM_TAGS`` — the
replica formula, so the blocks occupy ``k+m`` distinct tags; the
manifest is replicated ``m+1``-way on distinct tags, so any ``m`` tag
losses leave both a readable manifest and ≥ ``k`` blocks. All stripe
names start with ``^`` — glob-transparent to every discovery/cleanup
pattern, exactly like ``~`` replica names. The manifest (a one-line
JSON doc naming the block set with per-block CRCs) publishes LAST: a
producer killed mid-stripe leaves orphan blocks that no reader can see
(``exists``/``list`` answer for the manifest), so partial stripes are
invisible and a re-publish of the same name simply overwrites.

Group stripes (the bandwidth half, DESIGN §27): a push-mode mapper
holding several partitions' final frames concatenates them into ONE
payload, stripes it once, and writes each member its own manifest with
an ``(off, len)`` window into the shared block set — one coded
combination serving multiple reducer inboxes, amortizing the parity
and manifest cost across partitions instead of paying it per fragment.

The read side (:class:`CodedStore`, the ``reading_view`` twin of
ReplicatedStore) serves LOGICAL names: the systematic fast path
concatenates the ``k`` data blocks (no GF math on the healthy path);
a classified storage fault or a per-block CRC mismatch — a corrupted
block is a lost block — triggers decode-from-survivors inline, counted
``decode_reads`` + ``map_reruns_avoided`` once per name. Fewer than
``k`` readable blocks raises :class:`LostShuffleDataError`, the same
classified-transient escalation replication uses: the worker releases,
the scavenger tries :func:`repair_stripe`, and only a truly lost
stripe falls through to the map re-run last resort (engine/server.py).

Name construction (the ``^``-sigil grammar) is THIS module's monopoly —
lint rule LMR012 flags stripe-name literals anywhere else; placement.py
owns the parsing side (tag routing must work for every copy shape).
No new dependencies: the codec is pure Python (``bytes.translate`` +
big-int XOR) with a vectorized numpy table-gather fast path when numpy
is importable.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

from lua_mapreduce_tpu.engine.placement import (NUM_TAGS, check_replication,
                                                primary_tag, replica_names,
                                                replica_pattern,
                                                resolve_replication)
from lua_mapreduce_tpu.faults.errors import (LostShuffleDataError,
                                             classify_exception)
from lua_mapreduce_tpu.faults.retry import COUNTERS
from lua_mapreduce_tpu.store.base import FileBuilder, Store, encode_chunks


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _classifier(store):
    return getattr(store, "classify", classify_exception)


# --------------------------------------------------------------------------
# GF(256) Reed–Solomon codec (poly 0x11d, generator 2)
# --------------------------------------------------------------------------

_GF_POLY = 0x11D
_EXP = [0] * 512
_LOG = [0] * 256
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _GF_POLY
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]
del _x, _i


def _gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return _EXP[_LOG[a] + _LOG[b]]


def _gf_inv(a: int) -> int:
    return _EXP[255 - _LOG[a]]


_ROW_CACHE: Dict[int, bytes] = {}


def _mul_row(c: int) -> bytes:
    """The 256-entry multiply-by-``c`` table as bytes —
    ``block.translate(row)`` is the C-speed scalar·vector product the
    pure-Python path leans on."""
    row = _ROW_CACHE.get(c)
    if row is None:
        row = bytes(_gf_mul(c, b) for b in range(256))
        _ROW_CACHE[c] = row
    return row


_COEF_CACHE: Dict[Tuple[Tuple[int, ...], int], Tuple[int, ...]] = {}


def _lagrange_coeffs(xs: Tuple[int, ...], x: int) -> Tuple[int, ...]:
    """``c_j`` with ``P(x) = Σ c_j · P(xs[j])`` for every polynomial of
    degree < len(xs) — evaluation as a linear combination of any
    len(xs) known points, the one primitive both encode (data points
    0..k-1 → parity points k..k+m-1) and decode (any k survivors →
    the missing points) reduce to. GF(2^8) subtraction is XOR."""
    key = (xs, x)
    out = _COEF_CACHE.get(key)
    if out is None:
        coeffs = []
        for j, xj in enumerate(xs):
            num, den = 1, 1
            for t, xt in enumerate(xs):
                if t != j:
                    num = _gf_mul(num, x ^ xt)
                    den = _gf_mul(den, xj ^ xt)
            coeffs.append(_gf_mul(num, _gf_inv(den)))
        out = _COEF_CACHE[key] = tuple(coeffs)
    return out


_NUMPY = None            # (module, 256x256 mul table) | () when absent
_FORCE_PYTHON = False    # utest flips to cover the fallback path


def _numpy():
    global _NUMPY
    if _NUMPY is None:
        try:
            import numpy as np
            tbl = np.zeros((256, 256), dtype=np.uint8)
            for a in range(1, 256):
                tbl[a] = np.frombuffer(_mul_row(a), np.uint8)
            _NUMPY = (np, tbl)
        except Exception:
            _NUMPY = ()
    return _NUMPY if _NUMPY else (None, None)


def _combine(pairs: Sequence[Tuple[int, bytes]], blen: int) -> bytes:
    """``XOR_j coeff_j · block_j`` over GF(256): the numpy fast path is
    one table gather + XOR per block; the fallback is
    ``bytes.translate`` (the same table, C speed) + big-int XOR —
    vectorized either way, never a Python per-byte loop."""
    np, tbl = (None, None) if _FORCE_PYTHON else _numpy()
    if np is not None:
        acc = np.zeros(blen, np.uint8)
        for c, blk in pairs:
            if c == 0:
                continue
            arr = np.frombuffer(blk, np.uint8)
            acc ^= arr if c == 1 else tbl[c][arr]
        return acc.tobytes()
    acc = 0
    for c, blk in pairs:
        if c == 0:
            continue
        if c != 1:
            blk = blk.translate(_mul_row(c))
        acc ^= int.from_bytes(blk, "big")
    return acc.to_bytes(blen, "big")


def rs_parity(data_blocks: Sequence[bytes], m: int) -> List[bytes]:
    """The ``m`` parity blocks of ``k`` equal-length data blocks:
    evaluations of the interpolating polynomial at points k..k+m-1."""
    k, blen = len(data_blocks), len(data_blocks[0])
    xs = tuple(range(k))
    return [_combine(list(zip(_lagrange_coeffs(xs, x), data_blocks)), blen)
            for x in range(k, k + m)]


def rs_reconstruct(have: Dict[int, bytes], want: Sequence[int],
                   k: int) -> Dict[int, bytes]:
    """Rebuild the blocks at points ``want`` from any ≥ k survivors in
    ``have`` (point index → block). Raises ValueError below k — the
    caller's decode-vs-map-rerun decision point."""
    if len(have) < k:
        raise ValueError(f"need {k} surviving blocks, have {len(have)}")
    xs = tuple(sorted(have))[:k]
    basis = [have[x] for x in xs]
    blen = len(basis[0])
    return {x: _combine(list(zip(_lagrange_coeffs(xs, x), basis)), blen)
            for x in want}


# --------------------------------------------------------------------------
# the coding knob (the unified redundancy value engines thread through)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Coding:
    """A ``k+m`` erasure-coding spec: k data + m parity blocks, any m
    losses decodable, (k+m)/k write amplification. Flows through every
    ``replication=`` parameter in engine/ unchanged — the choke points
    (spill_writer / reading_view / repair) dispatch on the type."""

    k: int
    m: int

    def __post_init__(self):
        if self.k < 2:
            raise ValueError(f"coding k={self.k}: k must be >= 2 (k=1 is "
                             "plain replication — use the replication knob)")
        if self.m < 1:
            raise ValueError(f"coding m={self.m}: at least one parity block")
        if self.k + self.m > NUM_TAGS:
            raise ValueError(
                f"coding {self.k}+{self.m}: k+m blocks must fit the "
                f"{NUM_TAGS} distinct placement tags")

    @property
    def blocks(self) -> int:
        return self.k + self.m

    def __str__(self) -> str:
        return f"{self.k}+{self.m}"


_CODING_RE = re.compile(r"^\s*(\d+)\s*\+\s*(\d+)\s*$")

Redundancy = Union[int, Coding]


def parse_coding(spec) -> Coding:
    """``"4+1"`` → Coding(4, 1); a Coding passes through."""
    if isinstance(spec, Coding):
        return spec
    m = _CODING_RE.match(str(spec))
    if not m:
        raise ValueError(f"coding spec {spec!r} is not of the form 'k+m' "
                         "(e.g. '4+1')")
    return Coding(int(m.group(1)), int(m.group(2)))


def check_redundancy(value) -> Redundancy:
    """Validate the unified redundancy knob: an int replication factor
    (or int-string), a ``"k+m"`` coding spec, or a Coding. None means
    off (1)."""
    if value is None:
        return 1
    if isinstance(value, Coding):
        return value
    if isinstance(value, str) and "+" in value:
        return parse_coding(value)
    return check_replication(value)


def redundancy_on(value) -> bool:
    """True when the redundancy layer is active — coding of any shape,
    or replication > 1 (the engines' gate for scavenger probes and
    lost-data escalation)."""
    red = check_redundancy(value)
    return isinstance(red, Coding) or red > 1


def resolve_redundancy(replication=None, coding=None) -> Redundancy:
    """Server/LocalExecutor shared knob resolution: explicit ``coding``
    argument, else ``LMR_CODING``, else the replication knob (explicit,
    else ``LMR_REPLICATION``, else 1/off). Turning BOTH modes on is
    rejected loudly — they are alternative answers to the same
    durability question, and silently preferring one would make two
    deployments with the same env disagree on the data-plane layout."""
    c = parse_coding(coding) if coding else None
    if c is None:
        env = os.environ.get("LMR_CODING")
        c = parse_coding(env) if env else None
    r = check_redundancy(replication) if replication is not None else None
    if isinstance(r, Coding) and c is None:
        c, r = r, None
    if c is not None:
        if r is not None and r != 1 and r != c:
            raise ValueError(
                f"coding {c} and replication {r} are mutually exclusive "
                "redundancy modes — configure exactly one")
        return c
    return resolve_replication(replication)


def doc_fields(red) -> dict:
    """The task-document encoding of the unified redundancy value —
    JSON-safe (a Coding cannot land in the doc raw): the int
    replication factor plus a ``"coding"`` spec string (empty when
    off). :func:`doc_redundancy` is the decoder."""
    red = check_redundancy(red)
    if isinstance(red, Coding):
        return {"replication": 1, "coding": str(red)}
    return {"replication": red, "coding": ""}


def doc_redundancy(doc, default=1) -> Redundancy:
    """The redundancy a task document deploys: a non-empty ``coding``
    spec wins, else the doc's ``replication``, else ``default`` (the
    follower's own resolved value — docs predating either key must not
    silently turn redundancy off on resume)."""
    doc = doc or {}
    c = doc.get("coding")
    if c:
        return parse_coding(c)
    return check_redundancy(doc.get("replication", default) or 1)


# --------------------------------------------------------------------------
# stripe naming (the ^-sigil grammar — constructed HERE only, LMR012)
# --------------------------------------------------------------------------


def block_names(name: str, coding: Coding) -> List[str]:
    """The k+m physical block names of ``name``'s stripe, data first."""
    pt = primary_tag(name)
    return [f"^{i}.{(pt + i) % NUM_TAGS}^{name}"
            for i in range(coding.blocks)]


def manifest_name(name: str) -> str:
    return f"^M^{name}"


def manifest_copies(name: str, coding: Coding) -> List[str]:
    """The m+1 copy names of ``name``'s stripe manifest — replicated on
    distinct tags so any m tag losses leave one readable (the manifest
    is tiny; replicating it costs bytes the parity math can't save)."""
    return replica_names(manifest_name(name), coding.m + 1)


def manifest_pattern(pattern: str) -> str:
    """The glob matching the primary manifest of every logical name
    matching ``pattern``."""
    return f"^M^{pattern}"


def stripe_patterns(pattern: str) -> List[str]:
    """Globs matching EVERY physical stripe file of every logical name
    matching ``pattern`` — blocks + primary manifests (both carry the
    ``^..^`` wrap) and replica manifest copies. Sweeps pair these with
    the plain pattern."""
    return [f"^*^{pattern}", replica_pattern(manifest_pattern(pattern))]


# --------------------------------------------------------------------------
# write side: stripe publish
# --------------------------------------------------------------------------


def publish_stripe(store: Store, members: Sequence[Tuple[str, bytes]],
                   coding: Coding, group_base: Optional[str] = None) -> int:
    """Stripe the concatenated ``members`` payloads into k+m blocks
    named from ``group_base`` (default: the single member's own name)
    and publish each member's manifest LAST — the visibility gate: a
    producer killed anywhere before its manifest build leaves an
    invisible partial stripe, never a readable torn one.

    Returns the bytes published. Telemetry mirrors _TeeBuilder's
    honest-overhead split: the logical payload once
    (``spill_bytes_primary``), everything beyond it — parity blocks,
    padding, manifests — as ``spill_bytes_parity``.
    """
    if not members:
        raise ValueError("publish_stripe: no members")
    if group_base is None:
        if len(members) != 1:
            raise ValueError("multi-member stripes need a group_base name")
        group_base = members[0][0]
    payload = b"".join(p for _, p in members)
    total = len(payload)
    k, m = coding.k, coding.m
    blen = max(1, -(-total // k))
    data = [payload[i * blen:(i + 1) * blen].ljust(blen, b"\0")
            for i in range(k)]
    blocks = data + rs_parity(data, m)
    names = block_names(group_base, coding)
    published = 0
    for bname, blob in zip(names, blocks):
        with store.builder() as b:
            b.write_bytes(blob)
            b.build(bname)
        published += len(blob)
    bcrc = [_crc(blob) for blob in blocks]
    shared = len(members) > 1
    off = 0
    for lname, p in members:
        doc = {"v": 1, "k": k, "m": m, "blen": blen, "total": total,
               "off": off, "len": len(p), "crc": _crc(p),
               "blocks": names, "bcrc": bcrc, "shared": shared}
        raw = (json.dumps(doc, separators=(",", ":"), sort_keys=True)
               + "\n").encode("utf-8")
        for cname in manifest_copies(lname, coding):
            with store.builder() as b:
                b.write_bytes(raw)
                b.build(cname)
            published += len(raw)
        off += len(p)
    COUNTERS.bump("spill_bytes_primary", total)
    COUNTERS.bump("spill_bytes_parity", published - total)
    return published


class _StripeBuilder(FileBuilder):
    """spill_writer's coded twin of _TeeBuilder: accumulate the chunks,
    stripe on ``build``. The whole payload is held in memory until the
    publish — bounded by the frame size in push mode (the perf path)
    and by one map job's partition output when staged; the push
    engine's eviction tail stays on streaming (m+1)-way replication
    (see tail_redundancy) precisely because it exists to bound memory."""

    def __init__(self, store: Store, coding: Coding):
        self._store = store
        self._coding = coding
        self._chunks: List[Union[str, bytes]] = []

    def write(self, data: str) -> None:
        self._chunks.append(data)

    def write_bytes(self, data: bytes) -> None:
        self._chunks.append(data)

    def build(self, name: str) -> None:
        payload = encode_chunks(self._chunks)
        self._chunks = []
        publish_stripe(self._store, [(name, payload)], self._coding)

    def close(self) -> None:
        self._chunks = []


def stripe_builder(store: Store, coding: Coding) -> FileBuilder:
    """The builder spill_writer wraps for ``coding="k+m"`` publishes."""
    return _StripeBuilder(store, coding)


def tail_redundancy(red: Redundancy) -> int:
    """What the push engine's memory-pressure eviction tail degrades
    to: coded mode falls back to (m+1)-way streaming replication (same
    loss tolerance, no payload buffering — the tail exists to BOUND
    memory), plain replication keeps its own r."""
    red = check_redundancy(red)
    return red.m + 1 if isinstance(red, Coding) else red


class _CaptureBuilder(FileBuilder):
    def __init__(self, store: "CaptureStore"):
        self._store = store
        self._chunks: List[Union[str, bytes]] = []

    def write(self, data: str) -> None:
        self._chunks.append(data)

    def write_bytes(self, data: bytes) -> None:
        self._chunks.append(data)

    def build(self, name: str) -> None:
        self._store.files.append((name, encode_chunks(self._chunks)))
        self._chunks = []

    def close(self) -> None:
        self._chunks = []


class CaptureStore(Store):
    """In-memory single-shot capture target: the push engine serializes
    each group-stripe member through the NORMAL spill_writer path into
    one of these, then hands the captured (name, payload) list to
    :func:`publish_stripe` — group assembly without a parallel
    serialization code path."""

    publish_ambiguous = False

    def __init__(self):
        self.files: List[Tuple[str, bytes]] = []

    def builder(self) -> FileBuilder:
        return _CaptureBuilder(self)

    def _blob(self, name: str) -> bytes:
        for n, b in self.files:
            if n == name:
                return b
        raise FileNotFoundError(name)

    def lines(self, name: str) -> Iterator[str]:
        data = self._blob(name)
        try:
            text = data.decode("utf-8")
        except UnicodeDecodeError:
            text = data.decode("latin-1")
        yield from text.splitlines(keepends=True)

    def list(self, pattern: str) -> List[str]:
        return self._match([n for n, _ in self.files], pattern)

    def exists(self, name: str) -> bool:
        return any(n == name for n, _ in self.files)

    def remove(self, name: str) -> None:
        self.files = [(n, b) for n, b in self.files if n != name]

    def read_range(self, name: str, offset: int, length: int) -> bytes:
        return self._blob(name)[offset:offset + length]

    def size(self, name: str) -> int:
        return len(self._blob(name))


# --------------------------------------------------------------------------
# read side: decode-from-survivors view
# --------------------------------------------------------------------------


class _BadBlock(Exception):
    """Internal: a block read that is present but wrong (short read or
    CRC mismatch) — handled exactly like a lost block, never escapes."""


_PAYLOAD_CACHE_BYTES = 64 << 20


class CodedStore(Store):
    """The coded reading view (reading_view's Coding branch): ops
    address LOGICAL names, served by reassembling the stripe behind
    each manifest; names without a manifest pass through untouched
    (plain result files, pre-coding leftovers).

    The systematic fast path reads the k data blocks and concatenates —
    no GF math when the stripe is healthy. A classified storage fault
    or a per-block CRC mismatch flips the name to decode-from-survivors
    (any k of the k+m blocks), counted ``decode_reads`` +
    ``map_reruns_avoided`` once per name; below k readable blocks the
    classified-transient :class:`LostShuffleDataError` escapes and the
    scavenger/map-rerun ladder takes over, exactly like replication's
    total-copy loss. Decoded group payloads are cached (bounded, keyed
    by block set) so the k members of a group stripe don't re-read the
    shared blocks k times — the segment reader's many ranged reads per
    file lean on this the way they lean on ReplicatedStore's redirect
    cache. Like every reading view, only the portable Store surface is
    exposed: native fast paths (``local_path``) cannot bypass decode."""

    def __init__(self, inner: Store, coding: Coding):
        from lua_mapreduce_tpu.faults.replicate import ReplicatedStore
        self._inner = inner
        self._coding = parse_coding(coding)
        self._lock = threading.Lock()
        self._manifests: Dict[str, dict] = {}
        self._payloads: "Dict[Tuple[str, ...], bytes]" = {}
        self._payload_bytes = 0
        self._counted = set()
        # names WITHOUT a stripe manifest pass through a failover view
        # at the tail factor: the push engine's eviction tails stream at
        # (m+1)-way replication (tail_redundancy — striping would buffer
        # the payload the tail exists not to hold), and the coded view
        # must still serve them with every primary destroyed. Plain
        # unreplicated files are served identically (their copy 0 IS
        # the plain name).
        self._plain = ReplicatedStore(inner, tail_redundancy(self._coding))

    # -- stripe core --------------------------------------------------------

    def _manifest(self, name: str) -> Optional[dict]:
        """The stripe manifest behind logical ``name`` from any
        readable copy, positively cached (manifests are immutable once
        published); None when no copy EXISTS — the passthrough verdict.
        Copies that exist but stay unreadable raise the lost-data
        escalation rather than silently passing through to a plain
        name that was never published."""
        with self._lock:
            man = self._manifests.get(name)
        if man is not None:
            return man
        classify = _classifier(self._inner)
        copies = manifest_copies(name, self._coding)
        seen, last = False, None
        for cname in copies:
            try:
                if not self._inner.exists(cname):
                    continue
                seen = True
                raw = self._inner.read_range(cname, 0,
                                             self._inner.size(cname))
                man = json.loads(raw.decode("utf-8"))
            except Exception as exc:
                if classify(exc) is None:
                    raise
                last = exc
                continue
            with self._lock:
                self._manifests[name] = man
            return man
        if seen:
            raise LostShuffleDataError(
                f"manifest({name!r}): stripe manifest exists but no copy "
                f"is readable (last: {type(last).__name__}: {last})",
                op="manifest", name=name, files=[name]) from last
        return None

    def _read_block(self, bname: str, blen: int, crc: int) -> bytes:
        blob = self._inner.read_range(bname, 0, blen)
        if len(blob) != blen or _crc(blob) != crc:
            raise _BadBlock(bname)
        return blob

    def _group_payload(self, name: str, man: dict) -> bytes:
        """The decoded full-group payload behind ``man`` (truncated to
        ``total``); member windows are sliced by the caller."""
        key = tuple(man["blocks"])
        with self._lock:
            whole = self._payloads.get(key)
        if whole is not None:
            return whole
        classify = _classifier(self._inner)
        k, blen = man["k"], man["blen"]
        names, bcrc = man["blocks"], man["bcrc"]
        have: Dict[int, bytes] = {}
        degraded = False
        for i in range(len(names)):
            if i >= k and len(have) >= k:
                break               # enough survivors; skip spare parity
            try:
                have[i] = self._read_block(names[i], blen, bcrc[i])
            except Exception as exc:
                if not isinstance(exc, _BadBlock) and classify(exc) is None:
                    raise
                if i < k:
                    degraded = True  # a data block needs reconstruction
        if len(have) < k:
            raise LostShuffleDataError(
                f"stripe({name!r}): only {len(have)} of {len(names)} "
                f"blocks readable, {k} needed to decode — scavenger "
                "repair or map re-run required", op="stripe", name=name,
                files=[name])
        if degraded:
            missing = [i for i in range(k) if i not in have]
            have.update(rs_reconstruct(have, missing, k))
            if name not in self._counted:
                self._counted.add(name)
                COUNTERS.bump("decode_reads")
                COUNTERS.bump("map_reruns_avoided")
        whole = b"".join(have[i] for i in range(k))[:man["total"]]
        with self._lock:
            if key not in self._payloads:
                # bounded: evict whole entries FIFO past the cap (the
                # access pattern is one file read to completion, then
                # the next — LRU precision buys nothing here)
                while (self._payloads and
                       self._payload_bytes + len(whole)
                       > _PAYLOAD_CACHE_BYTES):
                    _, old = self._payloads.popitem()
                    self._payload_bytes -= len(old)
                self._payloads[key] = whole
                self._payload_bytes += len(whole)
        return whole

    def _payload(self, name: str, man: dict) -> bytes:
        whole = self._group_payload(name, man)
        payload = whole[man["off"]:man["off"] + man["len"]]
        if _crc(payload) != man["crc"]:
            raise LostShuffleDataError(
                f"stripe({name!r}): decoded payload fails its manifest "
                "CRC — corruption beyond the parity budget", op="stripe",
                name=name, files=[name])
        return payload

    # -- portable surface ---------------------------------------------------

    def builder(self) -> FileBuilder:
        return self._inner.builder()

    def lines(self, name: str) -> Iterator[str]:
        man = self._manifest(name)
        if man is None:
            yield from self._plain.lines(name)
            return
        data = self._payload(name, man)
        try:
            text = data.decode("utf-8")
        except UnicodeDecodeError:
            text = data.decode("latin-1")
        yield from text.splitlines(keepends=True)

    def read_range(self, name: str, offset: int, length: int) -> bytes:
        man = self._manifest(name)
        if man is None:
            return self._plain.read_range(name, offset, length)
        return self._payload(name, man)[offset:offset + length]

    def size(self, name: str) -> int:
        man = self._manifest(name)
        if man is None:
            return self._plain.size(name)
        return man["len"]

    def exists(self, name: str) -> bool:
        classify = _classifier(self._inner)
        if self._plain.exists(name):        # plain name or a tail replica
            return True
        for cname in manifest_copies(name, self._coding):
            try:
                if self._inner.exists(cname):
                    return True
            except Exception as exc:
                if classify(exc) is None:
                    raise
        return False

    def list(self, pattern: str) -> List[str]:
        from lua_mapreduce_tpu.engine.placement import base_name
        names = set(self._inner.list(pattern))
        # stripes are visible at their LOGICAL name while any manifest
        # copy survives — discovery and the reduce pull-integrity check
        # must not report a decodable file as missing; same for a
        # replicated tail whose primary is gone
        for n in self._inner.list(manifest_pattern(pattern)):
            names.add(base_name(n))
        for n in self._inner.list(
                replica_pattern(manifest_pattern(pattern))):
            names.add(base_name(n))
        for n in self._inner.list(replica_pattern(pattern)):
            names.add(base_name(n))
        return sorted(names)

    def remove(self, name: str) -> None:
        # best-effort fanout sweep, classified faults swallowed, like
        # ReplicatedStore.remove; SHARED group blocks outlive any one
        # member (the other members still window into them) and are
        # swept by the namespace-level stripe_patterns cleanup instead
        classify = _classifier(self._inner)
        try:
            man = self._manifest(name)
        except LostShuffleDataError:
            man = None
        self._plain.remove(name)    # plain copy + any tail replicas
        targets = manifest_copies(name, self._coding)
        if man is not None and not man.get("shared"):
            targets += list(man["blocks"])
        for t in targets:
            try:
                self._inner.remove(t)
            except Exception as exc:
                if classify(exc) is None:
                    raise
        with self._lock:
            self._manifests.pop(name, None)

    def classify(self, exc: BaseException):
        return self._inner.classify(exc)


# --------------------------------------------------------------------------
# scavenger reconstruction
# --------------------------------------------------------------------------


def repair_stripe(store: Store, name: str, coding: Coding) -> str:
    """Restore ``name``'s stripe to full k+m blocks + m+1 manifest
    copies from any ≥ k readable blocks — the scavenger's repair rung,
    same verdict contract as replicate.repair: ``"intact"`` (nothing to
    do), ``"repaired"`` (blocks/manifest copies rebuilt, counted
    ``stripe_repairs`` + ``map_reruns_avoided``), ``"degraded"``
    (decodable but every rebuild write failed — inline decode keeps
    serving reads), ``"lost"`` (below k readable blocks, or the
    manifest itself unrecoverable — only then does the caller escalate
    to the map re-run). ``store`` is the plain wrapped store; corrupt
    blocks (CRC mismatch) are treated as lost blocks. Idempotent per
    stripe, so the members of a shared group stripe can each be
    reported lost and repaired once."""
    coding = parse_coding(coding)
    classify = _classifier(store)
    copies = manifest_copies(name, coding)
    raw_man, man = None, None
    missing_copies = []
    for cname in copies:
        try:
            if not store.exists(cname):
                missing_copies.append(cname)
                continue
            raw = store.read_range(cname, 0, store.size(cname))
            doc = json.loads(raw.decode("utf-8"))
        except Exception as exc:
            if classify(exc) is None:
                raise
            missing_copies.append(cname)
            continue
        if man is None:
            raw_man, man = raw, doc
    if man is None:
        # no readable manifest: a readable plain passthrough file is
        # intact; a name with surviving REPLICA copies is a push
        # eviction tail (streamed at tail_redundancy, never striped) —
        # the replica repair rung recovers it; a stripe whose every
        # manifest copy is gone is unrecoverable (the block set is
        # unknowable for group stripes), as is a genuinely absent name
        try:
            if store.exists(name):
                return "intact"
        except Exception as exc:
            if classify(exc) is None:
                raise
        from lua_mapreduce_tpu.faults.replicate import repair as _rrepair
        return _rrepair(store, name, tail_redundancy(coding))
    k = man["k"]
    names, bcrc, blen = man["blocks"], man["bcrc"], man["blen"]
    have: Dict[int, bytes] = {}
    broken: List[int] = []
    for i, bname in enumerate(names):
        try:
            blob = store.read_range(bname, 0, blen)
            if len(blob) != blen or _crc(blob) != bcrc[i]:
                raise _BadBlock(bname)
            have[i] = blob
        except Exception as exc:
            if not isinstance(exc, _BadBlock) and classify(exc) is None:
                raise
            broken.append(i)
    if len(have) < k:
        return "lost"
    if not broken and not missing_copies:
        return "intact"
    rebuilt = 0
    if broken:
        for i, blob in rs_reconstruct(have, broken, k).items():
            try:
                with store.builder() as b:
                    b.write_bytes(blob)
                    b.build(names[i])
                rebuilt += 1
            except Exception as exc:
                if classify(exc) is None:
                    raise
                # target still dark: partial repair, reads keep decoding
    for cname in missing_copies:
        try:
            with store.builder() as b:
                b.write_bytes(raw_man)
                b.build(cname)
            rebuilt += 1
        except Exception as exc:
            if classify(exc) is None:
                raise
    if rebuilt:
        COUNTERS.bump("stripe_repairs")
        COUNTERS.bump("map_reruns_avoided")
        return "repaired"
    return "degraded"


def utest() -> None:
    """Self-test: GF identities, encode/decode under every erasure
    pattern (numpy and pure-Python paths agreeing), the knob grammar,
    stripe naming/placement/glob transparency, publish + CodedStore
    round-trips with loss/corruption, the manifest visibility gate,
    group stripes, and repair_stripe's verdict ladder."""
    import fnmatch
    import itertools
    global _FORCE_PYTHON
    from lua_mapreduce_tpu.engine.placement import base_name, tag_of
    from lua_mapreduce_tpu.store.memfs import MemStore

    # GF(256): inverses, distributivity spot checks, table sanity
    for a in (1, 2, 7, 93, 255):
        assert _gf_mul(a, _gf_inv(a)) == 1
    assert _gf_mul(0, 55) == 0 and _gf_mul(1, 55) == 55

    # RS: every ≤m erasure pattern reconstructs, both codec paths
    payload = bytes((i * 37 + (i >> 3)) % 256 for i in range(997))
    for k, m in ((4, 1), (4, 2), (2, 1), (5, 3)):
        blen = -(-len(payload) // k)
        data = [payload[i * blen:(i + 1) * blen].ljust(blen, b"\0")
                for i in range(k)]
        for force in (False, True):
            _FORCE_PYTHON = force
            try:
                parity = rs_parity(data, m)
                blocks = data + parity
                for lost in itertools.combinations(range(k + m), m):
                    have = {i: b for i, b in enumerate(blocks)
                            if i not in lost}
                    got = rs_reconstruct(have, list(lost), k)
                    assert all(got[i] == blocks[i] for i in lost)
            finally:
                _FORCE_PYTHON = False

    # knob grammar: parse/validate/resolve, replication interop
    assert parse_coding("4+1") == Coding(4, 1) and str(Coding(4, 2)) == "4+2"
    assert check_redundancy("4+1") == Coding(4, 1)
    assert check_redundancy(3) == 3 and check_redundancy(None) == 1
    assert redundancy_on(Coding(4, 1)) and redundancy_on(2)
    assert not redundancy_on(1) and not redundancy_on(None)
    assert tail_redundancy(Coding(4, 2)) == 3 and tail_redundancy(3) == 3
    for bad in ("4", "4-1", "1+1", "4+0", "7+2"):
        try:
            check_redundancy(bad) if "+" in bad else parse_coding(bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"coding {bad!r} must be rejected")
    try:
        resolve_redundancy(replication=2, coding="4+1")
    except ValueError:
        pass
    else:
        raise AssertionError("both redundancy modes on must be rejected")
    assert resolve_redundancy(replication="4+1") == Coding(4, 1)
    assert resolve_redundancy(replication=2) == 2

    # naming: k+m distinct tags, parse round-trip, glob transparency
    c41 = Coding(4, 1)
    lname = "result.P3.SPILL-00001-00002"
    bn = block_names(lname, c41)
    assert len({tag_of(n) for n in bn}) == c41.blocks
    assert all(base_name(n) == lname for n in bn)
    mans = manifest_copies(lname, c41)
    assert len(mans) == c41.m + 1
    assert len({tag_of(n) for n in mans}) == c41.m + 1
    assert all(base_name(n) == lname for n in mans)
    for phys in bn + mans:
        assert not fnmatch.fnmatchcase(phys, "result.P*")   # invisible
    assert any(fnmatch.fnmatchcase(n, stripe_patterns("result.P*")[0])
               for n in bn + mans[:1])
    assert fnmatch.fnmatchcase(mans[1], stripe_patterns("result.P*")[1])

    # publish + read round-trip; loss of any m blocks decodes inline
    raw = MemStore()
    publish_stripe(raw, [(lname, payload)], c41)
    view = CodedStore(raw, c41)
    assert view.exists(lname) and view.size(lname) == len(payload)
    assert view.read_range(lname, 0, 10 ** 9) == payload
    assert view.list("result.P*") == [lname]
    before = COUNTERS.snapshot().get("decode_reads", 0)
    raw._files.pop(bn[0])                       # lose a data block
    fresh = CodedStore(raw, c41)
    assert fresh.read_range(lname, 5, 17) == payload[5:22]
    assert COUNTERS.snapshot()["decode_reads"] == before + 1
    assert fresh.read_range(lname, 0, 99) == payload[:99]   # counted once
    assert COUNTERS.snapshot()["decode_reads"] == before + 1

    # scavenger: repair rebuilds the lost data block; a corrupted
    # PARITY block (CRC mismatch == lost block) is rebuilt the same way
    assert repair_stripe(raw, lname, c41) == "repaired"
    raw._files[bn[4]] = b"garbage-not-parity"
    assert repair_stripe(raw, lname, c41) == "repaired"
    assert repair_stripe(raw, lname, c41) == "intact"
    assert CodedStore(raw, c41).read_range(lname, 0, 10 ** 9) == payload

    # below k survivors: reads raise the classified transient, repair
    # says lost — the map-rerun last resort
    for n in bn[:2]:
        raw._files.pop(n)
    try:
        CodedStore(raw, c41).read_range(lname, 0, 8)
    except LostShuffleDataError as e:
        assert e.transient and e.lost_files == [lname]
    else:
        raise AssertionError("sub-k survivors must raise lost-data")
    assert repair_stripe(raw, lname, c41) == "lost"

    # manifest gate: blocks without a manifest are INVISIBLE (the
    # SIGKILL-mid-stripe shape) — and a manifest with every copy gone
    # while blocks survive is also correctly not resurrectable
    raw2 = MemStore()
    half = "ns.P0.INBOX-00000001-00000"
    for bname2, blob in zip(block_names(half, c41), [b"x" * 8] * 5):
        with raw2.builder() as b:
            b.write_bytes(blob)
            b.build(bname2)
    gate = CodedStore(raw2, c41)
    assert not gate.exists(half)
    assert gate.list("ns.P0.INBOX-*") == []
    publish_stripe(raw2, [(half, b"whole")], c41)     # re-publish wins
    assert CodedStore(raw2, c41).read_range(half, 0, 99) == b"whole"

    # group stripe: members share one block set; each member windows
    # its own slice; removing one member leaves the others readable
    raw3 = MemStore()
    members = [(f"gns.P{i}.INBOX-00000007-00000",
                bytes((i + 1) * j % 256 for j in range(200 + 31 * i)))
               for i in range(3)]
    publish_stripe(raw3, members, c41, group_base="gns.CODE.00000007")
    gview = CodedStore(raw3, c41)
    for mname, mpay in members:
        assert gview.read_range(mname, 0, 10 ** 9) == mpay
        assert gview.size(mname) == len(mpay)
    gview.remove(members[0][0])
    gv2 = CodedStore(raw3, c41)
    assert not gv2.exists(members[0][0])
    assert gv2.read_range(members[1][0], 0, 10 ** 9) == members[1][1]
    # shared-member repair is idempotent across members
    blocks3 = block_names("gns.CODE.00000007", c41)
    raw3._files.pop(blocks3[1])
    assert repair_stripe(raw3, members[1][0], c41) == "repaired"
    assert repair_stripe(raw3, members[2][0], c41) == "intact"

    # passthrough: plain files below the view are untouched
    with raw3.builder() as b:
        b.write("plain\n")
        b.build("gns.P9.plainfile")
    assert list(gview.lines("gns.P9.plainfile")) == ["plain\n"]
    assert repair_stripe(raw3, "gns.P9.plainfile", c41) == "intact"
    assert not hasattr(gview, "local_path")

    # eviction tails ride (m+1)-way replication under coding (they
    # exist to bound memory — striping would buffer the payload): the
    # coded view fails over to a tail replica with the primary gone,
    # lists/serves the logical name, and the repair rung rebuilds it
    from lua_mapreduce_tpu.faults.replicate import spill_writer
    tname = "gns.P4.INBOX-00000009-00001T"
    with spill_writer(raw3, "v1", tail_redundancy(c41)) as tw:
        tw.add("tk", [7])
        tw.build(tname)
    raw3._files.pop(tname)                       # primary destroyed
    tv = CodedStore(raw3, c41)
    assert tv.exists(tname)
    assert tname in tv.list("gns.P4.INBOX-*")
    assert list(tv.lines(tname)) == ['["tk",[7]]\n']
    assert repair_stripe(raw3, tname, c41) == "repaired"
    assert raw3.exists(tname)
    tv.remove(tname)                             # fans to tail replicas
    assert raw3.list(replica_pattern("gns.P4.INBOX-*")) == []
