"""RetryPolicy — capped decorrelated-jitter backoff for store/coord ops.

The schedule is AWS-style decorrelated jitter (each delay drawn uniform
from [base, 3 * previous], capped), which spreads a thundering herd of
workers re-hitting a recovering store better than fixed exponential
steps. ``clock``/``sleep``/``rng`` are injectable so the whole fault
suite runs on a VIRTUAL clock — no wall-clock reads sneak into locked
regions (the LMR004 contract), and tests of 10-retry bursts finish in
microseconds.

Every retry event lands in the process-global :class:`FaultCounters`
(one instance, shared like JobStore's round counters) so the server can
fold per-iteration deltas into IterationStats.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Dict, Optional

from lua_mapreduce_tpu.faults.errors import (TransientStoreError,
                                             classify_exception)

_log = logging.getLogger(__name__)

DEFAULT_RETRIES = 3          # extra attempts after the first
DEFAULT_BASE_MS = 25.0       # first backoff draw's lower bound
DEFAULT_CAP_MS = 2000.0      # no single sleep beyond this


class FaultCounters:
    """Process-global fault/retry/degradation accounting.

    In-process pools share the module singleton (:data:`COUNTERS`), so a
    server's IterationStats fold sees the whole pool's retry traffic —
    the same visibility contract as JobStore.round_counts. Increments
    happen only on fault events (never on the hot fault-free path), so
    the lock is uncontended in healthy runs.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._c[key] = self._c.get(key, 0) + n

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)

    @staticmethod
    def delta(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
        return {k: after.get(k, 0) - before.get(k, 0)
                for k in set(before) | set(after)}


COUNTERS = FaultCounters()

# counter keys (shared vocabulary between retry layer, wrappers, stats):
#   retries            — sleeps taken before a retry attempt
#   retry_exhausted    — transient bursts that outlived the budget
#   faults_injected    — FaultPlan decisions that fired
#   infra_releases     — jobs released WAITING on transient infra faults
#   degraded_reads     — ranged-read fallbacks to a whole-file read
#   build_verified     — ambiguous builds resolved by readback-verify


class RetryPolicy:
    """Bounded transient-fault retry with decorrelated-jitter backoff.

    ``retries`` is the number of RE-attempts after the first try (0
    disables retrying entirely — the wrapper layer then strips to a
    passthrough). ``classify`` defaults to the central taxonomy; store
    wrappers pass the backend's own ``classify`` hook.
    """

    def __init__(self, retries: int = DEFAULT_RETRIES,
                 base_ms: float = DEFAULT_BASE_MS,
                 cap_ms: float = DEFAULT_CAP_MS,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None,
                 counters: FaultCounters = COUNTERS):
        self.retries = max(0, int(retries))
        self.base_s = max(0.0, float(base_ms)) / 1000.0
        self.cap_s = max(self.base_s, float(cap_ms) / 1000.0)
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()
        self.counters = counters

    def backoff_s(self, prev_s: float) -> float:
        """Next decorrelated-jitter delay: uniform in [base, 3*prev],
        capped. ``prev_s`` <= 0 means first retry (draw near base)."""
        hi = max(self.base_s, 3.0 * prev_s)
        return min(self.cap_s, self._rng.uniform(self.base_s, hi))

    def call(self, fn: Callable, *, op: str = "?", name: str = "?",
             classify: Callable = classify_exception,
             before_retry: Optional[Callable[[BaseException], bool]] = None):
        """Run ``fn()`` retrying transient faults up to the budget.

        - transient (classify → True): sleep a jittered backoff, retry;
          on exhaustion raise :class:`TransientStoreError` chaining the
          last fault (op/name/attempts recorded).
        - permanent (False) or unclassified (None): propagate RAW,
          immediately — wrapping would hide the type callers catch.

        ``before_retry(exc)``, when given, runs before each sleep; if it
        returns True the op is considered RESOLVED (the build-ambiguity
        readback-verify hook) and ``call`` returns None without
        retrying.
        """
        delay = 0.0
        for attempt in range(self.retries + 1):
            try:
                return fn()
            except Exception as exc:
                if classify(exc) is not True:
                    raise
                if before_retry is not None and before_retry(exc):
                    return None
                if attempt >= self.retries:
                    self.counters.bump("retry_exhausted")
                    raise TransientStoreError(
                        f"{op}({name!r}) still failing after "
                        f"{attempt + 1} attempt(s): "
                        f"{type(exc).__name__}: {exc}",
                        op=op, name=name, attempts=attempt + 1) from exc
                delay = self.backoff_s(delay)
                self.counters.bump("retries")
                _log.warning("store %s(%r): transient %s: %s — retry "
                             "%d/%d in %.0fms", op, name,
                             type(exc).__name__, exc, attempt + 1,
                             self.retries, delay * 1000.0)
                self._sleep(delay)


# -- process-global default policy (CLI knobs / env) ------------------------
#
# Engines build their store/jobstore wrappers through configure_retry()'s
# values; subprocess pools (multiprocess churn tests, CLI fleets) inherit
# them via LMR_STORE_RETRIES / LMR_RETRY_BASE_MS. A config *generation*
# token lets caches (router's wrapped mem:tag stores) invalidate when a
# test or CLI flips the knobs mid-process.

_config_lock = threading.Lock()
_config = {"retries": None, "base_ms": None, "generation": 0}


def configure_retry(retries: Optional[int] = None,
                    base_ms: Optional[float] = None) -> None:
    """Set the process-wide retry defaults (None = back to env/default).
    The CLI's ``--store-retries`` / ``--retry-base-ms`` land here."""
    with _config_lock:
        _config["retries"] = retries
        _config["base_ms"] = base_ms
        _config["generation"] += 1


def retry_settings() -> Dict[str, float]:
    """Effective (retries, base_ms): configure_retry() wins, then the
    LMR_STORE_RETRIES / LMR_RETRY_BASE_MS environment, then defaults.
    A SET-but-malformed env value is rejected loudly (the FaultPlan
    spec-parsing rule: a typo must not silently run with defaults)."""
    import os

    def _env(var, convert, default):
        raw = os.environ.get(var)
        if raw is None or raw == "":
            return default
        try:
            return convert(raw)
        except ValueError:
            raise ValueError(f"bad {var}={raw!r}: expected "
                             f"{convert.__name__}") from None

    with _config_lock:
        retries, base_ms = _config["retries"], _config["base_ms"]
    if retries is None:
        retries = _env("LMR_STORE_RETRIES", int, DEFAULT_RETRIES)
    if base_ms is None:
        base_ms = _env("LMR_RETRY_BASE_MS", float, DEFAULT_BASE_MS)
    return {"retries": retries, "base_ms": base_ms}


def config_generation() -> int:
    with _config_lock:
        return _config["generation"]


def default_policy() -> RetryPolicy:
    s = retry_settings()
    return RetryPolicy(retries=int(s["retries"]), base_ms=s["base_ms"])


def utest() -> None:
    """Self-test: virtual-clock schedule, classification routing, the
    readback-verify hook, counters."""
    sleeps = []
    counters = FaultCounters()
    policy = RetryPolicy(retries=3, base_ms=10, cap_ms=50,
                         sleep=sleeps.append, clock=lambda: 0.0,
                         rng=random.Random(7), counters=counters)

    # transient burst shorter than the budget: absorbed
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise TimeoutError("blip")
        return "ok"

    assert policy.call(flaky, op="read_range", name="f") == "ok"
    assert calls[0] == 3 and len(sleeps) == 2
    assert all(0.01 <= s <= 0.05 for s in sleeps)
    assert counters.snapshot()["retries"] == 2

    # exhaustion wraps in TransientStoreError, chains the cause
    def always():
        raise ConnectionResetError("down")

    try:
        policy.call(always, op="lines", name="g")
    except TransientStoreError as e:
        assert e.attempts == 4 and e.op == "lines"
        assert isinstance(e.__cause__, ConnectionResetError)
    else:
        raise AssertionError("exhausted burst must raise")
    assert counters.snapshot()["retry_exhausted"] == 1

    # permanent and unclassified propagate raw, no sleeps
    n0 = len(sleeps)
    for exc in (FileNotFoundError("x"), ValueError("user")):
        def bad(exc=exc):
            raise exc
        try:
            policy.call(bad, op="size", name="h")
        except type(exc):
            pass
        else:
            raise AssertionError("must propagate raw")
    assert len(sleeps) == n0

    # before_retry resolving the ambiguity short-circuits the retry
    def ambiguous():
        raise TimeoutError("did it land?")

    assert policy.call(ambiguous, op="build", name="s",
                       before_retry=lambda e: True) is None
    assert len(sleeps) == n0

    # decorrelated jitter grows from base toward the cap
    p = RetryPolicy(base_ms=10, cap_ms=80, rng=random.Random(0))
    d = 0.0
    for _ in range(50):
        d = p.backoff_s(d)
        assert 0.01 <= d <= 0.08
    assert retry_settings()["retries"] >= 0
    configure_retry(7, 5.0)
    try:
        assert retry_settings() == {"retries": 7, "base_ms": 5.0}
    finally:
        configure_retry(None, None)
