"""Replica-aware shuffle data plane: r-way publish, failover reads, repair.

Coded MapReduce's trade (PAPERS.md): spend shuffle bytes to buy
recovery latency. Three pieces implement it over any Store backend,
addressed by the deterministic placement function (engine/placement.py):

- :func:`spill_writer` — the replicated twin of
  ``core.segment.writer_for``: every spill producer in engine/ goes
  through it (lint rule LMR009), and with ``replication > 1`` the
  returned writer TEES each chunk into ``r`` builders and publishes the
  primary plus ``r−1`` replica copies (primary first, so a crash
  mid-fanout leaves a readable primary and merely under-replicates).
  No read-back: the copies are fanned from the in-flight chunks, so a
  store whose reads are already failing can still publish whole.

- :class:`ReplicatedStore` — the consumer's failover view. Every read
  op (``lines`` / ``read_range`` / ``size`` — the v2 segment reader's
  ranged surface included, since it calls straight through this store)
  tries the primary and, on a CLASSIFIED storage fault (transient burst
  that outlived the retry budget, or the copy simply gone), fails over
  to the next replica — counted (``failover_reads``,
  ``map_reruns_avoided``), never surfaced, never a repetition charge.
  ``exists``/``list`` answer for the LOGICAL file (any surviving copy);
  ``remove`` fans out to every copy. Only when every copy is
  unreadable does :class:`LostShuffleDataError` escape — transient, so
  the worker releases the job while the server's scavenger repairs or
  requeues (engine/server.py, DESIGN §20). Like FaultyStore, this
  wrapper exposes ONLY the portable Store surface: native fast paths
  (``local_path``) cannot bypass the failover routing.

- :func:`repair` — the scavenger's reconstruction primitive: copy any
  surviving replica over the missing/unreadable copies, restoring full
  ``r``-way redundancy without re-running the producing map job.

``replication == 1`` is the identity everywhere: ``spill_writer``
returns the plain writer, engines skip the wrapper, and not one extra
byte or op exists — the golden r=1 byte-compares are untouched.
"""

from __future__ import annotations

from typing import Iterator, List, Union

from lua_mapreduce_tpu.engine.placement import (base_name, check_replication,
                                                replica_names)
from lua_mapreduce_tpu.faults.errors import (LostShuffleDataError,
                                             classify_exception)
from lua_mapreduce_tpu.faults.retry import COUNTERS
from lua_mapreduce_tpu.store.base import FileBuilder, Store


def _classifier(store):
    """The backend's own classify hook when it has one, else the
    central taxonomy — the same resolution the segment reader uses."""
    return getattr(store, "classify", classify_exception)


# --------------------------------------------------------------------------
# write side: replicated spill publish
# --------------------------------------------------------------------------


class _TeeBuilder(FileBuilder):
    """Fan every chunk into ``r`` real builders; ``build`` publishes the
    primary name first, then each replica under its placement name.
    Each underlying build stays atomic (tempfile+rename / object PUT),
    so readers see whole copies or nothing; the primary-first order
    means a crash mid-fanout under-replicates instead of losing data."""

    def __init__(self, store: Store, replication: int):
        self._r = check_replication(replication)
        self._builders: List[FileBuilder] = []
        self._bytes = 0
        try:
            for _ in range(self._r):
                self._builders.append(store.builder())
        except Exception:
            self.close()        # a later builder() failed: release the
            raise               # earlier ones' fds/tempfiles/threads

    def write(self, data: str) -> None:
        self._bytes += len(data)
        for b in self._builders:
            b.write(data)

    def write_bytes(self, data: bytes) -> None:
        self._bytes += len(data)
        for b in self._builders:
            b.write_bytes(data)

    def build(self, name: str) -> None:
        for copy_name, b in zip(replica_names(name, self._r),
                                self._builders):
            b.build(copy_name)
        # write-amplification telemetry for the replication bench:
        # primary payload once, fanout cost separately (honest overhead)
        COUNTERS.bump("spill_bytes_primary", self._bytes)
        COUNTERS.bump("spill_bytes_replica", self._bytes * (self._r - 1))

    def close(self) -> None:
        # every builder gets its close (fds/tempfiles/writer threads
        # must not leak behind an earlier copy's close failure); the
        # first error still surfaces once the sweep is done
        first = None
        for b in self._builders:
            try:
                b.close()
            except Exception as exc:
                if first is None:
                    first = exc
        if first is not None:
            raise first


def spill_writer(store: Store, segment_format: str = "v1",
                 replication=1, codec: str = "zlib", coding=None):
    """The ONE factory every spill producer uses (LMR009): a
    v1/v2 record writer whose ``build(name)`` publishes with the
    configured redundancy at the placement function's addresses —
    ``r`` full copies under replication, a k+m erasure-coded stripe
    under ``coding="k+m"`` (faults/coded.py, DESIGN §27). The unified
    knob: ``replication`` accepts an int OR a Coding/"k+m" spec (the
    engines thread one value through), ``coding`` is the explicit
    override. ``replication=1`` returns exactly ``writer_for``'s plain
    writer — zero overhead."""
    from lua_mapreduce_tpu.core.segment import (SegmentWriter, TextWriter,
                                                check_format, writer_for)
    from lua_mapreduce_tpu.faults.coded import (Coding, check_redundancy,
                                                stripe_builder)
    check_format(segment_format)
    red = check_redundancy(coding if coding is not None else replication)
    if isinstance(red, Coding):
        builder: FileBuilder = stripe_builder(store, red)
    elif red == 1:
        return writer_for(store, segment_format, codec=codec)
    else:
        builder = _TeeBuilder(store, red)
    if segment_format == "v2":
        return SegmentWriter(builder, codec=codec)
    return TextWriter(builder)


# --------------------------------------------------------------------------
# read side: failover view
# --------------------------------------------------------------------------


class ReplicatedStore(Store):
    """Failover view over a wrapped store: ops address LOGICAL files,
    served from whichever of the ``r`` placement copies answers.

    Per-name redirects are cached (a dead primary is not re-probed on
    every frame of a segment read), and the first successful failover
    of a name bumps ``failover_reads`` + ``map_reruns_avoided`` once —
    the tail-latency events the replication bench sweeps. Unclassified
    exceptions (user/data/logic) propagate untouched from the primary
    attempt, exactly like the retry layer below.
    """

    def __init__(self, inner: Store, replication: int):
        self._inner = inner
        self._r = check_replication(replication)
        self._redirect = {}     # logical name -> serving copy index
        self._counted = set()   # names whose first failover was counted

    # -- failover core ------------------------------------------------------

    def _serve(self, op: str, name: str, fn):
        """Run ``fn(copy_name)`` against the cached copy, failing over
        through the remaining copies on classified storage faults."""
        classify = _classifier(self._inner)
        copies = replica_names(name, self._r)
        start = self._redirect.get(name, 0)
        last = None
        for i in range(self._r):
            idx = (start + i) % self._r
            try:
                out = fn(copies[idx])
            except Exception as exc:
                if classify(exc) is None:
                    raise               # not a storage fault: never mask
                last = exc
                continue
            if idx != start:
                self._redirect[name] = idx
            if idx != 0 and name not in self._counted:
                self._counted.add(name)
                COUNTERS.bump("failover_reads")
                COUNTERS.bump("map_reruns_avoided")
            return out
        raise LostShuffleDataError(
            f"{op}({name!r}): all {self._r} replica(s) unreadable "
            f"(last: {type(last).__name__}: {last}) — scavenger repair "
            "or map re-run required", op=op, name=name,
            files=[name]) from last

    # -- portable surface ----------------------------------------------------

    def builder(self) -> FileBuilder:
        return self._inner.builder()

    def lines(self, name: str) -> Iterator[str]:
        # prime the first record inside the failover scope (the same
        # open-window the retry layer covers); mid-stream faults after
        # that propagate — a silent replica restart would re-yield
        # records the merge already consumed
        def open_primed(copy_name):
            it = iter(self._inner.lines(copy_name))
            try:
                return next(it), it
            except StopIteration:
                return None, None

        first, it = self._serve("lines", name, open_primed)
        if it is None:
            return
        yield first
        yield from it

    def read_range(self, name: str, offset: int, length: int) -> bytes:
        return self._serve(
            "read_range", name,
            lambda n: self._inner.read_range(n, offset, length))

    def size(self, name: str) -> int:
        return self._serve("size", name, lambda n: self._inner.size(n))

    def exists(self, name: str) -> bool:
        classify = _classifier(self._inner)
        for copy_name in replica_names(name, self._r):
            try:
                if self._inner.exists(copy_name):
                    return True
            except Exception as exc:
                if classify(exc) is None:
                    raise
        return False

    def list(self, pattern: str) -> List[str]:
        from lua_mapreduce_tpu.engine.placement import replica_pattern
        names = set(self._inner.list(pattern))
        # a lost primary stays VISIBLE while any replica survives — the
        # reduce pull-integrity check must not report a recoverable
        # file as missing
        names.update(base_name(n)
                     for n in self._inner.list(replica_pattern(pattern)))
        return sorted(names)

    def remove(self, name: str) -> None:
        # cleanup fans out to every copy; per-copy storage faults are
        # swallowed (best-effort sweep — the iteration-start cleanup
        # and the consumed-leftover sweeps catch stragglers)
        classify = _classifier(self._inner)
        for copy_name in replica_names(name, self._r):
            try:
                self._inner.remove(copy_name)
            except Exception as exc:
                if classify(exc) is None:
                    raise

    def classify(self, exc: BaseException):
        return self._inner.classify(exc)


def reading_view(store: Store, replication) -> Store:
    """The engines' wrap point: the decode view when coding is on, the
    failover view when replication is, the store itself (identity —
    zero overhead) when neither. ``replication`` is the unified
    redundancy value: int, Coding, or a "k+m" spec string."""
    from lua_mapreduce_tpu.faults.coded import (CodedStore, Coding,
                                                check_redundancy)
    red = check_redundancy(replication)
    if isinstance(red, Coding):
        if isinstance(store, CodedStore):
            return store
        return CodedStore(store, red)
    if red <= 1:
        return store
    if isinstance(store, ReplicatedStore):
        return store
    return ReplicatedStore(store, red)


# --------------------------------------------------------------------------
# scavenger reconstruction
# --------------------------------------------------------------------------


def repair(store: Store, name: str, replication) -> str:
    """Restore full ``r``-way redundancy of ``name`` from any readable
    copy — the scavenger's cheap alternative to re-running the
    producing map job. Under a coding spec this dispatches to
    :func:`faults.coded.repair_stripe` (decode-from-survivors rebuild),
    same verdict vocabulary.

    Returns ``"intact"`` (every copy already readable and whole),
    ``"repaired"`` (at least one copy rebuilt from a survivor),
    ``"degraded"`` (a survivor is readable but every rebuild write
    failed — reads still fail over, a later scavenge pass retries the
    heal), or ``"lost"`` (NO copy readable — only then does the caller
    escalate to map re-run). ``store`` is the plain wrapped store (copies addressed
    individually, never through the failover view). Copies are whole
    by construction (atomic publishes + readback-verify below), so the
    first readable copy is trusted as the source; copies whose size
    disagrees with it are rebuilt too."""
    from lua_mapreduce_tpu.faults.coded import (Coding, check_redundancy,
                                                repair_stripe)
    red = check_redundancy(replication)
    if isinstance(red, Coding):
        return repair_stripe(store, name, red)
    classify = _classifier(store)
    copies = replica_names(name, check_replication(red))
    data = None
    whole = set()
    for copy_name in copies:
        try:
            sz = store.size(copy_name)
            blob = store.read_range(copy_name, 0, sz)
        except Exception as exc:
            if classify(exc) is None:
                raise
            continue
        if data is None and len(blob) == sz:
            data = blob
        if data is not None and blob == data:
            whole.add(copy_name)
    if data is None:
        return "lost"
    if len(whole) == len(copies):
        return "intact"
    rebuilt = 0
    for copy_name in copies:
        if copy_name in whole:
            continue
        try:
            with store.builder() as b:
                b.write_bytes(data)
                b.build(copy_name)
            rebuilt += 1
        except Exception as exc:
            if classify(exc) is None:
                raise
            # this copy's target is still failing: partial repair —
            # redundancy improved where the store allowed it
    if rebuilt:
        COUNTERS.bump("replica_repairs", rebuilt)
        COUNTERS.bump("map_reruns_avoided")
    # a readable survivor means the data is NOT lost even when every
    # rebuild write failed (the targets are still dark): failover
    # reads keep serving it, and escalating to a map re-run here would
    # pay the exact cost this layer exists to avoid
    return "repaired" if rebuilt else "degraded"


def utest() -> None:
    """Self-test: tee publish fanout, failover reads + counting, the
    logical exists/list/remove surface, repair, and the r=1 identity."""
    from lua_mapreduce_tpu.core.segment import writer_for
    from lua_mapreduce_tpu.store.memfs import MemStore

    raw = MemStore()
    # r=1 identity: spill_writer IS writer_for's plain writer shape
    w1 = spill_writer(raw, "v1", 1)
    assert type(w1) is type(writer_for(raw, "v1"))
    w1.close()

    # r=3 publish lands 3 byte-identical copies at the placement names
    with spill_writer(raw, "v1", 3) as w:
        w.add("k", [1, 2])
        w.build("ns.P0.M00000001")
    copies = replica_names("ns.P0.M00000001", 3)
    blobs = [raw.read_range(n, 0, raw.size(n)) for n in copies]
    assert len(set(blobs)) == 1 and blobs[0]
    assert raw.list("ns.P*") == ["ns.P0.M00000001"]   # globs see primary

    # failover: primary destroyed -> reads serve the replica, counted
    before = COUNTERS.snapshot().get("failover_reads", 0)
    raw._files.pop("ns.P0.M00000001")
    view = reading_view(raw, 3)
    assert view.exists("ns.P0.M00000001")
    assert list(view.lines("ns.P0.M00000001")) == ['["k",[1,2]]\n']
    assert view.size("ns.P0.M00000001") == len(blobs[0])
    assert view.list("ns.P*") == ["ns.P0.M00000001"]  # logical listing
    assert COUNTERS.snapshot()["failover_reads"] == before + 1  # once/name

    # repair rebuilds the missing primary from a survivor
    assert repair(raw, "ns.P0.M00000001", 3) == "repaired"
    assert raw.read_range("ns.P0.M00000001", 0, 99) == blobs[0][:99]
    assert repair(raw, "ns.P0.M00000001", 3) == "intact"

    # a readable survivor + every rebuild target dark -> "degraded",
    # NOT "lost": the scavenger must not escalate to a map re-run
    # while failover reads can still serve the file
    class _DarkBuilders(MemStore):
        def builder(self):
            raise OSError(5, "brownout")        # EIO: transient
    dark = _DarkBuilders()
    for k, copy_name in enumerate(replica_names("ns.P1.M00000001", 2)):
        b = MemStore.builder(dark)              # publish past the dark
        b.write('["k",[3]]\n')                  # override: both copies
        b.build(copy_name)                      # land whole
    dark._files.pop("ns.P1.M00000001")          # primary destroyed
    assert repair(dark, "ns.P1.M00000001", 2) == "degraded"
    assert list(reading_view(dark, 2).lines("ns.P1.M00000001")) \
        == ['["k",[3]]\n']

    # remove fans out to every copy; total loss raises the classified
    # transient that releases (never breaks) the consuming job
    view.remove("ns.P0.M00000001")
    assert all(not raw.exists(n) for n in copies)
    assert repair(raw, "ns.P0.M00000001", 3) == "lost"
    try:
        list(view.lines("ns.P0.M00000001"))
    except LostShuffleDataError as e:
        assert e.transient and e.lost_files == ["ns.P0.M00000001"]
    else:
        raise AssertionError("total loss must raise LostShuffleDataError")

    assert reading_view(raw, 1) is raw                # identity when off
    assert not hasattr(reading_view(raw, 2), "local_path")

    # coded dispatch: the unified knob routes "k+m" through the stripe
    # layer (faults/coded.py, DESIGN §27) at every choke point
    from lua_mapreduce_tpu.faults.coded import CodedStore, Coding
    cs = MemStore()
    with spill_writer(cs, "v1", "4+1") as w:
        w.add("k", [9])
        w.build("cns.P0.M00000001")
    cview = reading_view(cs, Coding(4, 1))
    assert isinstance(cview, CodedStore)
    assert list(cview.lines("cns.P0.M00000001")) == ['["k",[9]]\n']
    assert cs.list("cns.P*") == []            # no plain primary exists
    assert cview.list("cns.P*") == ["cns.P0.M00000001"]
    assert repair(cs, "cns.P0.M00000001", "4+1") == "intact"
    assert reading_view(cview, "4+1") is cview
    assert not hasattr(cview, "local_path")
