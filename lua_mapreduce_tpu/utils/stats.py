"""Per-phase / per-iteration statistics.

Analog of the reference's tracing subsystem (SURVEY.md §5): per-job
lifecycle timestamps (creation/started/finished/written, cpu_time,
real_time — job.lua:117-152, task.lua:294-299) aggregated into per-phase
sums and cluster wall time = max(written) − min(started)
(server.lua:155-183). The reference computes the aggregation with MongoDB
server-side JavaScript mapreduce; here it is a plain fold over JobTimes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from lua_mapreduce_tpu.engine.job import JobTimes


@dataclasses.dataclass
class PhaseStats:
    """One phase's aggregate (reference stats schema task.lua:44-56)."""
    count: int = 0
    failed: int = 0
    sum_cpu_time: float = 0.0
    sum_real_time: float = 0.0
    cluster_time: float = 0.0   # max(written) - min(started)

    def fold(self, times: List[JobTimes], failed: int = 0) -> "PhaseStats":
        self.count = len(times)
        self.failed = failed
        if times:
            self.sum_cpu_time = sum(t.cpu for t in times)
            self.sum_real_time = sum(t.real for t in times)
            self.cluster_time = (max(t.written for t in times) -
                                 min(t.started for t in times))
        return self

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def overlap_fraction(map_times: List[JobTimes],
                     premerge_times: List[JobTimes]) -> float:
    """Fraction of pre-merge wall time hidden behind the map phase.

    The pipelined shuffle's effectiveness metric: per pre-merge job, the
    part of its started→written window that falls before the last map
    job's written time is overlapped (free); the rest extended the
    iteration. 1.0 = every pre-merge second was hidden under still-running
    mappers; 0.0 = no overlap (or no pre-merge ran).
    """
    if not map_times or not premerge_times:
        return 0.0
    map_end = max(t.written for t in map_times)
    total = sum(t.real for t in premerge_times)
    if total <= 0.0:
        return 0.0
    hidden = sum(max(0.0, min(t.written, map_end) - t.started)
                 for t in premerge_times)
    return min(1.0, hidden / total)


# the counter-key → IterationStats-field fold, shared by BOTH executors
# (fold_fault_counters below). Grown by PRs 5-7; store_faults is the one
# composite: un-absorbed transient bursts PLUS injected FaultPlan events.
COUNTER_FOLD = {
    "store_retries": ("retries",),
    "store_faults": ("retry_exhausted", "faults_injected"),
    "infra_releases": ("infra_releases",),
    "degraded_reads": ("degraded_reads",),
    "failover_reads": ("failover_reads",),
    "replica_repairs": ("replica_repairs",),
    "map_reruns_avoided": ("map_reruns_avoided",),
    "map_reruns": ("map_reruns",),
    "decode_reads": ("decode_reads",),
    "stripe_repairs": ("stripe_repairs",),
    "spec_launched": ("spec_launched",),
    "spec_wins": ("spec_wins",),
    "spec_cancelled": ("spec_cancelled",),
    "spec_wasted_s": ("spec_wasted_s",),
    "push_frames": ("push_frames",),
    "push_evictions": ("push_evictions",),
    "ingraph_iterations": ("ingraph_iterations",),
    "ingraph_fallbacks": ("ingraph_fallbacks",),
    "hybrid_map_legs": ("hybrid_map_legs",),
    "hybrid_reduce_legs": ("hybrid_reduce_legs",),
    "hybrid_fallbacks": ("hybrid_fallbacks",),
    "autotune_decisions": ("autotune_decisions",),
    "autotune_vetoes": ("autotune_vetoes",),
    "autotune_scale_events": ("autotune_scale_events",),
    "leader_takeovers": ("leader_takeovers",),
    "fenced_writes": ("fenced_writes",),
    "standby_wakeups": ("standby_wakeups",),
}
_FLOAT_COUNTERS = frozenset({"spec_wasted_s"})


@dataclasses.dataclass
class IterationStats:
    """Stats for one map→reduce iteration (server.lua:536-601), plus the
    pipelined-shuffle pre-merge phase when it ran."""
    iteration: int
    map: PhaseStats = dataclasses.field(default_factory=PhaseStats)
    reduce: PhaseStats = dataclasses.field(default_factory=PhaseStats)
    premerge: PhaseStats = dataclasses.field(default_factory=PhaseStats)
    wall_time: float = 0.0
    overlap_fraction: float = 0.0   # see overlap_fraction() above
    # control-plane round trips observed through the server's job-store
    # instance this iteration (JobStore.round_counts deltas). In-process
    # pools share that instance, so these count the whole pool's claim
    # and commit traffic — the batch-lease protocol's effectiveness
    # metric (claim_rounds << job count when batch_k amortizes); in
    # multi-process pools each worker process counts its own and the
    # coord bench aggregates them explicitly.
    claim_rounds: int = 0
    commit_rounds: int = 0
    # fault-plane accounting (DESIGN §19), folded from the process-global
    # FaultCounters deltas exactly like the round counters above:
    #   store_retries  — transient store/coord faults absorbed by a
    #                    backoff-retry (the op eventually succeeded)
    #   store_faults   — faults that were NOT absorbed silently: retry
    #                    budgets exhausted + injected FaultPlan events
    #   infra_releases — jobs released back to WAITING on transient
    #                    infra faults (no repetition charged)
    #   degraded_reads — ranged segment reads that fell back to a
    #                    whole-file read (the degradation ladder's
    #                    read-side rung)
    # replica-aware shuffle accounting (DESIGN §20), same fold:
    #   failover_reads     — shuffle files served from a non-primary
    #                        replica after the primary failed
    #   replica_repairs    — replica copies rebuilt from a survivor by
    #                        the scavenger (under-replication healed
    #                        without re-running the producer)
    #   map_reruns_avoided — map re-executions the replication layer
    #                        made unnecessary (one per failed-over or
    #                        repaired file); the chaos gate asserts the
    #                        companion map_reruns stays ZERO while this
    #                        climbs
    #   map_reruns         — last-resort producer requeues (every
    #                        replica of a file gone)
    # erasure-coded shuffle accounting (DESIGN §27), same fold:
    #   decode_reads       — stripes reassembled from parity survivors
    #                        after a block loss/corruption (one per
    #                        logical file — the inline recovery twin of
    #                        failover_reads)
    #   stripe_repairs     — stripes the scavenger rebuilt to full k+m
    #                        blocks from ≥k survivors (the coded twin
    #                        of replica_repairs)
    # speculative-execution accounting (DESIGN §21), same fold:
    #   spec_launched  — duplicate leases the straggler detector opened
    #   spec_wins      — commit races a CLONE won (the original's
    #                    commit degraded to a zero-repetition no-op)
    #   spec_cancelled — clones that lost, failed, or observed their
    #                    revocation (job state untouched either way)
    #   spec_wasted_s  — seconds EITHER duplicate (clone or disowned
    #                    original) spent on work that lost its commit
    #                    race (the duplicate-execution trade's cost
    #                    side; the bench's wasted-work fraction)
    # push-shuffle accounting (DESIGN §24), same fold:
    #   push_frames    — inbox frame files published by pushing maps
    #   push_evictions — partition buffers evicted to the staged tail
    #                    path under memory-budget pressure (the
    #                    degrade-to-staged rung; >0 proves a budgeted
    #                    run survived via eviction, not OOM)
    # in-graph engine accounting (DESIGN §26), same fold:
    #   ingraph_iterations — iterations whose whole data plane ran as
    #                        the compiled shard_map/jit program
    #                        (engine/ingraph.py) instead of store jobs
    #   ingraph_fallbacks  — runtime degrades to the store plane (the
    #                        oracle accepted the task but lowering
    #                        raised at trace time — logged, traced as
    #                        an ``ingraph.fallback`` span, never a
    #                        crash under engine=auto)
    # hybrid engine accounting (DESIGN §28), same fold:
    #   hybrid_map_legs    — map-job batches executed as one compiled
    #                        map+combine program whose partitions were
    #                        published through the ordinary spill path
    #                        (engine/hybrid.py)
    #   hybrid_reduce_legs — reduce jobs whose per-group fold ran as
    #                        the jitted compiled reducefn instead of
    #                        the interpreted per-record call
    #   hybrid_fallbacks   — compiled legs that degraded back to the
    #                        interpreted store plane at trace/run time
    #                        (logged, traced as ``hybrid.fallback``
    #                        spans, never a crash)
    # autotune controller accounting (DESIGN §29), same fold:
    #   autotune_decisions    — knob changes the feedback controller
    #                           applied (each one also an
    #                           ``autotune.<knob>`` evidence span)
    #   autotune_vetoes       — changes the evidence warranted but the
    #                           stability gates (per-knob cooldown /
    #                           flip lockout) suppressed
    #   autotune_scale_events — the elastic subset of decisions: fleet
    #                           grow/retire targets issued
    # HA leader-lease accounting (DESIGN §31), same fold:
    #   leader_takeovers — lease acquisitions that BUMPED the epoch past
    #                      a dead/expired leader's (a mid-run takeover;
    #                      the first election of a run is epoch 1 and
    #                      not counted)
    #   fenced_writes    — server-side mutations REJECTED by the fencing
    #                      check (a zombie leader's write attempts; each
    #                      one is also an errors-stream entry carrying
    #                      the epoch evidence)
    #   standby_wakeups  — standby election probes (leader-topic wakeup
    #                      or TTL-bounded timeout) that found the lease
    #                      still held. LocalExecutor folds all three as
    #                      zeros by construction: no lease exists
    #                      in-process.
    store_retries: int = 0
    store_faults: int = 0
    infra_releases: int = 0
    degraded_reads: int = 0
    failover_reads: int = 0
    replica_repairs: int = 0
    map_reruns_avoided: int = 0
    map_reruns: int = 0
    decode_reads: int = 0
    stripe_repairs: int = 0
    spec_launched: int = 0
    spec_wins: int = 0
    spec_cancelled: int = 0
    spec_wasted_s: float = 0.0
    push_frames: int = 0
    push_evictions: int = 0
    ingraph_iterations: int = 0
    ingraph_fallbacks: int = 0
    hybrid_map_legs: int = 0
    hybrid_reduce_legs: int = 0
    hybrid_fallbacks: int = 0
    autotune_decisions: int = 0
    autotune_vetoes: int = 0
    autotune_scale_events: int = 0
    leader_takeovers: int = 0
    fenced_writes: int = 0
    standby_wakeups: int = 0

    def fold_fault_counters(self, delta: Dict[str, float]
                            ) -> "IterationStats":
        """Fold a FaultCounters delta (COUNTERS.delta of per-iteration
        snapshots) into the counter fields — the ONE place the
        counter-key → stats-field mapping lives. Server.loop and
        LocalExecutor.run_one_iteration both route through here, so the
        two executors cannot drift apart in which counters they surface
        (they did, briefly: the local executor silently never folded
        infra_releases; the drift test in tests/test_trace.py pins the
        key sets identical)."""
        for field, keys in COUNTER_FOLD.items():
            val = sum(delta.get(k, 0) for k in keys)
            setattr(self, field,
                    float(val) if field in _FLOAT_COUNTERS else int(val))
        return self

    @property
    def cluster_time(self) -> float:
        """map+reduce cluster time — the reference's headline metric
        (README.md:68-70). Pre-merge time is deliberately NOT added:
        overlapped work is already inside the map window, and counting
        the spill-over would double-charge what wall_time captures."""
        return self.map.cluster_time + self.reduce.cluster_time

    def as_dict(self) -> dict:
        return {
            "iteration": self.iteration,
            "map": self.map.as_dict(),
            "reduce": self.reduce.as_dict(),
            "premerge": self.premerge.as_dict(),
            "overlap_fraction": self.overlap_fraction,
            "claim_rounds": self.claim_rounds,
            "commit_rounds": self.commit_rounds,
            "store_retries": self.store_retries,
            "store_faults": self.store_faults,
            "infra_releases": self.infra_releases,
            "degraded_reads": self.degraded_reads,
            "failover_reads": self.failover_reads,
            "replica_repairs": self.replica_repairs,
            "map_reruns_avoided": self.map_reruns_avoided,
            "map_reruns": self.map_reruns,
            "decode_reads": self.decode_reads,
            "stripe_repairs": self.stripe_repairs,
            "spec_launched": self.spec_launched,
            "spec_wins": self.spec_wins,
            "spec_cancelled": self.spec_cancelled,
            "spec_wasted_s": self.spec_wasted_s,
            "push_frames": self.push_frames,
            "push_evictions": self.push_evictions,
            "ingraph_iterations": self.ingraph_iterations,
            "ingraph_fallbacks": self.ingraph_fallbacks,
            "hybrid_map_legs": self.hybrid_map_legs,
            "hybrid_reduce_legs": self.hybrid_reduce_legs,
            "hybrid_fallbacks": self.hybrid_fallbacks,
            "autotune_decisions": self.autotune_decisions,
            "autotune_vetoes": self.autotune_vetoes,
            "autotune_scale_events": self.autotune_scale_events,
            "leader_takeovers": self.leader_takeovers,
            "fenced_writes": self.fenced_writes,
            "standby_wakeups": self.standby_wakeups,
            "cluster_time": self.cluster_time,
            "wall_time": self.wall_time,
        }


@dataclasses.dataclass
class TaskStats:
    """Whole-task stats across iterations."""
    iterations: List[IterationStats] = dataclasses.field(default_factory=list)
    wall_time: float = 0.0

    @property
    def last(self) -> Optional[IterationStats]:
        return self.iterations[-1] if self.iterations else None

    def as_dict(self) -> dict:
        return {
            "iterations": [s.as_dict() for s in self.iterations],
            "wall_time": self.wall_time,
        }


def utest() -> None:
    """Self-test (reference server.lua:629-655 utest role: the stats
    aggregation — per-phase sums + cluster time = max(written) −
    min(started), server.lua:155-183)."""
    times = [JobTimes(started=1.0, finished=2.0, written=3.0, cpu=0.5),
             JobTimes(started=2.0, finished=4.0, written=6.0, cpu=1.5)]
    ph = PhaseStats().fold(times, failed=1)
    assert ph.count == 2 and ph.failed == 1
    assert abs(ph.sum_cpu_time - 2.0) < 1e-9
    assert abs(ph.sum_real_time - (2.0 + 4.0)) < 1e-9
    assert abs(ph.cluster_time - (6.0 - 1.0)) < 1e-9
    red = PhaseStats().fold(
        [JobTimes(started=6.0, finished=7.0, written=8.0, cpu=1.0)])
    it = IterationStats(iteration=1, map=ph, reduce=red)
    assert abs(it.cluster_time - (5.0 + 2.0)) < 1e-9
    d = TaskStats(iterations=[it]).as_dict()
    assert d["iterations"][0]["map"]["count"] == 2
    assert d["iterations"][0]["premerge"]["count"] == 0
    assert d["iterations"][0]["claim_rounds"] == 0
    assert d["iterations"][0]["commit_rounds"] == 0
    # overlap: map ends at 6.0; one pre-merge fully inside (2→4), one
    # half outside (5→7): hidden = 2 + 1 of real = 2 + 2 → 3/4
    pre = [JobTimes(started=2.0, finished=3.0, written=4.0, cpu=0.1),
           JobTimes(started=5.0, finished=6.0, written=7.0, cpu=0.1)]
    assert abs(overlap_fraction(times, pre) - 0.75) < 1e-9
    assert overlap_fraction([], pre) == 0.0 and overlap_fraction(times, []) == 0.0
    # the shared counter fold: composite store_faults, float passthrough,
    # zeroed absent keys, and every folded field present in as_dict
    it2 = IterationStats(iteration=2).fold_fault_counters(
        {"retries": 3, "faults_injected": 1, "retry_exhausted": 2,
         "spec_wasted_s": 1.5})
    assert it2.store_retries == 3 and it2.store_faults == 3
    assert it2.spec_wasted_s == 1.5 and it2.infra_releases == 0
    assert set(COUNTER_FOLD) <= set(it2.as_dict())
