"""Per-phase / per-iteration statistics.

Analog of the reference's tracing subsystem (SURVEY.md §5): per-job
lifecycle timestamps (creation/started/finished/written, cpu_time,
real_time — job.lua:117-152, task.lua:294-299) aggregated into per-phase
sums and cluster wall time = max(written) − min(started)
(server.lua:155-183). The reference computes the aggregation with MongoDB
server-side JavaScript mapreduce; here it is a plain fold over JobTimes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from lua_mapreduce_tpu.engine.job import JobTimes


@dataclasses.dataclass
class PhaseStats:
    """One phase's aggregate (reference stats schema task.lua:44-56)."""
    count: int = 0
    failed: int = 0
    sum_cpu_time: float = 0.0
    sum_real_time: float = 0.0
    cluster_time: float = 0.0   # max(written) - min(started)

    def fold(self, times: List[JobTimes], failed: int = 0) -> "PhaseStats":
        self.count = len(times)
        self.failed = failed
        if times:
            self.sum_cpu_time = sum(t.cpu for t in times)
            self.sum_real_time = sum(t.real for t in times)
            self.cluster_time = (max(t.written for t in times) -
                                 min(t.started for t in times))
        return self

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class IterationStats:
    """Stats for one map→reduce iteration (server.lua:536-601)."""
    iteration: int
    map: PhaseStats = dataclasses.field(default_factory=PhaseStats)
    reduce: PhaseStats = dataclasses.field(default_factory=PhaseStats)
    wall_time: float = 0.0

    @property
    def cluster_time(self) -> float:
        """map+reduce cluster time — the reference's headline metric
        (README.md:68-70)."""
        return self.map.cluster_time + self.reduce.cluster_time

    def as_dict(self) -> dict:
        return {
            "iteration": self.iteration,
            "map": self.map.as_dict(),
            "reduce": self.reduce.as_dict(),
            "cluster_time": self.cluster_time,
            "wall_time": self.wall_time,
        }


@dataclasses.dataclass
class TaskStats:
    """Whole-task stats across iterations."""
    iterations: List[IterationStats] = dataclasses.field(default_factory=list)
    wall_time: float = 0.0

    @property
    def last(self) -> Optional[IterationStats]:
        return self.iterations[-1] if self.iterations else None

    def as_dict(self) -> dict:
        return {
            "iterations": [s.as_dict() for s in self.iterations],
            "wall_time": self.wall_time,
        }


def utest() -> None:
    """Self-test (reference server.lua:629-655 utest role: the stats
    aggregation — per-phase sums + cluster time = max(written) −
    min(started), server.lua:155-183)."""
    times = [JobTimes(started=1.0, finished=2.0, written=3.0, cpu=0.5),
             JobTimes(started=2.0, finished=4.0, written=6.0, cpu=1.5)]
    ph = PhaseStats().fold(times, failed=1)
    assert ph.count == 2 and ph.failed == 1
    assert abs(ph.sum_cpu_time - 2.0) < 1e-9
    assert abs(ph.sum_real_time - (2.0 + 4.0)) < 1e-9
    assert abs(ph.cluster_time - (6.0 - 1.0)) < 1e-9
    red = PhaseStats().fold(
        [JobTimes(started=6.0, finished=7.0, written=8.0, cpu=1.0)])
    it = IterationStats(iteration=1, map=ph, reduce=red)
    assert abs(it.cluster_time - (5.0 + 2.0)) < 1e-9
    d = TaskStats(iterations=[it]).as_dict()
    assert d["iterations"][0]["map"]["count"] == 2
