"""Roofline accounting: chip peak FLOP/s and MFU.

The reference ships no MFU notion — its perf story is wall-clock tables
(/root/reference/README.md:43-113). The build's north star is stated as
an MFU target (BASELINE.md: "≥50% MFU on the digits model"), so model
FLOP helpers (``models/*/flops_per_example``) need a denominator: the
chip's peak matmul FLOP/s. Known TPU generations are in a table (public
per-chip bf16 figures, e.g. jax-ml.github.io/scaling-book); anything
unknown falls back to a measured big-matmul probe so MFU stays defined
(if optimistically scaled) on CPU test boxes.
"""

from __future__ import annotations

import os
from typing import Optional

# Per-chip peak dense bf16 matmul FLOP/s, keyed by jax Device.device_kind.
PEAK_BF16_FLOPS = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,     # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,          # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,     # Trillium / v6e
    "TPU v6e": 918e12,
}

# Per-chip HBM bandwidth (bytes/s), keyed like PEAK_BF16_FLOPS. Public
# figures (jax-ml.github.io/scaling-book hardware table). Used as the
# memory-roofline denominator for FLOP-less ops: an op cannot finish
# faster than reading its inputs once at this rate.
PEAK_HBM_BYTES = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,      # v5e
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,          # v5p
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,     # Trillium / v6e
    "TPU v6e": 1640e9,
}


def peak_hbm_bytes_per_s(device=None) -> Optional[float]:
    """Peak HBM bandwidth for one chip, or None when the generation is
    unknown (no probe fallback: a bandwidth probe through the tunnel
    measures the tunnel, and the only consumer — kernel_bench's
    elision sanity check — simply skips the check when this is None)."""
    env = os.environ.get("LMR_PEAK_HBM_BYTES")
    if env:
        return float(env)
    import jax
    if device is None:
        device = jax.devices()[0]
    return PEAK_HBM_BYTES.get(device.device_kind)


_probe_cache: dict = {}


def peak_flops_per_s(device=None) -> float:
    """Peak dense bf16 FLOP/s for one chip.

    Resolution order: ``LMR_PEAK_FLOPS`` env override → known-generation
    table → measured probe (timed 4096³ bf16 matmul — a floor on peak,
    so MFU against it is an upper bound; fine for CPU test boxes).
    """
    env = os.environ.get("LMR_PEAK_FLOPS")
    if env:
        return float(env)
    import jax
    if device is None:
        device = jax.devices()[0]
    kind = device.device_kind
    if kind in PEAK_BF16_FLOPS:
        return PEAK_BF16_FLOPS[kind]
    # smaller probe off-accelerator: a 4096³ matmul takes ~10s on the
    # single-core CPU test box and resolution doesn't need it
    return _measured_peak(device, n=1024 if device.platform == "cpu"
                          else 4096)


def best_time(fn, reps: int = 3) -> float:
    """Best wall time of ``fn()`` over ``reps`` calls. ``fn`` must force
    completion itself (fetch a result device→host with ``np.asarray`` —
    under a tunneled backend ``block_until_ready`` can return before
    execution finishes, yielding impossible throughputs)."""
    import time

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _measured_peak(device, n: int = 4096) -> float:
    """Best achieved FLOP/s over a few timed n³ bf16 matmuls."""
    if device in _probe_cache:
        return _probe_cache[device]
    import jax
    import jax.numpy as jnp
    import numpy as np

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    a = jax.device_put(jax.random.normal(k1, (n, n), jnp.bfloat16), device)
    b = jax.device_put(jax.random.normal(k2, (n, n), jnp.bfloat16), device)
    f = jax.jit(lambda a, b: a @ b)
    np.asarray(f(a, b))          # compile + warm
    peak = 2 * n**3 / best_time(lambda: np.asarray(f(a, b)))
    _probe_cache[device] = peak
    return peak


def mfu(model_flops: float, seconds: float, n_chips: int = 1,
        device=None) -> float:
    """Model FLOP utilization in [0,1]: counted model FLOPs per second
    as a fraction of ``n_chips`` × chip peak."""
    return model_flops / seconds / (n_chips * peak_flops_per_s(device))
