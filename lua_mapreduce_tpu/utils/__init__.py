"""Shared utilities: stats aggregation, logging, config."""
