"""JAX API drift shims (library-wide, lazily resolved).

``jax.shard_map`` went public (with the ``check_vma`` kwarg) in newer
JAX; installed older releases carry it as
``jax.experimental.shard_map.shard_map`` with the same semantics under
the ``check_rep`` kwarg. Every library call site routes through
:func:`shard_map` here so the whole package — not just individual tests
with local try/except shims — runs on both API generations.
"""

from __future__ import annotations


def shard_map(f, *args, **kwargs):
    import jax
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return fn(f, *args, **kwargs)


def vma_shard_map(f, *args, **kwargs):
    """:func:`shard_map` for programs that close over ``pallas_call``.

    Newer JAX's ``check_vma`` machinery carries replication rules for
    ``pallas_call``, so kernels trace under the checker; the legacy
    ``check_rep`` checker has no such rule and raises
    ``NotImplementedError`` on any kernel-bearing body. On the legacy
    API the check is therefore disabled (its documented workaround)
    instead of crashing; on the public API full vma checking stays on.
    """
    import jax
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
        kwargs.setdefault("check_rep", False)
    return fn(f, *args, **kwargs)


def spec_axes(spec) -> set:
    """Mesh-axis names a ``PartitionSpec`` shards over (flattening
    tuple entries); empty for ``P()`` — the replicated spec."""
    out = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.update(entry)
        else:
            out.add(entry)
    return out


def stamp_replicated(tree, axes):
    """Make mathematically-replicated shard_map outputs *statically*
    replicated for the rep/vma checker (the ``shard_step`` out_specs
    drift).

    Newer JAX rejects ``out_specs=P()`` for gradients of replicated
    params at trace time: the transpose machinery still auto-psums the
    replicated-input cotangents (the values ARE identical across
    ``axes``), but the static checker cannot infer that through
    ``value_and_grad``. ``lax.pmean`` over each axis is a numerical
    identity on an already-replicated value and carries the replication
    fact the checker needs — so the check stays ON (the loud failure
    mode the call sites prefer) on every API generation, instead of
    being disabled with ``check_vma=False`` (which on older JAX also
    disables the auto-psum itself: silently un-summed grads).
    """
    import jax
    from jax import lax
    axes = tuple(a for a in axes if a)
    if not axes:
        return tree

    def stamp(x):
        for a in axes:
            x = lax.pmean(x, a)
        return x

    return jax.tree.map(stamp, tree)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` — renamed from ``TPUCompilerParams``.

    Newer pallas dropped the ``TPU`` prefix (the module path already
    says it); older releases only export the prefixed class. Same
    constructor kwargs either way, so every kernel call site routes
    through here instead of hard-coding one generation's name.
    """
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
