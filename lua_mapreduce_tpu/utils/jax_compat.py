"""JAX API drift shims (library-wide, lazily resolved).

``jax.shard_map`` went public (with the ``check_vma`` kwarg) in newer
JAX; installed older releases carry it as
``jax.experimental.shard_map.shard_map`` with the same semantics under
the ``check_rep`` kwarg. Every library call site routes through
:func:`shard_map` here so the whole package — not just individual tests
with local try/except shims — runs on both API generations.
"""

from __future__ import annotations


def shard_map(f, *args, **kwargs):
    import jax
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return fn(f, *args, **kwargs)
