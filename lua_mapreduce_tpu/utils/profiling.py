"""Device-level tracing: the TPU-native deepening of utils/stats.py.

The reference's observability is host-side phase timing (map/reduce
cluster times, utils/stats.py's analog of server.lua's counters). On an
accelerator the interesting time is INSIDE the jitted step — kernel
schedules, collective overlap, HBM stalls — which only the XLA profiler
sees. :func:`device_trace` wraps any region in a jax.profiler trace
whose output TensorBoard (or xprof) renders; train_lm's ``--profile``
flag wires it around the train loop.
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Trace everything inside the ``with`` to ``log_dir`` (created if
    missing). Traces include host Python annotations and, on TPU, the
    device timeline; view with TensorBoard's profile plugin.

    NOTE: entering the trace initializes the JAX backend — callers that
    need the CPU fallback (utils/jax_env.force_cpu_if_unavailable) must
    run it BEFORE this context, which is why train_lm starts its trace
    inside run() after the bootstrap, never around it."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield log_dir


def annotate(name: str):
    """Named sub-span inside a device_trace (jax.profiler.TraceAnnotation
    passthrough) — marks host-side phases so device ops group under
    readable labels."""
    import jax

    return jax.profiler.TraceAnnotation(name)
