"""Device-level tracing: the TPU-native deepening of utils/stats.py.

The reference's observability is host-side phase timing (map/reduce
cluster times, utils/stats.py's analog of server.lua's counters). On an
accelerator the interesting time is INSIDE the jitted step — kernel
schedules, collective overlap, HBM stalls — which only the XLA profiler
sees. :func:`device_trace` wraps any region in a jax.profiler trace
whose output TensorBoard (or xprof) renders; train_lm's ``--profile``
flag wires it around the train loop, and the distributed worker/server
CLIs (cli/execute_worker.py, cli/execute_server.py) expose the same
``--profile DIR`` around their execute/loop — always AFTER the
jax_env.force_cpu_if_unavailable bootstrap, since entering the trace
initializes the backend (the ordering note on device_trace below).
:func:`maybe_annotate` bridges lmr-trace span names (DESIGN §22) into
the device profile so host and TPU timelines correlate.
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Trace everything inside the ``with`` to ``log_dir`` (created if
    missing). Traces include host Python annotations and, on TPU, the
    device timeline; view with TensorBoard's profile plugin.

    NOTE: entering the trace initializes the JAX backend — callers that
    need the CPU fallback (utils/jax_env.force_cpu_if_unavailable) must
    run it BEFORE this context, which is why train_lm starts its trace
    inside run() after the bootstrap, never around it."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield log_dir


def annotate(name: str):
    """Named sub-span inside a device_trace (jax.profiler.TraceAnnotation
    passthrough) — marks host-side phases so device ops group under
    readable labels."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def maybe_annotate(name: str):
    """Best-effort :func:`annotate`: a no-op context when JAX (or the
    profiler) is unavailable. This is the lmr-trace bridge (DESIGN §22):
    a Tracer built with ``annotate=True`` — the ``--trace --profile``
    combination on the worker/server CLIs — enters one of these per
    span, so the SAME span names appear on the XLA profile's host rows
    and the Perfetto timeline exported from the store, and the host and
    device views correlate by name. Telemetry must never sink a job
    body, hence the swallow-to-no-op shape."""
    try:
        return annotate(name)
    except Exception:
        return contextlib.nullcontext()
